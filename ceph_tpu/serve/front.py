"""Multi-replica serving front: N `PlacementService` replicas behind a
consistent-hash router.

The rateless-codes load-balancing paper (PAPERS.md) frames the problem:
with work fanned out over replicas, one straggler — a replica staging
an epoch, or one hit by an injected stall — dominates the client tail
unless the router can shift its share to the others.  The front does
three things about it:

- **rendezvous-hash routing** — every lane (pool, seed) ranks all
  replicas by a seeded hash and goes to its argmax.  Excluding a
  replica remaps ONLY the lanes that replica owned (the defining
  rendezvous property): the rest of the traffic keeps its placement
  and its warm caches;
- **staggered epoch fan-out** — `apply`/`adopt_map` walk the replicas
  ONE at a time, marking the staging replica excluded-from-routing
  while it stages, so never two replicas stage the same epoch at once
  and the remaining replicas keep answering on the previous epoch
  (replicas briefly diverge by one epoch, by design — each reply
  carries its epoch);
- **slowest-replica shedding** — a per-replica EWMA of per-lane reply
  latency; a replica whose EWMA breaches `SHED_FACTOR` times the
  fastest gets excluded for `SHED_PROBE_S`, then probed again.  An
  injected stall (`serve_dispatch.<replica name>`) is absorbed after
  one slow block instead of taxing every block's p99.

All replicas serve the same map; answers are bit-identical whichever
replica answers (the placement pipeline is deterministic), so routing
is a latency decision, never a correctness one.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ceph_tpu import obs
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.incremental import Incremental
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.serve.service import (
    STATUS_CODES,
    BulkReply,
    PlacementService,
    Reply,
    ServeConfig,
    _SERVICES,
    _services_lock,
)
from ceph_tpu.utils import knobs
from ceph_tpu.utils.dout import subsys_logger

_log = subsys_logger("serve")

_L = obs.logger_for("serve")
_L.add_u64("front_blocks", "bulk blocks routed through a ServeFront")
_L.add_u64("front_shed_routes",
           "lanes remapped away from an excluded (staging or shed) "
           "replica by the rendezvous exclusion property — every other "
           "lane kept its placement")
_L.add_u64("front_replica_sheds",
           "slowest-replica shed transitions: a replica's per-lane "
           "latency EWMA breached SHED_FACTOR x the fastest and it "
           "left the routing set for a probe interval")
_L.add_u64("front_staggered_swaps",
           "epoch fan-outs completed by a front (replicas staged "
           "strictly one at a time, each excluded from routing while "
           "staging)")
_L.add_quantile("front_block_seconds",
                "client-visible latency of one bulk block through the "
                "front (route + replica sub-blocks + merge)")

# a replica is shed when its per-lane latency EWMA exceeds SHED_FACTOR
# times the fastest replica's; it rejoins after SHED_PROBE_S (one slow
# probe block re-sheds it, so a stuck replica costs one block per probe
# interval, not every block)
SHED_FACTOR = 4.0
SHED_PROBE_S = 0.25
_EWMA_ALPHA = 0.3


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — the per-(lane, replica) rendezvous rank."""
    x = (x + np.uint64(0x9E3779B97F4A7C15))
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


class ServeFront:
    """N placement-service replicas behind one rendezvous-hash front.

    The client surface mirrors the bulk protocol edge
    (`query_block`/`submit_many`/`lookup`); epoch swaps fan out
    staggered (`apply`/`adopt_map`).  Replica count comes from
    `CEPH_TPU_SERVE_REPLICAS` when not given."""

    def __init__(self, m: OSDMap, replicas: int | None = None,
                 config: ServeConfig | None = None,
                 name: str = "front"):
        if replicas is None:
            replicas = int(knobs.get("CEPH_TPU_SERVE_REPLICAS", "2"))
        if replicas < 1:
            raise ValueError("a front needs at least one replica")
        self.name = name
        self.config = config or ServeConfig.from_env()
        self.replicas = [
            PlacementService(m, config=self.config,
                             name=f"{name}.r{i}")
            for i in range(replicas)
        ]
        n = len(self.replicas)
        self._salts = _mix64(np.arange(1, n + 1, dtype=np.uint64)
                             * np.uint64(0xD6E8FEB86659FD93))
        self._apply_lock = threading.Lock()
        self._route_lock = threading.Lock()
        self._staging = [False] * n
        self._shed_until = [0.0] * n
        self._lat_ewma = [0.0] * n  # per-lane reply seconds
        with _services_lock:
            _SERVICES[name] = self

    # -- routing -----------------------------------------------------------

    def _rank(self, pool: int, seeds: np.ndarray) -> np.ndarray:
        """[n_lanes, n_replicas] rendezvous ranks."""
        base = (seeds.astype(np.uint64)
                ^ (np.uint64(pool & 0xFFFFFFFF) << np.uint64(32)))
        return _mix64(base[:, None] ^ self._salts[None, :])

    def _eligible(self, now: float) -> list[int]:
        with self._route_lock:
            el = [i for i in range(len(self.replicas))
                  if not self._staging[i]
                  and self._shed_until[i] <= now]
        # every replica excluded (all staging/shed at once) falls back
        # to full membership: routing degrades, never deadlocks
        return el or list(range(len(self.replicas)))

    def _owners(self, pool: int, seeds: np.ndarray,
                eligible: list[int]) -> np.ndarray:
        """Per-lane owning replica index.  Lanes whose full-membership
        argmax is excluded remap to their argmax over the eligible set
        (the rendezvous exclusion property: nobody else moves)."""
        rank = self._rank(pool, seeds)
        owners = np.argmax(rank, axis=1)
        if len(eligible) != len(self.replicas):
            moved = ~np.isin(owners, eligible)
            if moved.any():
                el = np.asarray(eligible)
                sub = rank[np.ix_(moved.nonzero()[0], el)]
                owners[moved] = el[np.argmax(sub, axis=1)]
                _L.inc("front_shed_routes", int(moved.sum()))
        return owners

    def _observe_replica(self, i: int, dt: float, lanes: int,
                         now: float) -> None:
        """EWMA update + shed decision for one replica's sub-block."""
        per_lane = dt / max(lanes, 1)
        with self._route_lock:
            e = self._lat_ewma[i]
            e = per_lane if e == 0.0 else (
                (1.0 - _EWMA_ALPHA) * e + _EWMA_ALPHA * per_lane)
            self._lat_ewma[i] = e
            peers = [v for j, v in enumerate(self._lat_ewma)
                     if j != i and v > 0.0]
            if peers and e > SHED_FACTOR * min(peers) \
                    and self._shed_until[i] <= now:
                self._shed_until[i] = now + SHED_PROBE_S
                _L.inc("front_replica_sheds")
                _log(1, f"front {self.name}: replica {i} shed "
                        f"({e * 1e6:.0f}us/lane vs best "
                        f"{min(peers) * 1e6:.0f}us)")

    # -- client surface ----------------------------------------------------

    @property
    def epoch(self) -> int:
        return max(r.epoch for r in self.replicas)

    def query_block(self, pool: int, seeds,
                    deadline_s: float | None = None) -> BulkReply:
        """One bulk block fanned over the replicas by rendezvous hash;
        per-lane statuses merge back in input order."""
        seeds = np.ascontiguousarray(
            np.asarray(seeds, np.uint32).ravel())
        n = len(seeds)
        if n == 0:
            return BulkReply(np.zeros(0, np.uint8), epoch=self.epoch)
        t0 = time.perf_counter()
        eligible = self._eligible(t0)
        owners = self._owners(pool, seeds, eligible)
        statuses = np.zeros(n, np.uint8)
        up = upp = act = actp = None
        sources: set[str] = set()
        errors: list[str] = []
        epoch = 0
        with obs.span("serve.front", lookups=n, pool=pool,
                      replicas=len(eligible)):
            for i in eligible:
                mask = owners == i
                lanes = int(mask.sum())
                if not lanes:
                    continue
                t_r = time.perf_counter()
                r = self.replicas[i].query_block(
                    pool, seeds[mask], deadline_s)
                self._observe_replica(
                    i, time.perf_counter() - t_r, lanes, t0)
                statuses[mask] = r.statuses
                if r.up is not None:
                    if up is None:
                        w = r.up.shape[1]
                        up = np.full((n, w), ITEM_NONE, np.int32)
                        upp = np.full(n, -1, np.int32)
                        act = np.full((n, w), ITEM_NONE, np.int32)
                        actp = np.full(n, -1, np.int32)
                    up[mask] = r.up
                    upp[mask] = r.up_primary
                    act[mask] = r.acting
                    actp[mask] = r.acting_primary
                if r.source:
                    sources.add(r.source)
                if r.error:
                    errors.append(r.error)
                epoch = max(epoch, r.epoch)
        _L.inc("front_blocks")
        _L.observe("front_block_seconds", time.perf_counter() - t0)
        source = sources.pop() if len(sources) == 1 else (
            "mixed" if sources else "")
        return BulkReply(statuses, epoch=epoch or self.epoch,
                         source=source, up=up, up_primary=upp,
                         acting=act, acting_primary=actp,
                         error="; ".join(errors)[:200])

    def submit_many(self, pools, seeds,
                    deadline_s: float | None = None) -> BulkReply:
        """Mixed-pool bulk submit through the front: group by pool,
        route each group, scatter back (same shape as the service's
        own submit_many, one routing decision per pool group)."""
        seeds = np.asarray(seeds, np.uint32).ravel()
        pools_a = np.asarray(pools, np.int64).ravel()
        if pools_a.size == 1:
            return self.query_block(int(pools_a[0]), seeds, deadline_s)
        if pools_a.shape != seeds.shape:
            return BulkReply(
                np.full(len(seeds), STATUS_CODES["EFAULT"], np.uint8),
                epoch=self.epoch, error="pools/seeds length mismatch")
        n = len(seeds)
        if n == 0:
            return BulkReply(np.zeros(0, np.uint8), epoch=self.epoch)
        order = np.argsort(pools_a, kind="stable")
        cuts = np.flatnonzero(np.diff(pools_a[order])) + 1
        statuses = np.zeros(n, np.uint8)
        W = 0
        parts: list[tuple[np.ndarray, BulkReply]] = []
        for idx in np.split(order, cuts):
            r = self.query_block(int(pools_a[idx[0]]), seeds[idx],
                                 deadline_s)
            parts.append((idx, r))
            if r.up is not None:
                W = max(W, r.up.shape[1])
        up = np.full((n, W), ITEM_NONE, np.int32)
        upp = np.full(n, -1, np.int32)
        act = np.full((n, W), ITEM_NONE, np.int32)
        actp = np.full(n, -1, np.int32)
        epoch = 0
        for idx, r in parts:
            statuses[idx] = r.statuses
            if r.up is not None:
                w = r.up.shape[1]
                up[idx, :w] = r.up
                upp[idx] = r.up_primary
                act[idx, :w] = r.acting
                actp[idx] = r.acting_primary
            epoch = max(epoch, r.epoch)
        return BulkReply(statuses, epoch=epoch or self.epoch,
                         up=up, up_primary=upp, acting=act,
                         acting_primary=actp)

    def lookup(self, pool: int, seed: int,
               deadline_s: float | None = None) -> Reply:
        """Scalar path: one lane through the same routing."""
        now = time.perf_counter()
        eligible = self._eligible(now)
        owner = int(self._owners(
            pool, np.asarray([seed], np.uint32), eligible)[0])
        return self.replicas[owner].lookup(pool, seed, deadline_s)

    # -- epoch fan-out -----------------------------------------------------

    def _fan_out(self, stage_one) -> dict:
        """Staggered epoch fan-out: replicas stage strictly one at a
        time, the staging replica excluded from routing for the
        duration — the rest keep answering on the previous epoch, so
        a structural epoch costs the front NO reader stall and at most
        1/N of its capacity at any moment."""
        with self._apply_lock:
            results = []
            for i, rep in enumerate(self.replicas):
                with self._route_lock:
                    self._staging[i] = True
                try:
                    results.append(stage_one(rep))
                finally:
                    with self._route_lock:
                        self._staging[i] = False
            _L.inc("front_staggered_swaps")
            ok = all(r.get("ok") for r in results)
            return {"ok": ok, "epoch": self.epoch,
                    "replicas": results}

    def apply(self, inc: Incremental) -> dict:
        return self._fan_out(lambda rep: rep.apply(inc))

    def adopt_map(self, m: OSDMap, reason: str = "") -> dict:
        return self._fan_out(
            lambda rep: rep.adopt_map(m, reason=reason))

    # -- introspection / lifecycle ----------------------------------------

    def status(self) -> dict:
        d = _L.dump()
        fb = d.get("front_block_seconds") or {}
        with self._route_lock:
            shed = [i for i, t in enumerate(self._shed_until)
                    if t > time.perf_counter()]
            staging = [i for i, s in enumerate(self._staging) if s]
            ewma = [round(v * 1e6, 1) for v in self._lat_ewma]
        return {
            "replicas": len(self.replicas),
            "epochs": [r.epoch for r in self.replicas],
            "staging": staging,
            "shed": shed,
            "lat_ewma_us_per_lane": ewma,
            "front_blocks": d.get("front_blocks", 0),
            "front_shed_routes": d.get("front_shed_routes", 0),
            "front_replica_sheds": d.get("front_replica_sheds", 0),
            "front_staggered_swaps": d.get("front_staggered_swaps", 0),
            "front_block_p50_s": fb.get("p50"),
            "front_block_p99_s": fb.get("p99"),
        }

    def close(self) -> None:
        for r in self.replicas:
            r.close()
        with _services_lock:
            if _SERVICES.get(self.name) is self:
                del _SERVICES[self.name]

    def __enter__(self) -> "ServeFront":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
