"""Mesh bit-identity witness for the serving buffer.

The serve buffer shards its PG axis over the `CEPH_TPU_MESH_DEVICES`
mesh exactly like `ClusterState` (same `NamedSharding`, same
executables — GSPMD partitions the one compiled pipeline).  The
contract is that sharding is a THROUGHPUT decision with zero semantic
surface: answers must be bit-identical on 1, 2 or 8 forced devices,
and bit-identical to the host-mapper oracle.

Forced CPU devices only exist if `XLA_FLAGS=
--xla_force_host_platform_device_count=N` is set before jax
initializes, so the N>1 legs must run in a fresh process.  This module
is that worker: `python -m ceph_tpu.serve.meshcheck` builds the
canonical deterministic map, serves every PG of every pool through
`query_block`, verifies each answer against the host oracle in-process
and prints one JSON line:

    {"digest": ..., "oracle_match": true, "devices": N, "mesh": {...}}

`placement_digest` is importable, so the parent (a tier-1 test, the
bench serve stage) computes its own single-device digest in-process
and compares — equal digests across forced device counts IS the
bit-identity proof.
"""

from __future__ import annotations

import hashlib
import json

import numpy as np

from ceph_tpu.osd.osdmap import OSDMap, build_hierarchical
from ceph_tpu.osd.types import PgPool, PoolType

# the canonical witness cluster: small enough to stage in seconds,
# two pools so the digest walks a pool boundary, PG counts divisible
# by every forced device count the checks use (1/2/8)
DEFAULT_PGS = 256
DEFAULT_OSDS = 16


def build_default(pgs: int = DEFAULT_PGS,
                  osds: int = DEFAULT_OSDS) -> OSDMap:
    pool = PgPool(type=PoolType.REPLICATED, size=3, crush_rule=0,
                  pg_num=pgs, pgp_num=pgs)
    m = build_hierarchical(osds // 4, 4, n_rack=1, pool=pool)
    p2 = PgPool(type=PoolType.REPLICATED, size=2, crush_rule=0,
                pg_num=pgs // 2, pgp_num=pgs // 2)
    m.add_pool("meshcheck2", p2)
    return m


def placement_digest(svc, m: OSDMap) -> tuple[str, bool]:
    """(sha256 digest, oracle_match) over every PG of every pool,
    answered through the bulk edge.  The digest covers all four row
    tensors; the oracle check replays each pool through the host
    mapper at the same padded width."""
    h = hashlib.sha256()
    oracle_ok = True
    for pid in sorted(m.pools):
        seeds = np.arange(m.pools[pid].pg_num, dtype=np.uint32)
        r = svc.query_block(pid, seeds, deadline_s=0)
        if not r.ok:
            h.update(f"{pid}:notok".encode())
            oracle_ok = False
            continue
        for a in (r.up, r.up_primary, r.acting, r.acting_primary):
            h.update(np.ascontiguousarray(a).tobytes())
        up, upp, act, actp = svc._active.host_rows(pid, seeds)
        oracle_ok = oracle_ok and bool(
            (r.up == up).all() and (r.up_primary == upp).all()
            and (r.acting == act).all()
            and (r.acting_primary == actp).all())
    return h.hexdigest(), oracle_ok


def run(pgs: int = DEFAULT_PGS, osds: int = DEFAULT_OSDS) -> dict:
    import jax

    from ceph_tpu.serve.service import PlacementService, ServeConfig

    m = build_default(pgs, osds)
    cfg = ServeConfig(block=128, bulk_max=pgs, max_queue=256,
                      deadline_s=0)
    svc = PlacementService(m, config=cfg, name="meshcheck")
    try:
        digest, oracle_match = placement_digest(svc, m)
        st = svc.status()
        return {
            "digest": digest,
            "oracle_match": oracle_match,
            "devices": len(jax.devices()),
            "mesh": st["mesh"],
        }
    finally:
        svc.close()


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="serve mesh bit-identity worker")
    ap.add_argument("--pgs", type=int, default=DEFAULT_PGS)
    ap.add_argument("--osds", type=int, default=DEFAULT_OSDS)
    args = ap.parse_args(argv)
    print(json.dumps(run(args.pgs, args.osds)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
