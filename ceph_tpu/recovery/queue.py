"""Recovery data plane: a device-resident backlog/contention queue.

The lifetime simulator (PR 10/12) modeled recovery as ONE flat division
— `epoch_s = max(interval_s, moved_bytes / recovery_mbps)` — which has a
silent floor: whenever the configured bandwidth drains an epoch's
movement inside `interval_s`, the remainder is discarded, so
`at_risk_pg_seconds` never sees a backlog, queueing, or client
contention.  This module is the real queue the online-EC SSD-array study
("Understanding System Characteristics of Online Erasure Coding on
Scalable, Distributed and Large-Scale SSD Array Systems", PAPERS.md)
describes: recovery work is *queued per PG*, drained by *per-OSD*
resources (bandwidth + concurrent-recovery slots, the
`osd_max_backfills` shape), degraded/at-risk PGs drain *first*
(degraded-read priority), and unfinished work carries across epochs as
backlog that the next epoch's clients then land on.

The model, exact in int64 (bytes) and int64 (microseconds) so the jax
kernel and the numpy mirror produce bit-identical digests:

- **Enqueue.**  Each epoch, every moved-in replica lane of a PG queues
  `shard_bytes = pg_gb·1e9 / size` of recovery work onto that PG's
  backlog.
- **Drain.**  An epoch lasts `interval_s` (fixed — the backlog carries,
  nothing is discarded).  Each OSD contributes `osd_mbps·interval_s`
  bytes of epoch capacity, shared by client traffic (subtracted first
  when the workload generator runs) and recovery.  Recovery streams are
  slot-limited: an OSD runs at most `max_backfills` concurrent PG
  recoveries, each at the per-stream rate below, so an OSD's drain this
  epoch is `min(streams · stream_bytes, capacity)`.  PGs queue on their
  primary (first live lane); **at-risk PGs are drained first** (class
  0), everything else shares the remaining slots/capacity (class 1);
  within a class the OSD's allotment splits evenly (processor-sharing
  approximation of round-robin backfill).
- **Pipelined repair (RapidRAID).**  An EC repair stream chains
  encode → placement → transfer.  Serially those stages sum:
  `rate = 1 / (1/encode + 1/transfer)` (harmonic).  With
  `pipeline_repair=1` the stages overlap the way "RapidRAID: Pipelined
  Erasure Codes for Fast Data Archival" (PAPERS.md) chains nodes, and
  the stream runs at the bottleneck stage: `min(encode, transfer)`.
  The encode rate is calibrated from the measured EC strategy GB/s
  (`ec_gbps`, default the r07 jax RS 8+4 number).
- **Risk integration.**  `at_risk_pg_seconds` integrates the *real*
  time each at-risk PG spends below tolerance: a PG whose backlog fully
  drains mid-epoch contributes `backlog / share · interval_s`; one
  still queued (or with nothing queued to fix it — down-not-out OSDs
  CRUSH has not remapped around) contributes the whole epoch.
- **Conservation.**  Every epoch, per pool:
  `prev_backlog + enqueued == drained + new_backlog`, in exact int64 —
  checked by the lifetime engine as a sim invariant (a violation means
  the device and host disagree about bytes, which is data loss).

Queue state lives in ClusterState-style device vectors (per-pool
`backlog[n]` int64, per-OSD capacity/slot vectors), stepped by one
jitted kernel per (rows-shape, device-vector-bound) — steady epochs
book 0 compiles; a host-side numpy mirror (refreshed by the per-epoch
O(n) d2h fetch that also feeds checkpoints) serves the "ref" backend
and the device-loss degradation path bit-identically.
"""

from __future__ import annotations

import base64

import numpy as np

from ceph_tpu import obs
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.utils.dout import subsys_logger

_log = subsys_logger("sim")

_L = obs.logger_for("recovery")
_L.add_u64("enqueued_bytes",
           "recovery bytes queued by moved-in replica lanes")
_L.add_u64("drained_bytes",
           "recovery bytes drained by per-OSD slot-limited streams")
_L.add_u64("completed_pgs",
           "PG recoveries that fully drained within an epoch")
_L.add_u64("queued_pg_epochs",
           "PG-epochs spent with a nonzero recovery backlog")
_L.add_u64("fallbacks",
           "recovery drains degraded to the host mirror after a device "
           "loss")
_L.add_u64("conservation_violations",
           "epochs where prev_backlog + enqueued != drained + backlog "
           "(also booked as a sim invariant violation)")
_L.add_avg("backlog_bytes",
           "end-of-epoch total recovery backlog (one observation per "
           "epoch)")
_L.add_avg("streams",
           "concurrent recovery streams granted per epoch")
_L.add_quantile("drain_seconds",
                "wall time of one epoch's recovery drain (all pools: "
                "dispatch + scalar fetch, or the numpy mirror)")


def stream_bytes_per_epoch(recovery_mbps: float, t_us: int,
                           ec_gbps: float = 0.0,
                           pipelined: bool = False) -> int:
    """Bytes one recovery stream moves in one epoch.  Replicated pools
    copy at the transfer rate; EC repair chains encode->transfer —
    serial stages sum (harmonic rate), pipelined (RapidRAID) runs at
    the bottleneck stage."""
    xfer = int(recovery_mbps * 1e6)
    if ec_gbps > 0:
        enc = int(ec_gbps * 1e9)
        rate = min(enc, xfer) if pipelined else (
            (enc * xfer) // (enc + xfer))
    else:
        rate = xfer
    return (rate * t_us) // 1_000_000


DRAIN_KEYS = ("enqueued", "drained", "backlog", "risk_us", "completed",
              "queued", "streams")


def drain_pool_np(backlog, moved, rows, cap, slots, *, shard_bytes: int,
                  stream_bytes: int, t_us: int, n: int, size: int,
                  tol: int):
    """The authoritative drain formula, numpy executor (exact int64).
    Returns (new_backlog, new_cap, new_slots, scalars dict)."""
    rows = np.asarray(rows)
    N, _ = rows.shape
    DV = int(cap.shape[0])
    backlog = np.asarray(backlog, np.int64)
    moved = (np.zeros(N, np.int64) if moved is None
             else np.asarray(moved, np.int64))
    cap = np.asarray(cap, np.int64).copy()
    slots = np.asarray(slots, np.int64).copy()
    real = np.arange(N) < n
    valid = (rows != ITEM_NONE) & (rows >= 0)
    occ = valid.sum(axis=1)
    enq = np.where(real, moved * np.int64(shard_bytes), np.int64(0))
    b0 = backlog + enq
    at_risk = real & (occ < size - tol)
    queued = real & (b0 > 0)
    first = np.argmax(valid, axis=1)
    prim = rows[np.arange(N), first].astype(np.int64)
    prim = np.where(valid.any(axis=1) & (prim >= 0) & (prim < DV),
                    prim, np.int64(DV))
    drain = np.zeros(N, np.int64)
    share_all = np.zeros(N, np.int64)
    streams_total = 0
    for cls in (queued & at_risk, queued & ~at_risk):
        n_o = np.zeros(DV + 1, np.int64)
        np.add.at(n_o, prim, cls.astype(np.int64))
        n_o = n_o[:DV]
        streams = np.minimum(n_o, slots)
        allot = np.minimum(streams * np.int64(stream_bytes), cap)
        share_o = np.where(n_o > 0, allot // np.maximum(n_o, 1),
                           np.int64(0))
        share = np.where(cls, np.append(share_o, 0)[prim], np.int64(0))
        d = np.minimum(b0, share)
        drained_o = np.zeros(DV + 1, np.int64)
        np.add.at(drained_o, prim, d)
        cap = cap - drained_o[:DV]
        slots = np.maximum(slots - streams, 0)
        drain = drain + d
        share_all = share_all + share
        streams_total += int(streams.sum())
    b_after = b0 - drain
    completed = queued & (b_after == 0)
    num = np.minimum(b0, share_all) * np.int64(t_us)
    risk_t = np.where(completed & (share_all > 0),
                      num // np.maximum(share_all, 1), np.int64(t_us))
    risk_us = int(np.where(at_risk, risk_t, np.int64(0)).sum())
    scalars = {
        "enqueued": int(enq.sum()),
        "drained": int(drain.sum()),
        "backlog": int((b_after * real).sum()),
        "risk_us": risk_us,
        "completed": int(completed.sum()),
        "queued": int(queued.sum()),
        "streams": streams_total,
    }
    return b_after, cap, slots, scalars


def _build_drain():
    """The jitted device executor of the SAME formula (lazy jax import;
    everything int64 — the two executors must never diverge, digest
    equality across backends depends on it)."""
    import jax
    import jax.numpy as jnp

    def _drain(backlog, moved, rows, cap, slots, shard_bytes,
               stream_bytes, t_us, n, size, tol):
        N = rows.shape[0]
        DV = cap.shape[0]
        real = jnp.arange(N) < n
        valid = (rows != ITEM_NONE) & (rows >= 0)
        occ = jnp.sum(valid.astype(jnp.int64), axis=1)
        enq = jnp.where(real, moved.astype(jnp.int64) * shard_bytes,
                        jnp.int64(0))
        b0 = backlog + enq
        at_risk = real & (occ < size.astype(jnp.int64)
                          - tol.astype(jnp.int64))
        queued = real & (b0 > 0)
        first = jnp.argmax(valid, axis=1)
        prim = jnp.take_along_axis(
            rows, first[:, None], axis=1)[:, 0].astype(jnp.int64)
        prim = jnp.where(valid.any(axis=1) & (prim >= 0) & (prim < DV),
                         prim, jnp.int64(DV))
        drain = jnp.zeros(N, jnp.int64)
        share_all = jnp.zeros(N, jnp.int64)
        streams_total = jnp.int64(0)
        for cls in (queued & at_risk, queued & ~at_risk):
            n_o = jnp.zeros(DV + 1, jnp.int64).at[prim].add(
                cls.astype(jnp.int64))[:DV]
            streams = jnp.minimum(n_o, slots)
            allot = jnp.minimum(streams * stream_bytes, cap)
            share_o = jnp.where(n_o > 0, allot // jnp.maximum(n_o, 1),
                                jnp.int64(0))
            share = jnp.where(
                cls, jnp.append(share_o, jnp.int64(0))[prim],
                jnp.int64(0))
            d = jnp.minimum(b0, share)
            drained_o = jnp.zeros(DV + 1, jnp.int64).at[prim].add(d)
            cap = cap - drained_o[:DV]
            slots = jnp.maximum(slots - streams, 0)
            drain = drain + d
            share_all = share_all + share
            streams_total = streams_total + jnp.sum(streams)
        b_after = b0 - drain
        completed = queued & (b_after == 0)
        num = jnp.minimum(b0, share_all) * t_us
        risk_t = jnp.where(completed & (share_all > 0),
                           num // jnp.maximum(share_all, 1), t_us)
        risk_us = jnp.sum(
            jnp.where(at_risk, risk_t, jnp.int64(0)))
        scalars = jnp.stack([
            jnp.sum(enq), jnp.sum(drain),
            jnp.sum(jnp.where(real, b_after, jnp.int64(0))),
            risk_us,
            jnp.sum(completed.astype(jnp.int64)),
            jnp.sum(queued.astype(jnp.int64)),
            streams_total,
        ])
        return b_after, cap, slots, scalars

    return obs.JitAccount(jax.jit(_drain), _L, "drain")


_DRAIN_ACCTS: dict[tuple, obs.JitAccount] = {}


def _drain_account(shape_key: tuple) -> obs.JitAccount:
    acct = _DRAIN_ACCTS.get(shape_key)
    if acct is None:
        acct = _DRAIN_ACCTS[shape_key] = _build_drain()
    return acct


class RecoveryQueue:
    """Per-pool recovery backlogs + cumulative accounting.

    Master state: the per-pool int64 backlog vectors.  On the jax
    backend they live on device epoch-to-epoch (`_dev`); the numpy
    mirror (`backlog`) is refreshed by each epoch's O(n) fetch and is
    what checkpoints serialize and the degraded path drains.  The
    engine drives the per-epoch loop (it owns the rows, the moved
    vectors, and the fault point); this class owns the state, the
    executors, and the totals."""

    def __init__(self, *, pg_gb: float, recovery_mbps: float,
                 interval_s: float, max_backfills: int, osd_mbps: float,
                 pipeline_repair: int, ec_gbps: float):
        self.pg_gb = pg_gb
        self.recovery_mbps = recovery_mbps
        self.t_us = int(round(interval_s * 1e6))
        self.max_backfills = int(max_backfills)
        self.cap_epoch_bytes = (
            int(osd_mbps * 1e6) * self.t_us) // 1_000_000
        self.pipeline_repair = int(pipeline_repair)
        self.ec_gbps = ec_gbps
        self.backlog: dict[int, np.ndarray] = {}   # pid -> int64 mirror
        self._dev: dict[int, object] = {}          # pid -> device array
        self.prev_total: dict[int, int] = {}
        self.totals = {"enqueued": 0, "drained": 0, "completed": 0,
                       "risk_us": 0, "queued_pg_epochs": 0}
        self.backlog_peak = 0   # max END-of-epoch backlog (carried)
        self.queue_peak = 0     # max pre-drain queue depth in an epoch
        self._epoch_queue = 0
        self.fallback_epochs = 0
        self.conservation_violations = 0
        self._warmed: set[tuple] = set()

    # -- rates -------------------------------------------------------------

    def shard_bytes(self, size: int) -> int:
        return int(self.pg_gb * 1e9) // max(int(size), 1)

    def stream_bytes(self, is_erasure: bool) -> int:
        return stream_bytes_per_epoch(
            self.recovery_mbps, self.t_us,
            ec_gbps=self.ec_gbps if is_erasure else 0.0,
            pipelined=bool(self.pipeline_repair))

    # -- state -------------------------------------------------------------

    def ensure(self, pid: int, N: int) -> np.ndarray:
        """The pool's backlog mirror at row-count N.  A pg_num split
        keeps the parent seeds' backlog (children start empty); any
        resize drops the device copy (re-uploaded lazily)."""
        b = self.backlog.get(pid)
        if b is None or b.shape[0] != N:
            nb = np.zeros(N, np.int64)
            if b is not None:
                k = min(N, b.shape[0])
                nb[:k] = b[:k]
                self._dev.pop(pid, None)
            self.backlog[pid] = b = nb
            self.prev_total.setdefault(pid, int(b.sum()))
        return b

    def drop(self, pid: int) -> None:
        self.backlog.pop(pid, None)
        self._dev.pop(pid, None)
        self.prev_total.pop(pid, None)

    def device_backlog(self, pid: int):
        import jax.numpy as jnp

        d = self._dev.get(pid)
        if d is None:
            d = self._dev[pid] = jnp.asarray(self.backlog[pid])
        return d

    def total_backlog(self) -> int:
        return sum(int(b.sum()) for b in self.backlog.values())

    def pg_undrained(self, pid: int, n: int) -> np.ndarray:
        """Bool [n]: PGs still carrying recovery backlog, from the host
        mirror (valid after the epoch's drain refreshed it).  The
        lifetime engine's durability pass keys wound healing off this —
        a wound may only clear once its PG's backlog was seen and then
        fully drained."""
        b = self.backlog.get(pid)
        if b is None:
            return np.zeros(n, bool)
        if b.shape[0] < n:
            out = np.zeros(n, bool)
            out[:b.shape[0]] = b > 0
            return out
        return b[:n] > 0

    # -- the drain ---------------------------------------------------------

    def warm(self, pid: int, rows, cap, slots) -> None:
        """Compile the drain kernel for this pool's shapes (baseline /
        structural epochs) so a later steady epoch's first backlogged
        drain cannot book a compile.  No counters, no digest effect —
        the zero-input outputs are discarded."""
        import jax.numpy as jnp

        N = int(rows.shape[0])
        key = (N, int(rows.shape[1]), int(cap.shape[0]))
        if key in self._warmed:
            return
        _drain_account(key)(
            jnp.zeros(N, jnp.int64), jnp.zeros(N, jnp.int64), rows,
            cap, slots, np.int64(1), np.int64(1), np.int64(self.t_us),
            np.uint32(N), np.int32(1), np.int32(0))
        self._warmed.add(key)

    def drain_device(self, pid: int, moved, rows, cap, slots, *,
                     n: int, size: int, tol: int, is_erasure: bool):
        """One pool's drain on device: backlog stays resident, the
        mirror refreshes from the O(n) fetch, scalars come back as
        exact ints.  Returns (new_cap, new_slots, scalars)."""
        import jax.numpy as jnp

        N = int(rows.shape[0])
        self.ensure(pid, N)
        key = (N, int(rows.shape[1]), int(cap.shape[0]))
        if moved is None:
            moved = jnp.zeros(N, jnp.int64)
        b_after, cap, slots, scal = _drain_account(key)(
            self.device_backlog(pid), moved.astype(jnp.int64), rows,
            cap, slots,
            np.int64(self.shard_bytes(size)),
            np.int64(self.stream_bytes(is_erasure)),
            np.int64(self.t_us), np.uint32(n), np.int32(size),
            np.int32(tol))
        self._dev[pid] = b_after
        self.backlog[pid] = np.asarray(b_after)
        scalars = dict(zip(DRAIN_KEYS, (int(v) for v in
                                        np.asarray(scal))))
        self._warmed.add(key)
        return cap, slots, scalars

    def drain_host(self, pid: int, moved, rows, cap, slots, *, n: int,
                   size: int, tol: int, is_erasure: bool):
        """The numpy executor over the host mirror (ref backend, and
        the device-loss degradation path — bit-identical scalars)."""
        rows = np.asarray(rows)
        self.ensure(pid, int(rows.shape[0]))
        if moved is not None:
            moved = np.asarray(moved)
        b_after, cap, slots, scalars = drain_pool_np(
            self.backlog[pid], moved, rows, cap, slots,
            shard_bytes=self.shard_bytes(size),
            stream_bytes=self.stream_bytes(is_erasure),
            t_us=self.t_us, n=n, size=size, tol=tol)
        self.backlog[pid] = b_after
        self._dev.pop(pid, None)
        return cap, slots, scalars

    def book(self, pid: int, scalars: dict) -> bool:
        """Fold one pool-epoch's scalars into totals/counters and check
        byte conservation.  Returns True when conserved."""
        prev = self.prev_total.get(pid, 0)
        conserved = (prev + scalars["enqueued"]
                     == scalars["drained"] + scalars["backlog"])
        self._epoch_queue += prev + scalars["enqueued"]
        self.prev_total[pid] = scalars["backlog"]
        self.totals["enqueued"] += scalars["enqueued"]
        self.totals["drained"] += scalars["drained"]
        self.totals["completed"] += scalars["completed"]
        self.totals["risk_us"] += scalars["risk_us"]
        self.totals["queued_pg_epochs"] += scalars["queued"]
        _L.inc("enqueued_bytes", scalars["enqueued"])
        _L.inc("drained_bytes", scalars["drained"])
        _L.inc("completed_pgs", scalars["completed"])
        _L.inc("queued_pg_epochs", scalars["queued"])
        _L.observe("streams", scalars["streams"])
        if not conserved:
            _L.inc("conservation_violations")
            self.conservation_violations += 1
        return conserved

    def end_epoch(self) -> int:
        total = sum(self.prev_total.values())
        self.backlog_peak = max(self.backlog_peak, total)
        self.queue_peak = max(self.queue_peak, self._epoch_queue)
        self._epoch_queue = 0
        _L.observe("backlog_bytes", total)
        return total

    # -- checkpoint --------------------------------------------------------

    def state(self) -> dict:
        return {
            "backlog": {
                str(pid): base64.b64encode(
                    np.ascontiguousarray(b).tobytes()).decode()
                for pid, b in self.backlog.items()
            },
            "totals": dict(self.totals),
            "backlog_peak": self.backlog_peak,
            "queue_peak": self.queue_peak,
            "fallback_epochs": self.fallback_epochs,
            "conservation_violations": self.conservation_violations,
        }

    def restore(self, st: dict) -> None:
        self.backlog = {
            int(pid): np.frombuffer(
                base64.b64decode(b64), np.int64).copy()
            for pid, b64 in (st.get("backlog") or {}).items()
        }
        self._dev = {}
        self.prev_total = {pid: int(b.sum())
                           for pid, b in self.backlog.items()}
        self.totals = dict(st["totals"])
        self.backlog_peak = int(st["backlog_peak"])
        self.queue_peak = int(st.get("queue_peak", 0))
        self.fallback_epochs = int(st.get("fallback_epochs", 0))
        self.conservation_violations = int(
            st.get("conservation_violations", 0))

    def summary(self) -> dict:
        total = self.total_backlog()
        return {
            "model": "queue",
            "pipelined_repair": bool(self.pipeline_repair),
            "enqueued_gb": round(self.totals["enqueued"] / 1e9, 3),
            "drained_gb": round(self.totals["drained"] / 1e9, 3),
            "backlog_gb": round(total / 1e9, 3),
            "backlog_peak_gb": round(self.backlog_peak / 1e9, 3),
            "queue_peak_gb": round(self.queue_peak / 1e9, 3),
            "completed_pgs": self.totals["completed"],
            "queued_pg_epochs": self.totals["queued_pg_epochs"],
            "at_risk_pg_seconds": round(
                self.totals["risk_us"] / 1e6, 3),
            "conservation_violations": self.conservation_violations,
            "fallback_epochs": self.fallback_epochs,
        }
