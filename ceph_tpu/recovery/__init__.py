"""Recovery data plane: device-resident backlog/contention queue
(`queue.py`) stepped by the lifetime simulator each epoch — per-PG
recovery work, per-OSD bandwidth + concurrency slots, degraded-read
priority, RapidRAID-style pipelined repair rates, and exact int64 byte
conservation."""

from ceph_tpu.recovery.queue import (
    DRAIN_KEYS,
    RecoveryQueue,
    drain_pool_np,
    stream_bytes_per_epoch,
)

__all__ = [
    "DRAIN_KEYS",
    "RecoveryQueue",
    "drain_pool_np",
    "stream_bytes_per_epoch",
]
