"""Pareto-front reduction over per-cluster fleet outcomes.

A fleet run turns N clusters into N outcome points; the interesting
output is not any single point but the non-dominated *front* over

    cluster_years_per_hour   higher is better (simulation throughput)
    served_qps               higher is better (client traffic kept)
    pg_lost                  lower is better  (irreversible data loss)
    exposure                 lower is better  (PG-epochs spent past
                                               tolerance)

Dominated points are kept with full accounting — which front point
dominated them — because the triage question for a dominated
configuration is always "what should this cluster have been instead".
"""

from __future__ import annotations

from dataclasses import dataclass, field

# (key, higher_is_better) in headline order
OBJECTIVES: tuple[tuple[str, bool], ...] = (
    ("cluster_years_per_hour", True),
    ("served_qps", True),
    ("pg_lost", False),
    ("exposure", False),
)


@dataclass
class Point:
    """One cluster's outcome: its fleet index, pinned spec, and the
    objective values."""

    index: int
    spec: str
    values: dict[str, float]
    dominated_by: int | None = None  # front point index, set by reduce
    front: bool = field(default=False)

    @classmethod
    def from_summary(cls, index: int, spec: str, summary: dict)\
            -> "Point":
        par = summary.get("pareto") or {}
        dur = summary.get("durability") or {}
        return cls(index=index, spec=spec, values={
            "cluster_years_per_hour": float(
                par.get("cluster_years_per_hour",
                        summary.get("cluster_years_per_hour", 0.0))),
            "served_qps": float(par.get("served_qps", 0.0)),
            "pg_lost": float(dur.get("pg_lost", 0)),
            "exposure": float(dur.get("exposure_pg_epochs",
                                      dur.get("exposure", 0))),
        })


def dominates(a: dict, b: dict) -> bool:
    """True when `a` is at least as good as `b` on every objective and
    strictly better on at least one."""
    strict = False
    for key, higher in OBJECTIVES:
        av, bv = a[key], b[key]
        if higher:
            if av < bv:
                return False
            strict = strict or av > bv
        else:
            if av > bv:
                return False
            strict = strict or av < bv
    return strict


def pareto_front(points: list[Point]) -> tuple[list[Point],
                                               list[Point]]:
    """Split points into (front, dominated); each dominated point's
    `dominated_by` names one front point that dominates it."""
    front: list[Point] = []
    dominated: list[Point] = []
    for p in points:
        p.front = not any(dominates(q.values, p.values)
                          for q in points if q is not p)
    for p in points:
        if p.front:
            front.append(p)
            continue
        for q in points:
            if q.front and dominates(q.values, p.values):
                p.dominated_by = q.index
                break
        dominated.append(p)
    return front, dominated


def triage_table(points: list[Point], max_spec: int = 48) -> str:
    """Human triage view: front members first, then dominated points
    with the front index that beats them."""
    front, dominated = ([p for p in points if p.front],
                        [p for p in points if not p.front])
    head = ("idx", "front", "cyrs/h", "qps", "pg_lost", "exposure",
            "beaten-by", "spec")
    rows = [head]
    for p in sorted(points, key=lambda p: (not p.front, p.index)):
        v = p.values
        spec = p.spec if len(p.spec) <= max_spec \
            else p.spec[:max_spec - 1] + "…"
        rows.append((
            str(p.index), "*" if p.front else "",
            f"{v['cluster_years_per_hour']:.3f}",
            f"{v['served_qps']:.1f}",
            f"{int(v['pg_lost'])}", f"{int(v['exposure'])}",
            "" if p.dominated_by is None else str(p.dominated_by),
            spec,
        ))
    widths = [max(len(r[c]) for r in rows) for c in range(len(head))]
    lines = ["  ".join(cell.ljust(w) for cell, w in zip(r, widths))
             .rstrip() for r in rows]
    lines.append(f"front {len(front)} / dominated {len(dominated)} "
                 f"of {len(points)} clusters")
    return "\n".join(lines)
