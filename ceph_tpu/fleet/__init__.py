"""Fleet simulation: N independent clusters per stacked dispatch.

`spec` expands one sweep-grammar string into pinned members, `engine`
evolves them in lockstep with per-member digests bit-identical to solo
`LifetimeSim` runs, and `pareto` reduces the outcomes into a
non-dominated front.
"""

from ceph_tpu.fleet.engine import FleetSim
from ceph_tpu.fleet.pareto import Point, pareto_front, triage_table
from ceph_tpu.fleet.spec import (
    FLEET_KNOBS,
    SWEEP_AXES,
    FleetMember,
    parse_fleet,
)

__all__ = [
    "FLEET_KNOBS",
    "SWEEP_AXES",
    "FleetMember",
    "FleetSim",
    "Point",
    "pareto_front",
    "parse_fleet",
    "triage_table",
]
