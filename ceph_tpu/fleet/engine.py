"""Fleet engine: N independent clusters, one stacked dispatch per epoch.

`FleetSim` evolves many `LifetimeSim` members in lockstep.  Each fleet
epoch runs every live member's `_step_begin` (chaos event application),
then reduces EVERY member's per-pool mapping stats through ONE stacked
vmapped dispatch (`_plan_pool` / `_commit_pool` are the solo engine's
own read/write halves, so the numbers — and therefore each member's
SHA-256 replay digest — are bit-identical to a solo run of the same
scenario), then runs every member's `_step_finish` (recovery drain,
workload sampling, durability ledger, digest line).

Exactness of the stacking: lanes pad to the batch max over (rows, width)
with ITEM_NONE, and every `core/reduce` reduction masks
ITEM_NONE/negative lanes before exact-integer accumulation — the same
mesh contract that makes the sharded solo digest equal the unsharded
one makes the padded stacked digest equal the solo one.  n/size/tol
ride as per-lane operand vectors, and `real = arange(Nmax) < n` masks
the row padding, so no padded element can reach a sum.

Steady-state contract: every (member, pool) lane rides EVERY epoch —
tag-equal lanes go as self-compares whose outputs are discarded at
commit (the solo cache-replay short-circuit still supplies their
stats) — so the stacked executable's input structure is constant
across steady epochs and books 0 compiles; a changed lane structure
(pool create/split/resize, member retirement) is a structural epoch by
construction.  Member engines receive a zero jit-delta (the shared
batch compile cannot be attributed to ONE member); the fleet books the
batch-level delta itself.

The whole stack checkpoints atomically into ONE file (every member's
`_state()` slice plus the pinned member list); resume refuses any
drift in cluster count, order, or any single member's spec string with
a per-cluster diff.

Deliberately shared process state across members: `obs.health` and the
"sim" timeline series interleave member samples (observation runs
after the digest update, so this is digest-invisible by construction).
"""

from __future__ import annotations

import time

import numpy as np

from ceph_tpu import obs
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.fleet import pareto as pareto_mod
from ceph_tpu.fleet.spec import FleetMember, parse_fleet
from ceph_tpu.runtime import Checkpoint, faults
from ceph_tpu.sim.lifetime import LifetimeSim
from ceph_tpu.utils import knobs
from ceph_tpu.utils.dout import subsys_logger

_log = subsys_logger("sim")

_FL = obs.logger_for("fleet")
_FL.add_u64("epochs", "fleet epoch batches stepped")
_FL.add_u64("cluster_epochs", "member cluster-epochs advanced")
_FL.add_u64("stacked_lanes",
            "pool lanes reduced through the stacked dispatch")
_FL.add_u64("host_lanes",
            "pool lanes accounted host-side (ref members or "
            "device-loss degradation)")
_FL.add_u64("structural_epochs",
            "fleet epochs with a structural member epoch or a changed "
            "lane structure")
_FL.add_u64("steady_epochs",
            "fleet epochs with unchanged lane structure")
_FL.add_u64("steady_compiles",
            "compiles booked during steady fleet epochs (contract: 0)")
_FL.add_u64("checkpoints", "fleet stack checkpoints flushed")
_FL.add_time_avg("epoch_seconds", "one fleet epoch batch wall time")


def _build_stack_account():
    """The stacked reducer: tuple-of-lanes in, [L, 6] stats + per-lane
    moved rows out.  Pure restack of `lifetime._epoch_stats`'s formula
    set under vmap — the two must never diverge (per-member digest
    equality depends on it), which is why the body calls the same
    `core/reduce` helpers the solo kernel does."""
    import jax
    import jax.numpy as jnp

    from ceph_tpu.core import reduce

    def _lane_stats(prev, rows, n, size, tol):
        real = jnp.arange(rows.shape[0]) < n
        occ = reduce.result_sizes(rows)
        size = size.astype(jnp.int32)
        tol = tol.astype(jnp.int32)
        degraded = jnp.sum((real & (occ < size)).astype(jnp.int64))
        unmapped = jnp.sum((real & (occ == 0)).astype(jnp.int64))
        at_risk = jnp.sum(
            (real & (occ < size - tol)).astype(jnp.int64))
        dup = jnp.sum(
            (real & reduce.duplicate_rows(rows)).astype(jnp.int64))
        moved_rows = jnp.sum(
            (reduce.moved_in_lanes(prev, rows) & real[:, None])
            .astype(jnp.int64), axis=1)
        moved = jnp.sum(moved_rows)
        remapped = jnp.sum(
            (real & reduce.changed_rows(prev, rows))
            .astype(jnp.int64))
        return jnp.stack(
            [degraded, unmapped, at_risk, dup, moved, remapped]), \
            moved_rows

    def _stacked(prevs, rowss, ns, sizes, tols):
        nmax = max(r.shape[0] for r in rowss)
        wmax = max(r.shape[1] for r in rowss)

        def pad(x):
            return jnp.pad(
                x, ((0, nmax - x.shape[0]), (0, wmax - x.shape[1])),
                constant_values=ITEM_NONE)

        sp = jnp.stack([pad(p) for p in prevs])
        sr = jnp.stack([pad(r) for r in rowss])
        stats, moved = jax.vmap(_lane_stats)(sp, sr, ns, sizes, tols)
        # each lane's moved rows slice back to its natural row count
        # (static shapes): the recovery queue enqueues from them at the
        # same shape the solo kernel would have produced
        moved_out = tuple(moved[i, :r.shape[0]]
                          for i, r in enumerate(rowss))
        return stats, moved_out

    def _key(prevs, rowss, ns, sizes, tols):
        # the default signature maps tuples to "tuple": it cannot see
        # the per-lane shapes that actually drive retraces
        return (tuple((tuple(p.shape), str(p.dtype)) for p in prevs),
                tuple((tuple(r.shape), str(r.dtype)) for r in rowss),
                tuple(ns.shape))

    return obs.JitAccount(jax.jit(_stacked), _FL, "stack_stats",
                          key_fn=_key)


_STACK_ACCT = None


def _stack_account():
    global _STACK_ACCT
    if _STACK_ACCT is None:
        _STACK_ACCT = _build_stack_account()
    return _STACK_ACCT


def _zero_delta() -> dict:
    return {"compiles": 0, "cache_hits": 0, "retraces": 0,
            "pipe_cache_hits": 0, "pipe_cache_misses": 0}


def _spec_diff(have: str, want: str) -> list[str]:
    """Per-field diff of two Scenario.spec() strings (field order is
    fixed by the dataclass, so a dict compare is complete)."""
    ha = dict(it.split("=", 1) for it in have.split(",") if "=" in it)
    wa = dict(it.split("=", 1) for it in want.split(",") if "=" in it)
    out = []
    for k in list(ha) + [k for k in wa if k not in ha]:
        if ha.get(k) != wa.get(k):
            out.append(f"{k}: checkpoint {ha.get(k)!r} != "
                       f"requested {wa.get(k)!r}")
    if not out and have != want:
        out.append(f"spec: checkpoint {have!r} != requested {want!r}")
    return out


class FleetSim:
    """N pinned clusters advanced in lockstep through one stacked
    dispatch per epoch batch."""

    def __init__(self, members: list[FleetMember], checkpoint=None,
                 resume: bool = False, mesh=None,
                 balancer_backend: str | None = "device_loop"):
        if not members:
            raise ValueError("fleet has no members")
        self.members = list(members)
        self.mesh = mesh
        self.balancer_backend = balancer_backend
        self.stack = knobs.get("CEPH_TPU_FLEET_STACK", "1") != "0"
        self.checkpoint_every = int(
            knobs.get("CEPH_TPU_FLEET_CHECKPOINT_EVERY", "50"))
        self.steps = 0
        self.structural_epochs = 0
        self.steady_epochs = 0
        self.steady_compiles = 0
        self.steady_pipe_misses = 0
        self.total_compiles = 0
        self.resumed_from: int | None = None
        self._cluster_epochs = 0
        self._cluster_epochs_this_proc = 0
        self._wall_this_proc = 0.0
        self._prev_sig = None

        self.ck = Checkpoint(checkpoint, resume=resume) \
            if checkpoint else None
        state = (self.ck.data.get("fleet")
                 if (self.ck is not None and resume) else None)
        if resume and state is None:
            raise ValueError(
                f"--resume: checkpoint {checkpoint!r} has no fleet "
                "state to resume from")
        slices: list[dict | None] = [None] * len(self.members)
        if state is not None:
            self._validate_resume(state)
            self.steps = int(state["epoch"])
            self.resumed_from = self.steps
            c = state.get("counters") or {}
            self.structural_epochs = int(c.get("structural_epochs", 0))
            self.steady_epochs = int(c.get("steady_epochs", 0))
            self.steady_compiles = int(c.get("steady_compiles", 0))
            self.steady_pipe_misses = int(
                c.get("steady_pipe_misses", 0))
            self.total_compiles = int(c.get("total_compiles", 0))
            self._cluster_epochs = int(c.get("cluster_epochs", 0))
            slices = list(state["clusters"])
        self.engines: list[LifetimeSim] = []
        for m, sl in zip(self.members, slices):
            sim = LifetimeSim(m.scenario, backend=m.backend,
                              mesh=mesh, restore_state=sl)
            if m.backend == "jax" and balancer_backend:
                sim.balancer_options = {
                    "upmap_state_backend": balancer_backend}
            self.engines.append(sim)
        if state is not None:
            _log(1, f"fleet resumed at epoch {self.steps} "
                    f"({len(self.engines)} clusters)")

    @classmethod
    def from_spec(cls, spec: str, **kw) -> "FleetSim":
        return cls(parse_fleet(spec), **kw)

    # -- checkpoint/resume -------------------------------------------------

    def _validate_resume(self, state: dict) -> None:
        want = [(m.scenario.spec(), m.backend) for m in self.members]
        have = [(c["scenario"], c["backend"])
                for c in state.get("members", [])]
        diffs = []
        if len(have) != len(want):
            diffs.append(f"cluster count: checkpoint {len(have)} != "
                         f"requested {len(want)}")
        for i in range(min(len(have), len(want))):
            hs, hb = have[i]
            ws, wb = want[i]
            for line in _spec_diff(hs, ws):
                diffs.append(f"cluster {i}: {line}")
            if hb != wb:
                diffs.append(f"cluster {i}: backend: checkpoint "
                             f"{hb!r} != requested {wb!r}")
        if diffs:
            raise ValueError(
                "fleet checkpoint does not match the requested fleet "
                "(count, order, and every member's pinned spec must be "
                "identical):\n  " + "\n  ".join(diffs))

    def _state(self) -> dict:
        return {
            "epoch": self.steps,
            "members": [{"index": m.index,
                         "scenario": m.scenario.spec(),
                         "backend": m.backend}
                        for m in self.members],
            "clusters": [sim._state() for sim in self.engines],
            "counters": {
                "structural_epochs": self.structural_epochs,
                "steady_epochs": self.steady_epochs,
                "steady_compiles": self.steady_compiles,
                "steady_pipe_misses": self.steady_pipe_misses,
                "total_compiles": self.total_compiles,
                "cluster_epochs": self._cluster_epochs,
            },
        }

    def checkpoint(self) -> None:
        if self.ck is None:
            return
        self.ck.progress("fleet", self._state())
        _FL.inc("checkpoints")
        obs.instant("fleet.checkpoint", epoch=self.steps)

    # -- stepping ----------------------------------------------------------

    def live(self) -> list[LifetimeSim]:
        return [s for s in self.engines
                if s.steps < s.scenario.epochs]

    def warm(self) -> None:
        """Dispatch the stacked reducer once over the current lane
        structure (every lane as a self-compare, outputs discarded) so
        the first timed epoch runs warm — the fleet-level mirror of the
        solo engine's construction-time `_baseline` warmup."""
        if not self.stack:
            return
        lanes = []
        for sim in self.live():
            if sim.backend != "jax" or sim.state is None:
                continue
            for pid in sorted(sim.m.pools):
                try:
                    lane, _ = sim._plan_pool(pid)
                except Exception as exc:
                    if not faults.looks_like_device_loss(exc):
                        raise
                    continue
                lanes.append(dict(lane, prev=lane["rows"]))
        if not lanes:
            return
        try:
            stats, _ = self._dispatch(lanes)
            np.asarray(stats)
        except Exception as exc:
            if not faults.looks_like_device_loss(exc):
                raise

    def _dispatch(self, lanes: list[dict]):
        import jax.numpy as jnp

        prevs = tuple(l["prev"] for l in lanes)
        rowss = tuple(l["rows"] for l in lanes)
        ns = jnp.asarray([l["n"] for l in lanes], jnp.uint32)
        sizes = jnp.asarray([l["size"] for l in lanes], jnp.int32)
        tols = jnp.asarray([l["tol"] for l in lanes], jnp.int32)
        return _stack_account()(prevs, rowss, ns, sizes, tols)

    def _account(self, ctxs: list) -> dict:
        """Account every begun member's epoch: host engines through
        their own `_account_epoch`, stacked engines through one shared
        dispatch.  Returns {id(sim): (stats, skeys)}."""
        plans: dict[int, tuple] = {}
        lanes: list[tuple] = []  # (sim, lane) in dispatch order
        stacked_sims = []
        for sim, ctx in ctxs:
            e = ctx["e"]
            if not (self.stack and sim.backend == "jax"
                    and sim.state is not None):
                st, sk = sim._account_epoch(e)
                plans[id(sim)] = (st, set(sk))
                _FL.inc("host_lanes", len(st))
                continue
            stacked_sims.append(sim)
            stats: dict[int, dict] = {}
            skeys: set = set()
            for pid in sorted(sim.m.pools):
                try:
                    faults.check("epoch_apply", qual=str(e))
                    lane, skey = sim._plan_pool(pid)
                except Exception as exc:
                    if not faults.looks_like_device_loss(exc):
                        raise
                    sim._record_fallback(e, pid, exc)
                    st, skey = sim._account_pool(pid,
                                                 force_host=True)
                    stats[pid] = st
                    skeys.add(skey)
                    _FL.inc("host_lanes")
                    continue
                lanes.append((sim, lane))
                skeys.add(skey)
            plans[id(sim)] = (stats, skeys)
        if lanes:
            try:
                stats_dev, moved = self._dispatch(
                    [lane for _, lane in lanes])
                stats_np = obs.timed_fetch(_FL, "stack_stats",
                                           stats_dev)
            except Exception as exc:
                if not faults.looks_like_device_loss(exc):
                    raise
                # whole-batch device loss: every planned lane degrades
                # to the bit-exact host path, same digest
                for sim, lane in lanes:
                    sim._record_fallback(sim.steps + 1, lane["pid"],
                                         exc)
                    st, _ = sim._account_pool(lane["pid"],
                                              force_host=True)
                    plans[id(sim)][0][lane["pid"]] = st
                    _FL.inc("host_lanes")
            else:
                _FL.inc("stacked_lanes", len(lanes))
                for j, (sim, lane) in enumerate(lanes):
                    st = sim._commit_pool(lane, stats_np[j], moved[j])
                    plans[id(sim)][0][lane["pid"]] = st
        for sim in stacked_sims:
            sim._prune_removed_pools()
        return {k: (st, frozenset(sk))
                for k, (st, sk) in plans.items()}

    def step(self) -> list[dict]:
        """One fleet epoch: every live member advances one lifetime
        epoch; all stacked accounting rides one dispatch.  Returns the
        per-member step records (in member order)."""
        live = self.live()
        if not live:
            return []
        t0 = time.perf_counter()
        jit0 = obs.jit_counters()
        fspan = obs.span("fleet.epoch", epoch=self.steps + 1,
                         clusters=len(live))
        fspan.__enter__()
        ctxs: list[tuple] = []   # begun, not yet finished
        recs: list[dict] = []
        try:
            for sim in live:
                ctxs.append((sim, sim._step_begin(None)))
            plans = self._account(ctxs)
            for sim, ctx in list(ctxs):
                stats, skeys = plans[id(sim)]
                rec = sim._step_finish(ctx, stats, skeys,
                                       jit_delta=_zero_delta())
                ctxs.remove((sim, ctx))   # its span is closed now
                recs.append(rec)
        except BaseException:
            for _, ctx in ctxs:
                ctx["span"].__exit__(None, None, None)
            fspan.__exit__(None, None, None)
            raise
        fspan.__exit__(None, None, None)
        jd = obs.jit_counters_delta(jit0)
        compiles = jd["compiles"] + jd["retraces"]
        sig = tuple((id(sim), plans[id(sim)][1]) for sim in live)
        structural = (any(r["structural"] for r in recs)
                      or self._prev_sig is None
                      or sig != self._prev_sig)
        self._prev_sig = sig
        self.total_compiles += compiles
        if structural:
            self.structural_epochs += 1
            _FL.inc("structural_epochs")
        else:
            self.steady_epochs += 1
            self.steady_compiles += compiles
            self.steady_pipe_misses += jd["pipe_cache_misses"]
            _FL.inc("steady_epochs")
            if compiles:
                _FL.inc("steady_compiles", compiles)
                _log(1, f"fleet epoch {self.steps + 1}: steady batch "
                        f"booked {compiles} compile(s) — stacked "
                        "structure contract broken")
        self.steps += 1
        self._cluster_epochs += len(live)
        self._cluster_epochs_this_proc += len(live)
        wall = time.perf_counter() - t0
        self._wall_this_proc += wall
        _FL.inc("epochs")
        _FL.inc("cluster_epochs", len(live))
        _FL.observe("epoch_seconds", wall)
        if (self.ck is not None and self.checkpoint_every
                and self.steps % self.checkpoint_every == 0):
            self.checkpoint()
        return recs

    def run(self, epochs: int | None = None,
            stop_after: int | None = None) -> dict:
        total = epochs if epochs is not None \
            else max(m.scenario.epochs for m in self.members)
        while self.steps < total and self.live():
            if stop_after is not None and self.steps >= stop_after:
                break
            self.step()
        self.checkpoint()
        return self.summary()

    # -- reporting ---------------------------------------------------------

    def digests(self) -> list[str]:
        return [sim.digest for sim in self.engines]

    def points(self) -> list[pareto_mod.Point]:
        """Per-member pareto points with front/dominated accounting
        resolved (feeds `pareto.triage_table`)."""
        pts = [pareto_mod.Point.from_summary(
            m.index, m.scenario.spec(), sim.summary())
            for m, sim in zip(self.members, self.engines)]
        pareto_mod.pareto_front(pts)
        return pts

    def summary(self) -> dict:
        member_rows = []
        points = []
        for m, sim in zip(self.members, self.engines):
            s = sim.summary()
            p = pareto_mod.Point.from_summary(
                m.index, m.scenario.spec(), s)
            points.append(p)
            member_rows.append({
                "index": m.index,
                "scenario": m.scenario.spec(),
                "backend": m.backend,
                "epochs": sim.steps,
                "digest": sim.digest,
                "steady_compiles": sim.steady_compiles,
                "invariant_violations": len(sim.violations),
                "pg_lost": sim.pg_lost_total,
                "pareto": dict(p.values),
            })
        front, dominated = pareto_mod.pareto_front(points)
        wall = self._wall_this_proc
        out = {
            "clusters": len(self.engines),
            "fleet_epochs": self.steps,
            "cluster_epochs": self._cluster_epochs,
            "stacked": self.stack,
            "balancer_backend": self.balancer_backend,
            "trace_once": {
                "structural_epochs": self.structural_epochs,
                "steady_epochs": self.steady_epochs,
                "steady_compiles": self.steady_compiles,
                "steady_pipe_misses": self.steady_pipe_misses,
                "total_compiles": self.total_compiles,
            },
            "wall_s": round(wall, 3),
            "cluster_epochs_per_sec": round(
                self._cluster_epochs_this_proc / wall, 2
            ) if wall else 0.0,
            "members": member_rows,
            "pareto": {
                "front": [dict(p.values, index=p.index)
                          for p in front],
                "front_size": len(front),
                "dominated": [{"index": p.index,
                               "dominated_by": p.dominated_by}
                              for p in dominated],
            },
        }
        if self.resumed_from is not None:
            out["resumed_from"] = self.resumed_from
        return out
