"""Fleet sweep grammar: one string expands into N pinned clusters.

A fleet spec is a semicolon-separated directive list:

    base=<scenario items>      the Scenario every member starts from
                               (comma-separated key=value, the exact
                               `Scenario.parse` grammar)
    axis=<key>:v1|v2|...       sweep axis: the cross-product over every
                               `axis=` directive (in declaration order)
                               expands into one member per combination
    clusters=N                 cycle the expanded combinations up to N
                               members; repetition r offsets `seed` by
                               r so repeated combinations stay
                               heterogeneous (never applied when seed
                               is itself a swept axis value of the
                               member — the pinned spec() wins)
    cluster=<i>:k=v,k=v        explicit post-expansion overrides for
                               member i (any Scenario field, or a
                               fleet-level knob)
    backend=jax|ref            fleet-level knob: every member's engine
                               backend (per-member override via
                               `cluster=i:backend=...`)

Example:

    base=epochs=16,pgs=64,ec=2+1;axis=seed:1|2|3;axis=p_death:0.02|0.1;
    clusters=12;backend=jax;cluster=0:correlated=1

Expansion yields `FleetMember`s whose `scenario.spec()` strings are
PINNED: the fleet checkpoint stores them verbatim, and resume refuses
any drift (count, order, or any single member's spec) with a
per-cluster diff — a resumed fleet can never silently mix
configurations.

`SWEEP_AXES` is the curated axis registry (pure dict literal: the
graftlint `sweep-grammar` pass literal_evals it without importing).
Every key must name a real `Scenario` dataclass field, appear in the
README sweep-grammar table, and be forced by at least one test; an
`axis=` directive outside the registry is a parse error, so the
registry IS the sweep surface.  `FLEET_KNOBS` are the fleet-level keys
that are not Scenario fields.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, fields

from ceph_tpu.sim.lifetime import Scenario

# Curated sweep axes: key -> why you would sweep it.  Keep this a pure
# dict literal (graftlint `sweep-grammar` literal_evals it); every key
# must be a `dataclasses.fields(Scenario)` name.
SWEEP_AXES: dict[str, str] = {
    "seed": "chaos trajectory replicas of one configuration",
    "epochs": "lifetime length (shorter screening vs longer soak)",
    "pgs": "replicated-pool scale (pg_num of the base pool)",
    "ec": "erasure profile k+m (redundancy vs overhead frontier)",
    "ec_pgs": "EC-pool scale",
    "hosts": "initial cluster width (failure-domain count)",
    "p_flap": "transient-failure pressure",
    "p_death": "permanent-loss pressure (durability stressor)",
    "correlated": "independent vs correlated failure regime",
    "recovery_mbps": "recovery-pipe budget (the repair/risk trade)",
    "max_backfills": "per-OSD recovery concurrency budget",
    "osd_mbps": "per-OSD bandwidth clients and recovery share",
    "balance_every": "mgr balancer cadence (0 disables)",
    "workload": "client traffic on/off (served_qps pareto axis)",
    "base_qps": "client load level",
}

# Fleet-level member keys that are NOT Scenario fields.  Same literal
# contract as SWEEP_AXES; the lint additionally refuses a knob that
# shadows a Scenario field (the grammar would become ambiguous).
FLEET_KNOBS: dict[str, str] = {
    "backend": "per-member engine backend: jax (device accounting, "
               "rides the stacked fleet dispatch) or ref (host mirror)",
}


@dataclass
class FleetMember:
    """One pinned cluster of the fleet: an index, a fully-resolved
    Scenario, and its engine backend."""

    index: int
    scenario: Scenario
    backend: str = "jax"

    def spec(self) -> str:
        return self.scenario.spec()


def _scenario_keys() -> set:
    return {f.name for f in fields(Scenario)}


def _split_axis(value: str) -> tuple[str, list[str]]:
    key, sep, vals = value.partition(":")
    key = key.strip()
    if not sep or not vals:
        raise ValueError(
            f"bad axis directive {value!r}: want axis=key:v1|v2|...")
    out = [v.strip() for v in vals.split("|") if v.strip()]
    if not out:
        raise ValueError(f"axis {key!r} sweeps no values")
    known = set(SWEEP_AXES) | set(FLEET_KNOBS)
    if key not in known:
        raise ValueError(
            f"unknown sweep axis {key!r} (declared axes: "
            f"{sorted(known)}; add new ones to fleet/spec.py "
            "SWEEP_AXES — the graftlint sweep-grammar pass holds "
            "them to the README table and the test suite)")
    return key, out


def parse_fleet(spec: str) -> list[FleetMember]:
    """Expand one fleet spec string into its pinned members."""
    base_items = ""
    axes: list[tuple[str, list[str]]] = []
    overrides: dict[int, dict[str, str]] = {}
    clusters = None
    fleet_kv: dict[str, str] = {"backend": "jax"}
    for directive in (spec or "").split(";"):
        directive = directive.strip()
        if not directive:
            continue
        key, sep, val = directive.partition("=")
        key, val = key.strip(), val.strip()
        if not sep:
            raise ValueError(f"bad fleet directive {directive!r}")
        if key == "base":
            base_items = val
        elif key == "axis":
            axes.append(_split_axis(val))
        elif key == "clusters":
            clusters = int(val)
            if clusters < 1:
                raise ValueError(f"clusters={clusters}: want >= 1")
        elif key == "cluster":
            idx_s, sep2, items = val.partition(":")
            if not sep2:
                raise ValueError(
                    f"bad cluster override {directive!r}: want "
                    "cluster=<index>:k=v,k=v")
            kv = overrides.setdefault(int(idx_s), {})
            for item in items.split(","):
                item = item.strip()
                if not item:
                    continue
                k, s3, v = item.partition("=")
                if not s3:
                    raise ValueError(
                        f"bad cluster override item {item!r}")
                kv[k.strip()] = v.strip()
        elif key in FLEET_KNOBS:
            fleet_kv[key] = val
        else:
            raise ValueError(
                f"unknown fleet directive {key!r} (known: base, axis, "
                f"clusters, cluster, {sorted(FLEET_KNOBS)})")

    sc_keys = _scenario_keys()
    combos = [dict()]
    if axes:
        combos = [
            dict(zip((k for k, _ in axes), vals))
            for vals in itertools.product(*(v for _, v in axes))
        ]
    total = clusters if clusters is not None else len(combos)
    members: list[FleetMember] = []
    for i in range(total):
        combo = combos[i % len(combos)]
        rep = i // len(combos)
        items = [base_items] if base_items else []
        items += [f"{k}={v}" for k, v in combo.items()
                  if k in sc_keys]
        backend = fleet_kv["backend"]
        if "backend" in combo:
            backend = combo["backend"]
        sc = Scenario.parse(",".join(items))
        if rep and "seed" not in combo:
            sc.seed += rep  # repetition offset: stay heterogeneous
        ov = overrides.get(i, {})
        if ov:
            merged = {k: v for k, v in
                      (it.split("=", 1)
                       for it in sc.spec().split(","))}
            for k, v in ov.items():
                if k in FLEET_KNOBS:
                    continue
                if k not in sc_keys:
                    raise ValueError(
                        f"cluster={i} override {k!r} is neither a "
                        "Scenario field nor a fleet knob")
                merged[k] = v
            sc = Scenario.parse(
                ",".join(f"{k}={v}" for k, v in merged.items()))
            if "backend" in ov:
                backend = ov["backend"]
        if backend not in ("jax", "ref"):
            raise ValueError(
                f"cluster={i}: backend={backend!r} (want jax or ref)")
        members.append(FleetMember(index=i, scenario=sc,
                                   backend=backend))
    for i in overrides:
        if i >= total:
            raise ValueError(
                f"cluster={i} override targets a member beyond the "
                f"fleet size {total}")
    return members
