"""Cluster health model: Ceph-coded checks over already-fetched state.

The reference's `ceph status` collapses cluster state into
HEALTH_OK/WARN/ERR plus coded checks (OSD_DOWN, PG_DEGRADED, ...).  This
module is that model for the graft: a registry of check codes, a
process-global current-checks table, and an `evaluate()` helper the sim
/ serve / CLI layers feed with **host integers they already computed**.

Purity contract: health evaluation is a pure observer.  It never
launches device work, never forces a fetch, and never contributes to
lifetime replay digests — callers pass it numbers that already crossed
the device boundary for accounting.  Disabling it (`CEPH_TPU_HEALTH=0`)
must therefore be bit-invisible to every digest and compile counter,
which bench and tests/test_health.py pin.

Check codes are a lint-enforced contract (tools/graftlint `health-check`
pass): `HEALTH_CHECKS` below must stay a module-level dict **literal**
so the linter can read it without importing, production
`raise_check`/`clear` call sites must use declared codes, and every
declared code must be exercised by tests/.

Muting mirrors `ceph health mute`: codes listed in
`CEPH_TPU_HEALTH_MUTE` (comma-separated) still evaluate and dump, but
stop contributing to the summarized status.
"""

from __future__ import annotations

import threading

from ceph_tpu.obs import trace
from ceph_tpu.obs.prometheus import escape_label
from ceph_tpu.utils import knobs
from ceph_tpu.utils.perf_counters import logger_for

# The compiled-in check registry: code -> what raises it.  Keep this a
# pure dict literal (graftlint health-check literal_evals it).
HEALTH_CHECKS: dict[str, str] = {
    "OSD_DOWN": "existing OSDs are down (exists bit set, up bit clear)",
    "PG_DEGRADED": "PGs have fewer valid replicas/shards than pool size",
    "PG_UNMAPPED": "PGs have no valid mapping at all (data unavailable)",
    "PG_AT_RISK": "PGs lost more shards than the EC profile tolerates",
    "RECOVERY_BACKLOG": "recovery queue holds unrecovered bytes",
    "SLO_BURN": "serve SLO error budget is burning (see serve/slo.py)",
    "DEVICE_DEGRADED": "runtime fell back to host mapping after device loss",
    "DATA_LOSS": "PGs lost more chunks than tolerance before recovery "
                 "drained — irreversible; never auto-clears (raised "
                 "directly, outside evaluate(), so only an explicit "
                 "operator clear()/reset() removes it)",
}

OK = "HEALTH_OK"
WARN = "HEALTH_WARN"
ERR = "HEALTH_ERR"
_RANK = {OK: 0, WARN: 1, ERR: 2}

_L = logger_for("health")
_L.add_u64("checks_raised", "health checks raised (OK->non-OK transitions)")
_L.add_u64("checks_cleared", "health checks cleared (non-OK->OK transitions)")
_L.add_u64("evaluations", "evaluate() calls over already-fetched state")

_lock = threading.Lock()
# code -> {"severity", "summary", "count", "detail": [..]}
_checks: dict[str, dict] = {}


def enabled() -> bool:
    return knobs.get("CEPH_TPU_HEALTH", "1") != "0"


def rank(severity: str) -> int:
    """Numeric rank of a status string (OK=0, WARN=1, ERR=2) — the
    encoding timelines and Prometheus gauges record."""
    return _RANK[severity]


def muted() -> frozenset[str]:
    raw = knobs.get("CEPH_TPU_HEALTH_MUTE", "")
    return frozenset(c.strip() for c in raw.split(",") if c.strip())


def raise_check(code: str, severity: str, summary: str,
                detail: tuple[str, ...] = (), count: int = 0) -> bool:
    """Raise (or refresh) a check; True on the OK->raised transition."""
    if code not in HEALTH_CHECKS:
        raise KeyError(f"undeclared health check code {code!r}")
    if severity not in (WARN, ERR):
        raise ValueError(f"severity must be {WARN} or {ERR}, got {severity!r}")
    with _lock:
        fresh = code not in _checks
        _checks[code] = {
            "severity": severity,
            "summary": summary,
            "count": int(count),
            "detail": list(detail)[:8],
        }
    if fresh:
        _L.inc("checks_raised")
        trace.instant("health.raised", code=code, severity=severity)
    return fresh


def clear(code: str) -> bool:
    """Clear a check; True on the raised->OK transition."""
    if code not in HEALTH_CHECKS:
        raise KeyError(f"undeclared health check code {code!r}")
    with _lock:
        was = _checks.pop(code, None) is not None
    if was:
        _L.inc("checks_cleared")
        trace.instant("health.cleared", code=code)
    return was


def _set(code: str, active: bool, severity: str, summary: str,
         count: int = 0, detail: tuple[str, ...] = ()) -> None:
    if active:
        raise_check(code, severity, summary, detail=detail, count=count)
    else:
        clear(code)


def evaluate(*, osds_down: int = 0, osd_count: int = 0, degraded: int = 0,
             unmapped: int = 0, at_risk: int = 0, backlog_gb: float = 0.0,
             device_degraded: int = 0,
             detail: tuple[str, ...] = ()) -> str:
    """Map standard host-side reductions onto the standard checks and
    return the summarized status.  Every argument is a plain int/float
    the caller already holds — this function is observation only.

    Latched checks (DATA_LOSS) are deliberately NOT evaluated here:
    `_set` would auto-clear them the first healthy epoch.  Callers
    raise them directly via `raise_check`, and the returned status
    still reflects them (status() ranks every raised check)."""
    if not enabled():
        return OK
    _L.inc("evaluations")
    _set("OSD_DOWN", osds_down > 0, WARN,
         f"{osds_down}/{osd_count} osds down", count=osds_down, detail=detail)
    _set("PG_DEGRADED", degraded > 0, WARN,
         f"{degraded} pgs degraded", count=degraded)
    _set("PG_UNMAPPED", unmapped > 0, ERR,
         f"{unmapped} pgs unmapped", count=unmapped)
    _set("PG_AT_RISK", at_risk > 0, ERR,
         f"{at_risk} pgs past EC tolerance", count=at_risk)
    _set("RECOVERY_BACKLOG", backlog_gb > 0, WARN,
         f"{backlog_gb:.3f} GB awaiting recovery", count=int(backlog_gb))
    _set("DEVICE_DEGRADED", device_degraded > 0, WARN,
         f"{device_degraded} device-loss fallback(s) to host mapping",
         count=device_degraded)
    return status()


def checks() -> dict[str, dict]:
    """Snapshot of the currently-raised checks (copies)."""
    with _lock:
        return {c: dict(v) for c, v in _checks.items()}


def status() -> str:
    """Worst severity among currently-raised, non-muted checks."""
    m = muted()
    worst = OK
    with _lock:
        for code, v in _checks.items():
            if code in m:
                continue
            if _RANK[v["severity"]] > _RANK[worst]:
                worst = v["severity"]
    return worst


def summary() -> dict:
    """The `ceph status`-shaped view: status + per-check one-liners."""
    snap = checks()
    m = muted()
    return {
        "status": status(),
        "checks": {
            code: {
                "severity": v["severity"],
                "summary": v["summary"],
                "count": v["count"],
                "muted": code in m,
            }
            for code, v in sorted(snap.items())
        },
    }


def dump() -> dict:
    """Full detail view for `health` on the admin socket / daemon CLI."""
    out = summary()
    snap = checks()
    for code, v in out["checks"].items():
        v["detail"] = snap[code]["detail"]
    out["muted"] = sorted(muted())
    out["registry"] = dict(HEALTH_CHECKS)
    return out


def reset() -> None:
    with _lock:
        _checks.clear()


def prometheus_gauges() -> str:
    """`ceph_tpu_health_status` (0/1/2) plus one labelled gauge per
    raised check.  Check summaries embed operator-visible strings, so
    label values go through the shared escaper."""
    snap = checks()
    m = muted()
    lines = [
        "# HELP ceph_tpu_health_status cluster health (0=OK 1=WARN 2=ERR)",
        "# TYPE ceph_tpu_health_status gauge",
        f"ceph_tpu_health_status {_RANK[status()]}",
        "# HELP ceph_tpu_health_check per-check count (labels: code, "
        "severity, summary, muted)",
        "# TYPE ceph_tpu_health_check gauge",
    ]
    for code, v in sorted(snap.items()):
        lines.append(
            f'ceph_tpu_health_check{{code="{escape_label(code)}",'
            f'severity="{escape_label(v["severity"])}",'
            f'summary="{escape_label(v["summary"])}",'
            f'muted="{int(code in m)}"}} {int(v["count"])}'
        )
    return "\n".join(lines) + "\n"
