"""Prometheus text exposition (format 0.0.4) over the perf registry.

Mapping from perf-counter kinds:

    u64        -> counter      ceph_tpu_<group>_<key>
    avg        -> summary      _sum / _count
    time_avg   -> summary      _sum / _count (seconds)
    histogram  -> histogram    cumulative _bucket{le=...} / _sum / _count

Group and key names are sanitized to the Prometheus metric charset
([a-zA-Z_][a-zA-Z0-9_]*); '.' and '-' become '_'.
"""

from __future__ import annotations

import re

from ceph_tpu.utils.perf_counters import perf_schema

_BAD = re.compile(r"[^a-zA-Z0-9_]")


def _name(group: str, key: str) -> str:
    return _BAD.sub("_", f"ceph_tpu_{group}_{key}")


def _fmt(v: float) -> str:
    if isinstance(v, float) and v != v:  # NaN
        return "NaN"
    return repr(v) if isinstance(v, float) else str(v)


def prometheus_text(dump: dict, schema: dict | None = None) -> str:
    """Render a perf_dump() dict; `schema` (perf_schema()) supplies kinds
    and HELP strings — without it kinds are inferred from value shapes."""
    schema = schema if schema is not None else perf_schema()
    lines: list[str] = []
    for group in sorted(dump):
        for key in sorted(dump[group]):
            v = dump[group][key]
            name = _name(group, key)
            meta = (schema.get(group) or {}).get(key, {})
            desc = meta.get("description") or f"{group}.{key}"
            kind = meta.get("type")
            if kind is None:  # infer
                if isinstance(v, dict):
                    kind = "histogram" if "buckets" in v else "avg"
                else:
                    kind = "u64"
            lines.append(f"# HELP {name} {desc}")
            if kind == "u64":
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {_fmt(v)}")
            elif kind in ("avg", "time_avg"):
                lines.append(f"# TYPE {name} summary")
                lines.append(f"{name}_sum {_fmt(float(v['sum']))}")
                lines.append(f"{name}_count {v['avgcount']}")
            else:  # histogram
                lines.append(f"# TYPE {name} histogram")
                cum = 0
                for bound, n in zip(v["bounds"], v["buckets"]):
                    cum += n
                    lines.append(
                        f'{name}_bucket{{le="{_fmt(float(bound))}"}} {cum}'
                    )
                lines.append(f'{name}_bucket{{le="+Inf"}} {sum(v["buckets"])}')
                lines.append(f"{name}_sum {_fmt(float(v['sum']))}")
                lines.append(f"{name}_count {v['count']}")
    return "\n".join(lines) + "\n"
