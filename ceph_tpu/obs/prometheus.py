"""Prometheus text exposition (format 0.0.4) over the perf registry.

Mapping from perf-counter kinds (the kind comes from the declaration
schema — `perf_schema()` — never from duck-typing the dump's value
shapes, which broke the moment two kinds shared a shape):

    u64        -> counter      ceph_tpu_<group>_<key>
    avg        -> summary      _sum / _count
    time_avg   -> summary      _sum / _count (seconds)
    histogram  -> histogram    cumulative _bucket{le=...} / _sum / _count
    quantile   -> histogram    same series (Prometheus-side quantile
                               estimation stays possible); the in-process
                               p50/p90/p99 estimates live in `perf dump`

Group and key names are sanitized to the Prometheus metric charset
([a-zA-Z_][a-zA-Z0-9_]*); '.' and '-' become '_'.
"""

from __future__ import annotations

import re

from ceph_tpu.utils.perf_counters import perf_schema

_BAD = re.compile(r"[^a-zA-Z0-9_]")


def _name(group: str, key: str) -> str:
    return _BAD.sub("_", f"ceph_tpu_{group}_{key}")


def escape_label(label: str) -> str:
    """Prometheus label-value escaping (`\\`, `"`, newline).  Any gauge
    whose label embeds an operator- or user-chosen string (plan names,
    health summaries, timeline series) must route through this — raw
    interpolation corrupts the exposition on the first quote."""
    return (label.replace("\\", "\\\\")
                 .replace('"', '\\"')
                 .replace("\n", "\\n"))


def _fmt(v: float) -> str:
    if isinstance(v, float) and v != v:  # NaN
        return "NaN"
    return repr(v) if isinstance(v, float) else str(v)


def _infer_kind(v) -> str | None:
    """Fallback for dumps with no schema entry (foreign snapshots, e.g.
    a BENCH_partial.json perf blob rendered offline).  Registry-backed
    dumps always resolve through the schema instead.  None means 'not a
    counter value, skip it' — a saved `perf dump` reply also carries the
    embedded executables registry section, whose dicts are not
    counters."""
    if isinstance(v, dict):
        if "buckets" in v:
            return "quantile" if "p50" in v else "histogram"
        if "avgcount" in v and "sum" in v:
            return "avg"
        return None
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return "u64"
    return None


def prometheus_text(dump: dict, schema: dict | None = None) -> str:
    """Render a perf_dump() dict; `schema` (perf_schema()) supplies the
    authoritative kinds and HELP strings."""
    schema = schema if schema is not None else perf_schema()
    lines: list[str] = []
    for group in sorted(dump):
        grp = dump[group]
        if not isinstance(grp, dict) or group == "executables":
            # a saved admin-socket `perf dump` reply embeds the
            # executables registry section; it has its own exposition
            # (executables.prometheus_gauges) — rendering its scalar
            # fields as counters here would collide with those series
            continue
        for key in sorted(grp):
            v = grp[key]
            name = _name(group, key)
            meta = (schema.get(group) or {}).get(key, {})
            desc = meta.get("description") or f"{group}.{key}"
            kind = meta.get("type") or _infer_kind(v)
            if kind is None:
                continue
            lines.append(f"# HELP {name} {desc}")
            if kind == "u64":
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {_fmt(v)}")
            elif kind in ("avg", "time_avg"):
                lines.append(f"# TYPE {name} summary")
                lines.append(f"{name}_sum {_fmt(float(v['sum']))}")
                lines.append(f"{name}_count {v['avgcount']}")
            elif kind in ("histogram", "quantile"):
                lines.append(f"# TYPE {name} histogram")
                cum = 0
                for bound, n in zip(v["bounds"], v["buckets"]):
                    cum += n
                    lines.append(
                        f'{name}_bucket{{le="{_fmt(float(bound))}"}} {cum}'
                    )
                lines.append(f'{name}_bucket{{le="+Inf"}} {sum(v["buckets"])}')
                lines.append(f"{name}_sum {_fmt(float(v['sum']))}")
                lines.append(f"{name}_count {v['count']}")
            else:  # an unknown declared kind is a schema bug: say so
                lines.append(f"# TYPE {name} untyped")
                lines.append(f"{name} NaN")
    return "\n".join(lines) + "\n"
