"""JAX-aware time accounting: compile vs dispatch vs device→host transfer.

A jitted entry point's first call traces + compiles (tens of seconds for
the big pipeline kernels); steady-state calls only dispatch.  A headline
number that mixes the two is exactly the diagnostic gap observability is
meant to close (BENCH_r05: 24.7s cold compiles hidden in one number), so
every instrumented jit callsite routes through `JitAccount`, which books
the two phases into separate counters:

    <key>_compiles          u64       how many cold (compile) calls
    <key>_compile_seconds   time_avg  wall time of cold calls
    <key>_dispatch_seconds  time_avg  wall time of steady-state calls

and wraps each call in a span ("<group>.<key>.compile" / ".dispatch").

Cold-call detection is per (wrapper, input-shape-signature): jax retraces
per shape, and the instrumented drivers call each wrapper with a fixed
block shape, so the first call per signature IS the compile.  Dispatch
timing does not block on the result — it measures enqueue cost, honest
for async callers; callers that want completion timed use `timed_fetch`
(device→host transfer + forced completion) which books

    <key>_fetch_seconds     time_avg  d2h transfer (np.asarray) wall time
"""

from __future__ import annotations

import time

import numpy as np

from ceph_tpu.obs import trace
from ceph_tpu.utils.perf_counters import PerfCounters


def _sig(args) -> tuple:
    """Shape signature of positional args (arrays by shape+dtype, dicts
    by sorted keys, scalars by type)."""
    out = []
    for a in args:
        shape = getattr(a, "shape", None)
        if shape is not None:
            out.append((tuple(shape), str(getattr(a, "dtype", ""))))
        elif isinstance(a, dict):
            out.append(tuple(sorted(a)))
        else:
            out.append(type(a).__name__)
    return tuple(out)


class JitAccount:
    """Wrap a jitted callable with compile/dispatch accounting.

    `key_fn(*args)` overrides the default shape signature when the
    wrapped function's recompile granularity is not purely shape-based
    (e.g. a matrix passed as static content retraces per matrix);
    `span` overrides the span base name and `span_args(*args)` supplies
    per-call span arguments; `exec_record` (obs.executables.ExecRecord)
    links the wrapper to its entry in the executable registry, which
    then receives the same compile/dispatch timings; `warm_hist` names
    an additional quantile counter (declared here, shared across
    wrappers) that receives ONLY warm dispatch times — several wrappers
    feeding one logical distribution (every map_block dispatch,
    whichever kernel serves it) without cold compiles polluting the
    tail."""

    def __init__(
        self, fn, logger: PerfCounters, key: str,
        key_fn=None, span: str | None = None, span_args=None,
        exec_record=None, warm_hist: str | None = None,
    ):
        self.fn = fn
        self.log = logger
        self.key = key
        self.key_fn = key_fn
        self.span = span or f"{logger.name}.{key}"
        self.span_args = span_args
        self.exec_record = exec_record
        self._seen: set[tuple] = set()
        logger.add_u64(f"{key}_compiles", "cold (trace+compile) calls")
        logger.add_u64(
            f"{key}_cache_hits",
            "calls served by an already-compiled executable",
        )
        logger.add_u64(
            f"{key}_retraces",
            "recompiles beyond the first (new input signature on a warm "
            "wrapper)",
        )
        logger.add_time_avg(f"{key}_compile_seconds", "cold call wall time")
        logger.add_time_avg(
            f"{key}_dispatch_seconds", "steady-state dispatch wall time"
        )
        # tail latency, not just the mean: p50/p99 per dump
        logger.add_quantile(
            f"{key}_dispatch_hist",
            "steady-state dispatch wall-time distribution",
        )
        self.warm_hist = warm_hist
        if warm_hist:
            logger.add_quantile(
                warm_hist,
                "steady-state dispatch wall-time distribution "
                "(shared across kernels; cold compiles excluded)",
            )

    def __call__(self, *args, **kw):
        sig = self.key_fn(*args) if self.key_fn else _sig(args)
        cold = sig not in self._seen
        phase = "compile" if cold else "dispatch"
        extra = self.span_args(*args) if self.span_args else {}
        with trace.span(f"{self.span}.{phase}", **extra):
            t0 = time.perf_counter()
            out = self.fn(*args, **kw)
            dt = time.perf_counter() - t0
        if cold:
            if self._seen:
                self.log.inc(f"{self.key}_retraces")
            self._seen.add(sig)
            self.log.inc(f"{self.key}_compiles")
            self.log.observe(f"{self.key}_compile_seconds", dt)
        else:
            self.log.inc(f"{self.key}_cache_hits")
            self.log.observe(f"{self.key}_dispatch_seconds", dt)
            self.log.observe(f"{self.key}_dispatch_hist", dt)
            if self.warm_hist:
                self.log.observe(self.warm_hist, dt)
        if self.exec_record is not None:
            self.exec_record.note_call(
                dt, cold, args if cold else None, kw if cold else None
            )
        return out


def timed_fetch(logger: PerfCounters, key: str, x):
    """np.asarray(x) with the d2h transfer (which also forces completion
    of the producing computation) booked into <key>_fetch_seconds, and
    its distribution into the <key>_fetch_hist quantile counter."""
    name = f"{key}_fetch_seconds"
    hist = f"{key}_fetch_hist"
    # declare-on-first-use: declares are idempotent, so re-declaring
    # on every call is safe (one lock acquisition, no state churn)
    logger.add_time_avg(name, "device->host transfer wall time")
    logger.add_quantile(hist, "device->host transfer time distribution")
    with trace.span(f"{logger.name}.{key}.fetch"):
        t0 = time.perf_counter()
        out = np.asarray(x)
        dt = time.perf_counter() - t0
    logger.observe(name, dt)
    logger.observe(hist, dt)
    return out
