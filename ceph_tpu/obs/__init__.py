"""Observability layer: spans + perf counters + JAX-aware accounting.

One import surface for the hot paths:

    from ceph_tpu import obs

    L = obs.logger_for("pipeline")        # perf-counter group
    L.add_u64("pgs_mapped")
    with obs.span("pipeline.map_block", pgs=n):
        ...
        L.inc("pgs_mapped", n)

Three cooperating pieces (each usable alone):

- `trace`: nested, thread-safe span tracer, env-gated via
  `CEPH_TPU_TRACE=<path>`, exported as Chrome trace-event JSON (open in
  Perfetto: https://ui.perfetto.dev).
- `perf_counters` (ceph_tpu.utils): the reference's perf-dump registry
  (u64 / avg / time_avg / histogram), exposed by
  `python -m ceph_tpu.cli.daemon perf dump|metrics` and, for live
  processes, the env-gated admin socket (`CEPH_TPU_ADMIN_SOCKET`).
- `jax_accounting`: compile vs dispatch vs device→host-transfer time per
  jitted entry point (first-call-per-shape = compile).

Importing this package is cheap (no jax import) and, when
`CEPH_TPU_ADMIN_SOCKET` is set, starts the admin-socket server.
"""

from __future__ import annotations

from ceph_tpu.obs import executables, placement, quantiles, spans, trace
from ceph_tpu.obs import health, timeline  # noqa: E402 (need trace first)
from ceph_tpu.obs.admin_socket import maybe_start_from_env
from ceph_tpu.obs.jax_accounting import JitAccount, timed_fetch
from ceph_tpu.obs.trace import (
    counter,
    flush,
    instant,
    set_trace_path,
    span,
    trace_path,
)
from ceph_tpu.utils.perf_counters import (
    UndeclaredCounterError,
    logger_for,
    perf_dump,
    perf_schema,
    reset_values,
)


def prometheus_text() -> str:
    """Prometheus text exposition of the whole perf registry, plus the
    executable-registry gauges (per-cache entry counts, compile seconds,
    dispatch totals), the placement-diagnostics per-source gauges, the
    health-check gauges, and the timeline latest-sample gauges."""
    from ceph_tpu.obs.prometheus import prometheus_text as _render

    return (_render(perf_dump()) + executables.prometheus_gauges()
            + placement.prometheus_gauges() + health.prometheus_gauges()
            + timeline.prometheus_gauges())


def jit_counters() -> dict:
    """Flat compile/cache totals summed across perf groups: the
    JitAccount `*_compiles` / `*_cache_hits` / `*_retraces` trios plus
    the _PIPE_CACHE hit/miss pair.  Callers (bench stage records, the
    cache-contract tests) diff two snapshots to get a per-phase delta."""
    out = {"compiles": 0, "cache_hits": 0, "retraces": 0,
           "pipe_cache_hits": 0, "pipe_cache_misses": 0}
    for grp in perf_dump().values():
        if not isinstance(grp, dict):
            continue
        for k, v in grp.items():
            if not isinstance(v, int):
                continue
            if k in ("pipe_cache_hits", "pipe_cache_misses"):
                out[k] += v
            elif k.endswith("_compiles"):
                out["compiles"] += v
            elif k.endswith("_cache_hits"):
                out["cache_hits"] += v
            elif k.endswith("_retraces"):
                out["retraces"] += v
    return out


def jit_counters_delta(before: dict) -> dict:
    now = jit_counters()
    return {k: now[k] - before[k] for k in now}


maybe_start_from_env()

__all__ = [
    "JitAccount",
    "UndeclaredCounterError",
    "counter",
    "executables",
    "flush",
    "health",
    "instant",
    "jit_counters",
    "jit_counters_delta",
    "logger_for",
    "perf_dump",
    "perf_schema",
    "placement",
    "prometheus_text",
    "quantiles",
    "reset_values",
    "set_trace_path",
    "span",
    "spans",
    "timed_fetch",
    "timeline",
    "trace",
    "trace_path",
]
