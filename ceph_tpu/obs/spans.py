"""Span registry — the single authoritative list of trace event names.

Every `obs.span("...")` / `obs.instant("...")` / `obs.counter("...")`
name in the tree must be declared here: a typo'd span name silently
orphans its trace events (nothing fails, Perfetto just shows a stray
track nobody is looking for), and the no-host-sync lint used to carry
its own hardcoded tuple of dispatch spans that could drift from the
instrumented code.  `tools/graftlint` (the `span-name` pass) checks the
literal call sites against this module statically, and the `host-sync`
pass takes the dispatch-span set from `DISPATCH_SPANS` instead of a
private copy.

Three kinds of entry:

- `SPANS`: complete ("ph":"X") span names -> one-line doc.  Names that
  serve as a base for derived events (JitAccount appends `.compile` /
  `.dispatch` / `.fetch`) are still declared once, by the base name.
- `INSTANTS` / `COUNTERS`: zero-duration markers and counter tracks.
- `PREFIXES`: allowed prefixes for dynamically built span names
  (f-strings); the static head of the f-string must match one of these.
  JitAccount's fully dynamic `f"{group}.{key}.{phase}"` names carry no
  static head and are exempt from the lint by construction.

Keep this module import-light: graftlint parses it as an AST (no
import), and `obs` re-exports it for runtime introspection.
"""

from __future__ import annotations

SPANS: dict[str, str] = {
    # osd/pipeline_jax.py + bench.py - the batched mapping pipeline
    "pipeline.map_block": "dispatch of one jitted fast-path block",
    "pipeline.rescue": "dispatch of exact-loop recompute of flagged lanes",
    "pipeline.fetch": "d2h fetch of finished mapping results",
    "pipeline.diagnose": "dispatch of one instrumented (with_diag) block",
    # crush/explain.py — placement-decision triage
    "crush.diag_batch": "instrumented rule-kernel batch (tries planes)",
    # bench.py drivers
    "bench.cold_pass": "first full mapping pass (includes compiles)",
    "bench.warm_pass": "steady-state full mapping pass",
    "bench.balancer": "balancer bench stage body",
    "bench.diff": "bench-trajectory diff against a prior BENCH series",
    # obs/ itself
    "obs.exec_analyze": "executable-registry cost-analysis sweep",
    # balancer/
    "balancer.map_pool": "DeviceState full-pool mapping pass",
    "balancer.pgs_of": "device membership query for one OSD",
    "balancer.build_state": "O(PGs) membership-state build",
    "balancer.round": "one greedy upmap optimizer round",
    "balancer.score_candidates": "one vectorized deviation-delta "
                                 "evaluation over a batch of "
                                 "prospective upmap changes",
    "balancer.device_loop": "one whole-plan device-resident optimizer "
                            "dispatch (every round of the greedy "
                            "inside one lax.while_loop)",
    # mgr/
    "mgr.map_pool": "eval distribution mapping pass for one pool",
    "mgr.pool_counts": "per-OSD pg/object/byte count reduction",
    "mgr.calc_eval": "full eval scoring pass",
    "mgr.optimize": "one Balancer.optimize() call",
    "mgr.do_upmap_pool": "upmap optimization of one pool",
    "mgr.execute": "plan application through apply_incremental",
    # ec/
    "ec.encode": "RS encode_chunks call",
    "ec.decode": "RS decode_chunks call",
    "ec.encode_batch": "batched multi-stripe encode",
    "ec.decode_batch": "batched multi-stripe decode",
    "ec.clay_encode": "Clay encode_chunks call",
    "ec.clay_decode": "Clay decode_chunks call",
    "ec.clay_repair": "Clay minimum-bandwidth single-chunk repair",
    "ec.gf_dispatch": "GF kernel dispatch (device work only)",
    # JitAccount span= bases (derived: .compile / .dispatch / .fetch)
    "ec.gf_matmul": "instrumented GF matmul entry (JitAccount base)",
    "ec.gf_matmul_batch": "instrumented batched GF matmul (JitAccount base)",
    # runtime/
    "runtime.acquire_backend": "ladder descent to a healthy backend",
    "runtime.probe": "one watchdogged device preflight probe",
    # osd/state.py — the device-resident ClusterState
    "state.apply": "one ClusterState.apply: classify + host model "
                   "advance + O(delta) device scatter (value) or "
                   "re-key (structural)",
    "state.rebuild": "structural re-key: CRUSH arrays rebuilt, operand "
                     "tables re-device_put, mappers reconstructed",
    "state.rows": "version-tagged device rows (re)build for one pool "
                  "(mapping dispatch + overlay fixup scatter)",
    "state.raw_fixup": "raw-kernel refresh of overlay-carrying PGs' "
                       "descent rows (fixed-shape dispatch, O(overlay) "
                       "fetch)",
    # sim/lifetime.py
    "sim.epoch": "one lifetime epoch: Incremental apply + remap + "
                 "device accounting + invariant checks",
    "sim.recovery": "one epoch's recovery-queue drain: per-PG enqueue "
                    "+ slot-limited priority drain against per-OSD "
                    "capacity (scalar fetches allowed: the epoch books "
                    "exact int64 totals)",
    "sim.workload": "one epoch's client-workload pass: seeded request "
                    "samples through the placement rows + contention "
                    "accounting",
    "bench.lifetime": "lifetime bench stage body",
    # fleet/ — N clusters per stacked dispatch
    "fleet.epoch": "one fleet epoch batch: every live member's chaos "
                   "event + ONE stacked accounting dispatch + data "
                   "planes + digests",
    "bench.fleet": "fleet bench stage body",
    "bench.multichip": "multichip bench: mesh-sharded map/lifetime/"
                       "optimizer measurements for one device count",
    # serve/ — the placement serving daemon
    "serve.batch": "one micro-batch: deadline triage + device map + "
                   "reply delivery (host syncs allowed: the mapper "
                   "fetches results inside)",
    "serve.bulk": "one bulk protocol block (query_block/submit_many): "
                  "pool-grouped lanes, one fixed-shape dispatch per "
                  "sub-block on the caller's thread",
    "serve.front": "one bulk block through the multi-replica front: "
                   "rendezvous-hash routing + per-replica sub-blocks "
                   "+ reply merge",
    "serve.swap": "epoch-swap staging: clone + incremental apply + "
                  "mapper construction + warm dispatch (off the "
                  "reader path; the flip itself is swap_stall_seconds)",
    "serve.chaos": "chaos-client harness: lifetime churn against a "
                   "live service under client load",
    "serve.background_balance": "one background balancing round: "
                                "device-loop plan computed off the "
                                "query path, applied as a value-only "
                                "overlay swap",
    "bench.serve": "serve bench stage body",
    # cli/
    "daemon.selftest": "daemon CLI miniature workload",
    # tools/perf_probe.py
    "probe.scaling": "perf-probe block-size scaling sweep",
    "probe.ablations": "perf-probe ablation sweep",
    "probe.trace": "perf-probe traced demonstration run",
}

INSTANTS: dict[str, str] = {
    "fault.fired": "an armed fault point fired",
    "stage.overrun": "a stage was abandoned by the watchdog",
    "runtime.acquired": "backend acquisition finished",
    "sharded.make_mesh": "device mesh construction",
    "sim.checkpoint": "a lifetime-sim checkpoint was flushed",
    "fleet.checkpoint": "a whole-stack fleet checkpoint was flushed",
    "serve.swap_applied": "an epoch swap flipped the active buffer",
    "serve.degraded": "serve dispatch lost the device; batch answered "
                      "by the host mapper",
    "serve.recovered": "serve dispatch returned to the device",
    "health.raised": "a health check transitioned OK -> raised",
    "health.cleared": "a health check transitioned raised -> OK",
}

COUNTERS: dict[str, str] = {
    "balancer.stddev": "deviation trajectory across optimizer rounds",
    "mgr.score": "eval score after each calc_eval",
}

# f-string span names must start with one of these static heads
PREFIXES: tuple[str, ...] = (
    "stage.",  # runtime/scheduler.py: f"stage.{stage_name}"
)

# spans that time DISPATCH only: enqueue of already-compiled device work.
# The graftlint `host-sync` pass forbids host syncs inside their bodies;
# fetches belong in pipeline.fetch / ec.gf_fetch or between spans.
DISPATCH_SPANS: tuple[str, ...] = (
    "pipeline.map_block",
    "pipeline.rescue",
    "pipeline.diagnose",
    "ec.gf_dispatch",
)


def known(name: str) -> bool:
    """True if `name` is a declared event name or matches a dynamic
    prefix (runtime helper; the lint does the same check statically)."""
    if name in SPANS or name in INSTANTS or name in COUNTERS:
        return True
    return any(name.startswith(p) for p in PREFIXES)
