"""Placement-decision observability — the surface over the CRUSH
flight recorder.

The batched pipeline fuses millions of `crush_do_rule` calls into one
XLA executable, and every decision inside it — retries, collisions,
out-of-weight rejections, rescue-lane activations, bad mappings — is
invisible from the outside.  The instrumented kernel variant
(`mapper_jax.compile_rule(with_diag=True)`) re-exposes them as device
arrays; THIS module is where those arrays become operator-visible
state:

- a `placement` perf-counter group (u64 decision tallies, a
  `choose_tries` histogram counter fed by `merge_histogram` from the
  device-reduced retry histogram, and a `diagnose_seconds` quantile for
  the instrumented dispatch itself);
- a per-source snapshot store (`record()` / `dump()`): the latest
  diagnostics summary per producer ("pool0", "sim.epoch12",
  "mgr.optimize", bench), served by the daemon `bad dump` admin command;
- Prometheus gauges for the snapshot-only numbers (the perf-group
  counters render through the registry exposition already);
- an explainer registry (`register_explainer()` / `explain()`): a live
  process's PoolMapper publishes a host-oracle replay closure so the
  daemon `explain <pgid>` command can answer for the maps it actually
  serves.

Import-light: no jax at module load (the snapshot payloads are plain
python by the time they arrive here).
"""

from __future__ import annotations

import threading

from ceph_tpu.utils.perf_counters import logger_for

# retry counts are small non-negative ints; integer bounds make the
# histogram exact (value == bound), and 0..63 covers every tunable
# default (choose_total_tries=50) with headroom for SET_CHOOSE_TRIES
TRIES_BOUNDS = list(range(64))

_L = logger_for("placement")
_L.add_u64("pgs_diagnosed",
           "PGs run through the instrumented (with_diag) pipeline")
_L.add_u64("bad_mappings",
           "diagnosed PGs whose CRUSH result was shorter than numrep "
           "(the tester's bad-mapping test, on device)")
_L.add_u64("retry_exhausted",
           "diagnostics lanes left unplaced (-1 retry marker): the "
           "choose walk ran out of tries or candidates")
_L.add_u64("collisions",
           "duplicate-item rejections across diagnosed choose draws")
_L.add_u64("rejections_out",
           "out-of-weight (is_out) rejections across diagnosed draws")
_L.add_u64("skips",
           "skip_rep draws (dead source bucket / wrong item type / "
           "exhausted count) across diagnosed choose walks")
_L.add_u64("unresolved_masked",
           "diagnosed lanes excluded from the planes because the fast "
           "window flagged them (rescued exactly elsewhere)")
_L.add_histogram(
    "choose_tries", TRIES_BOUNDS,
    "per-placement retry histogram folded from the device diagnostics "
    "planes (the reference collect_choose_tries shape; bucket value == "
    "retry count)")
_L.add_quantile(
    "diagnose_seconds",
    "instrumented-pipeline dispatch wall time per diagnose() block")

_lock = threading.Lock()
_snapshots: dict[str, dict] = {}
_explainers: dict[str, object] = {}


def fold_summary(agg: dict, s: dict) -> dict:
    """Elementwise-fold one diagnostics summary into an aggregate (the
    per-epoch shape sim/ and the balancer loop book): scalar tallies
    sum, retry histograms sum index-wise, diag_exact ANDs.  Returns
    `agg` (for chaining)."""
    for k in ("pgs", "bad_mappings", "retry_exhausted", "collisions",
              "rejections", "skips", "unresolved"):
        agg[k] = agg.get(k, 0) + int(s.get(k, 0))
    hist = s.get("tries_histogram") or []
    ah = agg.setdefault("tries_histogram", [])
    if len(ah) < len(hist):
        ah.extend([0] * (len(hist) - len(ah)))
    for i, v in enumerate(hist):
        ah[i] += int(v)
    agg["diag_exact"] = bool(agg.get("diag_exact", True)
                             and s.get("diag_exact", False))
    return agg


def record(source: str, summary: dict) -> dict:
    """Book one diagnostics summary into the perf group and the
    snapshot store.  `summary` is the plain-python dict produced by
    PoolMapper.diagnose / explain.diag_summary: pgs, bad_mappings,
    retry_exhausted, collisions, rejections, skips, unresolved,
    tries_histogram (list[int], index == retry count), diag_exact.
    Returns the summary (for chaining)."""
    _L.inc("pgs_diagnosed", int(summary.get("pgs", 0)))
    _L.inc("bad_mappings", int(summary.get("bad_mappings", 0)))
    _L.inc("retry_exhausted", int(summary.get("retry_exhausted", 0)))
    _L.inc("collisions", int(summary.get("collisions", 0)))
    _L.inc("rejections_out", int(summary.get("rejections", 0)))
    _L.inc("skips", int(summary.get("skips", 0)))
    _L.inc("unresolved_masked", int(summary.get("unresolved", 0)))
    hist = summary.get("tries_histogram")
    if hist:
        _L.merge_histogram("choose_tries", list(hist))
    with _lock:
        _snapshots[source] = dict(summary)
    return summary


def dump() -> dict:
    """The daemon `bad dump` payload: latest snapshot per source plus
    the aggregate perf-group values."""
    with _lock:
        sources = {k: dict(v) for k, v in _snapshots.items()}
    return {
        "sources": sources,
        "counters": _L.dump(),
        "explainers": sorted(_explainers),
    }


def reset() -> None:
    """Test isolation: drop snapshots and explainers (perf counters are
    zeroed by the registry-wide reset, not here)."""
    with _lock:
        _snapshots.clear()
        _explainers.clear()


def register_explainer(key: str, fn) -> None:
    """Publish a replay closure `fn(x: int) -> dict` (the host-oracle
    decision log for one placement seed) under `key` — PoolMapper
    registers "pool<id>" so a live daemon can answer `explain`."""
    with _lock:
        _explainers[key] = fn


def explain(pgid: str) -> dict:
    """Admin-command entry: `pgid` is "<pool>.<seed>" (the reference
    pgid spelling) or "<pool> <seed>".  Replays through the explainer
    registered under "pool<pool>"."""
    parts = pgid.replace(".", " ").split()
    if len(parts) != 2:
        return {"error": f"pgid {pgid!r} not of the form <pool>.<seed>"}
    key, x = f"pool{parts[0]}", parts[1]
    with _lock:
        fn = _explainers.get(key)
    if fn is None:
        with _lock:
            known = sorted(_explainers)
        return {"error": f"no explainer registered for {key!r}",
                "registered": known}
    try:
        return fn(int(x))
    except Exception as e:  # the admin surface reports, never raises
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _esc(label: str) -> str:
    """Prometheus label-value escaping — sources embed user-chosen plan
    names, unlike the internal-constant labels elsewhere in obs."""
    from ceph_tpu.obs.prometheus import escape_label

    return escape_label(label)


def prometheus_gauges() -> str:
    """Gauges for the snapshot-only numbers (per-source bad mappings /
    retry exhaustion); the placement perf-group counters render through
    the registry exposition."""
    with _lock:
        items = sorted(_snapshots.items())
    if not items:
        return ""
    lines = [
        "# HELP ceph_tpu_placement_source_bad_mappings latest diagnosed "
        "bad-mapping count per source",
        "# TYPE ceph_tpu_placement_source_bad_mappings gauge",
    ]
    for src, s in items:
        lines.append(
            f'ceph_tpu_placement_source_bad_mappings{{source="{_esc(src)}"}} '
            f'{int(s.get("bad_mappings", 0))}'
        )
    lines += [
        "# HELP ceph_tpu_placement_source_retry_exhausted latest "
        "unplaced-lane count per source",
        "# TYPE ceph_tpu_placement_source_retry_exhausted gauge",
    ]
    for src, s in items:
        lines.append(
            f'ceph_tpu_placement_source_retry_exhausted{{source="{_esc(src)}"}} '
            f'{int(s.get("retry_exhausted", 0))}'
        )
    return "\n".join(lines) + "\n"
