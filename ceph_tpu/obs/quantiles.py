"""Log-bucketed latency quantiles — the math behind the `quantile` kind.

The serve-stage roadmap item needs p50/p99 tail latency, and the EC/
load-balance literature the reproduction follows assumes per-dispatch
latency *distributions*, not means (a mean hides exactly the tail a QPS
target is written against).  A full reservoir per hot span is too
expensive for dispatch paths that run tens of thousands of times per
bench stage, so the perf registry grows a histogram-backed estimator:

- observations land in log-spaced buckets (`DEFAULT_BOUNDS`: 1 µs to
  100 s, 4 buckets per decade — one `observe()` is a short linear scan,
  no allocation);
- quantiles are estimated at *dump* time by walking the cumulative
  histogram and interpolating geometrically inside the landing bucket
  (the buckets are log-spaced, so log-linear interpolation is the
  unbiased choice); the tracked min/max make the open-ended first and
  overflow buckets exact at the ends.

The estimate's error is bounded by the bucket ratio (10^(1/4) ≈ 1.78x
worst case, far less in practice with interpolation) — plenty for
regression detection, where the question is "did p99 double", not "is
p99 1.03 ms or 1.04 ms".

Import-light on purpose: `utils/perf_counters.py` (which must not drag
jax or the obs package in) calls into this module lazily.
"""

from __future__ import annotations

# 1 µs .. 100 s, 4 buckets per decade: 33 bounds -> 34 buckets.  Spans
# everything between a single device enqueue and a deadline-killed stage.
DEFAULT_BOUNDS: tuple[float, ...] = tuple(
    10.0 ** (-6 + i / 4) for i in range(33)
)

#: the quantiles every `quantile`-kind counter reports in its dump
REPORTED = (("p50", 0.50), ("p90", 0.90), ("p99", 0.99))


def _interpolate(bounds, i: int, cum: float, n: float, rank: float,
                 vmin: float | None, vmax: float | None) -> float:
    """Position of `rank` inside landing bucket i (see module doc)."""
    if i == 0:
        lo = vmin if vmin is not None else bounds[0] / 10.0
        hi = bounds[0]
    elif i == len(bounds):
        lo = bounds[-1]
        hi = vmax if vmax is not None else bounds[-1] * 10.0
    else:
        lo, hi = bounds[i - 1], bounds[i]
    if vmin is not None:
        lo = max(lo, min(vmin, hi))
    if vmax is not None:
        hi = min(hi, max(vmax, lo))
    frac = (rank - cum) / n
    if lo > 0 and hi > lo:
        return lo * (hi / lo) ** frac  # log-linear: see module doc
    return lo + (hi - lo) * frac


def estimate(
    bounds, buckets, q: float,
    vmin: float | None = None, vmax: float | None = None,
) -> float:
    """Estimate the q-quantile (0 < q < 1) of a histogram.

    `bounds[i]` is the inclusive upper edge of bucket i; the final
    bucket (`buckets[len(bounds)]`) is the overflow.  `vmin`/`vmax`
    (tracked by the counter) tighten the open-ended first and last
    buckets; without them the bucket edges bound the estimate.
    """
    total = sum(buckets)
    if total <= 0:
        return 0.0
    rank = q * total
    cum = 0.0
    for i, n in enumerate(buckets):
        if n == 0:
            continue
        if cum + n >= rank:
            return _interpolate(bounds, i, cum, n, rank, vmin, vmax)
        cum += n
    # rank beyond the last populated bucket (fp rounding): the maximum
    return vmax if vmax is not None else (bounds[-1] if bounds else 0.0)


def summarize(bounds, buckets, vmin=None, vmax=None) -> dict[str, float]:
    """The {p50, p90, p99} record embedded in a quantile counter dump.

    Single cumulative walk resolving every reported rank in ascending
    order — dumps run this over dozens of quantile counters per bench
    stage, so one pass per counter, not one per quantile.  Must stay
    value-equivalent to per-quantile `estimate()` calls
    (tests/test_obs.py pins the equivalence)."""
    total = sum(buckets)
    if total <= 0:
        return {name: 0.0 for name, _ in REPORTED}
    out: dict[str, float] = {}
    ranks = sorted(((q * total, name) for name, q in REPORTED))
    r = 0  # next unresolved rank
    cum = 0.0
    for i, n in enumerate(buckets):
        if n == 0:
            continue
        while r < len(ranks) and cum + n >= ranks[r][0]:
            rank, name = ranks[r]
            out[name] = _interpolate(bounds, i, cum, n, rank, vmin, vmax)
            r += 1
        if r == len(ranks):
            return out
        cum += n
    # ranks beyond the last populated bucket (fp rounding): the maximum
    tail = vmax if vmax is not None else (bounds[-1] if bounds else 0.0)
    for rank, name in ranks[r:]:
        out[name] = tail
    return out
