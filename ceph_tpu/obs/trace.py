"""Span tracer — nested, thread-safe, exported as Chrome trace-event JSON.

The reference inspects live daemons through the admin socket; for *time*
questions it leans on external tracing (src/common/tracer.cc wraps
Jaeger spans around op paths).  Here the same role is played by a
process-local tracer that records complete ("ph":"X") trace events and
writes a Chrome trace-event file readable by Perfetto / chrome://tracing.

Env-gated: set `CEPH_TPU_TRACE=/path/trace.json` before the process
starts (or call `set_trace_path` at runtime).  When disabled, `span()`
returns a shared no-op context manager — the hot paths pay one dict
lookup and nothing else.  The in-memory buffer is a ring of the most
recent `CEPH_TPU_TRACE_MAX_EVENTS` events (default 1M) so a long-lived
traced process stays bounded; the flush records how many fell off.

Nesting is the trace-event model's: complete events on the same thread
nest by time containment, so `with span("outer"): with span("inner"):`
renders as a two-deep flame in Perfetto.  Thread safety: each event is
appended under a lock; per-thread ordering comes from the tid field.

The file is written by `flush()` — called automatically at interpreter
exit and opportunistically by long-running drivers (bench.py flushes per
stage) so a SIGKILLed run still leaves the spans recorded so far.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque

_lock = threading.Lock()
_flush_lock = threading.Lock()  # serializes writers of <path>.tmp
# Bounded: a long-lived traced process (admin-socket server under
# CEPH_TPU_TRACE) must not accumulate events forever.  Ring semantics —
# the most recent events win, and the flush records how many fell off.
_DEFAULT_MAX_EVENTS = 1_000_000


def _max_events() -> int:
    try:
        n = int(os.environ.get("CEPH_TPU_TRACE_MAX_EVENTS", ""))
    except ValueError:
        return _DEFAULT_MAX_EVENTS  # a bad tuning var must not traceback
    return n if n > 0 else _DEFAULT_MAX_EVENTS


_events: deque = deque(maxlen=_max_events())
_dropped = 0
_path: str | None = os.environ.get("CEPH_TPU_TRACE") or None
# trace timestamps are µs from this origin (perf_counter is monotonic;
# the absolute epoch is recorded in metadata for cross-log correlation)
_t0 = time.perf_counter()
_epoch = time.time()


def enabled() -> bool:
    return _path is not None


def trace_path() -> str | None:
    return _path


def set_trace_path(path: str | None) -> None:
    """Enable (or disable with None) tracing at runtime; events recorded
    so far are kept."""
    global _path
    _path = path


def _now_us() -> float:
    return (time.perf_counter() - _t0) * 1e6


def _append(ev: dict) -> None:
    global _dropped
    with _lock:
        if len(_events) == _events.maxlen:
            _dropped += 1
        _events.append(ev)


class _NullSpan:
    """Shared do-nothing context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "cat", "args", "t0")

    def __init__(self, name: str, cat: str, args: dict):
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self.t0 = _now_us()
        return self

    def __exit__(self, exc_type, exc, tb):
        ev = {
            "ph": "X",
            "name": self.name,
            "cat": self.cat,
            "ts": self.t0,
            "dur": _now_us() - self.t0,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if self.args:
            ev["args"] = self.args
        if exc_type is not None:
            ev.setdefault("args", {})["error"] = exc_type.__name__
        _append(ev)
        return False


def span(name: str, cat: str = "ceph_tpu", **args):
    """`with span("pipeline.map_block", pgs=65536): ...`"""
    if _path is None:
        return _NULL
    return _Span(name, cat, args)


def instant(name: str, cat: str = "ceph_tpu", **args) -> None:
    """A zero-duration marker ("ph":"i")."""
    if _path is None:
        return
    ev = {
        "ph": "i",
        "s": "t",
        "name": name,
        "cat": cat,
        "ts": _now_us(),
        "pid": os.getpid(),
        "tid": threading.get_ident(),
    }
    if args:
        ev["args"] = args
    _append(ev)


def counter(name: str, value: float, cat: str = "ceph_tpu") -> None:
    """A counter-track sample ("ph":"C") — e.g. the balancer's deviation
    trajectory renders as a stepped line in Perfetto."""
    if _path is None:
        return
    _append({
        "ph": "C",
        "name": name,
        "cat": cat,
        "ts": _now_us(),
        "pid": os.getpid(),
        "args": {"value": value},
    })


def n_events() -> int:
    with _lock:
        return len(_events)


def clear() -> None:
    global _dropped
    with _lock:
        _events.clear()
        _dropped = 0


def flush(path: str | None = None) -> str | None:
    """Write the Chrome trace-event file; returns the path written (None
    if tracing is disabled or nothing was recorded).  Safe to call
    repeatedly — each call rewrites the full event list, so the last
    flush before a kill wins."""
    path = path or _path
    if path is None:
        return None
    # _flush_lock serializes whole flushes — concurrent callers (the
    # admin-socket thread's "trace flush" racing a bench stage flush)
    # must neither interleave writes into the shared tmp file nor let a
    # stale snapshot overwrite a newer one, so the snapshot is taken
    # inside it.  Span recording only needs _lock and continues meanwhile.
    with _flush_lock:
        with _lock:
            if not _events:
                return None
            doc = {
                "traceEvents": list(_events),
                "displayTimeUnit": "ms",
                "otherData": {
                    "epoch_s": _epoch,
                    "producer": "ceph_tpu.obs.trace",
                },
            }
            if _dropped:
                doc["otherData"]["dropped_events"] = _dropped
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    return path


def _flush_at_exit() -> None:
    try:
        flush()
    except OSError:
        pass  # a bad CEPH_TPU_TRACE path must not traceback at exit


atexit.register(_flush_at_exit)
