"""Admin socket — query a LIVE process's perf registry from the outside.

The reference exposes every daemon's internals on a UNIX stream socket
(`ceph daemon <name> perf dump`, reference src/common/admin_socket.cc:
one command line per connection, JSON reply, connection closed).  Same
protocol here:

    client: "perf dump\\n"      server: perf-dump JSON (+ an `executables`
                                section: the compile-cache registry,
                                records only — no analysis work)
    client: "perf schema\\n"    server: perf-schema JSON
    client: "perf reset\\n"     server: {"ok": true} (values zeroed)
    client: "metrics\\n"        server: Prometheus text exposition
    client: "cache dump\\n"     server: executable registry with lazy JAX
                                cost/memory analysis (may trace; do not
                                point it at a wedged device — `perf dump`
                                is the always-answers path)
    client: "trace flush\\n"    server: {"path": <trace file or null>}
    client: "bad dump\\n"       server: placement-diagnostics snapshots
                                (per-source bad-mapping / retry planes
                                booked by PoolMapper.diagnose)
    client: "explain 1.42\\n"   server: host-oracle decision log for PG
                                42 of pool 1 (an explainer must have
                                been registered by a PoolMapper of that
                                pool in THIS process)
    client: "runtime\\n"        server: backend-acquisition provenance
                                + armed fault points
    client: "serve status\\n"   server: live placement-service status
                                (epoch, queue depth, shed/degraded
                                counters, swap-stall tail) per service
    client: "health\\n"         server: summarized HEALTH_OK/WARN/ERR +
                                raised checks (ceph_tpu.obs.health)
    client: "timeline dump\\n"  server: every recorded timeline series,
                                both retention tiers, chronological
    client: "help\\n"           server: command list JSON

Env-gated like tracing: set `CEPH_TPU_ADMIN_SOCKET=/path/x.asok` and any
process that imports ceph_tpu.obs serves on it; then from another shell:

    python -m ceph_tpu.cli.daemon --sock /path/x.asok perf dump
"""

from __future__ import annotations

import atexit
import json
import os
import socket
import threading

from ceph_tpu.utils.dout import subsys_logger

_log = subsys_logger("obs")

_server: "AdminSocket | None" = None

COMMANDS = (
    "perf dump", "perf schema", "perf reset", "metrics", "cache dump",
    "bad dump", "explain <pool>.<seed>", "trace flush", "runtime",
    "serve status", "health", "timeline dump", "help",
)

# concurrent per-connection handler threads (beyond this, accepts wait):
# a slow `cache dump` analysis must not block a concurrent `perf dump` —
# the always-answers diagnostic path — but a flood of clients must not
# spawn unbounded threads either
MAX_HANDLERS = 8


def handle_command(cmd: str) -> str:
    """Execute one admin command against this process; returns the reply
    text.  Shared by the socket server and the in-process CLI path."""
    from ceph_tpu import obs
    from ceph_tpu.obs import executables, trace
    from ceph_tpu.utils import perf_counters as pc

    cmd = " ".join(cmd.split())
    if cmd == "perf dump":
        # analyze=False: a live query (possibly against a process whose
        # device is wedged) must answer without touching jax
        d = pc.perf_dump()
        d["executables"] = executables.dump(analyze=False)
        return json.dumps(d, indent=1, sort_keys=True)
    if cmd == "perf schema":
        return json.dumps(pc.perf_schema(), indent=1, sort_keys=True)
    if cmd == "perf reset":
        pc.reset_values()
        return json.dumps({"ok": True})
    if cmd == "metrics":
        # the one exposition recipe lives in obs.prometheus_text()
        # (counters + executable-registry gauges)
        return obs.prometheus_text()
    if cmd == "cache dump":
        # short analysis budget: a live diagnostic must answer promptly;
        # entries beyond it keep cost=null (re-query to resume — results
        # cache per record)
        return json.dumps(executables.dump(analyze=True, budget_s=5.0),
                          indent=1, sort_keys=True)
    if cmd == "bad dump":
        # the placement flight-recorder surface: latest diagnostics
        # snapshot per source + the aggregate placement counters
        from ceph_tpu.obs import placement

        return json.dumps(placement.dump(), indent=1, sort_keys=True)
    if cmd.startswith("explain"):
        from ceph_tpu.obs import placement

        arg = cmd[len("explain"):].strip()
        if not arg:
            return json.dumps(
                {"error": "usage: explain <pool>.<seed>"})
        return json.dumps(placement.explain(arg), indent=1)
    if cmd == "trace flush":
        return json.dumps({"path": trace.flush()})
    if cmd == "runtime":
        # backend-acquisition provenance + armed fault points of the
        # live process (None until something walked the ladder)
        from ceph_tpu import runtime

        return json.dumps({
            "provenance": runtime.last_provenance(),
            "default_ladder": runtime.default_ladder(),
            "faults_armed": runtime.faults.active(),
        }, indent=1, sort_keys=True)
    if cmd == "serve status":
        # the placement-serving daemon's live status (epoch, queue
        # depth, shed/degraded counters, swap-stall tail) — empty
        # `services` when this process runs none
        from ceph_tpu.serve import service as serve_service

        return json.dumps(serve_service.status_dump(), indent=1,
                          sort_keys=True)
    if cmd == "health":
        # the `ceph status` analogue: summarized status + raised checks
        from ceph_tpu.obs import health

        return json.dumps(health.dump(), indent=1, sort_keys=True)
    if cmd == "timeline dump":
        # the flight recorder: every recorded series, both tiers,
        # chronological
        from ceph_tpu.obs import timeline

        return json.dumps(timeline.dump(), indent=1, sort_keys=True)
    if cmd == "help":
        return json.dumps(list(COMMANDS))
    return json.dumps({"error": f"unknown command {cmd!r}", "help": list(COMMANDS)})


class AdminSocket:
    """Threaded UNIX stream server; one command per connection.

    Each accepted connection runs on its own handler thread (bounded by
    MAX_HANDLERS): a 5 s `cache dump` analysis no longer blocks a
    concurrent `perf dump` — the diagnostic path must always answer."""

    def __init__(self, path: str):
        self.path = path
        if os.path.exists(path):
            os.unlink(path)
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.bind(path)
        self.sock.listen(4)
        self._stop = False
        self._handlers = threading.Semaphore(MAX_HANDLERS)
        self.thread = threading.Thread(
            target=self._serve, name="ceph-tpu-asok", daemon=True
        )
        self.thread.start()

    def _serve(self) -> None:
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            self._handlers.acquire()
            threading.Thread(
                target=self._handle, args=(conn,),
                name="ceph-tpu-asok-conn", daemon=True,
            ).start()

    def _handle(self, conn) -> None:
        cmd = "<no command read>"
        try:
            conn.settimeout(5)
            buf = b""
            while b"\n" not in buf:
                chunk = conn.recv(4096)
                if not chunk:
                    break
                buf += chunk
            cmd = buf.split(b"\n", 1)[0].decode("utf-8", "replace")
            if cmd:
                try:
                    reply = handle_command(cmd)
                except Exception as e:
                    # the client must see the failure, not an empty
                    # reply that reads as success
                    reply = json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}
                    )
                conn.sendall(reply.encode())
        except Exception as e:
            # send failures / recv timeouts: the peer is gone or stuck,
            # but a silent pass here hides every such failure from the
            # operator diagnosing exactly this path
            _log(1, f"admin socket connection failed serving "
                    f"{cmd!r}: {type(e).__name__}: {e}")
        finally:
            self._handlers.release()
            conn.close()

    def close(self) -> None:
        self._stop = True
        try:
            self.sock.close()
        finally:
            if os.path.exists(self.path):
                try:
                    os.unlink(self.path)
                except OSError:
                    pass


def client_command(path: str, cmd: str, timeout: float = 10.0) -> str:
    """Send one command to a live process's admin socket."""
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(timeout)
    try:
        s.connect(path)
        s.sendall(cmd.encode() + b"\n")
        s.shutdown(socket.SHUT_WR)
        out = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            out += chunk
        return out.decode()
    finally:
        s.close()


def start(path: str) -> AdminSocket:
    """Start (or replace) this process's admin socket server."""
    global _server
    if _server is not None:
        _server.close()
    _server = AdminSocket(path)
    return _server


def release() -> None:
    """Stop serving and free the socket path.

    For supervisor/worker process pairs sharing one environment (bench.py):
    the UNIX path can only name one server, and the interesting registry
    lives in the worker — the supervisor calls this before spawning, so
    the worker's own `maybe_start_from_env` binds the path uncontested."""
    global _server
    if _server is not None:
        _server.close()
        _server = None


def _path_serving(path: str) -> bool:
    """True if a live server already answers on `path` (a stale socket
    file left by a killed process refuses the connect)."""
    if not os.path.exists(path):
        return False
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(0.5)
    try:
        s.connect(path)
        return True
    except OSError:
        return False
    finally:
        s.close()


def maybe_start_from_env() -> AdminSocket | None:
    path = os.environ.get("CEPH_TPU_ADMIN_SOCKET")
    if path and _server is None:
        # never steal a live server's path: a client shell with the env
        # var still exported imports obs too, and must not unlink the
        # socket of the process it is about to query
        if _path_serving(path):
            return None
        try:
            return start(path)
        except OSError as e:
            # a bad socket path (missing dir, unwritable, too long) must
            # not crash every module that imports obs
            _log(1, f"cannot serve admin socket {path}: {e}")
            return None
    return _server


def _cleanup() -> None:
    if _server is not None:
        _server.close()


atexit.register(_cleanup)
