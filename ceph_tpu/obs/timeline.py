"""Bounded time-series flight recorder: numpy rings, 2-tier retention.

Every obs surface before this one was a point-in-time snapshot, so a
p99 excursion across a structural swap was a lost transient.  This
module records named series ("sim", "serve", "balancer") of small float
samples into fixed-capacity numpy ring buffers, Prometheus-TSDB style:

- tier 0 holds the newest `CEPH_TPU_TIMELINE_CAP` raw samples;
- samples evicted from tier 0 fold into a downsample accumulator that
  emits one averaged tier-1 sample per `TIER1_FACTOR` evictions into a
  second ring of the same capacity — so total memory is fixed while the
  recorded horizon is `cap * (1 + TIER1_FACTOR)` samples deep.

Sample indices increase monotonically per series for the life of the
process *and across checkpoint/resume*: `state()`/`restore()` round-trip
a series as JSON-safe lists so sim/serve checkpoints can carry their
timeline and `--resume` continues the same recording (bench gates on
index continuity).

Recording is host-only observation — callers pass plain floats they
already fetched; `CEPH_TPU_TIMELINE_CAP=0` disables recording entirely
and must be bit-invisible to digests and compile counts.
"""

from __future__ import annotations

import threading

import numpy as np

from ceph_tpu.obs.prometheus import escape_label
from ceph_tpu.utils import knobs
from ceph_tpu.utils.perf_counters import logger_for

TIER1_FACTOR = 8  # tier-0 evictions averaged into one tier-1 sample

_L = logger_for("timeline")
_L.add_u64("samples", "timeline samples recorded across all series")
_L.add_u64("downsamples", "tier-1 samples emitted by eviction folding")
_L.add_u64("restores", "series restored from checkpoint state")

_lock = threading.Lock()
_SERIES: dict[str, "_Series"] = {}


def cap() -> int:
    """Per-series tier-0 ring capacity; 0 disables recording."""
    try:
        return max(0, int(knobs.get("CEPH_TPU_TIMELINE_CAP", "512")))
    except ValueError:
        return 512


def enabled() -> bool:
    return cap() > 0


class _Series:
    """One named series: tier-0 ring + tier-1 downsample ring."""

    def __init__(self, capacity: int):
        self.cap = capacity
        self.n = 0  # samples ever recorded (== next index)
        self.idx = np.zeros(capacity, np.int64)
        self.fields: dict[str, np.ndarray] = {}
        self.t1_n = 0
        self.t1_idx = np.zeros(capacity, np.int64)
        self.t1_fields: dict[str, np.ndarray] = {}
        self._acc: dict[str, float] = {}
        self._acc_n = 0
        self._acc_first = 0

    def _ring(self, tier: dict[str, np.ndarray], name: str) -> np.ndarray:
        r = tier.get(name)
        if r is None:
            r = tier[name] = np.zeros(self.cap, np.float64)
        return r

    def _fold(self, index: int, values: dict[str, float]) -> bool:
        if self._acc_n == 0:
            self._acc_first = index
        for name, v in values.items():
            self._acc[name] = self._acc.get(name, 0.0) + v
        self._acc_n += 1
        if self._acc_n < TIER1_FACTOR:
            return False
        pos = self.t1_n % self.cap
        self.t1_idx[pos] = self._acc_first
        for name in self._acc:
            self._ring(self.t1_fields, name)[pos] = (
                self._acc[name] / TIER1_FACTOR)
        self.t1_n += 1
        self._acc = {}
        self._acc_n = 0
        return True

    def sample(self, values: dict[str, float]) -> int:
        pos = self.n % self.cap
        if self.n >= self.cap:  # evict the slot we are about to reuse
            self._fold(int(self.idx[pos]),
                       {f: float(r[pos]) for f, r in self.fields.items()})
        self.idx[pos] = self.n
        for name, r in self.fields.items():
            r[pos] = 0.0  # a field absent from this sample reads as 0
        for name, v in values.items():
            self._ring(self.fields, name)[pos] = float(v)
        self.n += 1
        return self.n - 1

    def _window(self, n: int, idx: np.ndarray,
                fields: dict[str, np.ndarray]) -> dict:
        valid = min(n, self.cap)
        order = [(n - valid + k) % self.cap for k in range(valid)]
        return {
            "index": [int(idx[p]) for p in order],
            "fields": {name: [float(r[p]) for p in order]
                       for name, r in sorted(fields.items())},
        }

    def dump(self) -> dict:
        out = {"cap": self.cap, "count": self.n,
               "tier0": self._window(self.n, self.idx, self.fields),
               "tier1": self._window(self.t1_n, self.t1_idx, self.t1_fields)}
        out["tier1"]["factor"] = TIER1_FACTOR
        return out

    def state(self) -> dict:
        st = self.dump()
        st["acc"] = {"n": self._acc_n, "first": self._acc_first,
                     "sums": dict(self._acc)}
        st["t1_count"] = self.t1_n
        return st

    def restore(self, st: dict) -> None:
        for n_key, idx_attr, f_attr, tier in (
                ("count", "idx", "fields", st.get("tier0") or {}),
                ("t1_count", "t1_idx", "t1_fields", st.get("tier1") or {})):
            n = int(st.get(n_key, 0))
            index = list(tier.get("index") or [])[-self.cap:]
            base = len(list(tier.get("index") or [])) - len(index)
            idx = getattr(self, idx_attr)
            rings = getattr(self, f_attr)
            for k, i in enumerate(index):
                idx[(n - len(index) + k) % self.cap] = int(i)
            for name, vals in (tier.get("fields") or {}).items():
                r = self._ring(rings, name)
                vals = list(vals)[base:][-self.cap:]
                for k, v in enumerate(vals):
                    r[(n - len(vals) + k) % self.cap] = float(v)
            if n_key == "count":
                self.n = n
            else:
                self.t1_n = n
        acc = st.get("acc") or {}
        self._acc_n = int(acc.get("n", 0))
        self._acc_first = int(acc.get("first", 0))
        self._acc = {k: float(v) for k, v in (acc.get("sums") or {}).items()}


def sample(series: str, values: dict[str, float]) -> int:
    """Record one sample; returns its monotonic index (-1 when timeline
    recording is disabled via CEPH_TPU_TIMELINE_CAP=0)."""
    c = cap()
    if c <= 0:
        return -1
    with _lock:
        s = _SERIES.get(series)
        if s is None:
            s = _SERIES[series] = _Series(c)
        before = s.t1_n
        i = s.sample(values)
        folded = s.t1_n - before
    _L.inc("samples")
    if folded:
        _L.inc("downsamples", folded)
    return i


def next_index(series: str) -> int:
    """The index the next sample in `series` will get (0 when unknown)."""
    with _lock:
        s = _SERIES.get(series)
        return s.n if s is not None else 0


def last(series: str) -> tuple[int, dict[str, float]]:
    """(index, values) of the newest sample; (-1, {}) when empty."""
    with _lock:
        s = _SERIES.get(series)
        if s is None or s.n == 0:
            return -1, {}
        pos = (s.n - 1) % s.cap
        return int(s.idx[pos]), {name: float(r[pos])
                                 for name, r in sorted(s.fields.items())}


def dump(series: str | None = None) -> dict:
    """JSON view (chronological) of one series or all of them."""
    with _lock:
        if series is not None:
            s = _SERIES.get(series)
            return s.dump() if s is not None else {}
        return {name: s.dump() for name, s in sorted(_SERIES.items())}


def state(series: str) -> dict:
    """JSON-safe checkpoint payload for one series ({} when empty)."""
    with _lock:
        s = _SERIES.get(series)
        return s.state() if s is not None else {}


def restore(series: str, st: dict) -> None:
    """Rebuild a series from `state()` output so resumed runs continue
    the same monotonic index sequence."""
    if not st or cap() <= 0:
        return
    with _lock:
        s = _SERIES[series] = _Series(cap())
        s.restore(st)
    _L.inc("restores")


def reset() -> None:
    with _lock:
        _SERIES.clear()


def prometheus_gauges() -> str:
    """Per-series sample totals plus the newest value of every field."""
    with _lock:
        names = sorted(_SERIES)
        if not names:
            return ""
        counts = {name: _SERIES[name].n for name in names}
    lines = [
        "# HELP ceph_tpu_timeline_samples samples recorded per series",
        "# TYPE ceph_tpu_timeline_samples gauge",
    ]
    for name in names:
        lines.append(
            f'ceph_tpu_timeline_samples{{series="{escape_label(name)}"}} '
            f"{counts[name]}"
        )
    lines += [
        "# HELP ceph_tpu_timeline_last newest sample value per series/field",
        "# TYPE ceph_tpu_timeline_last gauge",
    ]
    for name in names:
        i, vals = last(name)
        if i < 0:
            continue
        for field, v in vals.items():
            lines.append(
                f'ceph_tpu_timeline_last{{series="{escape_label(name)}",'
                f'field="{escape_label(field)}"}} {v!r}'
            )
    return "\n".join(lines) + "\n"
