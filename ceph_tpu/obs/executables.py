"""Executable registry — per-compile metadata for the trace-once caches.

The trace-once stack made compiled executables the unit of performance
(`_PIPE_CACHE` / `_KERNEL_CACHE` / `_EC_CACHE`), but until now they were
invisible: the caches exposed aggregate hit/miss counters and nothing
else.  Tuning `_PALLAS_TILE` for MXU occupancy or setting a serve-stage
QPS budget needs per-executable facts — what does this kernel cost to
compile, how often does it dispatch, how many flops/bytes does one
dispatch move, and how close is it to the roofline.

Every cache registers its compiled entries here (the caches stay the
owners; this module only observes):

- `register()` creates a metadata record at cache-miss time (cheap:
  refs only, no jax work);
- `JitAccount(..., exec_record=rec)` feeds per-call compile/dispatch
  wall time into the record; `wrap()` does the same for raw jitted
  callables that have no JitAccount (the EC and batched-kernel caches);
- `dump()` renders the registry: per-entry cache_key digest, compile
  seconds, hit counts, last use, and — lazily, cached per record — JAX
  `Lowered.cost_analysis()` (flops, bytes accessed) plus
  `Compiled.memory_analysis()` (peak temp bytes) where the backend
  provides them, with derived roofline numbers (achieved GB/s and
  flops/s from the dispatch timings).

Cost analysis re-lowers the function from a recorded ShapeDtypeStruct
signature (never from live buffers — the registry must not pin operand
memory).  Lowering is trace-cache-warm and cheap; the XLA *compile*
needed for memory_analysis is only attempted when the record's own
measured compile time was under `_MEM_COMPILE_MAX_S`, so dumping the
registry can never re-pay a 20 s pipeline compile.  `dump(analyze=False)`
does no jax work at all — the admin-socket `perf dump` path uses it,
because a live query against a wedged device must still answer.

Dispatch timings measure enqueue (JitAccount's honest-for-async
contract), so on accelerators the derived GB/s is an upper bound; on the
CPU backend dispatch is effectively synchronous and the number is real.

Import-light: jax is only imported inside analysis calls, which only
run after a jitted callable has already executed in this process.
"""

from __future__ import annotations

import hashlib
import threading
import time

from ceph_tpu.obs import trace
from ceph_tpu.obs.jax_accounting import _sig

# registry insertion order is kept (dict semantics): dumps list entries
# oldest-compile first within a cache
_REG: dict[tuple, "ExecRecord"] = {}
_LOCK = threading.Lock()

# memory_analysis needs a real XLA compile; only re-pay it for records
# whose measured compile was at most this many seconds
_MEM_COMPILE_MAX_S = 5.0

# cost-analysis keys kept from the raw backend dict (the rest are
# per-operand utilization details nobody reads from a dump)
_COST_KEYS = (
    ("flops", "flops"),
    ("bytes accessed", "bytes_accessed"),
    ("transcendentals", "transcendentals"),
)


def _digest(key) -> str:
    return hashlib.sha1(repr(key).encode()).hexdigest()[:12]


def _shape_spec(args: tuple, kw: dict):
    """args/kwargs with every array leaf replaced by ShapeDtypeStruct —
    enough to re-lower later, without keeping device buffers alive."""
    import jax

    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    return jax.tree_util.tree_map(leaf, (args, kw))


class ExecRecord:
    """Metadata for one compiled executable of one trace-once cache."""

    def __init__(self, cache: str, kind: str, key):
        self.cache = cache  # "pipe" | "kernel" | "ec" | "bench"
        self.kind = kind  # e.g. "fast", "loop", "xor", "batched_fast"
        self.key_digest = _digest(key)
        self.key_repr = repr(key)[:240]
        self.created = time.time()
        self.last_use = self.created
        self.compiles = 0
        self.compile_seconds = 0.0
        self.hits = 0  # steady-state dispatches
        self.dispatch_seconds = 0.0
        # per-record lock: call accounting sits on the innermost
        # dispatch paths, and independent kernels must not contend on
        # one process-wide mutex (the module _LOCK guards only the
        # registry's shape — register/records/reset)
        self._lock = threading.Lock()
        self._fn = None  # the jitted callable (for re-lowering)
        self._spec = None  # (args, kw) ShapeDtypeStruct pytree
        self._cost: dict | None = None  # cached analysis (or {"error"})
        self._mem_tried = False  # memory analysis ATTEMPTED (it may
        # legitimately yield nothing on some backends; the attempt must
        # still count, or every "full" dump would re-compile forever)

    def note_call(self, dt: float, cold: bool, args=None, kw=None) -> None:
        """Book one call; on a cold call, snapshot the arg signature so
        the executable can be re-lowered for analysis later."""
        with self._lock:
            self.last_use = time.time()
            if cold:
                self.compiles += 1
                self.compile_seconds += dt
            else:
                self.hits += 1
                self.dispatch_seconds += dt
        if cold and self._spec is None and args is not None:
            try:
                self._spec = _shape_spec(args, kw or {})
            except Exception:  # exotic operand pytree: lose analysis,
                self._spec = None  # never the caller's dispatch

    # -- analysis --------------------------------------------------------
    def _mem_eligible(self) -> bool:
        return self.compile_seconds <= _MEM_COMPILE_MAX_S

    def analysis_pending(self, memory: bool = False) -> bool:
        """True when analyze(memory=...) would actually do jax work —
        dump() uses this to apply its budget only to real work, and to
        keep serving already-cached results for free."""
        if self._fn is None or self._spec is None or not hasattr(
                self._fn, "lower"):
            return False
        if self._cost is None:
            return True
        if "error" in self._cost:
            return False  # tried and failed: don't hammer the backend
        return memory and not self._mem_tried and self._mem_eligible()

    def analyze(self, memory: bool = False) -> dict | None:
        """Cost (and optionally memory) analysis, computed once and
        cached.  The default is COST ONLY — `Lowered.cost_analysis()`
        needs a (trace-cache-warm) re-lower but no XLA compile, so it is
        cheap even for the big pipeline kernels.  `memory=True` adds
        `Compiled.memory_analysis()` (peak temp bytes), which *does*
        compile: it is attempted AT MOST ONCE, and only when the
        record's own measured compile time was at most
        _MEM_COMPILE_MAX_S (only the bench end-of-run dump asks for it).
        Returns the cost dict, {"error": ...} when the backend refused,
        or None when the record has nothing to analyze (no jitted fn /
        no spec)."""
        if not self.analysis_pending(memory):
            return self._cost
        fn, spec = self._fn, self._spec
        try:
            lowered = fn.lower(*spec[0], **spec[1])
            raw = lowered.cost_analysis()
            if isinstance(raw, (list, tuple)):  # older jax returns [dict]
                raw = raw[0] if raw else {}
            cost = {
                out: float(raw[src]) for src, out in _COST_KEYS
                if src in raw
            }
            if memory and self._mem_eligible():
                self._mem_tried = True
                try:
                    mem = lowered.compile().memory_analysis()
                    if mem is not None:
                        cost["peak_temp_bytes"] = int(
                            getattr(mem, "temp_size_in_bytes", 0)
                        )
                        cost["argument_bytes"] = int(
                            getattr(mem, "argument_size_in_bytes", 0)
                        )
                        cost["output_bytes"] = int(
                            getattr(mem, "output_size_in_bytes", 0)
                        )
                except Exception:  # backend has no memory stats: fine
                    pass
            self._cost = cost
        except Exception as e:  # analysis is best-effort by contract
            if memory:
                # the attempt counts even when it fails (a wedged
                # device must not be re-lowered on every later dump)
                self._mem_tried = True
            if self._cost is None or "error" in self._cost:
                self._cost = {"error": f"{type(e).__name__}: {e}"[:200]}
            # else: a later memory pass failed — keep the good cached
            # cost rather than clobbering it with the error
        return self._cost

    def summary(self, analyze: bool = False) -> dict:
        cost = self.analyze() if analyze else self._cost
        out = {
            "cache": self.cache,
            "kind": self.kind,
            "key": self.key_digest,
            "cache_key": self.key_repr,
            "compiles": self.compiles,
            "compile_seconds": round(self.compile_seconds, 4),
            "hits": self.hits,
            "dispatch_seconds": round(self.dispatch_seconds, 4),
            "last_use_unix": round(self.last_use, 1),
            "cost": cost,
        }
        if cost and "error" not in cost and self.hits:
            per = self.dispatch_seconds / self.hits
            roof = {"dispatch_avg_s": round(per, 6)}
            if per > 0:
                ba = cost.get("bytes_accessed")
                fl = cost.get("flops")
                if ba:
                    roof["achieved_gbps"] = round(ba / per / 1e9, 3)
                if fl:
                    roof["achieved_gflops"] = round(fl / per / 1e9, 3)
            out["roofline"] = roof
        return out


def register(cache: str, kind: str, key, fn=None) -> ExecRecord:
    """Create (or return) the record for one compiled cache entry.
    Called at cache-miss time by the owning cache; `fn` is the jitted
    callable (kept by reference — the cache keeps it alive anyway)."""
    rk = (cache, kind, _digest(key))
    with _LOCK:
        rec = _REG.get(rk)
        if rec is None:
            rec = _REG[rk] = ExecRecord(cache, kind, key)
    if fn is not None and rec._fn is None:
        rec._fn = fn
    return rec


class _Instrumented:
    """Call-through wrapper for caches that store raw jitted callables
    (no JitAccount): books compile/dispatch splits into the record with
    the same first-call-per-signature cold detection JitAccount uses."""

    __slots__ = ("fn", "rec", "_seen")

    def __init__(self, fn, rec: ExecRecord):
        self.fn = fn
        self.rec = rec
        self._seen: set[tuple] = set()

    def __call__(self, *args, **kw):
        sig = _sig(args)
        cold = sig not in self._seen
        t0 = time.perf_counter()
        out = self.fn(*args, **kw)
        dt = time.perf_counter() - t0
        if cold:
            self._seen.add(sig)
        self.rec.note_call(dt, cold, args if cold else None,
                           kw if cold else None)
        return out


def wrap(fn, cache: str, kind: str, key):
    """Register `fn` and return it wrapped with call accounting — the
    one-liner for _EC_CACHE / _KERNEL_CACHE build sites."""
    return _Instrumented(fn, register(cache, kind, key, fn=fn))


def dump(analyze: bool | str = True, budget_s: float = 10.0) -> dict:
    """The `executables` section: every registered record, plus per-cache
    totals.  analyze=True cost-analyzes records (cached after the first
    dump; lowering only, no XLA compile) until `budget_s` of wall clock
    is spent — later entries keep cost=None rather than stalling a
    diagnostic dump.  analyze="full" additionally collects memory
    analysis (the bench end-of-run snapshot; see ExecRecord.analyze)."""
    with _LOCK:
        recs = list(_REG.values())
    entries = []
    memory = analyze == "full"
    t0 = time.perf_counter()
    with trace.span("obs.exec_analyze", entries=len(recs)):
        for rec in recs:
            if analyze and rec.analysis_pending(memory):
                # the budget must bound work BEFORE it starts, so
                # estimate from the record's measured compile time:
                # memory mode re-pays the compile itself (~1.5x), while
                # a cost-only re-lower is trace-cache-warm python with
                # no XLA (~0.2x) — a big pipeline kernel must still fit
                # the daemon's 5s budget, it is the registry's primary
                # target.  Cached results are always served for free.
                remaining = budget_s - (time.perf_counter() - t0)
                est = rec.compile_seconds * (
                    1.5 if memory and rec._mem_eligible() else 0.2
                )
                if remaining > 0 and est <= remaining:
                    rec.analyze(memory=memory)
            entries.append(rec.summary())
    by_cache: dict[str, int] = {}
    for e in entries:
        by_cache[e["cache"]] = by_cache.get(e["cache"], 0) + 1
    return {
        "entries": entries,
        "by_cache": by_cache,
        "cost_analyzed": sum(
            1 for e in entries
            if e["cost"] and "error" not in e["cost"]
        ),
        "total_compile_seconds": round(
            sum(e["compile_seconds"] for e in entries), 3
        ),
    }


def prometheus_gauges() -> str:
    """Aggregate registry gauges appended to the metrics exposition —
    per-cache entry counts, compile seconds, dispatch counts."""
    with _LOCK:
        recs = list(_REG.values())
    per: dict[str, list] = {}
    for r in recs:
        agg = per.setdefault(r.cache, [0, 0.0, 0])
        agg[0] += 1
        agg[1] += r.compile_seconds
        agg[2] += r.hits
    if not per:
        return ""
    lines = []
    # the `_total` series are monotone accumulations -> counter type
    # (Prometheus reserves the _total suffix for counters); the entry
    # count can shrink on reset() -> gauge
    for metric, help_, mtype, idx, fmt in (
        ("ceph_tpu_executables_registered",
         "compiled executables registered per trace-once cache",
         "gauge", 0, str),
        ("ceph_tpu_executables_compile_seconds_total",
         "wall seconds spent compiling, per cache",
         "counter", 1, lambda v: repr(round(v, 4))),
        ("ceph_tpu_executables_dispatches_total",
         "steady-state dispatches served, per cache",
         "counter", 2, str),
    ):
        lines.append(f"# HELP {metric} {help_}")
        lines.append(f"# TYPE {metric} {mtype}")
        for cache in sorted(per):
            lines.append(
                f'{metric}{{cache="{cache}"}} {fmt(per[cache][idx])}'
            )
    return "\n".join(lines) + "\n"


def records(cache: str | None = None, kind: str | None = None
            ) -> list[ExecRecord]:
    """Live records, optionally filtered — lets callers analyze a
    *specific* executable without paying for a whole-registry sweep."""
    with _LOCK:
        return [
            r for r in _REG.values()
            if (cache is None or r.cache == cache)
            and (kind is None or r.kind == kind)
        ]


def reset() -> None:
    """Test isolation: drop every record (unlike perf counters, records
    hold no import-time declarations — a fresh registry is safe)."""
    with _LOCK:
        _REG.clear()
