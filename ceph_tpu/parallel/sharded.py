"""PG-axis data parallelism over a jax device Mesh.

The reference scales batch placement by sharding pgid ranges over a thread
pool (ParallelPGMapper, reference src/osd/OSDMapMapping.h:18-140) and merges
per-shard results under a lock.  The TPU-native equivalent: commit the PG
axis of the batched pipeline's inputs to a `jax.sharding.Mesh` with
`NamedSharding` and let GSPMD partition the SAME compiled executables the
single-device path dispatches (`_PIPE_CACHE` entries; per-map tensors
replicated) — no locks, no merge pass, one XLA program per structure.

This module owns the mesh itself:

- `make_mesh(n)` — a 1-D mesh over the first n devices, with requested-vs-
  actual provenance (`last_mesh_provenance()`): a mesh that silently came
  up smaller than asked can never masquerade as a scaling run.
- `default_mesh()` — the `CEPH_TPU_MESH_DEVICES` knob routed through
  `make_mesh`; every production consumer (`osd.state.ClusterState`, the
  balancer's `DeviceState`, mgr eval, the lifetime engine, serve staging)
  resolves its mesh here, so one env var shards the whole pipeline.
- sharding helpers (`pg_sharding` / `row_sharding` / `replicated`) shared
  by the consumers above.

`ShardedClusterMapper` is the multichip driver surface (dryrun + bench):
it maps and reduces through the PoolMapper's OWN jitted fast/rescue
executables — the production pipeline, not a parallel copy of it — so the
MULTICHIP equality asserts cover exactly the kernels serving traffic.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ceph_tpu import obs
from ceph_tpu.core import reduce
from ceph_tpu.crush.mapper_jax import rescue_pad_for
from ceph_tpu.osd.pipeline_jax import PoolMapper
from ceph_tpu.utils import knobs

PG_AXIS = "pg"

_PL = obs.logger_for("pipeline")

# requested-vs-actual record of the LAST make_mesh call (the BENCH/
# MULTICHIP provenance surface): a degraded mesh — fewer devices than
# asked — must be visible in every record built on top of it
_MESH_PROV: dict = {}

# default_mesh() cache, keyed by the knob's current value so tests that
# monkeypatch the env observe the change
_DEFAULT_MESH: dict = {}


def pg_sharding(mesh: Mesh) -> NamedSharding:
    """1-D arrays sharded over the PG axis."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """[pg, lane] row tensors: PG axis sharded, lanes replicated."""
    return NamedSharding(mesh, P(mesh.axis_names[0], None))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated operands (per-OSD vectors, CRUSH tables)."""
    return NamedSharding(mesh, P())


def make_mesh(n_devices: int | None = None, axis: str = PG_AXIS,
              allow_fewer: bool = False) -> Mesh:
    """1-D mesh over the first n devices; the PG axis shards over it.

    The backend is acquired through the runtime degradation ladder
    (ensure_jax_backend -> runtime.acquire_backend), so a dead TPU
    transport degrades to the virtual-device CPU mesh with provenance —
    backend, fallback_reason, attempts — recorded in the `runtime` perf
    group and `runtime.last_provenance()`, which multichip drivers embed
    in their MULTICHIP JSON.

    allow_fewer: degrade to however many devices exist instead of
    raising.  Either way `last_mesh_provenance()` records requested vs
    actual, so a mesh that came up smaller than asked (the old silent
    1-device fallback) is always visible to the caller and to BENCH
    records built on it.

    (The placement workload has a single giant data axis — see SURVEY's
    parallelism inventory; there is no tensor/pipeline dimension to shard,
    so the mesh is 1-D by design.)
    """
    from ceph_tpu import runtime
    from ceph_tpu.utils import ensure_jax_backend

    backend = ensure_jax_backend()
    devs = jax.devices()
    requested = n_devices
    if n_devices is None:
        n_devices = len(devs)
    if len(devs) < n_devices:
        if not allow_fewer:
            raise RuntimeError(
                f"need {n_devices} devices, have {len(devs)} "
                "(set XLA_FLAGS=--xla_force_host_platform_device_count=N)"
            )
        n_devices = len(devs)
    prov = runtime.last_provenance() or {}
    _MESH_PROV.clear()
    _MESH_PROV.update({
        "backend": backend,
        "requested": requested,
        "actual": n_devices,
        "available": len(devs),
        "degraded": requested is not None and n_devices != requested,
        "fallback_reason": prov.get("fallback_reason"),
    })
    obs.instant("sharded.make_mesh", backend=backend,
                requested=requested, devices=n_devices,
                fallback_reason=prov.get("fallback_reason"))
    return Mesh(np.array(devs[:n_devices]), (axis,))


def last_mesh_provenance() -> dict:
    """Requested-vs-actual record of the most recent make_mesh call
    (empty before the first one)."""
    return dict(_MESH_PROV)


def default_mesh(axis: str = PG_AXIS) -> Mesh | None:
    """The process-wide production mesh: CEPH_TPU_MESH_DEVICES routed
    through make_mesh (None when the knob is unset/<=1 — single-device,
    the default).  Degrades to the available device count with
    provenance instead of raising, so a production path never crashes
    on a mis-sized knob; `last_mesh_provenance()["degraded"]` says when
    that happened."""
    val = knobs.get("CEPH_TPU_MESH_DEVICES")
    if not val:
        return None
    try:
        n = int(val)
    except ValueError:
        # a mis-typed knob degrades to single-device (the documented
        # contract), visibly rather than crashing every consumer
        _MESH_PROV.clear()
        _MESH_PROV.update({"requested": val, "actual": 1,
                           "degraded": True,
                           "fallback_reason": "unparseable knob"})
        obs.instant("sharded.make_mesh", requested=val, devices=1,
                    fallback_reason="unparseable knob")
        return None
    if n <= 1:
        return None
    key = (val, axis)
    mesh = _DEFAULT_MESH.get(key)
    if mesh is None:
        mesh = _DEFAULT_MESH[key] = make_mesh(n, axis, allow_fewer=True)
    return mesh


def _hist(ids, n, extra_mask=None):
    """Per-OSD counts via scatter-add (the shared device reduction from
    ceph_tpu.core.reduce; traceable inside other jits — bench's stats
    kernels reuse it; invalid lanes, ITEM_NONE pads and -1 no-primary
    markers, fall off the end)."""
    return reduce.osd_histogram(ids, n, extra_mask)


# (pm.cache_key, pg_padded, DV, mesh size) -> jitted stats/step kernels
# for ShardedClusterMapper — the same trace-once idiom as bench's
# _BENCH_JITS: drivers whose maps share structure share the compile.
_SHARD_JITS: dict = {}


class ShardedClusterMapper:
    """Batched pool mapping + cluster stats over a device mesh, through
    the PRODUCTION pipeline executables (PoolMapper's jitted fast/rescue
    kernels out of `_PIPE_CACHE`) with only the tiny histogram/weight
    reductions compiled here.

    Usage:
        mesh = make_mesh()
        scm = ShardedClusterMapper(osdmap, pool_id, mesh)
        out = scm.map_stats()          # mapping + per-OSD histograms
        st  = scm.rebalance_step(w)    # one on-device balancer iteration
    """

    def __init__(self, m, pool_id: int, mesh: Mesh):
        self.mesh = mesh
        self.pm = PoolMapper(m, pool_id, overlays=False, mesh=mesh)
        self.n_dev_total = mesh.devices.size
        self.DV = int(self.pm.dev["weight"].shape[0])
        self.pg_num = self.pm.spec.pg_num
        # pad the PG axis to a multiple of the mesh size (cycle-pad:
        # pad lanes duplicate early seeds and are masked out of stats)
        n = self.n_dev_total
        self.pg_padded = ((self.pg_num + n - 1) // n) * n
        # crush-weight target pinned at construction (rebalance_step)
        self._target_w = jax.device_put(
            jnp.asarray(self.pm.dev["weight"]), replicated(mesh))
        # pg_num rides in the key explicitly: pool_operands drops it
        # from pm.cache_key, but the kernels below close over it (live
        # mask, rebalance target) — same-structure pools with different
        # pg counts must not share a stats/step kernel
        key = (self.pm.cache_key, self.pg_num, self.pg_padded,
               self.DV, n)
        ent = _SHARD_JITS.get(key)
        if ent is None:
            ent = _SHARD_JITS[key] = self._build_kernels()
        self._jit_stats, self._jit_step = ent

    def _build_kernels(self):
        DV, pg_num, pg_padded = self.DV, self.pg_num, self.pg_padded
        R = self.pm.spec.size

        @jax.jit
        def stats(acting, actp):
            live = (jnp.arange(pg_padded) < pg_num)[:, None]
            hist = reduce.osd_histogram(acting, DV, live)
            phist = reduce.osd_histogram(actp[:, None], DV, live)
            fhist = reduce.osd_histogram(acting[:, :1], DV, live)
            return hist, phist, fhist

        @jax.jit
        def step(acting, weight, target_w):
            live = (jnp.arange(pg_padded) < pg_num)[:, None]
            hist = reduce.osd_histogram(acting, DV, live)
            # weight-proportional target (reference src/osd/OSDMap.cc:
            # 4707-4732 deviation build): target_i = pgs*R * w_i / sum(w)
            # computed from the FIXED crush weights (target_w), not the
            # per-iteration adjustment weights — the crush-compat balancer
            # varies the weight-set while chasing the crush-weight target
            # (reference pybind/mgr/balancer/module.py:1031 do_crush_compat)
            tw = target_w.astype(jnp.float32)
            target = (pg_num * R) * tw / jnp.maximum(jnp.sum(tw), 1.0)
            w = weight.astype(jnp.float32)
            dev_f = hist.astype(jnp.float32) - target
            stddev = jnp.sqrt(
                jnp.sum(dev_f * dev_f) / jnp.maximum(jnp.sum(tw > 0), 1)
            )
            # multiplicative correction on the 16.16 adjustment weights
            # (the choose_args weight-set update of crush-compat mode)
            ratio = target / jnp.maximum(hist.astype(jnp.float32), 1.0)
            ratio = jnp.clip(ratio, 0.5, 2.0)
            new_w = jnp.where(
                (w > 0) & (target > 0),
                jnp.clip(w * ratio, 1.0, None),
                w,
            ).astype(jnp.uint32)
            return new_w, stddev, hist

        jstats = obs.JitAccount(
            stats, _PL, "shard_stats",
            exec_record=obs.executables.register(
                "bench", "shard_stats",
                (self.pm.cache_key, pg_padded, DV), fn=stats))
        jstep = obs.JitAccount(
            step, _PL, "shard_step",
            exec_record=obs.executables.register(
                "bench", "shard_step",
                (self.pm.cache_key, pg_padded, DV), fn=step))
        return jstats, jstep

    def _ps(self):
        ps = (np.arange(self.pg_padded) % self.pg_num).astype(np.uint32)
        return jax.device_put(ps, pg_sharding(self.mesh))

    def _map_planes(self, dev):
        """All four mapping planes for every PG, device-resident and
        PG-sharded, through the production fast+rescue contract: the
        fast-window kernel runs first, flagged lanes are recomputed
        exactly through the loop kernel and scattered back — the same
        executables PoolMapper.map_batch dispatches."""
        ps = self._ps()
        with obs.span("pipeline.map_block", pgs=self.pg_num,
                      sharded=self.n_dev_total):
            *out, flg = self.pm.jitted_fast()(ps, dev, {})
        _PL.inc("pgs_mapped", self.pg_num)
        flg = np.asarray(flg)
        if flg.any():
            idx = np.nonzero(flg)[0]
            _PL.inc("unresolved_pgs", int((idx < self.pg_num).sum()))
            _PL.inc("rescue_invocations")
            jloop = self.pm.jitted_loop()
            ps_np = np.asarray((np.arange(self.pg_padded) % self.pg_num)
                               .astype(np.uint32))
            with obs.span("pipeline.rescue", lanes=len(idx)):
                Pp = rescue_pad_for(len(idx))
                for i in range(0, len(idx), Pp):
                    pad = np.resize(idx[i:i + Pp], Pp)
                    sub = jloop(jnp.asarray(ps_np[pad]), dev, {})
                    bidx = jnp.asarray(pad)
                    out = [o.at[bidx].set(s)
                           for o, s in zip(out, sub)]
        return out

    # -- sharded mapping + stats ------------------------------------------
    def map_stats(self):
        """Map all PGs; returns dict with per-PG mappings (device-sharded)
        and replicated per-OSD histograms (count / primary / first)."""
        up, upp, acting, actp = self._map_planes(self.pm.dev)[:4]
        hist, phist, fhist = self._jit_stats(acting, actp)
        return {
            "up": up, "up_primary": upp,
            "acting": acting, "acting_primary": actp,
            "pgs_per_osd": hist,
            "primary_per_osd": phist,
            "first_per_osd": fhist,
        }

    # -- one balancer iteration, fully on device ---------------------------
    def rebalance_step(self, weights=None):
        """One balancer iteration: map→histogram→deviation→weight update.
        `weights` are the adjustment weights to map with (default: the
        map's current in-weights); the deviation target always comes from
        the initial weights captured at construction.
        Returns (new_weight u32[DV], stddev, pgs_per_osd)."""
        dev = dict(self.pm.dev)
        if weights is not None:
            dev["weight"] = jax.device_put(
                jnp.asarray(weights, jnp.uint32), replicated(self.mesh))
        acting = self._map_planes(dev)[2]
        return self._jit_step(acting, dev["weight"], self._target_w)
