"""PG-axis data parallelism over a jax device Mesh.

The reference scales batch placement by sharding pgid ranges over a thread
pool (ParallelPGMapper, reference src/osd/OSDMapMapping.h:18-140) and merges
per-shard results under a lock.  The TPU-native equivalent: shard the PG axis
of the batched pipeline over a `jax.sharding.Mesh` with `shard_map`, keep the
(small) map tensors replicated, and reduce the per-OSD statistics with
`psum` over ICI — no locks, no merge pass, one XLA program.

This module also carries the cluster "step" used for rebalancing: map every
PG, histogram PGs/primaries per OSD (the stats of osdmaptool
--test-map-pgs, reference src/tools/osdmaptool.cc:696-754), and produce a
crush-compat style multiplicative weight adjustment from the deviation — one
iteration of the balancer's outer loop, fully on-device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ceph_tpu.core import reduce
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.pipeline_jax import PoolMapper

PG_AXIS = "pg"


def _shard_map(f, mesh: Mesh, in_specs, out_specs):
    """jax.shard_map moved out of jax.experimental at ~0.6; support both
    spellings (the arg asserting replication also renamed:
    check_vma <- check_rep)."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    from jax.experimental.shard_map import shard_map as esm

    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def make_mesh(n_devices: int | None = None, axis: str = PG_AXIS) -> Mesh:
    """1-D mesh over the first n devices; the PG axis shards over it.

    The backend is acquired through the runtime degradation ladder
    (ensure_jax_backend -> runtime.acquire_backend), so a dead TPU
    transport degrades to the virtual-device CPU mesh with provenance —
    backend, fallback_reason, attempts — recorded in the `runtime` perf
    group and `runtime.last_provenance()`, which multichip drivers embed
    in their MULTICHIP JSON.

    (The placement workload has a single giant data axis — see SURVEY's
    parallelism inventory; there is no tensor/pipeline dimension to shard,
    so the mesh is 1-D by design.)
    """
    from ceph_tpu import obs, runtime
    from ceph_tpu.utils import ensure_jax_backend

    backend = ensure_jax_backend()
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    if len(devs) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(devs)} "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N)"
        )
    prov = runtime.last_provenance() or {}
    obs.instant("sharded.make_mesh", backend=backend, devices=n_devices,
                fallback_reason=prov.get("fallback_reason"))
    return Mesh(np.array(devs[:n_devices]), (axis,))


def _hist(ids, n, extra_mask=None):
    """Per-OSD counts via scatter-add (the shared device reduction from
    ceph_tpu.core.reduce; traceable inside the shard_map bodies below —
    invalid lanes, ITEM_NONE pads and -1 no-primary markers, fall off
    the end)."""
    return reduce.osd_histogram(ids, n, extra_mask)


class ShardedClusterMapper:
    """Batched pool mapping + cluster stats, sharded over a device mesh.

    Usage:
        mesh = make_mesh()
        scm = ShardedClusterMapper(osdmap, pool_id, mesh)
        out = scm.map_stats()          # mapping + per-OSD histograms
        st  = scm.rebalance_step(w)    # one on-device balancer iteration
    """

    def __init__(self, m, pool_id: int, mesh: Mesh):
        self.mesh = mesh
        self.pm = PoolMapper(m, pool_id, overlays=False)
        self.n_dev_total = mesh.devices.size
        self.DV = int(self.pm.dev["weight"].shape[0])
        self.pg_num = self.pm.spec.pg_num
        # pad the PG axis to a multiple of the mesh size
        n = self.n_dev_total
        self.pg_padded = ((self.pg_num + n - 1) // n) * n
        self._jit_map = None
        self._jit_step = None
        # crush-weight target pinned at construction (rebalance_step)
        self._target_w = jnp.asarray(self.pm.dev["weight"])

    # -- sharded mapping + stats ------------------------------------------
    def _build_map_fn(self):
        fn, DV, pg_num = self.pm.fn, self.DV, self.pg_num
        vf = jax.vmap(fn, in_axes=(0, None, 0))
        axis = self.mesh.axis_names[0]

        def local(ps, dev):
            # the exact kernel's trailing with_raw output (pre-overlay
            # descent row) is not sharded state — drop it here
            up, upp, acting, actp = vf(ps, dev, {})[:4]
            live = ps < pg_num  # padding rows don't count
            hist = _hist(acting, DV, live[:, None])
            phist = _hist(actp[:, None], DV, live[:, None])
            fhist = _hist(acting[:, :1], DV, live[:, None])
            hist = jax.lax.psum(hist, axis)
            phist = jax.lax.psum(phist, axis)
            fhist = jax.lax.psum(fhist, axis)
            return up, upp, acting, actp, hist, phist, fhist

        sm = _shard_map(
            local,
            self.mesh,
            (P(axis), P()),
            (P(axis), P(axis), P(axis), P(axis), P(), P(), P()),
        )
        return jax.jit(sm)

    def _ps(self):
        ps = np.arange(self.pg_padded, dtype=np.uint32)
        sh = NamedSharding(self.mesh, P(self.mesh.axis_names[0]))
        return jax.device_put(ps, sh)

    def map_stats(self):
        """Map all PGs; returns dict with per-PG mappings (device-sharded)
        and replicated per-OSD histograms (count / primary / first)."""
        if self._jit_map is None:
            self._jit_map = self._build_map_fn()
        up, upp, acting, actp, hist, phist, fhist = self._jit_map(
            self._ps(), self.pm.dev
        )
        return {
            "up": up, "up_primary": upp,
            "acting": acting, "acting_primary": actp,
            "pgs_per_osd": hist,
            "primary_per_osd": phist,
            "first_per_osd": fhist,
        }

    # -- one balancer iteration, fully on device ---------------------------
    def _build_step_fn(self):
        fn, DV, pg_num = self.pm.fn, self.DV, self.pg_num
        R = self.pm.spec.size
        vf = jax.vmap(fn, in_axes=(0, None, 0))
        axis = self.mesh.axis_names[0]

        def local(ps, dev, target_w):
            _, _, acting, _ = vf(ps, dev, {})[:4]
            live = ps < pg_num
            hist = jax.lax.psum(_hist(acting, DV, live[:, None]), axis)
            # weight-proportional target (reference src/osd/OSDMap.cc:
            # 4707-4732 deviation build): target_i = pgs*R * w_i / sum(w)
            # computed from the FIXED crush weights (target_w), not the
            # per-iteration adjustment weights — the crush-compat balancer
            # varies the weight-set while chasing the crush-weight target
            # (reference pybind/mgr/balancer/module.py:1031 do_crush_compat)
            tw = target_w.astype(jnp.float32)
            target = (pg_num * R) * tw / jnp.maximum(jnp.sum(tw), 1.0)
            w = dev["weight"].astype(jnp.float32)
            dev_f = hist.astype(jnp.float32) - target
            stddev = jnp.sqrt(
                jnp.sum(dev_f * dev_f) / jnp.maximum(jnp.sum(tw > 0), 1)
            )
            # multiplicative correction on the 16.16 adjustment weights
            # (the choose_args weight-set update of crush-compat mode)
            ratio = target / jnp.maximum(hist.astype(jnp.float32), 1.0)
            ratio = jnp.clip(ratio, 0.5, 2.0)
            new_w = jnp.where(
                (w > 0) & (target > 0),
                jnp.clip(w * ratio, 1.0, None),
                w,
            ).astype(jnp.uint32)
            return new_w, stddev, hist

        sm = _shard_map(
            local,
            self.mesh,
            (P(axis), P(), P()),
            (P(), P(), P()),
        )
        return jax.jit(sm)

    def rebalance_step(self, weights=None):
        """One balancer iteration: map→histogram→deviation→weight update.
        `weights` are the adjustment weights to map with (default: the
        map's current in-weights); the deviation target always comes from
        the initial weights captured at construction.
        Returns (new_weight u32[DV], stddev, pgs_per_osd)."""
        if self._jit_step is None:
            self._jit_step = self._build_step_fn()
        dev = dict(self.pm.dev)
        if weights is not None:
            dev["weight"] = jnp.asarray(weights, jnp.uint32)
        return self._jit_step(self._ps(), dev, self._target_w)
