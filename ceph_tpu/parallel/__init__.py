from ceph_tpu.parallel.sharded import (
    ShardedClusterMapper,
    make_mesh,
)

__all__ = ["ShardedClusterMapper", "make_mesh"]
