from ceph_tpu.parallel.sharded import (
    ShardedClusterMapper,
    default_mesh,
    last_mesh_provenance,
    make_mesh,
)

__all__ = ["ShardedClusterMapper", "default_mesh",
           "last_mesh_provenance", "make_mesh"]
