"""Cluster-lifetime chaos simulator: thousands of epochs of failure,
churn, and growth under deterministic fault schedules.

Every other workload in the repo maps a *static* cluster (bench configs)
or runs single-shot thrash rounds (`sim.failure.ClusterSim`).  This
module composes every subsystem into one long-running torture test:

- **Events are real epoch deltas.**  Each simulated epoch builds an
  `osd.incremental.Incremental` (OSD flaps/deaths/permanent removals,
  CRUSH-tree-aware host/rack outages, reweights, pg_temp overrides, pool
  creation, `pg_num` splits, cluster expansion via the CRUSH builder
  API) and advances the map through `apply_incremental` — the same
  epoch-monotonic chain a monitor would publish.  Every
  `balance_every` epochs the mgr balancer (`ceph_tpu.mgr.Balancer`,
  upmap mode) runs and `execute()`s its plan, so its Incrementals ride
  the same chain.

- **Deterministic chaos.**  The event at epoch `e` is drawn from
  `numpy.random.default_rng([seed, e])` — no RNG state spans epochs, so
  the same seed produces a bit-identical event trajectory AND a resumed
  run continues exactly where the interrupted one left off.  The running
  `digest` (a SHA-256 chain over per-epoch event + accounting lines) is
  the equality witness: same seed ⇒ same digest, resume ⇒ same final
  digest.

- **Correlated failures (scenario `correlated=1`).**  Three layers on
  top of the independent draws, every one deterministic and
  checkpoint-exact: *repeat-offender flappers* (a once-per-lifetime
  draw marks `flappers` OSDs whose flap-victim weight is multiplied by
  `flapper_boost`, so the same OSDs flap again and again);
  *failure-domain hazard windows* (a host/rack outage raises a
  `cascade_hazard` outage-probability boost on its sibling domains
  that decays by `cascade_decay` per epoch for `cascade_len` epochs —
  and while windows are open, outages strike hazarded siblings,
  producing cascading-rack sequences); and *durability accounting*
  (true deaths wound every PG that carried the OSD; wounds heal when
  the PG's recovery backlog drains; a PG wounded past its EC tolerance
  while un-drained is irreversibly `pg_lost` — folded into the digest
  line as a `|D` segment, raised as the never-auto-clearing
  `DATA_LOSS` health check, and exported as timeline exposure series).
  Flaps and outages revive with their bytes intact (false-positive
  down-marks); only deaths feed wounds and the recovery queue.

- **Accounting stays device-side, and epoch state is O(delta).**  The
  per-map device operands live in ONE `osd.state.ClusterState` shared
  with the balancer and mgr: epoch deltas apply ON DEVICE in O(delta)
  (vector scatters, overlay fixups from device-resident raw results),
  and version tags let an epoch that did not touch a pool's mapping
  skip its remap AND its stats entirely — digest-exactly, since equal
  tags guarantee bit-identical rows.  Per-epoch degraded / unmapped /
  at-risk / moved / remapped tallies reduce ON DEVICE
  (`core/reduce.py`); only a handful of int64 scalars are fetched per
  pool per epoch.  Compiled pipelines come from `_PIPE_CACHE`
  (trace-once): a steady epoch — values changed, structure unchanged —
  must book **0 compiles and 0 state rebuilds**, proven by the
  `pipe_cache_*` / JitAccount / `state.*` counters and recorded per
  run in the `trace_once` summary.  Epochs that genuinely change
  structure (expansion, removal, splits crossing a block-shape
  boundary, the first balancer pass over a new overlay layout) are
  classified `structural` and excluded from that gate.

- **EC-aware data-at-risk windows.**  A PG is *at risk* when its up set
  has lost more chunks than the pool tolerates (EC profile: > m chunks;
  replicated: > size-1 replicas).  Each epoch's simulated duration
  follows a configurable recovery-rate model (`moved bytes /
  recovery_mbps`, floored at `interval_s`), and `at_risk_pg_seconds`
  integrates the at-risk PG count over that simulated time — the
  recovery-traffic/data-at-risk framing of "Understanding System
  Characteristics of Online Erasure Coding on Scalable, Distributed and
  Large-Scale SSD Array Systems" (PAPERS.md).

- **Robustness is the headline.**  Device loss mid-lifetime
  (`runtime.faults` point `epoch_apply`, or a real transport loss)
  degrades that epoch's accounting to the bit-exact host mapper — the
  digest is unchanged by construction — records provenance, and the
  simulation continues.  An every-epoch invariant checker (no PG
  silently unmapped — empty device row while the host oracle maps it,
  no duplicate OSDs in a row, upmap / pg_temp respected, periodic
  jax==host spot-check lanes) feeds the `sim` perf group.  Crash safety rides `runtime.Checkpoint`: the full
  state (map blob + digest + transient-event bookkeeping) flushes
  atomically every `checkpoint_every` epochs, and `resume=True`
  continues from the last checkpointed epoch (`lifetime_step=exit:N`
  fault + `cli/sim.py --resume` is the kill test).

Scenario syntax (`Scenario.parse`): comma-separated `key=value` pairs
over the `Scenario` dataclass fields, e.g.

    epochs=500,seed=7,hosts=8,osds_per_host=4,racks=2,ec=4+2,
    balance_every=16,p_flap=0.3,recovery_mbps=250

Headline metric: simulated cluster-years per wallclock hour
(`cluster_years_per_hour` in the run summary and the `lifetime` bench
stage).
"""

from __future__ import annotations

import base64
import copy
import hashlib
import time
from dataclasses import dataclass, fields

import numpy as np

from ceph_tpu import obs
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.incremental import Incremental, apply_incremental
from ceph_tpu.osd.osdmap import IN_WEIGHT, OSD_EXISTS, OSD_UP, OSDMap
from ceph_tpu.osd.types import PgId, PgPool, PoolType
from ceph_tpu.runtime import Checkpoint, faults
from ceph_tpu.sim.failure import (
    MovementReport,
    _device_loss_counter,
    _map_ref,
)
from ceph_tpu.utils import knobs
from ceph_tpu.utils.dout import subsys_logger

_log = subsys_logger("sim")

_L = obs.logger_for("sim")
_L.add_u64("epochs", "lifetime epochs applied (one Incremental chain "
                     "link each, plus the balancer's own links)")
_L.add_u64("events_applied", "non-quiet chaos events applied")
_L.add_u64("invariant_violations",
           "per-epoch invariant checks that failed (device-empty rows "
           "the host oracle maps, duplicate OSDs in a row, "
           "upmap/pg_temp not respected, jax==host spot-check "
           "mismatches)")
_L.add_u64("degraded_pg_epochs", "epochs that ended with >=1 degraded PG")
_L.add_u64("structural_epochs",
           "epochs whose event changed compiled structure (expansion, "
           "removal, block-shape-crossing splits, new overlay layouts) "
           "— the only epochs allowed to book compiles")
_L.add_u64("spot_checks", "jax==host spot-check lanes compared")
_L.add_u64("spotcheck_mismatches", "spot-check lanes that disagreed")
_L.add_u64("checkpoints", "lifetime checkpoints flushed")
_L.add_u64("cascade_outages",
           "outages that fired while a sibling-domain hazard window "
           "was open (correlated model: links of a cascade chain)")
_L.add_u64("flap_revives",
           "false-positive down-marks (link flaps) that revived with "
           "their bytes intact")
_L.add_u64("pgs_lost",
           "PGs whose simultaneously-dead chunks exceeded the pool's "
           "tolerance while their recovery backlog was un-drained — "
           "irreversible data loss (DATA_LOSS health check)")
_L.add_avg("at_risk_pg_seconds",
           "integral of the at-risk PG count over simulated seconds "
           "(one observation per epoch)")
_L.add_quantile("epoch_seconds",
                "wall-clock seconds per lifetime epoch (apply + remap + "
                "accounting + invariants)")


# --------------------------------------------------------------- scenario

# The compiled-in chaos-event registry: kind -> what it does.  Keep this
# a pure dict literal (the graftlint `scenario-event` pass literal_evals
# it without importing): `Scenario.event_probs()` must walk exactly
# these kinds, and every kind must be exercised by at least one test —
# a new event type cannot land untested.
EVENT_KINDS: dict[str, str] = {
    "flap": "one OSD marked down transiently; bytes intact, revives "
            "after flap_len epochs (repeat offenders under correlated)",
    "death": "one OSD marked down and weighted out permanently; its "
             "chunks are gone and the recovery queue re-replicates",
    "remove": "a previously-dead OSD destroyed and pulled from CRUSH",
    "host_outage": "a whole host bucket's OSDs marked down together; "
                   "bytes intact, revives after outage_len epochs",
    "rack_outage": "a whole rack bucket's OSDs marked down together; "
                   "bytes intact, revives after outage_len epochs",
    "reweight": "one in OSD's weight nudged (0.6..1.0 of IN_WEIGHT)",
    "pg_temp": "one PG's acting set rotated via pg_temp/primary_temp, "
               "cleared after temp_len epochs",
    "pool_create": "a new replicated pool (up to max_pools)",
    "split": "one pool's pg_num doubled (up to max_pgs)",
    "expand": "a new host of osds_per_host OSDs joins CRUSH (up to "
              "max_expand over the lifetime)",
}


@dataclass
class Scenario:
    """One lifetime run's shape: cluster, chaos mix, recovery model.

    Parsed from comma-separated `key=value` pairs (`Scenario.parse`);
    `spec()` renders the canonical string a checkpoint pins so a resume
    cannot silently continue a *different* scenario."""

    epochs: int = 500
    seed: int = 0
    # initial cluster
    hosts: int = 8
    osds_per_host: int = 4
    racks: int = 2
    pgs: int = 256           # replicated pool pg_num
    size: int = 3            # replicated pool size
    ec: str = "4+2"          # EC pool "k+m" ("" disables it)
    ec_pgs: int = 128
    chunk: int = 4096        # PG-axis block size of the accounting pass
    # mgr balancer cadence (0 disables)
    balance_every: int = 16
    balance_max: int = 8     # upmap_max_optimizations per run
    # chaos probabilities per epoch (remaining mass = quiet epoch)
    p_flap: float = 0.25
    p_death: float = 0.04
    p_remove: float = 0.02
    p_host_outage: float = 0.04
    p_rack_outage: float = 0.01
    p_reweight: float = 0.10
    p_pg_temp: float = 0.04
    p_pool_create: float = 0.01
    p_split: float = 0.01
    p_expand: float = 0.01
    # transient-event durations (epochs, drawn uniform in [1, len])
    flap_len: int = 4
    outage_len: int = 6
    temp_len: int = 5
    # recovery model.  "" resolves from CEPH_TPU_SIM_RECOVERY (default
    # "queue": the per-PG backlog / per-OSD slot+bandwidth data plane of
    # ceph_tpu.recovery; "flat" is the legacy one-division model, kept
    # bit-identical).  spec() pins the resolved value, so a checkpoint
    # can never be resumed under the other model.
    recovery: str = ""
    pg_gb: float = 1.0       # data per PG (GB), spread over `size` shards
    recovery_mbps: float = 100.0
    interval_s: float = 30.0  # floor of one epoch's simulated duration
    # queue-model resources (ignored under recovery=flat)
    max_backfills: int = 2   # per-OSD concurrent recovery streams
    osd_mbps: float = 125.0  # per-OSD epoch bandwidth (client + recovery)
    pipeline_repair: int = 0  # 1 = RapidRAID-style stage overlap (EC)
    ec_gbps: float = 1.6     # measured EC strategy GB/s (encode stage)
    # client workload generator (0 disables; metrics + digest lines
    # only exist when enabled)
    workload: int = 0
    base_qps: float = 1000.0
    read_fraction: float = 0.75
    zipf_a: float = 4.0      # hot-key skew exponent (higher = hotter)
    hot_pool: float = 1.0    # Zipf rank weight across pools
    diurnal_amp: float = 0.5
    diurnal_period: int = 288
    obj_kb: int = 64         # bytes per modeled object request
    wl_sample: int = 128     # sampled requests per pool per epoch
    # correlated-failure model (0 = legacy independent draws; spec()
    # pins the whole block, so a checkpoint can never be resumed under
    # the other regime and digests never mix)
    correlated: int = 0
    flappers: int = 2           # repeat-offender OSDs (drawn once)
    flapper_boost: float = 8.0  # flap-victim weight for offenders
    cascade_hazard: float = 0.35  # outage hazard added on siblings
    cascade_decay: float = 0.6  # per-epoch hazard strength multiplier
    cascade_len: int = 6        # epochs a hazard window stays open
    # growth limits
    new_pool_pgs: int = 64
    max_pools: int = 6
    max_pgs: int = 4096      # per-pool pg_num cap for splits
    max_expand: int = 8      # hosts added over the whole lifetime
    # cadences (0 disables); -1 = take the CEPH_TPU_SIM_* env knob
    checkpoint_every: int = -1
    spotcheck_every: int = -1
    spotcheck_lanes: int = 4

    def __post_init__(self):
        if self.checkpoint_every < 0:
            self.checkpoint_every = int(
                knobs.get("CEPH_TPU_SIM_CHECKPOINT_EVERY", "100"))
        if self.spotcheck_every < 0:
            self.spotcheck_every = int(
                knobs.get("CEPH_TPU_SIM_SPOTCHECK", "16"))
        if not self.recovery:
            self.recovery = knobs.get("CEPH_TPU_SIM_RECOVERY", "queue")
        if self.recovery not in ("queue", "flat"):
            raise ValueError(
                f"recovery={self.recovery!r}: known models are 'queue' "
                "(per-PG backlog / per-OSD slot+bandwidth drain) and "
                "'flat' (legacy one-division)")

    @classmethod
    def parse(cls, spec: str | None) -> "Scenario":
        kw: dict = {}
        types = {f.name: f.type for f in fields(cls)}
        for item in (spec or "").replace("\n", ",").split(","):
            item = item.strip()
            if not item:
                continue
            key, sep, val = item.partition("=")
            key, val = key.strip(), val.strip()
            if not sep or key not in types:
                raise ValueError(f"bad scenario item {item!r} "
                                 f"(known keys: {sorted(types)})")
            t = types[key]
            kw[key] = val if t == "str" else (
                float(val) if t == "float" else int(val))
        return cls(**kw)

    def spec(self) -> str:
        return ",".join(
            f"{f.name}={getattr(self, f.name)}" for f in fields(self)
        )

    def ec_km(self) -> tuple[int, int] | None:
        if not self.ec:
            return None
        k, _, mm = self.ec.partition("+")
        return int(k), int(mm)

    def event_probs(self) -> tuple[tuple[str, float], ...]:
        """(kind, probability) in a FIXED order — the cumulative walk
        the per-epoch draw runs over (order is part of determinism).
        Kinds must match `EVENT_KINDS` exactly (graftlint + the drift
        test pin both directions)."""
        return (
            ("flap", self.p_flap),
            ("death", self.p_death),
            ("remove", self.p_remove),
            ("host_outage", self.p_host_outage),
            ("rack_outage", self.p_rack_outage),
            ("reweight", self.p_reweight),
            ("pg_temp", self.p_pg_temp),
            ("pool_create", self.p_pool_create),
            ("split", self.p_split),
            ("expand", self.p_expand),
        )


def build_cluster(sc: Scenario) -> OSDMap:
    """The scenario's initial map: hierarchical hosts/racks, one
    replicated pool, optionally one EC pool with a real erasure rule
    and profile entry."""
    from ceph_tpu.osd.osdmap import build_hierarchical

    m = build_hierarchical(
        sc.hosts, sc.osds_per_host, n_rack=sc.racks,
        pool=PgPool(
            type=PoolType.REPLICATED, size=sc.size, crush_rule=0,
            pg_num=sc.pgs, pgp_num=sc.pgs,
        ),
    )
    km = sc.ec_km()
    if km is not None:
        k, mm = km
        root = next(
            bid for bid, b in m.crush.buckets.items() if b.type == 11
        )
        ruleno = m.crush.make_erasure_rule(
            root, 1 if sc.hosts > 1 else 0, num_chunks=k + mm
        )
        m.erasure_code_profiles["lifetime-ec"] = {
            "k": str(k), "m": str(mm), "plugin": "jax",
        }
        m.add_pool("lifetime-ec", PgPool(
            type=PoolType.ERASURE, size=k + mm, min_size=k + 1,
            crush_rule=ruleno, pg_num=sc.ec_pgs, pgp_num=sc.ec_pgs,
            erasure_code_profile="lifetime-ec",
        ))
    return m


# --------------------------------------------------- shared stat formulas
# One formula set, two executors: the jax version runs inside a jitted
# kernel on device rows; the numpy version is the bit-exact host mirror
# the degraded (device-lost) path and the "ref" backend use — digest
# equality across backends depends on these two never diverging.


def _stats_np(prev, rows, n: int, size: int, tol: int):
    """Returns ([degraded, unmapped, at_risk, dup, moved, remapped],
    per-PG moved-lane counts int64 [N]) — the second output feeds the
    recovery queue's per-PG enqueue."""
    rows = np.asarray(rows)
    prev = np.asarray(prev)
    real = np.arange(rows.shape[0]) < n
    valid = (rows != ITEM_NONE) & (rows >= 0)
    occ = valid.sum(axis=1)
    degraded = int((real & (occ < size)).sum())
    unmapped = int((real & (occ == 0)).sum())
    at_risk = int((real & (occ < size - tol)).sum())
    w = rows.shape[1]
    eq = (rows[:, :, None] == rows[:, None, :]) \
        & valid[:, :, None] & valid[:, None, :]
    dup = int((real & (eq & np.triu(np.ones((w, w), bool), 1)).any(
        axis=(1, 2))).sum())
    mem_ab = (rows[:, :, None] == prev[:, None, :]).any(axis=2)
    moved_l = ~mem_ab & valid
    moved_rows = (moved_l & real[:, None]).sum(axis=1).astype(np.int64)
    moved = int(moved_rows.sum())
    pvalid = (prev != ITEM_NONE) & (prev >= 0)
    mem_ba = (prev[:, :, None] == rows[:, None, :]).any(axis=2)
    changed = moved_l.any(axis=1) | (~mem_ba & pvalid).any(axis=1)
    remapped = int((real & changed).sum())
    return [degraded, unmapped, at_risk, dup, moved, remapped], \
        moved_rows


def _build_stats_account():
    """The jitted device-side epoch reducer (lazy: no jax at module
    import).  n/size/tol ride as scalar operands so pools sharing row
    shapes share one executable."""
    import jax
    import jax.numpy as jnp

    from ceph_tpu.core import reduce

    def _epoch_stats(prev, rows, n, size, tol):
        real = jnp.arange(rows.shape[0]) < n
        occ = reduce.result_sizes(rows)
        size = size.astype(jnp.int32)
        tol = tol.astype(jnp.int32)
        degraded = jnp.sum((real & (occ < size)).astype(jnp.int64))
        unmapped = jnp.sum((real & (occ == 0)).astype(jnp.int64))
        at_risk = jnp.sum((real & (occ < size - tol)).astype(jnp.int64))
        dup = jnp.sum(
            (real & reduce.duplicate_rows(rows)).astype(jnp.int64))
        moved_rows = jnp.sum(
            (reduce.moved_in_lanes(prev, rows) & real[:, None])
            .astype(jnp.int64), axis=1)
        moved = jnp.sum(moved_rows)
        remapped = jnp.sum(
            (real & reduce.changed_rows(prev, rows)).astype(jnp.int64))
        return jnp.stack(
            [degraded, unmapped, at_risk, dup, moved, remapped]), \
            moved_rows

    return obs.JitAccount(jax.jit(_epoch_stats), _L, "epoch_stats")


_STATS_ACCT = None


def _stats_account():
    global _STATS_ACCT
    if _STATS_ACCT is None:
        _STATS_ACCT = _build_stats_account()
    return _STATS_ACCT


STAT_KEYS = ("degraded", "unmapped", "at_risk", "dup", "moved",
             "remapped")

# recovery digest fields: the per-pool ints chained into the epoch line
# when the queue model runs (exact across jax/host by construction)
RECOVERY_DIGEST_KEYS = ("enqueued", "drained", "backlog", "risk_us",
                        "completed")
WORKLOAD_DIGEST_KEYS = ("requests", "reads", "degraded_reads",
                        "at_risk_hits", "backlog_hits")
# durability digest fields (correlated model only): per-pool dead-chunk
# sum, exposed-PG count, and the irreversible lost-PG count
DURABILITY_DIGEST_KEYS = ("wounds", "exposed", "lost")


def _recovery_counters():
    """The `recovery` perf group (declared in ceph_tpu/recovery/queue.py
    — only reachable here after that module was imported)."""
    return obs.logger_for("recovery")


# ------------------------------------------------------------- invariants


def check_rows_invariants(m: OSDMap, pid: int, rows, n: int,
                          only_seeds: set[int] | None = None,
                          oracle=None) -> list[str]:
    """Host-side invariant check over one pool's up rows [>=n, W]
    (numpy; lanes beyond n ignored).  Used as the detailed reporter when
    the device scalars flag a problem, and directly by the
    negative-control tests.  `only_seeds` restricts every check to that
    seed subset (the engine's sampled overlay checks, where the other
    rows were never fetched).  Returns violation strings (empty =
    clean).

    - no PG silently unmapped: an empty row only violates when the
      bit-exact host oracle maps the PG somewhere (device/host
      divergence).  CRUSH itself legitimately returns nothing when its
      tries exhaust under heavy weight-out, or when every replica is
      down — the reference calls that a *bad mapping* / a `down` PG
      (degradation, accounted), never an invariant breach;
    - no duplicate OSD inside one row;
    - pg_upmap / pg_upmap_items entries respected by the rows;

    `oracle(seed) -> up list` overrides the host replay source (the
    engine passes its descent-memoized oracle: a tries-exhausted PG
    would otherwise re-pay the full descent every flagged epoch).
    """
    rows = np.asarray(rows)[:n]
    seed_iter = sorted(only_seeds) if only_seeds is not None \
        else range(n)
    if oracle is None:
        def oracle(seed):
            up, _, _, _ = m.pg_to_up_acting_osds(PgId(pid, int(seed)))
            return up
    out: list[str] = []
    valid = (rows != ITEM_NONE) & (rows >= 0)
    occ = valid.sum(axis=1)
    empty = [s for s in seed_iter if occ[s] == 0][:8]
    for seed in empty:  # bounded host replays
        want = [o for o in oracle(int(seed)) if o != ITEM_NONE]
        if want:
            out.append(
                f"pool {pid} pg {pid}.{int(seed):x} device row empty "
                f"but the host oracle maps {want}"
            )
    # duplicate scan stays vectorized; python only walks the hits
    w = rows.shape[1]
    eq = (rows[:, :, None] == rows[:, None, :]) \
        & valid[:, :, None] & valid[:, None, :]
    dup_rows = (eq & np.triu(np.ones((w, w), bool), 1)).any(axis=(1, 2))
    for seed in seed_iter:
        if dup_rows[seed]:
            lanes = [int(o) for o in rows[seed]
                     if o != ITEM_NONE and o >= 0]
            out.append(
                f"pool {pid} pg {pid}.{seed:x} carries duplicate OSDs "
                f"{lanes}"
            )
            if len(out) >= 16:
                return out
    for pg, p in m.pg_upmap.items():
        if pg.pool != pid or pg.seed >= n or (
                only_seeds is not None and pg.seed not in only_seeds):
            continue
        if any(o != ITEM_NONE and 0 <= o < m.max_osd
               and m.osd_weight[o] == 0 for o in p):
            continue  # rejected upmap (out target): not applied
        want = sorted(o for o in p if m.is_up(o))
        got = sorted(int(o) for o in rows[pg.seed]
                     if o != ITEM_NONE and o >= 0)
        if want and got != want:
            out.append(
                f"pool {pid} pg {pg} pg_upmap {list(p)} not respected: "
                f"row {got}"
            )
    for pg, pairs in m.pg_upmap_items.items():
        if pg.pool != pid or pg.seed >= n or (
                only_seeds is not None and pg.seed not in only_seeds):
            continue
        lanes = {int(o) for o in rows[pg.seed]
                 if o != ITEM_NONE and o >= 0}
        for frm, to in pairs:
            if (0 <= to < m.max_osd and m.is_up(to) and m.is_in(to)
                    and frm in lanes and to not in lanes):
                out.append(
                    f"pool {pid} pg {pg} upmap item {frm}->{to} not "
                    f"respected: {frm} still mapped, {to} absent"
                )
    return out


def check_pg_temp_invariants(m: OSDMap) -> list[str]:
    """Model-level pg_temp check: every live pg_temp entry must drive
    the acting set the reference semantics prescribe (entries filtered
    of dead OSDs, primary_temp honored)."""
    out: list[str] = []
    for pg, temp in m.pg_temp.items():
        pool = m.pools.get(pg.pool)
        if pool is None or pg.seed >= pool.pg_num:
            continue
        expect = [o for o in temp if m.exists(o) and not m.is_down(o)] \
            if pool.can_shift_osds() else [
                o if (m.exists(o) and not m.is_down(o)) else ITEM_NONE
                for o in temp]
        if not [o for o in expect if o != ITEM_NONE]:
            continue  # fully-dead temp: acting falls back to up
        _, _, acting, actp = m.pg_to_up_acting_osds(pg)
        if list(acting) != list(expect):
            out.append(
                f"pg_temp {pg} {list(temp)} not respected: acting "
                f"{list(acting)} != {list(expect)}"
            )
        want_p = m.primary_temp.get(pg)
        if want_p is not None and actp != want_p:
            out.append(
                f"primary_temp {pg} {want_p} not respected: acting "
                f"primary {actp}"
            )
    return out


# ------------------------------------------------------------- the engine


class LifetimeSim:
    """Scenario-driven lifetime engine (see module docstring).

    backend: "jax" (device accounting, host-degradable) or "ref" (host
    mapper + numpy accounting end to end — bit-identical digests).
    checkpoint: path of the atomic state file (runtime.Checkpoint
    shape); resume=True restores from it and continues."""

    def __init__(self, scenario: Scenario | str | None = None,
                 backend: str = "jax",
                 checkpoint: str | None = None, resume: bool = False,
                 mesh=None, restore_state: dict | None = None):
        if isinstance(scenario, str) or scenario is None:
            scenario = Scenario.parse(scenario)
        self.scenario = scenario
        self.backend = backend
        # PG-axis device mesh for the whole epoch loop: the shared
        # ClusterState shards its rows over it (None = ClusterState
        # resolves the CEPH_TPU_MESH_DEVICES knob itself), so chaos
        # epochs exercise SHARDED mapping with the same SHA-256 replay
        # digest as single-device — the reductions are exact-integer,
        # so partitioning cannot move a digest bit
        self.mesh = mesh
        self.steps = 0
        self.digest = hashlib.sha256(
            scenario.spec().encode()).hexdigest()
        self.sim_seconds = 0.0
        self.report = MovementReport()
        self.violations: list[str] = []
        self.fallback_events: list[str] = []
        self.event_counts: dict[str, int] = {}
        self.degraded_epochs = 0
        self.structural_epochs = 0
        self.steady_epochs = 0
        self.steady_compiles = 0
        self.steady_pipe_misses = 0
        self.total_compiles = 0
        # transient-event bookkeeping (all JSON-serializable)
        self.flap_down: dict[int, int] = {}     # osd -> revive step
        self.outages: list[list] = []           # [revive step, [osds]]
        self.temps: list[list] = []             # [pool, seed, clear step]
        self.dead: list[int] = []
        self.host_seq = scenario.hosts
        self.expanded = 0
        # correlated-failure model state.  Hazard windows are
        # PATH-DEPENDENT (their decayed strengths depend on when each
        # outage fired), so they are checkpointed, never recomputed.
        # [bucket type, bucket id, expire epoch, strength]
        self.hazards: list[list] = []
        self.wounded: dict[int, np.ndarray] = {}   # pid -> dead chunks/PG
        self.healing: dict[int, np.ndarray] = {}   # pid -> repair seen
        self.lost: dict[int, list[int]] = {}       # pid -> lost seeds
        self.pg_lost_total = 0
        self.exposed_pg_epochs = 0
        self.flap_counts: dict[int, int] = {}
        self.false_flap_revives = 0
        self.domain_outages: dict[str, int] = {}
        self.cascades = 0
        self.longest_cascade = 0
        self._cascade_run = 0
        self.hazard_windows = 0
        # repeat offenders: a pure function of the scenario (one draw
        # per LIFETIME, not per epoch), so resume recomputes the same
        # set and the per-epoch rng stream stays untouched by it
        self.flapper_osds: list[int] = []
        if scenario.correlated and scenario.flappers > 0:
            n0 = scenario.hosts * scenario.osds_per_host
            pick = np.random.default_rng(
                [scenario.seed, 0xF1A9]).choice(
                n0, size=min(scenario.flappers, n0), replace=False)
            self.flapper_osds = sorted(int(o) for o in pick)
        self._flapper_set = set(self.flapper_osds)
        self.resumed_from: int | None = None
        # in-process caches (never checkpointed: cache state, not truth).
        # self.state is the device-resident ClusterState (jax backend):
        # per-OSD vectors scatter-updated in O(delta), per-pool rows
        # version-tagged so unchanged pools skip ALL device work.
        self.state = None
        self._prev_rows: dict[int, tuple] = {}   # pid -> (tag, rows)
        self._stats_cache: dict[int, tuple] = {}  # pid -> (tag, row-stats)
        self._moved: dict[int, object] = {}  # pid -> per-PG moved lanes
        # recovery data plane + client workload (ceph_tpu.recovery /
        # sim.workload): the queue model is the default for fresh
        # scenarios; "flat" keeps the legacy one-division model
        # bit-identical.  The generator is opt-in (scenario workload=1).
        self.recovery = None
        if scenario.recovery == "queue":
            from ceph_tpu.recovery import RecoveryQueue

            self.recovery = RecoveryQueue(
                pg_gb=scenario.pg_gb,
                recovery_mbps=scenario.recovery_mbps,
                interval_s=scenario.interval_s,
                max_backfills=scenario.max_backfills,
                osd_mbps=scenario.osd_mbps,
                pipeline_repair=scenario.pipeline_repair,
                ec_gbps=scenario.ec_gbps)
        self.workload = None
        if scenario.workload:
            from ceph_tpu.sim.workload import WorkloadGen

            self.workload = WorkloadGen(
                seed=scenario.seed, base_qps=scenario.base_qps,
                read_fraction=scenario.read_fraction,
                zipf_a=scenario.zipf_a, hot_pool=scenario.hot_pool,
                diurnal_amp=scenario.diurnal_amp,
                diurnal_period=scenario.diurnal_period,
                obj_kb=scenario.obj_kb, sample=scenario.wl_sample,
                interval_s=scenario.interval_s)
        self._cap_rem = None  # per-OSD capacity left after clients
        # test hook: perturb a pool-epoch's drain scalars to prove the
        # byte-conservation invariant catches a disagreeing data plane
        self.recovery_corrupt_hook = None
        self.steady_full_rebuilds = 0
        # per-epoch summarized health status tallies (obs/health.py)
        self._health_counts = {"ok": 0, "warn": 0, "err": 0}
        self._prev_skeys: frozenset | None = None
        self._last_balance_key = None
        self._overlay_checked: dict[int, tuple] = {}
        self._pg_temp_checked = None
        self._structural_apply = False
        self._steps_this_proc = 0
        self._wall_this_proc = 0.0
        self._sim_this_proc = 0.0
        # test hook: host-path row corruption for invariant negative
        # controls (fn(pid, rows_np) -> rows_np); None in production
        self.corrupt_hook = None
        # extra mgr Balancer options merged into every _balance round
        # (the fleet engine pins upmap_state_backend="device_loop" here
        # so the whole fleet's balancer cadence rides the PR 18
        # one-dispatch optimizer; part of engine behavior, so a solo
        # digest oracle must set the same options)
        self.balancer_options: dict = {}

        self.ck = Checkpoint(checkpoint, resume=resume) \
            if checkpoint else None
        # restore_state: an externally-held _state() dict (the fleet
        # engine checkpoints the whole stack in ONE file and hands each
        # member its slice); otherwise the engine's own checkpoint
        state = restore_state
        if state is None:
            state = (self.ck.data.get("lifetime")
                     if (self.ck is not None and resume) else None)
        if state:
            self._restore(state)
        else:
            self.m = build_cluster(scenario)
        # warm baseline: map every pool once so epoch 1 has prev rows
        # and the steady-compile gate starts from a compiled structure
        self._baseline()

    # -- checkpoint/resume -------------------------------------------------

    def _state(self) -> dict:
        from ceph_tpu.osd.codec import encode_osdmap

        return {
            "scenario": self.scenario.spec(),
            "backend": self.backend,
            "steps": self.steps,
            "digest": self.digest,
            "sim_seconds": self.sim_seconds,
            "report": vars(self.report),
            "violations": self.violations,
            "fallback_events": self.fallback_events,
            "event_counts": self.event_counts,
            "degraded_epochs": self.degraded_epochs,
            "structural_epochs": self.structural_epochs,
            "steady_epochs": self.steady_epochs,
            "steady_compiles": self.steady_compiles,
            "steady_pipe_misses": self.steady_pipe_misses,
            "steady_full_rebuilds": self.steady_full_rebuilds,
            "total_compiles": self.total_compiles,
            "flap_down": {str(k): v for k, v in self.flap_down.items()},
            "outages": self.outages,
            "temps": self.temps,
            "dead": self.dead,
            "host_seq": self.host_seq,
            "expanded": self.expanded,
            # hazard windows carry their CURRENT decayed strengths:
            # resume must continue the decay curve, not restart it
            # (json round-trips float64 exactly)
            "hazards": [list(h) for h in self.hazards],
            "wounded": {str(pid): [int(x) for x in w]
                        for pid, w in self.wounded.items()},
            "healing": {str(pid): [int(x) for x in h]
                        for pid, h in self.healing.items()},
            "lost": {str(pid): list(s) for pid, s in self.lost.items()},
            "pg_lost_total": self.pg_lost_total,
            "exposed_pg_epochs": self.exposed_pg_epochs,
            "chaos": {
                "flap_counts": {str(k): v
                                for k, v in self.flap_counts.items()},
                "false_flap_revives": self.false_flap_revives,
                "domain_outages": dict(self.domain_outages),
                "cascades": self.cascades,
                "longest_cascade": self.longest_cascade,
                "cascade_run": self._cascade_run,
                "hazard_windows": self.hazard_windows,
            },
            "map_b64": base64.b64encode(
                encode_osdmap(self.m)).decode(),
            "recovery": (None if self.recovery is None
                         else self.recovery.state()),
            "workload": (None if self.workload is None
                         else self.workload.state()),
            "health_epochs": dict(self._health_counts),
            "timeline": obs.timeline.state("sim"),
        }

    def _restore(self, state: dict) -> None:
        from ceph_tpu.osd.codec import decode_osdmap

        if state.get("scenario") != self.scenario.spec():
            raise ValueError(
                "checkpoint was written by a different scenario:\n"
                f"  checkpoint: {state.get('scenario')}\n"
                f"  requested:  {self.scenario.spec()}"
            )
        self.m = decode_osdmap(base64.b64decode(state["map_b64"]))
        self.steps = int(state["steps"])
        self.digest = state["digest"]
        self.sim_seconds = float(state["sim_seconds"])
        self.report = MovementReport(**state["report"])
        self.violations = list(state["violations"])
        self.fallback_events = list(state["fallback_events"])
        self.event_counts = dict(state["event_counts"])
        self.degraded_epochs = int(state["degraded_epochs"])
        self.structural_epochs = int(state["structural_epochs"])
        self.steady_epochs = int(state["steady_epochs"])
        self.steady_compiles = int(state["steady_compiles"])
        self.steady_pipe_misses = int(state["steady_pipe_misses"])
        self.steady_full_rebuilds = int(
            state.get("steady_full_rebuilds", 0))
        self.total_compiles = int(state["total_compiles"])
        self.flap_down = {int(k): int(v)
                          for k, v in state["flap_down"].items()}
        self.outages = [list(x) for x in state["outages"]]
        self.temps = [list(x) for x in state["temps"]]
        self.dead = list(state["dead"])
        self.host_seq = int(state["host_seq"])
        self.expanded = int(state["expanded"])
        self.hazards = [list(h) for h in state.get("hazards", [])]
        self.wounded = {int(k): np.asarray(v, np.int64)
                        for k, v in (state.get("wounded") or {}).items()}
        self.healing = {int(k): np.asarray(v, bool)
                        for k, v in (state.get("healing") or {}).items()}
        self.lost = {int(k): [int(s) for s in v]
                     for k, v in (state.get("lost") or {}).items()}
        self.pg_lost_total = int(state.get("pg_lost_total", 0))
        self.exposed_pg_epochs = int(state.get("exposed_pg_epochs", 0))
        cz = state.get("chaos") or {}
        self.flap_counts = {
            int(k): int(v)
            for k, v in (cz.get("flap_counts") or {}).items()}
        self.false_flap_revives = int(cz.get("false_flap_revives", 0))
        self.domain_outages = dict(cz.get("domain_outages") or {})
        self.cascades = int(cz.get("cascades", 0))
        self.longest_cascade = int(cz.get("longest_cascade", 0))
        self._cascade_run = int(cz.get("cascade_run", 0))
        self.hazard_windows = int(cz.get("hazard_windows", 0))
        if self.recovery is not None and state.get("recovery"):
            self.recovery.restore(state["recovery"])
        if self.workload is not None and state.get("workload"):
            self.workload.restore(state["workload"])
        self._health_counts = dict(
            state.get("health_epochs") or {"ok": 0, "warn": 0, "err": 0})
        if state.get("timeline"):
            # resumed runs continue the same monotonic sample indices
            obs.timeline.restore("sim", state["timeline"])
        self.resumed_from = self.steps
        _log(1, f"lifetime resumed at epoch {self.steps} "
                f"(map epoch {self.m.epoch})")

    def _checkpoint(self) -> None:
        if self.ck is None:
            return
        self.ck.progress("lifetime", self._state())
        _L.inc("checkpoints")
        obs.instant("sim.checkpoint", epoch=self.steps)

    # -- mapping + accounting ---------------------------------------------

    def _baseline(self) -> None:
        """Map every pool once (rows become epoch 0's `prev`), and
        establish the structure key set the steady-compile gate diffs
        against.  Compiles booked here are warmup, not epoch cost."""
        if self.backend == "jax":
            from ceph_tpu.osd.state import ClusterState

            try:
                self.state = ClusterState(self.m,
                                          chunk=self.scenario.chunk,
                                          mesh=self.mesh)
            except Exception as e:
                if not faults.looks_like_device_loss(e):
                    raise
                self._record_fallback(0, "state", e)
        skeys = set()
        for pid in sorted(self.m.pools):
            try:
                _, skey = self._account_pool(pid, baseline=True)
            except Exception as e:
                if not faults.looks_like_device_loss(e):
                    raise
                self._record_fallback(0, pid, e)
                _, skey = self._account_pool(pid, baseline=True,
                                             force_host=True)
            skeys.add(skey)
        self._prev_skeys = frozenset(skeys)
        self._warm_dataplane()

    def _dv(self) -> int:
        """Per-OSD vector bound for the recovery/workload kernels: the
        ClusterState quantum on the jax backend, the same power-of-two
        formula on "ref".  Lanes beyond max_osd are never addressed, so
        the bound itself does not shape the (digested) outputs."""
        if self.state is not None:
            return self.state.DV
        n = max(self.m.max_osd, 1)
        return 1 << max(int(n - 1).bit_length(), 5)

    def _fresh_cap(self, device: bool):
        """A fresh epoch's per-OSD (capacity, slots) vectors."""
        DV = self._dv()
        cap_bytes = (self.recovery.cap_epoch_bytes
                     if self.recovery is not None else 0)
        slots = (self.recovery.max_backfills
                 if self.recovery is not None else 0)
        if device:
            import jax.numpy as jnp

            return (jnp.full(DV, jnp.int64(cap_bytes)),
                    jnp.full(DV, jnp.int64(slots)))
        return (np.full(DV, cap_bytes, np.int64),
                np.full(DV, slots, np.int64))

    def _warm_dataplane(self) -> None:
        """Compile the recovery-drain and workload-traffic kernels for
        every current pool shape (baseline and post-resume), so steady
        epochs dispatch warm.  New shapes appearing mid-life (pool
        creation, splits, expansion) compile on their own epoch, which
        the skey diff already classifies structural."""
        if self.backend != "jax" or self.state is None:
            return
        if self.recovery is None and self.workload is None:
            return
        try:
            cap, slots = self._fresh_cap(device=True)
            for pid in sorted(self.m.pools):
                ent = self._prev_rows.get(pid)
                if ent is None or isinstance(ent[1], np.ndarray):
                    continue
                rows = ent[1]
                if self.recovery is not None:
                    self.recovery.ensure(pid, int(rows.shape[0]))
                    self.recovery.warm(pid, rows, cap, slots)
                if self.workload is not None:
                    self.workload.warm(
                        pid, rows, self.recovery.device_backlog(pid)
                        if self.recovery is not None else None,
                        self._dv())
        except Exception as e:
            if not faults.looks_like_device_loss(e):
                raise
            self._record_fallback(0, "dataplane-warm", e)

    def _pool_tolerance(self, pool: PgPool) -> int:
        """Chunks/replicas the pool can lose before data is at risk:
        EC -> m (from the profile), replicated -> size-1."""
        if pool.is_erasure():
            prof = self.m.erasure_code_profiles.get(
                pool.erasure_code_profile, {})
            try:
                return int(prof["m"])
            except (KeyError, ValueError):
                return max(0, pool.size - 1)
        return max(0, pool.size - 1)

    def _host_up(self, pid: int, seed: int) -> list[int]:
        """One PG's host-exact `up` set — the invariant oracle.  On the
        jax backend the ClusterState answers overlay-carrying seeds
        from its device-resident raw cache; everything else replays the
        host descent directly (bounded call sites)."""
        if self.state is not None:
            return self.state.host_up(pid, int(seed))
        m = self.m
        pool = m.pools[pid]
        pg = PgId(pid, int(seed))
        raw, pps = m._pg_to_raw_osds(pool, pg)
        m._apply_upmap(pool, pg, raw)
        up = m._raw_to_up_osds(pool, raw)
        up_primary = m._pick_primary(up)
        m._apply_primary_affinity(pps, pool, up, up_primary)
        return up

    # stats that are pure functions of the CURRENT rows — replayable
    # without device work when the rows' version tag is unchanged
    # (moved/remapped compare against prev rows: identical rows give 0)
    _ROW_STATS = ("degraded", "unmapped", "at_risk", "dup")

    def _account_pool(self, pid: int, baseline: bool = False,
                      force_host: bool = False):
        """Map one pool and reduce the epoch stats.  Device path unless
        the backend is "ref" or a device loss degraded this call.

        O(delta) steady path: when the pool's ClusterState version tag
        matches both the previous epoch's rows and the cached row-stats
        — nothing feeding this pool's mapping changed — the epoch books
        NO device work at all: rows are bit-identical by the tag
        contract, so moved/remapped are 0 and the row-pure stats replay
        from the cache, digest-exactly."""
        pool = self.m.pools[pid]
        tol = self._pool_tolerance(pool)
        if (self.backend == "jax" and not force_host
                and self.state is not None):
            import jax.numpy as jnp

            rows, skey, tag = self.state.rows(pid)
            n = pool.pg_num
            prev = self._prev_rows.get(pid)
            cached = self._stats_cache.get(pid)
            if (not baseline and prev is not None and prev[0] == tag
                    and cached is not None and cached[0] == tag
                    and cached[1]["tol"] == tol):
                st = dict(cached[1]["stats"], moved=0, remapped=0)
                self._moved[pid] = None  # tag-equal rows: nothing moved
            else:
                if (prev is None
                        or tuple(prev[1].shape) != tuple(rows.shape)):
                    prev_dev = rows  # fresh/resized pool: self-compare
                else:
                    prev_dev = prev[1] if not isinstance(
                        prev[1], np.ndarray) else jnp.asarray(prev[1])
                out, moved_rows = _stats_account()(
                    prev_dev, rows, jnp.uint32(n), jnp.int32(pool.size),
                    jnp.int32(tol),
                )
                out = np.asarray(out)
                st = {k: int(v) for k, v in zip(STAT_KEYS, out)}
                self._moved[pid] = moved_rows  # stays device-resident
                self._stats_cache[pid] = (tag, {
                    "tol": tol,
                    "stats": {k: st[k] for k in self._ROW_STATS},
                })
            self._prev_rows[pid] = (tag, rows)  # stays device-resident
            if baseline:  # ran for the warmup, not the books
                return None, skey
        else:
            up, _, _, _ = _map_ref(self.m, pid)
            rows = up.astype(np.int32)
            if self.corrupt_hook is not None:
                rows = self.corrupt_hook(pid, rows)
            n = pool.pg_num
            skey = ("ref", n, int(rows.shape[1]))
            prev = self._prev_rows.get(pid)
            prev_np = rows if (
                prev is None
                or tuple(np.shape(prev[1])) != tuple(rows.shape)
            ) else np.asarray(prev[1])
            self._prev_rows[pid] = (None, rows)
            self._stats_cache.pop(pid, None)
            if baseline:
                self._moved[pid] = None
                return None, skey
            stats_list, moved_rows = _stats_np(
                prev_np, rows, n, pool.size, tol)
            self._moved[pid] = moved_rows
            st = dict(zip(STAT_KEYS, stats_list))
        st["n"] = n
        st["size"] = pool.size
        st["tol"] = tol
        return st, skey

    # The fleet engine (ceph_tpu.fleet) reduces MANY engines' pools in
    # one stacked vmapped dispatch.  _plan_pool/_commit_pool are the
    # read and write halves of _account_pool's device path, split so
    # the dispatch between them can be batched across engines; they
    # must stay exact mirrors of _account_pool — per-member digest
    # equality between a solo run and a fleet run depends on it.

    def _plan_pool(self, pid: int):
        """Read half (device path only): version-tagged rows, the
        tag-equal short-circuit decision, and the prev operand, WITHOUT
        dispatching.  Returns (lane, skey); `lane["cached"]` non-None
        means the stats replay from cache (the lane still rides the
        stacked dispatch as a self-compare so the batch structure stays
        fixed across steady epochs — its outputs are discarded)."""
        import jax.numpy as jnp

        pool = self.m.pools[pid]
        tol = self._pool_tolerance(pool)
        rows, skey, tag = self.state.rows(pid)
        prev = self._prev_rows.get(pid)
        cached = self._stats_cache.get(pid)
        lane = {"pid": pid, "rows": rows, "n": pool.pg_num,
                "size": pool.size, "tol": tol, "tag": tag,
                "cached": None}
        if (prev is not None and prev[0] == tag
                and cached is not None and cached[0] == tag
                and cached[1]["tol"] == tol):
            lane["cached"] = dict(cached[1]["stats"],
                                  moved=0, remapped=0)
            lane["prev"] = rows  # self-compare: outputs discarded
        elif (prev is None
                or tuple(prev[1].shape) != tuple(rows.shape)):
            lane["prev"] = rows  # fresh/resized pool: self-compare
        else:
            lane["prev"] = prev[1] if not isinstance(
                prev[1], np.ndarray) else jnp.asarray(prev[1])
        return lane, skey

    def _commit_pool(self, lane: dict, out, moved_rows) -> dict:
        """Write half: book one lane's stacked-dispatch outputs (`out`
        the fetched 6-stat row, `moved_rows` the device-resident per-PG
        moved lanes) into the same caches the solo path maintains."""
        pid, tag = lane["pid"], lane["tag"]
        if lane["cached"] is not None:
            st = lane["cached"]
            self._moved[pid] = None  # tag-equal rows: nothing moved
        else:
            st = {k: int(v) for k, v in zip(STAT_KEYS, out)}
            self._moved[pid] = moved_rows  # stays device-resident
            self._stats_cache[pid] = (tag, {
                "tol": lane["tol"],
                "stats": {k: st[k] for k in self._ROW_STATS},
            })
        self._prev_rows[pid] = (tag, lane["rows"])
        st["n"] = lane["n"]
        st["size"] = lane["size"]
        st["tol"] = lane["tol"]
        return st

    def _record_fallback(self, e: int, pid, exc) -> None:
        _device_loss_counter().inc("device_loss_fallbacks")
        msg = f"epoch {e} pool {pid}: {exc} -> host mapper"
        self.fallback_events.append(msg)
        _log(1, "device lost mid-lifetime; degrading accounting to "
                f"the bit-exact host mapper ({msg})")

    def _account_epoch(self, e: int):
        stats: dict[int, dict] = {}
        skeys = set()
        for pid in sorted(self.m.pools):
            try:
                faults.check("epoch_apply", qual=str(e))
                st, skey = self._account_pool(pid)
            except Exception as exc:
                # real transport losses raise jaxlib shapes, injected
                # ones DeviceLostError — both degrade, others are bugs
                if not faults.looks_like_device_loss(exc):
                    raise
                self._record_fallback(e, pid, exc)
                st, skey = self._account_pool(pid, force_host=True)
            stats[pid] = st
            skeys.add(skey)
        self._prune_removed_pools()
        return stats, frozenset(skeys)

    def _prune_removed_pools(self) -> None:
        """Removed pools leave no stale prev rows (or queue/durability
        state) behind."""
        for pid in list(self._prev_rows):
            if pid not in self.m.pools:
                del self._prev_rows[pid]
                self._stats_cache.pop(pid, None)
                self._moved.pop(pid, None)
                self.wounded.pop(pid, None)
                self.healing.pop(pid, None)
                self.lost.pop(pid, None)  # pg_lost_total stays booked
                if self.recovery is not None:
                    self.recovery.drop(pid)

    # -- invariants --------------------------------------------------------

    def _row_slice(self, pid: int, seeds: np.ndarray) -> np.ndarray:
        rows = self._prev_rows[pid][1]
        if isinstance(rows, np.ndarray):
            return rows[seeds]
        import jax.numpy as jnp

        return np.asarray(rows[jnp.asarray(seeds)])

    def _invariants(self, e: int, rng, stats: dict) -> None:
        up_osds = sum(
            1 for o in range(self.m.max_osd) if self.m.is_up(o))
        for pid, st in stats.items():
            pool = self.m.pools[pid]
            flagged = st["dup"] > 0 or (
                st["unmapped"] > 0 and up_osds >= pool.size)
            if flagged:
                rows = self._prev_rows[pid][1]
                msgs = check_rows_invariants(
                    self.m, pid, np.asarray(rows), st["n"],
                    oracle=lambda s, pid=pid: self._host_up(pid, s))
                if st["dup"] and not any("duplicate" in v
                                         for v in msgs):
                    msgs.append(
                        f"pool {pid}: device scalars flagged "
                        f"dup={st['dup']} but the host detail pass "
                        "found none (device/host divergence)")
                self._violate(e, msgs)  # may be empty: an empty up
                # row whose raw replay maps nothing is degradation
            else:
                # overlay respect stays cheap: only overlay-carrying
                # seeds are fetched (bounded sample), and a pool whose
                # rows version tag is unchanged since its last CLEAN
                # check is skipped outright — equal tags guarantee
                # bit-identical rows, so re-checking cannot differ
                tag = self._prev_rows[pid][0]
                if tag is None or self._overlay_checked.get(pid) != tag:
                    self._check_overlays(e, pid, st["n"], rng)
                    if tag is not None:
                        self._overlay_checked[pid] = tag
        tkey = None
        if self.state is not None:
            # pg_temp semantics only need re-checking when an input
            # changed: the temp/primary entries themselves or anything
            # feeding the mapping (the state's aggregate version tag)
            tkey = (
                self.state.state_tag(),
                tuple(sorted(((pg.pool, pg.seed), tuple(v))
                             for pg, v in self.m.pg_temp.items())),
                tuple(sorted(((pg.pool, pg.seed), v)
                             for pg, v in self.m.primary_temp.items())),
            )
        if tkey is None or tkey != self._pg_temp_checked:
            temp_msgs = check_pg_temp_invariants(self.m)
            if temp_msgs:
                self._violate(e, temp_msgs)
            elif tkey is not None:
                self._pg_temp_checked = tkey
        every = self.scenario.spotcheck_every
        if every and e % every == 0:
            self._spot_check(e, rng)

    def _check_overlays(self, e: int, pid: int, n: int, rng) -> None:
        seeds = sorted({
            pg.seed for src in (self.m.pg_upmap, self.m.pg_upmap_items)
            for pg in src if pg.pool == pid and pg.seed < n
        })
        if not seeds:
            return
        if len(seeds) > 32:
            pick = rng.choice(len(seeds), 32, replace=False)
            seeds = sorted(seeds[i] for i in pick)
        idx = np.asarray(seeds, np.int64)
        sub = self._row_slice(pid, idx)
        full = np.full((n, sub.shape[1]), ITEM_NONE, sub.dtype)
        full[idx] = sub
        msgs = check_rows_invariants(
            self.m, pid, full, n, only_seeds=set(seeds),
            oracle=lambda s, pid=pid: self._host_up(pid, s))
        if msgs:
            self._violate(e, msgs)

    def _spot_check(self, e: int, rng) -> None:
        K = self.scenario.spotcheck_lanes
        for pid in sorted(self.m.pools):
            n = self.m.pools[pid].pg_num
            seeds = np.unique(rng.integers(0, n, size=K))
            got = self._row_slice(pid, seeds)
            for seed, row in zip(seeds, got):
                _L.inc("spot_checks")
                up, _, _, _ = self.m.pg_to_up_acting_osds(
                    PgId(pid, int(seed)))
                want = sorted(o for o in up if o != ITEM_NONE)
                have = sorted(int(o) for o in row
                              if o != ITEM_NONE and o >= 0)
                if want != have:
                    _L.inc("spotcheck_mismatches")
                    self._violate(e, [
                        f"spot-check pool {pid} pg {pid}.{int(seed):x}: "
                        f"device {have} != host {want}"
                    ])

    def _violate(self, e: int, msgs: list[str]) -> None:
        for msg in msgs:
            _L.inc("invariant_violations")
            self.violations.append(f"epoch {e}: {msg}")
            _log(0, f"INVARIANT epoch {e}: {msg}")

    # -- events ------------------------------------------------------------

    def _devices_under(self, bid: int) -> list[int]:
        out: list[int] = []
        b = self.m.crush.buckets.get(bid)
        if b is None:
            return out
        for it in b.items:
            if it >= 0:
                out.append(it)
            else:
                out.extend(self._devices_under(it))
        return out

    def _buckets_of_type(self, type_: int) -> list[int]:
        shadows = {
            sid for per in self.m.crush.class_bucket.values()
            for sid in per.values()
        }
        return sorted(
            (bid for bid, b in self.m.crush.buckets.items()
             if b.type == type_ and bid not in shadows),
            reverse=True,
        )

    def _sibling_domains(self, bid: int, type_: int) -> list[int]:
        """The failure domains a bucket's outage raises hazard on: the
        other same-type buckets under the same (non-shadow) parent —
        hosts sharing a rack, racks sharing the root.  Falls back to
        every other same-type bucket when no parent carries siblings
        (flat hierarchies)."""
        pool = self._buckets_of_type(type_)
        shadows = {
            sid for per in self.m.crush.class_bucket.values()
            for sid in per.values()
        }
        parent = next(
            (pb for pb, b in self.m.crush.buckets.items()
             if bid in b.items and pb not in shadows), None)
        sibs: list[int] = []
        if parent is not None:
            inside = set(self.m.crush.buckets[parent].items)
            sibs = [b for b in pool if b in inside and b != bid]
        if not sibs:
            sibs = [b for b in pool if b != bid]
        return sibs

    def _floor(self) -> int:
        return max((p.size for p in self.m.pools.values()), default=3)

    def _ups(self, exclude: set) -> list[int]:
        return [o for o in range(self.m.max_osd)
                if self.m.is_up(o) and o not in exclude]

    def _hazard_boost(self) -> dict[int, float]:
        """Summed live hazard strength per bucket type (1=host,
        3=rack) — the correlation mass added to the outage draws."""
        add: dict[int, float] = {}
        for t, _bid, _exp, s in self.hazards:
            add[t] = add.get(t, 0.0) + float(s)
        return add

    def _decay_hazards(self, e: int) -> None:
        """Advance every open hazard window by one epoch: strength
        decays geometrically, expired/vanished windows close.  Runs
        exactly once per epoch (before the kind draw), and the decayed
        strengths are checkpointed — a resume continues the curve."""
        faults.check("hazard_decay", qual=str(e))
        kept: list[list] = []
        for rec in self.hazards:
            rec[3] = float(rec[3]) * self.scenario.cascade_decay
            if rec[2] > e and rec[3] >= 1e-9:
                kept.append(rec)
        self.hazards = kept

    def _draw_kind(self, rng) -> str:
        u = float(rng.random())
        boost = self._hazard_boost() if (
            self.scenario.correlated and self.hazards) else {}
        acc = 0.0
        for kind, p in self.scenario.event_probs():
            if kind == "host_outage":
                p += boost.get(1, 0.0)
            elif kind == "rack_outage":
                p += boost.get(3, 0.0)
            acc += p
            if u < acc:
                return kind
        return "quiet"

    def _apply_event(self, e: int, rng, force: str | None) -> str:
        m = self.m
        sc = self.scenario
        inc = Incremental(epoch=m.epoch + 1)
        notes: list[str] = []
        touched: set[int] = set()

        if sc.correlated:
            self._decay_hazards(e)

        # transient expiries ride the same epoch delta
        for osd in sorted(o for o, t in self.flap_down.items()
                          if t <= e):
            del self.flap_down[osd]
            if m.exists(osd) and m.is_down(osd):
                inc.new_state[osd] = OSD_UP
                touched.add(osd)
                # a flap revive is the false-positive-down story: the
                # OSD comes back with every byte intact (no recovery
                # enqueue ever happened for it)
                self.false_flap_revives += 1
                _L.inc("flap_revives")
                notes.append(f"revive osd.{osd}")
        for rec in [r for r in self.outages if r[0] <= e]:
            self.outages.remove(rec)
            back = []
            for osd in rec[1]:
                if (m.exists(osd) and m.is_down(osd)
                        and osd not in touched
                        and osd not in self.flap_down
                        and osd not in self.dead):
                    inc.new_state[osd] = OSD_UP
                    touched.add(osd)
                    back.append(osd)
            notes.append(f"outage-end osds={back}")
        for rec in [r for r in self.temps if r[2] <= e]:
            self.temps.remove(rec)
            pg = PgId(int(rec[0]), int(rec[1]))
            inc.new_pg_temp[pg] = []
            inc.new_primary_temp[pg] = -1
            notes.append(f"pg_temp-clear {pg}")

        balance = (sc.balance_every
                   and e % sc.balance_every == 0 and force is None)
        kind = "balance" if balance else (force or self._draw_kind(rng))
        if kind != "balance":
            kind, detail = self._apply_kind(kind, e, rng, inc, touched)
            self._apply_inc(inc)
        else:
            if (inc.new_state or inc.new_pg_temp
                    or inc.new_primary_temp):
                self._apply_inc(inc)  # expiries first, own epoch
            detail = self._balance(e)
        if kind != "quiet":
            _L.inc("events_applied")
        self.event_counts[kind] = self.event_counts.get(kind, 0) + 1
        if notes:
            detail = detail + " +" + "+".join(notes)
        return detail

    def _apply_inc(self, inc: Incremental) -> None:
        """Advance the map by one epoch delta — through the
        device-resident ClusterState (value deltas scatter on device in
        O(delta), structural ones re-key) on the jax backend, plain
        host application on "ref".  A genuinely structural delta marks
        the epoch structural even when the compiled shapes happen to
        coincide (e.g. a crush item removal that keeps every table
        shape); a FORCED rebuild (CEPH_TPU_STATE_DELTA=0) does not —
        that is exactly the contract break steady_full_rebuilds
        exposes."""
        if self.state is not None:
            if self.state.apply(inc) == "rebuild":
                self._structural_apply = True
        else:
            apply_incremental(self.m, inc)

    def _apply_kind(self, kind: str, e: int, rng, inc: Incremental,
                    touched: set) -> tuple[str, str]:
        m, sc = self.m, self.scenario
        ups = self._ups(touched)
        floor = self._floor()

        def quiet(why: str) -> tuple[str, str]:
            return "quiet", f"quiet({why})"

        if kind == "quiet":
            return "quiet", "quiet"

        if kind == "flap":
            if len(ups) - 1 < floor or not ups:
                return quiet("flap:floor")
            if sc.correlated and self._flapper_set:
                # repeat offenders: the once-per-lifetime flakiness
                # multipliers weight the victim draw, so the same OSDs
                # flap again and again (cumulative-sum draw, exact
                # float64 — identical on every backend and on resume)
                w = np.asarray(
                    [sc.flapper_boost if o in self._flapper_set
                     else 1.0 for o in ups], np.float64)
                cum = np.cumsum(w)
                u = float(rng.random()) * float(cum[-1])
                idx = min(int(np.searchsorted(cum, u, side="right")),
                          len(ups) - 1)
                osd = int(ups[idx])
            else:
                osd = int(ups[int(rng.integers(len(ups)))])
            self.flap_counts[osd] = self.flap_counts.get(osd, 0) + 1
            inc.new_state[osd] = OSD_UP
            self.flap_down[osd] = e + 1 + int(
                rng.integers(1, sc.flap_len + 1))
            return kind, f"flap osd.{osd}"

        if kind == "death":
            if len(ups) - 1 < floor or not ups:
                return quiet("death:floor")
            osd = int(ups[int(rng.integers(len(ups)))])
            inc.new_state[osd] = OSD_UP
            inc.new_weight[osd] = 0
            self.dead.append(osd)
            if sc.correlated:
                self._wound_osd(osd)
            return kind, f"death osd.{osd}"

        if kind == "remove":
            if not self.dead:
                return quiet("remove:none-dead")
            cand = sorted(self.dead)
            osd = int(cand[int(rng.integers(len(cand)))])
            self.dead.remove(osd)
            c2 = copy.deepcopy(m.crush)
            c2.remove_item(osd)
            from ceph_tpu.crush.codec import encode_crushmap

            inc.crush = encode_crushmap(c2)
            inc.new_state[osd] = OSD_EXISTS  # destroy
            return kind, f"remove osd.{osd}"

        if kind in ("host_outage", "rack_outage"):
            type_ = 1 if kind == "host_outage" else 3
            buckets = self._buckets_of_type(type_)
            if not buckets:
                return quiet(f"{kind}:no-bucket")
            if sc.correlated:
                # cascade bias: while hazard windows of this type are
                # open, the outage strikes a hazarded sibling domain —
                # that is what turns one rack outage into a sequence
                hot = {int(h[1]) for h in self.hazards
                       if h[0] == type_}
                hazarded = [b for b in buckets if b in hot]
                if hazarded:
                    buckets = hazarded
            bid = int(buckets[int(rng.integers(len(buckets)))])
            victims = [o for o in self._devices_under(bid)
                       if m.is_up(o) and o not in touched]
            if not victims or len(ups) - len(victims) < floor:
                return quiet(f"{kind}:floor")
            for osd in victims:
                inc.new_state[osd] = OSD_UP
            self.outages.append([
                e + 1 + int(rng.integers(1, sc.outage_len + 1)),
                victims,
            ])
            name = m.crush.item_names.get(bid, str(bid))
            self.domain_outages[name] = \
                self.domain_outages.get(name, 0) + 1
            if sc.correlated:
                if self.hazards:
                    # fired inside an open window: one more link of the
                    # current cascade chain
                    self.cascades += 1
                    self._cascade_run += 1
                    _L.inc("cascade_outages")
                else:
                    self._cascade_run = 1
                self.longest_cascade = max(self.longest_cascade,
                                           self._cascade_run)
                for sib in self._sibling_domains(bid, type_):
                    self.hazards.append([
                        type_, int(sib), e + 1 + sc.cascade_len,
                        float(sc.cascade_hazard),
                    ])
                    self.hazard_windows += 1
            return kind, f"{kind} {name} osds={victims}"

        if kind == "reweight":
            cand = [o for o in ups if m.is_in(o)]
            if not cand:
                return quiet("reweight:none")
            osd = int(cand[int(rng.integers(len(cand)))])
            w = int(round((0.6 + 0.4 * float(rng.random())) * IN_WEIGHT))
            inc.new_weight[osd] = w
            return kind, f"reweight osd.{osd} {w}"

        if kind == "pg_temp":
            pids = sorted(m.pools)
            pid = int(pids[int(rng.integers(len(pids)))])
            pool = m.pools[pid]
            seed = int(rng.integers(pool.pg_num))
            pg = PgId(pid, seed)
            if any(r[0] == pid and r[1] == seed for r in self.temps):
                return quiet("pg_temp:exists")
            up, _, _, _ = m.pg_to_up_acting_osds(pg)
            members = [o for o in up if o != ITEM_NONE]
            if len(members) < 2:
                return quiet("pg_temp:thin")
            temp = members[1:] + members[:1]  # rotated acting override
            inc.new_pg_temp[pg] = temp
            inc.new_primary_temp[pg] = temp[0]
            self.temps.append([
                pid, seed,
                e + 1 + int(rng.integers(1, sc.temp_len + 1)),
            ])
            return kind, f"pg_temp {pg} {temp}"

        if kind == "pool_create":
            if len(m.pools) >= sc.max_pools:
                return quiet("pool_create:cap")
            pid = m.pool_max + 1
            inc.new_pool_max = pid
            inc.new_pools[pid] = PgPool(
                type=PoolType.REPLICATED, size=sc.size, crush_rule=0,
                pg_num=sc.new_pool_pgs, pgp_num=sc.new_pool_pgs,
            )
            inc.new_pool_names[pid] = f"pool{pid}"
            return kind, f"pool_create pool{pid} pgs={sc.new_pool_pgs}"

        if kind == "split":
            cand = sorted(
                pid for pid, p in m.pools.items()
                if p.pg_num * 2 <= sc.max_pgs
            )
            if not cand:
                return quiet("split:cap")
            pid = int(cand[int(rng.integers(len(cand)))])
            pool = inc.get_new_pool(pid, m.pools[pid])
            pool.pg_num *= 2
            pool.pgp_num = pool.pg_num
            return kind, f"split pool{pid} pg_num={pool.pg_num}"

        if kind == "expand":
            if self.expanded >= sc.max_expand:
                return quiet("expand:cap")
            H = self.host_seq
            first = m.max_osd
            new = list(range(first, first + sc.osds_per_host))
            c2 = copy.deepcopy(m.crush)
            loc = {"host": f"host{H}", "root": "default"}
            if sc.racks:
                loc["rack"] = f"rack{int(rng.integers(sc.racks))}"
            for o in new:
                c2.insert_item(o, 1.0, f"osd.{o}", loc)
            from ceph_tpu.crush.codec import encode_crushmap

            inc.crush = encode_crushmap(c2)
            inc.new_max_osd = first + sc.osds_per_host
            for o in new:
                inc.new_up_client[o] = b""
                inc.new_weight[o] = IN_WEIGHT
            self.host_seq += 1
            self.expanded += 1
            return kind, (f"expand host{H} osds={new} "
                          f"rack={loc.get('rack', '-')}")

        raise ValueError(f"unknown event kind {kind!r}")

    def _balance(self, e: int) -> str:
        from ceph_tpu.mgr import Balancer, MappingState, \
            synthetic_pg_stats

        mapper = "jax" if self.backend == "jax" else "ref"
        try:
            bal = Balancer(
                options={"upmap_max_optimizations":
                         self.scenario.balance_max,
                         **self.balancer_options},
                rng=np.random.default_rng(
                    [self.scenario.seed, e, 1]),
            )
            ms = MappingState(self.m, synthetic_pg_stats(self.m),
                              desc=f"epoch{e}", mapper=mapper,
                              state=self.state)
            plan = bal.plan_create(f"epoch{e}", ms, mode="upmap")
            rc, _ = bal.optimize(plan)
            if rc == 0:
                rc2, msg = bal.execute(plan, self.m, state=self.state)
                if rc2 != 0:
                    raise RuntimeError(f"balancer execute: {msg}")
                changed = (len(plan.inc.new_pg_upmap_items)
                           + len(plan.inc.old_pg_upmap_items))
                obs.timeline.sample("balancer",
                                    {"epoch": e, "changed": changed})
                return f"balance changed={changed}"
        except Exception as exc:
            # same contract as _account_epoch: REAL transport losses
            # raise jaxlib shapes, injected ones DeviceLostError — both
            # degrade (skip this round, sim continues); anything else
            # is a bug and aborts
            if not faults.looks_like_device_loss(exc):
                raise
            self._record_fallback(e, "balancer", exc)
        self._apply_inc(Incremental(epoch=self.m.epoch + 1))
        return "balance changed=0"

    # -- recovery + workload data plane ------------------------------------

    def _workload_epoch(self, e: int) -> dict:
        """One epoch of modeled client traffic through the current
        placement rows (sim/workload.py): per-pool request samples,
        client-visible tallies, and the per-OSD capacity remainder the
        recovery drain then competes for."""
        import time as _time

        wl = self.workload
        t0 = _time.perf_counter()
        use_device = self.backend == "jax" and self.state is not None
        pids = sorted(self.m.pools)
        reqs = wl.pool_requests(e, pids)
        per_pool: dict[int, dict] = {}
        client_total = None
        with obs.span("sim.workload", epoch=e):
            for pid in pids:
                pool = self.m.pools[pid]
                tol = self._pool_tolerance(pool)
                rows = self._prev_rows[pid][1]
                wq = reqs[pid] // wl.sample
                backlog = None
                if self.recovery is not None:
                    self.recovery.ensure(pid, int(rows.shape[0]))
                kw = dict(n=pool.pg_num, size=pool.size, tol=tol,
                          DV=self._dv(), wq=wq)
                if use_device and not isinstance(rows, np.ndarray):
                    try:
                        if self.recovery is not None:
                            backlog = self.recovery.device_backlog(pid)
                        client, scal = wl.step_pool_device(
                            e, pid, rows, backlog, **kw)
                    except Exception as exc:
                        if not faults.looks_like_device_loss(exc):
                            raise
                        self._record_fallback(e, "workload", exc)
                        use_device = False
                        if client_total is not None:
                            client_total = np.asarray(client_total)
                if not (use_device
                        and not isinstance(rows, np.ndarray)):
                    if self.recovery is not None:
                        backlog = self.recovery.backlog.get(pid)
                    client, scal = wl.step_pool_host(
                        e, pid, np.asarray(rows), backlog, **kw)
                wl.book(scal)
                per_pool[pid] = scal
                client_total = client if client_total is None \
                    else client_total + client
            from ceph_tpu.sim.workload import (
                contention_jnp,
                contention_np,
            )

            cap_bytes = self._epoch_cap_bytes()
            if isinstance(client_total, np.ndarray):
                rem, throttled, contended = contention_np(
                    client_total, cap_bytes)
            else:
                rem, throttled, contended = contention_jnp(
                    client_total, cap_bytes)
            wl.book_contention(throttled, contended)
            self._cap_rem = rem
        wl.observe_epoch(wl.qps(e), _time.perf_counter() - t0)
        return {"per_pool": per_pool, "throttled": throttled,
                "contended": contended}

    def _epoch_cap_bytes(self) -> int:
        """ONE capacity number: clients are charged against exactly the
        bytes the recovery drain then competes for."""
        if self.recovery is not None:
            return self.recovery.cap_epoch_bytes
        sc = self.scenario
        t_us = int(round(sc.interval_s * 1e6))
        return (int(sc.osd_mbps * 1e6) * t_us) // 1_000_000

    def _recovery_epoch(self, e: int, stats: dict) -> dict:
        """One epoch of the recovery queue (ceph_tpu.recovery): enqueue
        from the per-PG moved lanes, slot-limited priority drain against
        the per-OSD capacity clients left over, byte conservation
        checked per pool.  A device loss (real, or the `recovery_step`
        fault) degrades the rest of the epoch to the bit-identical host
        mirror — digest unchanged."""
        import time as _time

        rq = self.recovery
        t0 = _time.perf_counter()
        use_device = self.backend == "jax" and self.state is not None
        with obs.span("sim.recovery", epoch=e):
            try:
                faults.check("recovery_step", qual=str(e))
            except Exception as exc:
                if not faults.looks_like_device_loss(exc):
                    raise
                self._record_fallback(e, "recovery", exc)
                rq.fallback_epochs += 1
                _recovery_counters().inc("fallbacks")
                use_device = False
            cap = self._cap_rem
            _, slots = self._fresh_cap(use_device)
            if cap is None:
                cap, _ = self._fresh_cap(use_device)
            elif use_device and isinstance(cap, np.ndarray):
                use_device = False
            elif not use_device and not isinstance(cap, np.ndarray):
                cap = np.asarray(cap)
            per_pool: dict[int, dict] = {}
            for pid in sorted(self.m.pools):
                pool = self.m.pools[pid]
                tol = self._pool_tolerance(pool)
                rows = self._prev_rows[pid][1]
                N = int(rows.shape[0])
                rq.ensure(pid, N)
                dev_pool = use_device and not isinstance(
                    rows, np.ndarray)
                warmed = (not dev_pool) or (
                    (N, int(rows.shape[1]), self._dv()) in rq._warmed)
                kw = dict(n=pool.pg_num, size=pool.size, tol=tol,
                          is_erasure=pool.is_erasure())
                if (warmed and stats[pid]["moved"] == 0
                        and rq.prev_total.get(pid, 0) == 0):
                    # nothing queued, nothing enqueued: the drain is
                    # identically zero — at-risk PGs (nothing queued to
                    # fix them) accrue the whole epoch
                    scal = dict.fromkeys(
                        ("enqueued", "drained", "backlog", "completed",
                         "queued", "streams"), 0)
                    scal["risk_us"] = stats[pid]["at_risk"] * rq.t_us
                else:
                    moved = self._moved.get(pid)
                    if dev_pool:
                        try:
                            cap, slots, scal = rq.drain_device(
                                pid, moved, rows, cap, slots, **kw)
                        except Exception as exc:
                            if not faults.looks_like_device_loss(exc):
                                raise
                            self._record_fallback(e, "recovery", exc)
                            rq.fallback_epochs += 1
                            _recovery_counters().inc("fallbacks")
                            use_device = dev_pool = False
                            cap = np.asarray(cap)
                            slots = np.asarray(slots)
                    if not dev_pool:
                        cap, slots, scal = rq.drain_host(
                            pid, None if moved is None
                            else np.asarray(moved),
                            np.asarray(rows), cap, slots, **kw)
                if self.recovery_corrupt_hook is not None:
                    scal = self.recovery_corrupt_hook(pid, scal) or scal
                if not rq.book(pid, scal):
                    self._violate(e, [
                        f"pool {pid}: recovery byte conservation "
                        f"broken: prev+enqueued != drained+backlog "
                        f"({scal})"
                    ])
                per_pool[pid] = scal
            total = rq.end_epoch()
        _recovery_counters().observe(
            "drain_seconds", _time.perf_counter() - t0)
        self._cap_rem = None
        return {"per_pool": per_pool, "backlog_total": total}

    # -- durability accounting (correlated model) --------------------------

    def _wounds(self, pid: int, n: int) -> np.ndarray:
        """The pool's per-PG simultaneously-dead-chunk counts, grown
        with zeros on splits (parent seeds keep their wounds, children
        start whole — mirroring RecoveryQueue.ensure)."""
        w = self.wounded.get(pid)
        if w is None or w.shape[0] < n:
            grown = np.zeros(n, np.int64)
            if w is not None:
                grown[:w.shape[0]] = w
            self.wounded[pid] = w = grown
        return w

    def _heal_flags(self, pid: int, n: int) -> np.ndarray:
        """Per-PG 'repair observed' flags: a wound may only heal after
        its PG's repair was seen running — lanes moved or backlog held
        — so a hole PG (CRUSH found no spare target, nothing enqueued,
        queue trivially quiet) stays wounded until the cluster actually
        remaps it."""
        h = self.healing.get(pid)
        if h is None or h.shape[0] < n:
            grown = np.zeros(n, bool)
            if h is not None:
                grown[:h.shape[0]] = h
            self.healing[pid] = h = grown
        return h

    def _wound_osd(self, osd: int) -> None:
        """Chunk-loss bookkeeping for a true death: every PG whose
        current up set carries the OSD has one more simultaneously-dead
        chunk.  Flaps and outages never come here — their bytes revive
        intact.  Pure host work on the already-resident rows (the
        np.asarray on a device array is a transfer, never a compile),
        and death epochs are never steady anyway."""
        for pid in sorted(self.m.pools):
            ent = self._prev_rows.get(pid)
            if ent is None:
                continue
            rows = np.asarray(ent[1])
            n = min(self.m.pools[pid].pg_num, rows.shape[0])
            hit = (rows[:n] == osd).any(axis=1)
            if hit.any():
                self._wounds(pid, n)[:n][hit] += 1

    def _durability_epoch(self, e: int) -> dict:
        """Post-recovery durability pass (exact host ints on every
        backend — the |D digest segment hangs off these).  A wound
        heals once its PG's repair was OBSERVED — lanes moved (the
        remap that re-replicates the dead chunk) or backlog held — and
        the backlog has drained to zero: redundancy restored.  A
        concurrent outage hiding intact replicas of the same PG never
        blocks the heal (those bytes revive); a hole PG whose repair
        never started stays wounded however long its queue is quiet.
        A PG whose wounds exceed the pool's tolerance before its
        repair drains is irreversibly LOST.  The np.asarray on a
        wounded pool's device arrays is a transfer, never a compile —
        and only wounded pools pay it."""
        rq = self.recovery
        per_pool: dict[int, dict] = {}
        exposed_total = 0
        for pid in sorted(self.m.pools):
            pool = self.m.pools[pid]
            n = pool.pg_num
            w = self._wounds(pid, n)
            wnz = w[:n] > 0
            if wnz.any() and rq is not None:
                heal = self._heal_flags(pid, n)
                undrained = rq.pg_undrained(pid, n)
                repairing = undrained.copy()
                moved = self._moved.get(pid)
                if moved is not None:
                    mv = np.asarray(moved)
                    k = min(n, mv.shape[0])
                    repairing[:k] |= mv[:k] > 0
                heal[:n][wnz & repairing] = True
                done = wnz & heal[:n] & ~undrained
                w[:n][done] = 0
                heal[:n][done] = False
                wnz = w[:n] > 0
            tol = self._pool_tolerance(pool)
            lost = self.lost.setdefault(pid, [])
            lmask = np.zeros(n, bool)
            if lost:
                lmask[np.asarray([s for s in lost if s < n],
                                 np.int64)] = True
            newly = (w[:n] > tol) & ~lmask
            if newly.any():
                lost.extend(int(s) for s in np.nonzero(newly)[0])
                lost.sort()
                k = int(newly.sum())
                self.pg_lost_total += k
                _L.inc("pgs_lost", k)
                _log(0, f"epoch {e}: pool {pid} lost {k} PG(s) — dead "
                        f"chunks exceeded tolerance {tol} before the "
                        "backlog drained")
            if rq is None:
                # flat model: recovery completes within the stretched
                # epoch by construction, so surviving wounds heal now
                w[:n] = 0
                wnz = w[:n] > 0
            exposed = int(wnz.sum())
            exposed_total += exposed
            per_pool[pid] = {
                "wounds": int(w[:n].sum()),
                "exposed": exposed,
                "lost": len(lost),
            }
        self.exposed_pg_epochs += exposed_total
        return {"per_pool": per_pool, "exposed": exposed_total}

    # -- the step ----------------------------------------------------------

    def _overlay_presence(self) -> tuple:
        m = self.m
        return tuple(sorted(
            (pid,
             any(pg.pool == pid for pg in m.pg_upmap),
             any(pg.pool == pid for pg in m.pg_upmap_items),
             any(pg.pool == pid for pg in m.pg_temp))
            for pid in m.pools
        ))

    def step(self, force_event: str | None = None) -> dict:
        ctx = self._step_begin(force_event)
        try:
            stats, skeys = self._account_epoch(ctx["e"])
        except BaseException:
            ctx["span"].__exit__(None, None, None)
            raise
        return self._step_finish(ctx, stats, skeys)

    def _step_begin(self, force_event: str | None = None) -> dict:
        """First half of one epoch, up to (not including) the mapping
        accounting: fault gate, the epoch's seeded rng, compile/rebuild
        snapshots, event application.  Split out so the fleet engine
        (ceph_tpu.fleet) can run MANY engines' accounting through one
        stacked dispatch between begin and finish; `step()` composes
        begin/account/finish into the unchanged solo behavior."""
        e = self.steps + 1
        faults.check("lifetime_step", qual=str(e))
        rng = np.random.default_rng([self.scenario.seed, e])
        ctx = {
            "e": e, "rng": rng,
            "t0": time.perf_counter(),
            "jit0": obs.jit_counters(),
            "rb0": (self.state.full_rebuilds
                    if self.state is not None else 0),
        }
        self._structural_apply = False
        span = obs.span("sim.epoch", epoch=e)
        span.__enter__()
        ctx["span"] = span
        try:
            event = self._apply_event(e, rng, force_event)
            if event.startswith("balance"):
                bal_key = (self._prev_skeys, self._overlay_presence())
                ctx["hint"] = bal_key != self._last_balance_key
                self._last_balance_key = bal_key
            else:
                ctx["hint"] = False
        except BaseException:
            span.__exit__(None, None, None)
            raise
        ctx["event"] = event
        return ctx

    def _step_finish(self, ctx: dict, stats: dict, skeys: frozenset,
                     jit_delta: dict | None = None) -> dict:
        """Second half of one epoch: data planes, integration,
        invariants, structural classification, the digest line, and
        observation.  `jit_delta` overrides the measured compile delta:
        the fleet engine passes zeros for its member engines (the
        process-global jit counters cannot attribute the shared stacked
        dispatch to ONE member) and books the batch-level delta itself.
        """
        e, rng, event = ctx["e"], ctx["rng"], ctx["event"]
        t0 = ctx["t0"]
        try:
            wl = (self._workload_epoch(e)
                  if self.workload is not None else None)
            rec = (self._recovery_epoch(e, stats)
                   if self.recovery is not None else None)
            dur = (self._durability_epoch(e)
                   if self.scenario.correlated else None)
            epoch_s = self._integrate(stats, rec)
            self._invariants(e, rng, stats)
        except BaseException:
            ctx["span"].__exit__(None, None, None)
            raise
        ctx["span"].__exit__(None, None, None)
        jd = (jit_delta if jit_delta is not None
              else obs.jit_counters_delta(ctx["jit0"]))
        compiles = jd["compiles"] + jd["retraces"]
        rebuilds = (self.state.full_rebuilds - ctx["rb0"]
                    if self.state is not None else 0)
        structural = (ctx["hint"]
                      or self._structural_apply
                      or self._prev_skeys is None
                      or skeys != self._prev_skeys)
        self._prev_skeys = skeys
        self.total_compiles += compiles
        if structural:
            self.structural_epochs += 1
            _L.inc("structural_epochs")
        else:
            self.steady_epochs += 1
            self.steady_compiles += compiles
            self.steady_pipe_misses += jd["pipe_cache_misses"]
            self.steady_full_rebuilds += rebuilds
            if compiles or rebuilds:
                _log(1, f"epoch {e}: steady epoch booked {compiles} "
                        f"compile(s) + {rebuilds} state rebuild(s) — "
                        f"O(delta) contract broken ({event})")
        line = (
            f"{e}|{event}|"
            + ";".join(
                "{}:{}".format(pid, ":".join(
                    str(stats[pid][k]) for k in ("n",) + STAT_KEYS))
                for pid in sorted(stats))
            + f"|{epoch_s:.6f}"
        )
        # new digest segments exist ONLY when the subsystem is enabled:
        # a flat-model, workload-off run chains the exact legacy lines
        if rec is not None:
            line += "|R" + ";".join(
                "{}:{}".format(pid, ":".join(
                    str(rec["per_pool"][pid][k])
                    for k in RECOVERY_DIGEST_KEYS))
                for pid in sorted(rec["per_pool"]))
        if wl is not None:
            line += "|W" + ";".join(
                "{}:{}".format(pid, ":".join(
                    str(wl["per_pool"][pid][k])
                    for k in WORKLOAD_DIGEST_KEYS))
                for pid in sorted(wl["per_pool"])
            ) + f"|C{wl['throttled']}:{wl['contended']}"
        if dur is not None:
            line += "|D" + ";".join(
                "{}:{}".format(pid, ":".join(
                    str(dur["per_pool"][pid][k])
                    for k in DURABILITY_DIGEST_KEYS))
                for pid in sorted(dur["per_pool"])
            ) + f"|L{self.pg_lost_total}"
        self.digest = hashlib.sha256(
            (self.digest + line).encode()).hexdigest()
        self.steps = e
        self._steps_this_proc += 1
        _L.inc("epochs")
        wall = time.perf_counter() - t0
        self._wall_this_proc += wall
        _L.observe("epoch_seconds", wall)
        # observation AFTER the digest update: health/timeline read only
        # the host ints accounting already fetched, so enabling them is
        # bit-invisible to the replay digest by construction
        health_status = self._observe_epoch(e, stats, rec, wl, dur,
                                            structural)
        every = self.scenario.checkpoint_every
        if self.ck is not None and every and e % every == 0:
            self._checkpoint()
        return {
            "epoch": e,
            "event": event,
            "stats": {pid: dict(st) for pid, st in stats.items()},
            "sim_epoch_s": epoch_s,
            "structural": structural,
            "compiles": compiles,
            "health": health_status,
        }

    def _observe_epoch(self, e: int, stats: dict, rec: dict | None,
                       wl: dict | None, dur: dict | None,
                       structural: bool) -> str:
        """Pure-observer tail of step(): evaluate the health checks and
        record the "sim" timeline sample from numbers that already
        crossed the device boundary.  No device work, no digest input —
        `CEPH_TPU_HEALTH=0` / `CEPH_TPU_TIMELINE_CAP=0` skip it with
        zero effect on replay digests or compile counts."""
        health = obs.health
        totals = {k: 0 for k in ("degraded", "unmapped", "at_risk",
                                 "moved")}
        for st in stats.values():
            for k in totals:
                totals[k] += st[k]
        backlog_gb = (rec["backlog_total"] / 1e9) if rec else 0.0
        status = health.OK
        if health.enabled():
            if self.pg_lost_total > 0:
                # raised DIRECTLY, outside evaluate()'s auto-clearing
                # _set machinery: data loss is irreversible, so
                # DATA_LOSS never clears on its own — only an explicit
                # operator reset/clear removes it
                health.raise_check(
                    "DATA_LOSS", health.ERR,
                    f"{self.pg_lost_total} PG(s) suffered unrecoverable"
                    " chunk loss (dead chunks exceeded tolerance before"
                    " the backlog drained)",
                    count=self.pg_lost_total)
            exists = down = 0
            for o in range(self.m.max_osd):
                if self.m.exists(o):
                    exists += 1
                    if self.m.is_down(o):
                        down += 1
            status = health.evaluate(
                osds_down=down, osd_count=exists,
                degraded=totals["degraded"], unmapped=totals["unmapped"],
                at_risk=totals["at_risk"], backlog_gb=backlog_gb,
                device_degraded=len(self.fallback_events),
            )
            key = {health.OK: "ok", health.WARN: "warn",
                   health.ERR: "err"}[status]
            self._health_counts[key] += 1
        obs.timeline.sample("sim", {
            "epoch": e,
            "degraded": totals["degraded"],
            "unmapped": totals["unmapped"],
            "at_risk": totals["at_risk"],
            "moved": totals["moved"],
            "backlog_gb": backlog_gb,
            "throttled": (wl or {}).get("throttled", 0),
            "structural": int(structural),
            "health": health.rank(status),
            # durability exposure: PGs currently below full redundancy
            # from true chunk deaths, and the irreversible loss count
            "exposed": 0 if dur is None else dur["exposed"],
            "pg_lost": self.pg_lost_total,
        })
        return status

    def _integrate(self, stats: dict, rec: dict | None = None) -> float:
        sc = self.scenario
        moved_bytes = 0.0
        totals = {k: 0 for k in STAT_KEYS}
        total_pgs = 0
        for st in stats.values():
            for k in STAT_KEYS:
                totals[k] += st[k]
            total_pgs += st["n"]
            moved_bytes += st["moved"] * (sc.pg_gb / st["size"]) * 1e9
        if rec is None:
            # legacy flat model (recovery=flat): one division, silently
            # floored at interval_s — bit-identical to PR 10's formula
            epoch_s = max(sc.interval_s,
                          moved_bytes / (sc.recovery_mbps * 1e6))
            at_risk_s = totals["at_risk"] * epoch_s
        else:
            # queue model: epochs are fixed control-plane intervals,
            # unfinished work carries as backlog, and the risk window
            # is the drain kernel's per-PG completion-time integral
            epoch_s = sc.interval_s
            at_risk_s = sum(
                p["risk_us"] for p in rec["per_pool"].values()) / 1e6
        self.sim_seconds += epoch_s
        self._sim_this_proc += epoch_s
        rep = MovementReport(
            total_pgs=total_pgs,
            pgs_remapped=totals["remapped"],
            replicas_moved=totals["moved"],
            degraded_pgs=totals["degraded"],
            pgs_at_risk=totals["at_risk"],
            at_risk_pg_seconds=at_risk_s,
        )
        self.report.merge(rep)
        _L.observe("at_risk_pg_seconds", rep.at_risk_pg_seconds)
        if totals["degraded"]:
            self.degraded_epochs += 1
            _L.inc("degraded_pg_epochs")
        return epoch_s

    # -- driving -----------------------------------------------------------

    def run(self, stop_after: int | None = None,
            epochs: int | None = None) -> dict:
        total = self.scenario.epochs if epochs is None else epochs
        while self.steps < total:
            if stop_after is not None and self.steps >= stop_after:
                break
            self.step()
        self._checkpoint()
        return self.summary()

    def provenance(self) -> dict:
        return {
            "backend": self.backend,
            "device_loss_fallbacks": len(self.fallback_events),
            "fallback_events": list(self.fallback_events),
        }

    def summary(self) -> dict:
        wall = self._wall_this_proc
        steps = self._steps_this_proc
        sim_years = self.sim_seconds / (86400.0 * 365.0)
        out = {
            "scenario": self.scenario.spec(),
            "epochs": self.steps,
            "map_epoch": self.m.epoch,
            "digest": self.digest,
            "sim_seconds": round(self.sim_seconds, 3),
            "sim_years": round(sim_years, 6),
            "events": dict(sorted(self.event_counts.items())),
            "invariant_violations": len(self.violations),
            "violations": self.violations[:20],
            "degraded_epochs": self.degraded_epochs,
            "report": vars(self.report),
            "trace_once": {
                "structural_epochs": self.structural_epochs,
                "steady_epochs": self.steady_epochs,
                "steady_compiles": self.steady_compiles,
                "steady_pipe_misses": self.steady_pipe_misses,
                "steady_full_rebuilds": self.steady_full_rebuilds,
                "total_compiles": self.total_compiles,
            },
            "state": None if self.state is None else {
                "delta_applies": self.state.delta_applies,
                "full_rebuilds": self.state.full_rebuilds,
            },
            "jit_compiles_per_epoch": round(
                self.total_compiles / self.steps, 4
            ) if self.steps else 0.0,
            "provenance": self.provenance(),
            "wall_s": round(wall, 3),
            "epochs_per_sec": round(steps / wall, 2) if wall else 0.0,
            # simulated years covered by THIS process's epochs per
            # wallclock hour — the headline rate (a resumed run reports
            # its own portion, not the checkpointed history's)
            "cluster_years_per_hour": round(
                (self._sim_this_proc / (86400.0 * 365.0))
                / (wall / 3600.0), 3
            ) if wall else 0.0,
            "recovery_model": self.scenario.recovery,
            "health": {
                **obs.health.summary(),
                "epochs": dict(self._health_counts),
                "timeline_samples": obs.timeline.next_index("sim"),
            },
            "recovery": (None if self.recovery is None
                         else self.recovery.summary()),
            "workload": (None if self.workload is None
                         else self.workload.summary(self.sim_seconds)),
        }
        if self.scenario.correlated:
            worst = sorted(self.flap_counts.items(),
                           key=lambda kv: (-kv[1], kv[0]))
            out["chaos"] = {
                "flapper_osds": list(self.flapper_osds),
                "flap_counts": {f"osd.{o}": c for o, c in worst[:8]},
                "repeat_flaps": max(self.flap_counts.values(),
                                    default=0),
                "false_flap_revives": self.false_flap_revives,
                "domain_outages": dict(sorted(
                    self.domain_outages.items(),
                    key=lambda kv: (-kv[1], kv[0]))),
                "cascades": self.cascades,
                "longest_cascade": self.longest_cascade,
                "hazard_windows": self.hazard_windows,
                "active_hazards": len(self.hazards),
            }
            out["durability"] = {
                "pg_lost": self.pg_lost_total,
                "lost": {str(pid): list(s)
                         for pid, s in sorted(self.lost.items()) if s},
                "exposed_pg_epochs": self.exposed_pg_epochs,
                "wounded_pgs": int(sum(
                    int((w > 0).sum())
                    for w in self.wounded.values())),
                "max_wounds": int(max(
                    (int(w.max()) for w in self.wounded.values()
                     if w.size), default=0)),
            }
        if self.workload is not None:
            # the pareto headline: simulated coverage rate AT a stated
            # client service level (with the recovery backlog the queue
            # model carried between them)
            out["pareto"] = {
                "cluster_years_per_hour":
                    out["cluster_years_per_hour"],
                "served_qps": out["workload"]["served_qps"],
            }
        if self.resumed_from is not None:
            out["resumed_from"] = self.resumed_from
        return out


# Which deltas invalidate what is no longer event-string heuristics:
# `osd.state.classify_incremental` reads the Incremental itself —
# value-only deltas scatter on device in O(delta) and bump the exact
# version counters (vectors / raw descent / per-pool overlays), while
# structural ones re-key the ClusterState.  Staleness would be caught
# by the spot-check lanes and the overlay-respect invariant.
