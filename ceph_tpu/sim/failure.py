"""Failure / recovery simulation over the batched placement pipeline.

The reference's failure handling is declarative: heartbeats mark OSDs down
(reference src/osd/OSD.cc:5327 handle_osd_ping, :5698 heartbeat_check),
the monitor publishes a new epoch, and recovery IS the difference between
the old and new up/acting sets per PG (peering/backfill,
reference src/osd/PeeringState.cc; pg_temp keeps serving from the old
acting set during backfill, reference src/osd/OSDMap.cc:2592).

For a placement framework, that means failure simulation = flip osd state,
re-run the batched mapping, and diff — this module does exactly that, plus
an OSDThrasher-style randomized fault injector (the qa harness pattern,
reference qa/tasks/ceph_manager.py:185) used by the tests.

Degraded-mode placement: the device backend itself can die mid-batch
(transport loss; `runtime.faults` injects the same shape at the
`map_batch` fault point).  When it does, the sim degrades that mapping
pass to the host reference mapper — which produces *identical* mappings
by the bit-exactness contract — and records the descent in the `runtime`
perf group and `ClusterSim.fallback_events`, so a thrash run that
silently lost its accelerator still reports which backend actually
produced each epoch's placements.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.types import PgId
from ceph_tpu.runtime import DeviceLostError
from ceph_tpu.utils.dout import subsys_logger

_log = subsys_logger("sim")


@dataclass
class MovementReport:
    """Diff of two cluster mappings (per pool)."""

    total_pgs: int = 0
    pgs_remapped: int = 0  # up set changed
    pgs_primary_changed: int = 0
    replicas_moved: int = 0  # osds that entered a pg's up set
    degraded_pgs: int = 0  # up set smaller than pool size
    moved_fraction: float = 0.0
    # EC-aware risk accounting (sim/lifetime.py): PGs whose up set has
    # lost more chunks than the pool tolerates (EC: > m, replicated:
    # > size-1), and the integral of that count over simulated time
    # under the recovery-rate model
    pgs_at_risk: int = 0
    at_risk_pg_seconds: float = 0.0

    def merge(self, other: "MovementReport") -> None:
        self.total_pgs += other.total_pgs
        self.pgs_remapped += other.pgs_remapped
        self.pgs_primary_changed += other.pgs_primary_changed
        self.replicas_moved += other.replicas_moved
        self.degraded_pgs += other.degraded_pgs
        self.pgs_at_risk += other.pgs_at_risk
        self.at_risk_pg_seconds += other.at_risk_pg_seconds
        if self.total_pgs:
            self.moved_fraction = self.pgs_remapped / self.total_pgs


def _map_ref(m: OSDMap, pid: int) -> tuple:
    """Host reference mapper for one pool (the degradation target)."""
    pool = m.pools[pid]
    n, W = pool.pg_num, pool.size
    up = np.full((n, W), ITEM_NONE, np.int32)
    upp = np.full(n, -1, np.int32)
    acting = np.full((n, W), ITEM_NONE, np.int32)
    actp = np.full(n, -1, np.int32)
    for ps in range(n):
        u, up_pr, a, a_pr = m.pg_to_up_acting_osds(PgId(pid, ps))
        up[ps, : len(u)] = u
        acting[ps, : len(a)] = a
        upp[ps], actp[ps] = up_pr, a_pr
    return (up, upp, acting, actp)


def _device_loss_counter():
    from ceph_tpu import obs

    L = obs.logger_for("runtime")
    L.add_u64("device_loss_fallbacks",
              "mapping passes degraded to the host mapper after a "
              "mid-batch device loss")
    return L


def _map_all(
    m: OSDMap, backend: str, events: list[str] | None = None
) -> dict[int, tuple]:
    out = {}
    for pid in sorted(m.pools):
        if backend == "jax":
            from ceph_tpu.osd.pipeline_jax import PoolMapper

            try:
                out[pid] = PoolMapper(m, pid).map_all()
                continue
            except DeviceLostError as e:
                # degrade, don't die: the host mapper is bit-exact with
                # the device pipeline, so placements are identical —
                # only slower.  Record the descent loudly.
                _device_loss_counter().inc("device_loss_fallbacks")
                _log(1, f"device lost mapping pool {pid} ({e}); "
                        "degrading to host mapper")
                if events is not None:
                    events.append(
                        f"pool {pid} epoch {m.epoch}: {e} -> ref"
                    )
        out[pid] = _map_ref(m, pid)
    return out


def diff_mappings(
    before: dict[int, tuple], after: dict[int, tuple], pools: dict
) -> MovementReport:
    rep = MovementReport()
    for pid, (up1, upp1, _, _) in before.items():
        up2, upp2, _, _ = after[pid]
        size = pools[pid].size
        total = up1.shape[0]
        rep.total_pgs += total
        for ps in range(total):
            a = [o for o in up1[ps] if o != ITEM_NONE]
            b = [o for o in up2[ps] if o != ITEM_NONE]
            if a != b:
                rep.pgs_remapped += 1
                rep.replicas_moved += len(set(b) - set(a))
            if upp1[ps] != upp2[ps]:
                rep.pgs_primary_changed += 1
            if len(b) < size:
                rep.degraded_pgs += 1
    if rep.total_pgs:
        rep.moved_fraction = rep.pgs_remapped / rep.total_pgs
    return rep


class ClusterSim:
    """Stateful failure simulator: apply events, measure movement.

    diagnostics: run the instrumented placement-diagnostics pass after
    every epoch — per-epoch bad-mapping / retry-exhaustion accounting
    (`diag_history`, latest snapshot under source "sim" in
    `obs.placement`).  Defaults to the CEPH_TPU_PLACEMENT_DIAG knob:
    the pass costs one extra mapping dispatch per epoch."""

    def __init__(self, m: OSDMap, backend: str = "jax",
                 diagnostics: bool | None = None):
        from ceph_tpu.utils import knobs

        self.m = m
        self.backend = backend
        self.epoch = m.epoch
        if diagnostics is None:
            diagnostics = knobs.get("CEPH_TPU_PLACEMENT_DIAG", "0") == "1"
        self.diagnostics = diagnostics
        self.diag_history: list[tuple[str, dict]] = []
        # provenance of degraded mapping passes (device loss -> ref)
        self.fallback_events: list[str] = []
        self.current = _map_all(m, backend, self.fallback_events)
        self.history: list[tuple[str, MovementReport]] = []
        if self.diagnostics:
            self._diagnose_epoch("init")

    def _diagnose_epoch(self, label: str) -> dict:
        """Per-epoch decision accounting over every pool.  jax pools run
        the instrumented device pipeline (full retry/collision planes);
        a ref/degraded pass falls back to host-side bad-mapping counts
        from the rows already mapped (no retry visibility)."""
        from ceph_tpu.obs import placement

        agg: dict = {"epoch": int(self.epoch), "label": label}
        for pid in sorted(self.m.pools):
            s = None
            if self.backend == "jax":
                from ceph_tpu.osd.pipeline_jax import PoolMapper

                try:
                    s = PoolMapper(self.m, pid).diagnose(record=False)
                except DeviceLostError as e:
                    _log(1, f"device lost diagnosing pool {pid} ({e}); "
                            "host bad-mapping counts only")
            if s is None:
                up = self.current[pid][0]
                occupied = (np.asarray(up) != ITEM_NONE).sum(axis=1)
                s = {"pgs": int(up.shape[0]),
                     "bad_mappings": int(
                         (occupied < self.m.pools[pid].size).sum()),
                     "diag_exact": False}
            placement.fold_summary(agg, s)
        placement.record("sim", agg)
        self.diag_history.append((label, agg))
        return agg

    def provenance(self) -> dict:
        """Which backend produced the placements, and every degradation
        that happened along the way."""
        return {
            "backend": self.backend,
            "device_loss_fallbacks": len(self.fallback_events),
            "fallback_events": list(self.fallback_events),
        }

    def _step(self, label: str) -> MovementReport:
        self.epoch += 1
        self.m.epoch = self.epoch
        new = _map_all(self.m, self.backend, self.fallback_events)
        rep = diff_mappings(self.current, new, self.m.pools)
        self.current = new
        self.history.append((label, rep))
        if self.diagnostics:
            self._diagnose_epoch(label)
        return rep

    # -- events ------------------------------------------------------------
    def fail_osd(self, osd: int, out: bool = True) -> MovementReport:
        """down (+out): the heartbeat-timeout → mark-down → mark-out path."""
        self.m.mark_down(osd)
        if out:
            self.m.mark_out(osd)
        return self._step(f"fail osd.{osd}")

    def revive_osd(self, osd: int) -> MovementReport:
        self.m.mark_up_in(osd)
        return self._step(f"revive osd.{osd}")

    def reweight_osd(self, osd: int, weight: float) -> MovementReport:
        self.m.osd_weight[osd] = int(weight * 0x10000)
        return self._step(f"reweight osd.{osd} {weight}")

    def set_pg_temp(
        self, pg: PgId, acting: list[int], primary: int = -1
    ) -> MovementReport:
        """Serve from the old acting set during backfill."""
        self.m.pg_temp[pg] = list(acting)
        if primary >= 0:
            self.m.primary_temp[pg] = primary
        return self._step(f"pg_temp {pg}")

    def balance(self, **kw) -> MovementReport:
        from ceph_tpu.balancer import calc_pg_upmaps

        kw.setdefault("use_tpu", self.backend == "jax")
        calc_pg_upmaps(self.m, **kw)
        return self._step("balance")

    # -- thrasher ----------------------------------------------------------
    def thrash(
        self,
        rounds: int,
        rng: np.random.Generator | None = None,
        p_fail: float = 0.5,
    ) -> list[MovementReport]:
        """OSDThrasher pattern: random kill/revive rounds; every PG must
        stay mapped (no PG falls off the cluster while >= size OSDs up).

        The up-OSD floor derives from the LARGEST pool's size: an EC
        pool of k+m chunks needs k+m distinct up OSDs to stay mappable,
        so the thrasher never kills below that (the old hardcoded `> 3`
        floor silently over-thrashed any pool wider than replicated
        size-3)."""
        rng = rng or np.random.default_rng(0)
        floor = max(
            (p.size for p in self.m.pools.values()), default=3
        )
        downed: list[int] = []
        reports = []
        for _ in range(rounds):
            up_osds = [
                o for o in range(self.m.max_osd)
                if self.m.is_up(o)
            ]
            if downed and (
                rng.random() > p_fail or len(up_osds) <= floor
            ):
                osd = downed.pop(int(rng.integers(len(downed))))
                reports.append(self.revive_osd(osd))
            elif len(up_osds) > floor:
                osd = int(up_osds[int(rng.integers(len(up_osds)))])
                downed.append(osd)
                reports.append(self.fail_osd(osd))
        return reports
