from ceph_tpu.sim.failure import ClusterSim, MovementReport

__all__ = ["ClusterSim", "MovementReport"]
