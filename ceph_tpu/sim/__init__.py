from ceph_tpu.sim.failure import ClusterSim, MovementReport
from ceph_tpu.sim.lifetime import LifetimeSim, Scenario

__all__ = ["ClusterSim", "LifetimeSim", "MovementReport", "Scenario"]
