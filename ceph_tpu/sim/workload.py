"""Seeded client workload generator for the lifetime simulator.

Real clusters fail *under load* (ROADMAP item 5): control-plane churn
alone never shows degraded reads, requests landing on at-risk PGs, or
recovery-vs-client bandwidth contention — the behaviors the online-EC
SSD-array study (PAPERS.md) calls out.  This module models client
traffic whose object→PG→OSD path rides the SAME device-resident
placement rows the accounting pass already produced (ClusterState /
trace-once pipeline — no second mapping dispatch):

- **QPS curve.**  Epoch `e` serves `base_qps · diurnal(e)` requests per
  simulated second, where `diurnal` is a piecewise-linear (triangle)
  day curve of amplitude `diurnal_amp` and period `diurnal_period`
  epochs — exact float arithmetic, so both backends compute the same
  request count.
- **Skew.**  Requests split across pools by a Zipf-like rank weight
  (`(rank+1)^-hot_pool`, hottest pool first) and across PGs inside a
  pool by a power-law hot-key draw (`pg = floor(n · u^zipf_a)`), both
  from `default_rng([seed, epoch, pid, 0x77])` — per-epoch streams, no
  RNG state spans epochs, so the trajectory is resume-exact.
- **Mapping.**  A fixed-size sample (`wl_sample` draws, each standing
  for `requests // sample` real requests) gathers the pool's device
  rows ON DEVICE: reads hit the primary (first live lane), writes hit
  every live replica lane, and the per-OSD client byte histogram, the
  degraded-read / at-risk-hit / backlog-hit tallies all reduce in the
  same kernel.  All int64 — the numpy mirror is bit-identical, which
  is what keeps the trajectory digest equal across jax and ref.
- **Contention.**  Per-OSD client bytes are charged against the same
  `osd_mbps · interval_s` epoch capacity the recovery queue drains
  from: clients take their share first, recovery gets the remainder —
  `throttled_bytes` (client demand beyond capacity) and
  `contended_osd_epochs` (OSDs whose full epoch capacity went to
  clients) are the contention record.

Client-visible metrics land in the `workload` perf group and the
per-epoch digest line (when the generator is enabled), giving the
lifetime bench its pareto headline: cluster-years/hour *at* a stated
served QPS.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu import obs
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.utils.dout import subsys_logger

_log = subsys_logger("sim")

_L = obs.logger_for("workload")
_L.add_u64("requests",
           "modeled client requests mapped through the placement rows")
_L.add_u64("reads", "read requests (primary lane)")
_L.add_u64("writes", "write requests (all live replica lanes)")
_L.add_u64("degraded_reads",
           "reads served degraded: up set below pool size with >=1 "
           "live replica")
_L.add_u64("at_risk_hits",
           "requests that landed on at-risk PGs (below tolerance)")
_L.add_u64("backlog_hits",
           "requests that landed on PGs carrying recovery backlog")
_L.add_u64("unserved",
           "requests whose PG had no live replica at all")
_L.add_u64("throttled_bytes",
           "client bytes beyond the per-OSD epoch capacity")
_L.add_u64("contended_osd_epochs",
           "OSD-epochs whose full bandwidth capacity was consumed by "
           "client traffic (recovery starved)")
_L.add_avg("qps", "modeled client QPS (one observation per epoch)")
_L.add_quantile("step_seconds",
                "wall time of one epoch's workload pass (all pools: "
                "draws + dispatch + scalar fetch, or the numpy mirror)")

WL_KEYS = ("requests", "reads", "writes", "degraded_reads",
           "at_risk_hits", "backlog_hits", "unserved")


def zipf_pg_seeds(u: np.ndarray, n: int, zipf_a: float) -> np.ndarray:
    """The hot-key power-law PG draw: `floor(n · u^a)` clamped to
    [0, n).  Shared by the simulator's per-epoch samples and the serve
    chaos clients so both sides of ROADMAP item 3 shape traffic with
    the SAME formula."""
    return np.minimum((n * np.power(u, zipf_a)).astype(np.int64), n - 1)


def pool_rank_weights(k: int, hot_pool: float) -> list[float]:
    """Zipf-like rank weights across `k` pools (`(rank+1)^-hot_pool`,
    hottest first).  A plain Python list summed left-to-right — the
    exact arithmetic `pool_requests` always used, so extracting it
    moved no digests."""
    return [(i + 1) ** -hot_pool for i in range(k)]


def workload_pool_np(rows, backlog, seeds, read, *, wq: int,
                     obj_bytes: int, DV: int, size: int, tol: int):
    """The authoritative per-pool traffic formula, numpy executor
    (exact int64).  Returns (client_bytes[DV], scalars dict)."""
    rows = np.asarray(rows)
    seeds = np.asarray(seeds, np.int64)
    read = np.asarray(read, bool)
    backlog = (np.zeros(rows.shape[0], np.int64) if backlog is None
               else np.asarray(backlog, np.int64))
    r = rows[seeds]
    valid = (r != ITEM_NONE) & (r >= 0)
    occ = valid.sum(axis=1)
    degraded = occ < size
    at_risk = occ < size - tol
    unserved = occ == 0
    degraded_read = read & degraded & (occ > 0)
    backlog_hit = backlog[seeds] > 0
    first = np.argmax(valid, axis=1)
    prim = r[np.arange(r.shape[0]), first].astype(np.int64)
    prim = np.where(valid.any(axis=1) & (prim >= 0) & (prim < DV),
                    prim, np.int64(DV))
    hist = np.zeros(DV + 1, np.int64)
    np.add.at(hist, np.where(read, prim, np.int64(DV)), 1)
    wl = valid & (r >= 0) & (r < DV) & ~read[:, None]
    np.add.at(hist, np.where(wl, r, DV).reshape(-1).astype(np.int64),
              wl.reshape(-1).astype(np.int64))
    # read lanes that fell in the DV drop bucket (no primary) were
    # counted there; slice it off
    client = hist[:DV] * np.int64(obj_bytes) * np.int64(wq)
    S = int(seeds.shape[0])
    scalars = {
        "requests": S * wq,
        "reads": int(read.sum()) * wq,
        "writes": int((~read).sum()) * wq,
        "degraded_reads": int(degraded_read.sum()) * wq,
        "at_risk_hits": int(at_risk.sum()) * wq,
        "backlog_hits": int(backlog_hit.sum()) * wq,
        "unserved": int(unserved.sum()) * wq,
    }
    return client, scalars


def _build_wl():
    """The jitted device executor of the SAME formula (lazy jax
    import; int64 end to end — bit-identical to workload_pool_np)."""
    import jax
    import jax.numpy as jnp

    def _wl(rows, backlog, seeds, read, wq, obj_bytes, DV, size, tol):
        dv = int(DV)  # static: shapes derive from it
        r = rows[seeds]
        valid = (r != ITEM_NONE) & (r >= 0)
        occ = jnp.sum(valid.astype(jnp.int64), axis=1)
        size = size.astype(jnp.int64)
        tol = tol.astype(jnp.int64)
        degraded = occ < size
        at_risk = occ < size - tol
        unserved = occ == 0
        degraded_read = read & degraded & (occ > 0)
        backlog_hit = backlog[seeds] > 0
        first = jnp.argmax(valid, axis=1)
        prim = jnp.take_along_axis(
            r, first[:, None], axis=1)[:, 0].astype(jnp.int64)
        prim = jnp.where(valid.any(axis=1) & (prim >= 0) & (prim < dv),
                         prim, jnp.int64(dv))
        hist = jnp.zeros(dv + 1, jnp.int64)
        hist = hist.at[jnp.where(read, prim, jnp.int64(dv))].add(1)
        wl = valid & (r >= 0) & (r < dv) & ~read[:, None]
        hist = hist.at[
            jnp.where(wl, r, dv).reshape(-1).astype(jnp.int64)
        ].add(wl.reshape(-1).astype(jnp.int64))
        client = hist[:dv] * obj_bytes * wq
        scalars = jnp.stack([
            jnp.int64(seeds.shape[0]) * wq,
            jnp.sum(read.astype(jnp.int64)) * wq,
            jnp.sum((~read).astype(jnp.int64)) * wq,
            jnp.sum(degraded_read.astype(jnp.int64)) * wq,
            jnp.sum(at_risk.astype(jnp.int64)) * wq,
            jnp.sum(backlog_hit.astype(jnp.int64)) * wq,
            jnp.sum(unserved.astype(jnp.int64)) * wq,
        ])
        return client, scalars

    return obs.JitAccount(
        jax.jit(_wl, static_argnums=(6,)), _L, "traffic")


_WL_ACCTS: dict[tuple, obs.JitAccount] = {}


def _wl_account(shape_key: tuple) -> obs.JitAccount:
    acct = _WL_ACCTS.get(shape_key)
    if acct is None:
        acct = _WL_ACCTS[shape_key] = _build_wl()
    return acct


def contention_np(client_total: np.ndarray, cap_bytes: int):
    """Charge client bytes against the per-OSD epoch capacity: returns
    (cap_remaining[DV], throttled_bytes, contended_osds) — exact
    int64, the numpy executor."""
    client_total = np.asarray(client_total, np.int64)
    cap0 = np.full(client_total.shape[0], np.int64(cap_bytes), np.int64)
    rem = np.maximum(cap0 - client_total, 0)
    throttled = int(np.maximum(client_total - cap0, 0).sum())
    contended = int(((rem == 0) & (client_total > 0)).sum())
    return rem, throttled, contended


def contention_jnp(client_total, cap_bytes: int):
    """Device twin of contention_np (elementwise int64; the two scalar
    fetches are the only host syncs)."""
    import jax.numpy as jnp

    cap0 = jnp.full(client_total.shape[0], jnp.int64(cap_bytes))
    rem = jnp.maximum(cap0 - client_total, 0)
    throttled = int(jnp.sum(jnp.maximum(client_total - cap0, 0)))
    contended = int(jnp.sum(((rem == 0) & (client_total > 0))
                            .astype(jnp.int64)))
    return rem, throttled, contended


class WorkloadGen:
    """Seeded client traffic model (module docstring).  The engine
    drives the per-epoch loop; this class owns the draws, the
    executors, and the cumulative tallies."""

    def __init__(self, *, seed: int, base_qps: float,
                 read_fraction: float, zipf_a: float, hot_pool: float,
                 diurnal_amp: float, diurnal_period: int,
                 obj_kb: int, sample: int, interval_s: float):
        self.seed = seed
        self.base_qps = base_qps
        self.read_fraction = read_fraction
        self.zipf_a = zipf_a
        self.hot_pool = hot_pool
        self.diurnal_amp = diurnal_amp
        self.diurnal_period = max(int(diurnal_period), 1)
        self.obj_bytes = int(obj_kb) * 1024
        self.sample = int(sample)
        self.interval_s = interval_s
        self.totals = {k: 0 for k in WL_KEYS}
        self.totals["throttled_bytes"] = 0
        self.totals["contended_osd_epochs"] = 0
        self._warmed: set[tuple] = set()

    # -- draws -------------------------------------------------------------

    def qps(self, e: int) -> float:
        """Piecewise-linear diurnal curve (exact float arithmetic)."""
        phase = (e % self.diurnal_period) / self.diurnal_period
        tri = 1.0 - 2.0 * abs(2.0 * phase - 1.0)  # [-1, 1] triangle
        return self.base_qps * (1.0 + self.diurnal_amp * tri)

    def epoch_requests(self, e: int) -> int:
        return int(self.qps(e) * self.interval_s)

    def pool_requests(self, e: int, pids: list[int]) -> dict[int, int]:
        """Zipf-rank split of the epoch's requests across pools (pool
        rank = position in sorted pid order: oldest pool hottest)."""
        R = self.epoch_requests(e)
        w = pool_rank_weights(len(pids), self.hot_pool)
        tot = sum(w)
        return {pid: int(R * wi / tot) for pid, wi in zip(pids, w)}

    def draws(self, e: int, pid: int, n: int):
        """The epoch's seeded sample for one pool: hot-key power-law
        PG seeds + the read/write mix."""
        rng = np.random.default_rng([self.seed, e, pid, 0x77])
        u = rng.random(self.sample)
        seeds = zipf_pg_seeds(u, n, self.zipf_a)
        read = rng.random(self.sample) < self.read_fraction
        return seeds, read

    # -- executors ---------------------------------------------------------

    def warm(self, pid: int, rows, backlog, DV: int) -> None:
        """Compile the traffic kernel for this pool's shapes (baseline /
        structural epochs); outputs discarded, nothing booked."""
        import jax.numpy as jnp

        key = (int(rows.shape[0]), int(rows.shape[1]), DV, self.sample)
        if key in self._warmed:
            return
        if backlog is None:
            backlog = jnp.zeros(int(rows.shape[0]), jnp.int64)
        _wl_account(key)(
            rows, backlog, jnp.zeros(self.sample, jnp.int64),
            jnp.zeros(self.sample, bool), np.int64(0),
            np.int64(self.obj_bytes), DV, np.int32(1), np.int32(0))
        self._warmed.add(key)

    def step_pool_device(self, e: int, pid: int, rows, backlog, *,
                         n: int, size: int, tol: int, DV: int,
                         wq: int):
        import jax.numpy as jnp

        seeds, read = self.draws(e, pid, n)
        key = (int(rows.shape[0]), int(rows.shape[1]), DV, self.sample)
        if backlog is None:
            backlog = jnp.zeros(int(rows.shape[0]), jnp.int64)
        client, scal = _wl_account(key)(
            rows, backlog, jnp.asarray(seeds), jnp.asarray(read),
            np.int64(wq), np.int64(self.obj_bytes), DV, np.int32(size),
            np.int32(tol))
        self._warmed.add(key)
        scalars = dict(zip(WL_KEYS, (int(v) for v in np.asarray(scal))))
        return client, scalars

    def step_pool_host(self, e: int, pid: int, rows, backlog, *,
                       n: int, size: int, tol: int, DV: int, wq: int):
        seeds, read = self.draws(e, pid, n)
        return workload_pool_np(
            np.asarray(rows),
            None if backlog is None else np.asarray(backlog),
            seeds, read, wq=wq, obj_bytes=self.obj_bytes, DV=DV,
            size=size, tol=tol)

    # -- accounting --------------------------------------------------------

    def book(self, scalars: dict) -> None:
        for k in WL_KEYS:
            self.totals[k] += scalars[k]
            _L.inc(k, scalars[k])

    def book_contention(self, throttled: int, contended: int) -> None:
        self.totals["throttled_bytes"] += throttled
        self.totals["contended_osd_epochs"] += contended
        _L.inc("throttled_bytes", throttled)
        _L.inc("contended_osd_epochs", contended)

    def observe_epoch(self, qps: float, wall_s: float) -> None:
        _L.observe("qps", qps)
        _L.observe("step_seconds", wall_s)

    def state(self) -> dict:
        return {"totals": dict(self.totals)}

    def restore(self, st: dict) -> None:
        self.totals = dict(st["totals"])

    def summary(self, sim_seconds: float) -> dict:
        out = {
            "requests": self.totals["requests"],
            "served_qps": round(
                self.totals["requests"] / sim_seconds, 1
            ) if sim_seconds else 0.0,
            "reads": self.totals["reads"],
            "writes": self.totals["writes"],
            "degraded_reads": self.totals["degraded_reads"],
            "at_risk_hits": self.totals["at_risk_hits"],
            "backlog_hits": self.totals["backlog_hits"],
            "unserved": self.totals["unserved"],
            "throttled_gb": round(
                self.totals["throttled_bytes"] / 1e9, 3),
            "contended_osd_epochs": self.totals["contended_osd_epochs"],
        }
        return out
