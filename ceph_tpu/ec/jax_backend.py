"""Device engine for GF(2^8) chunk math — strategies, caches, batching.

STRATEGIES below is the one authoritative list (names, mechanism, when
each wins).  Every strategy is bit-exact against the host mul-table
oracle and the frozen ec_corpus; they differ only in how the matmul
parity = M·data lowers onto the device:

- compiled *executables* cache in the module-level `_EC_CACHE` keyed on
  structural facts only (matrix content / shape, stripe shape, batch
  arity) — the same trace-once contract as pipeline_jax._PIPE_CACHE,
  booked into the shared `pipe_cache_hits`/`pipe_cache_misses`
  counters.  One compile per (profile matrix or decode-plan matrix,
  stripe shape); every further stripe and every repeat of an erasure
  pattern is a dispatch.
- XOR schedules (ec.xor_schedule) lower once per matrix at
  profile-registration time (`JaxEngine.prepare`).
- `encode_batch`-style multi-stripe calls ride `matmul_batch`, which
  vmaps the single-stripe kernels over a leading stripes axis with the
  GF tables as operands.

The `tile` knob (default `_BIT_TILE`) bounds the bitplane strategy's
8× bit expansion: byte axes longer than `tile` are processed in
`lax.map` tiles so peak memory is O(tile).  The pallas strategy has its
own VMEM tile (`_PALLAS_TILE`).

Strategy selection: `CEPH_TPU_EC_STRATEGY` env var > explicit
constructor arg > backend default (cpu: `xor`, accelerators: `pallas`).
`strategy="auto"` runs a small measured autotune per matrix (cached in
`_AUTOTUNE`, recorded in BENCH's ec stage).
"""

from __future__ import annotations

import os
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu import obs
from ceph_tpu.ec.gf import GF_LOG, gf_device_tables, matrix_to_bitmatrix
from ceph_tpu.ec.xor_schedule import XorSchedule, build_schedule, matrix_key

# byte-axis tile of the bitplane strategy (see module docstring)
_BIT_TILE = 1 << 17
# VMEM byte-axis tile of the pallas strategy
_PALLAS_TILE = 1 << 12

#: name -> how it computes parity = M·data, and when it wins.  This dict
#: is the single source of truth for strategy names; the engine and the
#: CEPH_TPU_EC_STRATEGY env override validate against it.
STRATEGIES = {
    "xor": (
        "XOR schedule over virtual byte rows 2^j·data[i] (naive term "
        "form: XLA fuses the whole program into one pass; recompute is "
        "free inside a fusion).  Fastest on CPU."
    ),
    "xor_cse": (
        "Same schedule, CSE form: temps materialized per Paar dedup. "
        "Fewer XORs on paper; wins only where temps beat recompute."
    ),
    "bitplane": (
        "GF(2) bit-matrix as int8 MXU matmul mod 2; byte axis tiled to "
        "`tile` (default _BIT_TILE).  The dense-matmul form for MXU-class "
        "hardware via plain XLA."
    ),
    "logexp": (
        "exp[log M + log data] gathers XOR-reduced over k; matrix baked "
        "into the trace (retraces per matrix), tables are operands."
    ),
    "pallas": (
        "Fused Pallas kernel: VMEM-tiled unpack -> MXU matmul -> repack "
        "(tile _PALLAS_TILE).  Interpret-mode when the runtime ladder's "
        "provenance says the backend is cpu; real lowering otherwise."
    ),
    "auto": (
        "Measured autotune over the backend's candidate strategies on a "
        "small sample, cached per matrix in _AUTOTUNE."
    ),
}

_L = obs.logger_for("ec")
# _EC_CACHE books into the same aggregate the pipeline cache uses
# (obs.jit_counters special-cases these names): the bench `jit` records
# prove EC dispatches ride cached executables exactly like pipelines.
_L.add_u64("pipe_cache_hits",
           "EC executables served from _EC_CACHE (no new jit)")
_L.add_u64("pipe_cache_misses", "EC executables built into _EC_CACHE")
_L.add_u64("autotunes", "measured strategy autotunes (one per matrix)")


def _matmul_key(eng, M, data) -> tuple:
    """Warm-key granularity mirrors the actual jit caches: bitplane /
    pallas trace on array shapes only (the bitmatrix is a traced
    operand), while logexp and the xor schedules trace per matrix
    content."""
    strategy = eng._resolved_strategy
    if strategy in ("logexp", "xor", "xor_cse"):
        mat_key = eng._key(M)
    else:
        mat_key = M.shape
    return (mat_key, np.shape(data), strategy)


# Module-level (one shared warm set) because the jit caches it models
# (_matmul_bitplane etc.) are also process-global: a second JaxEngine's
# first call on a warm shape is a dispatch, not a compile.
_gf_acct = obs.JitAccount(
    lambda eng, M, data: eng._matmul(M, data), _L, "gf",
    key_fn=_matmul_key,
    span="ec.gf_matmul",
    span_args=lambda eng, M, data: {
        "rows": int(M.shape[0]),
        "bytes": int(np.prod(np.shape(data))),
        "strategy": eng._resolved_strategy,
    },
)

_gf_batch_acct = obs.JitAccount(
    lambda eng, M, data: eng._matmul_batch(M, data), _L, "gf_batch",
    key_fn=_matmul_key,
    span="ec.gf_matmul_batch",
    span_args=lambda eng, M, data: {
        "rows": int(M.shape[0]),
        "stripes": int(np.shape(data)[0]),
        "bytes": int(np.prod(np.shape(data))),
        "strategy": eng._resolved_strategy,
    },
)


@partial(jax.jit, static_argnums=(2,))
def _matmul_bitplane(Bbits, data, n_out):
    """Bbits: int8[8R, 8S] GF(2) matrix; data: uint8[S, L]."""
    S, L = data.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (
        (data[:, None, :] >> shifts[None, :, None]) & 1
    ).astype(jnp.int8).reshape(8 * S, L)
    acc = jax.lax.dot(
        Bbits, bits, preferred_element_type=jnp.int32
    )  # [8R, L]
    acc = (acc & 1).astype(jnp.uint8).reshape(n_out, 8, L)
    weights = (jnp.uint8(1) << shifts)[None, :, None]
    return jnp.sum(acc * weights, axis=1, dtype=jnp.uint8)


@partial(jax.jit, static_argnums=(0,))
def _matmul_logexp(M_tuple, data, exp, log):
    """M as a static tuple of rows of ints; data: uint8[S, L].  The
    log/exp tables are OPERANDS (gf_device_tables: one device_put per
    backend) — as trace constants they were re-embedded and re-uploaded
    on every per-matrix retrace of this kernel."""
    logd = log[data]  # [S, L]
    nz = data != 0
    rows = []
    for row in M_tuple:
        acc = jnp.zeros(data.shape[1], jnp.uint8)
        for j, c in enumerate(row):
            if c == 0:
                continue
            lc = int(GF_LOG[c])
            prod = exp[lc + logd[j]]
            acc = acc ^ jnp.where(nz[j], prod, 0)
        rows.append(acc)
    return jnp.stack(rows)


def _xtime(x):
    """Traced GF(2^8)/0x11D doubling, branch-free: the arithmetic-shift
    mask form ((int8 >> 7) & 0x1D) measures ~3x faster than the
    jnp.where select on XLA CPU (PROFILE_r07)."""
    mask = (x.astype(jnp.int8) >> 7).astype(jnp.uint8) & jnp.uint8(0x1D)
    return jnp.left_shift(x, 1).astype(jnp.uint8) ^ mask


def xor_schedule_fn(sched: XorSchedule, use_cse: bool):
    """Traceable executor of an XOR schedule: data u8[S, L] -> u8[R, L].

    The program is unrolled from the schedule, so the trace (and the
    compiled executable) is structural per (matrix, cse-form) — exactly
    what `_EC_CACHE` keys on."""
    m, k = sched.shape

    def fn(data):
        vals = {}
        for i in range(k):
            v = data[i]
            vals[8 * i] = v
            for j in range(1, sched.max_power[i] + 1):
                v = _xtime(v)
                vals[8 * i + j] = v
        if use_cse:
            for tid, a, b in sched.ops:
                vals[tid] = vals[a] ^ vals[b]
        outs = []
        for term in (sched.outs if use_cse else sched.terms):
            acc = None
            for t in term:
                acc = vals[t] if acc is None else acc ^ vals[t]
            outs.append(acc if acc is not None else jnp.zeros_like(data[0]))
        return jnp.stack(outs)

    return fn


def gf_matmul_pallas(Bbits, data, n_out: int, tile: int = 4096,
                     interpret: bool | None = None):
    """Fused Pallas TPU kernel: parity = (GF(2) bit-matrix) · data.

    The pure-XLA bitplane path materializes the 8× bit expansion in HBM
    (8S·L i8 written + read back around the matmul).  This kernel tiles
    the byte axis into VMEM blocks and performs unpack → MXU matmul →
    mod-2 repack entirely in VMEM, so HBM traffic is exactly data-in +
    parity-out.  bf16 is exact here: bit operands are 0/1 and the MXU
    accumulates bf16 products in f32 (sums <= 8S << 2^24).

    `interpret=None` gates on the runtime ladder's backend provenance
    (ceph_tpu.runtime.last_provenance): runs that degraded to cpu get
    interpret mode (CI runs the same kernel code), acquisitions that
    landed on an accelerator get the real Mosaic lowering.

    Matches the role of isa-l's ec_encode_data SIMD loops (reference
    src/erasure-code/isa/ErasureCodeIsa.cc:120-149) as the engine's
    innermost hot op.
    """
    from jax.experimental import pallas as pl

    S, L = data.shape
    R8 = Bbits.shape[0]
    assert L % tile == 0, (L, tile)
    if interpret is None:
        interpret = pallas_interpret()

    def kernel(b_ref, d_ref, o_ref):
        d = d_ref[...]  # u8 [S, tile]
        shifts = jnp.arange(8, dtype=jnp.uint8)
        bits = ((d[:, None, :] >> shifts[None, :, None]) & 1).astype(
            jnp.bfloat16
        ).reshape(8 * S, tile)
        acc = jnp.dot(
            b_ref[...].astype(jnp.bfloat16), bits,
            preferred_element_type=jnp.float32,
        )  # [8R, tile]
        accb = acc.astype(jnp.int32) & 1
        accb = accb.reshape(n_out, 8, tile)
        weights = (1 << jnp.arange(8, dtype=jnp.int32))[None, :, None]
        o_ref[...] = jnp.sum(accb * weights, axis=1).astype(jnp.uint8)

    return pl.pallas_call(
        kernel,
        grid=(L // tile,),
        in_specs=[
            pl.BlockSpec((R8, 8 * S), lambda i: (0, 0)),
            pl.BlockSpec((S, tile), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((n_out, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((n_out, L), jnp.uint8),
        interpret=interpret,
    )(Bbits, data)


def pallas_interpret() -> bool:
    """True when the Pallas kernels should run in interpret mode: the
    runtime ladder's acquisition provenance (authoritative — it is what
    actually probed the hardware) says cpu, or, before any acquisition,
    jax's default backend is cpu."""
    from ceph_tpu import runtime

    prov = runtime.last_provenance()
    backend = (prov or {}).get("backend") or jax.default_backend()
    return backend in ("cpu", "none")


# -- trace-once executable cache (the _PIPE_CACHE contract) -----------------
# key -> jitted callable.  Keys are structural only: (kind, matrix key or
# shape, cse-form, batched).  jax.jit adds its own per-input-shape cache
# under each entry, so one entry serves every stripe length.
_EC_CACHE: dict[tuple, object] = {}


def _ec_cached(key: tuple, build):
    fn = _EC_CACHE.get(key)
    if fn is None:
        _L.inc("pipe_cache_misses")
        # executable-registry record per cache entry (key[0] is the
        # strategy/kind tag): compile cost, dispatch counts, and lazy
        # cost analysis become visible in `perf dump` / `cache dump`
        fn = obs.executables.wrap(build(), "ec", str(key[0]), key)
        _EC_CACHE[key] = fn
    else:
        _L.inc("pipe_cache_hits")
    return fn


# measured autotune results: (backend, matrix key) -> record dict
_AUTOTUNE: dict[tuple, dict] = {}


class JaxEngine:
    """Device GF matmul engine: M u8[R,S] × data u8[S,L] -> u8[R,L].

    Device constants (bit-matrices, XOR schedules) are cached per matrix
    in process-global caches — the engine is reused across calls with
    the same code matrix (encode, repeated decode) without re-deriving,
    re-tracing, or re-uploading anything.  When `data` is already a jax
    array the result STAYS on device (no host round-trip); numpy in →
    numpy out for the host-facing plugin API, with the d2h fetch booked
    into `gf_fetch_seconds` (outside the dispatch span — the
    check_no_host_sync lint covers `ec.gf_dispatch`).

    Strategy resolution (see STRATEGIES): env CEPH_TPU_EC_STRATEGY (a
    true override — it FORCES the strategy even when a profile or
    caller picked one) > explicit arg / profile["strategy"] > backend
    default (cpu: xor, else pallas).
    """

    def __init__(self, strategy: str | None = None, tile: int = _BIT_TILE):
        from ceph_tpu.utils import ensure_jax_backend

        ensure_jax_backend()
        env = os.environ.get("CEPH_TPU_EC_STRATEGY")
        if env:
            strategy = env
        if strategy is None:
            strategy = "xor" if jax.default_backend() == "cpu" else "pallas"
        if strategy not in STRATEGIES:
            raise ValueError(
                f"unknown EC strategy {strategy!r}; "
                f"pick one of {sorted(STRATEGIES)}"
            )
        self.strategy = strategy
        self.tile = tile
        self._bitmats: dict[tuple, jnp.ndarray] = {}
        self._logexp_cache: dict[tuple, tuple] = {}
        self._resolved_strategy = strategy  # per-call for "auto"
        self.autotune: dict[tuple, dict] = {}  # matrix key -> record

    @staticmethod
    def _key(M: np.ndarray):
        return matrix_key(M)

    def _bitmat(self, M: np.ndarray):
        key = self._key(M)
        B = self._bitmats.get(key)
        if B is None:
            B = jnp.asarray(matrix_to_bitmatrix(M).astype(np.int8))
            self._bitmats[key] = B
        return B

    def prepare(self, M: np.ndarray) -> None:
        """Profile-registration hook: derive the matrix's structural
        artifacts (XOR schedule, bit-matrix, logexp tuple) ONCE, before
        any stripe arrives.  Called by the plugins at parse() time so
        the first encode pays only the jit compile, and by the decode
        plan cache for each new erasure pattern's recover matrix."""
        M = np.asarray(M, np.uint8)
        s = self.strategy
        if s in ("xor", "xor_cse", "auto"):
            build_schedule(M)
        if s in ("bitplane", "pallas", "auto"):
            self._bitmat(M)
        if s in ("logexp", "auto"):
            self._logexp_tuple(M)

    def _logexp_tuple(self, M: np.ndarray):
        key = self._key(M)
        mt = self._logexp_cache.get(key)
        if mt is None:
            mt = tuple(tuple(int(c) for c in r) for r in M)
            self._logexp_cache[key] = mt
        return mt

    # -- strategy resolution / autotune ---------------------------------
    def _candidates(self) -> tuple[str, ...]:
        if jax.default_backend() == "cpu":
            # pallas-interpret is orders of magnitude off; not a candidate
            return ("xor", "xor_cse", "bitplane", "logexp")
        return ("pallas", "bitplane", "xor", "logexp")

    def _resolve(self, M: np.ndarray, d) -> str:
        """Concrete strategy for this matrix (autotunes on 'auto')."""
        if self.strategy != "auto":
            return self.strategy
        key = (jax.default_backend(), self._key(M))
        rec = _AUTOTUNE.get(key)
        if rec is None:
            rec = self._run_autotune(M, d)
            _AUTOTUNE[key] = rec
        self.autotune[key[1]] = rec
        return rec["strategy"]

    def _run_autotune(self, M: np.ndarray, d) -> dict:
        """Measure each candidate on a small sample slice and pick the
        fastest.  Runs OUTSIDE the dispatch span (it blocks on results);
        one-time per (backend, matrix), cached in _AUTOTUNE."""
        sample_L = min(d.shape[1], 1 << 16)
        sample = jnp.asarray(d[:, :sample_L])
        measured: dict[str, float] = {}
        errors: dict[str, str] = {}
        nbytes = int(np.prod(sample.shape))
        for s in self._candidates():
            try:
                run = lambda: jax.block_until_ready(
                    self._dispatch(s, M, sample)
                )
                run()  # compile + warm
                t0 = time.perf_counter()
                run()
                dt = time.perf_counter() - t0
                measured[s] = round(nbytes / max(dt, 1e-9) / 1e9, 3)
            except Exception as e:  # one strategy down ≠ engine down,
                # but the failure must stay visible in the record (the
                # pallas lowering on fresh hardware is the expected case)
                measured[s] = 0.0
                errors[s] = f"{type(e).__name__}: {e}"[:200]
        working = {s: g for s, g in measured.items() if g > 0}
        if not working:
            raise RuntimeError(
                f"EC autotune: every candidate strategy failed: {errors}"
            )
        best = max(working, key=lambda s: working[s])
        _L.inc("autotunes")
        rec = {"strategy": best, "measured_gbps": measured,
               "sample_bytes": nbytes}
        if errors:
            rec["errors"] = errors
        return rec

    # -- entry points ----------------------------------------------------
    def matmul(self, M: np.ndarray, data):
        """Instrumented entry point: spans + compile/dispatch split.  A
        (matrix, shape, strategy) triple not seen by this process before
        pays the jit trace+compile; its wall time books into
        ec.gf_compile_seconds, steady-state calls into
        ec.gf_dispatch_seconds (dispatch only — the host-facing fetch is
        booked separately into ec.gf_fetch_seconds)."""
        M = np.asarray(M, np.uint8)
        on_device = isinstance(data, jax.Array)
        d = data if on_device else jnp.asarray(
            np.asarray(data, np.uint8)
        )
        self._resolved_strategy = self._resolve(M, d)
        out = _gf_acct(self, M, d)
        if on_device:
            return out
        return obs.timed_fetch(_L, "gf", out)

    def matmul_batch(self, M: np.ndarray, data):
        """Batched-stripe matmul: data [N, S, L] -> [N, R, L], one
        dispatch for the whole stripe batch (vmapped over the stripes
        axis; tables/bitmatrices ride as operands, so stripe count N is
        just another shape — no per-stripe retrace)."""
        M = np.asarray(M, np.uint8)
        on_device = isinstance(data, jax.Array)
        d = data if on_device else jnp.asarray(
            np.asarray(data, np.uint8)
        )
        assert d.ndim == 3, d.shape
        self._resolved_strategy = self._resolve(M, d[0])
        out = _gf_batch_acct(self, M, d)
        if on_device:
            return out
        return obs.timed_fetch(_L, "gf_batch", out)

    # -- dispatch (device work only; no host syncs in here) --------------
    def _matmul(self, M: np.ndarray, d):
        with obs.span(
            "ec.gf_dispatch", rows=int(M.shape[0]),
            strategy=self._resolved_strategy,
        ):
            return self._dispatch(self._resolved_strategy, M, d)

    def _matmul_batch(self, M: np.ndarray, d):
        with obs.span(
            "ec.gf_dispatch", rows=int(M.shape[0]), batched=True,
            strategy=self._resolved_strategy,
        ):
            return self._dispatch_batch(self._resolved_strategy, M, d)

    def _dispatch(self, strategy: str, M: np.ndarray, d):
        S, L = d.shape
        if strategy == "logexp":
            gft = gf_device_tables()
            return _matmul_logexp(self._logexp_tuple(M), d,
                                  gft["exp"], gft["log"])
        if strategy in ("xor", "xor_cse"):
            sched = build_schedule(M)
            use_cse = strategy == "xor_cse"
            fn = _ec_cached(
                ("xor", sched.key, use_cse, False),
                lambda: jax.jit(xor_schedule_fn(sched, use_cse)),
            )
            return fn(d)
        B = self._bitmat(M)
        R = M.shape[0]
        if strategy == "pallas":
            ptile = _PALLAS_TILE
            if L % ptile == 0 and L >= ptile:
                return gf_matmul_pallas(B, d, R, tile=ptile)
            # ragged tail: pad to a tile multiple (pads are zeros; GF
            # linearity makes padded parity columns zeros too)
            Lp = -(-L // ptile) * ptile
            dpad = jnp.pad(d, ((0, 0), (0, Lp - L)))
            return gf_matmul_pallas(B, dpad, R, tile=ptile)[:, :L]
        # bitplane
        if L <= self.tile:
            return _matmul_bitplane(B, d, R)
        # tile the byte axis; pad L up to a tile multiple
        T = (L + self.tile - 1) // self.tile
        pad = T * self.tile - L
        dpad = jnp.pad(d, ((0, 0), (0, pad)))
        tiles = dpad.reshape(S, T, self.tile).transpose(1, 0, 2)
        out = jax.lax.map(
            lambda t: _matmul_bitplane(B, t, R), tiles
        )  # [T, R, tile]
        out = out.transpose(1, 0, 2).reshape(R, T * self.tile)
        return out[:, :L]

    def _dispatch_batch(self, strategy: str, M: np.ndarray, d):
        N, S, L = d.shape
        R = M.shape[0]
        if strategy == "pallas":
            # per-stripe independence: the stripes axis folds into the
            # byte axis, one kernel launch covers the whole batch
            flat = d.transpose(1, 0, 2).reshape(S, N * L)
            out = self._dispatch(strategy, M, flat)
            return out.reshape(R, N, L).transpose(1, 0, 2)
        if strategy == "logexp":
            gft = gf_device_tables()
            mt = self._logexp_tuple(M)  # plain tuple: the cached
            # executable must not close over the engine instance
            fn = _ec_cached(
                ("logexp", self._key(M), None, True),
                lambda: jax.jit(jax.vmap(
                    lambda dd, exp, log: _matmul_logexp(
                        mt, dd, exp, log
                    ),
                    in_axes=(0, None, None),
                )),
            )
            return fn(d, gft["exp"], gft["log"])
        if strategy in ("xor", "xor_cse"):
            sched = build_schedule(M)
            use_cse = strategy == "xor_cse"
            fn = _ec_cached(
                ("xor", sched.key, use_cse, True),
                lambda: jax.jit(
                    jax.vmap(xor_schedule_fn(sched, use_cse))
                ),
            )
            return fn(d)
        # bitplane: vmap over stripes while the whole batch's bit
        # expansion stays under the `tile` bound; beyond it, fold the
        # stripes axis into the byte axis so the single-stripe lax.map
        # tiling keeps peak memory O(tile) (stripes are independent, so
        # the fold is exact)
        if N * L > self.tile:
            flat = d.transpose(1, 0, 2).reshape(S, N * L)
            out = self._dispatch(strategy, M, flat)
            return out.reshape(R, N, L).transpose(1, 0, 2)
        B = self._bitmat(M)
        fn = _ec_cached(
            ("bitplane", (R, S), None, True),
            lambda: jax.jit(
                jax.vmap(_matmul_bitplane, in_axes=(None, 0, None)),
                static_argnums=(2,),
            ),
        )
        return fn(B, d, R)
