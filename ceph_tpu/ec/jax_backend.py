"""TPU engine for GF(2^8) chunk math.

Two compiled strategies for parity = C·data over GF(2^8):

1. **Bit-plane MXU matmul** (default on TPU): expand C to its (8m × 8k)
   GF(2) bit-matrix (any GF(2^8) constant multiply is GF(2)-linear on the
   byte's bits — the same fact behind jerasure's bitmatrix schedules),
   unpack data bytes to bit rows, and compute parity bits as an int8 matmul
   mod 2 on the MXU, then repack.  This turns erasure coding into dense
   matrix multiply — the op the TPU is built for — instead of the reference's
   table-lookup SIMD loops (isa-l ec_encode_data, reference
   src/erasure-code/isa/ErasureCodeIsa.cc:120-149).

2. **log/antilog VPU path**: parity bytes via exp[log C + log data] gathers,
   XOR-reduced over k.  Fewer memory blowups; wins for tiny stripes.

The byte axis is tiled with lax.map so the 8× bit expansion never
materializes for more than one tile.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu import obs
from ceph_tpu.ec.gf import GF_LOG, gf_device_tables, matrix_to_bitmatrix

_BIT_TILE = 1 << 17  # bytes per lane-tile in the bitplane path

_L = obs.logger_for("ec")


def _matmul_key(eng, M, data) -> tuple:
    """Warm-key granularity mirrors the actual jit caches: bitplane /
    pallas trace on array shapes only (the bitmatrix is a traced
    operand), while logexp passes the matrix as a static tuple and
    recompiles per content."""
    mat_key = eng._key(M) if eng.strategy == "logexp" else M.shape
    return (mat_key, np.shape(data), eng.strategy)


# Module-level (one shared warm set) because the jit caches it models
# (_matmul_bitplane etc.) are also process-global: a second JaxEngine's
# first call on a warm shape is a dispatch, not a compile.
_gf_acct = obs.JitAccount(
    lambda eng, M, data: eng._matmul(M, data), _L, "gf",
    key_fn=_matmul_key,
    span="ec.gf_matmul",
    span_args=lambda eng, M, data: {
        "rows": int(M.shape[0]),
        "bytes": int(np.prod(np.shape(data))),
        "strategy": eng.strategy,
    },
)


@partial(jax.jit, static_argnums=(2,))
def _matmul_bitplane(Bbits, data, n_out):
    """Bbits: int8[8R, 8S] GF(2) matrix; data: uint8[S, L]."""
    S, L = data.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (
        (data[:, None, :] >> shifts[None, :, None]) & 1
    ).astype(jnp.int8).reshape(8 * S, L)
    acc = jax.lax.dot(
        Bbits, bits, preferred_element_type=jnp.int32
    )  # [8R, L]
    acc = (acc & 1).astype(jnp.uint8).reshape(n_out, 8, L)
    weights = (jnp.uint8(1) << shifts)[None, :, None]
    return jnp.sum(acc * weights, axis=1, dtype=jnp.uint8)


@partial(jax.jit, static_argnums=(0,))
def _matmul_logexp(M_tuple, data, exp, log):
    """M as a static tuple of rows of ints; data: uint8[S, L].  The
    log/exp tables are OPERANDS (gf_device_tables: one device_put per
    backend) — as trace constants they were re-embedded and re-uploaded
    on every per-matrix retrace of this kernel."""
    logd = log[data]  # [S, L]
    nz = data != 0
    rows = []
    for row in M_tuple:
        acc = jnp.zeros(data.shape[1], jnp.uint8)
        for j, c in enumerate(row):
            if c == 0:
                continue
            lc = int(GF_LOG[c])
            prod = exp[lc + logd[j]]
            acc = acc ^ jnp.where(nz[j], prod, 0)
        rows.append(acc)
    return jnp.stack(rows)


def gf_matmul_pallas(Bbits, data, n_out: int, tile: int = 4096):
    """Fused Pallas TPU kernel: parity = (GF(2) bit-matrix) · data.

    The pure-XLA bitplane path materializes the 8× bit expansion in HBM
    (8S·L i8 written + read back around the matmul).  This kernel tiles
    the byte axis into VMEM blocks and performs unpack → MXU matmul →
    mod-2 repack entirely in VMEM, so HBM traffic is exactly data-in +
    parity-out.  bf16 is exact here: bit operands are 0/1 and the MXU
    accumulates bf16 products in f32 (sums <= 8S << 2^24).

    Matches the role of isa-l's ec_encode_data SIMD loops (reference
    src/erasure-code/isa/ErasureCodeIsa.cc:120-149) as the engine's
    innermost hot op.
    """
    from jax.experimental import pallas as pl

    S, L = data.shape
    R8 = Bbits.shape[0]
    assert L % tile == 0, (L, tile)

    def kernel(b_ref, d_ref, o_ref):
        d = d_ref[...]  # u8 [S, tile]
        shifts = jnp.arange(8, dtype=jnp.uint8)
        bits = ((d[:, None, :] >> shifts[None, :, None]) & 1).astype(
            jnp.bfloat16
        ).reshape(8 * S, tile)
        acc = jnp.dot(
            b_ref[...].astype(jnp.bfloat16), bits,
            preferred_element_type=jnp.float32,
        )  # [8R, tile]
        accb = acc.astype(jnp.int32) & 1
        accb = accb.reshape(n_out, 8, tile)
        weights = (1 << jnp.arange(8, dtype=jnp.int32))[None, :, None]
        o_ref[...] = jnp.sum(accb * weights, axis=1).astype(jnp.uint8)

    return pl.pallas_call(
        kernel,
        grid=(L // tile,),
        in_specs=[
            pl.BlockSpec((R8, 8 * S), lambda i: (0, 0)),
            pl.BlockSpec((S, tile), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((n_out, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((n_out, L), jnp.uint8),
        interpret=jax.default_backend() == "cpu",  # CI runs the same kernel
    )(Bbits, data)


class JaxEngine:
    """Device GF matmul engine: M u8[R,S] × data u8[S,L] -> u8[R,L].

    Device constants (the GF(2) bit-matrix of M) are cached per matrix —
    the engine is reused across calls with the same code matrix (encode,
    repeated decode) without re-deriving or re-uploading anything.  When
    `data` is already a jax array the result STAYS on device (no host
    round-trip); numpy in → numpy out for the host-facing plugin API.
    """

    def __init__(self, strategy: str | None = None, tile: int = _BIT_TILE):
        from ceph_tpu.utils import ensure_jax_backend

        ensure_jax_backend()
        if strategy is None:
            strategy = (
                "pallas"
                if jax.default_backend() not in ("cpu",)
                else "logexp"
            )
        assert strategy in ("pallas", "bitplane", "logexp")
        self.strategy = strategy
        self.tile = tile
        self._bitmats: dict[tuple, jnp.ndarray] = {}
        self._logexp_cache: dict[tuple, tuple] = {}

    @staticmethod
    def _key(M: np.ndarray):
        return (M.shape, M.tobytes())

    def _bitmat(self, M: np.ndarray):
        key = self._key(M)
        B = self._bitmats.get(key)
        if B is None:
            B = jnp.asarray(matrix_to_bitmatrix(M).astype(np.int8))
            self._bitmats[key] = B
        return B

    def matmul(self, M: np.ndarray, data):
        """Instrumented entry point: spans + compile/dispatch split.  A
        (matrix, shape, strategy) triple not seen by this process before
        pays the jit trace+compile; its wall time books into
        ec.gf_compile_seconds, steady-state calls into
        ec.gf_dispatch_seconds (dispatch only — device completion is the
        caller's fetch)."""
        M = np.asarray(M, np.uint8)
        return _gf_acct(self, M, data)

    def _matmul(self, M: np.ndarray, data):
        on_device = isinstance(data, jax.Array)
        d = data if on_device else jnp.asarray(data, jnp.uint8)
        S, L = d.shape

        def finish(out):
            return out if on_device else np.asarray(out)

        if self.strategy == "logexp":
            key = self._key(M)
            mt = self._logexp_cache.get(key)
            if mt is None:
                mt = tuple(tuple(int(c) for c in r) for r in M)
                self._logexp_cache[key] = mt
            gft = gf_device_tables()
            return finish(_matmul_logexp(mt, d, gft["exp"], gft["log"]))
        B = self._bitmat(M)
        R = M.shape[0]
        if self.strategy == "pallas":
            ptile = 1 << 12
            if L % ptile == 0 and L >= ptile:
                return finish(gf_matmul_pallas(B, d, R, tile=ptile))
            # ragged tail: pad to a tile multiple (pads are zeros; GF
            # linearity makes padded parity columns zeros too)
            Lp = -(-L // ptile) * ptile
            dpad = jnp.pad(d, ((0, 0), (0, Lp - L)))
            return finish(gf_matmul_pallas(B, dpad, R, tile=ptile)[:, :L])
        if L <= self.tile:
            return finish(_matmul_bitplane(B, d, R))
        # tile the byte axis; pad L up to a tile multiple
        T = (L + self.tile - 1) // self.tile
        pad = T * self.tile - L
        dpad = jnp.pad(d, ((0, 0), (0, pad)))
        tiles = dpad.reshape(S, T, self.tile).transpose(1, 0, 2)
        out = jax.lax.map(
            lambda t: _matmul_bitplane(B, t, R), tiles
        )  # [T, R, tile]
        out = out.transpose(1, 0, 2).reshape(R, T * self.tile)
        return finish(out[:, :L])
