"""TPU engine for GF(2^8) chunk math.

Two compiled strategies for parity = C·data over GF(2^8):

1. **Bit-plane MXU matmul** (default on TPU): expand C to its (8m × 8k)
   GF(2) bit-matrix (any GF(2^8) constant multiply is GF(2)-linear on the
   byte's bits — the same fact behind jerasure's bitmatrix schedules),
   unpack data bytes to bit rows, and compute parity bits as an int8 matmul
   mod 2 on the MXU, then repack.  This turns erasure coding into dense
   matrix multiply — the op the TPU is built for — instead of the reference's
   table-lookup SIMD loops (isa-l ec_encode_data, reference
   src/erasure-code/isa/ErasureCodeIsa.cc:120-149).

2. **log/antilog VPU path**: parity bytes via exp[log C + log data] gathers,
   XOR-reduced over k.  Fewer memory blowups; wins for tiny stripes.

The byte axis is tiled with lax.map so the 8× bit expansion never
materializes for more than one tile.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.ec.gf import GF_EXP, GF_LOG, matrix_to_bitmatrix

_BIT_TILE = 1 << 17  # bytes per lane-tile in the bitplane path


@partial(jax.jit, static_argnums=(2,))
def _matmul_bitplane(Bbits, data, n_out):
    """Bbits: int8[8R, 8S] GF(2) matrix; data: uint8[S, L]."""
    S, L = data.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (
        (data[:, None, :] >> shifts[None, :, None]) & 1
    ).astype(jnp.int8).reshape(8 * S, L)
    acc = jax.lax.dot(
        Bbits, bits, preferred_element_type=jnp.int32
    )  # [8R, L]
    acc = (acc & 1).astype(jnp.uint8).reshape(n_out, 8, L)
    weights = (jnp.uint8(1) << shifts)[None, :, None]
    return jnp.sum(acc * weights, axis=1, dtype=jnp.uint8)


@partial(jax.jit, static_argnums=(0,))
def _matmul_logexp(M_tuple, data):
    """M as a static tuple of rows of ints; data: uint8[S, L]."""
    exp = jnp.asarray(GF_EXP)  # [512]
    log = jnp.asarray(np.where(np.arange(256) == 0, 0, GF_LOG).astype(np.int32))
    logd = log[data]  # [S, L]
    nz = data != 0
    rows = []
    for row in M_tuple:
        acc = jnp.zeros(data.shape[1], jnp.uint8)
        for j, c in enumerate(row):
            if c == 0:
                continue
            lc = int(GF_LOG[c])
            prod = exp[lc + logd[j]]
            acc = acc ^ jnp.where(nz[j], prod, 0)
        rows.append(acc)
    return jnp.stack(rows)


class JaxEngine:
    """Device GF matmul engine: M u8[R,S] × data u8[S,L] -> u8[R,L]."""

    def __init__(self, strategy: str | None = None, tile: int = _BIT_TILE):
        from ceph_tpu.utils import ensure_jax_backend

        ensure_jax_backend()
        if strategy is None:
            strategy = (
                "bitplane"
                if jax.default_backend() != "cpu"
                else "logexp"
            )
        assert strategy in ("bitplane", "logexp")
        self.strategy = strategy
        self.tile = tile

    def matmul(self, M: np.ndarray, data) -> np.ndarray:
        M = np.asarray(M, np.uint8)
        d = jnp.asarray(data, jnp.uint8)
        S, L = d.shape
        if self.strategy == "logexp":
            out = _matmul_logexp(tuple(tuple(int(c) for c in r) for r in M), d)
            return np.asarray(out)
        B = jnp.asarray(matrix_to_bitmatrix(M).astype(np.int8))
        R = M.shape[0]
        if L <= self.tile:
            return np.asarray(_matmul_bitplane(B, d, R))
        # tile the byte axis; pad L up to a tile multiple
        T = (L + self.tile - 1) // self.tile
        pad = T * self.tile - L
        dpad = jnp.pad(d, ((0, 0), (0, pad)))
        tiles = dpad.reshape(S, T, self.tile).transpose(1, 0, 2)
        out = jax.lax.map(
            lambda t: _matmul_bitplane(B, t, R), tiles
        )  # [T, R, tile]
        out = out.transpose(1, 0, 2).reshape(R, T * self.tile)
        return np.asarray(out[:, :L])
