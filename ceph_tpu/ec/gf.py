"""GF(2^8) arithmetic — the field under every Reed–Solomon code here.

The reference delegates GF math to out-of-tree libraries (gf-complete /
isa-l, vendored as *empty* submodules — reference .gitmodules:7-16), so this
framework owns the field arithmetic.  Field: GF(2^8) with the primitive
polynomial x^8+x^4+x^3+x^2+1 (0x11D), generator α=2 — the conventional RS
field used by jerasure's w=8 default (reference
src/erasure-code/jerasure/ErasureCodeJerasure.h:89-91 pins w=8) and isa-l.

Host side (numpy): log/antilog tables, scalar ops, matrix multiply/invert —
used for code construction and the tiny decode-matrix inversions.
Device side: see ec.jax_backend (bit-plane MXU matmul / log-table VPU path).
"""

from __future__ import annotations

import numpy as np

PRIM_POLY = 0x11D
FIELD = 256


def _build_tables():
    exp = np.zeros(512, np.uint8)  # doubled so exp[log a + log b] works
    log = np.zeros(256, np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= PRIM_POLY
    exp[255:510] = exp[:255]
    log[0] = 512  # sentinel: exp[>=510] unused; callers mask zero operands
    return exp, log


GF_EXP, GF_LOG = _build_tables()

# full 256x256 multiplication table (64 KiB) — handy for vectorized host ops
_a = np.arange(256)
_nz = (_a[:, None] != 0) & (_a[None, :] != 0)
GF_MUL_TABLE = np.where(
    _nz,
    GF_EXP[(GF_LOG[_a][:, None] + GF_LOG[_a][None, :]) % 255],
    0,
).astype(np.uint8)
del _a, _nz


def gf_mul(a, b):
    """Element-wise GF(2^8) product (numpy, any broadcastable shapes)."""
    a = np.asarray(a, np.uint8)
    b = np.asarray(b, np.uint8)
    return GF_MUL_TABLE[a, b]


def gf_xtime(x: np.ndarray) -> np.ndarray:
    """Element-wise doubling (·2) in GF(2^8)/0x11D, branch-free:
    (x<<1) ^ (0x1D masked by bit 7 via arithmetic shift).  The host
    twin of the device executor's `_xtime` (ec.jax_backend) — the XOR
    schedules' only non-XOR primitive."""
    x = np.asarray(x, np.uint8)
    mask = ((x.astype(np.int8) >> 7).astype(np.uint8)) & np.uint8(0x1D)
    return ((x << 1).astype(np.uint8)) ^ mask


def gf_inv(a):
    a = int(a)
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of 0")
    return int(GF_EXP[255 - GF_LOG[a]])


def gf_div(a, b):
    a, b = int(a), int(b)
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by 0")
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] - GF_LOG[b]) % 255])


def gf_pow(a, n):
    a, n = int(a), int(n)
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] * n) % 255])


def gf_matmul(A, B):
    """GF(2^8) matrix product: (n,k)·(k,m) uint8 -> (n,m) uint8.
    XOR-accumulate of table products; fine for the small code matrices."""
    A = np.asarray(A, np.uint8)
    B = np.asarray(B, np.uint8)
    prod = GF_MUL_TABLE[A[:, :, None], B[None, :, :]]  # (n,k,m)
    out = np.zeros((A.shape[0], B.shape[1]), np.uint8)
    for j in range(A.shape[1]):
        out ^= prod[:, j, :]
    return out


def gf_matvec_data(M, data):
    """(m,k) code matrix × (k,L) data bytes -> (m,L) parity bytes (host)."""
    M = np.asarray(M, np.uint8)
    data = np.asarray(data, np.uint8)
    out = np.zeros((M.shape[0], data.shape[1]), np.uint8)
    for j in range(M.shape[1]):
        out ^= GF_MUL_TABLE[M[:, j][:, None], data[j][None, :]]
    return out


def gf_invert_matrix(M):
    """Gauss–Jordan inversion over GF(2^8).  Raises on singular input.
    (The decode-matrix inversion of jerasure_matrix_decode — tiny k×k,
    stays on host by design; see SURVEY §7 step 7.)"""
    M = np.array(M, np.uint8)
    n = M.shape[0]
    assert M.shape == (n, n)
    aug = np.concatenate([M, np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        piv = col + int(np.argmax(aug[col:, col] != 0))
        if aug[piv, col] == 0:
            raise np.linalg.LinAlgError("singular GF(2^8) matrix")
        if piv != col:
            aug[[col, piv]] = aug[[piv, col]]
        inv = gf_inv(aug[col, col])
        aug[col] = GF_MUL_TABLE[aug[col], inv]
        for r in range(n):
            if r != col and aug[r, col]:
                aug[r] ^= GF_MUL_TABLE[aug[r, col], aug[col]]
    return aug[:, n:]


# -- device-resident table cache --------------------------------------------

_DEV_TABLES: dict[str, dict] = {}  # jax backend name -> device arrays


def gf_device_tables() -> dict:
    """GF(2^8) log/exp/mul tables as DEVICE arrays, uploaded once per jax
    backend and shared by every engine/kernel in the process.  Device
    kernels take these as runtime operands (ec.jax_backend._matmul_logexp)
    instead of re-embedding the tables as trace constants per code
    matrix — one device_put total, zero per-call re-upload.  Keys:
    `exp` u8[512], `log` i32[256] (log[0] = 0 sentinel; callers mask zero
    operands), `mul` u8[256, 256]."""
    import jax
    import jax.numpy as jnp

    b = jax.default_backend()
    t = _DEV_TABLES.get(b)
    if t is None:
        t = {
            "exp": jnp.asarray(GF_EXP),
            "log": jnp.asarray(
                np.where(np.arange(256) == 0, 0, GF_LOG).astype(np.int32)
            ),
            "mul": jnp.asarray(GF_MUL_TABLE),
        }
        _DEV_TABLES[b] = t
    return t


# -- bit-plane (GF(2)) representation ---------------------------------------
# Multiplication by a constant c is GF(2)-linear on the 8 bits of the input
# byte, so any GF(2^8) code matrix expands to a bit-matrix over GF(2); this
# is how jerasure's bitmatrix techniques work and — more importantly here —
# how encode becomes a plain 0/1 matmul that runs on the TPU MXU
# (ec.jax_backend).

def gf_bitmatrix(c: int) -> np.ndarray:
    """8×8 GF(2) matrix of y = c·x: column j = bits of c·2^j."""
    cols = [int(GF_MUL_TABLE[c, 1 << j]) for j in range(8)]
    out = np.zeros((8, 8), np.uint8)
    for j, v in enumerate(cols):
        for i in range(8):
            out[i, j] = (v >> i) & 1
    return out


def matrix_to_bitmatrix(M: np.ndarray, w: int = 8) -> np.ndarray:
    """(m,k) GF(2^8) matrix -> (8m, 8k) GF(2) matrix (jerasure
    jerasure_matrix_to_bitmatrix semantics for w=8)."""
    assert w == 8
    M = np.asarray(M, np.uint8)
    m, k = M.shape
    out = np.zeros((8 * m, 8 * k), np.uint8)
    for i in range(m):
        for j in range(k):
            out[8 * i : 8 * i + 8, 8 * j : 8 * j + 8] = gf_bitmatrix(
                int(M[i, j])
            )
    return out
