"""XOR-schedule compiler: lower a GF(2^8) code matrix to an XOR DAG.

Per "Accelerating XOR-based Erasure Coding using Program Optimization
Techniques" (PAPERS.md), any GF(2^8) matrix multiply C·data decomposes
into pure XORs of *byte rows*: C[r,i]·x = XOR over the set bits j of
C[r,i] of (2^j·x), and 2^j·x is j applications of the carry-reduced
doubling `xtime`.  So parity row r is an XOR of "virtual rows"
v[8i+j] = 2^j·data[i], with the term set read straight off the GF(2)
bit-matrix (gf.matrix_to_bitmatrix: bit j of C[r,i] is B[8r+j, 8i]).

This module lowers a matrix ONCE — at profile-registration time — into
an `XorSchedule`:

- `terms`: the naive per-output term lists (the bitmatrix rows), and
- `ops` / `outs`: the same program after greedy pairwise common-
  subexpression elimination (Paar's algorithm): the pair of operands
  shared by the most outputs becomes a temp, repeat to fixpoint.  For
  RS(8,4) reed_sol_van this cuts 106 XORs to ~63.

Schedules are purely structural — a function of the matrix bytes only —
so they cache by matrix key (`_SCHEDULES`) and the *executables* built
from them key into the module-level `_EC_CACHE` in ec.jax_backend
exactly like the pipeline's `_PIPE_CACHE`: one compile per
(matrix, stripe-shape) — every stripe and every repeat of an erasure
pattern after the first rides a cached executable.

Which form runs where is an engine/autotune decision (ec.jax_backend):
XLA fuses the naive form into one pass over the data (recompute is
free inside a fusion), while the CSE form materializes temps — faster
only where temps are cheaper than recompute (host executor, native
engines, small cache-resident tiles).  Both forms are bit-exact by
construction; `host_apply` executes the CSE DAG in numpy and is the
oracle the tests pin both against.

This module is jax-free: the compiler runs in jax-free entry points
(profile parsing) and the device lowering lives in ec.jax_backend.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field

import numpy as np

from ceph_tpu import obs
from ceph_tpu.ec.gf import gf_xtime, matrix_to_bitmatrix

_L = obs.logger_for("ec")
_L.add_u64("xor_schedules_built", "XOR DAG lowerings (one per new matrix)")
_L.add_u64("xor_schedule_cache_hits",
           "schedule requests served from _SCHEDULES")


def matrix_key(M: np.ndarray) -> tuple:
    """Structural identity of a code matrix (shape + content bytes)."""
    M = np.asarray(M, np.uint8)
    return (M.shape, M.tobytes())


@dataclass(frozen=True)
class XorSchedule:
    """Compiled XOR program for parity = M·data over virtual byte rows.

    Virtual row ids: 0..8k-1 are inputs (id 8i+j ≡ 2^j·data[i]); ids
    >= 8k are CSE temps in `ops` order.  `terms[r]` is the naive term
    list of output r; `outs[r]` the residual list after CSE (may
    reference temp ids)."""

    shape: tuple            # (m, k) of the source matrix
    key: tuple              # matrix_key(M) — the structural cache key
    terms: tuple            # tuple[tuple[int, ...]] naive per-output
    ops: tuple              # tuple[(temp_id, a, b)] CSE temps
    outs: tuple             # tuple[tuple[int, ...]] post-CSE per-output
    max_power: tuple = field(default=())  # per input i: highest j used

    @property
    def n_inputs(self) -> int:
        return 8 * self.shape[1]

    @property
    def n_xors_naive(self) -> int:
        return sum(max(len(t) - 1, 0) for t in self.terms)

    @property
    def n_xors_cse(self) -> int:
        return len(self.ops) + sum(max(len(t) - 1, 0) for t in self.outs)

    def stats(self) -> dict:
        """BENCH/PROFILE record: how much the lowering saved."""
        return {
            "outputs": self.shape[0],
            "inputs": self.shape[1],
            "xors_naive": self.n_xors_naive,
            "xors_cse": self.n_xors_cse,
            "temps": len(self.ops),
        }


def bit_terms(M: np.ndarray) -> list[list[int]]:
    """Naive term lists: output r reads virtual row 8i+j iff bit j of
    M[r,i] — i.e. iff matrix_to_bitmatrix(M)[8r+j, 8i] (first column of
    each 8-wide block holds the bits of the untwisted constant)."""
    M = np.asarray(M, np.uint8)
    B = matrix_to_bitmatrix(M)
    m, k = M.shape
    return [
        [8 * i + j for i in range(k) for j in range(8) if B[8 * r + j, 8 * i]]
        for r in range(m)
    ]


def _paar_cse(term_sets: list[set[int]], next_id: int):
    """Greedy pairwise CSE (Paar): factor out the operand pair shared by
    the most outputs until no pair repeats.  Deterministic tie-break on
    the lowest pair so schedules are stable across runs."""
    ops: list[tuple[int, int, int]] = []
    while True:
        cnt: Counter = Counter()
        for s in term_sets:
            rs = sorted(s)
            for x in range(len(rs)):
                for y in range(x + 1, len(rs)):
                    cnt[(rs[x], rs[y])] += 1
        if not cnt:
            break
        (a, b), c = min(
            cnt.items(), key=lambda t: (-t[1], t[0][0], t[0][1])
        )
        if c < 2:
            break
        ops.append((next_id, a, b))
        for s in term_sets:
            if a in s and b in s:
                s -= {a, b}
                s.add(next_id)
        next_id += 1
    return ops, [tuple(sorted(s)) for s in term_sets]


_SCHEDULES: dict[tuple, XorSchedule] = {}


def build_schedule(M: np.ndarray) -> XorSchedule:
    """Lower M to its XOR schedule, cached per matrix content — the
    "derive once per profile" step; decode plans reuse it per erasure
    pattern because their recover matrices are matrices too."""
    key = matrix_key(M)
    sched = _SCHEDULES.get(key)
    if sched is not None:
        _L.inc("xor_schedule_cache_hits")
        return sched
    terms = bit_terms(M)
    m, k = np.asarray(M).shape
    ops, outs = _paar_cse([set(t) for t in terms], 8 * k)
    used = {t for term in terms for t in term}
    max_power = tuple(
        max((j for j in range(8) if 8 * i + j in used), default=0)
        for i in range(k)
    )
    sched = XorSchedule(
        shape=(int(m), int(k)), key=key,
        terms=tuple(tuple(t) for t in terms),
        ops=tuple(ops), outs=tuple(outs), max_power=max_power,
    )
    _SCHEDULES[key] = sched
    _L.inc("xor_schedules_built")
    return sched


def host_apply(sched: XorSchedule, data: np.ndarray) -> np.ndarray:
    """Execute the CSE DAG on host (numpy).  Bit-exact oracle for the
    device executors and a direct correctness proof of the CSE pass
    (it runs `ops`/`outs`, not the naive `terms`)."""
    data = np.asarray(data, np.uint8)
    m, k = sched.shape
    assert data.shape[0] == k, (data.shape, sched.shape)
    vals: dict[int, np.ndarray] = {}
    for i in range(k):
        v = data[i]
        vals[8 * i] = v
        for j in range(1, sched.max_power[i] + 1):
            v = gf_xtime(v)
            vals[8 * i + j] = v
    for tid, a, b in sched.ops:
        vals[tid] = vals[a] ^ vals[b]
    out = np.zeros((m,) + data.shape[1:], np.uint8)
    for r, term in enumerate(sched.outs):
        acc = None
        for t in term:
            acc = vals[t] if acc is None else acc ^ vals[t]
        if acc is not None:
            out[r] = acc
    return out
