"""Clay (coupled-layer) MSR regenerating code.

Re-implementation of the reference's clay plugin semantics (reference
src/erasure-code/clay/ErasureCodeClay.{h,cc}): an (k, m, d) MSR code built
by coupling q^t "layers" (planes) of an inner scalar MDS code, where
q = d-k+1, t = ceil((k+m)/q), nu = q*t-(k+m) virtual shortened nodes, and
every chunk splits into sub_chunk_no = q^t sub-chunks.  Single-chunk repair
contacts d helpers and reads only a 1/q fraction of each — the
minimum-bandwidth property (reference minimum_to_repair :325,
get_repair_subchunks :360).

Structure of this port (array-first, not buffer-slice-first):
- chunks live as numpy arrays [sub_chunk_no, sc_size] per node id in the
  padded q*t grid (external chunk i ↔ node i for data, i+nu for parity);
- the pair-wise coupling (reference's "pft" jerasure k=2,m=2 code,
  get_{coupled,uncoupled}_* :814-871) is a (2,2) RS code over the 4-tuple
  [c_lo, c_hi, u_lo, u_hi]: any two symbols determine the rest;
- the inner MDS across a plane (decode_uncoupled :742) is our RS(k+nu, m)
  vandermonde code;
- decode_layered (:647) walks planes in intersection-score order, exactly
  the reference's schedule.

The per-plane math vectorizes over the sub-chunk byte axis; every pair /
MDS operation is a GF(2^8) matmul over [*, sc_size] arrays, so the whole
decode runs as batched table ops (and rides the same engines as ec.rs).
"""

from __future__ import annotations

import numpy as np

from ceph_tpu import obs
from ceph_tpu.ec import matrices
from ceph_tpu.ec.gf import GF_MUL_TABLE, gf_matvec_data
from ceph_tpu.ec.interface import ErasureCode, ErasureCodeProfileError

_L = obs.logger_for("ec")
_L.add_u64("bytes_encoded", "stripe bytes pushed through encode_chunks")
_L.add_u64("bytes_decoded", "chunk bytes rebuilt by decode_chunks")
_L.add_time_avg("encode_seconds", "encode_chunks wall time")
_L.add_time_avg("decode_seconds", "decode_chunks wall time")
_L.add_u64("repair_bytes", "chunk bytes rebuilt by minimum-bandwidth repair")
_L.add_time_avg("repair_seconds", "repair wall time")
_L.add_avg("repair_read_fraction",
           "helper bytes read / full-stripe bytes, per repair")
_L.add_u64("repair_plan_hits",
           "batched repairs served by a cached product-matrix plan")
_L.add_u64("repair_plan_misses",
           "product-matrix repair plans built (one per lost node)")


def _pow_int(a: int, x: int) -> int:
    return a**x


class _PairTransform:
    """(2,2) RS code over [c_lo, c_hi, u_lo, u_hi]; recovers any 2 missing
    symbols from the other 2 (the reference's pft scalar code)."""

    def __init__(self):
        self.C = matrices.vandermonde_rs(2, 2)

    def recover(
        self, known: dict[int, np.ndarray], want: list[int]
    ) -> list[np.ndarray]:
        present = sorted(known)
        R = matrices.recover_matrix(self.C, present, want)
        stack = np.stack([known[i] for i in present[:2]])
        out = gf_matvec_data(R, stack.reshape(2, -1))
        shp = known[present[0]].shape
        return [row.reshape(shp) for row in out]


class ClayCode(ErasureCode):
    """plugin=clay; profile: k, m, [d=k+m-1], [scalar_mds], [technique]."""

    def __init__(self):
        super().__init__()
        self.d = 0
        self.q = 0
        self.t = 0
        self.nu = 0
        self.sub_chunk_no = 0
        # lost node -> product-matrix repair plan (see _repair_plan)
        self._repair_plans: dict[int, dict] = {}

    # -- profile -----------------------------------------------------------
    def parse(self, profile: dict) -> None:
        self.k, self.m = 4, 2  # reference DEFAULT_K/DEFAULT_M
        super().parse(profile)
        k, m = self.k, self.m
        try:
            self.d = int(profile.get("d", k + m - 1))
        except (TypeError, ValueError):
            raise ErasureCodeProfileError("d must be an integer")
        if not (k <= self.d <= k + m - 1):
            raise ErasureCodeProfileError(
                f"value of d {self.d} must be within [{k},{k + m - 1}]"
            )
        self.q = self.d - k + 1
        self.nu = (self.q - (k + m) % self.q) % self.q
        if k + m + self.nu > 254:
            raise ErasureCodeProfileError("k+m+nu must be <= 254")
        self.t = (k + m + self.nu) // self.q
        self.sub_chunk_no = _pow_int(self.q, self.t)
        # inner MDS across each plane: (k+nu) data + m parity
        technique = profile.get("technique", "reed_sol_van")
        maker = {
            "reed_sol_van": matrices.vandermonde_rs,
            "cauchy_orig": matrices.cauchy_orig,
            "cauchy_good": matrices.cauchy_good,
            "cauchy": matrices.isa_cauchy,
        }.get(technique)
        if maker is None:
            raise ErasureCodeProfileError(
                f"clay: unsupported technique {technique!r}"
            )
        self.mds_C = maker(k + self.nu, m)
        self.pft = _PairTransform()
        from ceph_tpu.ec.rs import get_engine

        self.engine = get_engine(
            profile.get("backend", "numpy"), profile.get("strategy")
        )
        self._repair_plans.clear()  # geometry may have changed

    def get_sub_chunk_count(self) -> int:
        return self.sub_chunk_no

    def get_alignment(self) -> int:
        # sub_chunk_no * k * inner alignment (reference get_chunk_size)
        return self.sub_chunk_no * self.k * self.w * 4

    # -- plane geometry ----------------------------------------------------
    def _z_vec(self, z: int) -> list[int]:
        """base-q digits of z, most-significant first (reference
        get_plane_vector :888)."""
        v = [0] * self.t
        for i in range(self.t):
            v[self.t - 1 - i] = z % self.q
            z //= self.q
        return v

    def _z_sw(self, z: int, x: int, y: int, z_vec: list[int]) -> int:
        return z + (x - z_vec[y]) * _pow_int(self.q, self.t - 1 - y)

    # -- pairwise coupling helpers ----------------------------------------
    # canonical 4-tuple: positions 0/2 = coupled/uncoupled of the pair
    # node with LARGER x, 1/3 = the smaller-x node (the reference's
    # i0..i3 swap when z_vec[y] > x)
    def _pair_indices(self, x: int, zy: int) -> tuple[int, int, int, int]:
        """returns positions (c_xy, c_sw, u_xy, u_sw) in the 4-tuple."""
        if zy > x:
            return 1, 0, 3, 2
        return 0, 1, 2, 3

    # -- inner MDS over a plane -------------------------------------------
    def _mds_recover(
        self,
        U: dict[int, np.ndarray],
        z: int,
        erased: set[int],
    ) -> None:
        """decode_uncoupled (reference :742): recover U[erased][z] from the
        other nodes' U[z]."""
        n = self.q * self.t
        present = sorted(set(range(n)) - erased)[: self.k + self.nu]
        missing = sorted(erased)
        R = matrices.recover_matrix(self.mds_C, present, missing)
        stack = np.stack([U[i][z] for i in present])
        out = gf_matvec_data(R, stack)
        for row, i in zip(out, missing):
            U[i][z] = row

    # -- layered decode (reference decode_layered :647) --------------------
    def _decode_layered(
        self, erased: set[int], chunks: dict[int, np.ndarray]
    ) -> None:
        q, t, m = self.q, self.t, self.m
        n = q * t
        erased = set(erased)
        for i in range(self.k + self.nu, n):
            if len(erased) >= m:
                break
            erased.add(i)
        assert len(erased) == m

        sc_shape = chunks[0].shape[1:]
        U = {
            i: np.zeros((self.sub_chunk_no,) + sc_shape, np.uint8)
            for i in range(n)
        }

        order = [0] * self.sub_chunk_no
        for z in range(self.sub_chunk_no):
            zv = self._z_vec(z)
            order[z] = sum(1 for i in erased if i % q == zv[i // q])
        max_score = max(order, default=0)

        for score in range(max_score + 1):
            planes = [z for z in range(self.sub_chunk_no) if order[z] == score]
            for z in planes:
                self._decode_erasures(erased, z, chunks, U)
            for z in planes:
                zv = self._z_vec(z)
                for node_xy in sorted(erased):
                    x, y = node_xy % q, node_xy // q
                    node_sw = y * q + zv[y]
                    if zv[y] != x:
                        if node_sw not in erased:
                            self._recover_type1(chunks, U, x, y, z, zv)
                        elif zv[y] < x:
                            self._coupled_from_uncoupled(
                                chunks, U, x, y, z, zv
                            )
                    else:
                        chunks[node_xy][z] = U[node_xy][z]

    def _decode_erasures(
        self,
        erased: set[int],
        z: int,
        chunks: dict[int, np.ndarray],
        U: dict[int, np.ndarray],
    ) -> None:
        """reference decode_erasures :714: fill U for live nodes, then MDS."""
        q, t = self.q, self.t
        zv = self._z_vec(z)
        for x in range(q):
            for y in range(t):
                node_xy = q * y + x
                node_sw = q * y + zv[y]
                if node_xy in erased:
                    continue
                if zv[y] < x:
                    self._uncoupled_from_coupled(chunks, U, x, y, z, zv)
                elif zv[y] == x:
                    U[node_xy][z] = chunks[node_xy][z]
                else:
                    if node_sw in erased:
                        self._uncoupled_from_coupled(chunks, U, x, y, z, zv)
        self._mds_recover(U, z, erased)

    # the three pair operations (reference :775-871)
    def _recover_type1(self, chunks, U, x, y, z, zv):
        """erased coupled symbol from live partner + own uncoupled."""
        q = self.q
        node_xy, node_sw = y * q + x, y * q + zv[y]
        z_sw = self._z_sw(z, x, y, zv)
        c_xy, c_sw, u_xy, u_sw = self._pair_indices(x, zv[y])
        known = {
            c_sw: chunks[node_sw][z_sw],
            u_xy: U[node_xy][z],
        }
        (rec,) = self.pft.recover(known, [c_xy])
        chunks[node_xy][z] = rec

    def _coupled_from_uncoupled(self, chunks, U, x, y, z, zv):
        """both coupled symbols of the pair from both uncoupled."""
        q = self.q
        node_xy, node_sw = y * q + x, y * q + zv[y]
        z_sw = self._z_sw(z, x, y, zv)
        # no index swap here (reference get_coupled_from_uncoupled asserts
        # zv[y] < x): position 0 ↔ node_xy, 1 ↔ node_sw
        known = {2: U[node_xy][z], 3: U[node_sw][z_sw]}
        rec0, rec1 = self.pft.recover(known, [0, 1])
        chunks[node_xy][z] = rec0
        chunks[node_sw][z_sw] = rec1

    def _uncoupled_from_coupled(self, chunks, U, x, y, z, zv):
        """both uncoupled symbols of the pair from both coupled."""
        q = self.q
        node_xy, node_sw = y * q + x, y * q + zv[y]
        z_sw = self._z_sw(z, x, y, zv)
        c_xy, c_sw, u_xy, u_sw = self._pair_indices(x, zv[y])
        known = {c_xy: chunks[node_xy][z], c_sw: chunks[node_sw][z_sw]}
        rec_lo, rec_hi = self.pft.recover(known, [2, 3])
        rec = {2: rec_lo, 3: rec_hi}
        U[node_xy][z] = rec[u_xy]
        U[node_sw][z_sw] = rec[u_sw]

    # -- node/chunk plumbing ----------------------------------------------
    def _to_nodes(
        self, ext: dict[int, np.ndarray], sc_size: int
    ) -> dict[int, np.ndarray]:
        """external chunk id -> padded node grid ([sub_chunk_no, sc])."""
        n = self.q * self.t
        nodes: dict[int, np.ndarray] = {}
        for i in range(self.k + self.m):
            nid = i if i < self.k else i + self.nu
            if i in ext:
                nodes[nid] = (
                    np.asarray(ext[i], np.uint8)
                    .reshape(self.sub_chunk_no, sc_size)
                    .copy()
                )
            else:
                nodes[nid] = np.zeros(
                    (self.sub_chunk_no, sc_size), np.uint8
                )
        for i in range(self.k, self.k + self.nu):
            nodes[i] = np.zeros((self.sub_chunk_no, sc_size), np.uint8)
        return nodes

    # -- public API --------------------------------------------------------
    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        k, m = self.k, self.m
        cs = data.shape[1]
        assert cs % self.sub_chunk_no == 0, (
            f"chunk size {cs} not a multiple of sub_chunk_no "
            f"{self.sub_chunk_no}"
        )
        with obs.span(
            "ec.clay_encode", k=k, m=m, d=self.d, bytes=int(data.size)
        ), _L.time("encode_seconds"):
            sc = cs // self.sub_chunk_no
            ext = {i: data[i] for i in range(k)}
            nodes = self._to_nodes(ext, sc)
            parity_nodes = {
                i + self.nu for i in range(k, k + m)
            }
            self._decode_layered(parity_nodes, nodes)
            out = np.zeros((k + m, cs), np.uint8)
            for i in range(k + m):
                nid = i if i < k else i + self.nu
                out[i] = nodes[nid].reshape(-1)
        _L.inc("bytes_encoded", int(data.size))
        return out

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: dict[int, np.ndarray],
        chunk_size: int,
    ) -> dict[int, np.ndarray]:
        k, m = self.k, self.m
        if len(chunks) < k:
            raise ValueError(f"cannot decode: {len(chunks)} < k={k}")
        erased = {
            (i if i < k else i + self.nu)
            for i in range(k + m)
            if i not in chunks
        }
        n_missing = len(erased)
        with obs.span(
            "ec.clay_decode", k=k, m=m, missing=n_missing,
            bytes=n_missing * chunk_size,
        ), _L.time("decode_seconds"):
            sc = chunk_size // self.sub_chunk_no
            nodes = self._to_nodes(
                {i: np.asarray(c, np.uint8) for i, c in chunks.items()}, sc
            )
            self._decode_layered(erased, nodes)
            out = dict(chunks)
            for i in range(k + m):
                nid = i if i < k else i + self.nu
                if i not in out:
                    out[i] = nodes[nid].reshape(-1)
        _L.inc("bytes_decoded", n_missing * chunk_size)
        return out

    # -- repair (minimum-bandwidth single-node recovery) -------------------
    def is_repair(
        self, want_to_read: set[int], available: set[int]
    ) -> bool:
        """reference is_repair :305."""
        if want_to_read <= available:
            return False
        if len(want_to_read) > 1:
            return False
        i = next(iter(want_to_read))
        lost = i if i < self.k else i + self.nu
        for x in range(self.q):
            node = (lost // self.q) * self.q + x
            node = node if node < self.k else node - self.nu
            if node != i and node not in available:
                return False
        return len(available) >= self.d

    def get_repair_subchunks(self, lost_node: int) -> list[tuple[int, int]]:
        """(index, count) runs of the 1/q sub-chunks helpers must send
        (reference get_repair_subchunks :360)."""
        q, t = self.q, self.t
        y_lost, x_lost = lost_node // q, lost_node % q
        seq = _pow_int(q, t - 1 - y_lost)
        num_seq = _pow_int(q, y_lost)
        out = []
        index = x_lost * seq
        for _ in range(num_seq):
            out.append((index, seq))
            index += q * seq
        return out

    def minimum_to_repair(
        self, want_to_read: set[int], available: set[int]
    ) -> dict[int, list[tuple[int, int]]]:
        """reference minimum_to_repair :325: d helpers + their sub-chunk
        ranges, preferring the lost node's q-column."""
        i = next(iter(want_to_read))
        lost = i if i < self.k else i + self.nu
        sub_ind = self.get_repair_subchunks(lost)
        minimum: dict[int, list[tuple[int, int]]] = {}
        for j in range(self.q):
            if j != lost % self.q:
                rep = (lost // self.q) * self.q + j
                if rep < self.k:
                    minimum[rep] = sub_ind
                elif rep >= self.k + self.nu:
                    minimum[rep - self.nu] = sub_ind
        for c in sorted(available):
            if len(minimum) >= self.d:
                break
            minimum.setdefault(c, sub_ind)
        assert len(minimum) == self.d
        return minimum

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> set[int]:
        if self.is_repair(want_to_read, available):
            return set(self.minimum_to_repair(want_to_read, available))
        return super().minimum_to_decode(want_to_read, available)

    def repair(
        self,
        want_to_read: set[int],
        helper_chunks: dict[int, np.ndarray],
        chunk_size: int,
    ) -> dict[int, np.ndarray]:
        """Rebuild one chunk from d helpers' repair sub-chunks.  Helper
        arrays may be full chunks or just the repair sub-chunk runs
        (repair_blocksize = chunk_size/q).  reference repair :390 +
        repair_one_lost_chunk :462."""
        read_bytes = sum(
            int(np.asarray(b).size) for b in helper_chunks.values()
        )
        with obs.span(
            "ec.clay_repair", k=self.k, m=self.m, d=self.d,
            helpers=len(helper_chunks), read_bytes=read_bytes,
        ), _L.time("repair_seconds"):
            out = self._repair(want_to_read, helper_chunks, chunk_size)
        _L.inc("repair_bytes", len(want_to_read) * chunk_size)
        _L.observe(
            "repair_read_fraction", read_bytes / (self.k * chunk_size)
        )
        return out

    def _repair(
        self,
        want_to_read: set[int],
        helper_chunks: dict[int, np.ndarray],
        chunk_size: int,
    ) -> dict[int, np.ndarray]:
        assert len(want_to_read) == 1
        assert len(helper_chunks) == self.d
        q, t = self.q, self.t
        i = next(iter(want_to_read))
        lost = i if i < self.k else i + self.nu
        sub_ind = self.get_repair_subchunks(lost)
        repair_sub_count = sum(c for _, c in sub_ind)
        sc = chunk_size // self.sub_chunk_no
        repair_planes = [
            z for ind, cnt in sub_ind for z in range(ind, ind + cnt)
        ]
        plane_pos = {z: j for j, z in enumerate(repair_planes)}

        # node-indexed helper data [repair_sub_count, sc]
        helpers: dict[int, np.ndarray] = {}
        for ext_i, buf in helper_chunks.items():
            nid = ext_i if ext_i < self.k else ext_i + self.nu
            arr = np.asarray(buf, np.uint8).reshape(-1, sc)
            if arr.shape[0] == self.sub_chunk_no:
                arr = arr[repair_planes]
            assert arr.shape[0] == repair_sub_count
            helpers[nid] = arr
        for j in range(self.k, self.k + self.nu):
            helpers[j] = np.zeros((repair_sub_count, sc), np.uint8)

        aloof = {
            (j if j < self.k else j + self.nu)
            for j in range(self.k + self.m)
            if j != i and j not in helper_chunks
        }

        if not aloof:
            # the d = #helpers = k+m-1 case (and any no-aloof repair):
            # every plane has the same score, all deps vanish, and the
            # whole repair batches over the plane axis — one fused GF
            # matmul per (node, case) instead of per (node, plane)
            return {
                i: self._repair_batched(
                    lost, helpers, sc, repair_planes, plane_pos
                ).reshape(-1)
            }

        recovered = np.zeros((self.sub_chunk_no, sc), np.uint8)
        U = {
            n: np.zeros((self.sub_chunk_no, sc), np.uint8)
            for n in range(q * t)
        }
        erasures = {lost - lost % q + x for x in range(q)} | aloof

        # order planes by intersection score over erasures+aloof
        ordered: dict[int, list[int]] = {}
        for z in repair_planes:
            zv = self._z_vec(z)
            score = sum(
                1 for nd in ({lost} | aloof) if nd % q == zv[nd // q]
            )
            assert score > 0
            ordered.setdefault(score, []).append(z)

        for score in sorted(ordered):
            for z in ordered[score]:
                zv = self._z_vec(z)
                # phase 1: fill U for live nodes
                for y in range(t):
                    for x in range(q):
                        node_xy = y * q + x
                        if node_xy in erasures:
                            continue
                        z_sw = self._z_sw(z, x, y, zv)
                        node_sw = y * q + zv[y]
                        c_xy, c_sw, u_xy, u_sw = self._pair_indices(
                            x, zv[y]
                        )
                        if node_sw in aloof:
                            # partner coupled unknown; use partner's U
                            known = {
                                c_xy: helpers[node_xy][plane_pos[z]],
                                u_sw: U[node_sw][z_sw],
                            }
                            (rec,) = self.pft.recover(known, [u_xy])
                            U[node_xy][z] = rec
                        elif zv[y] != x:
                            known = {
                                c_xy: helpers[node_xy][plane_pos[z]],
                                c_sw: helpers[node_sw][plane_pos[z_sw]],
                            }
                            rec_lo, rec_hi = self.pft.recover(
                                known, [2, 3]
                            )
                            rec = {2: rec_lo, 3: rec_hi}
                            U[node_xy][z] = rec[u_xy]
                        else:
                            U[node_xy][z] = helpers[node_xy][plane_pos[z]]
                # phase 2: MDS across the plane
                assert len(erasures) <= self.m
                self._mds_recover(U, z, erasures)
                # phase 3: recover coupled symbols of erased nodes
                for nd in sorted(erasures):
                    if nd in aloof:
                        continue
                    x, y = nd % q, nd // q
                    node_sw = y * q + zv[y]
                    z_sw = self._z_sw(z, x, y, zv)
                    c_xy, c_sw, u_xy, u_sw = self._pair_indices(x, zv[y])
                    if x == zv[y]:  # hole-dot pair
                        recovered[z] = U[nd][z]
                    else:
                        assert node_sw == lost
                        known = {
                            c_xy: helpers[nd][plane_pos[z]],
                            u_xy: U[nd][z],
                        }
                        (rec,) = self.pft.recover(known, [c_sw])
                        recovered[z_sw] = rec
        return {i: recovered.reshape(-1)}

    def _repair_batched(
        self,
        lost: int,
        helpers: dict[int, np.ndarray],
        sc: int,
        repair_planes: list[int],
        plane_pos: dict[int, int],
    ) -> np.ndarray:
        """Plane-batched single-chunk repair for the no-aloof case.

        Same math as the per-plane loop in `repair` (reference
        repair_one_lost_chunk, src/erasure-code/clay/ErasureCodeClay.cc:
        462-640), restructured so the plane axis is a batch dimension:
        per live node the pair decoupling becomes ONE GF matmul over the
        [planes*sc] byte axis (split by the <x / >x index-swap cases),
        the inner MDS is one matmul over all planes, and the final
        coupled recovery is one matmul per erased column node.  The
        partner plane/node indices are precomputed index vectors — the
        'plane gather/scatter via precomputed index tensors' form that
        batches onto the engine instead of looping Python per plane."""
        q, t = self.q, self.t
        P = len(repair_planes)
        x_lost, y_lost = lost % q, lost // q
        zvs = np.array(
            [self._z_vec(z) for z in repair_planes], np.int64
        )  # [P, t]
        n = q * t
        U = np.zeros((n, P, sc), np.uint8)

        # phase 1: uncoupled symbols of live nodes, batched per (x, y)
        # (the lost column's nodes are the erasures; the y == y_lost
        # guard below skips them, and _repair_plan re-derives the set)
        for y in range(t):
            if y == y_lost:
                continue  # whole lost column is erased; no live nodes here
            for x in range(q):
                node_xy = y * q + x
                hx = helpers[node_xy]  # [P, sc] in repair_planes order
                zy = zvs[:, y]  # partner digit per plane
                # partner plane position: digit y of z flipped to x; since
                # y != y_lost the partner plane is itself a repair plane
                pos_sw = np.array(
                    [
                        plane_pos[
                            z + (x - int(zy[j])) * _pow_int(q, t - 1 - y)
                        ]
                        for j, z in enumerate(repair_planes)
                    ]
                )
                eq = zy == x
                U[node_xy][eq] = hx[eq]
                for swap, sel in (
                    (False, (~eq) & (zy < x)),
                    (True, (~eq) & (zy > x)),
                ):
                    if not sel.any():
                        continue
                    node_sw = y * q + zy[sel]  # [S] partner node per plane
                    c_here = hx[sel]  # own coupled
                    c_part = np.stack(
                        [
                            helpers[int(ns)][int(pp)]
                            for ns, pp in zip(node_sw, pos_sw[sel])
                        ]
                    )
                    # canonical 4-tuple positions (larger-x first): when
                    # zy > x our node holds position 1, partner 0
                    known = (
                        {0: c_part, 1: c_here} if swap
                        else {0: c_here, 1: c_part}
                    )
                    want_u = 3 if swap else 2
                    R = matrices.recover_matrix(
                        self.pft.C, [0, 1], [want_u]
                    )
                    stack = np.stack([known[0], known[1]])
                    rec = self.engine.matmul(
                        R, stack.reshape(2, -1)
                    ).reshape(-1, sc)
                    U[node_xy][sel] = rec

        # phases 2+3 fused: ONE matmul with the cached product matrix.
        # The plan's RB row for node nd composes the inner-MDS recovery
        # (U[nd] = R_mds[nd]·U[present]) with the pair uncoupling
        # (rec = ch·helpers[nd] ⊕ cu·U[nd]) into direct coefficients
        # over [helpers[col]; U[present]] — the product-matrix form of
        # "Fast Product-Matrix Regenerating Codes" (PAPERS.md): the two
        # chained GF matmuls per erased column node become one
        # precomputed row, so the repair never materializes U[missing].
        plan = self._repair_plan(lost, repair_planes)
        recovered = np.zeros((self.sub_chunk_no, sc), np.uint8)
        helper_rows = [
            helpers[nd].reshape(1, -1) for nd in plan["col_others"]
        ]
        X = np.concatenate(
            helper_rows
            + [U[plan["present"]].reshape(len(plan["present"]), -1)]
        )
        out = np.asarray(
            self.engine.matmul(plan["RB"], X)
        ).reshape(len(plan["missing"]), P, sc)
        for ri, dest in enumerate(plan["z_dest"]):
            recovered[dest] = out[ri]
        return recovered

    def _repair_plan(self, lost: int, repair_planes: list[int]) -> dict:
        """Cached product-matrix plan for the no-aloof batched repair of
        `lost`: input rows are [helpers of the lost column's other
        nodes; uncoupled rows of the surviving nodes], output row ri
        rebuilds the coupled bytes scattered to plane set z_dest[ri]."""
        plan = self._repair_plans.get(lost)
        if plan is not None:
            _L.inc("repair_plan_hits")
            return plan
        q, t = self.q, self.t
        n = q * t
        x_lost, y_lost = lost % q, lost // q
        erasures = {y_lost * q + x for x in range(q)}
        present = sorted(set(range(n)) - erasures)[: self.k + self.nu]
        missing = sorted(erasures)
        col_others = [nd for nd in missing if nd != lost]
        R_mds = matrices.recover_matrix(self.mds_C, present, missing)
        RB = np.zeros(
            (len(missing), len(col_others) + len(present)), np.uint8
        )
        z_dest: list[np.ndarray] = []
        for ri, nd in enumerate(missing):
            x = nd % q
            if x == x_lost:
                # hole-dot planes: uncoupled == coupled; row is the MDS
                # recovery itself, landing on the repair planes
                RB[ri, len(col_others):] = R_mds[ri]
                z_dest.append(np.asarray(repair_planes))
                continue
            c_xy, c_sw, u_xy, u_sw = self._pair_indices(x, x_lost)
            known_pos = sorted((c_xy, u_xy))
            R2 = matrices.recover_matrix(self.pft.C, known_pos, [c_sw])
            ch = int(R2[0, known_pos.index(c_xy)])
            cu = int(R2[0, known_pos.index(u_xy)])
            RB[ri, col_others.index(nd)] = ch
            RB[ri, len(col_others):] = GF_MUL_TABLE[cu, R_mds[ri]]
            z_dest.append(np.array(
                [
                    z + (x - x_lost) * _pow_int(q, t - 1 - y_lost)
                    for z in repair_planes
                ]
            ))
        plan = {
            "present": present,
            "missing": missing,
            "col_others": col_others,
            "RB": RB,
            "z_dest": z_dest,
        }
        self._repair_plans[lost] = plan
        _L.inc("repair_plan_misses")
        return plan

    def decode(
        self,
        want_to_read: set[int],
        chunks: dict[int, np.ndarray],
        chunk_size: int | None = None,
    ) -> dict[int, np.ndarray]:
        avail = set(chunks)
        if want_to_read <= avail:
            return {
                i: np.asarray(chunks[i], np.uint8) for i in want_to_read
            }
        if chunk_size is not None and self.is_repair(want_to_read, avail):
            first = next(iter(chunks.values()))
            if chunk_size > len(np.asarray(first).reshape(-1)):
                return self.repair(want_to_read, chunks, chunk_size)
        return super().decode(want_to_read, chunks, chunk_size)
