"""Code-matrix constructions for the RS-family techniques.

The reference's jerasure plugin exposes these as techniques
(reference src/erasure-code/jerasure/ErasureCodeJerasure.h:81-253) but the
actual matrix math lives in the *empty* jerasure/gf-complete submodules, so
the constructions here follow the published algorithms (Plank's jerasure
papers / isa-l docs).  Cross-byte compatibility with stock jerasure builds
cannot be differentially tested in this checkout (no vendored source); the
tests instead verify the defining properties: systematic form, first parity
row all-ones where specified, and the MDS property (every k×k submatrix of
the generator invertible) exhaustively for the supported (k,m) grid.

All matrices are the m×k *coding* block C of the systematic generator
[I_k; C]: parity = C · data.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.ec.gf import (
    GF_MUL_TABLE,
    gf_div,
    gf_inv,
    gf_matmul,
    gf_pow,
    matrix_to_bitmatrix,
)


def vandermonde_rs(k: int, m: int) -> np.ndarray:
    """jerasure reed_sol_van construction: (m+k)×k Vandermonde rows
    [1, i, i², …] column-reduced to systematic form, then column-scaled so
    the first parity row is all ones; returns the bottom m rows."""
    rows = m + k
    if rows > 256:
        raise ValueError("k+m must be <= 256 for w=8")
    V = np.zeros((rows, k), np.uint8)
    for i in range(rows):
        V[i, 0] = 1
        for j in range(1, k):
            V[i, j] = GF_MUL_TABLE[V[i, j - 1], i]

    # column-reduce the top k×k block to identity (elementary column ops
    # over the full column preserve the code)
    for i in range(k):
        if V[i, i] == 0:
            for j in range(i + 1, k):
                if V[i, j]:
                    V[:, [i, j]] = V[:, [j, i]]
                    break
            else:
                raise np.linalg.LinAlgError("vandermonde reduction failed")
        if V[i, i] != 1:
            V[:, i] = GF_MUL_TABLE[V[:, i], gf_inv(V[i, i])]
        for j in range(k):
            if j != i and V[i, j]:
                V[:, j] ^= GF_MUL_TABLE[V[i, j], V[:, i]]

    # scale the parity part of each column so parity row 0 is all ones
    # (valid: equivalent to a bijective per-symbol data transform)
    for j in range(k):
        c = V[k, j]
        if c == 0:
            raise np.linalg.LinAlgError("zero in first parity row")
        if c != 1:
            V[k:, j] = GF_MUL_TABLE[V[k:, j], gf_inv(c)]
    return V[k:].copy()


def rs_r6(k: int) -> np.ndarray:
    """reed_sol_r6_op (RAID-6, m=2): P row all ones, Q row = powers of 2
    (reference src/erasure-code/jerasure/ErasureCodeJerasure.h:111-141)."""
    C = np.zeros((2, k), np.uint8)
    C[0] = 1
    for j in range(k):
        C[1, j] = gf_pow(2, j)
    return C


def cauchy_orig(k: int, m: int) -> np.ndarray:
    """cauchy_original_coding_matrix: C[i,j] = 1/(i ⊕ (m+j))."""
    if k + m > 256:
        raise ValueError("k+m must be <= 256 for w=8")
    C = np.zeros((m, k), np.uint8)
    for i in range(m):
        for j in range(k):
            C[i, j] = gf_inv(i ^ (m + j))
    return C


def _ones_in_bitrow(row: np.ndarray) -> int:
    """Total set bits of a row's bitmatrix expansion — the XOR cost metric
    cauchy_good minimizes."""
    return int(matrix_to_bitmatrix(row[None, :]).sum())


def cauchy_good(k: int, m: int) -> np.ndarray:
    """cauchy_good: cauchy_orig improved for XOR count — scale each column
    so row 0 is all ones, then scale each later row by the divisor among its
    elements that minimizes the bitmatrix ones count."""
    C = cauchy_orig(k, m)
    for j in range(k):
        if C[0, j] != 1:
            C[:, j] = GF_MUL_TABLE[C[:, j], gf_inv(C[0, j])]
    for i in range(1, m):
        best = None
        best_row = None
        for d in C[i]:
            if d in (0, 1):
                continue
            cand = np.array(
                [gf_div(int(v), int(d)) for v in C[i]], np.uint8
            )
            ones = _ones_in_bitrow(cand)
            if best is None or ones < best:
                best, best_row = ones, cand
        if best_row is not None and best < _ones_in_bitrow(C[i]):
            C[i] = best_row
    return C


def isa_rs_vandermonde(k: int, m: int) -> np.ndarray:
    """isa-l gf_gen_rs_matrix coding block: row i = [g^0, g^i, g^2i, …]
    with g=2 (non-reduced Vandermonde; isa-l documents it as unsafe for
    m>2 at some k — kept for plugin parity, verified MDS per-instance)."""
    C = np.zeros((m, k), np.uint8)
    for i in range(m):
        for j in range(k):
            C[i, j] = gf_pow(2, i * j)
    return C


def isa_cauchy(k: int, m: int) -> np.ndarray:
    """isa-l gf_gen_cauchy1_matrix coding block: C[i,j] = 1/((k+i) ⊕ j)."""
    C = np.zeros((m, k), np.uint8)
    for i in range(m):
        for j in range(k):
            C[i, j] = gf_inv((k + i) ^ j)
    return C


def generator(C: np.ndarray) -> np.ndarray:
    """Full systematic generator [I_k; C]."""
    k = C.shape[1]
    return np.concatenate([np.eye(k, dtype=np.uint8), C], axis=0)


def is_mds(C: np.ndarray) -> bool:
    """Every k×k submatrix of [I;C] invertible ⇔ every square submatrix of
    C is invertible; checked directly on C (Cauchy/Vandermonde sizes here
    are small enough for the exhaustive test suite)."""
    from itertools import combinations

    from ceph_tpu.ec.gf import gf_invert_matrix

    m, k = C.shape
    G = generator(C)
    for rows in combinations(range(k + m), k):
        try:
            gf_invert_matrix(G[list(rows)])
        except np.linalg.LinAlgError:
            return False
    return True


def decode_matrix(C: np.ndarray, present_rows: list[int]) -> np.ndarray:
    """Inverse of the generator restricted to `present_rows` (chunk indices
    into [0,k+m)); multiplying it by the surviving chunks reconstructs the
    data chunks — jerasure_matrix_decode's core step."""
    from ceph_tpu.ec.gf import gf_invert_matrix

    k = C.shape[1]
    G = generator(C)
    sub = G[present_rows[:k]]
    return gf_invert_matrix(sub)


# recover_matrix is pure in (C, present, want) and sits on every decode
# and Clay pair/plane hot path; before this cache each call re-ran the
# Gauss–Jordan inversion.  Keys are tiny (code matrices), values m×k.
_RECOVER_CACHE: dict[tuple, np.ndarray] = {}


def recover_matrix(
    C: np.ndarray, present: list[int], want: list[int]
) -> np.ndarray:
    """Rows that rebuild the `want` chunks (data or parity ids) directly
    from the first k `present` chunks: R = G[want] · inv(G[present]).
    Cached per (matrix content, present, want) — the inner step of every
    cached decode/repair plan."""
    C = np.asarray(C, np.uint8)
    key = (C.shape, C.tobytes(), tuple(present), tuple(want))
    R = _RECOVER_CACHE.get(key)
    if R is None:
        inv = decode_matrix(C, present)
        G = generator(C)
        R = gf_matmul(G[list(want)], inv)
        _RECOVER_CACHE[key] = R
    return R.copy()
