"""Reed–Solomon family codes (matrix codes over GF(2^8)).

Covers the techniques of the reference's jerasure and isa plugins that are
plain generator-matrix codes (reference
src/erasure-code/jerasure/ErasureCodeJerasure.h:81-190,
src/erasure-code/isa/ErasureCodeIsa.cc:120-317): encode is C·data, decode
inverts the surviving rows of [I;C].  The per-stripe math runs on a
pluggable engine: numpy on host, or the TPU backend (ec.jax_backend) that
turns the GF matmul into an MXU bit-plane matmul.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu import obs
from ceph_tpu.ec import matrices
from ceph_tpu.ec.gf import gf_matvec_data
from ceph_tpu.ec.interface import ErasureCode, ErasureCodeProfileError

_L = obs.logger_for("ec")
_L.add_u64("bytes_encoded", "stripe bytes pushed through encode_chunks")
_L.add_u64("bytes_decoded", "chunk bytes rebuilt by decode_chunks")
_L.add_time_avg("encode_seconds", "encode_chunks wall time")
_L.add_time_avg("decode_seconds", "decode_chunks wall time")
_L.add_u64("decode_plan_hits",
           "decodes served by a cached per-erasure-pattern plan")
_L.add_u64("decode_plan_misses",
           "decode plans built (submatrix inverted + schedule lowered)")


def _is_device_array(x) -> bool:
    """True for jax device arrays; lists/bytes/numpy are host inputs (the
    plugin API coerces those with np.asarray).  Module-name check keeps
    the jax import lazy for jax-free entry points."""
    return type(x).__module__.split(".")[0] in ("jax", "jaxlib")


class NumpyEngine:
    """Host GF matmul engine (table-driven)."""

    def matmul(self, M: np.ndarray, data: np.ndarray) -> np.ndarray:
        return gf_matvec_data(M, data)


class NativeEngine:
    """C++ SIMD GF engine (ceph_tpu/native/gf.cpp)."""

    def __init__(self):
        import ctypes

        from ceph_tpu.native import load_gf

        lib = load_gf()
        if lib is None:
            raise ErasureCodeProfileError(
                "native GF library unavailable (no C++ compiler?)"
            )
        self.lib = lib
        self._u8p = ctypes.POINTER(ctypes.c_uint8)

    def matmul(self, M: np.ndarray, data: np.ndarray) -> np.ndarray:
        M = np.ascontiguousarray(M, np.uint8)
        data = np.ascontiguousarray(data, np.uint8)
        m, k = M.shape
        L = data.shape[1]
        out = np.empty((m, L), np.uint8)
        self.lib.gf_native_matvec(
            M.ctypes.data_as(self._u8p), m, k,
            data.ctypes.data_as(self._u8p),
            out.ctypes.data_as(self._u8p), L,
        )
        return out


_ENGINES = {"numpy": NumpyEngine, "native": NativeEngine}


def get_engine(name: str, strategy: str | None = None):
    """Build a per-stripe math engine.  `strategy` (jax only) picks one
    of ec.jax_backend.STRATEGIES; None defers to the engine's own
    resolution (env override, then backend default)."""
    if name == "jax":
        from ceph_tpu.ec.jax_backend import JaxEngine

        try:
            return JaxEngine(strategy)
        except ValueError as e:
            raise ErasureCodeProfileError(str(e))
    try:
        return _ENGINES[name]()
    except KeyError:
        raise ErasureCodeProfileError(f"unknown ec backend {name!r}")


# decode plans, shared across code instances with equal generators: an
# erasure pattern's recover matrix (one Gauss–Jordan inversion + a GF
# matmul) is pure in (C, surviving set, wanted set).  Before this cache
# every decode_chunks call re-inverted the submatrix; now a pattern pays
# once per process and its matrix is `prepare`d into the engine's
# structural caches (XOR schedule / bitmatrix) at the same moment.
_DECODE_PLANS: dict[tuple, np.ndarray] = {}


def decode_plan(C: np.ndarray, use: tuple, missing: tuple,
                engine=None) -> np.ndarray:
    key = (C.shape, C.tobytes(), use, missing)
    R = _DECODE_PLANS.get(key)
    if R is None:
        R = matrices.recover_matrix(C, list(use), list(missing))
        if engine is not None and hasattr(engine, "prepare"):
            engine.prepare(R)
        _DECODE_PLANS[key] = R
        _L.inc("decode_plan_misses")
    else:
        _L.inc("decode_plan_hits")
    return R


class RSErasureCode(ErasureCode):
    """Systematic matrix code; subclass/technique sets the coding block."""

    TECHNIQUES = {
        "reed_sol_van": matrices.vandermonde_rs,
        "cauchy_orig": matrices.cauchy_orig,
        "cauchy_good": matrices.cauchy_good,
        "isa_reed_sol_van": matrices.isa_rs_vandermonde,
        "isa_cauchy": matrices.isa_cauchy,
    }

    def __init__(self, technique: str = "reed_sol_van"):
        super().__init__()
        self.technique = technique
        self.C: np.ndarray | None = None
        self.engine = None

    def parse(self, profile: dict) -> None:
        # jerasure defaults k=7,m=3 (reference ErasureCodeJerasure.h:89-91)
        self.k, self.m = 7, 3
        super().parse(profile)
        if self.w != 8:
            raise ErasureCodeProfileError(
                f"w={self.w}: only w=8 is supported (the reference default)"
            )
        if self.technique == "reed_sol_r6_op":
            if self.m != 2:
                raise ErasureCodeProfileError(
                    "reed_sol_r6_op requires m=2"
                )
            self.C = matrices.rs_r6(self.k)
        else:
            try:
                make = self.TECHNIQUES[self.technique]
            except KeyError:
                raise ErasureCodeProfileError(
                    f"unknown technique {self.technique!r}"
                )
            self.C = make(self.k, self.m)
        self.engine = get_engine(
            profile.get("backend", "numpy"), profile.get("strategy")
        )
        # profile-registration-time lowering: derive the encode matrix's
        # structural artifacts (XOR schedule / bitmatrix) now, so the
        # first stripe pays only the jit compile
        if hasattr(self.engine, "prepare"):
            self.engine.prepare(self.C)

    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        assert data.shape[0] == self.k
        with obs.span(
            "ec.encode", k=self.k, m=self.m, bytes=int(data.size)
        ), _L.time("encode_seconds"):
            if _is_device_array(data):
                import jax.numpy as jnp  # device stripes stay on device

                parity = self.engine.matmul(self.C, data)
                out = jnp.concatenate([data, parity], axis=0)
            else:
                data = np.asarray(data, np.uint8)
                parity = self.engine.matmul(self.C, data)
                out = np.concatenate([data, np.asarray(parity)], axis=0)
        _L.inc("bytes_encoded", int(data.size))
        return out

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: dict[int, np.ndarray],
        chunk_size: int,
    ) -> dict[int, np.ndarray]:
        present = sorted(chunks)
        if len(present) < self.k:
            raise ValueError(
                f"cannot decode: {len(present)} < k={self.k} chunks"
            )
        use = present[: self.k]
        missing = sorted(set(want_to_read) - set(chunks))
        with obs.span(
            "ec.decode", k=self.k, m=self.m, missing=len(missing),
            bytes=len(missing) * chunk_size,
        ), _L.time("decode_seconds"):
            if any(_is_device_array(chunks[i]) for i in use):
                import jax.numpy as jnp

                stack = jnp.stack([chunks[i] for i in use])
            else:
                stack = np.stack(
                    [np.asarray(chunks[i], np.uint8) for i in use]
                )
            out = dict(chunks)
            if missing:
                R = decode_plan(
                    self.C, tuple(use), tuple(missing), self.engine
                )
                rebuilt = self.engine.matmul(R, stack)
                for row, i in enumerate(missing):
                    out[i] = rebuilt[row]
        _L.inc("bytes_decoded", len(missing) * chunk_size)
        return out

    def encode_parity(self, data):
        """Parity rows only: [k, cs] -> [m, cs], no stripe assembly.
        This is the reference benchmark's encode shape — its encoded
        data chunks alias the input bufferlist (zero copy), so parity
        generation IS the measured work; encode_chunks' concatenation
        is a convenience copy this path skips (on the throttled bench
        container that copy alone halves the apparent rate)."""
        assert data.shape[0] == self.k
        nbytes = int(np.prod(np.shape(data)))
        with obs.span(
            "ec.encode", k=self.k, m=self.m, bytes=nbytes
        ), _L.time("encode_seconds"):
            parity = self.engine.matmul(self.C, data)
        _L.inc("bytes_encoded", nbytes)
        return parity

    # -- batched-stripe paths ----------------------------------------------
    def encode_batch(self, data):
        """[N, k, cs] stripes -> [N, k+m, cs]: ONE device dispatch for
        the whole batch (engine.matmul_batch vmaps the single-stripe
        kernel over the stripes axis; stripe count is just a shape, so
        after one warmup compile every batch size change retraces but a
        steady stream of equal batches books 0 compiles)."""
        assert np.ndim(data) == 3 and np.shape(data)[1] == self.k, (
            np.shape(data), self.k
        )
        if not hasattr(self.engine, "matmul_batch"):
            # per-stripe fallback, OUTSIDE the batch accounting: each
            # encode_chunks call books its own span/seconds/bytes
            return np.stack(
                [np.asarray(self.encode_chunks(s)) for s in data]
            )
        nbytes = int(np.prod(np.shape(data)))
        with obs.span(
            "ec.encode_batch", k=self.k, m=self.m,
            stripes=int(np.shape(data)[0]), bytes=nbytes,
        ), _L.time("encode_seconds"):
            parity = self.engine.matmul_batch(self.C, data)
            if _is_device_array(parity):
                import jax.numpy as jnp

                out = jnp.concatenate([data, parity], axis=1)
            else:
                out = np.concatenate(
                    [np.asarray(data, np.uint8), parity], axis=1
                )
        _L.inc("bytes_encoded", nbytes)
        return out

    def decode_batch(
        self,
        want_to_read: set[int],
        chunks: dict[int, np.ndarray],
        chunk_size: int,
    ) -> dict[int, np.ndarray]:
        """Batched decode: every chunk value is [N, cs] (N stripes, all
        with the SAME erasure pattern — the repair-queue shape: one PG's
        lost OSD means many stripes missing the same shard).  The cached
        decode plan is looked up once and applied to the whole batch in
        one dispatch."""
        present = sorted(chunks)
        if len(present) < self.k:
            raise ValueError(
                f"cannot decode: {len(present)} < k={self.k} chunks"
            )
        use = present[: self.k]
        missing = sorted(set(want_to_read) - set(chunks))
        first = chunks[use[0]]
        n_stripes = int(np.shape(first)[0])
        with obs.span(
            "ec.decode_batch", k=self.k, m=self.m, missing=len(missing),
            stripes=n_stripes, bytes=len(missing) * chunk_size * n_stripes,
        ), _L.time("decode_seconds"):
            out = dict(chunks)
            if missing:
                R = decode_plan(
                    self.C, tuple(use), tuple(missing), self.engine
                )
                if any(_is_device_array(chunks[i]) for i in use):
                    import jax.numpy as jnp

                    stack = jnp.stack(
                        [chunks[i] for i in use], axis=1
                    )  # [N, k, cs]
                else:
                    stack = np.stack(
                        [np.asarray(chunks[i], np.uint8) for i in use],
                        axis=1,
                    )
                if hasattr(self.engine, "matmul_batch"):
                    rebuilt = self.engine.matmul_batch(R, stack)
                else:
                    rebuilt = np.stack(
                        [self.engine.matmul(R, s) for s in stack]
                    )
                for row, i in enumerate(missing):
                    out[i] = rebuilt[:, row]
        _L.inc("bytes_decoded", len(missing) * chunk_size * n_stripes)
        return out
