"""Reed–Solomon family codes (matrix codes over GF(2^8)).

Covers the techniques of the reference's jerasure and isa plugins that are
plain generator-matrix codes (reference
src/erasure-code/jerasure/ErasureCodeJerasure.h:81-190,
src/erasure-code/isa/ErasureCodeIsa.cc:120-317): encode is C·data, decode
inverts the surviving rows of [I;C].  The per-stripe math runs on a
pluggable engine: numpy on host, or the TPU backend (ec.jax_backend) that
turns the GF matmul into an MXU bit-plane matmul.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu import obs
from ceph_tpu.ec import matrices
from ceph_tpu.ec.gf import gf_matvec_data
from ceph_tpu.ec.interface import ErasureCode, ErasureCodeProfileError

_L = obs.logger_for("ec")
_L.add_u64("bytes_encoded", "stripe bytes pushed through encode_chunks")
_L.add_u64("bytes_decoded", "chunk bytes rebuilt by decode_chunks")
_L.add_time_avg("encode_seconds", "encode_chunks wall time")
_L.add_time_avg("decode_seconds", "decode_chunks wall time")


def _is_device_array(x) -> bool:
    """True for jax device arrays; lists/bytes/numpy are host inputs (the
    plugin API coerces those with np.asarray).  Module-name check keeps
    the jax import lazy for jax-free entry points."""
    return type(x).__module__.split(".")[0] in ("jax", "jaxlib")


class NumpyEngine:
    """Host GF matmul engine (table-driven)."""

    def matmul(self, M: np.ndarray, data: np.ndarray) -> np.ndarray:
        return gf_matvec_data(M, data)


class NativeEngine:
    """C++ SIMD GF engine (ceph_tpu/native/gf.cpp)."""

    def __init__(self):
        import ctypes

        from ceph_tpu.native import load_gf

        lib = load_gf()
        if lib is None:
            raise ErasureCodeProfileError(
                "native GF library unavailable (no C++ compiler?)"
            )
        self.lib = lib
        self._u8p = ctypes.POINTER(ctypes.c_uint8)

    def matmul(self, M: np.ndarray, data: np.ndarray) -> np.ndarray:
        M = np.ascontiguousarray(M, np.uint8)
        data = np.ascontiguousarray(data, np.uint8)
        m, k = M.shape
        L = data.shape[1]
        out = np.empty((m, L), np.uint8)
        self.lib.gf_native_matvec(
            M.ctypes.data_as(self._u8p), m, k,
            data.ctypes.data_as(self._u8p),
            out.ctypes.data_as(self._u8p), L,
        )
        return out


_ENGINES = {"numpy": NumpyEngine, "native": NativeEngine}


def get_engine(name: str):
    if name == "jax":
        from ceph_tpu.ec.jax_backend import JaxEngine

        return JaxEngine()
    try:
        return _ENGINES[name]()
    except KeyError:
        raise ErasureCodeProfileError(f"unknown ec backend {name!r}")


class RSErasureCode(ErasureCode):
    """Systematic matrix code; subclass/technique sets the coding block."""

    TECHNIQUES = {
        "reed_sol_van": matrices.vandermonde_rs,
        "cauchy_orig": matrices.cauchy_orig,
        "cauchy_good": matrices.cauchy_good,
        "isa_reed_sol_van": matrices.isa_rs_vandermonde,
        "isa_cauchy": matrices.isa_cauchy,
    }

    def __init__(self, technique: str = "reed_sol_van"):
        super().__init__()
        self.technique = technique
        self.C: np.ndarray | None = None
        self.engine = None

    def parse(self, profile: dict) -> None:
        # jerasure defaults k=7,m=3 (reference ErasureCodeJerasure.h:89-91)
        self.k, self.m = 7, 3
        super().parse(profile)
        if self.w != 8:
            raise ErasureCodeProfileError(
                f"w={self.w}: only w=8 is supported (the reference default)"
            )
        if self.technique == "reed_sol_r6_op":
            if self.m != 2:
                raise ErasureCodeProfileError(
                    "reed_sol_r6_op requires m=2"
                )
            self.C = matrices.rs_r6(self.k)
        else:
            try:
                make = self.TECHNIQUES[self.technique]
            except KeyError:
                raise ErasureCodeProfileError(
                    f"unknown technique {self.technique!r}"
                )
            self.C = make(self.k, self.m)
        self.engine = get_engine(profile.get("backend", "numpy"))

    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        assert data.shape[0] == self.k
        with obs.span(
            "ec.encode", k=self.k, m=self.m, bytes=int(data.size)
        ), _L.time("encode_seconds"):
            if _is_device_array(data):
                import jax.numpy as jnp  # device stripes stay on device

                parity = self.engine.matmul(self.C, data)
                out = jnp.concatenate([data, parity], axis=0)
            else:
                data = np.asarray(data, np.uint8)
                parity = self.engine.matmul(self.C, data)
                out = np.concatenate([data, np.asarray(parity)], axis=0)
        _L.inc("bytes_encoded", int(data.size))
        return out

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: dict[int, np.ndarray],
        chunk_size: int,
    ) -> dict[int, np.ndarray]:
        present = sorted(chunks)
        if len(present) < self.k:
            raise ValueError(
                f"cannot decode: {len(present)} < k={self.k} chunks"
            )
        use = present[: self.k]
        missing = sorted(set(want_to_read) - set(chunks))
        with obs.span(
            "ec.decode", k=self.k, m=self.m, missing=len(missing),
            bytes=len(missing) * chunk_size,
        ), _L.time("decode_seconds"):
            if any(_is_device_array(chunks[i]) for i in use):
                import jax.numpy as jnp

                stack = jnp.stack([chunks[i] for i in use])
            else:
                stack = np.stack(
                    [np.asarray(chunks[i], np.uint8) for i in use]
                )
            out = dict(chunks)
            if missing:
                R = matrices.recover_matrix(self.C, use, missing)
                rebuilt = self.engine.matmul(R, stack)
                for row, i in enumerate(missing):
                    out[i] = rebuilt[row]
        _L.inc("bytes_decoded", len(missing) * chunk_size)
        return out
