"""SHEC — Shingled Erasure Code (k, m, c).

Semantics of the reference's shec plugin (reference
src/erasure-code/shec/ErasureCodeShec.{h,cc}): a Vandermonde RS coding
matrix with runs of entries zeroed so each parity covers only a "shingle"
of the data chunks — local repair reads fewer chunks at the cost of
tolerating only c (not m) arbitrary failures.  The multiple-shingle layout
splits parities into two groups (m1,c1)/(m2,c2) chosen to minimize the
published recovery-efficiency metric (reference
shec_calc_recovery_efficiency1).

Defaults k=4, m=3, c=2 (reference ErasureCodeShec.h:47-57).
"""

from __future__ import annotations

import itertools

import numpy as np

from ceph_tpu.ec import matrices
from ceph_tpu.ec.gf import GF_MUL_TABLE, gf_invert_matrix
from ceph_tpu.ec.interface import ErasureCode, ErasureCodeProfileError


def _zero_shingles(M: np.ndarray, rows: range, mm: int, cc: int) -> None:
    """Zero matrix entries outside each parity row's shingle (the loop of
    reference shec_reedsolomon_coding_matrix)."""
    k = M.shape[1]
    for ri, rr in enumerate(rows):
        end = ((ri * k) // mm) % k
        start = (((ri + cc) * k) // mm) % k
        ccol = start
        while ccol != end:
            M[rr, ccol] = 0
            ccol = (ccol + 1) % k


def _recovery_efficiency1(k: int, m1: int, m2: int, c1: int, c2: int) -> float:
    """reference shec_calc_recovery_efficiency1."""
    if m1 < c1 or m2 < c2:
        return -1.0
    if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
        return -1.0
    r_eff_k = [10**8] * k
    r_e1 = 0.0
    for mm, cc in ((m1, c1), (m2, c2)):
        for rr in range(mm):
            start = ((rr * k) // mm) % k
            end = (((rr + cc) * k) // mm) % k
            ccol, first = start, True
            while first or ccol != end:
                first = False
                r_eff_k[ccol] = min(
                    r_eff_k[ccol],
                    ((rr + cc) * k) // mm - (rr * k) // mm,
                )
                ccol = (ccol + 1) % k
            r_e1 += ((rr + cc) * k) // mm - (rr * k) // mm
    return r_e1 + sum(r_eff_k)


def shec_matrix(k: int, m: int, c: int, single: bool = False) -> np.ndarray:
    """m×k shingled coding matrix."""
    if single:
        m1, c1 = 0, 0
    else:
        best = None
        m1 = c1 = 0
        for cc1 in range(c // 2 + 1):
            for mm1 in range(m + 1):
                cc2, mm2 = c - cc1, m - mm1
                if mm1 < cc1 or mm2 < cc2:
                    continue
                if (mm1 == 0) != (cc1 == 0) or (mm2 == 0) != (cc2 == 0):
                    continue
                r = _recovery_efficiency1(k, mm1, mm2, cc1, cc2)
                if r >= 0 and (best is None or r < best):
                    best, m1, c1 = r, mm1, cc1
    m2, c2 = m - m1, c - c1
    M = matrices.vandermonde_rs(k, m)
    if m1:
        _zero_shingles(M, range(m1), m1, c1)
    if m2:
        _zero_shingles(M[m1:], range(m2), m2, c2)
    return M


class ShecCode(ErasureCode):
    """plugin=shec; profile: k=4, m=3, c=2, technique=multiple|single,
    plus the shared backend/strategy engine knobs (the per-stripe
    matmuls ride the same engines as ec.rs)."""

    def __init__(self):
        super().__init__()
        self.c = 0
        self.C: np.ndarray | None = None
        self.engine = None

    def parse(self, profile: dict) -> None:
        self.k, self.m = 4, 3
        super().parse(profile)
        try:
            self.c = int(profile.get("c", 2))
        except (TypeError, ValueError):
            raise ErasureCodeProfileError("c must be an integer")
        if not (0 < self.c <= self.m):
            raise ErasureCodeProfileError(
                f"c={self.c} must be within (0, m={self.m}]"
            )
        if self.w != 8:
            raise ErasureCodeProfileError("only w=8 is supported")
        technique = profile.get("technique", "multiple")
        if technique not in ("single", "multiple"):
            raise ErasureCodeProfileError(
                f"shec: unknown technique {technique!r}"
            )
        self.C = shec_matrix(
            self.k, self.m, self.c, single=(technique == "single")
        )
        from ceph_tpu.ec.rs import get_engine

        self.engine = get_engine(
            profile.get("backend", "numpy"), profile.get("strategy")
        )
        if hasattr(self.engine, "prepare"):
            self.engine.prepare(self.C)

    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        parity = np.asarray(self.engine.matmul(self.C, data))
        return np.concatenate([np.asarray(data, np.uint8), parity], axis=0)

    # -- decoding: solve the shingled system --------------------------------
    def _plans(
        self,
        wanted: set[int],
        avail_parity: list[int],
        known_data: set[int],
    ):
        """Solvable recovery plans in increasing read-cost order.

        A plan is (cost, rows, unknowns, need): parity `rows` whose
        shingles touch exactly the erased-data `unknowns` ⊇ wanted
        (untouched erased columns stay out of the system), with the square
        submatrix C[rows, unknowns] invertible.  `need` is the known data
        the rows read; cost = |need| + |rows| — the minimal-read search of
        the reference's shec_make_decoding_matrix."""
        plans = []
        for u in range(max(len(wanted), 1), len(avail_parity) + 1):
            for rows in itertools.combinations(avail_parity, u):
                unknowns = set(wanted)
                need = set()
                for r in rows:
                    for j in range(self.k):
                        if not self.C[r, j]:
                            continue
                        if j in known_data:
                            need.add(j)
                        else:
                            unknowns.add(j)
                if len(unknowns) != u:
                    continue
                cols = sorted(unknowns)
                try:
                    inv = gf_invert_matrix(
                        self.C[np.ix_(list(rows), cols)]
                    )
                except np.linalg.LinAlgError:
                    continue
                plans.append(
                    (len(need) + u, list(rows), cols, need, inv)
                )
        plans.sort(key=lambda t: (t[0], t[1]))
        return plans

    def _apply_plan(
        self, rows, cols, inv, chunks: dict[int, np.ndarray]
    ) -> dict[int, np.ndarray]:
        """rhs = parity - known contribution; solve for the unknowns."""
        rhs = np.stack(
            [np.asarray(chunks[self.k + r], np.uint8).copy() for r in rows]
        )
        for j in range(self.k):
            if j in cols or j not in chunks:
                continue
            coef = self.C[rows, j]
            if not coef.any():
                continue
            rhs ^= GF_MUL_TABLE[
                coef[:, None], np.asarray(chunks[j], np.uint8)[None, :]
            ]
        sol = np.asarray(self.engine.matmul(inv, rhs))
        return {d: sol[i] for i, d in enumerate(cols)}

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: dict[int, np.ndarray],
        chunk_size: int,
    ) -> dict[int, np.ndarray]:
        out = {i: np.asarray(v, np.uint8) for i, v in chunks.items()}
        erased_data = {i for i in range(self.k) if i not in chunks}
        known = {i for i in range(self.k) if i in chunks}
        avail_parity = [
            r for r in range(self.m) if (self.k + r) in chunks
        ]
        want_parity = {
            r for r in range(self.m)
            if (self.k + r) in want_to_read and (self.k + r) not in chunks
        }
        # erased parity re-encode needs the full data vector
        wanted = (
            set(erased_data)
            if want_parity
            else (want_to_read & erased_data)
        )
        if wanted:
            solved = None
            for _, rows, cols, _, inv in self._plans(
                wanted, avail_parity, known
            ):
                solved = self._apply_plan(rows, cols, inv, out)
                break
            if solved is None:
                raise ValueError(
                    f"shec: cannot recover chunks {sorted(wanted)} from "
                    f"{sorted(chunks)}"
                )
            out.update(solved)
        if want_parity:
            data = np.stack([out[i] for i in range(self.k)])
            par = np.asarray(
                self.engine.matmul(self.C[sorted(want_parity)], data)
            )
            for row, r in zip(par, sorted(want_parity)):
                out[self.k + r] = row
        return out

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> set[int]:
        """Prefer the smallest shingle read (the point of SHEC) instead of
        the base first-k rule.  Mirrors decode_chunks' planning exactly so
        the returned set is guaranteed decodable: re-encoding a wanted
        erased parity chunk needs the *full* data vector, so every erased
        data chunk becomes an unknown in that case."""
        if want_to_read <= available:
            return set(want_to_read)
        erased_data = {i for i in range(self.k) if i not in available}
        want_parity = {
            r for r in range(self.m)
            if (self.k + r) in want_to_read
            and (self.k + r) not in available
        }
        wanted = (
            set(erased_data)
            if want_parity
            else (want_to_read & erased_data)
        )
        avail_parity = [
            r for r in range(self.m) if (self.k + r) in available
        ]
        known = {i for i in range(self.k) if i in available}
        base = want_to_read & available
        if want_parity:
            base = base | known  # re-encode reads all surviving data
        if not wanted:
            # only parity wanted with all data present: read all data.
            # (want_to_read ⊄ available guarantees want_parity here —
            # a wanted erased chunk is either data (wanted non-empty)
            # or parity.)
            assert want_parity
            return base
        for _, rows, _, need, _ in self._plans(
            wanted, avail_parity, known
        ):
            return set(need) | {self.k + r for r in rows} | base
        raise ValueError(
            f"shec: cannot satisfy want={sorted(want_to_read)} from "
            f"available={sorted(available)}"
        )
