from ceph_tpu.ec.interface import ErasureCode, ErasureCodeProfileError
from ceph_tpu.ec.registry import create_erasure_code, list_plugins

__all__ = [
    "ErasureCode",
    "ErasureCodeProfileError",
    "create_erasure_code",
    "list_plugins",
]
