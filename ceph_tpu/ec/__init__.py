from ceph_tpu.ec.interface import ErasureCode, ErasureCodeProfileError
from ceph_tpu.ec.registry import create_erasure_code, list_plugins
from ceph_tpu.ec.xor_schedule import XorSchedule, build_schedule

__all__ = [
    "ErasureCode",
    "ErasureCodeProfileError",
    "XorSchedule",
    "build_schedule",
    "create_erasure_code",
    "list_plugins",
]
