"""LRC — locally repairable layered code.

Semantics of the reference's lrc plugin (reference
src/erasure-code/lrc/ErasureCodeLrc.{h,cc}): the profile describes a global
`mapping` string plus a JSON list of layers `[[chunks_map, profile], …]`;
each layer runs an inner code (default jerasure reed_sol_van) over its 'D'
(data) and 'c' (coding) positions.  Decode walks the layers in reverse,
repairing erasures with whichever layer has few enough of them — local
layers fix single losses by reading only their group (reference
decode_chunks :777-860).

The k/m/l shorthand (profile k,m,l without mapping/layers) generates the
classic one-global + per-group-local layout (reference parse_kml :293-395).
"""

from __future__ import annotations

import json

import numpy as np

from ceph_tpu.ec.interface import ErasureCode, ErasureCodeProfileError


class _Layer:
    def __init__(self, chunks_map: str, profile: dict,
                 parent: dict | None = None):
        self.chunks_map = chunks_map
        self.data = [i for i, ch in enumerate(chunks_map) if ch == "D"]
        self.coding = [i for i, ch in enumerate(chunks_map) if ch == "c"]
        self.chunks = self.data + self.coding
        self.chunks_set = set(self.chunks)
        prof = dict(profile)
        prof.setdefault("k", len(self.data))
        prof.setdefault("m", len(self.coding))
        prof.setdefault("plugin", "jerasure")
        prof.setdefault("technique", "reed_sol_van")
        # the engine knobs inherit from the outer lrc profile: a
        # backend=jax lrc runs every layer's matmuls on the device
        # engine unless a layer profile overrides them
        for knob in ("backend", "strategy"):
            if parent and parent.get(knob) is not None:
                prof.setdefault(knob, parent[knob])
        from ceph_tpu.ec.registry import create_erasure_code

        self.code = create_erasure_code(prof)


def generate_kml(k: int, m: int, l: int) -> tuple[str, list]:
    """reference parse_kml: mapping + layers for the k/m/l shorthand."""
    if l == 0 or (k + m) % l:
        raise ErasureCodeProfileError("k + m must be a multiple of l")
    groups = (k + m) // l
    if k % groups or m % groups:
        raise ErasureCodeProfileError(
            "k and m must be multiples of (k + m) / l"
        )
    kg, mg = k // groups, m // groups
    mapping = ("D" * kg + "_" * mg + "_") * groups
    layers = []
    glob = ("D" * kg + "c" * mg + "_") * groups
    layers.append([glob, ""])
    for i in range(groups):
        row = ""
        for j in range(groups):
            row += ("D" * l + "c") if i == j else "_" * (l + 1)
        layers.append([row, ""])
    return mapping, layers


class LrcCode(ErasureCode):
    """plugin=lrc; profile: mapping+layers JSON, or k/m/l shorthand."""

    def __init__(self):
        super().__init__()
        self.layers: list[_Layer] = []
        self.mapping = ""

    def parse(self, profile: dict) -> None:
        self.w = 8
        mapping = profile.get("mapping")
        layers_desc = profile.get("layers")
        if mapping is None and layers_desc is None:
            k = profile.get("k")
            m = profile.get("m")
            l = profile.get("l")
            if k is None or m is None or l is None:
                raise ErasureCodeProfileError(
                    "lrc: need mapping+layers or all of k, m, l"
                )
            mapping, layers = generate_kml(int(k), int(m), int(l))
        else:
            if mapping is None or layers_desc is None:
                raise ErasureCodeProfileError(
                    "lrc: mapping and layers must both be set"
                )
            if isinstance(layers_desc, str):
                try:
                    layers = json.loads(layers_desc)
                except json.JSONDecodeError as e:
                    raise ErasureCodeProfileError(
                        f"lrc: layers is not valid JSON: {e}"
                    )
            else:
                layers = layers_desc
        self.mapping = mapping
        self.k = mapping.count("D")
        self.m = len(mapping) - self.k
        self.layers = []
        for entry in layers:
            if not isinstance(entry, (list, tuple)) or not entry:
                raise ErasureCodeProfileError(
                    "lrc: each layer must be [chunks_map, profile]"
                )
            cm = entry[0]
            if len(cm) != len(mapping):
                raise ErasureCodeProfileError(
                    f"lrc: layer map {cm!r} length != mapping length "
                    f"{len(mapping)}"
                )
            lp = entry[1] if len(entry) > 1 else ""
            if isinstance(lp, str):
                lpd: dict = {}
                for tok in lp.split():
                    key, _, v = tok.partition("=")
                    lpd[key] = v
            else:
                lpd = dict(lp)
            self.layers.append(_Layer(cm, lpd, parent=profile))
        if not self.layers:
            raise ErasureCodeProfileError("lrc: at least one layer needed")
        # chunk_mapping from the global mapping: D positions then the rest
        self.chunk_mapping = [
            i for i, ch in enumerate(mapping) if ch == "D"
        ] + [i for i, ch in enumerate(mapping) if ch != "D"]

    def get_chunk_count(self) -> int:
        return len(self.mapping)

    def get_coding_chunk_count(self) -> int:
        return self.get_chunk_count() - self.k

    def get_alignment(self) -> int:
        return self.k * self.w * 4

    # -- encode ------------------------------------------------------------
    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        """data rows are the k 'D' positions in mapping order; returns all
        chunk positions [chunk_count, cs]."""
        n = self.get_chunk_count()
        cs = data.shape[1]
        buf = np.zeros((n, cs), np.uint8)
        dpos = [i for i, ch in enumerate(self.mapping) if ch == "D"]
        for row, pos in enumerate(dpos):
            buf[pos] = data[row]
        for layer in self.layers:
            sub = np.stack([buf[c] for c in layer.chunks])
            enc = layer.code.encode_chunks(sub[: len(layer.data)])
            for j, c in enumerate(layer.chunks):
                buf[c] = enc[j]
        # external order: mapping positions as-is (the caller reads
        # data chunks through chunk_mapping)
        return buf

    # encode()/encode_prepare() come from the base class (k 'D' rows)

    # -- decode ------------------------------------------------------------
    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: dict[int, np.ndarray],
        chunk_size: int,
    ) -> dict[int, np.ndarray]:
        n = self.get_chunk_count()
        decoded = {
            i: (
                np.asarray(chunks[i], np.uint8).copy()
                if i in chunks
                else np.zeros(chunk_size, np.uint8)
            )
            for i in range(n)
        }
        erasures = {i for i in range(n) if i not in chunks}
        want_missing = want_to_read & erasures
        # sweep the layers until a fixpoint: a later sweep can use chunks
        # an earlier layer just recovered (the reference single-passes and
        # can miss recoverable chunks; iterating is strictly better and
        # keeps minimum_to_decode's peeling analysis honest)
        progress = True
        while want_missing and progress:
            progress = False
            for layer in reversed(self.layers):
                layer_erasures = layer.chunks_set & erasures
                if not layer_erasures:
                    continue
                if len(layer_erasures) > len(layer.coding):
                    continue  # too many for this layer
                sub_chunks = {
                    j: decoded[c]
                    for j, c in enumerate(layer.chunks)
                    if c not in erasures
                }
                try:
                    sub = layer.code.decode_chunks(
                        set(range(len(layer.chunks))), sub_chunks,
                        chunk_size,
                    )
                except (ValueError, np.linalg.LinAlgError):
                    continue
                for j, c in enumerate(layer.chunks):
                    decoded[c] = np.asarray(sub[j], np.uint8)
                    erasures.discard(c)
                progress = True
                want_missing = want_to_read & erasures
                if not want_missing:
                    break
        if want_missing:
            raise ValueError(
                f"lrc: unable to read {sorted(want_missing)} from "
                f"{sorted(chunks)}"
            )
        return decoded

    def _peel_recoverable(self, available: set[int]) -> set[int]:
        """Fixpoint of layer-by-layer repair over chunk *sets* (no data):
        which chunks decode_chunks would eventually recover."""
        have = set(available)
        changed = True
        while changed:
            changed = False
            for layer in self.layers:
                missing = layer.chunks_set - have
                if missing and len(missing) <= len(layer.coding):
                    have |= layer.chunks_set
                    changed = True
        return have

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> set[int]:
        """reference minimum_to_decode: prefer the single layer that can
        repair the erasures locally (reference ErasureCodeLrc.cc:560-730,
        condensed: smallest covering layer wins)."""
        if want_to_read <= available:
            return set(want_to_read)
        erasures = want_to_read - available
        best: set[int] | None = None
        for layer in self.layers:
            if not (erasures <= layer.chunks_set):
                continue
            layer_av = layer.chunks_set & available
            layer_er = layer.chunks_set - available
            if len(layer_er) > len(layer.coding):
                continue
            need = layer_av
            if best is None or len(need) < len(best):
                best = set(need)
        if best is None:
            # multi-layer decode: only claim sufficiency if the peeling
            # fixpoint actually reaches the wanted chunks
            if not (want_to_read <= self._peel_recoverable(available)):
                raise ValueError(
                    f"lrc: want {sorted(want_to_read)} unrecoverable "
                    f"from {sorted(available)}"
                )
            return set(available)
        return best | (want_to_read & available)

    def decode_concat(self, chunks: dict[int, np.ndarray]) -> bytes:
        dpos = [i for i, ch in enumerate(self.mapping) if ch == "D"]
        cs = len(np.asarray(next(iter(chunks.values()))).reshape(-1))
        out = self.decode(set(dpos), chunks, cs)
        return b"".join(
            np.asarray(out[i], np.uint8).tobytes() for i in dpos
        )
