"""Erasure-code interface + shared base logic.

Python-native equivalent of the reference's plugin surface
(`ErasureCodeInterface`, reference src/erasure-code/ErasureCodeInterface.h:
170-462) and the shared base class (`ErasureCode`, reference
src/erasure-code/ErasureCode.{h,cc}): profile parsing, chunk-size/alignment
math, `encode_prepare` split+pad, trivial `minimum_to_decode` (first k
available, reference src/erasure-code/ErasureCode.cc:103-120), and the
encode/decode driver loops.  Buffers are numpy uint8 arrays (bytes in/out at
the API edge); the heavy per-stripe math is delegated to a backend engine
(host numpy or the TPU path in ec.jax_backend).
"""

from __future__ import annotations

import numpy as np

SIMD_ALIGN = 32  # reference src/erasure-code/ErasureCode.cc:42


class ErasureCodeProfileError(ValueError):
    pass


def _get_int(profile: dict, key: str, default: int) -> int:
    v = profile.get(key, default)
    try:
        return int(v)
    except (TypeError, ValueError):
        raise ErasureCodeProfileError(f"{key}={v!r} is not an integer")


class ErasureCode:
    """Base code: systematic, chunked; subclasses fill k/m and the chunk
    math.  Mirrors the reference base-class semantics the OSD/benchmark
    depend on."""

    def __init__(self):
        self.k = 0
        self.m = 0
        self.w = 8
        self.chunk_mapping: list[int] = []
        self.profile: dict = {}

    # -- profile -----------------------------------------------------------
    def init(self, profile: dict) -> None:
        self.profile = dict(profile)
        self.parse(profile)

    def parse(self, profile: dict) -> None:
        self.k = _get_int(profile, "k", self.k or 2)
        self.m = _get_int(profile, "m", self.m or 1)
        self.w = _get_int(profile, "w", 8)
        if self.k < 1:
            raise ErasureCodeProfileError(f"k={self.k} must be >= 1")
        if self.m < 1:
            raise ErasureCodeProfileError(f"m={self.m} must be >= 1")
        mapping = profile.get("mapping")
        if mapping:
            # 'D' positions first (data), then the rest, in order
            # (reference src/erasure-code/ErasureCode.cc to_mapping)
            self.chunk_mapping = [
                i for i, c in enumerate(mapping) if c == "D"
            ] + [i for i, c in enumerate(mapping) if c != "D"]

    # -- geometry ----------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_coding_chunk_count(self) -> int:
        return self.m

    def get_sub_chunk_count(self) -> int:
        return 1  # array codes (clay) override

    def get_alignment(self) -> int:
        # jerasure reed_sol_van: k * w * sizeof(int)
        return self.k * self.w * 4

    def get_chunk_size(self, object_size: int) -> int:
        """Pad object to `alignment`, split into k (reference jerasure
        get_chunk_size semantics)."""
        alignment = self.get_alignment()
        tail = object_size % alignment
        padded = object_size + (alignment - tail if tail else 0)
        return padded // self.k

    # -- mapping -----------------------------------------------------------
    def chunk_index(self, i: int) -> int:
        return self.chunk_mapping[i] if self.chunk_mapping else i

    # -- minimum sets ------------------------------------------------------
    def _minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> set[int]:
        """First-k-available rule (reference ErasureCode.cc:103-120)."""
        if want_to_read <= available:
            return set(want_to_read)
        if len(available) < self.k:
            raise ValueError(
                f"need {self.k} chunks, only {len(available)} available"
            )
        return set(sorted(available)[: self.k])

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> set[int]:
        return self._minimum_to_decode(want_to_read, available)

    def minimum_to_decode_with_cost(
        self, want_to_read: set[int], available: dict[int, int]
    ) -> set[int]:
        """Cost-blind base version (reference ErasureCode.cc:122-133)."""
        return self.minimum_to_decode(want_to_read, set(available))

    # -- encode ------------------------------------------------------------
    def encode_prepare(self, data: bytes | np.ndarray) -> np.ndarray:
        """Split+zero-pad into k rows of chunk_size (reference
        ErasureCode.cc:151-186 encode_prepare)."""
        buf = np.frombuffer(bytes(data), np.uint8)
        cs = self.get_chunk_size(len(buf))
        out = np.zeros((self.k, cs), np.uint8)
        flat = out.reshape(-1)
        flat[: len(buf)] = buf
        return out

    def encode(
        self, want_to_encode: set[int], data: bytes | np.ndarray
    ) -> dict[int, np.ndarray]:
        chunks = self.encode_prepare(data)
        encoded = self.encode_chunks(chunks)
        return {i: encoded[i] for i in want_to_encode}

    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        """[k, cs] data rows -> [k+m, cs] all chunks."""
        raise NotImplementedError

    # -- decode ------------------------------------------------------------
    def decode(
        self,
        want_to_read: set[int],
        chunks: dict[int, np.ndarray],
        chunk_size: int | None = None,
    ) -> dict[int, np.ndarray]:
        """reference ErasureCode.cc _decode: trivial path if all present,
        else delegate to decode_chunks."""
        if want_to_read <= set(chunks):
            return {i: np.asarray(chunks[i], np.uint8) for i in want_to_read}
        if chunk_size is None:
            chunk_size = len(next(iter(chunks.values())))
        full = self.decode_chunks(want_to_read, chunks, chunk_size)
        return {i: full[i] for i in want_to_read}

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: dict[int, np.ndarray],
        chunk_size: int,
    ) -> dict[int, np.ndarray]:
        raise NotImplementedError

    def decode_concat(self, chunks: dict[int, np.ndarray]) -> bytes:
        """Reassemble the original object bytes from data chunks
        (reference ErasureCode.cc decode_concat)."""
        want = set(range(self.k))
        out = self.decode(want, chunks)
        return b"".join(
            out[i].tobytes() for i in range(self.k)
        )
