"""Plugin registry — name → factory, profile-driven.

Python-native equivalent of the reference's dlopen registry
(`ErasureCodePluginRegistry`, reference src/erasure-code/ErasureCodePlugin.
h:45-79, load via dlopen at ErasureCodePlugin.cc:120-128): here plugins are
entries in a table (extensible via register_plugin) and `create_erasure_code`
plays `factory`: pick plugin by profile["plugin"], build, init(profile).

Plugin name map (reference → here):
  jerasure  → techniques reed_sol_van / reed_sol_r6_op / cauchy_orig /
              cauchy_good        (bit-matrix XOR techniques: see ec.rs)
  isa       → techniques reed_sol_van (isa Vandermonde) / cauchy
  jax       → this framework's native plugin: reed_sol_van matrices with
              the TPU backend engine by default
  clay / shec / lrc → layered codes (ec.clay / ec.shec / ec.lrc)
  example   → toy XOR(k, m=1) code (mirrors the test fixture
              reference src/test/erasure-code/ErasureCodeExample.h)

Engine knobs shared by every matrix-code plugin: profile["backend"]
(numpy | native | jax) picks the per-stripe math engine and, for jax,
profile["strategy"] picks one of ec.jax_backend.STRATEGIES (lrc
propagates both into its layers; CEPH_TPU_EC_STRATEGY overrides all).
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.ec.interface import ErasureCode, ErasureCodeProfileError


def _make_jerasure(profile: dict) -> ErasureCode:
    from ceph_tpu.ec.rs import RSErasureCode

    return RSErasureCode(profile.get("technique", "reed_sol_van"))


def _make_isa(profile: dict) -> ErasureCode:
    from ceph_tpu.ec.rs import RSErasureCode

    tech = profile.get("technique", "reed_sol_van")
    mapped = {
        "reed_sol_van": "isa_reed_sol_van",
        "cauchy": "isa_cauchy",
    }.get(tech)
    if mapped is None:
        raise ErasureCodeProfileError(f"isa: unknown technique {tech!r}")
    return RSErasureCode(mapped)


def _make_jax(profile: dict) -> ErasureCode:
    from ceph_tpu.ec.rs import RSErasureCode

    profile.setdefault("backend", "jax")
    return RSErasureCode(profile.get("technique", "reed_sol_van"))


class XorExample(ErasureCode):
    """k data chunks + 1 XOR parity (the reference's example/test code)."""

    def parse(self, profile: dict) -> None:
        super().parse(profile)
        if self.m != 1:
            raise ErasureCodeProfileError("example code requires m=1")

    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        parity = np.bitwise_xor.reduce(data, axis=0)[None, :]
        return np.concatenate([data, parity], axis=0)

    def decode_chunks(self, want_to_read, chunks, chunk_size):
        out = dict(chunks)
        missing = sorted(set(want_to_read) - set(chunks))
        if not missing:
            return out
        if len(missing) > 1 or len(chunks) < self.k:
            raise ValueError("XOR code can rebuild at most one chunk")
        acc = np.zeros(chunk_size, np.uint8)
        for v in chunks.values():
            acc ^= np.asarray(v, np.uint8)
        out[missing[0]] = acc
        return out


def _make_clay(profile: dict) -> ErasureCode:
    from ceph_tpu.ec.clay import ClayCode

    return ClayCode()


def _make_shec(profile: dict) -> ErasureCode:
    from ceph_tpu.ec.shec import ShecCode

    return ShecCode()


def _make_lrc(profile: dict) -> ErasureCode:
    from ceph_tpu.ec.lrc import LrcCode

    return LrcCode()


_PLUGINS = {
    "jerasure": _make_jerasure,
    "isa": _make_isa,
    "jax": _make_jax,
    "example": lambda p: XorExample(),
    "clay": _make_clay,
    "shec": _make_shec,
    "lrc": _make_lrc,
}


def register_plugin(name: str, factory) -> None:
    _PLUGINS[name] = factory


def list_plugins() -> list[str]:
    return sorted(_PLUGINS)


def create_erasure_code(profile: dict) -> ErasureCode:
    """ErasureCodePluginRegistry::factory equivalent."""
    profile = dict(profile)
    name = profile.get("plugin", "jerasure")
    try:
        factory = _PLUGINS[name]
    except KeyError:
        raise ErasureCodeProfileError(f"unknown plugin {name!r}")
    code = factory(profile)
    code.init(profile)
    return code
