from ceph_tpu.osd.types import PgId, PgPool, PoolType
from ceph_tpu.osd.osdmap import OSDMap
