"""PG / pool types — the seed math between objects and CRUSH inputs.

Covers the reference's pg_t and pg_pool_t placement-relevant surface
(reference src/osd/osd_types.{h,cc}): stable_mod folding of the placement
seed onto pg_num, and the pool-mixing pps ("placement seed") that feeds
crush_do_rule.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import IntEnum

from ceph_tpu.core.intmath import pg_mask_for
from ceph_tpu.core.rjenkins import crush_hash32_2, str_hash_rjenkins

CEPH_NOSNAP = (1 << 64) - 2


class PoolType(IntEnum):
    # reference src/osd/osd_types.h pg_pool_t::TYPE_*
    REPLICATED = 1
    ERASURE = 3


@dataclass(frozen=True, order=True)
class PgId:
    """pg_t: (pool, seed) (reference src/osd/osd_types.h struct pg_t)."""

    pool: int
    seed: int

    def __str__(self):
        return f"{self.pool}.{self.seed:x}"

    @classmethod
    def parse(cls, s: str) -> "PgId":
        p, ps = s.split(".")
        return cls(int(p), int(ps, 16))


FLAG_HASHPSPOOL = 1 << 0  # reference src/osd/osd_types.h pg_pool_t::FLAG_*
FLAG_FULL = 1 << 1
FLAG_EC_OVERWRITES = 1 << 12


@dataclass
class PgPool:
    """pg_pool_t placement surface (reference src/osd/osd_types.h:1310+)."""

    type: PoolType = PoolType.REPLICATED
    size: int = 3
    min_size: int = 2
    pg_num: int = 64
    pgp_num: int = 0  # 0 => same as pg_num
    crush_rule: int = 0
    flags: int = FLAG_HASHPSPOOL
    object_hash: int = 2  # CEPH_STR_HASH_RJENKINS
    erasure_code_profile: str = ""
    pg_num_pending: int = 0
    expected_num_objects: int = 0

    def __post_init__(self):
        if not self.pgp_num:
            self.pgp_num = self.pg_num

    @property
    def pg_num_mask(self) -> int:
        return pg_mask_for(self.pg_num)

    @property
    def pgp_num_mask(self) -> int:
        return pg_mask_for(self.pgp_num)

    def can_shift_osds(self) -> bool:
        """Replicated pools compact gaps; EC pools are positional
        (reference src/osd/osd_types.h can_shift_osds)."""
        return self.type == PoolType.REPLICATED

    def is_erasure(self) -> bool:
        return self.type == PoolType.ERASURE

    def is_replicated(self) -> bool:
        return self.type == PoolType.REPLICATED

    # -- seed math ---------------------------------------------------------
    def raw_pg_to_pg(self, pg: PgId) -> PgId:
        """fold full-precision ps onto pg_num (reference
        src/osd/osd_types.cc:1787-1791)."""
        lo = pg.seed & self.pg_num_mask
        seed = lo if lo < self.pg_num else pg.seed & (self.pg_num_mask >> 1)
        return PgId(pg.pool, seed)

    def raw_pg_to_pps(self, pg: PgId) -> int:
        """placement seed fed to CRUSH (reference
        src/osd/osd_types.cc:1798-1814)."""
        lo = pg.seed & self.pgp_num_mask
        ps = lo if lo < self.pgp_num else pg.seed & (self.pgp_num_mask >> 1)
        if self.flags & FLAG_HASHPSPOOL:
            return int(crush_hash32_2(ps, pg.pool & 0xFFFFFFFF))
        return ps + pg.pool

    def hash_key(self, key: str, ns: str = "") -> int:
        """object name (+namespace) -> 32-bit hash (reference
        src/osd/osd_types.cc:1766-1777)."""
        if self.object_hash != 2:  # CEPH_STR_HASH_RJENKINS
            raise NotImplementedError(
                f"object_hash {self.object_hash} (only rjenkins supported)"
            )
        if not ns:
            return str_hash_rjenkins(key.encode())
        return str_hash_rjenkins(ns.encode() + b"\x1f" + key.encode())

    def object_to_pg(self, key: str, ns: str = "") -> PgId:
        return PgId(-1, self.hash_key(key, ns))  # pool filled by caller
