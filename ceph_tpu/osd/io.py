"""OSDMap / CrushMap native serialization (JSON).

The checkpoint/resume surface of the framework: everything durable in the
reference is a versioned binary encoding (OSDMap::encode/decode, reference
src/osd/OSDMap.cc:2914,3249; CrushWrapper::encode :2941) persisted by the
mon and read by the CLIs.  This module is our own format — explicit JSON of
the same state — used by the CLIs and the rebalance simulator; the
wire-compatible binary codec (for reading real cluster artifacts) lives in
ceph_tpu.osd.codec (separate module) once implemented.
"""

from __future__ import annotations

import json

from ceph_tpu.crush.compiler import compile_text, decompile
from ceph_tpu.crush.types import CrushMap
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.types import PgId, PgPool, PoolType

FORMAT_VERSION = 1


def osdmap_to_dict(m: OSDMap) -> dict:
    return {
        "format_version": FORMAT_VERSION,
        "epoch": m.epoch,
        "max_osd": m.max_osd,
        "osd_state": list(m.osd_state),
        "osd_weight": list(m.osd_weight),
        "osd_primary_affinity": (
            list(m.osd_primary_affinity)
            if m.osd_primary_affinity is not None
            else None
        ),
        "pools": {
            str(pid): {
                "name": m.pool_name.get(pid, f"pool{pid}"),
                "type": int(p.type),
                "size": p.size,
                "min_size": p.min_size,
                "pg_num": p.pg_num,
                "pgp_num": p.pgp_num,
                "crush_rule": p.crush_rule,
                "flags": p.flags,
                "erasure_code_profile": p.erasure_code_profile,
            }
            for pid, p in m.pools.items()
        },
        "erasure_code_profiles": m.erasure_code_profiles,
        "pg_temp": {str(pg): v for pg, v in m.pg_temp.items()},
        "primary_temp": {str(pg): v for pg, v in m.primary_temp.items()},
        "pg_upmap": {str(pg): v for pg, v in m.pg_upmap.items()},
        "pg_upmap_items": {
            str(pg): [list(pair) for pair in v]
            for pg, v in m.pg_upmap_items.items()
        },
        "crush": decompile(m.crush),
    }


def osdmap_from_dict(d: dict) -> OSDMap:
    crush = compile_text(d["crush"])
    m = OSDMap(crush)
    m.epoch = d.get("epoch", 1)
    m.set_max_osd(d["max_osd"])
    m.osd_state = list(d["osd_state"])
    m.osd_weight = list(d["osd_weight"])
    pa = d.get("osd_primary_affinity")
    m.osd_primary_affinity = list(pa) if pa is not None else None
    for pid_s, pd in d.get("pools", {}).items():
        pool = PgPool(
            type=PoolType(pd["type"]),
            size=pd["size"],
            min_size=pd.get("min_size", 2),
            pg_num=pd["pg_num"],
            pgp_num=pd.get("pgp_num", pd["pg_num"]),
            crush_rule=pd.get("crush_rule", 0),
            flags=pd.get("flags", 1),
            erasure_code_profile=pd.get("erasure_code_profile", ""),
        )
        m.add_pool(pd.get("name", f"pool{pid_s}"), pool, int(pid_s))
    m.erasure_code_profiles = {
        k: dict(v) for k, v in d.get("erasure_code_profiles", {}).items()
    }
    m.pg_temp = {
        PgId.parse(k): list(v) for k, v in d.get("pg_temp", {}).items()
    }
    m.primary_temp = {
        PgId.parse(k): v for k, v in d.get("primary_temp", {}).items()
    }
    m.pg_upmap = {
        PgId.parse(k): list(v) for k, v in d.get("pg_upmap", {}).items()
    }
    m.pg_upmap_items = {
        PgId.parse(k): [tuple(p) for p in v]
        for k, v in d.get("pg_upmap_items", {}).items()
    }
    return m


def save_osdmap(m: OSDMap, path: str, fmt: str = "bin") -> None:
    """fmt="bin" writes the reference wire format (what the real
    osdmaptool produces/consumes); fmt="json" writes the native JSON."""
    if fmt == "bin":
        from ceph_tpu.osd.codec import encode_osdmap

        with open(path, "wb") as f:
            f.write(encode_osdmap(m))
        return
    with open(path, "w") as f:
        json.dump(osdmap_to_dict(m), f, indent=1)


def load_osdmap(path: str) -> OSDMap:
    """Auto-detects the reference binary wire format vs native JSON."""
    from ceph_tpu.osd.codec import decode_osdmap, looks_like_osdmap

    with open(path, "rb") as f:
        data = f.read()
    if looks_like_osdmap(data):
        return decode_osdmap(data)
    return osdmap_from_dict(json.loads(data.decode()))


def save_crush_text(m: CrushMap, path: str) -> None:
    with open(path, "w") as f:
        f.write(decompile(m))


def load_crush_text(path: str) -> CrushMap:
    """Text or binary (wire format), auto-detected."""
    from ceph_tpu.crush.codec import decode_crushmap, looks_like_crushmap

    with open(path, "rb") as f:
        data = f.read()
    if looks_like_crushmap(data):
        return decode_crushmap(data)
    return compile_text(data.decode())
