"""Batched PG→OSD pipeline — the full placement stack as one XLA call.

TPU-native re-expression of the reference's 5-stage mapping
(reference src/osd/OSDMap.cc:2435-2715): for every PG of a pool,

    ps ──stable_mod──► pps ──crush rule kernel──► raw ──upmap──► up ──►
        primary affinity ──► (up, up_primary) ──pg_temp──► (acting, acting_primary)

The CRUSH rule kernel is the vmapped trace from ceph_tpu.crush.mapper_jax;
everything around it is masked lane arithmetic on [W]-wide vectors (W = pool
size, <= ~20), so the whole pipeline fuses into the rule kernel's program and
the PG axis shards freely over a device mesh.

Sparse host-side overrides (pg_upmap, pg_upmap_items, pg_temp, primary_temp —
hash maps in the reference, reference src/osd/OSDMap.h:567-575) become dense
per-PG tensors built once by `build_overlays`; each overlay stage is gated by
a *static* flag so the no-override case (the big-batch benchmark) compiles to
nothing.

Bit-exactness contract: same results as OSDMap._pg_to_up_acting_osds (the
host oracle in ceph_tpu.osd.osdmap) for every PG, padded to a fixed width
with CRUSH_ITEM_NONE; differential-tested in tests/test_pipeline_jax.py.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu import obs
from ceph_tpu.core.intmath import pg_mask_for, stable_mod
from ceph_tpu.runtime import faults
from ceph_tpu.core.rjenkins import crush_hash32_2
from ceph_tpu.crush import mapper_ref
from ceph_tpu.crush.mapper_jax import (
    FAST_WINDOW_EXTRA,
    compile_rule,
    device_tables,
    rescue_pad_for,
)
from ceph_tpu.crush.soa import CrushArrays, build_arrays
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.osdmap import (
    DEFAULT_PRIMARY_AFFINITY,
    MAX_PRIMARY_AFFINITY,
    OSDMap,
)
from ceph_tpu.osd.types import FLAG_HASHPSPOOL, PgId


_L = obs.logger_for("pipeline")
_L.add_u64("pgs_mapped", "placement seeds mapped through the batched pipeline")
_L.add_u64("unresolved_pgs", "fast-window inconclusive lanes (exact-loop rescued)")
_L.add_u64("rescue_invocations", "loop-kernel rescue passes")
_L.add_u64("pipe_cache_hits",
           "PoolMapper constructions served by _PIPE_CACHE (no new jit)")
_L.add_u64("pipe_cache_misses",
           "PoolMapper constructions that created a new jitted pipeline")
_L.add_quantile("map_block_seconds",
                "per-block map_block dispatch wall-time distribution "
                "(warm dispatches only — cold compiles are booked into "
                "*_compile_seconds, never into this tail; p50/p99 in "
                "the dump)")


def _h2(a, b):
    return crush_hash32_2(
        jnp.asarray(a).astype(jnp.uint32),
        jnp.asarray(b).astype(jnp.uint32),
        xp=jnp,
    )


@dataclass(frozen=True)
class PoolSpec:
    """Static per-pool parameters baked into the compiled pipeline."""

    pool_id: int
    size: int
    pg_num: int
    pgp_num: int
    can_shift: bool  # replicated pools compact; EC pools are positional
    hashpspool: bool
    ruleno: int
    max_osd: int  # OSDMap::max_osd (exists/upmap id bound)
    out_width: int  # padded output width (>= size)

    @classmethod
    def for_pool(
        cls, m: OSDMap, pool_id: int, extra_width: int = 0
    ) -> "PoolSpec":
        pool = m.pools[pool_id]
        ruleno = mapper_ref.find_rule(
            m.crush, pool.crush_rule, int(pool.type), pool.size
        )
        return cls(
            pool_id=pool_id,
            size=pool.size,
            pg_num=pool.pg_num,
            pgp_num=pool.pgp_num,
            can_shift=pool.can_shift_osds(),
            hashpspool=bool(pool.flags & FLAG_HASHPSPOOL),
            ruleno=ruleno,
            max_osd=m.max_osd,
            out_width=max(pool.size, extra_width),
        )


@dataclass
class Overlays:
    """Dense per-PG override tensors for one pool ([N = pg_num] rows).
    All-empty overlays are represented as None fields; the static gates in
    compile_pipeline key off which fields are present."""

    upmap_full: np.ndarray | None = None  # [N, Wu] i32, NONE-padded
    upmap_len: np.ndarray | None = None  # [N] i32 (0 = no entry)
    upmap_pairs: np.ndarray | None = None  # [N, P, 2] i32, NONE-padded
    temp: np.ndarray | None = None  # [N, Wt] i32, NONE-padded
    temp_len: np.ndarray | None = None  # [N] i32 (-1 = no entry)
    primary_temp: np.ndarray | None = None  # [N] i32 (-1 = none)

    @property
    def n_pairs(self) -> int:
        return 0 if self.upmap_pairs is None else self.upmap_pairs.shape[1]

    @property
    def extra_width(self) -> int:
        w = 0
        if self.upmap_full is not None:
            w = max(w, self.upmap_full.shape[1])
        if self.temp is not None:
            w = max(w, self.temp.shape[1])
        return w


def build_overlays(m: OSDMap, pool_id: int) -> Overlays:
    """Freeze the sparse override dicts into dense per-PG tensors."""
    pool = m.pools[pool_id]
    n = pool.pg_num
    ov = Overlays()

    full = {
        pg.seed: v
        for pg, v in m.pg_upmap.items()
        if pg.pool == pool_id and pg.seed < n
    }
    if full:
        w = max(len(v) for v in full.values())
        ov.upmap_full = np.full((n, w), ITEM_NONE, np.int32)
        ov.upmap_len = np.zeros(n, np.int32)
        for s, v in full.items():
            ov.upmap_full[s, : len(v)] = v
            ov.upmap_len[s] = len(v)

    items = {
        pg.seed: v
        for pg, v in m.pg_upmap_items.items()
        if pg.pool == pool_id and pg.seed < n
    }
    if items:
        p = max(len(v) for v in items.values())
        ov.upmap_pairs = np.full((n, p, 2), ITEM_NONE, np.int32)
        for s, v in items.items():
            for j, (frm, to) in enumerate(v):
                ov.upmap_pairs[s, j] = (frm, to)

    temps = {
        pg.seed: v
        for pg, v in m.pg_temp.items()
        if pg.pool == pool_id and pg.seed < n
    }
    if temps:
        w = max((len(v) for v in temps.values()), default=1) or 1
        ov.temp = np.full((n, w), ITEM_NONE, np.int32)
        ov.temp_len = np.full(n, -1, np.int32)
        for s, v in temps.items():
            ov.temp[s, : len(v)] = v
            ov.temp_len[s] = len(v)

    prim = {
        pg.seed: v
        for pg, v in m.primary_temp.items()
        if pg.pool == pool_id and pg.seed < n
    }
    if prim:
        ov.primary_temp = np.full(n, -1, np.int32)
        for s, v in prim.items():
            ov.primary_temp[s] = v
    return ov


def _compact(v, keep, width):
    """Stable left-compaction of kept lanes, NONE-padded (the vector `erase`
    loops of reference src/osd/OSDMap.cc:2416-2427, 2516-2522)."""
    idx = jnp.cumsum(keep.astype(jnp.int32)) - 1
    out = jnp.full(width, ITEM_NONE, jnp.int32)
    return out.at[jnp.where(keep, idx, width)].set(
        jnp.where(keep, v, ITEM_NONE), mode="drop"
    )


def _pad_lanes(v, width):
    n = v.shape[0]
    if n >= width:
        return v[:width]
    return jnp.concatenate(
        [v, jnp.full(width - n, ITEM_NONE, v.dtype)]
    )


def _first_not_none(v):
    """_pick_primary (reference src/osd/OSDMap.cc:2455-2463)."""
    ok = v != ITEM_NONE
    i = jnp.argmax(ok)
    return jnp.where(jnp.any(ok), v[i], -1)


def compile_pipeline(
    A: CrushArrays,
    spec: PoolSpec,
    *,
    with_upmap_full: bool = False,
    n_upmap_pairs: int = 0,
    with_temp: bool = False,
    with_primary_temp: bool = False,
    with_primary_affinity: bool = True,
    path: str = "auto",
    with_flag: bool = False,
    with_diag: bool = False,
    window_extra: int = FAST_WINDOW_EXTRA,
    pool_operands: bool = False,
    raw_only: bool = False,
    with_raw: bool = False,
):
    """Build the single-PG mapping function for one pool; vmap/jit-ready.

    Returns fn(ps, dev, ov) -> (up[W], up_primary, acting[W], acting_primary)
    where `dev` is the padded dict built by PoolMapper (exists/up bool[DV],
    weight/primary_affinity u32[DV], DV = max(crush devices, max_osd)) and
    `ov` holds this PG's overlay rows (only statically-enabled ones read).

    path / with_flag / window_extra: forwarded to the CRUSH kernel (see
    ceph_tpu.crush.mapper_jax.compile_rule).  With with_flag the tuple
    grows a trailing `unresolved` bool; PoolMapper.map_batch uses it to
    recompute flagged PGs through the loop kernel (bit-exactness rescue).
    A small window_extra shrinks the fast kernel's candidate window —
    more lanes flag unresolved and rescue (the fast-window/rescue trade
    of PROFILE_r05 §5); exactness is unaffected.

    pool_operands: read pool_id / pgp_num / pgp_mask from dev["pool"]
    (u32 scalar operands; ceph_stable_mod is branchless so the trace is
    identical for every value) instead of baking them — pools that share
    structure (rule, size, osd bound, overlay gates) then share one
    executable regardless of pool id or pg count (cache_key drops them).

    with_diag: the tuple grows a trailing diagnostics pytree from the
    instrumented CRUSH kernel (see mapper_jax.compile_rule with_diag) —
    the device-side flight recorder behind PoolMapper.diagnose.
    Requires with_flag; a static plan fact folded into cache_key, so the
    default pipeline's trace and cache entry are untouched.

    raw_only: stop after stage 2 + _remove_nonexistent_osds and return
    just the raw descent row (plus the unresolved flag under
    with_flag) — bit-identical to the host `_pg_to_raw_osds` result,
    NONE-padded to out_width.

    with_raw: append that same raw row as a TRAILING output of the full
    pipeline — the loop (exact) kernel carries it for free, so the
    operand ClusterState's overlay fixup reads device-resident raw
    results from the kernel it already compiled and warmed (no second
    descent program): the cheap host steps (upmap application, up/down
    filter, affinity) replay on the fetched O(overlay) rows.  Both are
    static plan facts in cache_key.
    """
    assert not (with_diag and not with_flag), (
        "with_diag needs with_flag: flagged lanes carry garbage "
        "diagnostics and the caller must mask or host-rescue them"
    )
    assert not (raw_only and with_diag), "raw_only excludes with_diag"
    assert not (with_raw and (raw_only or with_diag or with_flag)), (
        "with_raw rides the exact (flagless) full pipeline only"
    )
    W = spec.out_width
    R = spec.size
    rule_fn = (
        compile_rule(A, spec.ruleno, R, path=path, with_flag=with_flag,
                     with_diag=with_diag, window_extra=window_extra)
        if spec.ruleno >= 0 else None
    )
    D = A.max_devices  # crush device-id bound (weight vec for the kernel)
    MO = spec.max_osd  # OSDMap id bound (exists / upmap targets)
    DV = max(D, MO, 1)
    pgp_mask = pg_mask_for(spec.pgp_num)

    def fn(ps, dev, ov):
        ps = jnp.asarray(ps).astype(jnp.uint32)
        # per-map CRUSH tables ride in dev["crush"] as runtime operands
        # (device_put once by PoolMapper.refresh_dev); absent — bare-fn
        # callers — the kernel falls back to trace constants
        tabs = dev.get("crush") if isinstance(dev, dict) else None
        exists = dev["exists"]  # bool[DV]
        upb = dev["up"]  # bool[DV]
        weight = dev["weight"]  # u32[DV]
        aff = dev["primary_affinity"]  # u32[DV]

        # -- stage 1: placement seed (reference src/osd/osd_types.cc:1798) -
        if pool_operands:
            # u32 scalars: {pool_id, pgp_num, pgp_mask, max_osd}
            pool = dev["pool"]
            p_pgp, p_mask = pool["pgp_num"], pool["pgp_mask"]
            p_id = pool["pool_id"]
            # the OSDMap id bound is an OPERAND (and the vector clip
            # bound comes from the padded operand SHAPE): growing
            # max_osd inside the padding quantum — cluster expansion —
            # reuses the compiled executable instead of re-keying
            mo = pool["max_osd"].astype(jnp.int32)
            dv = exists.shape[0]
        else:
            p_pgp, p_mask = spec.pgp_num, pgp_mask
            p_id = jnp.uint32(spec.pool_id & 0xFFFFFFFF)
            mo = MO
            dv = DV

        def osd_ok(v, tbl):
            """valid OSDMap id with tbl true (exists()/is_up() lookups)."""
            return (v >= 0) & (v < mo) & tbl[jnp.clip(v, 0, dv - 1)]
        ps2 = stable_mod(ps, p_pgp, p_mask, xp=jnp)
        if spec.hashpspool:
            pps = _h2(ps2, p_id)
        else:
            pps = (ps2 + p_id).astype(jnp.uint32)

        # -- stage 2: CRUSH (reference src/osd/OSDMap.cc:2444-2447) --------
        unresolved = jnp.bool_(False)
        dg = None
        if rule_fn is None:
            raw = jnp.full(W, ITEM_NONE, jnp.int32)
            if with_diag:  # no rule: trivially bad, nothing decided
                dg = {"tries": jnp.zeros(0, jnp.int32),
                      "coll": jnp.int32(0), "rej": jnp.int32(0),
                      "skip": jnp.int32(0), "bad": jnp.int32(1),
                      "steps": jnp.zeros((0, R), jnp.int32)}
        elif with_diag:
            raw, unresolved, dg = rule_fn(pps, weight[:D], tabs)
            raw = _pad_lanes(raw, W)
        elif with_flag:
            raw, unresolved = rule_fn(pps, weight[:D], tabs)
            raw = _pad_lanes(raw, W)
        else:
            raw = _pad_lanes(rule_fn(pps, weight[:D], tabs), W)

        # -- _remove_nonexistent_osds (reference src/osd/OSDMap.cc:2412) ---
        if spec.can_shift:
            raw = _compact(raw, osd_ok(raw, exists), W)
        else:
            raw = jnp.where(
                osd_ok(raw, exists) | (raw == ITEM_NONE), raw, ITEM_NONE
            )
        if raw_only:
            return (raw, unresolved) if with_flag else raw
        raw_result = raw  # stage 3 mutates `raw` (upmap); the raw
        # output is the PRE-overlay row (host _pg_to_raw_osds)

        # -- stage 3: upmap (reference src/osd/OSDMap.cc:2465-2509) --------
        def marked_out(v):
            """the reject guard: valid id AND weight 0 (OSDMap.cc:2472,2496)."""
            return (
                (v != ITEM_NONE) & (v >= 0) & (v < mo)
                & (weight[jnp.clip(v, 0, dv - 1)] == 0)
            )

        # a pg_upmap entry with an out target aborts the whole _apply_upmap
        # (the early `return` at reference src/osd/OSDMap.cc:2474), skipping
        # pg_upmap_items as well
        upmap_aborted = jnp.bool_(False)
        if with_upmap_full:
            row = ov["upmap_full"]  # [Wu <= W]
            rl = ov["upmap_len"]
            lane_u = jnp.arange(row.shape[0])
            bad = jnp.any(marked_out(row) & (lane_u < rl))
            upmap_aborted = (rl > 0) & bad
            ok = (rl > 0) & ~bad
            repl = jnp.where(jnp.arange(W) < rl, _pad_lanes(row, W), ITEM_NONE)
            raw = jnp.where(ok, repl, raw)
        if n_upmap_pairs:
            pairs = ov["upmap_pairs"]  # [P, 2]
            lane = jnp.arange(W)
            for j in range(n_upmap_pairs):
                frm, to = pairs[j, 0], pairs[j, 1]
                present = jnp.any(raw == to)
                match = (raw == frm) & ~marked_out(to)
                pos = jnp.argmax(match)
                do = (
                    (frm != ITEM_NONE) & ~present & jnp.any(match)
                    & ~upmap_aborted
                )
                raw = jnp.where(do & (lane == pos), to, raw)

        # -- stage 4: raw → up (reference src/osd/OSDMap.cc:2512-2535) -----
        alive = osd_ok(raw, exists & upb)
        if spec.can_shift:
            up = _compact(raw, alive, W)
        else:
            up = jnp.where(alive, raw, ITEM_NONE)
        up_primary = _first_not_none(up)

        # -- stage 5: primary affinity (reference src/osd/OSDMap.cc:2537) --
        if with_primary_affinity:
            nonnone = up != ITEM_NONE
            a = aff[jnp.clip(up, 0, dv - 1)]
            gate = jnp.any(nonnone & (a != DEFAULT_PRIMARY_AFFINITY))
            h = (_h2(pps, up) >> 16).astype(jnp.uint32)
            rejected = nonnone & (a < MAX_PRIMARY_AFFINITY) & (h >= a)
            accepted = nonnone & ~rejected
            lane = jnp.arange(W)
            pos = jnp.where(
                jnp.any(accepted),
                jnp.argmax(accepted),
                jnp.where(jnp.any(nonnone), jnp.argmax(nonnone), -1),
            )
            do = gate & (pos >= 0)
            new_primary = jnp.where(do, up[jnp.maximum(pos, 0)], up_primary)
            if spec.can_shift:
                shifted = jnp.where(
                    (lane > 0) & (lane <= pos),
                    up[jnp.maximum(lane - 1, 0)],
                    up,
                )
                shifted = shifted.at[0].set(new_primary)
                up = jnp.where(do & (pos > 0), shifted, up)
            up_primary = new_primary

        # -- pg_temp / primary_temp (reference src/osd/OSDMap.cc:2592) -----
        acting, acting_primary = up, up_primary
        if with_temp or with_primary_temp:
            pt = ov["primary_temp"] if with_primary_temp else jnp.int32(-1)
            if with_temp:
                trow = _pad_lanes(ov["temp"], W)  # Wt <= W by construction
                tlen = ov["temp_len"]
                has_temp = tlen >= 0
                in_row = jnp.arange(W) < tlen
                t_alive = osd_ok(trow, exists & upb) & in_row
                if spec.can_shift:
                    filt = _compact(trow, t_alive, W)
                    t_n = jnp.sum(t_alive.astype(jnp.int32))
                else:
                    filt = jnp.where(t_alive, trow, ITEM_NONE)
                    filt = jnp.where(in_row, filt, ITEM_NONE)
                    t_n = jnp.maximum(tlen, 0)
                t_primary = jnp.where(pt >= 0, pt, _first_not_none(filt))
                use_temp = has_temp & (t_n > 0)
                acting = jnp.where(use_temp, filt, up)
                acting_primary = jnp.where(
                    use_temp, t_primary, jnp.where(pt >= 0, pt, up_primary)
                )
            else:
                acting_primary = jnp.where(pt >= 0, pt, up_primary)
        if with_diag:
            return up, up_primary, acting, acting_primary, unresolved, dg
        if with_flag:
            return up, up_primary, acting, acting_primary, unresolved
        if with_raw:
            return up, up_primary, acting, acting_primary, raw_result
        return up, up_primary, acting, acting_primary

    # structural signature: everything baked into the trace above (pool
    # statics, overlay gates, kernel path) + the CRUSH kernel's own
    # cache_key.  Equal cache_keys <=> identical traces, so _PIPE_CACHE
    # can hand the same jitted executable to any map that differs only
    # in operand content (weights, osd state, choose_args values).
    fn.cache_key = (
        "pipe",
        # with pool_operands the pool identity/pg counts AND the OSDMap
        # id bound are operands — structurally identical pools (and the
        # same cluster across expansions inside the vector-padding
        # quantum) share the executable
        (None if pool_operands else
         (spec.pool_id, spec.pg_num, spec.pgp_num),
         spec.size, spec.can_shift, spec.hashpspool, spec.ruleno,
         None if pool_operands else spec.max_osd, spec.out_width),
        with_upmap_full, n_upmap_pairs, with_temp, with_primary_temp,
        with_primary_affinity, path, with_flag, with_diag, window_extra,
        pool_operands, raw_only, with_raw,
        getattr(rule_fn, "cache_key", ("norule", spec.ruleno)),
    )
    fn.host_tables = getattr(rule_fn, "host_tables", {})
    fn.diag_exact = getattr(rule_fn, "diag_exact", False)
    fn.diag_tries_bound = getattr(rule_fn, "diag_tries_bound", 0)
    fn.diag_lanes = getattr(rule_fn, "diag_lanes", 0)
    return fn


DEFAULT_CHUNK = 65536  # PG-axis block size: peak device memory for the
                       # fast kernel's [B, T, lanes] intermediates is
                       # O(chunk), never O(pg_num)

# cache_key -> {"fast": JitAccount, "loop": JitAccount}.  The executables
# are keyed on the pipeline's structural signature, so every balancer
# iteration / upmap round / Incremental application — a fresh PoolMapper
# over a map that differs only in weights, osd state, or choose_args
# values — reuses one compile and only re-uploads operand tables.
_PIPE_CACHE: dict[tuple, dict] = {}


class PoolMapper:
    """Compiled batched mapper for one pool of one OSDMap.

    Usage:
        pm = PoolMapper(osdmap, pool_id)
        up, up_primary, acting, acting_primary = pm.map_all()

    Trace-once contract: constructing a PoolMapper never recompiles if a
    structurally-identical pipeline (same `cache_key`) was jitted before
    in this process — the per-map tables are runtime operands
    (device_put once here, carried in self.dev["crush"]).

    state: an `osd.state.ClusterState` to share per-map device operands
    with — the CRUSH arrays/tables (device_put once per structure, by
    the state) and the per-OSD vectors (scatter-updated in O(delta) by
    `ClusterState.apply`); refresh_dev then rebinds instead of
    re-uploading.  Without it the mapper owns its operands as before.
    """

    def __init__(self, m: OSDMap, pool_id: int, overlays: bool = True,
                 path: str = "auto", chunk: int | None = DEFAULT_CHUNK,
                 window_extra: int = FAST_WINDOW_EXTRA, state=None,
                 mesh=None):
        from ceph_tpu.utils import ensure_jax_backend

        ensure_jax_backend()
        self.m = m
        self.pool_id = pool_id
        self.window_extra = window_extra
        self._state = state
        # PG-axis device mesh (jax.sharding.Mesh): block inputs commit
        # to a NamedSharding over it and GSPMD partitions the SAME
        # compiled pipeline — per-map operands ride replicated (see
        # ceph_tpu.parallel.sharded).  Inherited from a shared
        # ClusterState so every consumer of one state shards alike.
        self.mesh = mesh if mesh is not None \
            else getattr(state, "mesh", None)
        ca_key = pool_id if pool_id in m.crush.choose_args else -1
        ca = m.crush.choose_args.get(pool_id, m.crush.choose_args.get(-1))
        self._ca_key = ca_key if ca is not None else None
        if state is not None:
            self.arrays = state.arrays_for(pool_id)
        else:
            self.arrays = build_arrays(m.crush, ca)
        self.ov = build_overlays(m, pool_id) if overlays else Overlays()
        self.spec = PoolSpec.for_pool(
            m, pool_id, extra_width=self.ov.extra_width
        )
        self._pipe_kw = dict(
            with_upmap_full=self.ov.upmap_full is not None,
            n_upmap_pairs=self.ov.n_pairs,
            with_temp=self.ov.temp is not None,
            with_primary_temp=self.ov.primary_temp is not None,
            # state-shared mappers bake the affinity stage ON even while
            # the map has no affinity table (an all-DEFAULT vector is a
            # bit-exact no-op): the first destroy/affinity delta then
            # updates an operand instead of re-keying every kernel
            with_primary_affinity=(m.osd_primary_affinity is not None
                                   or state is not None),
        )
        # self.fn is the exact (loop) kernel: path="auto" without a flag
        # resolves to the loop path in compile_rule, so it doubles as the
        # rescue kernel (jitted_loop).  with_raw: it also carries the
        # pre-overlay raw descent row as a trailing output (raw_rows /
        # ClusterState fixups) — for free, no second descent program.
        self.fn = compile_pipeline(
            self.arrays, self.spec, path=path, with_raw=True,
            window_extra=window_extra, pool_operands=True, **self._pipe_kw
        )
        self._fast = compile_pipeline(
            self.arrays, self.spec, path=path, with_flag=True,
            window_extra=window_extra, pool_operands=True, **self._pipe_kw,
        )
        # one device_put of this map's tables (fast ⊇ loop: same base
        # tables, plus the row-level tables only the fast path reads);
        # state-shared mappers take the state's once-per-structure copy
        if not self._fast.host_tables:
            self._tables_dev = None
        elif state is not None:
            self._tables_dev = state.device_tables_for(
                self._ca_key, self._fast
            )
        else:
            self._tables_dev = device_tables(self._fast.host_tables)
        self.cache_key = (self._fast.cache_key, self.fn.cache_key)
        self._cache = _PIPE_CACHE.setdefault(self.cache_key, {})
        self.refresh_dev()
        self._jitted = None
        self._jloop = None
        self._diag_fn = None
        self._jdiag = None
        self.chunk = chunk

    def shard_rows(self, rows):
        """Re-commit [pg, lane] result rows to the mesh (PG axis
        sharded) when one is configured and the shape divides — eager
        tail ops (the [:n] slice, rescue/fixup scatters) can fall back
        to a replicated layout, and the downstream reductions (epoch
        stats, histograms, membership queries) should stay partitioned.
        Bit-identical either way; this is layout only."""
        if self.mesh is None \
                or rows.shape[0] % self.mesh.devices.size:
            return rows
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(
            rows,
            NamedSharding(self.mesh, P(self.mesh.axis_names[0], None)),
        )

    def _shard_ps(self, ps):
        """Commit a PG-axis block to the mesh when one is configured and
        the block divides evenly (cycle-padded blocks always do); the
        jitted executables then run GSPMD-partitioned over the PG axis.
        No mesh (or an uneven tail) dispatches exactly as before."""
        arr = jnp.asarray(ps, np.uint32)
        if self.mesh is not None \
                and arr.shape[0] % self.mesh.devices.size == 0:
            from jax.sharding import NamedSharding, PartitionSpec as P

            arr = jax.device_put(
                arr, NamedSharding(self.mesh, P(self.mesh.axis_names[0]))
            )
        return arr

    def refresh_dev(self) -> None:
        """(Re)build the padded per-OSD vectors from the map's current
        osd state/weight/affinity — cheap O(OSDs) work, so callers that
        reuse a compiled PoolMapper across weight changes (the balancer's
        round cache) can refresh instead of recompiling.  The CRUSH
        operand tables (device-put once at construction) ride along in
        dev["crush"].  State-shared mappers rebind the ClusterState's
        scatter-maintained vectors instead of re-uploading anything.
        With a mesh, operands commit replicated across it (a no-op for
        leaves the shared state already replicated)."""
        if self._state is not None:
            vec = self._state.vectors
            self.dev = {
                "exists": vec["exists"],
                "up": vec["up"],
                "weight": vec["weight"],
                "primary_affinity": vec["primary_affinity"],
                "pool": {
                    "pool_id": jnp.uint32(self.spec.pool_id & 0xFFFFFFFF),
                    "pgp_num": jnp.uint32(self.spec.pgp_num),
                    "pgp_mask": jnp.uint32(pg_mask_for(self.spec.pgp_num)),
                    "max_osd": jnp.uint32(self.m.max_osd),
                },
            }
            if self._tables_dev is not None:
                self.dev["crush"] = self._tables_dev
            self._replicate_dev()
            return
        dv = self.m.frozen_vectors()
        DV = max(self.arrays.max_devices, self.m.max_osd, 1)
        self.dev = {
            "exists": _pad_to(dv["exists"], DV, False),
            "up": _pad_to(dv["up"], DV, False),
            "weight": _pad_to(dv["weight"], DV, 0),
            "primary_affinity": _pad_to(
                dv["primary_affinity"], DV, DEFAULT_PRIMARY_AFFINITY
            ),
            # pool identity as u32 scalar operands (pool_operands=True):
            # structurally-equal pools dispatch the same executable
            "pool": {
                "pool_id": jnp.uint32(self.spec.pool_id & 0xFFFFFFFF),
                "pgp_num": jnp.uint32(self.spec.pgp_num),
                "pgp_mask": jnp.uint32(pg_mask_for(self.spec.pgp_num)),
                "max_osd": jnp.uint32(self.m.max_osd),
            },
        }
        if self._tables_dev is not None:
            self.dev["crush"] = self._tables_dev
        self._replicate_dev()

    def _replicate_dev(self) -> None:
        """With a mesh: commit the whole operand pytree replicated over
        it, once — leaves already committed to the right sharding (the
        shared ClusterState's vectors/tables) are no-ops, so the per-
        dispatch cost of sharded mapping is zero host->device traffic."""
        if self.mesh is None:
            return
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.dev = jax.device_put(
            self.dev, NamedSharding(self.mesh, P()))

    def _cached_jit(self, kind: str, fn):
        acct = self._cache.get(kind)
        if acct is None:
            _L.inc("pipe_cache_misses")
            jfn = jax.jit(jax.vmap(fn, in_axes=(0, None, 0)))
            # every _PIPE_CACHE entry owns an executable-registry record:
            # compile cost, hit counts, and lazy cost analysis ride there
            rec = obs.executables.register(
                "pipe", kind, getattr(fn, "cache_key", self.cache_key),
                fn=jfn,
            )
            acct = obs.JitAccount(
                jfn, _L, kind, exec_record=rec,
                # the fast kernel IS the map_block dispatch; its warm
                # calls feed the shared tail-latency distribution
                warm_hist="map_block_seconds" if kind == "fast" else None,
            )
            self._cache[kind] = acct
        else:
            _L.inc("pipe_cache_hits")
        return acct

    def jitted_fast(self):
        """The jitted vmapped fast pipeline (with unresolved flag); one
        trace cache shared by map_batch and external batch drivers, AND
        across PoolMapper instances with equal cache_key (_PIPE_CACHE).
        Wrapped in compile/dispatch accounting (obs.JitAccount): the
        perf dump separates `fast_compile_seconds` (first call per block
        shape) from `fast_dispatch_seconds`, and counts `fast_compiles` /
        `fast_cache_hits` / `fast_retraces`."""
        if self._jitted is None:
            self._jitted = self._cached_jit("fast", self._fast)
        return self._jitted

    def jitted_loop(self):
        """The jitted vmapped exact loop pipeline (rescue kernel) —
        self.fn, shared through _PIPE_CACHE like the fast kernel."""
        if self._jloop is None:
            self._jloop = self._cached_jit("loop", self.fn)
        return self._jloop

    def jitted_diag(self):
        """The jitted vmapped INSTRUMENTED pipeline (with_diag): the
        device-side flight recorder.  A separate _PIPE_CACHE entry —
        instrumentation is a static plan fact in cache_key, so building
        it never touches the default kernels' executables."""
        if self._jdiag is None:
            if self._diag_fn is None:
                self._diag_fn = compile_pipeline(
                    self.arrays, self.spec, path="auto", with_flag=True,
                    with_diag=True, window_extra=self.window_extra,
                    pool_operands=True, **self._pipe_kw,
                )
            self._jdiag = self._cached_jit("diag", self._diag_fn)
        return self._jdiag

    def raw_rows(self, seeds: np.ndarray) -> np.ndarray:
        """Host-exact raw descent rows [K, out_width] for `seeds` —
        bit-identical to `OSDMap._pg_to_raw_osds` (descent + nonexistent
        removal), NONE-padded — read from the exact loop kernel's
        trailing with_raw output: the SAME compiled executable the
        rescue path already warms, so raw results cost no extra compile
        ever.  Dispatched in cycle-padded rescue-tier blocks (a handful
        of compiled shapes regardless of K)."""
        assert not (
            self._pipe_kw["with_upmap_full"]
            or self._pipe_kw["n_upmap_pairs"]
            or self._pipe_kw["with_temp"]
            or self._pipe_kw["with_primary_temp"]
        ), "raw_rows is an overlay-free path"
        seeds = np.asarray(seeds)
        n = len(seeds)
        if not n:
            return np.zeros((0, self.spec.out_width), np.int32)
        jloop = self.jitted_loop()
        P = rescue_pad_for(n)
        out = np.empty((n, self.spec.out_width), np.int32)
        for i in range(0, n, P):
            blk = seeds[i:i + P]
            pad = np.resize(blk, P)  # cycle-pad: one shape
            with obs.span("pipeline.map_block", pgs=len(blk), raw=True):
                sub = jloop(jnp.asarray(pad, np.uint32), self.dev, {})
            with obs.span("pipeline.fetch"):
                out[i:i + P] = np.asarray(sub[4])[: len(blk)]
        return out

    def diagnose(self, ps: np.ndarray | None = None,
                 source: str | None = None, record: bool = True) -> dict:
        """Run the instrumented pipeline over `ps` (default: every PG)
        and reduce the per-PG decision planes ON DEVICE into a
        placement-diagnostics summary: the per-placement retry histogram
        (the reference collect_choose_tries shape), collision /
        out-of-weight-rejection / skip tallies, bad-mapping and
        retry-exhaustion counts.  Only the O(tries-bound) histogram and
        a few scalars are fetched — never the per-PG planes.

        Fast-window-flagged lanes are EXCLUDED from every plane (their
        diagnostics are garbage by the with_diag contract; production
        mapping rescues them through the exact loop kernel) and reported
        as `unresolved`.  `diag_exact` says whether the retry lanes
        reproduce the host histogram bit-for-bit (fast-path firstn and
        non-leafy indep plans do).

        The summary lands in the `placement` perf group and snapshot
        store (`obs.placement`) unless record=False."""
        from ceph_tpu.core import reduce
        from ceph_tpu.obs import placement

        if ps is None:
            ps = np.arange(self.spec.pg_num, dtype=np.uint32)
        ps = np.asarray(ps)
        n = len(ps)
        jdiag = self.jitted_diag()
        dfn = self._diag_fn
        bound = min(int(dfn.diag_tries_bound),
                    len(placement.TRIES_BOUNDS) - 1)
        B = min(self.chunk or DEFAULT_CHUNK, n)
        _PL = obs.logger_for("placement")
        hist = jnp.zeros(bound + 1, jnp.int64)
        coll = rej = skip = bad = exhausted = jnp.int64(0)
        n_unres = jnp.int64(0)
        for i in range(0, n, B):
            blk = np.resize(ps[i:i + B], B)  # cycle-pad: one shape
            real = np.arange(B) < (n - i)
            nreal = int(real.sum())
            with obs.span("pipeline.diagnose", pgs=nreal), \
                    _PL.time("diagnose_seconds"):
                _, _, _, _, flg, dg = jdiag(
                    jnp.asarray(blk, np.uint32), self.dev,
                    self._ov_rows(blk),
                )
            ok = jnp.asarray(real) & ~flg  # [B] lanes the planes cover
            hist = hist + reduce.value_histogram(
                dg["tries"], bound, extra_mask=ok[:, None])
            okw = ok.astype(jnp.int64)
            coll = coll + jnp.sum(dg["coll"].astype(jnp.int64) * okw)
            rej = rej + jnp.sum(dg["rej"].astype(jnp.int64) * okw)
            skip = skip + jnp.sum(dg["skip"].astype(jnp.int64) * okw)
            bad = bad + jnp.sum(dg["bad"].astype(jnp.int64) * okw)
            if dfn.diag_exact:
                # -1 tries = unfilled lane = exhaustion ONLY on exact
                # plans; loop-path/leafy-indep plans fill whole planes
                # with -1 (uninstrumented), which is not exhaustion
                exhausted = exhausted + jnp.sum(
                    ((dg["tries"] < 0) & ok[:, None]).astype(jnp.int64))
            n_unres = n_unres + jnp.sum(
                (flg & jnp.asarray(real)).astype(jnp.int64))
        with obs.span("pipeline.fetch"):
            hist_v = np.asarray(hist)
            scalars = np.asarray(jnp.stack(
                [coll, rej, skip, bad, exhausted, n_unres]))
        summary = {
            "pgs": n,
            "pool_id": self.pool_id,
            "tries_histogram": [int(v) for v in hist_v],
            "tries_bound": bound,
            "diag_exact": bool(dfn.diag_exact),
            "diag_lanes": int(dfn.diag_lanes),
            "collisions": int(scalars[0]),
            "rejections": int(scalars[1]),
            "skips": int(scalars[2]),
            "bad_mappings": int(scalars[3]),
            "retry_exhausted": int(scalars[4]),
            "unresolved": int(scalars[5]),
        }
        if record:
            placement.record(source or f"pool{self.pool_id}", summary)
            placement.register_explainer(
                f"pool{self.pool_id}", self._explain_seed)
        return summary

    def _explain_seed(self, seed: int) -> dict:
        """Host-oracle replay of one placement seed of this pool — the
        daemon `explain <pool>.<seed>` payload."""
        from ceph_tpu.crush.explain import explain_pool_pg

        return explain_pool_pg(self.m, self.pool_id, seed)

    def _ov_rows(self, ps: np.ndarray) -> dict:
        ov, rows = self.ov, {}
        if ov.upmap_full is not None:
            rows["upmap_full"] = jnp.asarray(ov.upmap_full[ps])
            rows["upmap_len"] = jnp.asarray(ov.upmap_len[ps])
        if ov.upmap_pairs is not None:
            rows["upmap_pairs"] = jnp.asarray(ov.upmap_pairs[ps])
        if ov.temp is not None:
            rows["temp"] = jnp.asarray(ov.temp[ps])
            rows["temp_len"] = jnp.asarray(ov.temp_len[ps])
        if ov.primary_temp is not None:
            rows["primary_temp"] = jnp.asarray(ov.primary_temp[ps])
        return rows

    def map_batch(self, ps: np.ndarray):
        """Map a batch of placement seeds.  Returns numpy
        (up[N,W], up_primary[N], acting[N,W], acting_primary[N]).

        Batches larger than self.chunk run block-by-block (blocks
        cycle-padded to one fixed shape: one compile, O(chunk) peak
        device memory).  Within a block the fast-window kernel runs
        first; PGs whose candidate window was inconclusive (rare) are
        recomputed exactly through the loop kernel in fixed-size blocks
        (see mapper_jax.compile_batched)."""
        ps = np.asarray(ps)
        if self.chunk and len(ps) > self.chunk:
            B = self.chunk
            parts = []
            for i in range(0, len(ps), B):
                blk = ps[i:i + B]
                sub = self._map_block(np.resize(blk, B), n_real=len(blk))
                parts.append(tuple(o[: len(blk)] for o in sub))
            return tuple(
                np.concatenate([p[j] for p in parts]) for j in range(4)
            )
        return self._map_block(ps)

    def _map_block(self, ps: np.ndarray, n_real: int | None = None):
        # n_real: distinct seeds in a cycle-padded tail block — the
        # counters book real placement work, not pad-lane duplicates
        n = len(ps) if n_real is None else n_real
        # mid-batch device loss surfaces here (real transport loss raises
        # from the dispatch below; `map_batch=lost` injects the same
        # shape) — callers degrade via sim/ClusterSim or the runtime
        # ladder, so the fault point sits on the dispatch boundary and
        # real jaxlib transport errors are mapped onto DeviceLostError
        faults.check("map_batch")
        try:
            return self._map_block_inner(ps, n)
        except Exception as e:
            if faults.looks_like_device_loss(e):
                raise faults.DeviceLostError(
                    f"{type(e).__name__}: {e}"[:200]
                ) from e
            raise

    def _map_block_inner(self, ps: np.ndarray, n: int):
        # span contract (graftlint host-sync pass): map_block and
        # rescue time DISPATCH only — no np.asarray/.item()/float() on
        # traced values inside them.  The unresolved-flag fetch sits
        # between the spans; result rows stay on device (rescued lanes
        # scattered in with .at[].set) until pipeline.fetch.
        psd = self._shard_ps(ps)
        with obs.span("pipeline.map_block", pgs=n):
            *out, flg = self.jitted_fast()(psd, self.dev, self._ov_rows(ps))
        flg = obs.timed_fetch(_L, "result", flg)
        _L.inc("pgs_mapped", n)
        if flg.any():
            idx = np.nonzero(flg)[0]
            _L.inc("unresolved_pgs", int((idx < n).sum()))
            _L.inc("rescue_invocations")
            jloop = self.jitted_loop()
            with obs.span("pipeline.rescue", lanes=len(idx)):
                P = rescue_pad_for(len(idx))
                for i in range(0, len(idx), P):
                    blk = idx[i:i + P]
                    # cycle-pad: one compile per shape — for the loop
                    # kernel AND the scatter-back (duplicated lanes
                    # write identical rows, so full-block scatters are
                    # idempotent and never retrace on a new blk length)
                    pad = np.resize(blk, P)
                    sub = jloop(
                        jnp.asarray(ps[pad], np.uint32), self.dev,
                        self._ov_rows(ps[pad]),
                    )
                    bidx = jnp.asarray(pad)
                    out = [
                        o.at[bidx].set(s) for o, s in zip(out, sub)
                    ]
        with obs.span("pipeline.fetch"):
            return tuple(np.asarray(o) for o in out)

    def map_all(self):
        return self.map_batch(np.arange(self.spec.pg_num, dtype=np.uint32))

    def map_all_device(self, chunk: int | None = None):
        """Map every PG of the pool block-wise with results STAYING on
        device: returns `up` rows [pg_num, W] as a jax array.  Fast-window
        inconclusive lanes are recomputed through the exact loop kernel
        and scattered in (same rescue contract as map_batch, without the
        O(PGs) host transfer).  Overlay tensors are not supported here —
        callers correct overlay-carrying PGs themselves (see
        balancer.state.DeviceState)."""
        assert not (
            self._pipe_kw["with_upmap_full"]
            or self._pipe_kw["n_upmap_pairs"]
            or self._pipe_kw["with_temp"]
            or self._pipe_kw["with_primary_temp"]
        ), "map_all_device is an overlay-free path"
        n = self.spec.pg_num
        # block widths quantize to power-of-two classes (floor 32): a
        # pg_num split then moves the pool to the NEXT class instead of
        # minting a fresh compiled shape per pg_num, and small pools of
        # different sizes share executables (cycle-padded lanes beyond
        # n are discarded below)
        B = min(chunk or self.chunk or DEFAULT_CHUNK,
                1 << max(int(n - 1).bit_length(), 5))
        nb = (n + B - 1) // B
        vfast = self.jitted_fast()
        ups, flgs = [], []
        for i in range(nb):
            ps = self._shard_ps(
                (np.arange(i * B, (i + 1) * B) % n).astype(np.uint32)
            )
            with obs.span("pipeline.map_block", pgs=B, device_resident=True):
                up, _, _, _, flg = vfast(ps, self.dev, {})
            ups.append(up)
            flgs.append(flg)
        _L.inc("pgs_mapped", n)  # not nb*B: pad lanes are not real PGs
        rows = (jnp.concatenate(ups) if len(ups) > 1 else ups[0])[:n]
        # ONE sync point: the flag fetch itself forces the dispatched
        # chain (no separate eager reduce + scalar pull)
        flag_vs = [np.asarray(f) for f in flgs]
        if any(fv.any() for fv in flag_vs):
            _L.inc("rescue_invocations")
            vloop = self.jitted_loop()
            n_unres = 0
            with obs.span("pipeline.rescue",
                          lanes=int(sum(fv.sum() for fv in flag_vs))):
                for bi, fv in enumerate(flag_vs):
                    if not fv.any():
                        continue
                    idx = np.nonzero(fv)[0] + bi * B
                    idx = idx[idx < n]
                    n_unres += len(idx)
                    P = rescue_pad_for(len(idx))
                    for i in range(0, len(idx), P):
                        blk = idx[i:i + P]
                        pad = np.resize(blk, P)  # fixed shape
                        up = vloop(
                            jnp.asarray(pad.astype(np.uint32)), self.dev, {}
                        )[0]
                        # full-block scatter: duplicated cycle-pad lanes
                        # write identical rows (no per-length retrace)
                        rows = rows.at[jnp.asarray(pad)].set(up)
            _L.inc("unresolved_pgs", n_unres)
        return self.shard_rows(rows)


def overlay_fixup_rows(m: OSDMap, pool_id: int, width: int):
    """Host-exact `up` rows for the PGs of `pool_id` that carry a
    pg_upmap / pg_upmap_items entry: (seeds i64[K], rows i32[K, width]),
    both empty when the pool has none.  The overlay-free device paths
    (map_all_device and its callers — mgr eval, balancer DeviceState,
    upmap's pgs_by_osd) skip the dense overlay tensors so accumulating
    entries never change the compiled shape; they scatter these few
    oracle rows in instead, bit-identical to the overlay-gated kernel."""
    n = m.pools[pool_id].pg_num
    seeds = sorted({
        pg.seed for pg in list(m.pg_upmap) + list(m.pg_upmap_items)
        if pg.pool == pool_id and pg.seed < n
    })
    rows = np.full((len(seeds), width), ITEM_NONE, np.int32)
    for i, s in enumerate(seeds):
        up, _, _, _ = m.pg_to_up_acting_osds(PgId(pool_id, s))
        rows[i, : min(len(up), width)] = up[:width]
    return np.asarray(seeds, np.int64), rows


def map_cluster(m: OSDMap) -> dict[int, tuple]:
    """Map every pool; returns {pool_id: (up, up_primary, acting,
    acting_primary)} — the batched equivalent of the osdmaptool
    --test-map-pgs loop (reference src/tools/osdmaptool.cc:630-755)."""
    return {pid: PoolMapper(m, pid).map_all() for pid in sorted(m.pools)}


def _pad_to(v: np.ndarray, n: int, fill) -> jnp.ndarray:
    v = np.asarray(v)
    if v.shape[0] < n:
        v = np.concatenate([v, np.full(n - v.shape[0], fill, v.dtype)])
    return jnp.asarray(v[:n])
