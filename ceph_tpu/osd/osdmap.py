"""OSDMap — the cluster-map model above CRUSH.

Semantics-compatible with the reference's OSDMap placement surface
(reference src/osd/OSDMap.{h,cc}): per-OSD state/weight/primary-affinity
vectors, pools, pg_temp/primary_temp, pg_upmap/pg_upmap_items, and the
5-stage PG→OSD pipeline (_pg_to_raw_osds → _apply_upmap → _raw_to_up_osds →
_pick_primary → _apply_primary_affinity, reference src/osd/OSDMap.cc:2435-2715).

This module is the *host-side* model: mutable, used by builders, the CLIs,
and as the differential oracle.  The batched TPU pipeline
(ceph_tpu.osd.pipeline_jax) consumes the frozen tensors produced by
`freeze()` and must agree bit-for-bit.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.crush import mapper_ref
from ceph_tpu.crush.types import BucketAlg, CrushMap, ITEM_NONE, Tunables
from ceph_tpu.osd.types import PgId, PgPool, PoolType

# osd_state flags (reference src/include/rados.h:125-132)
OSD_EXISTS = 1 << 0
OSD_UP = 1 << 1
OSD_AUTOOUT = 1 << 2
OSD_NEW = 1 << 3
OSD_DESTROYED = 1 << 7

IN_WEIGHT = 0x10000  # CEPH_OSD_IN (reference src/include/rados.h:142)
MAX_PRIMARY_AFFINITY = 0x10000  # reference src/include/rados.h:145
DEFAULT_PRIMARY_AFFINITY = 0x10000

# default bucket type hierarchy (reference src/osd/OSDMap.cc:4286-4305
# _build_crush_types): 0=osd .. 11=root
DEFAULT_TYPES = {
    0: "osd", 1: "host", 2: "chassis", 3: "rack", 4: "row", 5: "pdu",
    6: "pod", 7: "room", 8: "datacenter", 9: "zone", 10: "region", 11: "root",
}


class OSDMap:
    """Cluster map: CRUSH tree + per-OSD vectors + pools + overrides."""

    def __init__(self, crush: CrushMap | None = None):
        self.epoch = 1
        self.crush = crush or CrushMap()
        self.max_osd = 0
        self.osd_state: list[int] = []
        self.osd_weight: list[int] = []  # 16.16 in/out weight
        self.osd_primary_affinity: list[int] | None = None
        self.pools: dict[int, PgPool] = {}
        self.pool_name: dict[int, str] = {}
        self.pool_max = -1
        self.pg_temp: dict[PgId, list[int]] = {}
        self.primary_temp: dict[PgId, int] = {}
        self.pg_upmap: dict[PgId, list[int]] = {}
        self.pg_upmap_items: dict[PgId, list[tuple[int, int]]] = {}
        # EC profile registry (reference src/osd/OSDMap.h:598)
        self.erasure_code_profiles: dict[str, dict[str, str]] = {}

    # -- OSD state ---------------------------------------------------------
    def set_max_osd(self, n: int) -> None:
        while len(self.osd_state) < n:
            self.osd_state.append(0)
            self.osd_weight.append(0)
            if self.osd_primary_affinity is not None:
                self.osd_primary_affinity.append(DEFAULT_PRIMARY_AFFINITY)
        del self.osd_state[n:]
        del self.osd_weight[n:]
        if self.osd_primary_affinity is not None:
            del self.osd_primary_affinity[n:]
        self.max_osd = n

    def exists(self, osd: int) -> bool:
        return 0 <= osd < self.max_osd and bool(self.osd_state[osd] & OSD_EXISTS)

    def is_up(self, osd: int) -> bool:
        return self.exists(osd) and bool(self.osd_state[osd] & OSD_UP)

    def is_down(self, osd: int) -> bool:
        return not self.is_up(osd)

    def is_out(self, osd: int) -> bool:
        return not self.exists(osd) or self.osd_weight[osd] == 0

    def is_in(self, osd: int) -> bool:
        return not self.is_out(osd)

    def get_weightf(self, osd: int) -> float:
        return self.osd_weight[osd] / IN_WEIGHT

    def set_primary_affinity(self, osd: int, aff: int) -> None:
        if self.osd_primary_affinity is None:
            self.osd_primary_affinity = (
                [DEFAULT_PRIMARY_AFFINITY] * self.max_osd
            )
        self.osd_primary_affinity[osd] = aff

    def mark_up_in(self, osd: int) -> None:
        self.osd_state[osd] |= OSD_EXISTS | OSD_UP
        self.osd_weight[osd] = IN_WEIGHT

    def mark_down(self, osd: int) -> None:
        self.osd_state[osd] &= ~OSD_UP

    def mark_out(self, osd: int) -> None:
        self.osd_weight[osd] = 0

    # -- pools -------------------------------------------------------------
    def add_pool(self, name: str, pool: PgPool, pool_id: int | None = None) -> int:
        if pool_id is None:
            self.pool_max += 1
            pool_id = self.pool_max
        else:
            self.pool_max = max(self.pool_max, pool_id)
        self.pools[pool_id] = pool
        self.pool_name[pool_id] = name
        return pool_id

    def get_pg_pool(self, pool_id: int) -> PgPool | None:
        return self.pools.get(pool_id)

    # -- the 5-stage pipeline (host reference) -----------------------------
    def _pg_to_raw_osds(self, pool: PgPool, pg: PgId) -> tuple[list[int], int]:
        """reference src/osd/OSDMap.cc:2435-2453."""
        pps = pool.raw_pg_to_pps(pg)
        size = pool.size
        ruleno = mapper_ref.find_rule(
            self.crush, pool.crush_rule, int(pool.type), size
        )
        osds: list[int] = []
        if ruleno >= 0:
            # choose_args_get_with_fallback semantics (reference
            # src/crush/CrushWrapper.h:1451-1457): pool id, else -1
            ca = self.crush.choose_args.get(
                pg.pool, self.crush.choose_args.get(-1)
            )
            osds = mapper_ref.do_rule(
                self.crush, ruleno, pps, size, self.osd_weight,
                choose_args=ca,
            )
        self._remove_nonexistent_osds(pool, osds)
        return osds, pps

    def _remove_nonexistent_osds(self, pool: PgPool, osds: list[int]) -> None:
        """reference src/osd/OSDMap.cc:2412-2433."""
        if pool.can_shift_osds():
            osds[:] = [o for o in osds if self.exists(o)]
        else:
            for i, o in enumerate(osds):
                if not self.exists(o) and o != ITEM_NONE:
                    osds[i] = ITEM_NONE

    def _apply_upmap(self, pool: PgPool, raw_pg: PgId, raw: list[int]) -> None:
        """reference src/osd/OSDMap.cc:2465-2509."""
        pg = pool.raw_pg_to_pg(raw_pg)
        p = self.pg_upmap.get(pg)
        if p is not None:
            for osd in p:
                if (
                    osd != ITEM_NONE and 0 <= osd < self.max_osd
                    and self.osd_weight[osd] == 0
                ):
                    return  # reject explicit mapping with out target
            raw[:] = list(p)
        q = self.pg_upmap_items.get(pg)
        if q is not None:
            for frm, to in q:
                exists = False
                pos = -1
                for i, osd in enumerate(raw):
                    if osd == to:
                        exists = True
                        break
                    if osd == frm and pos < 0 and not (
                        to != ITEM_NONE and 0 <= to < self.max_osd
                        and self.osd_weight[to] == 0
                    ):
                        pos = i
                if not exists and pos >= 0:
                    raw[pos] = to

    def _raw_to_up_osds(self, pool: PgPool, raw: list[int]) -> list[int]:
        """reference src/osd/OSDMap.cc:2512-2535."""
        if pool.can_shift_osds():
            return [o for o in raw if self.exists(o) and not self.is_down(o)]
        return [
            o if (self.exists(o) and not self.is_down(o)) else ITEM_NONE
            for o in raw
        ]

    @staticmethod
    def _pick_primary(osds: list[int]) -> int:
        """reference src/osd/OSDMap.cc:2455-2463."""
        for o in osds:
            if o != ITEM_NONE:
                return o
        return -1

    def _apply_primary_affinity(
        self, seed: int, pool: PgPool, osds: list[int], primary: int
    ) -> int:
        """reference src/osd/OSDMap.cc:2537-2590.  Mutates osds (shift for
        replicated pools); returns the new primary."""
        pa = self.osd_primary_affinity
        if pa is None:
            return primary
        if not any(
            o != ITEM_NONE and pa[o] != DEFAULT_PRIMARY_AFFINITY for o in osds
        ):
            return primary
        pos = -1
        for i, o in enumerate(osds):
            if o == ITEM_NONE:
                continue
            a = pa[o]
            if a < MAX_PRIMARY_AFFINITY and (
                int(mapper_ref._h2(seed, o)) >> 16
            ) >= a:
                if pos < 0:
                    pos = i
            else:
                pos = i
                break
        if pos < 0:
            return primary
        primary = osds[pos]
        if pool.can_shift_osds() and pos > 0:
            for i in range(pos, 0, -1):
                osds[i] = osds[i - 1]
            osds[0] = primary
        return primary

    def _get_temp_osds(self, pool: PgPool, pg: PgId) -> tuple[list[int], int]:
        """reference src/osd/OSDMap.cc:2592-2623."""
        pg = pool.raw_pg_to_pg(pg)
        temp_pg: list[int] = []
        p = self.pg_temp.get(pg)
        if p is not None:
            for o in p:
                if not self.exists(o) or self.is_down(o):
                    if not pool.can_shift_osds():
                        temp_pg.append(ITEM_NONE)
                else:
                    temp_pg.append(o)
        temp_primary = self.primary_temp.get(pg, -1)
        if temp_primary == -1 and temp_pg:
            for o in temp_pg:
                if o != ITEM_NONE:
                    temp_primary = o
                    break
        return temp_pg, temp_primary

    def pg_to_raw_osds(self, pg: PgId) -> tuple[list[int], int]:
        pool = self.get_pg_pool(pg.pool)
        if pool is None:
            return [], -1
        raw, _ = self._pg_to_raw_osds(pool, pg)
        return raw, self._pick_primary(raw)

    def pg_to_raw_up(self, pg: PgId) -> tuple[list[int], int]:
        """reference src/osd/OSDMap.cc:2648-2664."""
        pool = self.get_pg_pool(pg.pool)
        if pool is None:
            return [], -1
        raw, pps = self._pg_to_raw_osds(pool, pg)
        self._apply_upmap(pool, pg, raw)
        up = self._raw_to_up_osds(pool, raw)
        primary = self._pick_primary(raw)
        primary = self._apply_primary_affinity(pps, pool, up, primary)
        return up, primary

    def _pg_to_up_acting_osds(
        self, pg: PgId, raw_pg_to_pg: bool = True
    ) -> tuple[list[int], int, list[int], int]:
        """reference src/osd/OSDMap.cc:2667-2715.  Returns
        (up, up_primary, acting, acting_primary)."""
        pool = self.get_pg_pool(pg.pool)
        if pool is None or (not raw_pg_to_pg and pg.seed >= pool.pg_num):
            return [], -1, [], -1
        acting, acting_primary = self._get_temp_osds(pool, pg)
        raw, pps = self._pg_to_raw_osds(pool, pg)
        self._apply_upmap(pool, pg, raw)
        up = self._raw_to_up_osds(pool, raw)
        up_primary = self._pick_primary(up)
        up_primary = self._apply_primary_affinity(pps, pool, up, up_primary)
        if not acting:
            acting = list(up)
            if acting_primary == -1:
                acting_primary = up_primary
        return up, up_primary, acting, acting_primary

    def pg_to_up_acting_osds(self, pg: PgId):
        return self._pg_to_up_acting_osds(pg, raw_pg_to_pg=False)

    # -- upmap hygiene -----------------------------------------------------
    def clean_pg_upmaps(self) -> tuple[list[PgId], dict[PgId, list]]:
        """Drop invalid/no-op pg_upmap{,_items} entries (reference
        OSDMap::check_pg_upmaps + clean_pg_upmaps, src/osd/OSDMap.cc:2003).
        Returns (cancelled pgs, simplified items).  Mutates self."""
        from ceph_tpu.balancer.crush_analysis import (
            get_rule_weight_osd_map,
        )

        to_cancel: list[PgId] = []
        to_remap: dict[PgId, list] = {}
        rule_weight_cache: dict[int, dict[int, float]] = {}
        for pg in sorted(set(self.pg_upmap) | set(self.pg_upmap_items)):
            pool = self.get_pg_pool(pg.pool)
            if pool is None or pg.seed >= pool.pg_num:
                to_cancel.append(pg)
                continue
            raw, _ = self._pg_to_raw_osds(pool, pg)
            up = list(raw)
            self._apply_upmap(pool, pg, up)
            real = [o for o in up if o != ITEM_NONE]
            if len(real) != len(set(real)):  # duplicate targets
                to_cancel.append(pg)
                continue
            ruleno = mapper_ref.find_rule(
                self.crush, pool.crush_rule, int(pool.type), pool.size
            )
            wm = rule_weight_cache.get(ruleno)
            if wm is None and ruleno >= 0:
                wm = get_rule_weight_osd_map(self.crush, ruleno)
                rule_weight_cache[ruleno] = wm
            bad = False
            for osd in real:
                if wm is not None and osd not in wm:
                    bad = True  # moved out of the rule's crush tree
                    break
                if self.is_out(osd):
                    bad = True
                    break
            if bad:
                to_cancel.append(pg)
                continue
            p = self.pg_upmap.get(pg)
            if p is not None and list(raw) == list(p):
                to_cancel.append(pg)  # redundant full remap
                continue
            items = self.pg_upmap_items.get(pg)
            if items is not None:
                newmap = [
                    (frm, to)
                    for frm, to in items
                    if frm in raw
                    and not (
                        to != ITEM_NONE and 0 <= to < self.max_osd
                        and self.osd_weight[to] == 0
                    )
                ]
                if not newmap:
                    to_cancel.append(pg)
                elif newmap != list(items):
                    to_remap[pg] = newmap
        for pg in to_cancel:
            self.pg_upmap.pop(pg, None)
            self.pg_upmap_items.pop(pg, None)
        for pg, items in to_remap.items():
            self.pg_upmap_items[pg] = items
        return to_cancel, to_remap

    # -- freezing for the TPU pipeline -------------------------------------
    def frozen_vectors(self) -> dict[str, np.ndarray]:
        """Per-OSD state as dense arrays (consumed by pipeline_jax)."""
        n = self.max_osd
        state = np.asarray(self.osd_state, np.int32)
        weight = np.asarray(self.osd_weight, np.uint32)
        if self.osd_primary_affinity is None:
            aff = np.full(n, DEFAULT_PRIMARY_AFFINITY, np.uint32)
        else:
            aff = np.asarray(self.osd_primary_affinity, np.uint32)
        return {
            "exists": (state & OSD_EXISTS) != 0,
            "up": ((state & OSD_EXISTS) != 0) & ((state & OSD_UP) != 0),
            "weight": weight,
            "primary_affinity": aff,
        }


# -- builders --------------------------------------------------------------

def build_simple(
    n_osd: int,
    pg_bits: int = 6,
    pgp_bits: int = 6,
    default_pool: bool = True,
    chooseleaf_type: int = 1,
    tunables: Tunables | None = None,
    mark_up_in: bool = True,
) -> OSDMap:
    """OSDMap::build_simple semantics (reference src/osd/OSDMap.cc:4172-4270 +
    build_simple_crush_map :4307-4337): all OSDs at weight 1.0 under
    host "localhost" / rack "localrack" / root "default"; one replicated rule
    chooseleaf-firstn over `chooseleaf_type` (1=host); one "rbd" pool with
    poolbase<<pg_bits PGs."""
    crush = CrushMap(tunables)
    crush.type_names = dict(DEFAULT_TYPES)
    # bucket id order matches the reference builder: root -1 first, then
    # insert_item creates host -2 / rack -3 on the first device's walk
    root = crush.add_bucket(BucketAlg.STRAW2, 11, [], [], name="default")
    loc = {"host": "localhost", "rack": "localrack", "root": "default"}
    for o in range(n_osd):
        crush.insert_item(o, 1.0, f"osd.{o}", loc)
    crush.make_replicated_rule(root, chooseleaf_type)
    crush.rule_names[0] = "replicated_rule"

    m = OSDMap(crush)
    m.set_max_osd(n_osd)
    if mark_up_in:
        for o in range(n_osd):
            m.mark_up_in(o)
    if default_pool and n_osd:
        # pool id 1, as the reference's ++pool_max from 0 produces
        pool = PgPool(
            type=PoolType.REPLICATED, size=3, crush_rule=0,
            pg_num=n_osd << pg_bits, pgp_num=n_osd << min(pgp_bits, pg_bits),
        )
        m.pool_max = 0
        m.add_pool("rbd", pool, 1)
    return m


def build_hierarchical(
    n_host: int,
    osd_per_host: int,
    n_rack: int = 0,
    weight_fn=None,
    tunables: Tunables | None = None,
    pool: PgPool | None = None,
    pool_name: str = "rbd",
    chooseleaf_type: int = 1,
) -> OSDMap:
    """Synthesize a realistic multi-host (optionally multi-rack) map — the
    shape `osdmaptool --createsimple` + a crush built from conf produces
    (reference src/osd/OSDMap.cc:4339-4409 build_simple_crush_map_from_conf).
    weight_fn(osd_id) -> 16.16 device weight (default 1.0)."""
    crush = CrushMap(tunables)
    crush.type_names = dict(DEFAULT_TYPES)
    host_ids = []
    osd = 0
    for h in range(n_host):
        items = list(range(osd, osd + osd_per_host))
        ws = [
            IN_WEIGHT if weight_fn is None else int(weight_fn(i))
            for i in items
        ]
        hid = crush.add_bucket(
            BucketAlg.STRAW2, 1, items, ws, name=f"host{h}"
        )
        host_ids.append((hid, sum(ws)))
        osd += osd_per_host
    if n_rack:
        per = max(1, n_host // n_rack)
        top = []
        for r in range(n_rack):
            hs = host_ids[r * per : (r + 1) * per]
            if not hs:
                break
            rid = crush.add_bucket(
                BucketAlg.STRAW2, 3,
                [h for h, _ in hs], [w for _, w in hs], name=f"rack{r}",
            )
            top.append((rid, sum(w for _, w in hs)))
    else:
        top = host_ids
    root = crush.add_bucket(
        BucketAlg.STRAW2, 11,
        [b for b, _ in top], [w for _, w in top], name="default",
    )
    for o in range(osd):
        crush.item_names[o] = f"osd.{o}"
    crush.make_replicated_rule(root, chooseleaf_type)

    m = OSDMap(crush)
    m.set_max_osd(osd)
    for o in range(osd):
        m.mark_up_in(o)
    if pool is not None:
        m.add_pool(pool_name, pool)
    return m
