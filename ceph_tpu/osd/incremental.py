"""OSDMap::Incremental — epoch deltas, wire-compatible with the reference.

The monitor's actual currency is not full maps but per-epoch deltas: an
``Incremental`` carries "what changed from epoch e-1 to e" and every daemon
applies the chain locally (reference model: src/osd/OSDMap.h:376-496, the
field list; src/osd/OSDMap.cc:2061 ``apply_incremental``; codec
src/osd/OSDMap.cc:557-733 ``Incremental::encode``/``decode``).

This module implements the same three pieces for the TPU framework's OSDMap
model:

- :class:`Incremental` — the delta model, restricted to the fields the
  placement stack models (pools, weights, state, overlays, crush, EC
  profiles).  Fields outside that scope (addresses, xinfo, blocklist,
  snaps) are preserved as raw wire spans on decode and replayed on encode,
  the same fidelity model as ``osd.codec``.
- ``encode_incremental`` / ``decode_incremental`` — the binary format:
  ENCODE_START(8,7) meta wrapper, client-usable section (v4..v8),
  osd-only section, trailing CRC-32C over the buffer with the crc hole
  excluded (reference src/osd/OSDMap.cc:714-731).
- :func:`apply_incremental` — state transition, mirroring the reference's
  ordering: flags, max_osd, pools, weights/affinity, EC profiles, state
  XOR (with the destroy special case), pg_temp/primary_temp, upmaps, and
  the new crush blob last (src/osd/OSDMap.cc:2061-2341).

A chain test lives in tests/test_incremental.py: synthetic epoch chains
round-trip byte-exactly and applying them reproduces direct mutation.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ceph_tpu.crush.codec import decode_crushmap
from ceph_tpu.osd.codec import (
    CodecError,
    R,
    W,
    _decode_pool,
    _encode_pool,
    _skip_addrvec,
    decode_osdmap,
)
from ceph_tpu.osd.osdmap import (
    DEFAULT_PRIMARY_AFFINITY,
    OSD_AUTOOUT,
    OSD_EXISTS,
    OSD_NEW,
    OSD_UP,
    OSDMap,
)
from ceph_tpu.osd.types import PgId, PgPool
from ceph_tpu.utils.crc32c import crc32c


@dataclass
class Incremental:
    """Delta from ``epoch - 1`` to ``epoch`` (reference
    src/osd/OSDMap.h:354-496).  Sentinel conventions match the C++:
    ``new_flags``/``new_max_osd`` < 0 and ``new_pool_max`` == -1 mean
    "unchanged"; an empty ``new_pg_temp`` vector removes the entry; a
    ``new_primary_temp`` value of -1 removes the entry."""

    epoch: int = 0
    fsid: bytes = b"\0" * 16
    modified: tuple[int, int] = (0, 0)
    new_pool_max: int = -1
    new_flags: int = -1
    fullmap: bytes = b""          # in lieu of everything below (rare)
    crush: bytes = b""            # new crushmap blob, applied last
    new_max_osd: int = -1
    new_pools: dict[int, PgPool] = field(default_factory=dict)
    new_pool_wire: dict[int, dict] = field(default_factory=dict)
    new_pool_names: dict[int, str] = field(default_factory=dict)
    old_pools: set[int] = field(default_factory=set)
    new_up_client: dict[int, bytes] = field(default_factory=dict)  # raw addrvec
    new_state: dict[int, int] = field(default_factory=dict)   # XOR onto prev
    new_weight: dict[int, int] = field(default_factory=dict)
    new_pg_temp: dict[PgId, list[int]] = field(default_factory=dict)
    new_primary_temp: dict[PgId, int] = field(default_factory=dict)
    new_primary_affinity: dict[int, int] = field(default_factory=dict)
    new_erasure_code_profiles: dict[str, dict[str, str]] = field(
        default_factory=dict
    )
    old_erasure_code_profiles: list[str] = field(default_factory=list)
    new_pg_upmap: dict[PgId, list[int]] = field(default_factory=dict)
    old_pg_upmap: set[PgId] = field(default_factory=set)
    new_pg_upmap_items: dict[PgId, list[tuple[int, int]]] = field(
        default_factory=dict
    )
    old_pg_upmap_items: set[PgId] = field(default_factory=set)
    full_crc: int = 0
    wire: dict = field(default_factory=dict)  # raw spans for replay

    def get_new_pool(self, pool_id: int, orig: PgPool) -> PgPool:
        """Copy-on-write pool mutation handle (reference
        src/osd/OSDMap.h:451-455)."""
        if pool_id not in self.new_pools:
            self.new_pools[pool_id] = PgPool(**vars(orig))
        return self.new_pools[pool_id]


# ---------------------------------------------------------------- codec


def _pg_sorted(d):
    return sorted(d, key=lambda p: (p.pool, p.seed))


def decode_incremental(data: bytes) -> Incremental:
    """reference src/osd/OSDMap.cc:837 (Incremental::decode)."""
    r = R(data)
    meta_v, meta_compat, meta_end = r.start()
    if meta_v < 7:
        raise CodecError(f"incremental meta v{meta_v} (classic) unsupported")
    inc = Incremental()
    inc.wire = {"meta_v": meta_v, "meta_compat": meta_compat}

    v, compat, end = r.start()  # client-usable section
    inc.wire["client_v"], inc.wire["client_compat"] = v, compat
    if v < 4:
        raise CodecError(f"incremental client data v{v} unsupported")
    inc.fsid = r.take(16)
    inc.epoch = r.u32()
    inc.modified = r.utime()
    inc.new_pool_max = r.i64()
    inc.new_flags = r.i32()
    inc.fullmap = r.take(r.u32())
    inc.crush = r.take(r.u32())
    inc.new_max_osd = r.i32()
    for _ in range(r.u32()):
        pid = r.i64()
        pool, pw = _decode_pool(r)
        inc.new_pools[pid] = pool
        inc.new_pool_wire[pid] = pw
    for _ in range(r.u32()):
        pid = r.i64()
        inc.new_pool_names[pid] = r.string()
    for _ in range(r.u32()):
        inc.old_pools.add(r.i64())
    if v >= 7:
        for _ in range(r.u32()):
            osd = r.i32()
            p0 = r.off
            _skip_addrvec(r)
            inc.new_up_client[osd] = r.d[p0:r.off]
    else:
        raise CodecError("incremental client data v<7 addr maps unsupported")
    for _ in range(r.u32()):
        osd = r.i32()
        inc.new_state[osd] = r.u32() if v >= 5 else r.u8()
    for _ in range(r.u32()):
        osd = r.i32()
        inc.new_weight[osd] = r.u32()
    for _ in range(r.u32()):
        pg = r.pg()
        inc.new_pg_temp[pg] = [r.i32() for _ in range(r.u32())]
    for _ in range(r.u32()):
        pg = r.pg()
        inc.new_primary_temp[pg] = r.i32()
    for _ in range(r.u32()):
        osd = r.i32()
        inc.new_primary_affinity[osd] = r.u32()
    for _ in range(r.u32()):
        name = r.string()
        prof = inc.new_erasure_code_profiles[name] = {}
        for _ in range(r.u32()):
            k = r.string()
            prof[k] = r.string()
    for _ in range(r.u32()):
        inc.old_erasure_code_profiles.append(r.string())
    if v >= 4:
        for _ in range(r.u32()):
            pg = r.pg()
            inc.new_pg_upmap[pg] = [r.i32() for _ in range(r.u32())]
        for _ in range(r.u32()):
            inc.old_pg_upmap.add(r.pg())
        for _ in range(r.u32()):
            pg = r.pg()
            inc.new_pg_upmap_items[pg] = [
                (r.i32(), r.i32()) for _ in range(r.u32())
            ]
        for _ in range(r.u32()):
            inc.old_pg_upmap_items.add(r.pg())
    if v >= 6:
        p0 = r.off
        for _ in range(2):  # new_removed_snaps, new_purged_snaps
            for _ in range(r.u32()):
                r.i64()
                r.take(16 * r.u32())
        inc.wire["snaps_raw"] = r.d[p0:r.off]
    if v >= 8:
        inc.wire["last_up_change"] = r.utime()
        inc.wire["last_in_change"] = r.utime()
    inc.wire["client_tail"] = r.d[r.off:end]
    r.off = end

    # osd-only section: preserved raw, whole frame
    p0 = r.off
    _, _, oend = r.start()
    inc.wire["osd_raw"] = r.d[p0:oend]
    r.off = oend

    if r.off + 8 <= meta_end:
        stored = r.u32()  # inc_crc (in the hole position)
        inc.full_crc = r.u32()
        # crc covers [0, hole) + [hole_end, end) (reference OSDMap.cc:714-731)
        hole = r.off - 8
        calc = crc32c(data[:hole], 0xFFFFFFFF)
        calc = crc32c(data[hole + 4:], calc)
        if stored != calc:
            raise CodecError(
                f"incremental crc mismatch: stored {stored:#x} calc {calc:#x}"
            )
    return inc


def _default_inc_osd_only(inc: Incremental) -> bytes:
    """Minimal decodable osd-only section for self-built incrementals: all
    change-maps empty (reference field list src/osd/OSDMap.cc:650-709,
    target_v 9) — except new_hb_back_up/new_hb_front_up, which must carry
    an entry for every new_up_client osd: the reference's
    apply_incremental dereferences new_hb_back_up.find(osd) without a
    presence check (src/osd/OSDMap.cc:2203-2208)."""

    def hb_map(w: W):
        w.u32(len(inc.new_up_client))
        for osd in sorted(inc.new_up_client):
            w.i32(osd)
            w.u8(2)  # empty entity_addrvec_t
            w.u32(0)

    w = W()
    h = w.start(9, 1)
    hb_map(w)  # new_hb_back_up
    w.u32(0)  # new_up_thru
    w.u32(0)  # new_last_clean_interval
    w.u32(0)  # new_lost
    w.u32(0)  # new_blocklist
    w.u32(0)  # old_blocklist
    w.u32(0)  # new_up_cluster
    w.string("")  # cluster_snapshot
    w.u32(0)  # new_uuid
    w.u32(0)  # new_xinfo
    hb_map(w)  # new_hb_front_up
    w.u64(0)  # features
    w.raw(b"\x00\x00\x80\xbf" * 3)  # near/full/backfillfull ratios = -1.0f
    w.u8(0xFF)  # new_require_min_compat_client (unset)
    w.u8(0xFF)  # new_require_osd_release (unset)
    w.u32(0)  # new_crush_node_flags
    w.u32(0)  # new_device_class_flags
    w.finish(h)
    return bytes(w.b)


def encode_incremental(inc: Incremental) -> bytes:
    """reference src/osd/OSDMap.cc:557 (Incremental::encode)."""
    wire = inc.wire or {}
    w = W()
    mh = w.start(wire.get("meta_v", 8), wire.get("meta_compat", 7))

    v = wire.get("client_v", 8)
    ch = w.start(v, wire.get("client_compat", 1))
    w.raw(inc.fsid)
    w.u32(inc.epoch)
    w.utime(inc.modified)
    w.i64(inc.new_pool_max)
    w.i32(inc.new_flags)
    w.u32(len(inc.fullmap))
    w.raw(inc.fullmap)
    w.u32(len(inc.crush))
    w.raw(inc.crush)
    w.i32(inc.new_max_osd)
    w.u32(len(inc.new_pools))
    for pid in sorted(inc.new_pools):
        w.i64(pid)
        _encode_pool(w, inc.new_pools[pid], inc.new_pool_wire.get(pid))
    w.u32(len(inc.new_pool_names))
    for pid in sorted(inc.new_pool_names):
        w.i64(pid)
        w.string(inc.new_pool_names[pid])
    w.u32(len(inc.old_pools))
    for pid in sorted(inc.old_pools):
        w.i64(pid)
    w.u32(len(inc.new_up_client))
    for osd in sorted(inc.new_up_client):
        w.i32(osd)
        w.raw(inc.new_up_client[osd] or b"\x02\x00\x00\x00\x00")
    w.u32(len(inc.new_state))
    for osd in sorted(inc.new_state):
        w.i32(osd)
        w.u32(inc.new_state[osd])
    w.u32(len(inc.new_weight))
    for osd in sorted(inc.new_weight):
        w.i32(osd)
        w.u32(inc.new_weight[osd])
    w.u32(len(inc.new_pg_temp))
    for pg in _pg_sorted(inc.new_pg_temp):
        w.pg(pg)
        osds = inc.new_pg_temp[pg]
        w.u32(len(osds))
        for o in osds:
            w.i32(o)
    w.u32(len(inc.new_primary_temp))
    for pg in _pg_sorted(inc.new_primary_temp):
        w.pg(pg)
        w.i32(inc.new_primary_temp[pg])
    w.u32(len(inc.new_primary_affinity))
    for osd in sorted(inc.new_primary_affinity):
        w.i32(osd)
        w.u32(inc.new_primary_affinity[osd])
    w.u32(len(inc.new_erasure_code_profiles))
    for name in sorted(inc.new_erasure_code_profiles):
        w.string(name)
        prof = inc.new_erasure_code_profiles[name]
        w.u32(len(prof))
        for k in sorted(prof):
            w.string(k)
            w.string(prof[k])
    w.u32(len(inc.old_erasure_code_profiles))
    for name in inc.old_erasure_code_profiles:
        w.string(name)
    if v >= 4:
        w.u32(len(inc.new_pg_upmap))
        for pg in _pg_sorted(inc.new_pg_upmap):
            w.pg(pg)
            osds = inc.new_pg_upmap[pg]
            w.u32(len(osds))
            for o in osds:
                w.i32(o)
        w.u32(len(inc.old_pg_upmap))
        for pg in _pg_sorted(inc.old_pg_upmap):
            w.pg(pg)
        w.u32(len(inc.new_pg_upmap_items))
        for pg in _pg_sorted(inc.new_pg_upmap_items):
            w.pg(pg)
            pairs = inc.new_pg_upmap_items[pg]
            w.u32(len(pairs))
            for frm, to in pairs:
                w.i32(frm)
                w.i32(to)
        w.u32(len(inc.old_pg_upmap_items))
        for pg in _pg_sorted(inc.old_pg_upmap_items):
            w.pg(pg)
    if v >= 6:
        w.raw(wire.get("snaps_raw", b"\0" * 8))
    if v >= 8:
        w.utime(wire.get("last_up_change", (0, 0)))
        w.utime(wire.get("last_in_change", (0, 0)))
    w.raw(wire.get("client_tail", b""))
    w.finish(ch)

    w.raw(wire.get("osd_raw") or _default_inc_osd_only(inc))

    # inc_crc hole + full_crc, inside the meta wrapper (OSDMap.cc:714-731)
    hole = len(w.b)
    w.u32(0)
    w.u32(inc.full_crc)
    w.finish(mh)
    crc = crc32c(bytes(w.b[:hole]), 0xFFFFFFFF)
    crc = crc32c(bytes(w.b[hole + 4:]), crc)
    w.b[hole:hole + 4] = crc.to_bytes(4, "little")
    return bytes(w.b)


def looks_like_incremental(data: bytes) -> bool:
    """Full maps and incrementals share the outer framing; distinguish by
    the client section's layout: an incremental's bytes 22-29 are
    new_pool_max (i64), a full map's are created.utime — full maps have
    fsid right after the inner header, incrementals too, but the
    incremental's epoch is followed by modified + i64 new_pool_max whose
    high word is 0xffffffff for the common "-1 = unchanged" case.  Robust
    discrimination: try decoding as incremental and check crc."""
    try:
        decode_incremental(data)
        return True
    except Exception:
        return False


# ---------------------------------------------------------------- apply


def apply_incremental(m: OSDMap, inc: Incremental) -> OSDMap:
    """Advance ``m`` from epoch e to e+1 (reference src/osd/OSDMap.cc:2061).
    Returns the resulting map — ``m`` mutated in place, or a fresh decode
    when the incremental carries a full map."""
    if inc.epoch != m.epoch + 1:
        raise ValueError(f"incremental epoch {inc.epoch} != {m.epoch}+1")
    # fsid guard (reference OSDMap.cc:2064-2067): adopt at epoch 1, reject
    # mismatches otherwise.  An all-zero inc.fsid means "unset" for
    # programmatically-built deltas (the reference always carries one).
    zero_fsid = b"\0" * 16
    m_fsid = getattr(m, "wire", {}).get("fsid", zero_fsid) if \
        getattr(m, "wire", None) else zero_fsid
    if inc.epoch == 1:
        pass  # fsid adopted below via wire
    elif inc.fsid != zero_fsid and m_fsid != zero_fsid \
            and inc.fsid != m_fsid:
        raise ValueError("incremental fsid does not match map fsid")

    if inc.fullmap:
        full = decode_osdmap(inc.fullmap)
        if full.epoch != inc.epoch:
            raise ValueError("fullmap epoch mismatch")
        return full

    m.epoch += 1
    wire = getattr(m, "wire", None)
    if wire is None:
        wire = m.wire = {"pools": {}}
    wire["modified"] = inc.modified  # OSDMap.cc:2072
    if inc.epoch == 1 and inc.fsid != zero_fsid:
        wire["fsid"] = inc.fsid

    if inc.new_flags >= 0:
        wire["flags"] = inc.new_flags
    if inc.new_max_osd >= 0:
        m.set_max_osd(inc.new_max_osd)
    if inc.new_pool_max != -1:
        m.pool_max = inc.new_pool_max

    for pid, pool in inc.new_pools.items():
        m.pools[pid] = PgPool(**vars(pool))
        if pid in inc.new_pool_wire:
            pw = dict(inc.new_pool_wire[pid])
            pw["last_change"] = m.epoch  # OSDMap.cc:2106
            wire.setdefault("pools", {})[pid] = pw
    for pid, name in inc.new_pool_names.items():
        m.pool_name[pid] = name
    for pid in inc.old_pools:
        m.pools.pop(pid, None)
        m.pool_name.pop(pid, None)
        wire.get("pools", {}).pop(pid, None)

    for osd, weight in inc.new_weight.items():
        m.osd_weight[osd] = weight
        if weight:  # marking in clears AUTOOUT/NEW (OSDMap.cc:2153-2157)
            m.osd_state[osd] &= ~(OSD_AUTOOUT | OSD_NEW)

    for osd, aff in inc.new_primary_affinity.items():
        m.set_primary_affinity(osd, aff)

    profs = m.erasure_code_profiles
    for name in inc.old_erasure_code_profiles:
        profs.pop(name, None)
    for name, prof in inc.new_erasure_code_profiles.items():
        profs[name] = dict(prof)

    for osd, s in inc.new_state.items():
        s = s or OSD_UP
        if (m.osd_state[osd] & OSD_EXISTS) and (s & OSD_EXISTS):
            # destroy: clear everything interesting (OSDMap.cc:2183-2196)
            m.osd_state[osd] = 0
            m.set_primary_affinity(osd, DEFAULT_PRIMARY_AFFINITY)
        else:
            m.osd_state[osd] ^= s

    for osd in inc.new_up_client:
        m.osd_state[osd] |= OSD_EXISTS | OSD_UP

    for pg, osds in inc.new_pg_temp.items():
        if osds:
            m.pg_temp[pg] = list(osds)
        else:
            m.pg_temp.pop(pg, None)
    for pg, primary in inc.new_primary_temp.items():
        if primary == -1:
            m.primary_temp.pop(pg, None)
        else:
            m.primary_temp[pg] = primary

    for pg, osds in inc.new_pg_upmap.items():
        m.pg_upmap[pg] = list(osds)
    for pg in inc.old_pg_upmap:
        m.pg_upmap.pop(pg, None)
    for pg, pairs in inc.new_pg_upmap_items.items():
        m.pg_upmap_items[pg] = list(pairs)
    for pg in inc.old_pg_upmap_items:
        m.pg_upmap_items.pop(pg, None)

    # new crush map last, after up/down stuff (OSDMap.cc:2330-2341)
    if inc.crush:
        m.crush = decode_crushmap(inc.crush)
        wire["crush_raw"] = inc.crush
        wire["crush_obj"] = m.crush
        wire["crush_version"] = wire.get("crush_version", 1) + 1
    return m
