"""ceph.conf parsing + OSDMap::build_simple_from_conf.

Mirrors the reference flow used by ``osdmaptool --create-from-conf``
(reference src/osd/OSDMap.cc:4172 build_simple_optioned with nosd=-1 and
:4339 build_simple_crush_map_from_conf): every ``[osd.N]`` section becomes
a device inserted at its host/rack/row/room/datacenter location via
``insert_item``, sections processed in lexicographic order (the C++ conf
stores sections in a std::map), so bucket ids and item orders reproduce
the reference byte-for-byte — pinned by the create-racks.t cram golden.
"""

from __future__ import annotations

import re

from ceph_tpu.crush.types import BucketAlg, CrushMap, Tunables
from ceph_tpu.osd.osdmap import DEFAULT_TYPES, OSDMap
from ceph_tpu.osd.types import PgPool, PoolType


def parse_ceph_conf(path: str) -> dict[str, dict[str, str]]:
    """Minimal ini parser for ceph.conf: ``[section]`` headers,
    ``key = value`` lines, ``;``/``#`` comments.  Keys are normalized
    with spaces collapsed to underscores (ceph accepts ' ', '_', '-'
    interchangeably)."""
    sections: dict[str, dict[str, str]] = {}
    cur: dict[str, str] | None = None
    with open(path) as f:
        for line in f:
            line = line.split(";", 1)[0].split("#", 1)[0].strip()
            if not line:
                continue
            mh = re.match(r"\[(.+)\]$", line)
            if mh:
                cur = sections.setdefault(mh.group(1).strip(), {})
                continue
            if "=" in line and cur is not None:
                k, v = line.split("=", 1)
                k = re.sub(r"[\s_-]+", "_", k.strip())
                cur[k] = v.strip()
    return sections


def conf_get(sections: dict, keys: list[str], name: str,
             default: str | None = None) -> str | None:
    """Layered lookup: first match wins across the given section names."""
    name = re.sub(r"[\s_-]+", "_", name)
    for sec in keys:
        if sec in sections and name in sections[sec]:
            return sections[sec][name]
    return default


def build_from_conf(
    conf_path: str,
    pg_bits: int = 6,
    pgp_bits: int = 6,
    default_pool: bool = True,
    tunables: Tunables | None = None,
) -> OSDMap:
    """reference src/osd/OSDMap.cc:4172 (nosd=-1 path) + :4339."""
    sections = parse_ceph_conf(conf_path)

    crush = CrushMap(tunables)
    crush.type_names = dict(DEFAULT_TYPES)
    root = crush.add_bucket(BucketAlg.STRAW2, 11, [], [], name="default")

    osd_sections = sorted(
        s for s in sections
        if re.fullmatch(r"osd\.\d+", s)
    )
    max_id = -1
    for sec in osd_sections:
        o = int(sec[4:])
        max_id = max(max_id, o)
        host = conf_get(sections, [sec], "host") or "unknownhost"
        rack = conf_get(sections, [sec], "rack") or "unknownrack"
        loc = {"host": host, "rack": rack, "root": "default"}
        for extra in ("row", "room", "datacenter"):
            v = conf_get(sections, [sec], extra)
            if v:
                loc[extra] = v
        crush.insert_item(o, 1.0, sec, loc)

    crush.make_replicated_rule(root, failure_domain_type=1)
    crush.rule_names[0] = "replicated_rule"

    m = OSDMap(crush)
    m.epoch = 0  # caller (osdmaptool) bumps via `modified`
    m.set_max_osd(max_id + 1)

    if default_pool:
        size = int(conf_get(
            sections, ["global", "mon", "osd"], "osd_pool_default_size", "3"
        ))
        poolbase = m.max_osd if m.max_osd else 1
        pgp = min(pgp_bits, pg_bits)
        pool = PgPool(
            type=PoolType.REPLICATED, size=size,
            min_size=size - size // 2,
            crush_rule=0,
            pg_num=poolbase << pg_bits, pgp_num=poolbase << pgp,
        )
        m.pool_max = 0
        m.add_pool("rbd", pool, 1)
    m.erasure_code_profiles["default"] = {
        "k": "2", "m": "2", "plugin": "jerasure",
        "technique": "reed_sol_van",
    }
    return m
