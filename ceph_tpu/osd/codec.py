"""Binary OSDMap codec — wire-compatible with the reference.

Implements OSDMap::encode / ::decode (reference src/osd/OSDMap.cc:2914,
3249): the ENCODE_START(8,7) meta wrapper holding a client-usable section
(v3..v9), an osd-only section, and a trailing CRC-32C.  Nested structures
follow their reference encoders: pg_pool_t (src/osd/osd_types.cc:1833,
v≥14 length-framed), pg_t (osd_types.h:483: u8 1 + u64 pool + u32 seed +
i32 -1), utime_t (u32 sec + u32 nsec), entity_addr(vec)_t markers
(src/msg/msg_types.h:435, msg_types.cc:317).

Fidelity model: every field the placement stack uses is parsed into the
OSDMap model; everything else (addr vectors, the whole osd-only section,
pool cache/tier fields, unknown version tails) is captured as raw spans in
`m.wire` / per-pool raw dicts and replayed verbatim on encode — so
decode→encode of a real cluster artifact is byte-exact (CRC recomputed and
verified), without modeling subsystems the framework doesn't have.  Maps
built programmatically (no wire info) encode with modern defaults
(client v9 / pool v29 / osd-only v9) that the reference can decode.
"""

from __future__ import annotations

import struct

from ceph_tpu.crush.codec import decode_crushmap, encode_crushmap
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.types import PgId, PgPool, PoolType
from ceph_tpu.utils.crc32c import crc32c


class CodecError(ValueError):
    pass


# ---------------------------------------------------------------- primitives


class R:
    def __init__(self, data: bytes, off: int = 0):
        self.d = data
        self.off = off

    def take(self, n: int) -> bytes:
        if self.off + n > len(self.d):
            raise CodecError(
                f"truncated osdmap (need {n} at {self.off}/{len(self.d)})"
            )
        b = self.d[self.off:self.off + n]
        self.off += n
        return b

    def u8(self):
        return self.take(1)[0]

    def u16(self):
        return struct.unpack("<H", self.take(2))[0]

    def u32(self):
        return struct.unpack("<I", self.take(4))[0]

    def i32(self):
        return struct.unpack("<i", self.take(4))[0]

    def u64(self):
        return struct.unpack("<Q", self.take(8))[0]

    def i64(self):
        return struct.unpack("<q", self.take(8))[0]

    def string(self) -> str:
        return self.take(self.u32()).decode()

    def utime(self):
        return (self.u32(), self.u32())

    def start(self):
        """ENCODE_START framing: (struct_v, compat, end_offset)."""
        v = self.u8()
        compat = self.u8()
        ln = self.u32()
        return v, compat, self.off + ln

    def pg(self) -> PgId:
        v = self.u8()
        if v != 1:
            raise CodecError(f"pg_t v{v}")
        pool = self.u64()
        seed = self.u32()
        self.i32()  # preferred (-1)
        return PgId(pool, seed)


class W:
    def __init__(self):
        self.b = bytearray()

    def raw(self, data: bytes):
        self.b += data

    def u8(self, v):
        self.b += struct.pack("<B", v & 0xFF)

    def u16(self, v):
        self.b += struct.pack("<H", v & 0xFFFF)

    def u32(self, v):
        self.b += struct.pack("<I", v & 0xFFFFFFFF)

    def i32(self, v):
        self.b += struct.pack("<i", v)

    def u64(self, v):
        self.b += struct.pack("<Q", v & (2**64 - 1))

    def i64(self, v):
        self.b += struct.pack("<q", v)

    def string(self, s: str):
        e = s.encode()
        self.u32(len(e))
        self.b += e

    def utime(self, t):
        self.u32(t[0])
        self.u32(t[1])

    def pg(self, pg: PgId):
        self.u8(1)
        self.u64(pg.pool)
        self.u32(pg.seed)
        self.i32(-1)

    def start(self, v: int, compat: int):
        """ENCODE_START; returns a patch handle for finish()."""
        self.u8(v)
        self.u8(compat)
        self.u32(0)
        return len(self.b)

    def finish(self, handle: int):
        ln = len(self.b) - handle
        self.b[handle - 4:handle] = struct.pack("<I", ln)


# ------------------------------------------------------- addr skip helpers


def _skip_addr(r: R):
    """entity_addr_t (reference src/msg/msg_types.h:435): u8 marker —
    0 => legacy u32 marker + u32 nonce + 128B sockaddr_storage,
    1 => ENCODE wrapper."""
    marker = r.u8()
    if marker == 0:
        r.take(3 + 4 + 128)
    elif marker == 1:
        _, _, end = r.start()
        r.off = end
    else:
        raise CodecError(f"entity_addr_t marker {marker}")


def _skip_addrvec(r: R):
    """entity_addrvec_t (reference src/msg/msg_types.cc:317)."""
    marker = r.u8()
    if marker == 0:
        r.take(3 + 4 + 128)
    elif marker == 1:
        _, _, end = r.start()
        r.off = end
    elif marker == 2:
        n = r.u32()
        for _ in range(n):
            _skip_addr(r)
    else:
        raise CodecError(f"entity_addrvec_t marker {marker}")


def _skip_addr_vector(r: R, vecform: bool):
    """client_addrs: v>=8 vector<addrvec>, v<8 vector<addr>
    (reference src/osd/OSDMap.cc:2984-2988)."""
    n = r.u32()
    for _ in range(n):
        if vecform:
            _skip_addrvec(r)
        else:
            _skip_addr(r)


# ------------------------------------------------------------- pg_pool_t


def _decode_pool(r: R) -> tuple[PgPool, dict]:
    """pg_pool_t::decode (reference src/osd/osd_types.cc:2052; encode
    :1833).  Parses the placement-relevant head; preserves the rest raw."""
    v, compat, end = r.start()
    if v < 14:
        raise CodecError(f"pg_pool_t v{v} < 14 (pre-firefly) unsupported")
    w: dict = {"v": v, "compat": compat}
    ptype = r.u8()
    size = r.u8()
    crush_rule = r.u8()
    object_hash = r.u8()
    pg_num = r.u32()
    pgp_num = r.u32()
    r.u32()  # lpg_num
    r.u32()  # lpgp_num
    w["last_change"] = r.u32()
    w["snap_seq"] = r.u64()
    w["snap_epoch"] = r.u32()
    # snaps: map<snapid_t, pool_snap_info_t> — wrapper-framed entries
    p0 = r.off
    n = r.u32()
    for _ in range(n):
        r.u64()
        _, _, e2 = r.start()
        r.off = e2
    # removed_snaps: interval_set<snapid_t>
    m = r.u32()
    for _ in range(m):
        r.u64()
        r.u64()
    w["snaps_raw"] = r.d[p0:r.off]
    w["auid"] = r.u64()
    flags = r.u64()
    r.u32()  # crash_replay_interval
    min_size = r.u8()
    w["quota_max_bytes"] = r.u64()
    w["quota_max_objects"] = r.u64()
    p0 = r.off
    tn = r.u32()
    r.take(8 * tn)  # tiers
    r.take(8)  # tier_of
    r.take(1)  # cache_mode
    r.take(16)  # read_tier, write_tier
    pn = r.u32()  # properties
    for _ in range(pn):
        r.string()
        r.string()
    _, _, e2 = r.start()  # hit_set_params wrapper
    r.off = e2
    r.take(4 * 3)  # hit_set_period, hit_set_count, stripe_width
    r.take(8 * 2)  # target_max_bytes/objects
    r.take(4 * 4)  # cache ratios/ages
    w["mid_raw"] = r.d[p0:r.off]
    ec_profile = r.string()
    w["tail_raw"] = r.d[r.off:end]
    r.off = end

    pool = PgPool(
        type=PoolType(ptype),
        size=size,
        min_size=min_size,
        pg_num=pg_num,
        pgp_num=pgp_num or pg_num,
        crush_rule=crush_rule,
        flags=flags,
        object_hash=object_hash,
        erasure_code_profile=ec_profile,
    )
    return pool, w


def _encode_pool(w: W, pool: PgPool, wire: dict | None):
    if wire:  # replay a decoded pool byte-exactly
        h = w.start(wire["v"], wire["compat"])
        w.u8(int(pool.type))
        w.u8(pool.size)
        w.u8(pool.crush_rule)
        w.u8(pool.object_hash)
        w.u32(pool.pg_num)
        w.u32(pool.pgp_num)
        w.u32(0)
        w.u32(0)
        w.u32(wire["last_change"])
        w.u64(wire["snap_seq"])
        w.u32(wire["snap_epoch"])
        w.raw(wire["snaps_raw"])
        w.u64(wire["auid"])
        w.u64(pool.flags)
        w.u32(0)
        w.u8(pool.min_size)
        w.u64(wire["quota_max_bytes"])
        w.u64(wire["quota_max_objects"])
        w.raw(wire["mid_raw"])
        w.string(pool.erasure_code_profile)
        w.raw(wire["tail_raw"])
        w.finish(h)
        return
    # fresh pool: modern v29 defaults (reference encode v29 field list,
    # src/osd/osd_types.cc:1954-2046)
    h = w.start(29, 5)
    w.u8(int(pool.type))
    w.u8(pool.size)
    w.u8(pool.crush_rule)
    w.u8(pool.object_hash)
    w.u32(pool.pg_num)
    w.u32(pool.pgp_num)
    w.u32(0)  # lpg_num
    w.u32(0)  # lpgp_num
    w.u32(0)  # last_change
    w.u64(0)  # snap_seq
    w.u32(0)  # snap_epoch
    w.u32(0)  # snaps (empty map)
    w.u32(0)  # removed_snaps (empty interval_set)
    w.u64(0)  # auid
    w.u64(pool.flags)
    w.u32(0)  # crash_replay_interval
    w.u8(pool.min_size)
    w.u64(0)  # quota_max_bytes
    w.u64(0)  # quota_max_objects
    w.u32(0)  # tiers
    w.i64(-1)  # tier_of
    w.u8(0)  # cache_mode
    w.i64(-1)  # read_tier
    w.i64(-1)  # write_tier
    w.u32(0)  # properties
    hh = w.start(1, 1)  # hit_set_params: TYPE_NONE
    w.u8(0)
    w.finish(hh)
    w.u32(0)  # hit_set_period
    w.u32(0)  # hit_set_count
    w.u32(0)  # stripe_width
    w.u64(0)  # target_max_bytes
    w.u64(0)  # target_max_objects
    w.u32(0)  # cache_target_dirty_ratio_micro
    w.u32(0)  # cache_target_full_ratio_micro
    w.u32(0)  # cache_min_flush_age
    w.u32(0)  # cache_min_evict_age
    w.string(pool.erasure_code_profile)
    w.u32(0)  # last_force_op_resend_preluminous
    w.u32(0)  # min_read_recency_for_promote
    w.u64(pool.expected_num_objects)
    w.u32(0)  # cache_target_dirty_high_ratio_micro (v19)
    w.u32(0)  # min_write_recency_for_promote (v20)
    w.u8(1)  # use_gmt_hitset (v21)
    w.u8(0)  # fast_read (v22)
    w.u32(0)  # hit_set_grade_decay_rate (v23)
    w.u32(0)  # hit_set_search_last_n (v23)
    hh = w.start(2, 1)  # opts: pool_opts_t empty (v24)
    w.u32(0)
    w.finish(hh)
    w.u32(0)  # last_force_op_resend_prenautilus (v25)
    w.u32(0)  # application_metadata (v26)
    w.utime((0, 0))  # create_time (v27)
    w.u32(pool.pg_num)  # pg_num_target (v28)
    w.u32(pool.pgp_num)  # pgp_num_target
    w.u32(pool.pg_num_pending or pool.pg_num)  # pg_num_pending
    w.u32(0)  # pg_num_dec_last_epoch_started (14.1.x relic)
    w.u32(0)  # pg_num_dec_last_epoch_clean
    w.u32(0)  # last_force_op_resend
    w.u8(0)  # pg_autoscale_mode
    hh = w.start(1, 1)  # last_pg_merge_meta (v29)
    w.pg(PgId(0, 0))
    w.u32(0)  # ready_epoch
    w.u32(0)  # last_epoch_started
    w.u32(0)  # last_epoch_clean
    w.u64(0)  # source_version.version
    w.u32(0)  # source_version.epoch
    w.u64(0)  # target_version.version
    w.u32(0)  # target_version.epoch
    w.finish(hh)
    w.finish(h)


# --------------------------------------------------------------- top level


def looks_like_osdmap(data: bytes) -> bool:
    if len(data) < 10 or data[1] != 7 or data[0] < 7 or data[0] > 10:
        return False
    ln = struct.unpack("<I", data[2:6])[0]
    return ln == len(data) - 6


def decode_osdmap(data: bytes) -> OSDMap:
    r = R(data)
    meta_v, meta_compat, meta_end = r.start()
    if meta_v < 7:
        raise CodecError(f"osdmap meta v{meta_v} (classic encoding) "
                         "unsupported")

    m = OSDMap()
    wire: dict = {"meta_v": meta_v, "meta_compat": meta_compat,
                  "pools": {}}
    m.wire = wire

    # ---- client-usable section (reference OSDMap.cc:2948-3023)
    v, compat, end = r.start()
    wire["client_v"], wire["client_compat"] = v, compat
    if v < 4:
        raise CodecError(f"client data v{v} unsupported")
    wire["fsid"] = r.take(16)
    m.epoch = r.u32()
    wire["created"] = r.utime()
    wire["modified"] = r.utime()
    n = r.u32()
    for _ in range(n):
        pid = r.i64()
        pool, pw = _decode_pool(r)
        m.pools[pid] = pool
        wire["pools"][pid] = pw
    n = r.u32()
    for _ in range(n):
        pid = r.i64()
        m.pool_name[pid] = r.string()
    m.pool_max = r.i32()  # int32_t (reference src/osd/OSDMap.h:523)
    wire["flags"] = r.u32()
    max_osd = r.i32()
    if v >= 5:
        n = r.u32()
        m.osd_state = [r.u32() for _ in range(n)]
    else:
        n = r.u32()
        m.osd_state = [r.u8() for _ in range(n)]
    n = r.u32()
    m.osd_weight = [r.u32() for _ in range(n)]
    p0 = r.off
    _skip_addr_vector(r, vecform=v >= 8)
    wire["client_addrs_raw"] = r.d[p0:r.off]
    n = r.u32()
    for _ in range(n):
        pg = r.pg()
        cnt = r.u32()
        m.pg_temp[pg] = [r.i32() for _ in range(cnt)]
    n = r.u32()
    for _ in range(n):
        pg = r.pg()
        m.primary_temp[pg] = r.i32()
    n = r.u32()
    if n:
        m.osd_primary_affinity = [r.u32() for _ in range(n)]
    cblob = r.take(r.u32())
    wire["crush_raw"] = cblob
    m.crush = decode_crushmap(cblob)
    wire["crush_obj"] = m.crush  # staleness guard for encode
    n = r.u32()
    profs: dict[str, dict[str, str]] = {}
    for _ in range(n):
        name = r.string()
        kn = r.u32()
        profs[name] = {}
        for _ in range(kn):
            k = r.string()
            profs[name][k] = r.string()
    wire["erasure_code_profiles"] = profs
    m.erasure_code_profiles = profs
    if v >= 4:
        n = r.u32()
        for _ in range(n):
            pg = r.pg()
            cnt = r.u32()
            m.pg_upmap[pg] = [r.i32() for _ in range(cnt)]
        n = r.u32()
        for _ in range(n):
            pg = r.pg()
            cnt = r.u32()
            m.pg_upmap_items[pg] = [
                (r.i32(), r.i32()) for _ in range(cnt)
            ]
    if v >= 6:
        wire["crush_version"] = r.u32()
    if v >= 7:
        p0 = r.off
        for _ in range(2):  # new_removed_snaps, new_purged_snaps
            n = r.u32()
            for _ in range(n):
                r.i64()
                iv = r.u32()
                r.take(16 * iv)
        wire["snaps_raw"] = r.d[p0:r.off]
    if v >= 9:
        wire["last_up_change"] = r.utime()
        wire["last_in_change"] = r.utime()
    wire["client_tail"] = r.d[r.off:end]
    r.off = end

    # ---- osd-only section: preserved raw (framing incl. header)
    p0 = r.off
    _, _, oend = r.start()
    wire["osd_raw"] = r.d[p0:oend]
    r.off = oend

    # ---- trailing crc (reference OSDMap.cc:3102-3112)
    if r.off + 4 <= meta_end:
        stored = r.u32()
        calc = crc32c(data[: r.off - 4], 0xFFFFFFFF)
        if stored != calc:
            raise CodecError(
                f"osdmap crc mismatch: stored {stored:#x} calc {calc:#x}"
            )
        wire["had_crc"] = True
    m.max_osd = max_osd  # decoded vectors are authoritative
    return m


def _default_osd_only(m: OSDMap) -> bytes:
    """A decodable osd-only section for self-built maps: default osd_info/
    xinfo/uuid entries, empty addrs/blocklist (reference field list
    OSDMap.cc:3025-3098, target_v 9)."""
    w = W()
    h = w.start(9, 1)
    w.u32(m.max_osd)  # hb_back_addrs: one empty addrvec per osd
    for _ in range(m.max_osd):
        w.u8(2)
        w.u32(0)
    w.u32(m.max_osd)  # osd_info: classic struct, six u32s after v byte
    for _ in range(m.max_osd):
        w.u8(1)
        for _ in range(6):
            w.u32(0)
    w.u32(0)  # blocklist
    w.u32(m.max_osd)  # cluster_addrs
    for _ in range(m.max_osd):
        w.u8(2)
        w.u32(0)
    w.u32(0)  # cluster_snapshot_epoch
    w.string("")  # cluster_snapshot
    w.u32(m.max_osd)  # osd_uuid
    for _ in range(m.max_osd):
        w.raw(b"\0" * 16)
    w.u32(m.max_osd)  # osd_xinfo_t (wrapper-framed each)
    for _ in range(m.max_osd):
        hh = w.start(4, 1)
        w.utime((0, 0))  # down_stamp
        w.u32(0)  # laggy_probability (scaled)
        w.u32(0)  # laggy_interval
        w.u64(0)  # features
        w.u32(0x10000)  # old_weight
        w.utime((0, 0))  # last_purged_snaps_scrub (v3)
        w.u32(0)  # dead_epoch (v4)
        w.finish(hh)
    w.u32(m.max_osd)  # hb_front_addrs
    for _ in range(m.max_osd):
        w.u8(2)
        w.u32(0)
    w.u32(0)  # nearfull_ratio (float as u32? encoded as float)
    w.u32(0)  # full_ratio
    w.u32(0)  # backfillfull_ratio
    w.u8(0)  # require_min_compat_client (ceph_release_t: u8)
    w.u8(0)  # require_osd_release
    w.u32(0)  # removed_snaps_queue (v6)
    w.u32(0)  # crush_node_flags (v8)
    w.u32(0)  # device_class_flags (v9)
    w.finish(h)
    return bytes(w.b)


def encode_osdmap(m: OSDMap) -> bytes:
    wire = getattr(m, "wire", None) or {}
    pools_w = wire.get("pools", {})

    w = W()
    mh = w.start(wire.get("meta_v", 8), wire.get("meta_compat", 7))

    v = wire.get("client_v", 9)
    ch = w.start(v, wire.get("client_compat", 1))
    w.raw(wire.get("fsid", b"\0" * 16))
    w.u32(m.epoch)
    w.utime(wire.get("created", (0, 0)))
    w.utime(wire.get("modified", (0, 0)))
    w.u32(len(m.pools))
    for pid in sorted(m.pools):
        w.i64(pid)
        _encode_pool(w, m.pools[pid], pools_w.get(pid))
    w.u32(len(m.pool_name))
    for pid in sorted(m.pool_name):
        w.i64(pid)
        w.string(m.pool_name[pid])
    w.i32(m.pool_max)  # int32_t (reference src/osd/OSDMap.h:523)
    w.u32(wire.get("flags", 0))
    w.i32(m.max_osd)
    w.u32(len(m.osd_state))
    for s in m.osd_state:
        w.u32(s)
    w.u32(len(m.osd_weight))
    for s in m.osd_weight:
        w.u32(s)
    if "client_addrs_raw" in wire:
        w.raw(wire["client_addrs_raw"])
    else:
        w.u32(m.max_osd)
        for _ in range(m.max_osd):
            w.u8(2)  # empty addrvec per osd
            w.u32(0)
    w.u32(len(m.pg_temp))
    for pg in sorted(m.pg_temp, key=lambda p: (p.pool, p.seed)):
        w.pg(pg)
        v2 = m.pg_temp[pg]
        w.u32(len(v2))
        for o in v2:
            w.i32(o)
    w.u32(len(m.primary_temp))
    for pg in sorted(m.primary_temp, key=lambda p: (p.pool, p.seed)):
        w.pg(pg)
        w.i32(m.primary_temp[pg])
    if m.osd_primary_affinity is not None:
        w.u32(len(m.osd_primary_affinity))
        for a in m.osd_primary_affinity:
            w.u32(a)
    else:
        w.u32(0)
    cblob = wire.get("crush_raw")
    if cblob is None or wire.get("crush_obj") is not m.crush:
        # crush was replaced/rebuilt since decode: re-encode it
        cblob = encode_crushmap(m.crush)
    w.u32(len(cblob))
    w.raw(cblob)
    profs = m.erasure_code_profiles
    w.u32(len(profs))
    for name in sorted(profs):
        w.string(name)
        w.u32(len(profs[name]))
        for k in sorted(profs[name]):
            w.string(k)
            w.string(profs[name][k])
    if v >= 4:
        w.u32(len(m.pg_upmap))
        for pg in sorted(m.pg_upmap, key=lambda p: (p.pool, p.seed)):
            w.pg(pg)
            v2 = m.pg_upmap[pg]
            w.u32(len(v2))
            for o in v2:
                w.i32(o)
        w.u32(len(m.pg_upmap_items))
        for pg in sorted(m.pg_upmap_items, key=lambda p: (p.pool, p.seed)):
            w.pg(pg)
            v2 = m.pg_upmap_items[pg]
            w.u32(len(v2))
            for frm, to in v2:
                w.i32(frm)
                w.i32(to)
    if v >= 6:
        w.u32(wire.get("crush_version", 1))
    if v >= 7:
        w.raw(wire.get("snaps_raw", b"\0" * 8))
    if v >= 9:
        w.utime(wire.get("last_up_change", (0, 0)))
        w.utime(wire.get("last_in_change", (0, 0)))
    w.raw(wire.get("client_tail", b""))
    w.finish(ch)

    w.raw(wire.get("osd_raw") or _default_osd_only(m))

    # crc goes inside the meta wrapper and covers everything before it
    # with the wrapper length already patched (reference OSDMap.cc:3099-3112)
    crc_at = len(w.b)
    w.u32(0)
    w.finish(mh)
    crc = crc32c(bytes(w.b[:crc_at]), 0xFFFFFFFF)
    w.b[crc_at:crc_at + 4] = struct.pack("<I", crc)
    return bytes(w.b)


def save_osdmap_bin(m: OSDMap, path: str) -> None:
    with open(path, "wb") as f:
        f.write(encode_osdmap(m))


def load_osdmap_bin(path: str) -> OSDMap:
    with open(path, "rb") as f:
        return decode_osdmap(f.read())
