"""Reference-parity text output for OSDMap: print / tree dumpers.

Mirrors the reference's exact formats so the osdmaptool cram transcripts
(reference src/test/cli/osdmaptool/*.t) replay verbatim:

- :func:`print_osdmap` — OSDMap::print (reference src/osd/OSDMap.cc:3855)
  incl. pg_pool_t's operator<< line format (src/osd/osd_types.cc:2339)
  and utime/uuid rendering.
- :func:`print_tree_plain` — OSDTreePlainDumper over a TextTable
  (src/osd/OSDMap.cc:3937-4002, src/common/TextTable.cc): ID/CLASS/
  WEIGHT/TYPE NAME/STATUS/REWEIGHT/PRI-AFF columns, children visited in
  (class, name) sort order (src/crush/CrushTreeDumper.h:130-152).
- :func:`tree_json` — OSDTreeFormattingDumper's node list (same
  traversal; children arrays in reverse-sorted order, `pool_weights`
  on non-root items, stray osd section).
"""

from __future__ import annotations

import time as _time

from ceph_tpu.osd.osdmap import (
    DEFAULT_PRIMARY_AFFINITY,
    OSDMap,
)

# ---------------------------------------------------------------- helpers


def fmt_float(v: float) -> str:
    """C++ ostream default float formatting (operator<< double): up to 6
    significant digits, no trailing zeros."""
    s = f"{v:.6g}"
    return s


def weightf5(v: float) -> str:
    """weightf_t: fixed 5 decimals (reference src/include/types.h
    operator<<(weightf_t): %.5f with < 0.01/0.0001 special cases)."""
    if v < 0.0001:
        return "0"
    if v < 0.01:
        return f"{v:.6f}"
    return f"{v:.5f}"


def fmt_uuid(b: bytes) -> str:
    h = b.hex()
    return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"


def fmt_utime(t: tuple[int, int]) -> str:
    """utime_t operator<< (reference src/include/utime.h): localtime ISO
    with numeric offset; we render in UTC."""
    sec, nsec = t
    base = _time.strftime("%Y-%m-%dT%H:%M:%S", _time.gmtime(sec))
    return f"{base}.{nsec // 1000:06d}+0000"


_RELEASE_NAMES = [
    "unknown", "argonaut", "bobtail", "cuttlefish", "dumpling", "emperor",
    "firefly", "giant", "hammer", "infernalis", "jewel", "kraken",
    "luminous", "mimic", "nautilus", "octopus", "pacific", "quincy",
]

# OSDMap flag bits -> names (reference src/include/ceph_osdmap.h +
# OSDMap::get_flag_string)
_FLAG_NAMES = [
    (1 << 0, "nearfull"), (1 << 1, "full"), (1 << 2, "pauserd"),
    (1 << 3, "pausewr"), (1 << 4, "pauserec"), (1 << 11, "noup"),
    (1 << 12, "nodown"), (1 << 13, "noout"), (1 << 14, "noin"),
    (1 << 15, "nobackfill"), (1 << 16, "norebalance"),
    (1 << 17, "norecover"), (1 << 18, "noscrub"), (1 << 19, "nodeep-scrub"),
    (1 << 20, "notieragent"), (1 << 21, "sortbitwise"),
    (1 << 22, "require_jewel_osds"), (1 << 23, "require_kraken_osds"),
    (1 << 24, "recovery_deletes"), (1 << 25, "purged_snapdirs"),
    (1 << 26, "pglog_hardlimit"),
]


def flag_string(flags: int) -> str:
    return ",".join(n for bit, n in _FLAG_NAMES if flags & bit)


def min_compat_client(m: OSDMap) -> str:
    """OSDMap::get_min_compat_client (reference src/osd/OSDMap.cc:3712):
    keyed off the features the map actually uses."""
    from ceph_tpu.crush.types import BucketAlg

    if m.pg_upmap or m.pg_upmap_items or m.crush.choose_args:
        return "luminous"
    t = m.crush.tunables
    if t.chooseleaf_stable:
        return "jewel"
    if any(b.alg == BucketAlg.STRAW2 for b in m.crush.buckets.values()):
        return "hammer"
    if t.chooseleaf_vary_r or (m.osd_primary_affinity is not None):
        return "firefly"
    if t.choose_local_tries == 0:
        return "dumpling"
    return "argonaut"


def _pool_flag_string(flags: int) -> str:
    names = [
        (1 << 0, "hashpspool"), (1 << 1, "full"),
        (1 << 2, "ec_overwrites"), (1 << 3, "incomplete_clones"),
        (1 << 4, "nodelete"), (1 << 5, "nopgchange"),
        (1 << 6, "nosizechange"), (1 << 7, "write_fadvise_dontneed"),
        (1 << 8, "noscrub"), (1 << 9, "nodeep-scrub"),
        (1 << 10, "full_quota"), (1 << 11, "nearfull"),
        (1 << 12, "backfillfull"), (1 << 13, "selfmanaged_snaps"),
        (1 << 14, "pool_snaps"), (1 << 15, "creating"),
    ]
    return ",".join(n for bit, n in names if flags & bit)


def pool_line(m: OSDMap, pid: int) -> str:
    """pg_pool_t operator<< (reference src/osd/osd_types.cc:2339)."""
    from ceph_tpu.osd.types import PoolType

    p = m.pools[pid]
    name = m.pool_name.get(pid, "<unknown>")
    tname = "replicated" if p.type == PoolType.REPLICATED else "erasure"
    out = [f"pool {pid} '{name}' {tname}"]
    if tname == "erasure":
        out.append(f" profile {p.erasure_code_profile}")
    out.append(
        f" size {p.size} min_size {p.min_size} crush_rule {p.crush_rule}"
        f" object_hash rjenkins pg_num {p.pg_num} pgp_num {p.pgp_num}"
    )
    mode = getattr(p, "pg_autoscale_mode", "on") or "on"
    out.append(f" autoscale_mode {mode}")
    out.append(f" last_change {getattr(p, 'last_change', 0)}")
    if p.flags:
        out.append(f" flags {_pool_flag_string(p.flags)}")
    out.append(f" stripe_width {getattr(p, 'stripe_width', 0)}")
    app = getattr(p, "application", None)
    if app is None and tname == "replicated" and name == "rbd":
        app = "rbd"
    if app:
        out.append(f" application {app}")
    return "".join(out)


def print_osdmap(m: OSDMap, out) -> None:
    """OSDMap::print (reference src/osd/OSDMap.cc:3855-3911)."""
    wire = getattr(m, "wire", None) or {}
    w = out.write
    w(f"epoch {m.epoch}\n")
    w(f"fsid {fmt_uuid(wire.get('fsid', bytes(16)))}\n")
    w(f"created {fmt_utime(wire.get('created', (0, 0)))}\n")
    w(f"modified {fmt_utime(wire.get('modified', (0, 0)))}\n")
    w(f"flags {flag_string(wire.get('flags', 0))}\n")
    w(f"crush_version {wire.get('crush_version', 1)}\n")
    w("full_ratio 0\n")
    w("backfillfull_ratio 0\n")
    w("nearfull_ratio 0\n")
    w(f"min_compat_client {min_compat_client(m)}\n")
    w("stretch_mode_enabled false\n")
    w("\n")
    for pid in sorted(m.pools):
        w(pool_line(m, pid) + "\n")
    w("\n")
    w(f"max_osd {m.max_osd}\n")
    for i in range(m.max_osd):
        if not m.exists(i):
            continue
        up = " up  " if m.is_up(i) else " down"
        inout = " in " if m.is_in(i) else " out"
        line = f"osd.{i}{up}{inout} weight {fmt_float(m.get_weightf(i))}"
        if (m.osd_primary_affinity is not None
                and m.osd_primary_affinity[i] != DEFAULT_PRIMARY_AFFINITY):
            aff = m.osd_primary_affinity[i] / DEFAULT_PRIMARY_AFFINITY
            line += f" primary_affinity {fmt_float(aff)}"
        w(line + "\n")
    w("\n")
    for pg in sorted(m.pg_upmap, key=lambda p: (p.pool, p.seed)):
        v = ",".join(str(o) for o in m.pg_upmap[pg])
        w(f"pg_upmap {pg} [{v}]\n")
    for pg in sorted(m.pg_upmap_items, key=lambda p: (p.pool, p.seed)):
        v = ",".join(str(x) for pr in m.pg_upmap_items[pg] for x in pr)
        w(f"pg_upmap_items {pg} [{v}]\n")
    for pg in sorted(m.pg_temp, key=lambda p: (p.pool, p.seed)):
        v = ",".join(str(o) for o in m.pg_temp[pg])
        w(f"pg_temp {pg} [{v}]\n")
    for pg in sorted(m.primary_temp, key=lambda p: (p.pool, p.seed)):
        w(f"primary_temp {pg} {m.primary_temp[pg]}\n")


# ------------------------------------------------------------------ tree


def _sort_key(m: OSDMap, item: int) -> str:
    """CrushTreeDumper child sort key (reference CrushTreeDumper.h:138-148):
    (device class, name) with osds zero-padded."""
    if item >= 0:
        c = m.crush.item_classes.get(item, "")
        return f"{c}_osd.{item:08d}"
    return "_" + m.crush.item_names.get(item, str(item))


def _tree_items(m: OSDMap):
    """Yield (id, parent, depth, weightf) in OSDTreePlainDumper order;
    each bucket's children visited in ascending sort-key order."""
    shadows = {
        sid for per in m.crush.class_bucket.values() for sid in per.values()
    }
    referenced = {
        it for bid, b in m.crush.buckets.items() if bid not in shadows
        for it in b.items
    }
    roots = sorted(
        (bid for bid in m.crush.buckets
         if bid not in shadows and bid not in referenced),
    )
    touched = set()

    def walk(item: int, parent: int, depth: int, weightf: float):
        touched.add(item)
        yield item, parent, depth, weightf
        b = m.crush.buckets.get(item)
        if item < 0 and b is not None:
            order = sorted(
                range(len(b.items)), key=lambda k: _sort_key(m, b.items[k])
            )
            for k in order:
                yield from walk(
                    b.items[k], item, depth + 1, b.weights[k] / 0x10000
                )

    for r in roots:
        b = m.crush.buckets[r]
        yield from walk(r, 0, 0, sum(b.weights) / 0x10000)
    # stray osds (exist in the osdmap but not the crush tree)
    for i in range(m.max_osd):
        if m.exists(i) and i not in touched:
            yield i, 0, 0, 0.0


def print_tree_plain(m: OSDMap, out) -> None:
    """osdmaptool --tree=plain (reference src/osd/OSDMap.cc:3937-4002 +
    TextTable rendering src/common/TextTable.cc)."""
    cols = ["ID", "CLASS", "WEIGHT", "TYPE NAME", "STATUS", "REWEIGHT",
            "PRI-AFF"]
    right = [True, True, True, False, True, True, True]
    rows: list[list[str]] = []
    for item, parent, depth, weightf in _tree_items(m):
        cls = m.crush.item_classes.get(item, "") if item >= 0 else ""
        indent = "    " * depth
        if item < 0:
            tname = m.crush.type_names.get(
                m.crush.buckets[item].type, "type?"
            )
            name = f"{indent}{tname} {m.crush.item_names.get(item, '?')}"
            rows.append([str(item), cls, weightf5(weightf), name])
        else:
            name = f"{indent}osd.{item}"
            if not m.exists(item):
                rows.append([str(item), cls, weightf5(weightf), name,
                             "DNE", "0"])
            else:
                st = "up" if m.is_up(item) else "down"
                aff = (
                    m.osd_primary_affinity[item] / DEFAULT_PRIMARY_AFFINITY
                    if m.osd_primary_affinity is not None else 1.0
                )
                rows.append([
                    str(item), cls, weightf5(weightf), name, st,
                    weightf5(m.get_weightf(item)), weightf5(aff),
                ])
    widths = [
        max(len(cols[j]), max((len(r[j]) for r in rows if j < len(r)),
                              default=0))
        for j in range(len(cols))
    ]

    def render(cells: list[str], align_header=False):
        parts = []
        for j in range(len(cols)):
            s = cells[j] if j < len(cells) else ""
            if align_header:
                parts.append(s.ljust(widths[j]))
            else:
                parts.append(
                    s.rjust(widths[j]) if right[j] else s.ljust(widths[j])
                )
        return "  ".join(parts)

    out.write(render(cols, align_header=True).rstrip() + "\n")
    for r in rows:
        out.write(render(r) + "\n")


def tree_json(m: OSDMap) -> dict:
    """osdmaptool --tree=json-pretty node list (reference
    OSDTreeFormattingDumper, src/osd/OSDMap.cc:4009-4076)."""
    nodes = []
    stray = []
    for item, parent, depth, weightf in _tree_items(m):
        n: dict = {"id": item}
        cls = m.crush.item_classes.get(item) if item >= 0 else None
        if cls:
            n["device_class"] = cls
        if item < 0:
            btype = m.crush.buckets[item].type
            n["name"] = m.crush.item_names.get(item, "?")
            n["type"] = m.crush.type_names.get(btype, "type?")
            n["type_id"] = btype
        else:
            n["name"] = f"osd.{item}"
            n["type"] = "osd"
            n["type_id"] = 0
            n["crush_weight"] = _js_float(weightf)
            n["depth"] = depth
        if parent < 0:
            n["pool_weights"] = {}
        if item < 0:
            b = m.crush.buckets[item]
            order = sorted(
                range(len(b.items)), key=lambda k: _sort_key(m, b.items[k])
            )
            n["children"] = [b.items[k] for k in reversed(order)]
        else:
            st = "up" if m.is_up(item) else "down"
            aff = (
                m.osd_primary_affinity[item] / DEFAULT_PRIMARY_AFFINITY
                if m.osd_primary_affinity is not None else 1.0
            )
            n["exists"] = 1 if m.exists(item) else 0
            n["status"] = st
            n["reweight"] = _js_float(m.get_weightf(item))
            n["primary_affinity"] = _js_float(aff)
        # osds outside the crush tree go to the stray section
        (stray if item >= 0 and parent == 0 else nodes).append(n)
    return {"nodes": nodes, "stray": stray}


def _js_float(v: float):
    """ceph JSONFormatter::dump_float: integral floats print as ints."""
    return int(v) if float(v) == int(v) else round(v, 6)
