"""One device-resident ClusterState: O(delta) on-device incremental apply.

The paper's thesis is that CRUSH placement is one batched XLA call over
device-resident operand tables — but historically every subsystem
rebuilt those tables from the host per map: the pipeline re-device_put
the full CRUSH pytree per PoolMapper, the balancer rebuilt its
membership rows per round, mgr eval built its own state, the lifetime
simulator paid a full rebuild every epoch (plus a host-side descent
memo), and the serving daemon deepcopied the whole map to stage each
epoch swap.  This module is the one canonical owner of the per-map
device operands, shared by all five consumers:

- **the host truth** — the mutable `OSDMap` model, still advanced by
  `osd.incremental.apply_incremental` (the monitor's epoch chain);
- **device operands** — the per-OSD exists/up/weight/primary-affinity
  vectors (one padded set for every pool) and the per-structure CRUSH
  operand tables (bucket rows, straw2 planes, choose_args weight-sets),
  each device_put once per structure;
- **result caches** — per-pool device-resident `up` rows and the raw
  descent rows of overlay-carrying PGs, tagged with version counters so
  a consumer can tell "nothing that feeds this pool's mapping changed"
  without any device work.

`apply(state, Incremental)` classifies each epoch delta:

- **value-only deltas** (reweights, osd up/down/destroy, primary
  affinity, pg_upmap / pg_temp entries, choose_args weight tweaks
  arriving as a structurally-identical crush blob) mutate operands ON
  DEVICE in O(delta): one jitted scatter over the four OSD vectors
  (`.at[idx].set`, cycle-padded index blocks — 0 compiles after warmup,
  no full-table device_put), overlay entries as host-dict updates whose
  device cost is deferred to the O(overlay) fixup, and choose_args
  tweaks as a pos_weights-plane upload into the existing table pytree.
  Proven by the `state.delta_applies` / `state.full_rebuilds` /
  `state.device_put_bytes` counters.
- **structural changes** (bucket add/remove, pg_num splits, pool
  create/delete, rule edits, max_osd growth, a first primary-affinity
  table) re-key the trace-once caches exactly as before: arrays are
  rebuilt, tables re-uploaded, and `full_rebuilds` books the event.

Overlay fixups ride **device-resident raw results**: the post-descent
raw row of an upmap-carrying PG comes from the pipeline's `raw_only`
kernel (bit-identical to `OSDMap._pg_to_raw_osds`), cached on device
and refetched (O(overlay) rows) only when a descent input changed; the
cheap host steps (upmap application, up/down filter, primary affinity)
replay on those few rows — replacing the lifetime simulator's host-side
`_raw_memo` descent cache.

The CEPH_TPU_STATE_DELTA=0 knob forces every apply down the rebuild
path — the A/B lever behind the counter-level delta-vs-rebuild tests.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu import obs
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.incremental import Incremental, apply_incremental
from ceph_tpu.osd.osdmap import (
    DEFAULT_PRIMARY_AFFINITY,
    OSD_EXISTS,
    OSD_UP,
    OSDMap,
)
from ceph_tpu.osd.types import PgId
from ceph_tpu.utils import knobs

_L = obs.logger_for("state")
_L.add_u64("delta_applies",
           "value-only Incrementals applied on device in O(delta) "
           "(jitted vector scatters + O(overlay) fixups — no re-key, "
           "no full-table device_put)")
_L.add_u64("full_rebuilds",
           "structural Incrementals (or CEPH_TPU_STATE_DELTA=0) that "
           "re-keyed the device state: CRUSH arrays rebuilt, operand "
           "tables re-device_put, mappers reconstructed")
_L.add_u64("device_put_bytes",
           "bytes uploaded host->device by state maintenance: delta "
           "applies count their O(delta) scatter operands, rebuilds "
           "count the full vector/table upload")
_L.add_u64("rows_served",
           "rows() calls answered from the version-tagged device cache "
           "(no mapping dispatch at all)")
_L.add_u64("rows_remapped",
           "rows() calls that re-dispatched the batched mapping because "
           "a mapping input changed")
_L.add_u64("raw_refreshes",
           "overlay raw-row refreshes: one fixed-shape raw-kernel "
           "dispatch + an O(overlay) fetch, replacing per-seed host "
           "descents")
_L.add_u64("value_forks",
           "value-only forks (serve staging): device tables shared, "
           "crush/pools host objects shared, O(OSDs) lists copied — no "
           "full-map deepcopy")
_L.add_quantile("apply_seconds",
                "wall time per ClusterState.apply: classification + "
                "host model advance + device delta (or rebuild)")

_DELTA_PAD = 32  # scatter index blocks cycle-pad to multiples of this:
                 # one compiled scatter shape per vector length

_SCATTER_ACCTS: dict[tuple, obs.JitAccount] = {}


def _scatter_account(dv: int):
    """The jitted 4-vector scatter, one executable per vector length,
    registered in the obs executables registry under cache "state"."""
    key = ("state", "scatter", dv)
    acct = _SCATTER_ACCTS.get(key)
    if acct is None:
        import jax

        def _upd(vec, idx, exists, up, weight, aff):
            return {
                "exists": vec["exists"].at[idx].set(exists, mode="drop"),
                "up": vec["up"].at[idx].set(up, mode="drop"),
                "weight": vec["weight"].at[idx].set(weight, mode="drop"),
                "primary_affinity":
                    vec["primary_affinity"].at[idx].set(aff, mode="drop"),
            }

        jfn = jax.jit(_upd)
        rec = obs.executables.register("state", "scatter", key, fn=jfn)
        acct = _SCATTER_ACCTS[key] = obs.JitAccount(
            jfn, _L, "scatter", exec_record=rec)
    return acct


# ----------------------------------------------------------- classification


def _crush_value_delta(old, new):
    """If `new` differs from `old` ONLY in choose_args weight-set
    VALUES (same buckets, rules, tunables, ids, shapes), return True —
    the delta is a pos_weights-plane upload, not a re-key.  Any other
    difference returns False (structural)."""
    from ceph_tpu.crush.soa import build_arrays

    try:
        a = build_arrays(old, None)
        b = build_arrays(new, None)
    except Exception:
        return False
    if a.tunables != b.tunables or a.rules != b.rules:
        return False
    for f in ("alg", "btype", "size", "bucket_weight", "items",
              "weights", "sum_weights", "straws", "node_weights",
              "num_nodes", "arg_ids"):
        if not np.array_equal(getattr(a, f), getattr(b, f)):
            return False
    if sorted(old.choose_args) != sorted(new.choose_args):
        return False
    for key, ca_new in new.choose_args.items():
        ca_old = old.choose_args[key]
        if sorted(ca_old.ids) != sorted(ca_new.ids) or any(
                list(ca_old.ids[k]) != list(ca_new.ids[k])
                for k in ca_new.ids):
            return False
        if sorted(ca_old.weight_sets) != sorted(ca_new.weight_sets):
            return False
        for bid, rows in ca_new.weight_sets.items():
            rows_old = ca_old.weight_sets[bid]
            if len(rows) != len(rows_old) or any(
                    len(r) != len(ro) for r, ro in zip(rows, rows_old)):
                return False
    return True


def classify_incremental(inc: Incremental, m: OSDMap):
    """Classify one epoch delta against the CURRENT map (pre-apply).

    Returns ("delta", info) for value-only incrementals — info carries
    the changed OSD id set, whether descent inputs changed (`raw`),
    whether the choose_args planes changed (`pos_weights`), and the
    pools whose upmap overlay entries changed — or ("rebuild", None)
    for structural changes that must re-key the trace-once caches."""
    if inc.fullmap or inc.new_max_osd >= 0:
        return "rebuild", None
    if any(pid in m.pools for pid in inc.new_pools):
        # mutating an EXISTING pool (pg_num split, size change) re-keys
        # that pool's compiled shapes; a brand-new pool is value-only —
        # no device operand changes, its caches build lazily on first use
        return "rebuild", None
    pos_weights = False
    if inc.crush:
        from ceph_tpu.crush.codec import decode_crushmap

        try:
            new_crush = decode_crushmap(inc.crush)
        except Exception:
            return "rebuild", None
        if not _crush_value_delta(m.crush, new_crush):
            return "rebuild", None
        pos_weights = True
    # a first new_primary_affinity (or a destroy resetting affinity) is
    # VALUE-ONLY: state-shared mappers bake the affinity stage on from
    # the start, so the new table is just an operand update
    osds = (set(inc.new_state) | set(inc.new_weight)
            | set(inc.new_primary_affinity) | set(inc.new_up_client))
    if any(o < 0 or o >= m.max_osd for o in osds):
        return "rebuild", None
    raw = bool(inc.new_weight) or bool(inc.new_up_client) or pos_weights
    for osd, s in inc.new_state.items():
        s = s or OSD_UP
        if s & OSD_EXISTS:
            # the EXISTS bit flips in EITHER direction (destroy clears
            # it, the XOR of a revival sets it) — the descent's
            # nonexistent-removal input changed, raw caches are stale
            raw = True
    pools = {pg.pool for src in (inc.new_pg_upmap, inc.old_pg_upmap,
                                 inc.new_pg_upmap_items,
                                 inc.old_pg_upmap_items) for pg in src}
    return "delta", {
        "osds": osds,
        "vec": bool(osds) or pos_weights,
        "raw": raw,
        "pos_weights": pos_weights,
        "upmap_pools": pools,
        "dropped_pools": set(inc.old_pools),
    }


def value_copy_map(m: OSDMap) -> OSDMap:
    """O(OSDs + entries) copy of a map that a VALUE-ONLY Incremental
    chain may then mutate: the crush tree and PgPool objects are shared
    (value deltas replace, never mutate, them), the per-OSD lists and
    overlay dicts are copied.  The serve swap path stages value epochs
    on this instead of a full-map deepcopy."""
    new = OSDMap.__new__(OSDMap)
    new.epoch = m.epoch
    new.crush = m.crush
    new.max_osd = m.max_osd
    new.osd_state = list(m.osd_state)
    new.osd_weight = list(m.osd_weight)
    new.osd_primary_affinity = (
        None if m.osd_primary_affinity is None
        else list(m.osd_primary_affinity))
    new.pools = dict(m.pools)
    new.pool_name = dict(m.pool_name)
    new.pool_max = m.pool_max
    new.pg_temp = dict(m.pg_temp)
    new.primary_temp = dict(m.primary_temp)
    new.pg_upmap = dict(m.pg_upmap)
    new.pg_upmap_items = dict(m.pg_upmap_items)
    new.erasure_code_profiles = {
        k: dict(v) for k, v in m.erasure_code_profiles.items()}
    wire = getattr(m, "wire", None)
    if wire is not None:
        new.wire = dict(wire)
    return new


# ------------------------------------------------------------- ClusterState


class ClusterState:
    """The canonical device-resident cluster state (module docstring).

    Consumers:
    - `mapper(pid)` — a PoolMapper sharing this state's arrays, tables
      and vectors (pipeline `_PIPE_CACHE` operands);
    - `rows(pid)` — device-resident overlay-corrected `up` rows with a
      version tag (balancer membership, mgr eval, sim accounting);
    - `apply(inc)` — advance the host model AND the device operands;
    - `fork(inc)` — a new state for a value-only epoch sharing every
      immutable device table (serve double-buffered staging).
    """

    def __init__(self, m: OSDMap, chunk: int | None = None, mesh=None):
        from ceph_tpu.utils import ensure_jax_backend

        ensure_jax_backend()
        self.m = m
        self.chunk = chunk
        # PG-axis device mesh: None = resolve from the
        # CEPH_TPU_MESH_DEVICES knob (parallel.sharded.default_mesh) —
        # ONE env var shards every consumer of this state (mapper rows,
        # balancer membership, mgr eval, lifetime accounting, serve
        # staging); per-OSD vectors and CRUSH tables replicate across it
        if mesh is None:
            from ceph_tpu.parallel.sharded import default_mesh

            mesh = default_mesh()
        self.mesh = mesh
        self.delta_enabled = knobs.get("CEPH_TPU_STATE_DELTA", "1") != "0"
        self._vec_ver = 0
        self._raw_ver = 0
        self._overlay_ver: dict[int, int] = {}
        self._pending_rebuild = False
        self.full_rebuilds = 0  # instance-level (the perf group is
        self.delta_applies = 0  # process-global; per-run gates need these)
        self._build(initial=True)

    # -- build / rebuild ---------------------------------------------------

    def _build(self, initial: bool = False) -> None:
        with obs.span("state.rebuild", epoch=self.m.epoch,
                      initial=initial):
            _L.inc("full_rebuilds")
            self.full_rebuilds += 1
            self._arrays: dict = {}       # ca_key -> CrushArrays
            self._tables: dict = {}       # (ca_key, fast key) -> dev tables
            self._mappers: dict = {}      # pid -> PoolMapper
            self._base: dict = {}         # pid -> (vec_ver, rows, skey)
            self._rows: dict = {}         # pid -> (tag, rows, skey)
            self._fix: dict = {}          # pid -> (fix_tag, {seed: row})
            self._raw: dict = {}          # pid -> (key, np rows)
            self._oracle: dict = {}       # (pid, seed) -> (raw_ver,
            #                               host raw list, pps)
            self._warmed: set = set()
            self._vec_ver += 1
            self._raw_ver += 1
            for pid in list(self._overlay_ver):
                self._overlay_ver[pid] += 1
            self._upload_vectors()
            self._pending_rebuild = False
            # warm the O(delta) scatter (no-op lanes) so the first
            # value apply after a re-key never books a steady compile
            import jax.numpy as jnp

            _scatter_account(self.DV)(
                self.vectors,
                jnp.full(_DELTA_PAD, self.DV, jnp.int32),
                jnp.zeros(_DELTA_PAD, bool), jnp.zeros(_DELTA_PAD, bool),
                jnp.zeros(_DELTA_PAD, jnp.uint32),
                jnp.full(_DELTA_PAD, DEFAULT_PRIMARY_AFFINITY,
                         jnp.uint32))

    def _ca_key(self, pid: int):
        ca = self.m.crush.choose_args
        if pid in ca:
            return pid
        return -1 if -1 in ca else None

    def arrays_for(self, pid: int):
        """The frozen CrushArrays for this pool's choose_args group —
        built once per group per structure."""
        from ceph_tpu.crush.soa import build_arrays

        key = self._ca_key(pid)
        A = self._arrays.get(key)
        if A is None:
            A = self._arrays[key] = build_arrays(
                self.m.crush, self.m.crush.choose_args.get(key),
                pad_devices=self.DV, quantize=True)
        return A

    def _put_replicated(self, x):
        """jnp.asarray, committed replicated across the mesh when one
        is configured (operands must live on every mesh device so a
        sharded dispatch moves zero host->device bytes)."""
        import jax
        import jax.numpy as jnp

        if self.mesh is None:
            return jnp.asarray(x)
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(x, NamedSharding(self.mesh, P()))

    def device_tables_for(self, ca_key, fast_fn) -> dict:
        """device_put one structure's operand tables once; keyed by the
        (choose_args group, CRUSH-rule structure) pair — the tables are
        rule-level data, so overlay-gate variants of one pool (serve's
        overlay-carrying mappers vs the overlay-free row mappers) share
        one upload.  With a mesh the pytree replicates across it."""
        key = (ca_key, fast_fn.cache_key[-1])
        tabs = self._tables.get(key)
        if tabs is None:
            import jax

            from ceph_tpu.crush.mapper_jax import device_tables

            host = fast_fn.host_tables
            tabs = device_tables(host)
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                tabs = jax.device_put(
                    tabs, NamedSharding(self.mesh, P()))
            self._tables[key] = tabs
            _L.inc("device_put_bytes", _tables_nbytes(host))
        return tabs

    @property
    def DV(self) -> int:
        """Quantized device-vector bound: the per-OSD vectors (and the
        kernels' weight operand) pad to the next power of two (floor
        32), so cluster expansion INSIDE the quantum keeps every
        compiled shape — max_osd rides as a kernel operand — and only
        growth past the quantum re-keys."""
        n = max(self.m.crush.max_devices, self.m.max_osd, 1)
        return 1 << max(int(n - 1).bit_length(), 5)

    def _upload_vectors(self) -> None:
        dv = self.m.frozen_vectors()
        DV = self.DV
        import jax.numpy as jnp

        def pad(v, fill):
            v = np.asarray(v)
            if v.shape[0] < DV:
                v = np.concatenate(
                    [v, np.full(DV - v.shape[0], fill, v.dtype)])
            _L.inc("device_put_bytes", int(v.nbytes))
            return self._put_replicated(v[:DV])

        self.vectors = {
            "exists": pad(dv["exists"], False),
            "up": pad(dv["up"], False),
            "weight": pad(dv["weight"], 0),
            "primary_affinity": pad(
                dv["primary_affinity"], DEFAULT_PRIMARY_AFFINITY),
        }

    # -- mappers -----------------------------------------------------------

    def mapper(self, pid: int):
        """The shared overlay-free PoolMapper for one pool (overlay
        corrections ride `rows()`; the compiled executables come from
        `_PIPE_CACHE` as always)."""
        from ceph_tpu.osd.pipeline_jax import PoolMapper

        if self._pending_rebuild:
            self._build()
        pm = self._mappers.get(pid)
        if pm is None:
            pm = PoolMapper(self.m, pid, overlays=False,
                            chunk=self.chunk, state=self)
            self._mappers[pid] = pm
        return pm

    def _warm_rescue(self, pm) -> None:
        """Precompile EVERY rescue tier of the loop kernel for this
        structure so a later steady epoch's first flagged lane (at any
        tier) cannot book a compile."""
        import jax.numpy as jnp

        from ceph_tpu.crush.mapper_jax import RESCUE_PADS

        wk = (pm.cache_key, self.DV)
        if wk not in self._warmed:
            for p in RESCUE_PADS:
                pm.jitted_loop()(jnp.zeros(p, jnp.uint32), pm.dev, {})
            self._warmed.add(wk)

    # -- rows --------------------------------------------------------------

    def rows_tag(self, pid: int):
        """Version tag of one pool's `up` rows: equal tags guarantee
        bit-identical rows (nothing feeding this pool's mapping
        changed).  Overlay-free pools exclude the raw version, so a
        reweight-free epoch's upmap churn elsewhere never invalidates
        them."""
        if self._overlay_seeds(pid):
            return (self._vec_ver, self._raw_ver,
                    self._overlay_ver.get(pid, 0))
        return (self._vec_ver, None, self._overlay_ver.get(pid, 0))

    def _overlay_seeds(self, pid: int) -> tuple:
        m = self.m
        n = m.pools[pid].pg_num
        return tuple(sorted({
            pg.seed for pg in list(m.pg_upmap) + list(m.pg_upmap_items)
            if pg.pool == pid and pg.seed < n
        }))

    def rows(self, pid: int):
        """Device-resident `up` rows [pg_num, W] for one pool, overlay
        PGs corrected — plus the structure key and version tag.
        Version-cached: a call whose tag is unchanged does NO device
        work."""
        if self._pending_rebuild:
            self._build()
        tag = self.rows_tag(pid)
        ent = self._rows.get(pid)
        if ent is not None and ent[0] == tag:
            _L.inc("rows_served")
            return ent[1], ent[2], tag
        import jax.numpy as jnp

        with obs.span("state.rows", pool=pid):
            pm = self.mapper(pid)
            pm.refresh_dev()
            self._warm_rescue(pm)
            base_ent = self._base.get(pid)
            if base_ent is not None and base_ent[0] == self._vec_ver:
                rows, skey = base_ent[1], base_ent[2]
            else:
                rows = pm.map_all_device(self.chunk)
                skey = (pm.cache_key, int(rows.shape[0]),
                        int(rows.shape[1]), self.DV)
                self._base[pid] = (self._vec_ver, rows, skey)
            fix = self._fixups(pid, pm, int(rows.shape[1]))
            if fix:
                from ceph_tpu.crush.mapper_jax import rescue_pad_for

                seeds = np.fromiter(sorted(fix), np.int64, len(fix))
                stacked = np.stack([fix[int(s)] for s in seeds])
                # fixed-shape scatter blocks (cycle-padded: duplicated
                # lanes write identical rows) — the overlay count can
                # grow every balance epoch without ever retracing
                P = rescue_pad_for(len(seeds))
                for i in range(0, len(seeds), P):
                    sd = np.resize(seeds[i:i + P], P)
                    vl = np.resize(stacked[i:i + P],
                                   (P,) + stacked.shape[1:])
                    rows = rows.at[jnp.asarray(sd)].set(jnp.asarray(vl))
                rows = pm.shard_rows(rows)
            self._rows[pid] = (tag, rows, skey)
        _L.inc("rows_remapped")
        return rows, skey, tag

    def _fixups(self, pid: int, pm, width: int) -> dict:
        """{seed: host-exact up row} for this pool's upmap-carrying PGs
        — device raw rows + the cheap host overlay/filter/affinity
        steps, cached until a feeding version changes."""
        seeds = self._overlay_seeds(pid)
        if not seeds:
            return {}
        ftag = (self._vec_ver, self._raw_ver,
                self._overlay_ver.get(pid, 0))
        ent = self._fix.get(pid)
        if ent is not None and ent[0] == ftag:
            return ent[1]
        raw = self._raw_rows(pid, pm, seeds)
        fix = {
            int(s): self._up_from_raw(pid, int(s), raw[i], width)
            for i, s in enumerate(seeds)
        }
        self._fix[pid] = (ftag, fix)
        return fix

    def _raw_rows(self, pid: int, pm, seeds: tuple) -> np.ndarray:
        """Device-resident raw descent rows for the overlay seeds —
        refetched only when a descent input changed (the O(delta)
        replacement for host descent memos)."""
        key = (self._raw_ver, seeds)
        ent = self._raw.get(pid)
        if ent is not None and ent[0] == key:
            return ent[1]
        with obs.span("state.raw_fixup", pool=pid, seeds=len(seeds)):
            self._warm_rescue(pm)
            rows = pm.raw_rows(np.asarray(seeds, np.int64))
        self._raw[pid] = (key, rows)
        _L.inc("raw_refreshes")
        return rows

    def _up_from_raw(self, pid: int, seed: int, raw_row, width: int):
        """The host tail of the placement pipeline on one device raw
        row: _apply_upmap → _raw_to_up_osds → _pick_primary →
        _apply_primary_affinity (reference OSDMap.cc:2667-2715) — bit
        identical to `pipeline_jax.overlay_fixup_rows`."""
        m = self.m
        pool = m.pools[pid]
        pg = PgId(pid, seed)
        if pool.can_shift_osds():
            raw = [int(o) for o in raw_row if o != ITEM_NONE]
        else:
            raw = [int(o) for o in raw_row[:pool.size]]
        pps = pool.raw_pg_to_pps(pg)
        m._apply_upmap(pool, pg, raw)
        up = m._raw_to_up_osds(pool, raw)
        up_primary = m._pick_primary(up)
        m._apply_primary_affinity(pps, pool, up, up_primary)
        row = np.full(width, ITEM_NONE, np.int32)
        row[: min(len(up), width)] = up[:width]
        return row

    def host_up(self, pid: int, seed: int) -> list[int]:
        """One PG's host-exact `up` set — the invariant-oracle surface.
        Overlay-carrying seeds answer from the device-resident fixup
        rows; everything else replays a HOST-pure descent, memoized by
        the raw version counter (a chronically-unmapped PG is
        re-descended once per descent-input change, not once per epoch
        — the exact job the old event-heuristic `_raw_memo` did, now
        version-exact).  The periodic spot-check lanes bypass this
        entirely: they stay an independent host witness."""
        fix = self._fix.get(pid)
        seeds = self._overlay_seeds(pid)
        if seed in seeds and fix is not None and fix[0] == (
                self._vec_ver, self._raw_ver,
                self._overlay_ver.get(pid, 0)):
            row = fix[1].get(seed)
            if row is not None:
                return [int(o) for o in row if o != ITEM_NONE]
        m = self.m
        pool = m.pools[pid]
        pg = PgId(pid, int(seed))
        ent = self._oracle.get((pid, seed))
        if ent is not None and ent[0] == self._raw_ver:
            raw, pps = list(ent[1]), ent[2]
        else:
            raw, pps = m._pg_to_raw_osds(pool, pg)
            if len(self._oracle) >= 4096:  # bounded memo
                self._oracle.clear()
            self._oracle[(pid, seed)] = (self._raw_ver, list(raw), pps)
        m._apply_upmap(pool, pg, raw)
        up = m._raw_to_up_osds(pool, raw)
        up_primary = m._pick_primary(up)
        m._apply_primary_affinity(pps, pool, up, up_primary)
        return up

    # -- apply -------------------------------------------------------------

    def apply(self, inc: Incremental) -> str:
        """Advance the host model AND the device operands by one epoch
        delta.  Returns "delta" (value-only, O(delta) device work) or
        "rebuild" (structural re-key).  A device loss during the device
        portion leaves the host model advanced and defers the re-key to
        the next rows()/mapper() access ("deferred") — the caller's
        mapping dispatch then degrades exactly as a mid-map loss
        would."""
        from ceph_tpu.runtime import faults

        with obs.span("state.apply", epoch=inc.epoch), \
                _L.time("apply_seconds"):
            kind, info = classify_incremental(inc, self.m)
            m2 = apply_incremental(self.m, inc)
            if m2 is not self.m:
                self.m = m2  # fullmap decode: a fresh map object
                kind = "rebuild"
            try:
                if kind == "rebuild":
                    self._build()
                    return "rebuild"
                if not self.delta_enabled or self._pending_rebuild:
                    # a rebuild the INCREMENTAL did not warrant (A/B
                    # knob, or recovery from a lost device): callers'
                    # steady-epoch accounting must still see it
                    self._build()
                    return "forced_rebuild"
                if self._apply_delta(info):
                    # the defensive pos_weights shape-drift fallback
                    # rebuilt after all — book it as what it was
                    return "rebuild"
            except Exception as e:
                if not faults.looks_like_device_loss(e):
                    raise
                self._pending_rebuild = True
                return "deferred"
            _L.inc("delta_applies")
            self.delta_applies += 1
            return "delta"

    def _apply_delta(self, info: dict) -> bool:
        """Returns True when the defensive pos_weights fallback rebuilt
        the whole state instead (the caller then reports "rebuild")."""
        if info["osds"]:
            self._scatter_vectors(sorted(info["osds"]))
        if info["pos_weights"]:
            if self._update_pos_weights():
                return True
        if info["vec"]:
            self._vec_ver += 1
        if info["raw"]:
            self._raw_ver += 1
        for pid in info["upmap_pools"]:
            self._overlay_ver[pid] = self._overlay_ver.get(pid, 0) + 1
        for pid in info.get("dropped_pools", ()):
            for cache in (self._mappers, self._base, self._rows,
                          self._fix, self._raw):
                cache.pop(pid, None)
        return False

    def _scatter_vectors(self, idx: list) -> None:
        """O(delta) on-device update of the four per-OSD vectors."""
        import jax.numpy as jnp

        m = self.m
        DV = self.DV
        if len(idx) > _DELTA_PAD or len(idx) * 2 >= DV:
            # a wide delta: one O(OSDs) vector re-upload moves fewer
            # bytes than scatter operands would, and keeps the scatter
            # at exactly ONE compiled shape per vector length
            self._upload_vectors()
            return
        pad = _DELTA_PAD
        ix = np.full(pad, DV, np.int32)  # out-of-range: dropped lanes
        ex = np.zeros(pad, bool)
        up = np.zeros(pad, bool)
        wt = np.zeros(pad, np.uint32)
        af = np.full(pad, DEFAULT_PRIMARY_AFFINITY, np.uint32)
        aff = m.osd_primary_affinity
        for i, o in enumerate(idx):
            st = m.osd_state[o]
            ix[i] = o
            ex[i] = bool(st & OSD_EXISTS)
            up[i] = bool(st & OSD_EXISTS) and bool(st & OSD_UP)
            wt[i] = m.osd_weight[o]
            af[i] = (aff[o] if aff is not None
                     else DEFAULT_PRIMARY_AFFINITY)
        _L.inc("device_put_bytes",
               int(ix.nbytes + ex.nbytes + up.nbytes + wt.nbytes
                   + af.nbytes))
        self.vectors = _scatter_account(DV)(
            self.vectors, jnp.asarray(ix), jnp.asarray(ex),
            jnp.asarray(up), jnp.asarray(wt), jnp.asarray(af))

    def _update_pos_weights(self) -> bool:
        """choose_args weight tweaks: refresh the pos_weights planes of
        every cached table pytree in place (same shapes, same traces —
        the kernels read the table dict per dispatch).  Returns True
        when shape drift forced a full rebuild instead (the caller then
        reports "rebuild", not "delta")."""
        from ceph_tpu.crush.soa import build_arrays

        import jax.numpy as jnp

        for ca_key in list(self._arrays):
            # same quantized padding as arrays_for: the refreshed
            # planes must keep the cached shapes exactly
            A2 = build_arrays(
                self.m.crush, self.m.crush.choose_args.get(ca_key),
                pad_devices=self.DV, quantize=True)
            old = self._arrays[ca_key]
            if (A2.pos_weights.shape != old.pos_weights.shape
                    or not np.array_equal(A2.arg_ids, old.arg_ids)):
                # shape drift should have classified structural; be safe
                self._build()
                return True
            self._arrays[ca_key] = A2
        for (ca_key, _), tabs in self._tables.items():
            A2 = self._arrays.get(ca_key)
            if A2 is not None and "pos_weights" in tabs:
                _L.inc("device_put_bytes", int(A2.pos_weights.nbytes))
                tabs["pos_weights"] = self._put_replicated(A2.pos_weights)
        for pm in self._mappers.values():
            pm.arrays = self._arrays.get(self._ca_key(pm.pool_id),
                                         pm.arrays)
        return False

    def rows_source_for(self, m2: OSDMap):
        """A per-pool device-rows provider valid for `m2` — the
        balancer/mgr surface.  `m2` is typically a working deepcopy of
        this state's map at the same epoch (a `Plan.osdmap`); the
        provider answers a pool only while that pool's mapping inputs
        still match (upmap churn the optimizer applied to OTHER pools
        doesn't invalidate it).  Returns None when the maps diverge
        wholesale (different epoch / vectors) — callers then build
        their own state exactly as before."""
        if m2 is not self.m and not (
                m2.epoch == self.m.epoch
                and m2.max_osd == self.m.max_osd
                and m2.osd_weight == self.m.osd_weight
                and m2.osd_state == self.m.osd_state
                and m2.osd_primary_affinity
                == self.m.osd_primary_affinity):
            return None

        def _entries(m, pid):
            return (
                {pg: tuple(v) for pg, v in m.pg_upmap.items()
                 if pg.pool == pid},
                {pg: tuple(v) for pg, v in m.pg_upmap_items.items()
                 if pg.pool == pid},
            )

        def src(pid: int):
            if pid not in self.m.pools or pid not in m2.pools:
                return None
            if (m2.pools[pid].pg_num != self.m.pools[pid].pg_num
                    or m2.pools[pid].size != self.m.pools[pid].size):
                return None
            if m2 is not self.m and \
                    _entries(m2, pid) != _entries(self.m, pid):
                return None
            rows, _, _ = self.rows(pid)
            return rows

        return src

    # -- forking (serve staging) ------------------------------------------

    def state_tag(self) -> tuple:
        """Aggregate version tag: equal tags guarantee no mapping-
        relevant input changed ANYWHERE (vectors, descent inputs, any
        pool's overlays).  The public surface for callers memoizing
        whole-map derived checks (the lifetime invariant gates)."""
        return (self._vec_ver, self._raw_ver,
                sum(self._overlay_ver.values()))

    def fork(self, inc: Incremental,
             _classified: tuple | None = None) -> "ClusterState":
        """A new ClusterState one VALUE-ONLY epoch ahead, sharing every
        immutable device table with this one (this state is not
        mutated; readers keep draining on it).  Raises ValueError on a
        structural incremental — the caller stages those from scratch.
        `_classified`: a (kind, info) pair from classify_incremental the
        caller already computed — skips re-classifying (the crush
        value-delta check freezes the whole map twice per run)."""
        kind, info = _classified or classify_incremental(inc, self.m)
        if kind != "delta":
            raise ValueError("fork() takes value-only incrementals; "
                             "stage structural epochs via a fresh "
                             "ClusterState")
        new = ClusterState.__new__(ClusterState)
        new.chunk = self.chunk
        new.mesh = self.mesh
        new.delta_enabled = self.delta_enabled
        new._pending_rebuild = False
        new.full_rebuilds = 0
        new.delta_applies = 0
        new.m = value_copy_map(self.m)
        apply_incremental(new.m, inc)
        new._arrays = dict(self._arrays)
        new._tables = {k: dict(v) for k, v in self._tables.items()}
        new._mappers = {}
        new._base = {}
        new._rows = {}
        new._fix = {}
        new._raw = {}
        new._warmed = set(self._warmed)
        new._vec_ver = self._vec_ver
        new._raw_ver = self._raw_ver
        new._overlay_ver = dict(self._overlay_ver)
        new.vectors = self.vectors
        new._apply_delta(info)
        _L.inc("delta_applies")
        new.delta_applies += 1
        _L.inc("value_forks")
        return new

    # -- introspection -----------------------------------------------------

    def counters(self) -> dict:
        """The process-global `state` perf group (convenience for bench
        stage deltas)."""
        return dict(obs.perf_dump().get("state") or {})


def _tables_nbytes(host_tables: dict) -> int:
    total = 0
    for k, v in host_tables.items():
        if k == "rowlvl":
            for tab in v.values():
                total += sum(int(a.nbytes) for a in tab.values())
        else:
            total += int(np.asarray(v).nbytes)
    return total


__all__ = [
    "ClusterState",
    "Incremental",
    "classify_incremental",
    "value_copy_map",
]
