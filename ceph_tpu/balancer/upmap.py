"""Upmap balancer — calc_pg_upmaps with TPU-batched cluster mapping.

Semantics port of the reference's greedy optimizer
(`OSDMap::calc_pg_upmaps`, reference src/osd/OSDMap.cc:4634-5208, with
`try_pg_upmap` :4590 and `CrushWrapper::try_remap_rule` /
`_choose_type_stack` at reference src/crush/CrushWrapper.cc:4061/3845).

The structure is the reference's: a host-side greedy loop that drops or adds
`pg_upmap_items` pairs one tiny change at a time, accepting only changes
that lower the PG-count deviation stddev.  The expensive part — mapping
every PG of every pool to build `pgs_by_osd` — runs as the batched JAX
pipeline (one XLA call per pool) instead of the reference's per-PG
`pg_to_up_acting_osds` loop; everything after that is incremental set
bookkeeping, so the TPU does the O(PGs) work and the host does the O(changes)
work.
"""

from __future__ import annotations

import time

from dataclasses import dataclass, field

import numpy as np

from ceph_tpu import obs
from ceph_tpu.balancer.crush_analysis import (
    get_parent_of_type,
    get_rule_weight_osd_map,
    subtree_contains,
)
from ceph_tpu.crush import mapper_ref
from ceph_tpu.crush.types import ITEM_NONE, RuleOp
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.types import PgId


# -- try_remap_rule ---------------------------------------------------------

def _choose_type_stack(
    m,
    stack: list[tuple[int, int]],
    overfull: set[int],
    underfull: list[int],
    more_underfull: list[int],
    orig: list[int],
    ipos: list[int],
    used: set[int],
    w: list[int],
    root_bucket: int,
    ruleno: int,
) -> list[int]:
    """reference CrushWrapper.cc:3845-4058; ipos is the shared orig cursor
    (a 1-list so the caller sees advancement)."""
    crush = m.crush
    cumulative_fanout = [0] * len(stack)
    f = 1
    for j in range(len(stack) - 1, -1, -1):
        cumulative_fanout[j] = f
        f *= stack[j][1]

    # per-level buckets that contain >=1 underfull device
    underfull_buckets: list[set[int]] = [set() for _ in range(len(stack) - 1)]
    for osd in underfull:
        item = osd
        for j in range(len(stack) - 2, -1, -1):
            type_ = stack[j][0]
            item = get_parent_of_type(crush, item, type_, ruleno)
            if not subtree_contains(crush, root_bucket, item):
                continue
            underfull_buckets[j].add(item)

    for j in range(len(stack)):
        type_, fanout = stack[j]
        cum_fanout = cumulative_fanout[j]
        o: list[int] = []
        tmpi = ipos[0]
        if ipos[0] >= len(orig):
            break
        for from_ in w:
            leaves: list[set[int]] = [set() for _ in range(fanout)]
            for pos in range(fanout):
                if type_ > 0:
                    if tmpi >= len(orig):
                        # reference "end of orig, break 1"
                        # (CrushWrapper.cc:3906): a degraded mapping is
                        # shorter than the rule's fanout product
                        break
                    item = get_parent_of_type(
                        crush, orig[tmpi], type_, ruleno
                    )
                    o.append(item)
                    n = cum_fanout
                    while n > 0 and tmpi < len(orig):
                        leaves[pos].add(orig[tmpi])
                        tmpi += 1
                        n -= 1
                else:
                    replaced = False
                    if orig[ipos[0]] in overfull:
                        for cand_list in (underfull, more_underfull):
                            for item in cand_list:
                                if item in used:
                                    continue
                                if not subtree_contains(crush, from_, item):
                                    continue
                                if item in orig:
                                    continue
                                o.append(item)
                                used.add(item)
                                replaced = True
                                ipos[0] += 1
                                break
                            if replaced:
                                break
                    if not replaced:
                        o.append(orig[ipos[0]])
                        ipos[0] += 1
                    if ipos[0] >= len(orig):
                        break
            if j + 1 < len(stack):
                # swap buckets with overfull leaves but no underfull
                # candidates for peers that do have some
                for pos in range(fanout):
                    if pos >= len(o):
                        break
                    if o[pos] in underfull_buckets[j]:
                        continue
                    if not any(osd in overfull for osd in leaves[pos]):
                        continue
                    for alt in sorted(underfull_buckets[j]):
                        if alt in o:
                            continue
                        if j == 0 or get_parent_of_type(
                            crush, o[pos], stack[j - 1][0], ruleno
                        ) == get_parent_of_type(
                            crush, alt, stack[j - 1][0], ruleno
                        ):
                            o[pos] = alt
                            break
            if ipos[0] >= len(orig):
                break
        w = o
    return w


def try_remap_rule(
    m: OSDMap,
    ruleno: int,
    maxout: int,
    overfull: set[int],
    underfull: list[int],
    more_underfull: list[int],
    orig: list[int],
) -> list[int] | None:
    """reference CrushWrapper.cc:4061-4156."""
    crush = m.crush
    rule = crush.rules[ruleno]
    w: list[int] = []
    out: list[int] = []
    ipos = [0]
    used: set[int] = set()
    type_stack: list[tuple[int, int]] = []
    root_bucket = 0
    for op, a1, a2 in rule.steps:
        if op == RuleOp.TAKE:
            w = [a1]
            root_bucket = a1
        elif op in (RuleOp.CHOOSELEAF_FIRSTN, RuleOp.CHOOSELEAF_INDEP):
            numrep, type_ = a1, a2
            if numrep <= 0:
                numrep += maxout
            type_stack.append((type_, numrep))
            if type_ > 0:
                type_stack.append((0, 1))
            w = _choose_type_stack(
                m, type_stack, overfull, underfull, more_underfull,
                orig, ipos, used, w, root_bucket, ruleno,
            )
            type_stack = []
        elif op in (RuleOp.CHOOSE_FIRSTN, RuleOp.CHOOSE_INDEP):
            numrep, type_ = a1, a2
            if numrep <= 0:
                numrep += maxout
            type_stack.append((type_, numrep))
        elif op == RuleOp.EMIT:
            if type_stack:
                w = _choose_type_stack(
                    m, type_stack, overfull, underfull, more_underfull,
                    orig, ipos, used, w, root_bucket, ruleno,
                )
                type_stack = []
            out.extend(w)
            w = []
    return out


def try_pg_upmap(
    m: OSDMap,
    pg: PgId,
    overfull: set[int],
    underfull: list[int],
    more_underfull: list[int],
    orig: list[int],
) -> list[int] | None:
    """reference OSDMap.cc:4590-4632."""
    pool = m.get_pg_pool(pg.pool)
    if pool is None:
        return None
    ruleno = mapper_ref.find_rule(
        m.crush, pool.crush_rule, int(pool.type), pool.size
    )
    if ruleno < 0:
        return None
    if not any(osd in overfull for osd in orig):
        return None
    out = try_remap_rule(
        m, ruleno, pool.size, overfull, underfull, more_underfull, orig
    )
    if out is None or out == orig:
        return None
    return out


# -- calc_pg_upmaps ---------------------------------------------------------

_L = obs.logger_for("balancer")
_L.add_u64("rounds", "greedy optimizer outer iterations")
_L.add_u64("changes_accepted", "upmap-item changes committed")
_L.add_u64("changes_rejected", "upmap-item changes rolled back (stddev up)")
_L.add_avg("stddev", "PG-count deviation stddev after each accepted change")
_L.add_avg("max_deviation", "max abs deviation after each accepted change")
_L.add_time_avg("round_seconds", "wall time per optimizer round")
_L.add_quantile("round_hist",
                "optimizer round wall-time distribution (p50/p99)")
_L.add_time_avg("build_state_seconds",
                "O(PGs) membership-state build time (booked ONLY when "
                "the build actually re-mapped pools — builds served "
                "from ClusterState rows book state_rows_reused "
                "instead)")
_L.add_u64("state_rows_reused",
           "membership builds served from the shared ClusterState's "
           "version-tagged device rows (no O(PGs) mapping pass)")
# the candidate-batched optimizer (calc_pg_upmaps candidate_batch>0):
# the sequential path books one accepted/rejected evaluation round-trip
# per prospective change; the batched path scores a whole batch per
# dispatch, so candidate_batches / changes_accepted is the
# dispatches-per-accepted-change ratio the bench records
_L.add_u64("candidate_batches",
           "candidate-scoring batch evaluations (one vectorized "
           "deviation-delta kernel per batch of prospective changes)")
_L.add_u64("candidates_scored",
           "prospective pg_upmap changes scored in candidate batches")
_L.add_u64("candidate_conflicts",
           "scored candidates skipped by the non-conflicting-subset "
           "rule (an accepted candidate already touched one of their "
           "OSDs or PGs)")
# the fully device-resident optimizer (upmap_state_backend
# "device_loop"): the whole multi-round greedy — candidate generation
# from the deviation vector, OSD-disjoint selection, the convergence
# loop with the float-tie guard — runs inside ONE lax.while_loop
# kernel, so plan_dispatches / changes_accepted is the
# dispatches-per-accepted-change ratio (1/plan vs 1/batch vs 1/change)
_L.add_u64("plan_dispatches",
           "whole-plan device-loop kernel dispatches (one per "
           "calc_pg_upmaps call on the device_loop backend — every "
           "round of the plan rides the same dispatch)")
_L.add_u64("plan_readback_reverts",
           "device-accepted moves rolled back at readback because the "
           "exact host pg_upmap_items overlay application could not "
           "reproduce the device row (booked as changes_rejected too)")


@dataclass
class UpmapResult:
    num_changed: int = 0
    new_pg_upmap_items: dict = field(default_factory=dict)
    old_pg_upmap_items: set = field(default_factory=set)
    stddev: float = 0.0
    max_deviation: float = 0.0
    # device_loop only: the applied moves as (pg, frm, to, round) —
    # the readback's audit trail, letting tests replay the plan and
    # check the OSD-disjoint/individually-improving invariants
    moves: list = field(default_factory=list)


def _build_pgs_by_osd(
    m: OSDMap, only_pools, use_tpu: bool, rows_source=None
) -> dict[int, set]:
    """Map every PG of every (selected) pool; the reference's per-PG loop
    (OSDMap.cc:4652-4665) replaced by the batched pipeline.

    The TPU path runs the OVERLAY-FREE kernel and fixes up the few
    upmap-carrying PGs from the host oracle: the compiled pipeline's
    shape then never depends on how many pg_upmap entries have
    accumulated, so every round of every rebalance run dispatches
    through one _PIPE_CACHE entry instead of recompiling.

    rows_source(pid) -> device rows (a ClusterState provider) replaces
    the whole mapping pass with the shared version-tagged cache when it
    answers; pools it declines fall back to the fresh build."""
    pgs_by_osd: dict[int, set] = {}
    for pool_id, pool in sorted(m.pools.items()):
        if only_pools and pool_id not in only_pools:
            continue
        cached = rows_source(pool_id) if rows_source is not None \
            else None
        if cached is not None:
            import numpy as _np

            up = _np.asarray(cached)
            for ps in range(pool.pg_num):
                pg = PgId(pool_id, ps)
                for osd in up[ps]:
                    if osd != ITEM_NONE and osd >= 0:
                        pgs_by_osd.setdefault(int(osd), set()).add(pg)
        elif use_tpu:
            import numpy as _np

            from ceph_tpu.osd.pipeline_jax import (
                PoolMapper,
                overlay_fixup_rows,
            )

            pm = PoolMapper(m, pool_id, overlays=False)
            up = _np.array(pm.map_all_device())  # writable: fixups below
            seeds, fix = overlay_fixup_rows(m, pool_id, up.shape[1])
            up[seeds] = fix
            for ps in range(pool.pg_num):
                pg = PgId(pool_id, ps)
                for osd in up[ps]:
                    if osd != ITEM_NONE and osd >= 0:
                        pgs_by_osd.setdefault(int(osd), set()).add(pg)
        else:
            for ps in range(pool.pg_num):
                pg = PgId(pool_id, ps)
                up, _, _, _ = m.pg_to_up_acting_osds(pg)
                for osd in up:
                    if osd != ITEM_NONE:
                        pgs_by_osd.setdefault(osd, set()).add(pg)
    return pgs_by_osd


# -- candidate-batched optimizer --------------------------------------------
# The sequential greedy (below) evaluates ONE prospective change per
# round-trip — the dispatch-bound analogue of the load-imbalance problem
# ("Rateless Codes for Near-Perfect Load Balancing...", PAPERS.md).  The
# batched form scores a whole batch of prospective pg_upmap changes in
# one vectorized deviation-delta kernel (device-side on the "device"
# backend), accepts the best NON-CONFLICTING subset host-side — the
# squared-deviation objective is separable per OSD, so OSD-disjoint
# candidates with negative deltas are each a guaranteed independent
# improvement — and iterates.  Dispatches per accepted change collapse
# from ~1:1 to ~1:N (candidate_batches / changes_accepted).

_CAND_PAD = 32  # candidate axis cycle-pads to multiples of this: one
                # compiled scoring shape per (OSD bound, slot width)

_SCORE_ACCTS: dict = {}


def _score_math(xp, counts, target, inw, osd, sgn, dv):
    """Sum-of-squares deviation delta of applying each candidate's moves
    alone.  Candidates are [K, S] slot arrays of (osd id, ±1 count
    delta); osd<0 = empty slot.  With a_j the masked slot delta, w the
    in-weight-set mask and dev_j = counts[o_j] - target[o_j]:

        d(sum_sq) = Σ_j 2·a_j·w_j·dev_j + Σ_{j,j'} a_j·a_j'·w_j·[o_j=o_j']

    (the exact expansion of Σ_o (c_o+d_o-t_o)² - (c_o-t_o)², duplicate
    OSDs inside one candidate included).  One expression, executed
    identically by jnp (device) and numpy (the "sets" mirror), so the
    backend cannot change an accept decision's sign."""
    ok = (osd >= 0) & (osd < dv)
    o = xp.clip(osd, 0, dv - 1)
    a = xp.where(ok, sgn, 0.0)
    w = inw[o]
    dev = counts.astype(xp.float64)[o] - target[o]
    lin = xp.sum(2.0 * a * w * dev, axis=1)
    eq = (o[:, :, None] == o[:, None, :]) \
        & ok[:, :, None] & ok[:, None, :]
    quad = xp.sum(
        a[:, :, None] * a[:, None, :] * w[:, :, None] * eq,
        axis=(1, 2))
    return lin + quad


def _score_account(dv: int):
    """The jitted candidate scorer, one executable per OSD bound,
    registered in the executables registry like every trace-once
    kernel."""
    acct = _SCORE_ACCTS.get(dv)
    if acct is None:
        import jax
        import jax.numpy as jnp

        def _score(counts, target, inw, osd, sgn):
            return _score_math(jnp, counts, target, inw, osd, sgn, dv)

        jfn = jax.jit(_score)
        rec = obs.executables.register(
            "balancer", "cand_score", ("cand_score", dv), fn=jfn)
        acct = _SCORE_ACCTS[dv] = obs.JitAccount(
            jfn, _L, "cand_score", exec_record=rec)
    return acct


def _classify_deviations(by_dev, max_deviation):
    """Overfull/underfull partition of the ascending (deviation, osd)
    list — the shared front half of both optimizer loops (reference
    OSDMap.cc:4707-4732)."""
    overfull: set[int] = set()
    more_overfull: set[int] = set()
    underfull: list[int] = []
    more_underfull: list[int] = []
    for osd, d in reversed(by_dev):
        if d <= 0:
            break
        if d > max_deviation:
            overfull.add(osd)
        else:
            more_overfull.add(osd)
    for osd, d in by_dev:
        if d >= 0:
            break
        if d < -max_deviation:
            underfull.append(osd)
        else:
            more_underfull.append(osd)
    return overfull, more_overfull, underfull, more_underfull


def _gen_candidates(m, st, by_dev, osd_deviation, overfull, underfull,
                    more_underfull, using_more_overfull, max_deviation,
                    only_pools, rng, aggressive, limit):
    """Up to `limit` prospective changes, AT MOST ONE per overfull OSD —
    each found exactly the way the sequential loop finds its single
    change (drop remaps INTO the osd, else add a pair via try_pg_upmap)
    but WITHOUT applying anything; the scorer arbitrates afterwards.
    Falls back to the underfull drop pass when the overfull sweep finds
    nothing, mirroring the sequential control flow."""
    cands: list[dict] = []
    seen_pgs: set = set()
    # underfull targets consume ACROSS the batch: without this every
    # overfull osd's try_pg_upmap picks the same most-underfull target
    # and the non-conflicting acceptance degenerates to one change per
    # round (the sequential rate with extra scoring)
    used_targets: set[int] = set()
    for osd, deviation in reversed(by_dev):
        if len(cands) >= limit:
            break
        if deviation < 0:
            break
        if not using_more_overfull and deviation <= max_deviation:
            break
        if osd not in overfull:
            continue
        pgs = [pg for pg in st.pgs_of(osd) if pg not in seen_pgs]
        if aggressive:
            rng.shuffle(pgs)
        cand = None
        # 1) drop existing remaps INTO this overfull osd
        for pg in pgs:
            items = m.pg_upmap_items.get(pg)
            if items is None:
                continue
            moves, new_items = [], []
            for frm, to in items:
                if to == osd:
                    moves.append((to, frm))
                else:
                    new_items.append((frm, to))
            if moves:
                cand = {"pg": pg, "moves": moves,
                        "unmap": not new_items, "items": new_items}
                break
        # 2) add a new remapping pair
        if cand is None:
            for pg in pgs:
                if pg in m.pg_upmap:
                    continue
                pool = m.get_pg_pool(pg.pool)
                new_items = list(m.pg_upmap_items.get(pg, []))
                if len(new_items) >= pool.size:
                    continue
                existing: set[int] = set()
                for frm, to in new_items:
                    existing.add(frm)
                    existing.add(to)
                raw, _ = m._pg_to_raw_osds(pool, pg)
                orig = list(raw)
                m._apply_upmap(pool, pg, orig)
                out = try_pg_upmap(
                    m, pg, overfull,
                    [o for o in underfull if o not in used_targets],
                    [o for o in more_underfull
                     if o not in used_targets],
                    orig)
                if out is None or len(out) != len(orig):
                    continue
                pos, max_dev = -1, 0.0
                for i2 in range(len(out)):
                    if orig[i2] == out[i2]:
                        continue
                    if orig[i2] in existing or out[i2] in existing:
                        continue
                    d = osd_deviation.get(orig[i2], 0.0)
                    if d > max_dev:
                        max_dev, pos = d, i2
                if pos != -1:
                    frm, to = orig[pos], out[pos]
                    cand = {"pg": pg, "moves": [(frm, to)],
                            "unmap": False,
                            "items": new_items + [(frm, to)]}
                    break
        if cand is not None:
            seen_pgs.add(cand["pg"])
            for _, to in cand["moves"]:
                used_targets.add(to)
            cands.append(cand)
    if not cands:
        # underfull pass: drop pairs remapping OUT of strongly-underfull
        # osds (the sequential loop's fallback when overfull found none)
        for osd, deviation in by_dev:
            if len(cands) >= limit or osd not in underfull:
                break
            if abs(deviation) < max_deviation:
                break
            candidates = [
                (pg, items)
                for pg, items in sorted(m.pg_upmap_items.items())
                if pg not in seen_pgs
                and (not only_pools or pg.pool in only_pools)
            ]
            if aggressive:
                rng.shuffle(candidates)
            for pg, items in candidates:
                moves, new_items = [], []
                for frm, to in items:
                    if frm == osd:
                        moves.append((to, frm))
                    else:
                        new_items.append((frm, to))
                if moves:
                    seen_pgs.add(pg)
                    cands.append({"pg": pg, "moves": moves,
                                  "unmap": not new_items,
                                  "items": new_items})
                    break
    return cands


def _score_candidates(st, cands, dv, target, inw, use_device):
    """Score a candidate batch: ONE vectorized deviation-delta kernel
    over [K, S] move slots (device dispatch on the "device" backend,
    the bit-mirrored numpy expression on "sets").  Returns f64[K]."""
    smax = max(len(c["moves"]) for c in cands)
    S = 2
    while S < 2 * smax:
        S *= 2
    K = len(cands)
    Kp = -(-K // _CAND_PAD) * _CAND_PAD
    osd = np.full((Kp, S), -1, np.int32)
    sgn = np.zeros((Kp, S), np.float64)
    for i, c in enumerate(cands):
        for j, (frm, to) in enumerate(c["moves"]):
            osd[i, 2 * j] = frm
            sgn[i, 2 * j] = -1.0
            osd[i, 2 * j + 1] = to
            sgn[i, 2 * j + 1] = 1.0
    counts = st.counts_np(dv)
    _L.inc("candidate_batches")
    _L.inc("candidates_scored", K)
    with obs.span("balancer.score_candidates", candidates=K,
                  device=use_device):
        if use_device:
            import jax.numpy as jnp

            deltas = np.asarray(_score_account(dv)(
                jnp.asarray(counts), jnp.asarray(target),
                jnp.asarray(inw), jnp.asarray(osd), jnp.asarray(sgn),
            ))[:K]
        else:
            deltas = np.asarray(_score_math(
                np, counts, target, inw, osd, sgn, dv))[:K]
    return deltas


def _run_batched(m, st, res, osd_deviation, stddev,
                 max_deviation, max_iter, only_pools, rng, aggressive,
                 candidate_batch, use_device_scoring):
    """The candidate-batched optimizer loop (see the block comment
    above).  `max_iter` bounds BOTH rounds and total accepted changes —
    the same optimization budget the sequential loop spends one change
    per round."""
    dv = max(int(m.max_osd), 1)
    target = np.zeros(dv, np.float64)
    inw = np.zeros(dv, np.float64)
    for osd, w in st.osd_weight.items():
        if 0 <= osd < dv:
            target[osd] = w * st.ppw
            inw[osd] = 1.0
    rounds = 0
    while rounds < max_iter and res.num_changed < max_iter:
        rounds += 1
        _L.inc("rounds")
        with obs.span("balancer.round", iteration=rounds, batched=True), \
                _L.time("round_seconds"), _L.time("round_hist"):
            by_dev = sorted(
                osd_deviation.items(), key=lambda kv: (kv[1], kv[0])
            )
            overfull, more_overfull, underfull, more_underfull = \
                _classify_deviations(by_dev, max_deviation)
            if not underfull and not overfull:
                break
            using_more = False
            if not overfull and underfull:
                overfull = more_overfull
                using_more = True
            cands = _gen_candidates(
                m, st, by_dev, osd_deviation, overfull, underfull,
                more_underfull, using_more, max_deviation, only_pools,
                rng, aggressive, candidate_batch)
            if not cands:
                break
            deltas = _score_candidates(
                st, cands, dv, target, inw, use_device_scoring)
            # candidates the scorer actually turned down — conflict
            # skips book candidate_conflicts, not changes_rejected
            _L.inc("changes_rejected", int(np.sum(deltas >= 0.0)))
            # best non-conflicting subset: ascending delta, skip any
            # candidate touching an OSD an accepted one already moved
            # ("no OSD touched twice") — disjointness makes the deltas
            # additive, so every accept is an independent improvement
            order = np.argsort(deltas, kind="stable")
            txn = st.begin()
            accepted = []
            touched: set[int] = set()
            for i in order:
                if deltas[i] >= 0.0:
                    break
                if res.num_changed + len(accepted) >= max_iter:
                    break
                c = cands[i]
                osds = {x for mv in c["moves"] for x in mv}
                if osds & touched:
                    _L.inc("candidate_conflicts")
                    continue
                for frm, to in c["moves"]:
                    txn.move(c["pg"], frm, to)
                touched |= osds
                accepted.append(c)
            if not accepted:
                break
            stddev_before = stddev
            st.commit(txn)
            for c in accepted:
                pg = c["pg"]
                if c["unmap"]:
                    if pg in m.pg_upmap_items:
                        del m.pg_upmap_items[pg]
                    res.old_pg_upmap_items.add(pg)
                else:
                    m.pg_upmap_items[pg] = list(c["items"])
                    res.new_pg_upmap_items[pg] = list(c["items"])
                res.num_changed += 1
            _L.inc("changes_accepted", len(accepted))
            osd_deviation, stddev, cur_max_deviation = st.deviations()
            _L.observe("stddev", stddev)
            _L.observe("max_deviation", cur_max_deviation)
            obs.counter("balancer.stddev", stddev)
            res.stddev = stddev
            res.max_deviation = cur_max_deviation
            if stddev >= stddev_before:
                break  # float-tie guard: never loop on a non-improvement
            if cur_max_deviation <= max_deviation:
                break
    return res


# -- fully device-resident optimizer ----------------------------------------
# backend="device_loop": the ENTIRE plan — per-round candidate
# generation from the device-resident deviation vector, OSD-disjoint
# subset selection, and the multi-round convergence loop with the
# float-tie guard and max_deviation early-exit — runs inside one
# lax.while_loop, so a whole upmap plan is ONE XLA dispatch whose
# bounded-shape changes buffer is read back once at the end.  Host work
# is O(changes): translate each (pg, frm, to) move back into
# pg_upmap_items pairs and VERIFY each pair list reproduces the device
# row through the exact production overlay application
# (OSDMap._apply_upmap) before committing it.
#
# Candidate semantics mirror _classify_deviations/_gen_candidates:
# strict overfull set with the more_overfull takeover when only
# underfull remain; at most one candidate per overfull OSD (its
# "dominant" PG — the PG whose worst overfull member it is, lowest
# global index, an exact-int scatter-min so the choice is identical
# under any mesh partitioning); targets drawn most-underfull-first from
# the rule's weight map, excluding the row's own members and any OSD
# whose failure domain collides with another member's (the
# try_remap_rule constraint), each target consumed across the round's
# batch.  Accepted moves must strictly improve the separable
# sum-of-squares objective (delta = 2*(dev_to - dev_frm) + 2 < 0) and
# touch no OSD twice, so deltas stay additive — the _run_batched
# invariant — and every accept is an independent improvement.
#
# NOT on device: the sequential loop's underfull fallback pass (drop
# remaps OUT of strongly-underfull OSDs) — it needs the pg_upmap_items
# dict, which stays host-side.  Irrelevant for fresh-map rebalance (no
# items to drop); converged maps that only need drops fall back to the
# host backends.

_LOOP_ACCTS: dict = {}

_DOM_NONE = np.int32(0x7FFFFFFF)  # dom_tbl sentinel: not in rule


def _loop_account(npg, w, dv, npool, nbatch, ncap, mesh_size):
    """The jitted whole-plan kernel, one executable per
    (PGs, slot width, OSD bound, pools, candidate batch, change cap,
    mesh) shape — registered like every trace-once kernel.
    max_deviation and the change/round budget are traced scalars, so
    re-planning with a different budget does not retrace."""
    key = (npg, w, dv, npool, nbatch, ncap, mesh_size)
    acct = _LOOP_ACCTS.get(key)
    if acct is None:
        import jax
        import jax.numpy as jnp
        from jax import lax

        def _plan(rows, pidx, movable, dom_tbl, tgt_ok, target, inw,
                  counts, max_dev, budget):
            inwm = inw > 0.0
            gidx = jnp.arange(npg, dtype=jnp.int32)
            warange = jnp.arange(w, dtype=jnp.int32)
            barange = jnp.arange(nbatch, dtype=jnp.int32)
            psafe = jnp.clip(pidx, 0, npool - 1)

            def dev_of(c):
                return jnp.where(
                    inwm, c.astype(jnp.float64) - target, 0.0)

            def round_body(carry):
                (rows, counts, cpg, cfrm, cto, crnd, n_chg, n_rej,
                 rounds, sum_sq, _) = carry
                dev = dev_of(counts)
                has_over = jnp.any(dev > max_dev)
                has_under = jnp.any(dev < -max_dev)
                # more_overfull takeover when only underfull remain
                over = jnp.where(has_over, dev > max_dev,
                                 (dev > 0.0) & has_under) & inwm
                # candidate PG per overfull OSD: the lowest-index PG
                # whose WORST overfull member it is (exact-int
                # scatter-min — identical under any sharding)
                valid_m = (rows >= 0) & (rows < dv)
                rsafe = jnp.where(valid_m, rows, 0)
                rdev = jnp.where(valid_m & over[rsafe],
                                 dev.astype(jnp.float32)[rsafe],
                                 -jnp.inf)
                dmax = jnp.max(rdev, axis=1)
                darg = jnp.argmax(rdev, axis=1).astype(jnp.int32)
                dosd = jnp.where(
                    jnp.isfinite(dmax) & movable,
                    jnp.take_along_axis(rsafe, darg[:, None], 1)[:, 0],
                    dv)
                pick = jnp.full((dv,), npg, jnp.int32).at[dosd].min(
                    gidx, mode="drop")
                # top-B overfull OSDs by deviation
                topv, topi = lax.top_k(jnp.where(over, dev, -jnp.inf),
                                       nbatch)

                def cand(k, acc):
                    used, apg, aslot, ato, afrm, n_acc, rej = acc
                    frm = topi[k].astype(jnp.int32)
                    pg = pick[frm]
                    valid = jnp.isfinite(topv[k]) & ~used[frm] \
                        & (pg < npg)
                    pgc = jnp.clip(pg, 0, npg - 1)
                    row = rows[pgc]
                    vm = (row >= 0) & (row < dv)
                    rsc = jnp.where(vm, row, 0)
                    smask = vm & (row == frm)
                    valid &= jnp.any(smask)
                    slot = jnp.argmax(smask).astype(jnp.int32)
                    p = psafe[pgc]
                    dtbl = dom_tbl[p]
                    in_row = jnp.zeros((dv,), bool).at[
                        jnp.where(vm, rsc, dv)].set(True, mode="drop")
                    # failure-domain constraint: the replacement may
                    # not land in any OTHER member's domain
                    mdom = jnp.where(vm & (warange != slot),
                                     dtbl[rsc], _DOM_NONE)
                    dom_ok = jnp.all(
                        dtbl[:, None] != mdom[None, :], axis=1)
                    allowed = inwm & (dev < 0.0) & tgt_ok[p] & ~used \
                        & ~in_row & dom_ok
                    has_t = jnp.any(allowed)
                    t = jnp.argmin(
                        jnp.where(allowed, dev, jnp.inf)
                    ).astype(jnp.int32)
                    # separable objective: moving one PG frm->to
                    delta = 2.0 * (dev[t] - dev[frm]) + 2.0
                    cand_ok = valid & has_t
                    accept = cand_ok & (delta < 0.0) \
                        & (n_chg + n_acc < budget)
                    rej = rej + jnp.where(
                        cand_ok & (delta >= 0.0), 1, 0
                    ).astype(jnp.int32)
                    ins = jnp.where(accept, n_acc, nbatch)
                    apg = apg.at[ins].set(pg, mode="drop")
                    aslot = aslot.at[ins].set(slot, mode="drop")
                    ato = ato.at[ins].set(t, mode="drop")
                    afrm = afrm.at[ins].set(frm, mode="drop")
                    # targets consume across the batch whether or not
                    # the score accepts (mirrors _gen_candidates'
                    # used_targets)
                    used = used.at[jnp.where(cand_ok, t, dv)].set(
                        True, mode="drop")
                    used = used.at[jnp.where(accept, frm, dv)].set(
                        True, mode="drop")
                    return (used, apg, aslot, ato, afrm,
                            n_acc + accept.astype(jnp.int32), rej)

                used, apg, aslot, ato, afrm, n_acc, rej_r = \
                    lax.fori_loop(
                        0, nbatch, cand,
                        (jnp.zeros((dv,), bool),
                         jnp.full((nbatch,), npg, jnp.int32),
                         jnp.zeros((nbatch,), jnp.int32),
                         jnp.full((nbatch,), dv, jnp.int32),
                         jnp.full((nbatch,), dv, jnp.int32),
                         jnp.int32(0), jnp.int32(0)))
                # apply: per-round PGs are distinct (one dominant
                # member each) and OSDs disjoint, so scatters commute
                rows2 = rows.at[apg, aslot].set(ato, mode="drop")
                counts2 = counts.at[afrm].add(-1, mode="drop")
                counts2 = counts2.at[ato].add(1, mode="drop")
                bpos = jnp.where(barange < n_acc,
                                 n_chg + barange, ncap)
                cpg2 = cpg.at[bpos].set(apg, mode="drop")
                cfrm2 = cfrm.at[bpos].set(afrm, mode="drop")
                cto2 = cto.at[bpos].set(ato, mode="drop")
                crnd2 = crnd.at[bpos].set(rounds + 1, mode="drop")
                n_chg2 = n_chg + n_acc
                devn = dev_of(counts2)
                ss2 = jnp.sum(devn * devn)
                mx2 = jnp.max(jnp.abs(devn))
                rounds2 = rounds + 1
                # the sequential loop's exits: nothing accepted, the
                # float-tie guard (never loop on a non-improvement),
                # max_deviation reached, round/change budget spent
                cont = (n_acc > 0) & (ss2 < sum_sq) \
                    & (mx2 > max_dev) & (rounds2 < budget) \
                    & (n_chg2 < budget)
                return (rows2, counts2, cpg2, cfrm2, cto2, crnd2,
                        n_chg2, n_rej + rej_r, rounds2, ss2, cont)

            dev0 = dev_of(counts)
            init = (rows, counts,
                    jnp.full((ncap,), npg, jnp.int32),
                    jnp.full((ncap,), dv, jnp.int32),
                    jnp.full((ncap,), dv, jnp.int32),
                    jnp.zeros((ncap,), jnp.int32),
                    jnp.int32(0), jnp.int32(0), jnp.int32(0),
                    jnp.sum(dev0 * dev0), jnp.bool_(True))
            (rows_f, counts_f, cpg, cfrm, cto, crnd, n_chg, n_rej,
             rounds, ss_f, _) = lax.while_loop(
                lambda c: c[-1], round_body, init)
            # gather the final rows of every changed PG INSIDE the
            # dispatch: readback is then a pure fetch of bounded-shape
            # outputs — no second kernel
            crows = rows_f[jnp.clip(cpg, 0, npg - 1)]
            dev_f = dev_of(counts_f)
            return (cpg, cfrm, cto, crnd, crows, n_chg, n_rej, rounds,
                    counts_f, ss_f, jnp.max(jnp.abs(dev_f)))

        jfn = jax.jit(_plan)
        rec = obs.executables.register(
            "balancer", "device_loop", ("device_loop",) + key, fn=jfn)
        acct = _LOOP_ACCTS[key] = obs.JitAccount(
            jfn, _L, "device_loop", exec_record=rec)
    return acct


def _run_device_loop(m, fst, res, max_deviation, max_iter,
                     candidate_batch):
    """Host driver for the device_loop backend: build the O(OSDs)
    metadata (targets/domain tables), launch the one-dispatch plan
    kernel, then translate the changes buffer back into
    pg_upmap_items — verifying every pair list against the exact
    production overlay application before committing it."""
    import jax.numpy as jnp

    st = fst.st
    dv = max(int(m.max_osd), 1)
    target = np.zeros(dv, np.float64)
    inw = np.zeros(dv, np.float64)
    for osd, w2 in st.osd_weight.items():
        if 0 <= osd < dv:
            target[osd] = w2 * st.ppw
            inw[osd] = 1.0
    # per-pool valid-target mask and failure-domain table (the
    # try_remap_rule subtree/domain constraints, precomputed once)
    P = max(len(fst.pools), 1)
    dom_tbl = np.full((P, dv), _DOM_NONE, np.int32)
    tgt_ok = np.zeros((P, dv), bool)
    for i, pid in enumerate(fst.pools):
        pool = m.pools[pid]
        ruleno = mapper_ref.find_rule(
            m.crush, pool.crush_rule, int(pool.type), pool.size)
        if ruleno < 0:
            continue
        dom_type = 0
        for op, _a1, a2 in m.crush.rules[ruleno].steps:
            if op in (RuleOp.CHOOSE_FIRSTN, RuleOp.CHOOSE_INDEP,
                      RuleOp.CHOOSELEAF_FIRSTN,
                      RuleOp.CHOOSELEAF_INDEP) and a2 > 0:
                dom_type = a2
                break
        for osd in get_rule_weight_osd_map(m.crush, ruleno):
            if not (0 <= osd < dv):
                continue
            # a down OSD reads maximally underfull (its count is 0 —
            # _raw_to_up_osds filters it everywhere) but can never be
            # a target: the committed pair would be skipped/filtered
            # by the exact overlay application and revert at readback
            tgt_ok[i, osd] = m.exists(osd) and not m.is_down(osd)
            dom_tbl[i, osd] = (
                get_parent_of_type(m.crush, osd, dom_type, ruleno)
                if dom_type > 0 else osd)
    movable = np.ones(fst.pool_idx.shape[0], bool)
    movable[fst.pool_idx < 0] = False  # mesh padding
    pool_pos = {pid: i for i, pid in enumerate(fst.pools)}
    for pg in m.pg_upmap:  # full-remap PGs are frozen
        i = pool_pos.get(pg.pool)
        if i is not None and pg.ps < m.pools[pg.pool].pg_num:
            movable[int(fst.offsets[i]) + pg.ps] = False

    B = max(1, min(int(candidate_batch), dv))
    C = -(-max(int(max_iter), 1) // 8) * 8  # change cap, cycle-padded
    npg = int(fst.rows.shape[0])
    W = int(fst.rows.shape[1])
    mesh_size = int(fst.mesh.devices.size) if fst.mesh is not None \
        else 0
    acct = _loop_account(npg, W, dv, P, B, C, mesh_size)
    _L.inc("plan_dispatches")
    with obs.span("balancer.device_loop", pgs=npg, osds=dv, batch=B,
                  budget=int(max_iter), mesh=mesh_size):
        out = acct(
            fst.rows, jnp.asarray(fst.pool_idx), jnp.asarray(movable),
            jnp.asarray(dom_tbl), jnp.asarray(tgt_ok),
            jnp.asarray(target), jnp.asarray(inw),
            jnp.asarray(st.counts.astype(np.int64)),
            jnp.float64(float(max_deviation)),
            jnp.int32(int(max_iter)))
    (cpg_d, cfrm_d, cto_d, crnd_d, crows_d, n_chg, n_rej, rounds_d,
     counts_f, _ss_f, _mx_f) = out
    n_chg, n_rej, rounds_d = int(n_chg), int(n_rej), int(rounds_d)
    _L.inc("rounds", rounds_d)
    _L.inc("changes_rejected", n_rej)
    cpg = np.asarray(cpg_d)[:n_chg]
    cfrm = np.asarray(cfrm_d)[:n_chg]
    cto = np.asarray(cto_d)[:n_chg]
    crnd = np.asarray(crnd_d)[:n_chg]
    crows = np.asarray(crows_d)[:n_chg]
    counts_np = np.asarray(counts_f).copy()

    # readback: compose each changed PG's recorded moves (in round
    # order) onto its existing pg_upmap_items — a move whose `frm` is
    # an earlier pair's target rewrites that pair (or cancels it when
    # it lands back on the raw member) — then VERIFY the pair list
    # reproduces the device row through the exact production transform
    # (_pg_to_raw_osds -> _apply_upmap -> _raw_to_up_osds) before
    # committing it
    last: dict[int, int] = {}
    moves_of: dict[int, list[int]] = {}
    for i in range(n_chg):
        g = int(cpg[i])
        last[g] = i
        moves_of.setdefault(g, []).append(i)
    W = int(crows.shape[1]) if n_chg else 0
    applied = 0
    for g in sorted(last):
        pid, seed = fst.locate(g)
        pool = m.pools[pid]
        pg = PgId(pid, seed)
        old = m.pg_upmap_items.get(pg)
        pairs = list(old or [])
        for j in moves_of[g]:
            frm, to = int(cfrm[j]), int(cto[j])
            for k2, (a, b) in enumerate(pairs):
                if b == frm:
                    if a == to:
                        del pairs[k2]  # back to the raw member
                    else:
                        pairs[k2] = (a, to)
                    break
            else:
                pairs.append((frm, to))
        raw, _ = m._pg_to_raw_osds(pool, pg)
        if pairs:
            m.pg_upmap_items[pg] = pairs
        elif pg in m.pg_upmap_items:
            del m.pg_upmap_items[pg]
        chk = list(raw)
        m._apply_upmap(pool, pg, chk)
        chk = m._raw_to_up_osds(pool, chk)
        want = chk + [ITEM_NONE] * (W - len(chk))
        if [int(x) for x in crows[last[g]]] != want[:W]:
            # the exact overlay application cannot express this row
            # (pair-order/skip interaction with pre-existing items):
            # revert, roll the counts back, book the moves rejected
            if old is not None:
                m.pg_upmap_items[pg] = old
            elif pg in m.pg_upmap_items:
                del m.pg_upmap_items[pg]
            for j in moves_of[g]:
                counts_np[int(cfrm[j])] += 1
                counts_np[int(cto[j])] -= 1
            _L.inc("plan_readback_reverts", len(moves_of[g]))
            _L.inc("changes_rejected", len(moves_of[g]))
            continue
        applied += len(moves_of[g])
        res.num_changed += len(moves_of[g])
        for j in moves_of[g]:
            res.moves.append((pg, int(cfrm[j]), int(cto[j]),
                              int(crnd[j])))
        if pairs:
            res.new_pg_upmap_items[pg] = list(pairs)
        elif old is not None:
            res.old_pg_upmap_items.add(pg)
    _L.inc("changes_accepted", applied)
    _, stddev, cur_max = st._dev_from_counts(counts_np)
    _L.observe("stddev", stddev)
    _L.observe("max_deviation", cur_max)
    obs.counter("balancer.stddev", stddev)
    res.stddev = stddev
    res.max_deviation = cur_max
    return res


def calc_pg_upmaps(
    m: OSDMap,
    max_deviation: int = 5,
    max_iter: int = 10,
    only_pools: set[int] | None = None,
    aggressive: bool = True,
    local_fallback_retries: int = 100,
    use_tpu: bool = True,
    rng: np.random.Generator | None = None,
    backend: str = "sets",
    mesh=None,
    device_cache: dict | None = None,
    rows_source=None,
    candidate_batch: int = 0,
) -> UpmapResult:
    """Greedy upmap optimization; mutates m.pg_upmap_items.  Returns the
    change set (the reference's pending_inc).  reference OSDMap.cc:4634.

    backend: "sets" (reference-faithful dict-of-sets, small maps),
    "device" (membership rows on device, O(OSDs) host state — the
    10M-PG/10k-OSD form; sharded over `mesh`, defaulting to the
    CEPH_TPU_MESH_DEVICES mesh), or "device_loop" (the whole
    multi-round greedy inside one lax.while_loop — a full plan in ONE
    XLA dispatch, changes read back once; sharded over `mesh` the same
    way).  All evolve the same bookkeeping; equivalence is pinned by
    tests/test_balancer.py and tests/test_multichip.py.

    candidate_batch: 0 = the reference-faithful sequential greedy (one
    evaluated change per round-trip); N>0 = the candidate-batched
    optimizer — score up to N prospective changes per vectorized
    dispatch and accept the best non-conflicting subset (counter ratio
    balancer.candidate_batches / changes_accepted is the
    dispatches-per-change proof).
    """
    from ceph_tpu.balancer.state import DeviceState, SetState

    if backend in ("device", "device_loop") and mesh is None:
        from ceph_tpu.parallel.sharded import default_mesh

        mesh = default_mesh()

    res = UpmapResult()
    max_deviation = max(1, max_deviation)
    only_pools = only_pools or set()
    rng = rng or np.random.default_rng(0)

    # per-osd weight from the pools' crush rules
    total_pgs = 0
    osd_weight: dict[int, float] = {}
    osd_weight_total = 0.0
    for pool_id, pool in sorted(m.pools.items()):
        if only_pools and pool_id not in only_pools:
            continue
        total_pgs += pool.size * pool.pg_num
        ruleno = mapper_ref.find_rule(
            m.crush, pool.crush_rule, int(pool.type), pool.size
        )
        if ruleno < 0:
            continue
        pmap = get_rule_weight_osd_map(m.crush, ruleno)
        for osd, w in pmap.items():
            adjusted = m.get_weightf(osd) * w if osd < m.max_osd else 0.0
            if adjusted == 0.0:
                continue
            osd_weight[osd] = osd_weight.get(osd, 0.0) + adjusted
            osd_weight_total += adjusted
    if osd_weight_total == 0 or max_iter <= 0:
        return res
    pgs_per_weight = total_pgs / osd_weight_total

    # a membership build served from the shared ClusterState's cached
    # rows is NOT an O(PGs) build — it books state_rows_reused, and
    # build_state_seconds stays a true build-cost signal (the steady
    # profile criterion: rebalance rounds riding a warm state show no
    # build_state time at all).  "Served" means the provider actually
    # ANSWERED every pool: a provider that declines (working copy
    # diverged) falls back to the O(PGs) build, which must book as one.
    served = {"hit": 0, "miss": 0}

    def _counted_src(pid):
        rows = rows_source(pid)
        served["hit" if rows is not None else "miss"] += 1
        return rows

    src = _counted_src if rows_source is not None else None
    t0 = time.perf_counter()
    with obs.span(
        "balancer.build_state", backend=backend, pgs=total_pgs,
        reused=rows_source is not None,
    ):
        if backend in ("device", "device_loop"):
            # device_loop re-pads/shards the CONCATENATED pg axis
            # itself, so its per-pool DeviceState rows stay unsharded
            st = DeviceState(
                m, osd_weight, pgs_per_weight, only_pools=only_pools,
                mesh=mesh if backend == "device" else None,
                cache=device_cache, rows_source=src,
            )
        else:
            pgs_by_osd = _build_pgs_by_osd(m, only_pools, use_tpu,
                                           rows_source=src)
            st = SetState(pgs_by_osd, osd_weight, pgs_per_weight)
    if src is not None and not served["miss"] and served["hit"]:
        _L.inc("state_rows_reused")
    else:
        _L.observe("build_state_seconds", time.perf_counter() - t0)

    osd_deviation, stddev, cur_max_deviation = st.deviations()
    res.stddev, res.max_deviation = stddev, cur_max_deviation
    if cur_max_deviation <= max_deviation:
        return res

    if backend == "device_loop":
        from ceph_tpu.balancer.state import FlatDeviceState

        fst = FlatDeviceState(st, mesh)
        return _run_device_loop(
            m, fst, res, max_deviation, max_iter,
            int(candidate_batch) or 16)

    if candidate_batch:
        return _run_batched(
            m, st, res, osd_deviation, stddev,
            max_deviation, max_iter, only_pools, rng, aggressive,
            int(candidate_batch),
            use_device_scoring=(backend == "device"),
        )

    skip_overfull = False
    iter_left = max_iter
    while iter_left > 0:
        iter_left -= 1
        _L.inc("rounds")
        with obs.span(
            "balancer.round", iteration=max_iter - iter_left
        ), _L.time("round_seconds"), _L.time("round_hist"):
            by_dev = sorted(
                osd_deviation.items(), key=lambda kv: (kv[1], kv[0])
            )
            overfull, more_overfull, underfull, more_underfull = \
                _classify_deviations(by_dev, max_deviation)
            if not underfull and not overfull:
                break
            using_more_overfull = False
            if not overfull and underfull:
                overfull = more_overfull
                using_more_overfull = True

            to_skip: set = set()
            local_fallback_retried = 0

            while True:  # retry: label
                to_unmap: set = set()
                to_upmap: dict = {}
                txn = st.begin()
                found = False

                # ---- overfull pass ---------------------------------------
                if not (skip_overfull and underfull):
                    for osd, deviation in reversed(by_dev):
                        if deviation < 0:
                            break
                        if (not using_more_overfull
                                and deviation <= max_deviation):
                            break
                        pgs = [
                            pg for pg in st.pgs_of(osd)
                            if pg not in to_skip
                        ]
                        if aggressive:
                            rng.shuffle(pgs)  # equal (in)attention
                        # 1) drop existing remaps INTO this overfull osd
                        for pg in pgs:
                            items = m.pg_upmap_items.get(pg)
                            if items is None:
                                continue
                            new_items = []
                            for frm, to in items:
                                if to == osd:
                                    txn.move(pg, to, frm)
                                else:
                                    new_items.append((frm, to))
                            if not new_items:
                                to_unmap.add(pg)
                                found = True
                                break
                            elif len(new_items) != len(items):
                                to_upmap[pg] = new_items
                                found = True
                                break
                        if found:
                            break
                        # 2) add a new remapping pair
                        for pg in pgs:
                            if pg in m.pg_upmap:
                                continue
                            pool = m.get_pg_pool(pg.pool)
                            new_items = list(m.pg_upmap_items.get(pg, []))
                            if len(new_items) >= pool.size:
                                continue
                            existing: set[int] = set()
                            for frm, to in new_items:
                                existing.add(frm)
                                existing.add(to)
                            # raw mapping including existing upmaps
                            raw, _ = m._pg_to_raw_osds(pool, pg)
                            orig = list(raw)
                            m._apply_upmap(pool, pg, orig)
                            out = try_pg_upmap(
                                m, pg, overfull, underfull, more_underfull,
                                orig
                            )
                            if out is None or len(out) != len(orig):
                                continue
                            pos, max_dev = -1, 0.0
                            for i2 in range(len(out)):
                                if orig[i2] == out[i2]:
                                    continue
                                if (
                                    orig[i2] in existing
                                    or out[i2] in existing
                                ):
                                    continue
                                d = osd_deviation.get(orig[i2], 0.0)
                                if d > max_dev:
                                    max_dev, pos = d, i2
                            if pos != -1:
                                frm, to = orig[pos], out[pos]
                                txn.move(pg, frm, to)
                                new_items.append((frm, to))
                                to_upmap[pg] = new_items
                                found = True
                                break
                        if found:
                            break

                # ---- underfull pass --------------------------------------
                if not found:
                    for osd, deviation in by_dev:
                        if osd not in underfull:
                            break
                        if abs(deviation) < max_deviation:
                            break
                        candidates = [
                            (pg, items)
                            for pg, items in sorted(m.pg_upmap_items.items())
                            if pg not in to_skip
                            and (not only_pools or pg.pool in only_pools)
                        ]
                        if aggressive:
                            rng.shuffle(candidates)
                        for pg, items in candidates:
                            new_items = []
                            for frm, to in items:
                                if frm == osd:
                                    txn.move(pg, to, frm)
                                else:
                                    new_items.append((frm, to))
                            if not new_items:
                                to_unmap.add(pg)
                                found = True
                                break
                            elif len(new_items) != len(items):
                                to_upmap[pg] = new_items
                                found = True
                                break
                        if found:
                            break

                if not found:
                    if not aggressive:
                        iter_left = 0
                    elif not skip_overfull:
                        iter_left = 0
                    else:
                        skip_overfull = False
                    break  # out of retry loop

                # ---- test_change -----------------------------------------
                temp_dev, new_stddev, cur_max_deviation = txn.deviations()
                if new_stddev >= stddev:
                    _L.inc(
                        "changes_rejected", len(to_unmap) + len(to_upmap)
                    )
                    if not aggressive:
                        iter_left = 0
                        break
                    local_fallback_retried += 1
                    if local_fallback_retried >= local_fallback_retries:
                        skip_overfull = not skip_overfull
                        break
                    to_skip |= to_unmap
                    to_skip |= set(to_upmap)
                    continue  # goto retry

                stddev = new_stddev
                st.commit(txn)
                osd_deviation = temp_dev
                for pg in to_unmap:
                    del m.pg_upmap_items[pg]
                    res.old_pg_upmap_items.add(pg)
                    res.num_changed += 1
                for pg, items in to_upmap.items():
                    m.pg_upmap_items[pg] = items
                    res.new_pg_upmap_items[pg] = items
                    res.num_changed += 1
                _L.inc("changes_accepted", len(to_unmap) + len(to_upmap))
                _L.observe("stddev", stddev)
                _L.observe("max_deviation", cur_max_deviation)
                obs.counter("balancer.stddev", stddev)
                res.stddev = stddev
                res.max_deviation = cur_max_deviation
                if cur_max_deviation <= max_deviation:
                    iter_left = 0
                break  # exit retry loop, next outer iteration

    return res
