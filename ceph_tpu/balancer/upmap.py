"""Upmap balancer — calc_pg_upmaps with TPU-batched cluster mapping.

Semantics port of the reference's greedy optimizer
(`OSDMap::calc_pg_upmaps`, reference src/osd/OSDMap.cc:4634-5208, with
`try_pg_upmap` :4590 and `CrushWrapper::try_remap_rule` /
`_choose_type_stack` at reference src/crush/CrushWrapper.cc:4061/3845).

The structure is the reference's: a host-side greedy loop that drops or adds
`pg_upmap_items` pairs one tiny change at a time, accepting only changes
that lower the PG-count deviation stddev.  The expensive part — mapping
every PG of every pool to build `pgs_by_osd` — runs as the batched JAX
pipeline (one XLA call per pool) instead of the reference's per-PG
`pg_to_up_acting_osds` loop; everything after that is incremental set
bookkeeping, so the TPU does the O(PGs) work and the host does the O(changes)
work.
"""

from __future__ import annotations

import time

from dataclasses import dataclass, field

import numpy as np

from ceph_tpu import obs
from ceph_tpu.balancer.crush_analysis import (
    get_parent_of_type,
    get_rule_weight_osd_map,
    subtree_contains,
)
from ceph_tpu.crush import mapper_ref
from ceph_tpu.crush.types import ITEM_NONE, RuleOp
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.types import PgId


# -- try_remap_rule ---------------------------------------------------------

def _choose_type_stack(
    m,
    stack: list[tuple[int, int]],
    overfull: set[int],
    underfull: list[int],
    more_underfull: list[int],
    orig: list[int],
    ipos: list[int],
    used: set[int],
    w: list[int],
    root_bucket: int,
    ruleno: int,
) -> list[int]:
    """reference CrushWrapper.cc:3845-4058; ipos is the shared orig cursor
    (a 1-list so the caller sees advancement)."""
    crush = m.crush
    cumulative_fanout = [0] * len(stack)
    f = 1
    for j in range(len(stack) - 1, -1, -1):
        cumulative_fanout[j] = f
        f *= stack[j][1]

    # per-level buckets that contain >=1 underfull device
    underfull_buckets: list[set[int]] = [set() for _ in range(len(stack) - 1)]
    for osd in underfull:
        item = osd
        for j in range(len(stack) - 2, -1, -1):
            type_ = stack[j][0]
            item = get_parent_of_type(crush, item, type_, ruleno)
            if not subtree_contains(crush, root_bucket, item):
                continue
            underfull_buckets[j].add(item)

    for j in range(len(stack)):
        type_, fanout = stack[j]
        cum_fanout = cumulative_fanout[j]
        o: list[int] = []
        tmpi = ipos[0]
        if ipos[0] >= len(orig):
            break
        for from_ in w:
            leaves: list[set[int]] = [set() for _ in range(fanout)]
            for pos in range(fanout):
                if type_ > 0:
                    if tmpi >= len(orig):
                        # reference "end of orig, break 1"
                        # (CrushWrapper.cc:3906): a degraded mapping is
                        # shorter than the rule's fanout product
                        break
                    item = get_parent_of_type(
                        crush, orig[tmpi], type_, ruleno
                    )
                    o.append(item)
                    n = cum_fanout
                    while n > 0 and tmpi < len(orig):
                        leaves[pos].add(orig[tmpi])
                        tmpi += 1
                        n -= 1
                else:
                    replaced = False
                    if orig[ipos[0]] in overfull:
                        for cand_list in (underfull, more_underfull):
                            for item in cand_list:
                                if item in used:
                                    continue
                                if not subtree_contains(crush, from_, item):
                                    continue
                                if item in orig:
                                    continue
                                o.append(item)
                                used.add(item)
                                replaced = True
                                ipos[0] += 1
                                break
                            if replaced:
                                break
                    if not replaced:
                        o.append(orig[ipos[0]])
                        ipos[0] += 1
                    if ipos[0] >= len(orig):
                        break
            if j + 1 < len(stack):
                # swap buckets with overfull leaves but no underfull
                # candidates for peers that do have some
                for pos in range(fanout):
                    if pos >= len(o):
                        break
                    if o[pos] in underfull_buckets[j]:
                        continue
                    if not any(osd in overfull for osd in leaves[pos]):
                        continue
                    for alt in sorted(underfull_buckets[j]):
                        if alt in o:
                            continue
                        if j == 0 or get_parent_of_type(
                            crush, o[pos], stack[j - 1][0], ruleno
                        ) == get_parent_of_type(
                            crush, alt, stack[j - 1][0], ruleno
                        ):
                            o[pos] = alt
                            break
            if ipos[0] >= len(orig):
                break
        w = o
    return w


def try_remap_rule(
    m: OSDMap,
    ruleno: int,
    maxout: int,
    overfull: set[int],
    underfull: list[int],
    more_underfull: list[int],
    orig: list[int],
) -> list[int] | None:
    """reference CrushWrapper.cc:4061-4156."""
    crush = m.crush
    rule = crush.rules[ruleno]
    w: list[int] = []
    out: list[int] = []
    ipos = [0]
    used: set[int] = set()
    type_stack: list[tuple[int, int]] = []
    root_bucket = 0
    for op, a1, a2 in rule.steps:
        if op == RuleOp.TAKE:
            w = [a1]
            root_bucket = a1
        elif op in (RuleOp.CHOOSELEAF_FIRSTN, RuleOp.CHOOSELEAF_INDEP):
            numrep, type_ = a1, a2
            if numrep <= 0:
                numrep += maxout
            type_stack.append((type_, numrep))
            if type_ > 0:
                type_stack.append((0, 1))
            w = _choose_type_stack(
                m, type_stack, overfull, underfull, more_underfull,
                orig, ipos, used, w, root_bucket, ruleno,
            )
            type_stack = []
        elif op in (RuleOp.CHOOSE_FIRSTN, RuleOp.CHOOSE_INDEP):
            numrep, type_ = a1, a2
            if numrep <= 0:
                numrep += maxout
            type_stack.append((type_, numrep))
        elif op == RuleOp.EMIT:
            if type_stack:
                w = _choose_type_stack(
                    m, type_stack, overfull, underfull, more_underfull,
                    orig, ipos, used, w, root_bucket, ruleno,
                )
                type_stack = []
            out.extend(w)
            w = []
    return out


def try_pg_upmap(
    m: OSDMap,
    pg: PgId,
    overfull: set[int],
    underfull: list[int],
    more_underfull: list[int],
    orig: list[int],
) -> list[int] | None:
    """reference OSDMap.cc:4590-4632."""
    pool = m.get_pg_pool(pg.pool)
    if pool is None:
        return None
    ruleno = mapper_ref.find_rule(
        m.crush, pool.crush_rule, int(pool.type), pool.size
    )
    if ruleno < 0:
        return None
    if not any(osd in overfull for osd in orig):
        return None
    out = try_remap_rule(
        m, ruleno, pool.size, overfull, underfull, more_underfull, orig
    )
    if out is None or out == orig:
        return None
    return out


# -- calc_pg_upmaps ---------------------------------------------------------

_L = obs.logger_for("balancer")
_L.add_u64("rounds", "greedy optimizer outer iterations")
_L.add_u64("changes_accepted", "upmap-item changes committed")
_L.add_u64("changes_rejected", "upmap-item changes rolled back (stddev up)")
_L.add_avg("stddev", "PG-count deviation stddev after each accepted change")
_L.add_avg("max_deviation", "max abs deviation after each accepted change")
_L.add_time_avg("round_seconds", "wall time per optimizer round")
_L.add_quantile("round_hist",
                "optimizer round wall-time distribution (p50/p99)")
_L.add_time_avg("build_state_seconds",
                "O(PGs) membership-state build time (booked ONLY when "
                "the build actually re-mapped pools — builds served "
                "from ClusterState rows book state_rows_reused "
                "instead)")
_L.add_u64("state_rows_reused",
           "membership builds served from the shared ClusterState's "
           "version-tagged device rows (no O(PGs) mapping pass)")


@dataclass
class UpmapResult:
    num_changed: int = 0
    new_pg_upmap_items: dict = field(default_factory=dict)
    old_pg_upmap_items: set = field(default_factory=set)
    stddev: float = 0.0
    max_deviation: float = 0.0


def _build_pgs_by_osd(
    m: OSDMap, only_pools, use_tpu: bool, rows_source=None
) -> dict[int, set]:
    """Map every PG of every (selected) pool; the reference's per-PG loop
    (OSDMap.cc:4652-4665) replaced by the batched pipeline.

    The TPU path runs the OVERLAY-FREE kernel and fixes up the few
    upmap-carrying PGs from the host oracle: the compiled pipeline's
    shape then never depends on how many pg_upmap entries have
    accumulated, so every round of every rebalance run dispatches
    through one _PIPE_CACHE entry instead of recompiling.

    rows_source(pid) -> device rows (a ClusterState provider) replaces
    the whole mapping pass with the shared version-tagged cache when it
    answers; pools it declines fall back to the fresh build."""
    pgs_by_osd: dict[int, set] = {}
    for pool_id, pool in sorted(m.pools.items()):
        if only_pools and pool_id not in only_pools:
            continue
        cached = rows_source(pool_id) if rows_source is not None \
            else None
        if cached is not None:
            import numpy as _np

            up = _np.asarray(cached)
            for ps in range(pool.pg_num):
                pg = PgId(pool_id, ps)
                for osd in up[ps]:
                    if osd != ITEM_NONE and osd >= 0:
                        pgs_by_osd.setdefault(int(osd), set()).add(pg)
        elif use_tpu:
            import numpy as _np

            from ceph_tpu.osd.pipeline_jax import (
                PoolMapper,
                overlay_fixup_rows,
            )

            pm = PoolMapper(m, pool_id, overlays=False)
            up = _np.array(pm.map_all_device())  # writable: fixups below
            seeds, fix = overlay_fixup_rows(m, pool_id, up.shape[1])
            up[seeds] = fix
            for ps in range(pool.pg_num):
                pg = PgId(pool_id, ps)
                for osd in up[ps]:
                    if osd != ITEM_NONE and osd >= 0:
                        pgs_by_osd.setdefault(int(osd), set()).add(pg)
        else:
            for ps in range(pool.pg_num):
                pg = PgId(pool_id, ps)
                up, _, _, _ = m.pg_to_up_acting_osds(pg)
                for osd in up:
                    if osd != ITEM_NONE:
                        pgs_by_osd.setdefault(osd, set()).add(pg)
    return pgs_by_osd


def calc_pg_upmaps(
    m: OSDMap,
    max_deviation: int = 5,
    max_iter: int = 10,
    only_pools: set[int] | None = None,
    aggressive: bool = True,
    local_fallback_retries: int = 100,
    use_tpu: bool = True,
    rng: np.random.Generator | None = None,
    backend: str = "sets",
    mesh=None,
    device_cache: dict | None = None,
    rows_source=None,
) -> UpmapResult:
    """Greedy upmap optimization; mutates m.pg_upmap_items.  Returns the
    change set (the reference's pending_inc).  reference OSDMap.cc:4634.

    backend: "sets" (reference-faithful dict-of-sets, small maps) or
    "device" (membership rows on device, O(OSDs) host state — the
    10M-PG/10k-OSD form; optionally sharded over `mesh`).  Both evolve
    the same bookkeeping; equivalence is pinned by tests/test_balancer.py.
    """
    from ceph_tpu.balancer.state import DeviceState, SetState

    res = UpmapResult()
    max_deviation = max(1, max_deviation)
    only_pools = only_pools or set()
    rng = rng or np.random.default_rng(0)

    # per-osd weight from the pools' crush rules
    total_pgs = 0
    osd_weight: dict[int, float] = {}
    osd_weight_total = 0.0
    for pool_id, pool in sorted(m.pools.items()):
        if only_pools and pool_id not in only_pools:
            continue
        total_pgs += pool.size * pool.pg_num
        ruleno = mapper_ref.find_rule(
            m.crush, pool.crush_rule, int(pool.type), pool.size
        )
        if ruleno < 0:
            continue
        pmap = get_rule_weight_osd_map(m.crush, ruleno)
        for osd, w in pmap.items():
            adjusted = m.get_weightf(osd) * w if osd < m.max_osd else 0.0
            if adjusted == 0.0:
                continue
            osd_weight[osd] = osd_weight.get(osd, 0.0) + adjusted
            osd_weight_total += adjusted
    if osd_weight_total == 0 or max_iter <= 0:
        return res
    pgs_per_weight = total_pgs / osd_weight_total

    # a membership build served from the shared ClusterState's cached
    # rows is NOT an O(PGs) build — it books state_rows_reused, and
    # build_state_seconds stays a true build-cost signal (the steady
    # profile criterion: rebalance rounds riding a warm state show no
    # build_state time at all).  "Served" means the provider actually
    # ANSWERED every pool: a provider that declines (working copy
    # diverged) falls back to the O(PGs) build, which must book as one.
    served = {"hit": 0, "miss": 0}

    def _counted_src(pid):
        rows = rows_source(pid)
        served["hit" if rows is not None else "miss"] += 1
        return rows

    src = _counted_src if rows_source is not None else None
    t0 = time.perf_counter()
    with obs.span(
        "balancer.build_state", backend=backend, pgs=total_pgs,
        reused=rows_source is not None,
    ):
        if backend == "device":
            st = DeviceState(
                m, osd_weight, pgs_per_weight, only_pools=only_pools,
                mesh=mesh, cache=device_cache, rows_source=src,
            )
        else:
            pgs_by_osd = _build_pgs_by_osd(m, only_pools, use_tpu,
                                           rows_source=src)
            st = SetState(pgs_by_osd, osd_weight, pgs_per_weight)
    if src is not None and not served["miss"] and served["hit"]:
        _L.inc("state_rows_reused")
    else:
        _L.observe("build_state_seconds", time.perf_counter() - t0)

    osd_deviation, stddev, cur_max_deviation = st.deviations()
    res.stddev, res.max_deviation = stddev, cur_max_deviation
    if cur_max_deviation <= max_deviation:
        return res

    skip_overfull = False
    iter_left = max_iter
    while iter_left > 0:
        iter_left -= 1
        _L.inc("rounds")
        with obs.span(
            "balancer.round", iteration=max_iter - iter_left
        ), _L.time("round_seconds"), _L.time("round_hist"):
            by_dev = sorted(
                osd_deviation.items(), key=lambda kv: (kv[1], kv[0])
            )
            overfull: set[int] = set()
            more_overfull: set[int] = set()
            underfull: list[int] = []
            more_underfull: list[int] = []
            for osd, d in reversed(by_dev):
                if d <= 0:
                    break
                if d > max_deviation:
                    overfull.add(osd)
                else:
                    more_overfull.add(osd)
            for osd, d in by_dev:
                if d >= 0:
                    break
                if d < -max_deviation:
                    underfull.append(osd)
                else:
                    more_underfull.append(osd)
            if not underfull and not overfull:
                break
            using_more_overfull = False
            if not overfull and underfull:
                overfull = more_overfull
                using_more_overfull = True

            to_skip: set = set()
            local_fallback_retried = 0

            while True:  # retry: label
                to_unmap: set = set()
                to_upmap: dict = {}
                txn = st.begin()
                found = False

                # ---- overfull pass ---------------------------------------
                if not (skip_overfull and underfull):
                    for osd, deviation in reversed(by_dev):
                        if deviation < 0:
                            break
                        if (not using_more_overfull
                                and deviation <= max_deviation):
                            break
                        pgs = [
                            pg for pg in st.pgs_of(osd)
                            if pg not in to_skip
                        ]
                        if aggressive:
                            rng.shuffle(pgs)  # equal (in)attention
                        # 1) drop existing remaps INTO this overfull osd
                        for pg in pgs:
                            items = m.pg_upmap_items.get(pg)
                            if items is None:
                                continue
                            new_items = []
                            for frm, to in items:
                                if to == osd:
                                    txn.move(pg, to, frm)
                                else:
                                    new_items.append((frm, to))
                            if not new_items:
                                to_unmap.add(pg)
                                found = True
                                break
                            elif len(new_items) != len(items):
                                to_upmap[pg] = new_items
                                found = True
                                break
                        if found:
                            break
                        # 2) add a new remapping pair
                        for pg in pgs:
                            if pg in m.pg_upmap:
                                continue
                            pool = m.get_pg_pool(pg.pool)
                            new_items = list(m.pg_upmap_items.get(pg, []))
                            if len(new_items) >= pool.size:
                                continue
                            existing: set[int] = set()
                            for frm, to in new_items:
                                existing.add(frm)
                                existing.add(to)
                            # raw mapping including existing upmaps
                            raw, _ = m._pg_to_raw_osds(pool, pg)
                            orig = list(raw)
                            m._apply_upmap(pool, pg, orig)
                            out = try_pg_upmap(
                                m, pg, overfull, underfull, more_underfull,
                                orig
                            )
                            if out is None or len(out) != len(orig):
                                continue
                            pos, max_dev = -1, 0.0
                            for i2 in range(len(out)):
                                if orig[i2] == out[i2]:
                                    continue
                                if (
                                    orig[i2] in existing
                                    or out[i2] in existing
                                ):
                                    continue
                                d = osd_deviation.get(orig[i2], 0.0)
                                if d > max_dev:
                                    max_dev, pos = d, i2
                            if pos != -1:
                                frm, to = orig[pos], out[pos]
                                txn.move(pg, frm, to)
                                new_items.append((frm, to))
                                to_upmap[pg] = new_items
                                found = True
                                break
                        if found:
                            break

                # ---- underfull pass --------------------------------------
                if not found:
                    for osd, deviation in by_dev:
                        if osd not in underfull:
                            break
                        if abs(deviation) < max_deviation:
                            break
                        candidates = [
                            (pg, items)
                            for pg, items in sorted(m.pg_upmap_items.items())
                            if pg not in to_skip
                            and (not only_pools or pg.pool in only_pools)
                        ]
                        if aggressive:
                            rng.shuffle(candidates)
                        for pg, items in candidates:
                            new_items = []
                            for frm, to in items:
                                if frm == osd:
                                    txn.move(pg, to, frm)
                                else:
                                    new_items.append((frm, to))
                            if not new_items:
                                to_unmap.add(pg)
                                found = True
                                break
                            elif len(new_items) != len(items):
                                to_upmap[pg] = new_items
                                found = True
                                break
                        if found:
                            break

                if not found:
                    if not aggressive:
                        iter_left = 0
                    elif not skip_overfull:
                        iter_left = 0
                    else:
                        skip_overfull = False
                    break  # out of retry loop

                # ---- test_change -----------------------------------------
                temp_dev, new_stddev, cur_max_deviation = txn.deviations()
                if new_stddev >= stddev:
                    _L.inc(
                        "changes_rejected", len(to_unmap) + len(to_upmap)
                    )
                    if not aggressive:
                        iter_left = 0
                        break
                    local_fallback_retried += 1
                    if local_fallback_retried >= local_fallback_retries:
                        skip_overfull = not skip_overfull
                        break
                    to_skip |= to_unmap
                    to_skip |= set(to_upmap)
                    continue  # goto retry

                stddev = new_stddev
                st.commit(txn)
                osd_deviation = temp_dev
                for pg in to_unmap:
                    del m.pg_upmap_items[pg]
                    res.old_pg_upmap_items.add(pg)
                    res.num_changed += 1
                for pg, items in to_upmap.items():
                    m.pg_upmap_items[pg] = items
                    res.new_pg_upmap_items[pg] = items
                    res.num_changed += 1
                _L.inc("changes_accepted", len(to_unmap) + len(to_upmap))
                _L.observe("stddev", stddev)
                _L.observe("max_deviation", cur_max_deviation)
                obs.counter("balancer.stddev", stddev)
                res.stddev = stddev
                res.max_deviation = cur_max_deviation
                if cur_max_deviation <= max_deviation:
                    iter_left = 0
                break  # exit retry loop, next outer iteration

    return res
