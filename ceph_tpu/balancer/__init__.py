from ceph_tpu.balancer.upmap import calc_pg_upmaps

__all__ = ["calc_pg_upmaps"]
