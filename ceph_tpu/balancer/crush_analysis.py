"""Tree-analysis helpers over CrushMap used by the upmap balancer.

Semantics ports of the CrushWrapper query surface the balancer depends on
(reference src/crush/CrushWrapper.cc): subtree_contains (:341),
get_parent_of_type (:1687), find_takes_by_rule, get_children_of_type,
get_rule_weight_osd_map (:??, weight map per TAKE, normalized then merged).
"""

from __future__ import annotations

from ceph_tpu.crush.types import CrushMap, RuleOp


def subtree_contains(m: CrushMap, root: int, item: int) -> bool:
    if root == item:
        return True
    if root >= 0:
        return False
    b = m.buckets.get(root)
    if b is None:
        return False
    return any(subtree_contains(m, c, item) for c in b.items)


def find_takes_by_rule(m: CrushMap, ruleno: int) -> list[int]:
    rule = m.rules[ruleno]
    return [a1 for op, a1, _ in rule.steps if op == RuleOp.TAKE]


def get_children_of_type(
    m: CrushMap, root: int, type_: int, include_shadow: bool = False
) -> list[int]:
    if root >= 0:
        return []
    b = m.buckets.get(root)
    if b is None:
        return []
    if b.type == type_:
        return [root]
    out: list[int] = []
    for c in b.items:
        if c >= 0:
            if type_ == 0:
                out.append(c)
        else:
            cb = m.buckets.get(c)
            if cb is not None and cb.type == type_:
                out.append(c)
            else:
                out.extend(get_children_of_type(m, c, type_))
    return out


def get_immediate_parent_id(m: CrushMap, item: int) -> int | None:
    for bid, b in m.buckets.items():
        if item in b.items:
            return bid
    return None


def get_parent_of_type(
    m: CrushMap, item: int, type_: int, ruleno: int = -1
) -> int:
    """reference CrushWrapper.cc:1687-1712."""
    if ruleno < 0:
        cur = item
        while True:
            p = get_immediate_parent_id(m, cur)
            if p is None:
                return 0
            cur = p
            b = m.buckets.get(cur)
            if b is not None and b.type == type_:
                return cur
    for root in find_takes_by_rule(m, ruleno):
        for cand in get_children_of_type(m, root, type_):
            if subtree_contains(m, cand, item):
                return cand
    return 0


def _take_weight_map(m: CrushMap, root: int, out: dict[int, float]) -> float:
    """Accumulate leaf crush-weights (float) under root; returns the sum
    (reference _get_take_weight_osd_map)."""
    total = 0.0
    b = m.buckets.get(root)
    if b is None:
        return 0.0
    for item, w in zip(b.items, b.weights):
        if item >= 0:
            wf = w / 0x10000
            out[item] = out.get(item, 0.0) + wf
            total += wf
        else:
            total += _take_weight_map(m, item, out)
    return total


def get_rule_weight_osd_map(m: CrushMap, ruleno: int) -> dict[int, float]:
    """Per-TAKE normalized weight maps, merged (reference
    get_rule_weight_osd_map)."""
    pmap: dict[int, float] = {}
    rule = m.rules[ruleno]
    for op, a1, _ in rule.steps:
        if op != RuleOp.TAKE:
            continue
        sub: dict[int, float] = {}
        if a1 >= 0:
            sub[a1] = 1.0
            s = 1.0
        else:
            s = _take_weight_map(m, a1, sub)
        if s > 0:
            for k, v in sub.items():
                pmap[k] = pmap.get(k, 0.0) + v / s
    return pmap
