"""Membership-state backends for the upmap balancer's greedy loop.

The reference optimizer (`OSDMap::calc_pg_upmaps`, reference
src/osd/OSDMap.cc:4634-5208) keeps a `map<osd, set<pg>>` of its OWN
bookkeeping — it never remaps after a change; membership evolves purely by
the discard/add pairs the greedy applies.  That bookkeeping is the state
interface here, with two implementations:

- SetState: dict-of-sets, bit-for-bit the semantics the oracle tests pin
  (small maps, CI).  Every change attempt copies the whole table, exactly
  like the reference's `temp_pgs_by_osd`.
- DeviceState: the 10M-PG/10k-OSD form.  Per-PG membership rows live ON
  DEVICE (one [pg_num, W] i32 tensor per pool, optionally sharded over a
  jax Mesh along the PG axis); the host keeps only O(OSDs) count/deviation
  vectors.  A change attempt is a tiny delta dict; `pgs_of` is a masked
  nonzero on device fetching only the matching PG indices.  Deviation
  totals are summed in ascending-osd order (the reference iterates a
  sorted std::map, src/osd/OSDMap.cc:4707).

Both expose:
    deviations() -> (dev: {osd: float}, sum_sq: float, max_abs: float)
    pgs_of(osd)  -> ascending list[PgId] of current members
    begin() -> txn;  txn.move(pg, frm, to);  txn.deviations();  commit(txn)

`move(pg, a, b)` = the reference's paired
`temp[a].discard(pg); temp[b].add(pg)`.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu import obs
from ceph_tpu.core import reduce
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.types import PgId

_L = obs.logger_for("balancer")
_L.add_u64("pgs_of_queries", "device membership queries (masked nonzero)")
_L.add_time_avg("pgs_of_seconds", "device membership query wall time")
_L.add_u64("txn_commits", "membership transactions committed")


class SetState:
    """dict-of-sets bookkeeping (reference-faithful small-scale backend)."""

    def __init__(self, pgs_by_osd: dict[int, set], osd_weight: dict[int, float],
                 pgs_per_weight: float):
        self.pbo = {o: s for o, s in pgs_by_osd.items() if o in osd_weight}
        for o in osd_weight:
            self.pbo.setdefault(o, set())
        self.osd_weight = osd_weight
        self.ppw = pgs_per_weight

    def _dev(self, pbo):
        # Summation order matters for float ties: both backends sum d^2 in
        # ascending-osd order via np.sum (the reference iterates a sorted
        # std::map, src/osd/OSDMap.cc:4707) so accept/reject decisions on
        # near-tie stddev comparisons cannot diverge between them.
        dev = {
            osd: len(pbo.get(osd, ())) - w * self.ppw
            for osd, w in self.osd_weight.items()
        }
        vals = np.asarray([dev[o] for o in sorted(dev)], np.float64)
        return dev, float(np.sum(vals * vals)), float(
            np.max(np.abs(vals), initial=0.0)
        )

    def deviations(self):
        return self._dev(self.pbo)

    def counts_np(self, n: int) -> np.ndarray:
        """Dense per-OSD membership counts i64[n] — the candidate-batch
        scorer's base vector (same numbers _dev derives deviations
        from)."""
        counts = np.zeros(n, np.int64)
        for osd, pgs in self.pbo.items():
            if 0 <= osd < n:
                counts[osd] = len(pgs)
        return counts

    def pgs_of(self, osd):
        return sorted(self.pbo.get(osd, ()))

    def begin(self):
        return _SetTxn(self)

    def commit(self, txn: "_SetTxn"):
        _L.inc("txn_commits")
        self.pbo = txn.temp


class _SetTxn:
    def __init__(self, st: SetState):
        self.st = st
        self.temp = {o: set(s) for o, s in st.pbo.items()}

    def move(self, pg, frm, to):
        self.temp.setdefault(frm, set()).discard(pg)
        self.temp.setdefault(to, set()).add(pg)

    def deviations(self):
        return self.st._dev(self.temp)


class DeviceState:
    """Device-resident membership rows + O(OSDs) host vectors.

    rows[pool] is the balancer's bookkeeping of which OSDs hold each PG
    (initialized from the batched pipeline's `up` result, evolved by
    `move` like the reference's set bookkeeping — NOT remapped).  With a
    mesh, rows shard over the PG axis and every query runs SPMD
    (ParallelPGMapper's pgid-range shards, reference
    src/osd/OSDMapMapping.h:18-140, as GSPMD partitions instead of
    threads)."""

    def __init__(self, m, osd_weight: dict[int, float],
                 pgs_per_weight: float, only_pools=None, mesh=None,
                 chunk: int | None = None, cache: dict | None = None,
                 rows_source=None):
        import jax
        import jax.numpy as jnp

        from ceph_tpu.osd.pipeline_jax import (
            DEFAULT_CHUNK,
            PoolMapper,
            overlay_fixup_rows,
        )

        self.jnp = jnp
        self.jax = jax
        self.osd_weight = dict(osd_weight)
        self.ppw = pgs_per_weight
        self.mesh = mesh
        self._weight_osds = np.asarray(sorted(self.osd_weight), np.int32)
        self._weight_vec = np.asarray(
            [self.osd_weight[o] for o in self._weight_osds], np.float64
        )
        self.max_osd = int(m.max_osd)
        self.rows: dict[int, object] = {}
        self.pg_num: dict[int, int] = {}
        chunk = chunk or DEFAULT_CHUNK
        self._sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._sharding = NamedSharding(mesh, P(mesh.axis_names[0], None))
        counts = jnp.zeros(self.max_osd, jnp.int64)
        for pid in sorted(m.pools):
            if only_pools and pid not in only_pools:
                continue
            # Map WITHOUT overlay tensors (a dense [pg_num] overlay upload
            # per call defeats the O(OSDs)-host-traffic design); the few
            # upmap-carrying PGs get exact host-computed rows scattered in
            # below.  Membership is content-based, so primary reordering
            # is irrelevant here.  `cache` (caller-owned dict) reuses the
            # compiled per-pool mapper across successive balancer rounds —
            # the kernel depends only on crush structure + bucket weights,
            # both fixed across a rebalance run; the per-OSD in/out/weight
            # vectors are refreshed from m on every build.
            n = m.pools[pid].pg_num
            rows = rows_source(pid) if rows_source is not None else None
            if rows is None:
                if cache is not None and pid in cache:
                    pm = cache[pid]
                    pm.refresh_dev()
                else:
                    pm = PoolMapper(m, pid, overlays=False)
                    if cache is not None:
                        cache[pid] = pm
                n = pm.spec.pg_num
                with obs.span("balancer.map_pool", pool=pid, pgs=n):
                    rows = pm.map_all_device(chunk)
                seeds, fix_rows = overlay_fixup_rows(
                    m, pid, int(rows.shape[1])
                )
                if len(seeds):
                    rows = rows.at[jnp.asarray(seeds)].set(
                        jnp.asarray(fix_rows)
                    )
            if mesh is not None:
                npad = -(-n // mesh.devices.size) * mesh.devices.size
                rows = rows[:min(n, rows.shape[0])]
                if npad > rows.shape[0]:
                    rows = jnp.concatenate([
                        rows,
                        jnp.full(
                            (npad - rows.shape[0], rows.shape[1]),
                            ITEM_NONE, rows.dtype,
                        ),
                    ])
                rows = jax.device_put(rows, self._sharding)
            self.rows[pid] = rows
            self.pg_num[pid] = n
            live = jnp.arange(rows.shape[0]) < n
            counts = counts + reduce.osd_histogram(
                rows, self.max_osd, live[:, None], dtype=jnp.int64
            )
        self.counts = np.array(counts)  # tiny fetch; writable
        self._pgs_cache: dict[int, list] = {}

    # -- deviations ------------------------------------------------------
    def _dev_from_counts(self, counts: np.ndarray):
        # ascending-osd np.sum — identical order/method to SetState._dev
        d = counts[self._weight_osds].astype(np.float64) \
            - self._weight_vec * self.ppw
        dev = {int(o): float(x) for o, x in zip(self._weight_osds, d)}
        return dev, float(np.sum(d * d)), float(np.max(np.abs(d), initial=0.0))

    def deviations(self):
        return self._dev_from_counts(self.counts)

    def counts_np(self, n: int) -> np.ndarray:
        """Dense per-OSD membership counts i64[n] (host mirror of the
        device rows' histogram; max_osd-bounded)."""
        out = np.zeros(n, np.int64)
        k = min(n, len(self.counts))
        out[:k] = self.counts[:k]
        return out

    # -- membership query ------------------------------------------------
    def pgs_of(self, osd):
        if osd in self._pgs_cache:
            return list(self._pgs_cache[osd])
        jnp = self.jnp
        out: list[PgId] = []
        total = int(self.counts[osd]) if 0 <= osd < self.max_osd else 0
        K = max(16, 1 << (total + 8).bit_length())
        _L.inc("pgs_of_queries")
        with obs.span("balancer.pgs_of", osd=osd), _L.time("pgs_of_seconds"):
            for pid in sorted(self.rows):
                rows = self.rows[pid]
                mask = jnp.any(rows == osd, axis=1)
                mask = mask & (jnp.arange(rows.shape[0]) < self.pg_num[pid])
                (idx,) = jnp.nonzero(mask, size=K, fill_value=-1)
                idx = np.asarray(idx)
                out.extend(PgId(pid, int(s)) for s in idx[idx >= 0])
        self._pgs_cache[osd] = out
        return list(out)

    # -- transactions ----------------------------------------------------
    def begin(self):
        return _DeviceTxn(self)

    def commit(self, txn: "_DeviceTxn"):
        _L.inc("txn_commits")
        jnp = self.jnp
        for (pid, seed), swaps in txn.ops.items():
            rows = self.rows[pid]
            row = rows[seed]
            for frm, to in swaps:
                row = jnp.where(row == frm, to, row)
            self.rows[pid] = rows.at[seed].set(row)
        for osd, delta in txn.delta.items():
            if 0 <= osd < self.max_osd:
                self.counts[osd] += delta
        touched = set(txn.delta)
        self._pgs_cache = {
            o: v for o, v in self._pgs_cache.items() if o not in touched
        }


class FlatDeviceState:
    """All selected pools' membership rows concatenated into ONE
    [N, Wmax] i32 tensor — the operand layout of the device-resident
    optimizer loop (`upmap_state_backend="device_loop"`).

    Built FROM a DeviceState (so row provenance — ClusterState
    rows_source, per-pool mapper cache, overlay fixups — is exactly the
    "device" backend's), then flattened: narrower pools pad their slot
    axis with ITEM_NONE, the global PG axis optionally pads to a
    multiple of the mesh device count and lands with a
    NamedSharding(P(axis, None)) placement, so the while_loop kernel's
    elementwise/scatter work partitions over the PG axis exactly like
    the PR 15 pipeline.  Host keeps only O(pools) metadata (offsets,
    pool ids) for the one readback at the end of a plan."""

    def __init__(self, st: DeviceState, mesh=None):
        jnp = st.jnp
        self.st = st
        self.mesh = mesh
        self.pools: list[int] = sorted(st.rows)
        self.W = max(
            (int(st.rows[p].shape[1]) for p in self.pools), default=1
        )
        parts, pidx, offs = [], [], [0]
        for i, pid in enumerate(self.pools):
            rows = st.rows[pid]
            n = st.pg_num[pid]
            rows = rows[:n]  # trim any per-pool mesh pad
            if int(rows.shape[1]) < self.W:
                rows = jnp.concatenate([
                    rows,
                    jnp.full((int(rows.shape[0]), self.W - int(
                        rows.shape[1])), ITEM_NONE, rows.dtype),
                ], axis=1)
            parts.append(rows)
            pidx.append(np.full(n, i, np.int32))
            offs.append(offs[-1] + n)
        self.n_total = int(offs[-1])
        rows = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        pool_idx = (pidx[0] if len(pidx) == 1 else np.concatenate(pidx)) \
            if pidx else np.zeros(0, np.int32)
        if mesh is not None:
            d = int(mesh.devices.size)
            npad = -(-max(self.n_total, 1) // d) * d
            if npad > self.n_total:
                rows = jnp.concatenate([
                    rows,
                    jnp.full((npad - self.n_total, self.W), ITEM_NONE,
                             rows.dtype),
                ])
                pool_idx = np.concatenate([
                    pool_idx,
                    np.full(npad - self.n_total, -1, np.int32),
                ])
            from jax.sharding import NamedSharding, PartitionSpec as P

            axis = mesh.axis_names[0]
            rows = st.jax.device_put(
                rows, NamedSharding(mesh, P(axis, None)))
        self.rows = rows
        self.pool_idx = pool_idx  # host i32[Np]; -1 = mesh padding
        self.offsets = np.asarray(offs, np.int64)

    def locate(self, gidx: int) -> tuple[int, int]:
        """global PG index -> (pool_id, seed) for the readback."""
        pos = int(np.searchsorted(self.offsets, gidx, side="right")) - 1
        return self.pools[pos], int(gidx - self.offsets[pos])


class _DeviceTxn:
    def __init__(self, st: DeviceState):
        self.st = st
        self.delta: dict[int, int] = {}
        self.ops: dict[tuple[int, int], list[tuple[int, int]]] = {}

    def move(self, pg, frm, to):
        self.delta[frm] = self.delta.get(frm, 0) - 1
        self.delta[to] = self.delta.get(to, 0) + 1
        self.ops.setdefault((pg.pool, pg.seed), []).append((frm, to))

    def deviations(self):
        counts = self.st.counts.copy()
        for osd, d in self.delta.items():
            if 0 <= osd < self.st.max_osd:
                counts[osd] += d
        return self.st._dev_from_counts(counts)
