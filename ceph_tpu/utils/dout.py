"""Leveled, per-subsystem debug logging (the `dout/ldout` pattern,
reference src/common/dout.h + per-subsystem levels in src/common/subsys.h).

Usage:
    log = subsys_logger("crush")
    log(10, "descend into", bucket_id)   # printed iff level(crush) >= 10

Line shape follows the reference log format (src/common/LogEntry.cc):

    2026-08-02T10:11:12.345678+0000 7f3a00c0 10 crush: descend into -2

i.e. ISO timestamp with microseconds and UTC offset, thread id (hex),
level, subsystem.  Levels follow the reference convention: 0/1 important,
5 normal detail, 10/20/30 increasingly verbose internals.  Configure
globally via set_subsys_level / CEPH_TPU_DEBUG env ("crush=10,osd=5"
syntax like --debug-crush).

The output stream is resolved at EVERY log call (never captured at logger
construction), so `set_output` redirects loggers created before the call.
"""

from __future__ import annotations

import os
import sys
import threading
import time

SUBSYS_DEFAULTS = {
    "crush": 1,
    "osd": 1,
    "ec": 1,
    "balancer": 1,
    "tester": 1,
    "native": 1,
    "sim": 1,
    "obs": 1,
    "runtime": 1,
    "serve": 1,
}

_levels = dict(SUBSYS_DEFAULTS)
_out = None  # None = sys.stderr resolved at call time


def _parse_env() -> None:
    spec = os.environ.get("CEPH_TPU_DEBUG", "")
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, lvl = part.partition("=")
        try:
            _levels[name.strip()] = int(lvl)
        except ValueError:
            pass


_parse_env()


def set_subsys_level(subsys: str, level: int) -> None:
    _levels[subsys] = level


def get_subsys_level(subsys: str) -> int:
    return _levels.get(subsys, 1)


def set_output(stream) -> None:
    """Redirect ALL subsystem loggers (including ones already created);
    None restores the default (current sys.stderr)."""
    global _out
    _out = stream


def _current_out():
    return _out if _out is not None else sys.stderr


def _timestamp() -> str:
    t = time.time()
    base = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(t))
    tz = time.strftime("%z") or "+0000"
    return f"{base}.{int(t % 1 * 1e6):06d}{tz}"


class subsys_logger:
    __slots__ = ("subsys",)

    def __init__(self, subsys: str):
        if subsys not in _levels:
            _levels[subsys] = 1
        self.subsys = subsys

    def __call__(self, level: int, *args) -> None:
        if level <= _levels.get(self.subsys, 1):
            print(
                f"{_timestamp()} {threading.get_ident():x} "
                f"{level:2d} {self.subsys}:",
                *args,
                file=_current_out(),
            )

    def enabled(self, level: int) -> bool:
        return level <= _levels.get(self.subsys, 1)
