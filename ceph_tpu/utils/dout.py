"""Leveled, per-subsystem debug logging (the `dout/ldout` pattern,
reference src/common/dout.h + per-subsystem levels in src/common/subsys.h).

Usage:
    log = subsys_logger("crush")
    log(10, "descend into", bucket_id)   # printed iff level(crush) >= 10

Levels follow the reference convention: 0/1 important, 5 normal detail,
10/20/30 increasingly verbose internals.  Configure globally via
set_subsys_level / CEPH_TPU_DEBUG env ("crush=10,osd=5" syntax like
--debug-crush).
"""

from __future__ import annotations

import os
import sys
import time

SUBSYS_DEFAULTS = {
    "crush": 1,
    "osd": 1,
    "ec": 1,
    "balancer": 1,
    "tester": 1,
    "native": 1,
    "sim": 1,
}

_levels = dict(SUBSYS_DEFAULTS)
_out = sys.stderr


def _parse_env() -> None:
    spec = os.environ.get("CEPH_TPU_DEBUG", "")
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, lvl = part.partition("=")
        try:
            _levels[name.strip()] = int(lvl)
        except ValueError:
            pass


_parse_env()


def set_subsys_level(subsys: str, level: int) -> None:
    _levels[subsys] = level


def get_subsys_level(subsys: str) -> int:
    return _levels.get(subsys, 1)


def set_output(stream) -> None:
    global _out
    _out = stream


class subsys_logger:
    __slots__ = ("subsys",)

    def __init__(self, subsys: str):
        if subsys not in _levels:
            _levels[subsys] = 1
        self.subsys = subsys

    def __call__(self, level: int, *args) -> None:
        if level <= _levels.get(self.subsys, 1):
            ts = time.strftime("%H:%M:%S")
            print(
                f"{ts} {level:2d} {self.subsys}:",
                *args,
                file=_out,
            )

    def enabled(self, level: int) -> bool:
        return level <= _levels.get(self.subsys, 1)
