"""Perf counters — in-process metrics, dumpable as JSON.

Mirrors the reference's per-daemon counter surface
(reference src/common/perf_counters.h: u64 counters, u64 averages
(sum+count pairs), time averages, histograms; exposed by `ceph daemon
<sock> perf dump` via the admin socket, reference
src/common/admin_socket.cc).  Here: a registry of named counters with the
same shapes, a `dump()` that matches the perf-dump JSON layout, and a
`logger_for` helper the hot paths use.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class _Counter:
    kind: str  # u64 | avg | time_avg | histogram
    value: int = 0
    sum: float = 0.0
    count: int = 0
    buckets: list[int] = field(default_factory=list)
    bucket_bounds: list[float] = field(default_factory=list)
    desc: str = ""


class PerfCounters:
    """One named group of counters (a daemon's `logger` equivalent)."""

    def __init__(self, name: str):
        self.name = name
        self._c: dict[str, _Counter] = {}
        self._lock = threading.Lock()

    # -- declaration -------------------------------------------------------
    def add_u64(self, key: str, desc: str = "") -> None:
        self._c[key] = _Counter("u64", desc=desc)

    def add_avg(self, key: str, desc: str = "") -> None:
        self._c[key] = _Counter("avg", desc=desc)

    def add_time_avg(self, key: str, desc: str = "") -> None:
        self._c[key] = _Counter("time_avg", desc=desc)

    def add_histogram(
        self, key: str, bounds: list[float], desc: str = ""
    ) -> None:
        c = _Counter("histogram", desc=desc)
        c.bucket_bounds = list(bounds)
        c.buckets = [0] * (len(bounds) + 1)
        self._c[key] = c

    # -- updates -----------------------------------------------------------
    def inc(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._c[key].value += n

    def set(self, key: str, v: int) -> None:
        with self._lock:
            self._c[key].value = v

    def observe(self, key: str, v: float) -> None:
        with self._lock:
            c = self._c[key]
            if c.kind == "histogram":
                i = 0
                while i < len(c.bucket_bounds) and v > c.bucket_bounds[i]:
                    i += 1
                c.buckets[i] += 1
            c.sum += v
            c.count += 1

    def time(self, key: str):
        """Context manager recording elapsed seconds into a time_avg."""
        pc = self

        class _T:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                pc.observe(key, time.perf_counter() - self.t0)
                return False

        return _T()

    # -- dump (perf-dump JSON layout) ---------------------------------------
    def dump(self) -> dict:
        out: dict = {}
        with self._lock:
            for key, c in self._c.items():
                if c.kind == "u64":
                    out[key] = c.value
                elif c.kind in ("avg", "time_avg"):
                    out[key] = {
                        "avgcount": c.count,
                        "sum": c.sum,
                        "avgtime" if c.kind == "time_avg" else "avg": (
                            c.sum / c.count if c.count else 0.0
                        ),
                    }
                else:
                    out[key] = {
                        "bounds": c.bucket_bounds,
                        "buckets": list(c.buckets),
                        "sum": c.sum,
                        "count": c.count,
                    }
        return out


_registry: dict[str, PerfCounters] = {}
_registry_lock = threading.Lock()


def logger_for(name: str) -> PerfCounters:
    with _registry_lock:
        pc = _registry.get(name)
        if pc is None:
            pc = _registry[name] = PerfCounters(name)
        return pc


def perf_dump() -> dict:
    """All groups — the `ceph daemon ... perf dump` shape."""
    with _registry_lock:
        return {name: pc.dump() for name, pc in _registry.items()}


def reset() -> None:
    with _registry_lock:
        _registry.clear()
