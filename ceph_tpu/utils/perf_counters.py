"""Perf counters — in-process metrics, dumpable as JSON.

Mirrors the reference's per-daemon counter surface
(reference src/common/perf_counters.h: u64 counters, u64 averages
(sum+count pairs), time averages, histograms; exposed by `ceph daemon
<sock> perf dump` via the admin socket, reference
src/common/admin_socket.cc).  Here: a registry of named counters with the
same shapes, a `dump()` that matches the perf-dump JSON layout, and a
`logger_for` helper the hot paths use.  One kind is ours, not the
reference's: `quantile` — a log-bucketed timing histogram whose dump
carries estimated p50/p90/p99 (ceph_tpu.obs.quantiles), the tail-latency
surface the serve-stage roadmap item budgets against.

Declarations are idempotent (re-declaring a key with the same kind keeps
the live counter — hot paths declare at import time and may be reloaded),
and updates to undeclared keys raise `UndeclaredCounterError` naming the
group and key instead of a bare KeyError.

`perf reset` semantics: `reset_values()` zeroes every counter but keeps
the declarations (the reference's `perf reset all`); `reset()` (test
isolation) does the same — declarations are made at import time by
module globals, so they are never dropped, only zeroed.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

KINDS = ("u64", "avg", "time_avg", "histogram", "quantile")


class UndeclaredCounterError(KeyError):
    """An inc/set/observe hit a key that was never declared."""


class CounterKindError(ValueError):
    """A declaration or update conflicts with the counter's kind."""


@dataclass
class _Counter:
    kind: str  # u64 | avg | time_avg | histogram | quantile
    value: int = 0
    sum: float = 0.0
    count: int = 0
    buckets: list[int] = field(default_factory=list)
    bucket_bounds: list[float] = field(default_factory=list)
    desc: str = ""
    # quantile kind only: observed extrema tighten the open-ended first
    # and overflow buckets of the dump-time estimate
    vmin: float = float("inf")
    vmax: float = float("-inf")


class _Timer:
    """Prebuilt timing context manager — `time()` sits inside the code
    being measured, so it must not allocate a type object per call."""

    __slots__ = ("pc", "key", "t0")

    def __init__(self, pc: "PerfCounters", key: str):
        self.pc = pc
        self.key = key

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.pc.observe(self.key, time.perf_counter() - self.t0)
        return False


class PerfCounters:
    """One named group of counters (a daemon's `logger` equivalent)."""

    def __init__(self, name: str):
        self.name = name
        self._c: dict[str, _Counter] = {}
        self._lock = threading.Lock()

    # -- declaration -------------------------------------------------------
    def _declare(
        self, key: str, kind: str, desc: str,
        bounds: list[float] | None = None,
    ) -> _Counter:
        with self._lock:
            c = self._c.get(key)
            if c is not None:
                if c.kind != kind:
                    raise CounterKindError(
                        f"perf counter '{self.name}.{key}' already declared "
                        f"as {c.kind}, cannot redeclare as {kind}"
                    )
                if bounds is not None and list(bounds) != c.bucket_bounds:
                    raise CounterKindError(
                        f"perf counter '{self.name}.{key}' already declared "
                        f"with bounds {c.bucket_bounds}, cannot redeclare "
                        f"with {bounds}"
                    )
                if desc:
                    c.desc = desc
                return c  # idempotent: keep the live counter + its values
            c = _Counter(kind, desc=desc)
            if bounds is not None:
                # under the lock: a half-initialized histogram must never
                # be observable
                c.bucket_bounds = list(bounds)
                c.buckets = [0] * (len(bounds) + 1)
            self._c[key] = c
            return c

    def add_u64(self, key: str, desc: str = "") -> None:
        self._declare(key, "u64", desc)

    def add_avg(self, key: str, desc: str = "") -> None:
        self._declare(key, "avg", desc)

    def add_time_avg(self, key: str, desc: str = "") -> None:
        self._declare(key, "time_avg", desc)

    def add_histogram(
        self, key: str, bounds: list[float], desc: str = ""
    ) -> None:
        self._declare(key, "histogram", desc, bounds=bounds)

    def add_quantile(
        self, key: str, desc: str = "", bounds: list[float] | None = None
    ) -> None:
        """A log-bucketed timing histogram whose dump carries estimated
        p50/p90/p99 (see ceph_tpu.obs.quantiles).  Default bounds cover
        1 µs .. 100 s at 4 buckets/decade; observe seconds into it
        (observe()/time() both work)."""
        if bounds is None:
            # lazy: perf_counters must not import the obs package at
            # module load (obs imports this module)
            from ceph_tpu.obs.quantiles import DEFAULT_BOUNDS

            bounds = list(DEFAULT_BOUNDS)
        self._declare(key, "quantile", desc, bounds=bounds)

    def _get(self, key: str) -> _Counter:
        try:
            return self._c[key]
        except KeyError:
            raise UndeclaredCounterError(
                f"perf counter '{self.name}.{key}' is not declared "
                "(declare it first with add_u64/add_avg/add_time_avg/"
                "add_histogram/add_quantile)"
            ) from None

    # -- updates -----------------------------------------------------------
    def inc(self, key: str, n: int = 1) -> None:
        with self._lock:
            c = self._get(key)
            if c.kind != "u64":
                raise CounterKindError(
                    f"perf counter '{self.name}.{key}' is {c.kind}; "
                    "inc() needs a u64 (use observe() instead)"
                )
            c.value += n

    def set(self, key: str, v: int) -> None:
        with self._lock:
            c = self._get(key)
            if c.kind != "u64":
                raise CounterKindError(
                    f"perf counter '{self.name}.{key}' is {c.kind}; "
                    "set() needs a u64"
                )
            c.value = v

    def observe(self, key: str, v: float) -> None:
        with self._lock:
            c = self._get(key)
            if c.kind == "u64":
                raise CounterKindError(
                    f"perf counter '{self.name}.{key}' is u64; observe() "
                    "needs avg/time_avg/histogram/quantile (use inc())"
                )
            if c.kind in ("histogram", "quantile"):
                i = 0
                while i < len(c.bucket_bounds) and v > c.bucket_bounds[i]:
                    i += 1
                c.buckets[i] += 1
                if c.kind == "quantile":
                    if v < c.vmin:
                        c.vmin = v
                    if v > c.vmax:
                        c.vmax = v
            c.sum += v
            c.count += 1

    def merge_histogram(self, key: str, counts: list[int],
                        values: list[float] | None = None) -> None:
        """Fold a precomputed histogram into a histogram counter:
        `counts[i]` observations of `values[i]` (default: value == i —
        the integer-bounds shape the placement choose_tries counter
        uses, where device-reduced retry histograms arrive already
        bucketed).  Exact when each value equals a declared bound; one
        call per device fetch instead of O(observations) observe()s."""
        with self._lock:
            c = self._get(key)
            if c.kind != "histogram":
                raise CounterKindError(
                    f"perf counter '{self.name}.{key}' is {c.kind}; "
                    "merge_histogram() needs a histogram"
                )
            for i, n in enumerate(counts):
                if not n:
                    continue
                v = values[i] if values is not None else float(i)
                j = 0
                while j < len(c.bucket_bounds) and v > c.bucket_bounds[j]:
                    j += 1
                c.buckets[j] += int(n)
                c.sum += v * int(n)
                c.count += int(n)

    def time(self, key: str) -> "_Timer":
        """Context manager recording elapsed seconds into a time_avg."""
        return _Timer(self, key)

    # -- dump (perf-dump JSON layout) ---------------------------------------
    def dump(self) -> dict:
        """Values in the reference perf-dump shape: u64 as bare ints, avg
        as {avgcount, sum}, time_avg as {avgcount, sum, avgtime},
        histogram as bounds+buckets+sum+count."""
        out: dict = {}
        with self._lock:
            for key, c in self._c.items():
                if c.kind == "u64":
                    out[key] = c.value
                elif c.kind == "avg":
                    out[key] = {"avgcount": c.count, "sum": c.sum}
                elif c.kind == "time_avg":
                    out[key] = {
                        "avgcount": c.count,
                        "sum": c.sum,
                        "avgtime": c.sum / c.count if c.count else 0.0,
                    }
                elif c.kind == "histogram":
                    out[key] = {
                        "bounds": c.bucket_bounds,
                        "buckets": list(c.buckets),
                        "sum": c.sum,
                        "count": c.count,
                    }
                else:  # quantile: histogram shape + dump-time estimates
                    from ceph_tpu.obs.quantiles import summarize

                    vmin = c.vmin if c.count else None
                    vmax = c.vmax if c.count else None
                    out[key] = {
                        "bounds": c.bucket_bounds,
                        "buckets": list(c.buckets),
                        "sum": c.sum,
                        "count": c.count,
                        "min": 0.0 if vmin is None else vmin,
                        "max": 0.0 if vmax is None else vmax,
                        **summarize(
                            c.bucket_bounds, c.buckets, vmin, vmax
                        ),
                    }
        return out

    def schema(self) -> dict:
        """The `perf schema` shape: kind + description per key."""
        with self._lock:
            return {
                key: {"type": c.kind, "description": c.desc}
                for key, c in self._c.items()
            }

    def reset_values(self) -> None:
        """Zero every counter, keep the declarations (`perf reset all`)."""
        with self._lock:
            for c in self._c.values():
                c.value = 0
                c.sum = 0.0
                c.count = 0
                c.buckets = [0] * len(c.buckets)
                c.vmin = float("inf")
                c.vmax = float("-inf")


_registry: dict[str, PerfCounters] = {}
_registry_lock = threading.Lock()


def logger_for(name: str) -> PerfCounters:
    with _registry_lock:
        pc = _registry.get(name)
        if pc is None:
            pc = _registry[name] = PerfCounters(name)
        return pc


def perf_dump() -> dict:
    """All groups — the `ceph daemon ... perf dump` shape."""
    with _registry_lock:
        return {name: pc.dump() for name, pc in sorted(_registry.items())}


def perf_schema() -> dict:
    """All groups' declarations — the `perf schema` shape."""
    with _registry_lock:
        return {name: pc.schema() for name, pc in sorted(_registry.items())}


def reset_values() -> None:
    """Zero every counter in every group, keeping declarations."""
    with _registry_lock:
        for pc in _registry.values():
            pc.reset_values()


def reset() -> None:
    """Test isolation: zero every counter in every group.

    Deliberately does NOT drop the registry dict: hot-path modules bind
    `logger_for(...)` to a module global at import time, and import-time
    declarations cannot re-run — dropping the dict would orphan those
    live groups, silently removing them from every later perf dump.
    Declarations are idempotent, so a test re-declaring its keys on a
    zeroed group gets exactly the clean slate it wants."""
    reset_values()
