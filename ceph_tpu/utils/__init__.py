from ceph_tpu.utils.platform import ensure_jax_backend

__all__ = ["ensure_jax_backend"]
