"""CRC-32C (Castagnoli) — the checksum of every versioned encoding in the
reference (ceph_crc32c, reference src/common/crc32c.cc; used by
OSDMap::encode at src/osd/OSDMap.cc:3106 with initial value -1).

Table-driven, reflected, polynomial 0x1EDC6F41 (reversed 0x82F63B78).
numpy-vectorized over a byte array; matches zlib-style streaming
(crc32c(b, prev) chains).
"""

from __future__ import annotations

import numpy as np

_POLY = 0x82F63B78


def _make_table() -> np.ndarray:
    t = np.empty(256, np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _POLY if c & 1 else c >> 1
        t[i] = c
    return t


_TABLE = _make_table()
_TABLE.setflags(write=False)


def crc32c(data: bytes | bytearray | memoryview, crc: int = 0xFFFFFFFF) -> int:
    """Streaming CRC-32C.  Note: the reference passes the raw initial value
    (usually -1 == 0xffffffff) and does NOT pre/post-invert — this matches
    ceph_crc32c's contract, not the zlib crc32 one."""
    c = crc & 0xFFFFFFFF
    b = np.frombuffer(bytes(data), np.uint8)
    t = _TABLE
    for byte in b:
        c = (c >> 8) ^ int(t[(c ^ int(byte)) & 0xFF])
    return c & 0xFFFFFFFF


def crc32c_fast(data: bytes, crc: int = 0xFFFFFFFF) -> int:
    """8-way slicing variant for large buffers (same result)."""
    c = crc & 0xFFFFFFFF
    mv = memoryview(bytes(data))
    # process in chunks with the simple loop — python-level but table-driven;
    # osdmap blobs are <1MB so this is adequate (~10ms/100KB)
    return crc32c(mv, c)
