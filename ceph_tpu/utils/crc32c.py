"""CRC-32C (Castagnoli) — the checksum of every versioned encoding in the
reference (ceph_crc32c, reference src/common/crc32c.cc; used by
OSDMap::encode at src/osd/OSDMap.cc:3106 with initial value -1).

Table-driven, reflected, polynomial 0x1EDC6F41 (reversed 0x82F63B78).
Two engines: a byte-at-a-time loop and a slicing-by-8 variant (the same
technique as the reference's crc32c_sctp fallback, 8 lookup tables / 8
bytes per iteration) used automatically for larger buffers.  Streaming:
crc32c(b2, crc32c(b1)) == crc32c(b1+b2).
"""

from __future__ import annotations

_POLY = 0x82F63B78


def _make_tables(n: int = 8) -> list[list[int]]:
    t0 = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _POLY if c & 1 else c >> 1
        t0.append(c)
    tables = [t0]
    for _ in range(1, n):
        prev = tables[-1]
        tables.append([t0[v & 0xFF] ^ (v >> 8) for v in prev])
    return tables


_TABLES = _make_tables()
_T0 = _TABLES[0]


def _crc_bytes(b: bytes, c: int) -> int:
    t0 = _T0
    for byte in b:
        c = (c >> 8) ^ t0[(c ^ byte) & 0xFF]
    return c


def crc32c_fast(data: bytes | bytearray | memoryview,
                crc: int = 0xFFFFFFFF) -> int:
    """Slicing-by-8: one 64-bit load + 8 table lookups per 8 input bytes
    (~8x the scalar loop on CPython)."""
    c = crc & 0xFFFFFFFF
    b = bytes(data)
    n8 = len(b) // 8 * 8
    t7, t6, t5, t4, t3, t2, t1, t0 = _TABLES[::-1]
    for i in range(0, n8, 8):
        q = int.from_bytes(b[i:i + 8], "little") ^ c
        c = (
            t7[q & 0xFF]
            ^ t6[(q >> 8) & 0xFF]
            ^ t5[(q >> 16) & 0xFF]
            ^ t4[(q >> 24) & 0xFF]
            ^ t3[(q >> 32) & 0xFF]
            ^ t2[(q >> 40) & 0xFF]
            ^ t1[(q >> 48) & 0xFF]
            ^ t0[(q >> 56) & 0xFF]
        )
    return _crc_bytes(b[n8:], c) & 0xFFFFFFFF


_native = None
_native_checked = False


def _load_native():
    global _native, _native_checked
    if not _native_checked:
        _native_checked = True
        try:
            from ceph_tpu.native import load_crc

            _native = load_crc()
        except Exception:
            _native = None
    return _native


def crc32c(data: bytes | bytearray | memoryview,
           crc: int = 0xFFFFFFFF) -> int:
    """Streaming CRC-32C.  Note: the reference passes the raw initial value
    (usually -1 == 0xffffffff) and does NOT pre/post-invert — this matches
    ceph_crc32c's contract, not the zlib crc32 one.

    Large buffers go through the native kernel (hardware SSE4.2 CRC32C
    when available — the ceph_crc32c_intel_fast role; native/crc.cpp),
    small ones through the Python table loop."""
    b = bytes(data)
    if len(b) >= 256:
        lib = _load_native()
        if lib is not None:
            return int(lib.ceph_tpu_crc32c(crc & 0xFFFFFFFF, b, len(b)))
        return crc32c_fast(b, crc)
    return _crc_bytes(b, crc & 0xFFFFFFFF) & 0xFFFFFFFF
