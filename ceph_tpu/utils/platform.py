"""Runtime platform guard.

The session's JAX may be pinned (via env) to an accelerator plugin whose
transport is unavailable (e.g. the TPU tunnel is down).  Library code and
CLIs call `ensure_jax_backend()` before the first device op; it routes
through the runtime degradation ladder (ceph_tpu.runtime): probe the
configured platform in-process, fall back to CPU with a warning instead
of crashing — every kernel here runs correctly (just slower) on the host
backend.  Entry points that can afford a subprocess watchdog (bench.py,
long-running CLIs) call `runtime.acquire_backend()` directly; this is
the cheap cached in-process path for library internals, and its
provenance still lands in `runtime.last_provenance()` and the `runtime`
perf-counter group.
"""

from __future__ import annotations

import warnings

_checked: str | None = None


def ensure_jax_backend() -> str:
    """Return the usable jax backend name, falling back down the runtime
    ladder (configured platform -> cpu) if the configured platform cannot
    initialize.  Cached: the ladder walk happens once per process."""
    global _checked
    if _checked is not None:
        return _checked
    from ceph_tpu import runtime

    # in-process (watchdog=False): library code must not fork, and an
    # in-process probe cannot desync this process's jax config from the
    # verdict.  x64 enforcement (load-bearing: s64 straw2 draws, u64 ln
    # math) lives in the probe/activation path.
    # jax-only ladder: drop the jax-free "native" rung, but keep this
    # module's contract — fall back to CPU, never raise — by ensuring
    # cpu is still the terminal rung after filtering (a user ladder like
    # "tpu,native" would otherwise filter down to just "tpu")
    ladder = [r for r in runtime.default_ladder() if r != "native"]
    if "cpu" not in ladder:
        ladder.append("cpu")
    # attempts=1: an in-process init failure is a plugin RuntimeError,
    # not a transient transport flake — degrade immediately, as the
    # pre-runtime guard always did
    info = runtime.acquire_backend(ladder=ladder, watchdog=False,
                                   attempts=1)
    if info.fallback_reason:
        warnings.warn(
            f"configured jax platform unavailable "
            f"({info.fallback_reason}); falling back to "
            f"{info.backend}",
            RuntimeWarning,
            stacklevel=2,
        )
    _checked = info.backend
    return _checked
