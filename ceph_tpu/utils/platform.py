"""Runtime platform guard.

The session's JAX may be pinned (via env) to an accelerator plugin whose
transport is unavailable (e.g. the TPU tunnel is down).  Library code and
CLIs call `ensure_jax_backend()` before the first device op: if the
configured platform fails to initialize, fall back to CPU with a warning
instead of crashing — every kernel here runs correctly (just slower) on the
host backend.
"""

from __future__ import annotations

import warnings

_checked: str | None = None


def ensure_jax_backend() -> str:
    """Return the usable jax backend name, falling back to CPU if the
    configured platform cannot initialize."""
    global _checked
    if _checked is not None:
        return _checked
    import jax

    # x64 is load-bearing (s64 straw2 draws, u64 ln math): another library
    # may have imported jax after mutating the env, or flipped the flag —
    # a silent 32-bit downcast would produce wrong placements, so force it.
    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)
    try:
        jax.devices()
        _checked = jax.default_backend()
    except RuntimeError as e:
        warnings.warn(
            f"configured jax platform unavailable ({e}); "
            "falling back to CPU",
            RuntimeWarning,
            stacklevel=2,
        )
        jax.config.update("jax_platforms", "cpu")
        jax.devices()
        _checked = "cpu"
    return _checked
