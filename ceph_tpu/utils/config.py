"""Config/option system — declared options with layered overrides.

Mirrors the reference's shape (reference src/common/options/global.yaml.in
declares options with type/level/default/min-max/enum, code-generated into
Option tables by y2c.py; md_config_t in src/common/config.cc layers
defaults < conf file < env < CLI overrides and notifies observers):

- options are declared in OPTIONS below (the subset this framework uses),
- Config resolves defaults < config file (ini-ish "key = value") <
  environment (CEPH_TPU_<KEY>) < programmatic set_val,
- observers get (name, new_value) callbacks on live updates.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable


@dataclass(frozen=True)
class Option:
    name: str
    type: type
    default: Any
    level: str = "advanced"
    desc: str = ""
    min: float | None = None
    max: float | None = None
    enum: tuple | None = None


OPTIONS: dict[str, Option] = {
    o.name: o
    for o in [
        # balancer knobs (reference common/options/global.yaml.in:
        # osd_calc_pg_upmaps_aggressively etc., read at OSDMap.cc:4735)
        Option("osd_calc_pg_upmaps_aggressively", bool, True,
               desc="try harder to optimize upmaps"),
        Option("osd_calc_pg_upmaps_local_fallback_retries", int, 100,
               desc="candidate retries per balancer iteration"),
        Option("upmap_max_deviation", int, 5,
               desc="deviation below which a PG distribution is perfect"),
        # mapper / tester
        Option("crush_backend", str, "jax",
               enum=("jax", "native", "ref"),
               desc="default batched mapping backend"),
        Option("mapper_batch_threads", int, 0,
               desc="native mapper threads (0 = hardware)"),
        # erasure coding
        Option("ec_backend", str, "numpy",
               enum=("numpy", "native", "jax"),
               desc="default erasure-code engine"),
        Option("osd_pool_default_size", int, 3, min=1, max=32),
        Option("osd_pool_default_pg_num", int, 32, min=1),
        Option("osd_crush_chooseleaf_type", int, 1,
               desc="chooseleaf failure-domain type for simple maps"),
        # logging
        Option("log_level", int, 1, min=0, max=20),
    ]
}

ENV_PREFIX = "CEPH_TPU_"


class ConfigError(ValueError):
    pass


def _coerce(opt: Option, raw: Any) -> Any:
    if isinstance(raw, str):
        if opt.type is bool:
            v: Any = raw.strip().lower() in ("1", "true", "yes", "on")
        elif opt.type is int:
            v = int(raw)
        elif opt.type is float:
            v = float(raw)
        else:
            v = raw
    else:
        v = opt.type(raw)
    if opt.enum is not None and v not in opt.enum:
        raise ConfigError(
            f"{opt.name}={v!r} not in {opt.enum}"
        )
    if opt.min is not None and v < opt.min:
        raise ConfigError(f"{opt.name}={v} < min {opt.min}")
    if opt.max is not None and v > opt.max:
        raise ConfigError(f"{opt.name}={v} > max {opt.max}")
    return v


class Config:
    """Layered option resolution + observers."""

    def __init__(self, conf_file: str | None = None, env: bool = True):
        self._values: dict[str, Any] = {}
        self._observers: list[Callable[[str, Any], None]] = []
        if conf_file:
            self.load_file(conf_file)
        if env:
            self._load_env()

    def _load_env(self) -> None:
        for name, opt in OPTIONS.items():
            # the CEPH_TPU_<OPTION> family is documented by the OPTIONS
            # table above, not the knob registry (one entry per Option)
            raw = os.environ.get(ENV_PREFIX + name.upper())  # graftlint: disable=env-knob
            if raw is not None:
                self._values[name] = _coerce(opt, raw)

    def load_file(self, path: str) -> None:
        with open(path) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line or "=" not in line:
                    continue
                k, _, v = line.partition("=")
                k = k.strip().replace(" ", "_")
                if k in OPTIONS:
                    self._values[k] = _coerce(OPTIONS[k], v.strip())

    def get(self, name: str) -> Any:
        opt = OPTIONS.get(name)
        if opt is None:
            raise ConfigError(f"unknown option {name!r}")
        return self._values.get(name, opt.default)

    def set_val(self, name: str, value: Any) -> None:
        opt = OPTIONS.get(name)
        if opt is None:
            raise ConfigError(f"unknown option {name!r}")
        v = _coerce(opt, value)
        self._values[name] = v
        for cb in self._observers:
            cb(name, v)

    def add_observer(self, cb: Callable[[str, Any], None]) -> None:
        self._observers.append(cb)

    def show_config(self) -> dict[str, Any]:
        return {name: self.get(name) for name in sorted(OPTIONS)}


_global: Config | None = None


def global_config() -> Config:
    global _global
    if _global is None:
        _global = Config()
    return _global
