"""CrushTester — the batched test driver behind `crushtool --test`.

Semantics-compatible with the reference's tester (reference
src/crush/CrushTester.cc:477-730): per rule × numrep × x, run the mapping,
accumulate per-device utilization, result-size histogram, bad mappings, and
optional RNG-simulated placement for comparison (random_placement,
CrushTester.cc:260).  Output lines match the reference's formatting so cram
transcripts stay comparable.

The x-loop — the reference's single-threaded hot loop (1 `crush_do_rule`
per PG) — runs here as ONE vmapped XLA call per (rule, numrep) through
ceph_tpu.crush.mapper_jax (`backend="jax"`), or through the pure-Python
host mapper for differential checks (`backend="ref"`).
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field

import numpy as np

from ceph_tpu.core.rjenkins import crush_hash32_2
from ceph_tpu.crush import mapper_ref
from ceph_tpu.crush.types import CrushMap, ITEM_NONE


def _vec(out) -> str:
    """C++ operator<< for vector<int>: [a,b,c]."""
    return "[" + ",".join(str(int(o)) for o in out) + "]"


@dataclass
class TesterConfig:
    __test__ = False  # not a test class, despite the Test* name (pytest)

    min_x: int = 0
    max_x: int = 1023
    rule: int = -1  # -1 = all rules
    min_rep: int = -1
    max_rep: int = -1
    num_rep: int = -1
    pool_id: int = -1
    weights: dict[int, int] = field(default_factory=dict)  # osd -> 16.16
    backend: str = "jax"  # jax | ref
    simulate: bool = False
    show_mappings: bool = False
    show_bad_mappings: bool = False
    show_utilization: bool = False
    show_utilization_all: bool = False
    show_statistics: bool = False
    # per-placement retry histogram (reference src/crush/mapper.c:640-643
    # choose_tries bookkeeping + CrushTester's --show-choose-tries dump).
    # Single source of truth: the device diagnostics planes
    # (mapper_jax with_diag -> crush.explain.device_choose_tries) when
    # the jax backend's compiled plan is diag-exact — bit-identical to
    # the host collection; other backends, inexact plans, and
    # fast-window-flagged lanes route through the instrumented host
    # reference mapper.
    show_choose_tries: bool = False


class CrushTester:
    def __init__(self, m: CrushMap, cfg: TesterConfig, out=None):
        self.m = m
        self.cfg = cfg
        self.out = out if out is not None else sys.stdout
        self.weight = [0x10000] * m.max_devices
        for osd, w in cfg.weights.items():
            if 0 <= osd < m.max_devices:
                self.weight[osd] = w

    # -- mapping backends --------------------------------------------------
    def _real_xs(self, xs: np.ndarray) -> np.ndarray:
        """pool-seed mix of the x range (CrushTester.cc:621)."""
        if self.cfg.pool_id == -1:
            return xs.astype(np.uint32)
        return np.asarray(
            crush_hash32_2(
                xs.astype(np.uint32),
                np.uint32(self.cfg.pool_id & 0xFFFFFFFF),
            )
        )

    @staticmethod
    def _rows_from_padded(padded: np.ndarray, rule) -> list[list[int]]:
        """firstn rules compact ITEM_NONE away; indep keep positions."""
        return [
            [o for o in row if o != ITEM_NONE]
            if rule.type == 1
            else list(row)
            for row in padded.tolist()
        ]

    def _map_batch_jax(self, ruleno: int, xs: np.ndarray, nr: int):
        from ceph_tpu.utils import ensure_jax_backend

        ensure_jax_backend()
        from ceph_tpu.crush.mapper_jax import compile_batched

        fn = compile_batched(self.m_arrays(), ruleno, nr)
        return np.asarray(fn(xs.astype(np.uint32),
                             np.asarray(self.weight, np.uint32)))

    def _stats_batch_jax(self, ruleno: int, xs: np.ndarray, nr: int, rule):
        """Per-device utilization + result-size histogram with the rows
        staying ON DEVICE (ceph_tpu.core.reduce): the tester fetches only
        the O(devices) summaries it prints — the device-resident form of
        the reference's host-side accumulation loop (reference
        src/crush/CrushTester.cc:637-698)."""
        from ceph_tpu.utils import ensure_jax_backend

        ensure_jax_backend()
        import jax.numpy as jnp

        from ceph_tpu.core import reduce
        from ceph_tpu.crush.mapper_jax import compile_batched

        fn = compile_batched(self.m_arrays(), ruleno, nr)
        rows = fn(xs.astype(np.uint32),
                  np.asarray(self.weight, np.uint32), device=True)
        per = np.asarray(
            reduce.osd_histogram(rows, self.m.max_devices, dtype=jnp.int64)
        )
        if rule.type == 1:
            # firstn compacts ITEM_NONE away: size = occupied lanes
            sh = np.asarray(reduce.size_histogram(rows, nr))
            sizes = {i: int(c) for i, c in enumerate(sh) if c}
        else:
            # indep keeps positions: every row reports the padded width
            sizes = {int(rows.shape[1]): int(rows.shape[0])}
        return per, sizes

    _arrays_cache = None

    def m_arrays(self):
        if self._arrays_cache is None:
            from ceph_tpu.crush.soa import build_arrays

            self._arrays_cache = build_arrays(self.m)
        return self._arrays_cache

    def _map_one_ref(self, ruleno: int, x: int, nr: int) -> list[int]:
        return mapper_ref.do_rule(
            self.m, ruleno, x, nr, self.weight,
            collect_choose_tries=self.cfg.show_choose_tries,
        )

    def _collect_tries_jax(self, ruleno: int, real_xs: np.ndarray,
                           nr: int) -> bool:
        """Fold this (rule, numrep) pass's per-placement retry counts
        into the histogram FROM THE DEVICE diagnostics planes.  Returns
        False when the compiled plan cannot reproduce the host
        increments exactly (loop-path steps, leafy indep) — the caller
        then routes the pass through the host mapper instead.  Lanes the
        fast window flagged are re-collected host-side (the same rescue
        contract the mapping path uses), so the histogram is
        bit-identical to a pure host collection either way."""
        from ceph_tpu.utils import ensure_jax_backend

        ensure_jax_backend()
        from ceph_tpu.crush import explain

        hist = self.m.choose_tries_histogram
        try:
            dev_hist, unresolved = explain.device_choose_tries(
                self.m_arrays(), ruleno, nr, real_xs,
                np.asarray(self.weight, np.uint32), len(hist),
            )
        except ValueError:  # not diag-exact
            return False
        for i, v in enumerate(dev_hist):
            hist[i] += int(v)
        for x in real_xs[unresolved]:
            mapper_ref.do_rule(self.m, ruleno, int(x), nr, self.weight,
                               collect_choose_tries=True)
        return True

    def _random_placement(
        self, rng: np.random.Generator, nr: int
    ) -> list[int]:
        """Weighted sample without replacement (reference
        CrushTester.cc:260-292 random_placement)."""
        w = np.asarray(self.weight, np.float64)
        total = w.sum()
        out: list[int] = []
        if total <= 0:
            return out
        for _ in range(nr):
            p = w / w.sum() if w.sum() > 0 else None
            if p is None:
                break
            pick = int(rng.choice(len(w), p=p))
            out.append(pick)
            w = w.copy()
            w[pick] = 0
        return out

    @property
    def choose_tries(self) -> list[int] | None:
        """The collected histogram: choose_tries[f] = placements that
        needed f retries (index 0 = first-draw success)."""
        return self.m.choose_tries_histogram

    def dump_choose_tries(self, out=None) -> None:
        """Print the histogram, trailing zeros trimmed (the shape of the
        reference tester's --show-choose-tries output)."""
        out = out if out is not None else self.out
        hist = self.choose_tries or []
        last = max((i for i, v in enumerate(hist) if v), default=-1)
        print("choose_tries histogram", file=out)
        for i in range(last + 1):
            print(f" {i}: {hist[i]}", file=out)

    # -- the test loop -----------------------------------------------------
    def test(self) -> int:
        cfg, m = self.cfg, self.m
        backend = cfg.backend
        if cfg.show_choose_tries:
            m.choose_tries_histogram = [0] * (
                m.tunables.choose_total_tries + 1
            )
            if backend != "jax":
                # only jax (diagnostics planes) and ref (instrumented
                # host walk) can collect; native routes through ref
                # (local override: the caller's config is not mutated)
                backend = "ref"
        rules = (
            [cfg.rule]
            if cfg.rule >= 0
            else [i for i, r in enumerate(m.rules) if r is not None]
        )
        w = self.out
        rng = np.random.default_rng(0)
        for r in rules:
            rule = m.rules[r] if r < len(m.rules) else None
            if rule is None:
                print(f"rule {r} dne", file=w)
                continue
            rname = m.rule_names.get(r, f"rule{r}")
            if cfg.num_rep >= 0:
                minr = maxr = cfg.num_rep
            elif cfg.min_rep >= 0 and cfg.max_rep >= 0:
                minr, maxr = cfg.min_rep, cfg.max_rep
            else:
                minr, maxr = rule.min_size, rule.max_size
            if cfg.show_statistics:
                print(
                    f"rule {r} ({rname}), x = {cfg.min_x}..{cfg.max_x}, "
                    f"numrep = {minr}..{maxr}",
                    file=w,
                )
            n_x = cfg.max_x - cfg.min_x + 1
            for nr in range(minr, maxr + 1):
                per = np.zeros(m.max_devices, np.int64)
                sizes: dict[int, int] = {}
                xs = np.arange(cfg.min_x, cfg.max_x + 1, dtype=np.int64)
                pass_backend = backend
                if (cfg.show_choose_tries and pass_backend == "jax"
                        and not cfg.simulate):
                    # histogram from the device diagnostics planes;
                    # plans that cannot reproduce the host increments
                    # route the whole pass through the host mapper
                    if not self._collect_tries_jax(
                        r, self._real_xs(xs), nr
                    ):
                        pass_backend = "ref"
                if cfg.simulate:
                    rows = [
                        self._random_placement(rng, nr) for _ in range(n_x)
                    ]
                    prefix = "RNG"
                elif pass_backend == "native":
                    from ceph_tpu.native.mapper import NativeMapper

                    if getattr(self, "_nm", None) is None:
                        self._nm = NativeMapper(m)
                    padded = self._nm.map_batch(
                        r, self._real_xs(xs), nr, self.weight
                    )
                    rows = self._rows_from_padded(padded, rule)
                    prefix = "CRUSH"
                elif pass_backend == "ref":
                    rows = [
                        self._map_one_ref(r, int(rx), nr)
                        for rx in self._real_xs(xs)
                    ]
                    prefix = "CRUSH"
                elif not (cfg.show_mappings or cfg.show_bad_mappings):
                    # nothing per-row to print: reduce on device, fetch
                    # only the O(devices) summaries
                    per_d, sizes_d = self._stats_batch_jax(
                        r, self._real_xs(xs), nr, rule
                    )
                    per += per_d
                    for sz, cn in sizes_d.items():
                        sizes[sz] = sizes.get(sz, 0) + cn
                    rows = None
                else:
                    padded = self._map_batch_jax(r, self._real_xs(xs), nr)
                    rows = self._rows_from_padded(padded, rule)
                    prefix = "CRUSH"
                for x, out_row in zip(xs, rows or ()):
                    if cfg.show_mappings:
                        print(
                            f"{prefix} rule {r} x {x} {_vec(out_row)}",
                            file=w,
                        )
                    has_none = False
                    realsize = 0
                    for o in out_row:
                        if o != ITEM_NONE:
                            per[o] += 1
                            realsize += 1
                        else:
                            has_none = True
                    sizes[len(out_row)] = sizes.get(len(out_row), 0) + 1
                    if cfg.show_bad_mappings and (
                        len(out_row) != nr or has_none
                    ):
                        print(
                            f"bad mapping rule {r} x {x} num_rep {nr} "
                            f"result {_vec(out_row)}",
                            file=w,
                        )
                total_w = sum(self.weight)
                expected = (
                    np.asarray(self.weight, np.float64)
                    / max(total_w, 1)
                    * n_x
                    * nr
                )
                if cfg.show_utilization and not cfg.show_statistics:
                    for i in range(m.max_devices):
                        print(f"  device {i}:\t{per[i]}", file=w)
                if cfg.show_statistics:
                    for sz in sorted(sizes):
                        print(
                            f"rule {r} ({rname}) num_rep {nr} "
                            f"result size == {sz}:\t{sizes[sz]}/{n_x}",
                            file=w,
                        )
                    if cfg.show_utilization or cfg.show_utilization_all:
                        for i in range(m.max_devices):
                            if cfg.show_utilization_all or (
                                expected[i] > 0 and per[i] > 0
                            ):
                                print(
                                    f"  device {i}:\t\t stored : {per[i]}"
                                    f"\t expected : {expected[i]:.0f}",
                                    file=w,
                                )
        if cfg.show_choose_tries:
            self.dump_choose_tries()
        return 0
