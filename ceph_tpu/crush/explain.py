"""Explain replay + jax-vs-host first-divergence triage.

Two halves of one debugging workflow:

1. `explain_seed` / `explain_pool_pg`: replay ONE placement through the
   instrumented host oracle (`mapper_ref.do_rule(recorder=...)`) and
   return the full decision log — bucket descents, straw2 draw
   winners/losers, collision / out-of-weight / skip rejections, leaf
   recursions, per-step work vectors.  `render_text` formats it the way
   `crushtool explain` prints it.

2. `first_divergence`: run a BATCH of seeds through both the
   instrumented device kernel (`compile_rule(with_diag=True)`, whose
   `steps` plane records the work vector after every choose step) and
   the host oracle, and pin any disagreement to the EARLIEST differing
   choose step — the triage entry point when a tunable/port bug makes
   the fused kernel drift from reference semantics.  The device side is
   one vmapped dispatch; only the O(N·steps·width) step planes are
   fetched, and only when the final results already disagree would a
   human ever look further than the returned record.

The device kernels land in mapper_jax._KERNEL_CACHE / the executable
registry like every other trace-once entry point; instrumentation is a
static plan fact, so building them never touches the default kernels.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.crush import mapper_ref
from ceph_tpu.crush.types import CrushMap, ITEM_NONE, RuleOp

_OPS = {
    int(RuleOp.CHOOSE_FIRSTN): "choose firstn",
    int(RuleOp.CHOOSELEAF_FIRSTN): "chooseleaf firstn",
    int(RuleOp.CHOOSE_INDEP): "choose indep",
    int(RuleOp.CHOOSELEAF_INDEP): "chooseleaf indep",
}


class ExplainRecorder:
    """Decision recorder the host oracle emits into (see
    mapper_ref.do_rule).  `events` is the flat chronological log (each
    dict carries the recursion `depth` it was emitted at); `steps` holds
    the work vector after every choose step — the host half of the
    first-divergence comparison.

    detail=False skips the straw2 per-item draw dumps (the only
    expensive payload) — what the batch locator uses."""

    __slots__ = ("events", "steps", "depth", "detail")

    def __init__(self, detail: bool = True):
        self.events: list[dict] = []
        self.steps: list[list[int]] = []
        self.depth = 0
        self.detail = detail

    def emit(self, **kw) -> None:
        kw["depth"] = self.depth
        self.events.append(kw)

    def step_result(self, w: list[int]) -> None:
        self.steps.append(list(w))


def explain_seed(
    m: CrushMap,
    ruleno: int,
    x: int,
    result_max: int,
    weight: list[int],
    choose_args=None,
    detail: bool = True,
) -> dict:
    """Replay one mapping through the instrumented host oracle."""
    rec = ExplainRecorder(detail=detail)
    result = mapper_ref.do_rule(
        m, ruleno, int(x), result_max, weight, choose_args, recorder=rec
    )
    return {
        "x": int(x),
        "ruleno": ruleno,
        "result": [int(v) for v in result],
        "steps": rec.steps,
        "events": rec.events,
    }


def explain_pool_pg(m_osd, pool_id: int, seed: int) -> dict:
    """Replay one PG of an OSDMap pool: the pipeline's stage-1 seed
    mixing (ps -> pps) on the host, then the CRUSH walk — the payload
    behind the daemon `explain <pool>.<seed>` command."""
    from ceph_tpu.osd.types import PgId

    pool = m_osd.pools.get(pool_id)
    if pool is None:
        return {"error": f"no pool {pool_id}"}
    if not (0 <= seed < pool.pg_num):
        return {"error": f"seed {seed} outside pg_num {pool.pg_num}"}
    pps = pool.raw_pg_to_pps(PgId(pool_id, seed))
    ruleno = mapper_ref.find_rule(
        m_osd.crush, pool.crush_rule, int(pool.type), pool.size
    )
    ca = m_osd.crush.choose_args.get(
        pool_id, m_osd.crush.choose_args.get(-1)
    )
    out = explain_seed(
        m_osd.crush, ruleno, pps, pool.size, list(m_osd.osd_weight), ca
    )
    up, up_p, _, _ = m_osd.pg_to_up_acting_osds(PgId(pool_id, seed))
    out.update(pool=pool_id, seed=seed, pps=int(pps),
               up=[int(v) for v in up], up_primary=int(up_p))
    return out


def render_text(ex: dict, item_names: dict | None = None) -> str:
    """Human formatting of an explain record (the `crushtool explain`
    output): one line per decision, indented by recursion depth."""
    if "error" in ex:
        return f"explain: {ex['error']}\n"

    def name(it):
        if it is None:
            return "?"
        if item_names and it in item_names:
            return f"{it} ({item_names[it]})"
        return str(it)

    lines = []
    head = f"explain x={ex['x']} rule {ex['ruleno']}"
    if "pool" in ex:
        head = (f"explain pg {ex['pool']}.{ex['seed']} (pps={ex['pps']}) "
                f"rule {ex['ruleno']}")
    lines.append(head)
    step = -1
    for ev in ex["events"]:
        pad = "  " * (ev.get("depth", 0) + 1)
        kind = ev["ev"]
        if kind == "take":
            lines.append(f"{pad}take {name(ev['item'])}"
                         + ("" if ev.get("valid", True) else " [invalid]"))
        elif kind == "choose":
            step += 1
            op = _OPS.get(ev.get("op"), "choose")
            lines.append(
                f"{pad}step {step}: {op} numrep={ev['numrep']} "
                f"type={ev['type']} from {ev['sources']}"
            )
        elif kind == "straw2":
            order = sorted(ev["draws"], key=lambda d: -d[1])
            top = ", ".join(
                f"{name(it)}:{d}" for it, d in order[:3]
            )
            lines.append(
                f"{pad}  straw2 bucket {ev['bucket']} r={ev['r']} -> "
                f"{name(ev['winner'])}  [top draws: {top}]"
            )
        elif kind == "draw":
            lines.append(
                f"{pad}  rep {ev['rep']} r={ev['r']} ftotal={ev['ftotal']}"
                f" bucket {ev['bucket']} -> {name(ev.get('item'))} "
                f"[{ev['status']}]"
            )
        elif kind == "leaf_enter":
            lines.append(f"{pad}  rep {ev['rep']}: descend to leaf in "
                         f"bucket {ev['bucket']} (r={ev['r']})")
        elif kind == "leaf_exit":
            lines.append(f"{pad}  leaf descent "
                         f"{'ok' if ev['ok'] else 'REJECTED'}")
        elif kind == "place":
            lines.append(
                f"{pad}  PLACE rep {ev['rep']} -> {name(ev['item'])} "
                f"(retries={ev['ftotal']}, slot {ev['outpos']})"
            )
        elif kind == "emit":
            lines.append(f"{pad}emit -> {ev['result']}")
    if "up" in ex:
        lines.append(f"  up={ex['up']} primary={ex['up_primary']}")
    else:
        lines.append(f"  result={ex['result']}")
    return "\n".join(lines) + "\n"


# -- device side -----------------------------------------------------------

def diag_batch(A, ruleno: int, result_max: int, window_extra=None):
    """Memoized instrumented batch runner over one CrushArrays:
    run(xs, dev_weights) -> (result, unresolved, diag) DEVICE arrays
    (diag: tries [N, lanes], coll/rej/skip/bad [N], steps [N, S, RMAX]).
    Mirrors mapper_jax.compile_batched's memo/cache discipline; the
    jitted executable lands in _KERNEL_CACHE + the executable registry.
    The returned runner carries the plan facts (`diag_exact`,
    `diag_tries_bound`, `diag_steps`, `diag_lanes`)."""
    import jax
    import jax.numpy as jnp

    from ceph_tpu.crush.mapper_jax import (
        FAST_WINDOW_EXTRA, _KERNEL_CACHE, compile_rule, device_tables,
    )
    from ceph_tpu.obs import executables as _executables

    if window_extra is None:
        window_extra = FAST_WINDOW_EXTRA
    memo = A.__dict__.get("_diag_batch_memo")
    if memo is None:
        memo = {}
        object.__setattr__(A, "_diag_batch_memo", memo)  # frozen dataclass
    mkey = (ruleno, result_max, window_extra)
    if mkey in memo:
        return memo[mkey]
    fn = compile_rule(A, ruleno, result_max, with_flag=True,
                      with_diag=True, window_extra=window_extra)
    tables = device_tables(fn.host_tables)
    fkey = ("batched_diag", fn.cache_key)
    jfn = _KERNEL_CACHE.get(fkey)
    if jfn is None:
        jfn = _executables.wrap(
            jax.jit(jax.vmap(fn, in_axes=(0, None, None))),
            "kernel", "batched_diag", fkey,
        )
        _KERNEL_CACHE[fkey] = jfn

    def run(xs, dev_weights):
        return jfn(jnp.asarray(xs).astype(jnp.uint32),
                   jnp.asarray(dev_weights).astype(jnp.uint32), tables)

    run.diag_exact = fn.diag_exact
    run.diag_tries_bound = fn.diag_tries_bound
    run.diag_steps = fn.diag_steps
    run.diag_lanes = fn.diag_lanes
    run.cache_key = fn.cache_key
    memo[mkey] = run
    return run


def device_choose_tries(A, ruleno: int, result_max: int, xs, weights,
                        hist_len: int):
    """The device half of the --show-choose-tries unification: the
    per-placement retry histogram from the diagnostics planes, reduced
    ON device (only the O(hist_len) counts and the unresolved flags are
    fetched).  Returns (hist i64[hist_len], unresolved_idx i64[k]) —
    flagged lanes carry garbage planes and are EXCLUDED; the caller
    re-collects them through the host mapper (the same rescue contract
    the mapping path uses).  Raises ValueError when the compiled plan
    cannot reproduce the host increments (`diag_exact` False) — callers
    fall back to full host collection."""
    from ceph_tpu import obs
    from ceph_tpu.core import reduce

    run = diag_batch(A, ruleno, result_max)
    if not run.diag_exact:
        raise ValueError("plan is not diag-exact; use host collection")
    with obs.span("crush.diag_batch", xs=len(np.asarray(xs))):
        _, flg, diag = run(xs, weights)
    hist = reduce.value_histogram(
        diag["tries"], hist_len - 1, extra_mask=~flg[:, None]
    )
    hist_v = np.asarray(hist)
    unresolved = np.nonzero(np.asarray(flg))[0]
    return hist_v, unresolved


def first_divergence(
    m_host: CrushMap,
    A,
    ruleno: int,
    xs,
    result_max: int,
    weights: list[int],
    choose_args=None,
) -> dict | None:
    """Locate the earliest choose step where the device kernel (built
    from `A`) and the host oracle (walking `m_host`) disagree, over a
    batch of seeds.  Returns None when every step of every seed agrees;
    otherwise a record naming the first divergent (step, x) with both
    work vectors and the host decision log for that seed.

    `m_host` and `A` are passed separately on purpose: triage compares
    a device kernel against a DIFFERENT host map (perturbed tunables, a
    candidate map edit) as readily as against its own source.  Lanes
    the fast window flagged unresolved are skipped (production rescues
    them exactly; their planes are garbage by contract)."""
    xs = np.asarray(xs)
    run = diag_batch(A, ruleno, result_max)
    res_d, flg_d, diag = run(xs, np.asarray(weights, np.uint32))
    steps_d = np.asarray(diag["steps"])      # [N, S, RMAX]
    flg = np.asarray(flg_d)
    S = steps_d.shape[1]

    best: tuple[int, int] | None = None  # (step, batch index)
    host_steps_at_best: list[list[int]] | None = None
    n_divergent = 0
    for b, x in enumerate(xs):
        if flg[b]:
            continue
        rec = ExplainRecorder(detail=False)
        mapper_ref.do_rule(m_host, ruleno, int(x), result_max,
                           list(weights), choose_args, recorder=rec)
        div_step = None
        for s in range(S):
            host = rec.steps[s] if s < len(rec.steps) else []
            host_p = (host + [ITEM_NONE] * result_max)[:result_max]
            if list(steps_d[b, s]) != host_p:
                div_step = s
                break
        if div_step is None:
            continue
        n_divergent += 1
        if best is None or div_step < best[0]:
            best = (div_step, b)
            host_steps_at_best = rec.steps
    if best is None:
        return None
    s, b = best
    host = host_steps_at_best[s] if s < len(host_steps_at_best) else []
    return {
        "step": s,
        "x": int(xs[b]),
        "batch_index": b,
        "jax": [int(v) for v in steps_d[b, s]],
        "host": (host + [ITEM_NONE] * result_max)[:result_max],
        "n_divergent": n_divergent,
        "n_checked": int(len(xs) - flg.sum()),
        "n_unresolved_skipped": int(flg.sum()),
        "host_log": explain_seed(
            m_host, ruleno, int(xs[b]), result_max, list(weights),
            choose_args,
        ),
    }
