"""Structure-of-arrays CrushMap for the TPU kernels.

The C reference walks pointer-linked bucket structs per PG
(reference src/crush/crush.h:354-461).  The TPU-native form is a frozen,
padded tensor bundle: one row per bucket slot (slot b holds bucket id -1-b),
items/weights padded to the max bucket size with a size vector for masking.
All mapping kernels (ceph_tpu.crush.mapper_jax) take this bundle; it is
hashable-by-identity and treated as a static+array pytree by jit.

Padding policy: item/weight rows pad with 0 (masked lanes never win a draw:
zero weight => S64_MIN draw in straw2, 0 straw in straw, excluded by the size
mask elsewhere).  Tree node arrays pad to the largest node count.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np

from ceph_tpu.crush.types import BucketAlg, CrushMap, Tunables


@dataclass(frozen=True)
class CrushArrays:
    """Frozen SoA view of a CrushMap.  numpy-held; kernels move to device."""

    # static metadata (python ints — baked into traces)
    n_buckets: int  # B: bucket slots
    max_size: int  # S: padded item axis
    max_nodes: int  # NN: padded tree-node axis
    positions: int  # P: choose_args weight-set positions (>=1)
    max_devices: int
    max_depth: int  # longest bucket->bucket chain (for loop bounds)
    tunables: Tunables
    rules: tuple  # tuple of Rule (static step data)

    # per-bucket arrays
    alg: np.ndarray  # [B] i32
    btype: np.ndarray  # [B] i32
    size: np.ndarray  # [B] i32
    bucket_weight: np.ndarray  # [B] u32 (sum of item weights)
    items: np.ndarray  # [B,S] i32
    weights: np.ndarray  # [B,S] u32  (16.16)
    sum_weights: np.ndarray  # [B,S] u32  (list prefix sums)
    straws: np.ndarray  # [B,S] u32  (straw scalers)
    node_weights: np.ndarray  # [B,NN] u32 (tree heap)
    num_nodes: np.ndarray  # [B] i32
    # choose_args (defaults mirror weights/items)
    pos_weights: np.ndarray  # [P,B,S] u32
    arg_ids: np.ndarray  # [B,S] i32

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other


def _map_depth(m: CrushMap) -> int:
    """Longest chain of nested buckets (loop bound for descents)."""
    depth: dict[int, int] = {}

    def d(bid: int) -> int:
        if bid >= 0:
            return 0
        if bid in depth:
            return depth[bid]
        depth[bid] = 1  # guard cycles
        b = m.buckets.get(bid)
        if b is None:
            return 1
        depth[bid] = 1 + max((d(i) for i in b.items), default=0)
        return depth[bid]

    return max((d(b) for b in m.buckets), default=1)


def build_arrays(
    m: CrushMap, choose_args: Any | int | str | None = None,
    pad_devices: int | None = None, quantize: bool = False,
) -> CrushArrays:
    """Freeze a CrushMap (+ optionally one named choose_args set) to SoA.

    pad_devices: raise `max_devices` to this bound (identity when lower
    than the real bound).  Device ids in [real, pad) never occur in a
    well-formed map's buckets, so padding only widens the weight-vector
    operand — callers that quantize the bound (ClusterState) keep one
    compiled kernel across cluster expansion inside the quantum.  The
    differential-oracle paths build WITHOUT padding: the `item >=
    max_devices` validity checks then match the host reference exactly
    even on corrupt maps.

    quantize: additionally pad the bucket-slot axis (B, pow2 floor 8)
    and the item axis (S, pow2 floor 4).  Pad slots are zero rows no
    descent can reach (bucket ids bind through items) and pad lanes are
    masked by the size vector (the module padding policy), so growth —
    a host added per expansion, a rack gaining hosts — keeps every
    table SHAPE, and with it every compiled executable, until the
    quantum is crossed."""
    if isinstance(choose_args, (int, str)):
        choose_args = m.choose_args.get(choose_args)

    B = m.max_buckets
    S = max((b.size for b in m.buckets.values()), default=1) or 1
    if quantize:
        B = 1 << max(int(B - 1).bit_length(), 3)
        S = 1 << max(int(S - 1).bit_length(), 2)
    NN = 2
    for b in m.buckets.values():
        if b.alg == BucketAlg.TREE and b.node_weights:
            NN = max(NN, len(b.node_weights))
    P = 1
    if choose_args is not None:
        for ws in choose_args.weight_sets.values():
            P = max(P, len(ws))

    alg = np.zeros(B, np.int32)
    btype = np.zeros(B, np.int32)
    size = np.zeros(B, np.int32)
    bw = np.zeros(B, np.uint32)
    items = np.zeros((B, S), np.int32)
    weights = np.zeros((B, S), np.uint32)
    sumw = np.zeros((B, S), np.uint32)
    straws = np.zeros((B, S), np.uint32)
    nodew = np.zeros((B, NN), np.uint32)
    nnodes = np.zeros(B, np.int32)
    arg_ids = np.zeros((B, S), np.int32)

    for bid, b in m.buckets.items():
        slot = -1 - bid
        alg[slot] = int(b.alg)
        btype[slot] = b.type
        size[slot] = b.size
        bw[slot] = b.weight & 0xFFFFFFFF
        items[slot, : b.size] = b.items
        weights[slot, : b.size] = b.weights
        arg_ids[slot, : b.size] = b.items
        if b.alg == BucketAlg.LIST:
            if b.sum_weights is None:
                b.finalize_derived(m.tunables.straw_calc_version)
            sumw[slot, : b.size] = b.sum_weights
        elif b.alg == BucketAlg.TREE:
            if b.node_weights is None:
                b.finalize_derived(m.tunables.straw_calc_version)
            nw = b.node_weights or []
            nodew[slot, : len(nw)] = nw
            nnodes[slot] = len(nw)
        elif b.alg == BucketAlg.STRAW:
            if b.straws is None:
                b.finalize_derived(m.tunables.straw_calc_version)
            straws[slot, : b.size] = b.straws

    pos_weights = np.broadcast_to(weights, (P, B, S)).copy()
    if choose_args is not None:
        for bid, ws in choose_args.weight_sets.items():
            slot = -1 - bid
            n = m.buckets[bid].size
            for p in range(P):
                row = ws[min(p, len(ws) - 1)]
                pos_weights[p, slot, :n] = row
        for bid, ids in choose_args.ids.items():
            slot = -1 - bid
            n = m.buckets[bid].size
            arg_ids[slot, :n] = ids

    return CrushArrays(
        n_buckets=B,
        max_size=S,
        max_nodes=NN,
        positions=P,
        max_devices=max(m.max_devices, pad_devices or 0),
        max_depth=_map_depth(m),
        tunables=m.tunables,
        rules=tuple(m.rules),
        alg=alg,
        btype=btype,
        size=size,
        bucket_weight=bw,
        items=items,
        weights=weights,
        sum_weights=sumw,
        straws=straws,
        node_weights=nodew,
        num_nodes=nnodes,
        pos_weights=pos_weights,
        arg_ids=arg_ids,
    )
