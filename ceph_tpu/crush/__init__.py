from ceph_tpu.crush.types import (
    CrushMap,
    Bucket,
    Rule,
    Tunables,
    ChooseArgs,
    BucketAlg,
    RuleOp,
    ITEM_NONE,
    ITEM_UNDEF,
)
from ceph_tpu.crush.mapper_ref import do_rule as do_rule_ref, find_rule
