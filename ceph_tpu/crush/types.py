"""CRUSH map object model — our own design, semantics-compatible with the C
reference (reference src/crush/crush.h:229-461, src/crush/builder.c).

A CrushMap is a hierarchy of weighted buckets (internal nodes, negative ids)
over devices (leaves, ids >= 0), plus placement rules.  Weights are 16.16
fixed point throughout (0x10000 == weight 1.0).

This is the *host-side* model: mutable, Pythonic, used by builders, the text
compiler and the CLIs.  The TPU kernels consume the frozen structure-of-arrays
form built by ceph_tpu.crush.soa.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from enum import IntEnum

ITEM_NONE = 0x7FFFFFFF  # CRUSH_ITEM_NONE  (reference src/crush/crush.h:33)
ITEM_UNDEF = 0x7FFFFFFE  # CRUSH_ITEM_UNDEF (mapping in progress)
MAX_DEPTH = 10  # CRUSH_MAX_DEPTH (reference src/crush/crush.h:26)


class BucketAlg(IntEnum):
    # reference src/crush/crush.h crush_algorithm
    UNIFORM = 1
    LIST = 2
    TREE = 3
    STRAW = 4
    STRAW2 = 5


class RuleOp(IntEnum):
    # reference src/crush/crush.h:52-70 crush_opcodes
    NOOP = 0
    TAKE = 1
    CHOOSE_FIRSTN = 2
    CHOOSE_INDEP = 3
    EMIT = 4
    CHOOSELEAF_FIRSTN = 6
    CHOOSELEAF_INDEP = 7
    SET_CHOOSE_TRIES = 8
    SET_CHOOSELEAF_TRIES = 9
    SET_CHOOSE_LOCAL_TRIES = 10
    SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11
    SET_CHOOSELEAF_VARY_R = 12
    SET_CHOOSELEAF_STABLE = 13


@dataclass
class Tunables:
    """Mapping tunables; defaults = the modern "jewel" profile
    (reference src/crush/CrushWrapper.h:331-368 set_tunables_jewel)."""

    choose_local_tries: int = 0
    choose_local_fallback_tries: int = 0
    choose_total_tries: int = 50
    chooseleaf_descend_once: int = 1
    chooseleaf_vary_r: int = 1
    chooseleaf_stable: int = 1
    straw_calc_version: int = 1
    allowed_bucket_algs: int = (
        (1 << BucketAlg.UNIFORM)
        | (1 << BucketAlg.LIST)
        | (1 << BucketAlg.STRAW)
        | (1 << BucketAlg.STRAW2)
    )

    @classmethod
    def profile(cls, name: str) -> "Tunables":
        # reference src/crush/CrushWrapper.h:331-368 (set_tunables_*)
        if name in ("legacy", "argonaut"):
            return cls(2, 5, 19, 0, 0, 0, 0, 0xFFFFFFFF)
        if name == "bobtail":
            return cls(0, 0, 50, 1, 0, 0, 0, 0xFFFFFFFF)
        if name in ("firefly", "hammer"):
            t = cls(0, 0, 50, 1, 1, 0)
            return t
        if name in ("jewel", "default", "optimal"):
            return cls()
        raise ValueError(f"unknown tunables profile {name!r}")


def _tree_height(n: int) -> int:
    h = 0
    while (n & 1) == 0:
        h += 1
        n >>= 1
    return h


def tree_node_of_leaf(i: int) -> int:
    """leaf index -> tree node id (reference src/crush/crush.h:504-507)."""
    return ((i + 1) << 1) - 1


def tree_parent(n: int) -> int:
    # reference src/crush/builder.c:305-311
    h = _tree_height(n)
    if n & (1 << (h + 1)):
        return n - (1 << h)
    return n + (1 << h)


@dataclass
class Bucket:
    """One internal node.  items are child ids (devices >= 0, buckets < 0);
    weights are per-child 16.16 fixed point."""

    id: int
    alg: BucketAlg
    type: int
    items: list[int] = field(default_factory=list)
    weights: list[int] = field(default_factory=list)
    hash: int = 0  # CRUSH_HASH_RJENKINS1
    # alg-specific derived tables (built lazily by finalize_derived):
    sum_weights: list[int] | None = None  # LIST: prefix sums
    node_weights: list[int] | None = None  # TREE: heap-layout node weights
    straws: list[int] | None = None  # STRAW: scaled straw lengths

    @property
    def size(self) -> int:
        return len(self.items)

    @property
    def weight(self) -> int:
        return sum(self.weights)

    def finalize_derived(self, straw_calc_version: int = 1) -> None:
        if self.alg == BucketAlg.LIST:
            s, acc = [], 0
            for w in self.weights:
                acc += w
                s.append(acc)
            self.sum_weights = s
        elif self.alg == BucketAlg.TREE:
            # reference src/crush/builder.c:328-391 crush_make_tree_bucket
            if self.size == 0:
                self.node_weights = []
                return
            # calc_depth semantics (reference src/crush/builder.c:314-326)
            t = self.size - 1
            depth = 1
            while t:
                t >>= 1
                depth += 1
            num_nodes = 1 << depth
            nw = [0] * num_nodes
            for i, w in enumerate(self.weights):
                node = tree_node_of_leaf(i)
                nw[node] = w
                for _ in range(1, depth):
                    node = tree_parent(node)
                    nw[node] += w
            self.node_weights = nw
        elif self.alg == BucketAlg.STRAW:
            self.straws = calc_straws(self.weights, straw_calc_version)


def calc_straws(weights: list[int], straw_calc_version: int = 1) -> list[int]:
    """Legacy straw(1) scaler (reference src/crush/builder.c:431-545
    crush_calc_straw).  Kept for parity with old maps; straw2 needs none."""
    size = len(weights)
    straws = [0] * size
    # stable reverse argsort by weight, ties keep original order (insertion
    # sort semantics of the reference)
    reverse = sorted(range(size), key=lambda i: (weights[i], i))
    numleft = size
    straw = 1.0
    wbelow = 0.0
    lastw = 0.0
    i = 0
    while i < size:
        if straw_calc_version == 0:
            if weights[reverse[i]] == 0:
                straws[reverse[i]] = 0
                i += 1
                continue
            straws[reverse[i]] = int(straw * 0x10000)
            i += 1
            if i == size:
                break
            if weights[reverse[i]] == weights[reverse[i - 1]]:
                continue
            wbelow += (float(weights[reverse[i - 1]]) - lastw) * numleft
            for j in range(i, size):
                if weights[reverse[j]] == weights[reverse[i]]:
                    numleft -= 1
                else:
                    break
            wnext = numleft * (weights[reverse[i]] - weights[reverse[i - 1]])
            pbelow = wbelow / (wbelow + wnext)
            straw *= math.pow(1.0 / pbelow, 1.0 / numleft)
            lastw = float(weights[reverse[i - 1]])
        else:
            if weights[reverse[i]] == 0:
                straws[reverse[i]] = 0
                i += 1
                numleft -= 1
                continue
            straws[reverse[i]] = int(straw * 0x10000)
            i += 1
            if i == size:
                break
            wbelow += (float(weights[reverse[i - 1]]) - lastw) * numleft
            numleft -= 1
            wnext = numleft * (weights[reverse[i]] - weights[reverse[i - 1]])
            pbelow = wbelow / (wbelow + wnext)
            straw *= math.pow(1.0 / pbelow, 1.0 / numleft)
            lastw = float(weights[reverse[i - 1]])
    return straws


@dataclass
class Rule:
    """A placement rule: list of (op, arg1, arg2) steps plus its mask
    (reference src/crush/crush.h crush_rule{,_mask,_step})."""

    steps: list[tuple[int, int, int]]
    ruleset: int = 0
    type: int = 1  # pool type: 1=replicated, 3=erasure
    min_size: int = 1
    max_size: int = 10


@dataclass
class ChooseArgs:
    """Per-bucket weight-set overrides (reference src/crush/crush.h:273-294
    crush_choose_arg{,_map}).  weight_sets[bucket_id] is a [positions][size]
    list of alternative 16.16 weights; ids[bucket_id] optionally remaps the
    hashed item ids."""

    weight_sets: dict[int, list[list[int]]] = field(default_factory=dict)
    ids: dict[int, list[int]] = field(default_factory=dict)


class CrushMap:
    """The full map: buckets + rules + tunables + named choose_args."""

    def __init__(self, tunables: Tunables | None = None):
        self.tunables = tunables or Tunables()
        self.buckets: dict[int, Bucket] = {}  # id (<0) -> Bucket
        self.rules: list[Rule | None] = []
        self.max_devices = 0
        self.choose_args: dict[int | str, ChooseArgs] = {}
        # naming layers (CrushWrapper equivalents)
        self.type_names: dict[int, str] = {0: "osd"}
        self.item_names: dict[int, str] = {}
        self.rule_names: dict[int, str] = {}
        self.item_classes: dict[int, str] = {}  # device id -> class name
        self.class_names: dict[int, str] = {}  # class id -> class name
        self.class_bucket: dict[int, dict[int, int]] = {}  # orig id -> class id -> shadow id
        self.choose_tries_histogram: list[int] | None = None

    # -- construction ------------------------------------------------------
    @property
    def max_buckets(self) -> int:
        return -min(self.buckets.keys(), default=0)

    def next_bucket_id(self) -> int:
        for i in range(len(self.buckets) + 1):
            if -1 - i not in self.buckets:
                return -1 - i
        raise AssertionError

    def add_bucket(
        self,
        alg: BucketAlg | int,
        type_: int,
        items: list[int],
        weights: list[int],
        id: int | None = None,
        hash: int = 0,
        name: str | None = None,
    ) -> int:
        bid = self.next_bucket_id() if id is None else id
        assert bid < 0 and bid not in self.buckets
        b = Bucket(bid, BucketAlg(alg), type_, list(items), list(weights), hash)
        b.finalize_derived(self.tunables.straw_calc_version)
        self.buckets[bid] = b
        for it in items:
            if it >= 0:
                self.max_devices = max(self.max_devices, it + 1)
        if name is not None:
            self.item_names[bid] = name
        return bid

    def add_rule(self, rule: Rule, ruleno: int | None = None) -> int:
        if ruleno is None:
            self.rules.append(rule)
            return len(self.rules) - 1
        while len(self.rules) <= ruleno:
            self.rules.append(None)
        self.rules[ruleno] = rule
        return ruleno

    def bucket(self, item: int) -> Bucket | None:
        return self.buckets.get(item)

    def refresh_derived(self) -> None:
        for b in self.buckets.values():
            b.finalize_derived(self.tunables.straw_calc_version)

    def parent_of(self, item: int) -> int | None:
        for bid, b in self.buckets.items():
            if item in b.items:
                return bid
        return None

    def adjust_item_weight(self, item: int, weight: int) -> None:
        """Set a device/bucket's weight and propagate the delta up every
        ancestor chain (reference CrushWrapper::adjust_item_weight /
        bucket_adjust_item_weight semantics)."""
        shadows = {
            sid for per in self.class_bucket.values() for sid in per.values()
        }
        for bid, b in self.buckets.items():
            if bid in shadows:
                continue
            for j, it in enumerate(b.items):
                if it == item:
                    delta = weight - b.weights[j]
                    b.weights[j] = weight
                    # bubble the delta up to the roots
                    cur = bid
                    while True:
                        parent = self.parent_of(cur)
                        if parent is None or parent in shadows:
                            break
                        pb = self.buckets[parent]
                        idx = pb.items.index(cur)
                        pb.weights[idx] += delta
                        cur = parent
        self.refresh_derived()

    # -- device classes ----------------------------------------------------
    def class_id(self, name: str) -> int:
        for cid, n in self.class_names.items():
            if n == name:
                return cid
        cid = max(self.class_names.keys(), default=-1) + 1
        self.class_names[cid] = name
        return cid

    def build_class_shadow_trees(
        self, preferred: dict[int, dict[str, int]] | None = None
    ) -> None:
        """Build per-class shadow hierarchies — the semantics of the
        reference's class-filtered trees (`device_class_clone`, reference
        src/crush/CrushWrapper.cc:2693 / rebuild_roots_with_classes): for
        every device class, clone each bucket keeping only that class's
        devices, so `step take <root> class <c>` TAKEs the shadow root.
        Shadow buckets are ordinary buckets here (the SoA kernel maps them
        like any other); they are named "<orig>~<class>" and recorded in
        class_bucket[orig][class_id].

        `preferred` pins shadow ids: {orig_bucket_id: {class_name: id}} —
        used by the text compiler to honor `id -N class <c>` declarations
        so choose_args entries keyed by shadow bucket id stay attached to
        the right bucket."""
        # drop previous shadows
        old = {
            sid
            for per in self.class_bucket.values()
            for sid in per.values()
        }
        for sid in old:
            self.buckets.pop(sid, None)
            self.item_names.pop(sid, None)
        self.class_bucket = {}
        classes = sorted(set(self.item_classes.values()))
        if not classes:
            return
        originals = sorted(self.buckets.keys(), reverse=True)  # -1, -2, ...

        for cname in classes:
            cid = self.class_id(cname)
            shadow_of: dict[int, int] = {}

            def clone(bid: int) -> int:
                if bid in shadow_of:
                    return shadow_of[bid]
                b = self.buckets[bid]
                items: list[int] = []
                weights: list[int] = []
                for it, w in zip(b.items, b.weights):
                    if it >= 0:
                        if self.item_classes.get(it) == cname:
                            items.append(it)
                            weights.append(w)
                    else:
                        sid = clone(it)
                        items.append(sid)
                        weights.append(self.buckets[sid].weight)
                want_id = (preferred or {}).get(bid, {}).get(cname)
                if want_id is not None and want_id in self.buckets:
                    want_id = None
                sid = self.add_bucket(
                    b.alg, b.type, items, weights, hash=b.hash,
                    id=want_id,
                    name=(
                        f"{self.item_names[bid]}~{cname}"
                        if bid in self.item_names else None
                    ),
                )
                shadow_of[bid] = sid
                self.class_bucket.setdefault(bid, {})[cid] = sid
                return sid

            for bid in originals:
                clone(bid)

    def split_id_class(self, item: int) -> tuple[int, int]:
        """shadow id -> (original id, class id); (item, -1) if not a
        shadow (reference CrushWrapper::split_id_class)."""
        for orig, per in self.class_bucket.items():
            for cid, sid in per.items():
                if sid == item:
                    return orig, cid
        return item, -1

    # -- convenience -------------------------------------------------------
    def insert_item(
        self, item: int, weightf: float, name: str,
        loc: dict[str, str],
    ) -> None:
        """CrushWrapper::insert_item semantics (reference
        src/crush/CrushWrapper.cc:1095-1210): walk the type hierarchy
        bottom-up creating missing location buckets (straw2, weight 0)
        and splice the chain into the first existing ancestor; then set
        the item's weight and bubble the delta to the roots.  Bucket ids
        are allocated lowest-free-slot (-1-slot), matching the C
        builder, so maps built this way decompile identically."""
        if self.item_names.get(item, name) != name and item >= 0:
            raise ValueError(f"name {name!r} vs existing "
                             f"{self.item_names[item]!r}")
        self.item_names.setdefault(item, name)
        name_to_id = {n: i for i, n in self.item_names.items()}
        cur = item
        for type_id in sorted(self.type_names):
            if type_id == 0:
                continue
            tname = self.type_names[type_id]
            if tname not in loc:
                continue
            bname = loc[tname]
            if bname not in name_to_id:
                bid = self.add_bucket(
                    BucketAlg.STRAW2, type_id, [cur], [0], name=bname
                )
                name_to_id[bname] = bid
                cur = bid
                continue
            b = self.buckets[name_to_id[bname]]
            b.items.append(cur)
            b.weights.append(0)
            b.finalize_derived(self.tunables.straw_calc_version)
            break
        if item >= 0:
            self.max_devices = max(self.max_devices, item + 1)
        self.adjust_item_weight(item, int(round(weightf * 0x10000)))

    def _detach_item(self, item: int) -> bool:
        """Remove `item` from EVERY bucket holding it (shadow class trees
        included), bubbling the weight delta up each chain.  Returns True
        if it was held anywhere."""
        found = False
        for bid, holder in list(self.buckets.items()):
            if item not in holder.items:
                continue
            found = True
            j = holder.items.index(item)
            delta = -holder.weights[j]
            holder.items.pop(j)
            holder.weights.pop(j)
            holder.finalize_derived(self.tunables.straw_calc_version)
            cur = bid
            while delta:
                parent = self.parent_of(cur)
                if parent is None:
                    break
                pb = self.buckets[parent]
                idx = pb.items.index(cur)
                pb.weights[idx] += delta
                pb.finalize_derived(self.tunables.straw_calc_version)
                cur = parent
        return found

    def remove_item(self, item: int) -> bool:
        """Detach a device/bucket from the tree and destroy its identity
        (reference CrushWrapper::remove_item: bucket freed, name erased).
        Returns True if found."""
        found = self._detach_item(item)
        if item < 0:
            found = self.buckets.pop(item, None) is not None or found
        self.item_names.pop(item, None)
        if item >= 0:
            self.item_classes.pop(item, None)
        return found

    def item_loc(self, item: int) -> dict[str, str]:
        """{type_name: bucket_name} chain of the item's current ancestors
        (non-shadow), for check_item_loc-style comparisons."""
        shadows = {
            sid for per in self.class_bucket.values()
            for sid in per.values()
        }
        out: dict[str, str] = {}
        cur = item
        while True:
            parent = next(
                (bid for bid, b in self.buckets.items()
                 if bid not in shadows and cur in b.items), None
            )
            if parent is None:
                return out
            b = self.buckets[parent]
            out[self.type_names.get(b.type, str(b.type))] = \
                self.item_names.get(parent, str(parent))
            cur = parent

    def item_weight(self, item: int) -> int | None:
        """Current (non-shadow) weight of the item, or None if absent."""
        shadows = {
            sid for per in self.class_bucket.values()
            for sid in per.values()
        }
        for bid, b in self.buckets.items():
            if bid in shadows:
                continue
            if item in b.items:
                return b.weights[b.items.index(item)]
        return None

    def create_or_move_item(
        self, item: int, weightf: float, name: str, loc: dict[str, str]
    ) -> bool:
        """reference CrushWrapper::create_or_move_item: no-op when the
        item already sits at loc; otherwise detach and re-insert, keeping
        an existing item's current weight over the passed one.  Returns
        True if the map changed."""
        cur_loc = self.item_loc(item)
        if cur_loc and all(cur_loc.get(t) == n for t, n in loc.items()
                           if t in cur_loc):
            return False  # already there
        w = self.item_weight(item)
        if w is not None:
            weightf = w / 0x10000  # "resetting name/weight to current"
        self._detach_item(item)
        self.item_names.pop(item, None)
        self.insert_item(item, weightf, name, loc)
        return True

    def make_replicated_rule(
        self, root: int, failure_domain_type: int, num_rep: int = 0
    ) -> int:
        """CrushWrapper::add_simple_rule semantics for a replicated pool
        (reference src/crush/CrushWrapper.cc:2370): take root ->
        chooseleaf_firstn {0|n} type fd -> emit."""
        steps = [(RuleOp.TAKE, root, 0)]
        if failure_domain_type == 0:
            steps.append((RuleOp.CHOOSE_FIRSTN, num_rep, 0))
        else:
            steps.append((RuleOp.CHOOSELEAF_FIRSTN, num_rep, failure_domain_type))
        steps.append((RuleOp.EMIT, 0, 0))
        return self.add_rule(Rule(steps, ruleset=len(self.rules), type=1))

    def make_erasure_rule(
        self, root: int, failure_domain_type: int, num_chunks: int = 0
    ) -> int:
        """ErasureCode::create_rule semantics (reference
        src/erasure-code/ErasureCode.cc:64-83): set_chooseleaf_tries 5 ->
        take root -> chooseleaf_indep {0|n} type fd -> emit."""
        steps = [
            (RuleOp.SET_CHOOSELEAF_TRIES, 5, 0),
            (RuleOp.TAKE, root, 0),
        ]
        if failure_domain_type == 0:
            steps.append((RuleOp.CHOOSE_INDEP, num_chunks, 0))
        else:
            steps.append((RuleOp.CHOOSELEAF_INDEP, num_chunks, failure_domain_type))
        steps.append((RuleOp.EMIT, 0, 0))
        return self.add_rule(
            Rule(steps, ruleset=len(self.rules), type=3, max_size=20)
        )
