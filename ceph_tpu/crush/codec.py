"""Binary crushmap codec — wire-compatible with the reference.

Implements the on-disk/on-wire crushmap encoding of CrushWrapper::encode /
::decode (reference src/crush/CrushWrapper.cc:2941,3117): little-endian
magic + bucket array (alg-tagged slots with per-alg payloads) + rules +
name maps + staged tunables + the luminous device-class and choose_args
sections.  Field widths follow the C structs (reference src/crush/crush.h:
crush_bucket :229, crush_rule_mask :84, tunables :377-456, CRUSH_MAGIC :24).

This lets the CLIs read/write real `crushtool -o` artifacts: a map encoded
by the reference decodes here bit-for-bit and vice versa (modulo optional
trailing sections governed by feature bits — we always emit the full modern
form, like a luminous+ cluster would).
"""

from __future__ import annotations

import struct

from ceph_tpu.crush.types import (
    Bucket,
    BucketAlg,
    ChooseArgs,
    CrushMap,
    Rule,
    Tunables,
)

CRUSH_MAGIC = 0x00010000


class CodecError(ValueError):
    pass


class Writer:
    def __init__(self):
        self.parts: list[bytes] = []

    def u8(self, v):
        self.parts.append(struct.pack("<B", v & 0xFF))

    def u16(self, v):
        self.parts.append(struct.pack("<H", v & 0xFFFF))

    def u32(self, v):
        self.parts.append(struct.pack("<I", v & 0xFFFFFFFF))

    def i32(self, v):
        self.parts.append(struct.pack("<i", v))

    def i64(self, v):
        self.parts.append(struct.pack("<q", v))

    def string(self, s: str):
        b = s.encode()
        self.u32(len(b))
        self.parts.append(b)

    def str_map(self, m: dict[int, str]):
        self.u32(len(m))
        for k in sorted(m):
            self.i32(k)
            self.string(m[k])

    def getvalue(self) -> bytes:
        return b"".join(self.parts)


class Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def _take(self, n: int) -> bytes:
        if self.off + n > len(self.data):
            raise CodecError("truncated crushmap")
        b = self.data[self.off : self.off + n]
        self.off += n
        return b

    def end(self) -> bool:
        return self.off >= len(self.data)

    def u8(self):
        return self._take(1)[0]

    def u16(self):
        return struct.unpack("<H", self._take(2))[0]

    def u32(self):
        return struct.unpack("<I", self._take(4))[0]

    def i32(self):
        return struct.unpack("<i", self._take(4))[0]

    def i64(self):
        return struct.unpack("<q", self._take(8))[0]

    def string(self) -> str:
        return self._take(self.u32()).decode()

    def str_map(self) -> dict[int, str]:
        """With the 32-or-64-bit key quirk (reference
        decode_32_or_64_string_map, CrushWrapper.cc:3100)."""
        out = {}
        n = self.u32()
        for _ in range(n):
            key = self.i32()
            slen = self.u32()
            if slen == 0:
                slen = self.u32()  # key was actually 64 bits
            out[key] = self._take(slen).decode()
        return out


def encode_crushmap(m: CrushMap) -> bytes:
    w = Writer()
    w.u32(CRUSH_MAGIC)
    max_buckets = getattr(m, "wire_max_buckets", None)
    if max_buckets is None or max_buckets < m.max_buckets:
        # emulate the C builder's slot-array growth (8, 16, 32, ...;
        # builder.c crush_add_bucket) so built maps encode byte-identical
        # to maps built through the reference builder
        max_buckets = 0 if not m.buckets else 8
        while max_buckets < m.max_buckets:
            max_buckets *= 2
    n_rules = len(m.rules)
    w.i32(max_buckets)
    w.u32(n_rules)
    w.i32(m.max_devices)

    # buckets
    for i in range(max_buckets):
        b = m.buckets.get(-1 - i)
        if b is None:
            w.u32(0)
            continue
        w.u32(int(b.alg))
        w.i32(b.id)
        w.u16(b.type)
        w.u8(int(b.alg))
        w.u8(b.hash)
        w.u32(b.weight)
        w.u32(b.size)
        for it in b.items:
            w.i32(it)
        if b.alg == BucketAlg.UNIFORM:
            w.u32(b.weights[0] if b.weights else 0)
        elif b.alg == BucketAlg.LIST:
            assert b.sum_weights is not None
            for iw, sw in zip(b.weights, b.sum_weights):
                w.u32(iw)
                w.u32(sw)
        elif b.alg == BucketAlg.TREE:
            assert b.node_weights is not None
            w.u8(len(b.node_weights))
            for nw in b.node_weights:
                w.u32(nw)
        elif b.alg == BucketAlg.STRAW:
            assert b.straws is not None
            for iw, st in zip(b.weights, b.straws):
                w.u32(iw)
                w.u32(st)
        elif b.alg == BucketAlg.STRAW2:
            for iw in b.weights:
                w.u32(iw)
        else:
            raise CodecError(f"unencodable bucket alg {b.alg}")

    # rules
    for rule in m.rules:
        if rule is None:
            w.u32(0)
            continue
        w.u32(1)
        w.u32(len(rule.steps))
        w.u8(rule.ruleset)
        w.u8(rule.type)
        w.u8(rule.min_size)
        w.u8(rule.max_size)
        for op, a1, a2 in rule.steps:
            w.u32(int(op))
            w.i32(a1)
            w.i32(a2)

    # name maps
    w.str_map(m.type_names)
    w.str_map(m.item_names)
    w.str_map(m.rule_names)

    # tunables (staged like the reference's decode expects)
    t = m.tunables
    w.u32(t.choose_local_tries)
    w.u32(t.choose_local_fallback_tries)
    w.u32(t.choose_total_tries)
    w.u32(t.chooseleaf_descend_once)
    w.u8(t.chooseleaf_vary_r)
    w.u8(t.straw_calc_version)
    w.u32(t.allowed_bucket_algs)
    w.u8(t.chooseleaf_stable)

    # device classes (luminous section)
    class_by_name = {n: c for c, n in m.class_names.items()}
    class_map = {
        dev: class_by_name[cname]
        for dev, cname in sorted(m.item_classes.items())
        if cname in class_by_name
    }
    w.u32(len(class_map))
    for dev in sorted(class_map):
        w.i32(dev)
        w.i32(class_map[dev])
    w.str_map(m.class_names)
    # class_bucket: map<i32, map<i32,i32>>
    w.u32(len(m.class_bucket))
    for orig in sorted(m.class_bucket):
        w.i32(orig)
        per = m.class_bucket[orig]
        w.u32(len(per))
        for cid in sorted(per):
            w.i32(cid)
            w.i32(per[cid])

    # choose_args
    int_keys = [k for k in m.choose_args if isinstance(k, int)]
    w.u32(len(int_keys))
    for key in sorted(int_keys):
        ca = m.choose_args[key]
        w.i64(key)
        entries = sorted(set(ca.weight_sets) | set(ca.ids))
        # bucket ids -> slot indexes
        w.u32(len(entries))
        for bid in entries:
            idx = -1 - bid
            w.u32(idx)
            ws = ca.weight_sets.get(bid, [])
            w.u32(len(ws))
            for row in ws:
                w.u32(len(row))
                for v in row:
                    w.u32(v)
            ids = ca.ids.get(bid, [])
            w.u32(len(ids))
            for v in ids:
                w.i32(v)
    return w.getvalue()


def decode_crushmap(data: bytes) -> CrushMap:
    r = Reader(data)
    magic = r.u32()
    if magic != CRUSH_MAGIC:
        raise CodecError(f"bad crush magic 0x{magic:x}")
    max_buckets = r.i32()
    max_rules = r.u32()
    max_devices = r.i32()

    # "legacy tunables, unless we decode something newer" — the reference
    # decode resets to the legacy profile before the staged tunable reads
    # (CrushWrapper.cc decode: set_tunables_legacy())
    m = CrushMap(Tunables.profile("legacy"))
    m.type_names = {}
    m.max_devices = max_devices
    # preserve the stored slot-array size: the C builder's capacity grows
    # 8,16,32,... and empty slots encode as a 4-byte 0 (builder.c
    # crush_add_bucket), so re-encode must replay the same capacity
    m.wire_max_buckets = max_buckets

    for i in range(max_buckets):
        alg = r.u32()
        if alg == 0:
            continue
        bid = r.i32()
        btype = r.u16()
        alg2 = r.u8()
        hash_ = r.u8()
        weight = r.u32()
        size = r.u32()
        items = [r.i32() for _ in range(size)]
        weights: list[int] = []
        sum_weights = None
        node_weights = None
        straws = None
        if alg2 == BucketAlg.UNIFORM:
            iw = r.u32()
            weights = [iw] * size
        elif alg2 == BucketAlg.LIST:
            sum_weights = []
            for _ in range(size):
                weights.append(r.u32())
                sum_weights.append(r.u32())
        elif alg2 == BucketAlg.TREE:
            n_nodes = r.u8()
            node_weights = [r.u32() for _ in range(n_nodes)]
            # leaf j lives at node (j+1)*2-1
            weights = [
                node_weights[((j + 1) << 1) - 1]
                if ((j + 1) << 1) - 1 < n_nodes
                else 0
                for j in range(size)
            ]
        elif alg2 == BucketAlg.STRAW:
            straws = []
            for _ in range(size):
                weights.append(r.u32())
                straws.append(r.u32())
        elif alg2 == BucketAlg.STRAW2:
            weights = [r.u32() for _ in range(size)]
        else:
            raise CodecError(f"unknown bucket alg {alg2}")
        b = Bucket(
            bid, BucketAlg(alg2), btype, items, weights, hash_,
            sum_weights=sum_weights, node_weights=node_weights,
            straws=straws,
        )
        m.buckets[bid] = b

    for ruleno in range(max_rules):
        yes = r.u32()
        if not yes:
            m.rules.append(None)
            continue
        length = r.u32()
        ruleset = r.u8()
        rtype = r.u8()
        min_size = r.u8()
        max_size = r.u8()
        steps = [(r.u32(), r.i32(), r.i32()) for _ in range(length)]
        m.rules.append(
            Rule(steps, ruleset=ruleset, type=rtype,
                 min_size=min_size, max_size=max_size)
        )

    m.type_names = r.str_map()
    m.item_names = r.str_map()
    m.rule_names = r.str_map()

    t = m.tunables
    if not r.end():
        t.choose_local_tries = r.u32()
        t.choose_local_fallback_tries = r.u32()
        t.choose_total_tries = r.u32()
    if not r.end():
        t.chooseleaf_descend_once = r.u32()
    if not r.end():
        t.chooseleaf_vary_r = r.u8()
    if not r.end():
        t.straw_calc_version = r.u8()
    if not r.end():
        t.allowed_bucket_algs = r.u32()
    if not r.end():
        t.chooseleaf_stable = r.u8()
    if not r.end():
        n = r.u32()
        class_map = {}
        for _ in range(n):
            dev = r.i32()
            class_map[dev] = r.i32()
        m.class_names = {
            k: v for k, v in r.str_map().items()
        }
        for dev, cid in class_map.items():
            if cid in m.class_names:
                m.item_classes[dev] = m.class_names[cid]
        n = r.u32()
        for _ in range(n):
            orig = r.i32()
            per_n = r.u32()
            per = {}
            for _ in range(per_n):
                cid = r.i32()
                per[cid] = r.i32()
            m.class_bucket[orig] = per
    if not r.end():
        n_ca = r.u32()
        for _ in range(n_ca):
            key = r.i64()
            ca = ChooseArgs()
            n_args = r.u32()
            for _ in range(n_args):
                idx = r.u32()
                bid = -1 - idx
                positions = r.u32()
                if positions:
                    ws = []
                    for _ in range(positions):
                        sz = r.u32()
                        ws.append([r.u32() for _ in range(sz)])
                    ca.weight_sets[bid] = ws
                ids_size = r.u32()
                if ids_size:
                    ca.ids[bid] = [r.i32() for _ in range(ids_size)]
            m.choose_args[key] = ca

    m.refresh_derived()
    return m


def looks_like_crushmap(data: bytes) -> bool:
    return len(data) >= 4 and struct.unpack("<I", data[:4])[0] == CRUSH_MAGIC
