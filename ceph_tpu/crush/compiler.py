"""Text crushmap compiler/decompiler.

Reads and writes the crushtool text format (the grammar of the reference's
CrushCompiler, reference src/crush/CrushCompiler.{h,cc} and
src/crush/grammar.h; `crushtool -d` output is the canonical form): tunables,
devices (with device classes), types, buckets, rules, choose_args.
Implemented as a straightforward tokenizer + recursive-descent parser — no
parser framework needed for this grammar.

compile_text(text) -> CrushMap     (builds class shadow trees when classes
                                    are present, so `take X class Y` works)
decompile(m)       -> str          (matches the reference's emitted layout,
                                    shadow buckets elided, `take` splits the
                                    shadow id back into name + class)
"""

from __future__ import annotations

import re

from ceph_tpu.crush.types import (
    BucketAlg,
    CrushMap,
    ChooseArgs,
    Rule,
    RuleOp,
    Tunables,
)

_ALG_NAMES = {
    BucketAlg.UNIFORM: "uniform",
    BucketAlg.LIST: "list",
    BucketAlg.TREE: "tree",
    BucketAlg.STRAW: "straw",
    BucketAlg.STRAW2: "straw2",
}
_ALG_BY_NAME = {v: k for k, v in _ALG_NAMES.items()}

_SET_STEPS = {
    "set_choose_tries": RuleOp.SET_CHOOSE_TRIES,
    "set_choose_local_tries": RuleOp.SET_CHOOSE_LOCAL_TRIES,
    "set_choose_local_fallback_tries": RuleOp.SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    "set_chooseleaf_tries": RuleOp.SET_CHOOSELEAF_TRIES,
    "set_chooseleaf_vary_r": RuleOp.SET_CHOOSELEAF_VARY_R,
    "set_chooseleaf_stable": RuleOp.SET_CHOOSELEAF_STABLE,
}
_SET_STEP_NAMES = {v: k for k, v in _SET_STEPS.items()}

_TUNABLES = (
    "choose_local_tries",
    "choose_local_fallback_tries",
    "choose_total_tries",
    "chooseleaf_descend_once",
    "chooseleaf_vary_r",
    "chooseleaf_stable",
    "straw_calc_version",
    "allowed_bucket_algs",
)


class CompileError(ValueError):
    pass


def _tokenize(text: str) -> list[str]:
    out = []
    for line in text.splitlines():
        line = line.split("#", 1)[0]
        for tok in re.findall(r"[\[\]{}]|[^\s\[\]{}]+", line):
            out.append(tok)
    return out


class _Parser:
    def __init__(self, toks: list[str]):
        self.toks = toks
        self.i = 0

    def peek(self) -> str | None:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> str:
        if self.i >= len(self.toks):
            raise CompileError("unexpected end of input")
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, want: str) -> None:
        t = self.next()
        if t != want:
            raise CompileError(f"expected {want!r}, got {t!r}")

    def int_(self) -> int:
        t = self.next()
        try:
            return int(t)
        except ValueError:
            raise CompileError(f"expected integer, got {t!r}")

    def fixed(self) -> int:
        t = self.next()
        try:
            return int(round(float(t) * 0x10000))
        except ValueError:
            raise CompileError(f"expected weight, got {t!r}")


def compile_text(text: str) -> CrushMap:
    """Parse a text crushmap into a CrushMap."""
    p = _Parser(_tokenize(text))
    m = CrushMap(Tunables())
    m.type_names = {}
    devices: dict[str, int] = {}
    dev_class: dict[int, str] = {}
    # (bucket body parsed before ids of referenced buckets may be known?
    #  no: the text format requires children to be defined first, same as
    #  the reference compiler)
    name_to_item: dict[str, int] = {}
    pending_rules: list[tuple[str | None, list]] = []
    shadow_decls: dict[int, dict[str, int]] = {}  # bucket -> class -> id

    def resolve(name: str) -> int:
        if name in name_to_item:
            return name_to_item[name]
        raise CompileError(f"unknown item {name!r}")

    while (tok := p.peek()) is not None:
        if tok == "tunable":
            p.next()
            key = p.next()
            val = p.int_()
            if key not in _TUNABLES:
                raise CompileError(f"unknown tunable {key!r}")
            setattr(m.tunables, key, val)
        elif tok == "device":
            p.next()
            did = p.int_()
            name = p.next()
            devices[name] = did
            name_to_item[name] = did
            m.item_names[did] = name
            m.max_devices = max(m.max_devices, did + 1)
            if p.peek() == "class":
                p.next()
                dev_class[did] = p.next()
        elif tok == "type":
            p.next()
            tid = p.int_()
            m.type_names[tid] = p.next()
        elif tok == "rule":
            p.next()
            name = None
            if p.peek() != "{":
                name = p.next()
            p.expect("{")
            body: dict = {"steps": []}
            while p.peek() != "}":
                k = p.next()
                if k in ("id", "ruleset"):
                    body["id"] = p.int_()
                elif k == "type":
                    t = p.next()
                    body["type"] = {"replicated": 1, "erasure": 3}.get(
                        t, None
                    )
                    if body["type"] is None:
                        body["type"] = int(t)
                elif k == "min_size":
                    body["min_size"] = p.int_()
                elif k == "max_size":
                    body["max_size"] = p.int_()
                elif k == "step":
                    body["steps"].append(_parse_step(p))
                else:
                    raise CompileError(f"unknown rule field {k!r}")
            p.expect("}")
            pending_rules.append((name, body))
        elif tok == "choose_args":
            p.next()
            ca_id_tok = p.next()
            try:
                ca_id: int | str = int(ca_id_tok)
                # the reference stores choose_args keys as s64 but some
                # dumps print them as u64 (the compat set shows up as
                # 18446744073709551615): normalize so -1 stays -1 and
                # the binary codec's i64 encode can round-trip the map
                if ca_id >= 1 << 63:
                    ca_id -= 1 << 64
            except ValueError:
                ca_id = ca_id_tok
            ca = ChooseArgs()
            p.expect("{")
            while p.peek() == "{":
                p.next()
                bucket_id = None
                ws = None
                ids = None
                while p.peek() != "}":
                    k = p.next()
                    if k == "bucket_id":
                        bucket_id = p.int_()
                    elif k == "weight_set":
                        ws = []
                        p.expect("[")
                        while p.peek() == "[":
                            p.next()
                            row = []
                            while p.peek() != "]":
                                row.append(p.fixed())
                            p.expect("]")
                            ws.append(row)
                        p.expect("]")
                    elif k == "ids":
                        ids = []
                        p.expect("[")
                        while p.peek() != "]":
                            ids.append(p.int_())
                        p.expect("]")
                    else:
                        raise CompileError(
                            f"unknown choose_args field {k!r}"
                        )
                p.expect("}")
                if bucket_id is None:
                    raise CompileError("choose_args entry missing bucket_id")
                if ws is not None:
                    ca.weight_sets[bucket_id] = ws
                if ids is not None:
                    ca.ids[bucket_id] = ids
            p.expect("}")
            m.choose_args[ca_id] = ca
        else:
            # bucket: <typename> <name> { ... }
            typename = p.next()
            tid = None
            for t, n in m.type_names.items():
                if n == typename:
                    tid = t
                    break
            if tid is None:
                raise CompileError(
                    f"unknown keyword or type name {typename!r}"
                )
            bname = p.next()
            p.expect("{")
            bid = None
            alg = None
            hash_ = 0
            items: list[tuple[int, int | None, int | None]] = []
            class_ids: dict[str, int] = {}
            while p.peek() != "}":
                k = p.next()
                if k == "id":
                    v = p.int_()
                    if p.peek() == "class":
                        p.next()
                        class_ids[p.next()] = v  # declared shadow id
                    else:
                        bid = v
                elif k == "alg":
                    a = p.next()
                    if a not in _ALG_BY_NAME:
                        raise CompileError(f"unknown bucket alg {a!r}")
                    alg = _ALG_BY_NAME[a]
                elif k == "hash":
                    hash_ = p.int_()
                elif k == "item":
                    iname = p.next()
                    w = None
                    pos = None
                    while p.peek() in ("weight", "pos"):
                        if p.next() == "weight":
                            w = p.fixed()
                        else:
                            pos = p.int_()
                    items.append((resolve(iname), w, pos))
                else:
                    raise CompileError(f"unknown bucket field {k!r}")
            p.expect("}")
            if alg is None:
                raise CompileError(f"bucket {bname!r} missing alg")
            # place items honoring explicit pos
            n = len(items)
            slot_items: list[int | None] = [None] * n
            slot_weights: list[int] = [0] * n
            unplaced = []
            for item, w, pos in items:
                if w is None:
                    b = m.buckets.get(item)
                    w = b.weight if b is not None else 0
                if pos is not None:
                    if pos >= n or slot_items[pos] is not None:
                        raise CompileError(
                            f"bad pos {pos} in bucket {bname!r}"
                        )
                    slot_items[pos] = item
                    slot_weights[pos] = w
                else:
                    unplaced.append((item, w))
            fill = iter(unplaced)
            for j in range(n):
                if slot_items[j] is None:
                    item, w = next(fill)
                    slot_items[j] = item
                    slot_weights[j] = w
            bid = m.add_bucket(
                alg, tid, slot_items, slot_weights, id=bid, hash=hash_,
                name=bname,
            )
            name_to_item[bname] = bid
            if class_ids:
                shadow_decls[bid] = class_ids

    for did, cname in dev_class.items():
        m.item_classes[did] = cname
        m.class_id(cname)
    m.build_class_shadow_trees(preferred=shadow_decls)

    # resolve + install rules (after buckets & shadows exist)
    for name, body in pending_rules:
        steps = []
        for st in body["steps"]:
            op, a1, a2 = st
            if op == RuleOp.TAKE:
                iname, cname = a1
                item = resolve(iname)
                if cname is not None:
                    cid = m.class_id(cname)
                    shadow = m.class_bucket.get(item, {}).get(cid)
                    if shadow is None:
                        raise CompileError(
                            f"no class {cname!r} subtree under {iname!r}"
                        )
                    item = shadow
                steps.append((RuleOp.TAKE, item, 0))
            elif op in (
                RuleOp.CHOOSE_FIRSTN, RuleOp.CHOOSE_INDEP,
                RuleOp.CHOOSELEAF_FIRSTN, RuleOp.CHOOSELEAF_INDEP,
            ):
                tname = a2
                tid = None
                for t, n in m.type_names.items():
                    if n == tname:
                        tid = t
                        break
                if tid is None:
                    raise CompileError(f"unknown type {tname!r}")
                steps.append((op, a1, tid))
            else:
                steps.append((op, a1, a2))
        rule = Rule(
            steps,
            ruleset=body.get("id", len(m.rules)),
            type=body.get("type", 1),
            min_size=body.get("min_size", 1),
            max_size=body.get("max_size", 10),
        )
        ruleno = m.add_rule(rule, body.get("id"))
        if name:
            m.rule_names[ruleno] = name
    m.refresh_derived()
    return m


def _parse_step(p: _Parser):
    kind = p.next()
    if kind == "noop":
        return (RuleOp.NOOP, 0, 0)
    if kind == "take":
        name = p.next()
        cname = None
        if p.peek() == "class":
            p.next()
            cname = p.next()
        return (RuleOp.TAKE, (name, cname), 0)
    if kind == "emit":
        return (RuleOp.EMIT, 0, 0)
    if kind in _SET_STEPS:
        return (_SET_STEPS[kind], p.int_(), 0)
    if kind in ("choose", "chooseleaf"):
        mode = p.next()
        if mode not in ("firstn", "indep"):
            raise CompileError(f"bad choose mode {mode!r}")
        n = p.int_()
        p.expect("type")
        tname = p.next()
        op = {
            ("choose", "firstn"): RuleOp.CHOOSE_FIRSTN,
            ("choose", "indep"): RuleOp.CHOOSE_INDEP,
            ("chooseleaf", "firstn"): RuleOp.CHOOSELEAF_FIRSTN,
            ("chooseleaf", "indep"): RuleOp.CHOOSELEAF_INDEP,
        }[(kind, mode)]
        return (op, n, tname)
    raise CompileError(f"unknown step {kind!r}")


# -- decompile --------------------------------------------------------------

def _fixedpoint(v: int) -> str:
    return f"{v / 0x10000:.5f}"


def _item_name(m: CrushMap, i: int) -> str:
    if i in m.item_names:
        return m.item_names[i]
    return f"device{i}" if i >= 0 else f"bucket{-1 - i}"


def _type_name(m: CrushMap, t: int) -> str:
    if t in m.type_names:
        return m.type_names[t]
    return "device" if t == 0 else f"type{t}"


def _shadow_ids(m: CrushMap) -> set[int]:
    return {
        sid for per in m.class_bucket.values() for sid in per.values()
    }


def decompile(m: CrushMap) -> str:
    """Emit the text form (layout-compatible with `crushtool -d`)."""
    out = ["# begin crush map\n"]
    t = m.tunables
    for key in _TUNABLES:
        out.append(f"tunable {key} {getattr(t, key)}\n")

    out.append("\n# devices\n")
    for did in range(m.max_devices):
        # unnamed device slots are holes: no line (reference
        # CrushCompiler.cc decompile device loop)
        name = m.item_names.get(did)
        if name is None:
            if any(did in b.items for b in m.buckets.values()):
                name = f"osd.{did}"  # in-tree but unnamed
            else:
                continue
        line = f"device {did} {name}"
        if did in m.item_classes:
            line += f" class {m.item_classes[did]}"
        out.append(line + "\n")

    out.append("\n# types\n")
    for tid in sorted(m.type_names):
        out.append(f"type {tid} {m.type_names[tid]}\n")

    out.append("\n# buckets\n")
    shadows = _shadow_ids(m)
    done: set[int] = set()

    def emit_bucket(bid: int) -> None:
        if bid in done or bid >= 0 or bid in shadows:
            return
        done.add(bid)
        b = m.buckets[bid]
        for it in b.items:
            if it < 0:
                emit_bucket(it)
        out.append(f"{_type_name(m, b.type)} {_item_name(m, bid)} {{\n")
        out.append(f"\tid {bid}\t\t# do not change unnecessarily\n")
        for cid, sid in sorted(m.class_bucket.get(bid, {}).items()):
            out.append(
                f"\tid {sid} class {m.class_names[cid]}"
                "\t\t# do not change unnecessarily\n"
            )
        out.append(f"\t# weight {_fixedpoint(b.weight)}\n")
        out.append(f"\talg {_ALG_NAMES[b.alg]}\n")
        out.append(f"\thash {b.hash}\t# rjenkins1\n")
        for it, w in zip(b.items, b.weights):
            out.append(
                f"\titem {_item_name(m, it)} weight {_fixedpoint(w)}\n"
            )
        out.append("}\n")

    for bid in sorted(m.buckets, reverse=True):
        emit_bucket(bid)

    out.append("\n# rules\n")
    for ruleno, rule in enumerate(m.rules):
        if rule is None:
            continue
        rname = m.rule_names.get(ruleno, f"rule{ruleno}")
        out.append(f"rule {rname} {{\n")
        out.append(f"\tid {ruleno}\n")
        tname = {1: "replicated", 3: "erasure"}.get(
            rule.type, str(rule.type)
        )
        out.append(f"\ttype {tname}\n")
        out.append(f"\tmin_size {rule.min_size}\n")
        out.append(f"\tmax_size {rule.max_size}\n")
        for op, a1, a2 in rule.steps:
            if op == RuleOp.NOOP:
                out.append("\tstep noop\n")
            elif op == RuleOp.TAKE:
                orig, cid = m.split_id_class(a1)
                line = f"\tstep take {_item_name(m, orig)}"
                if cid >= 0:
                    line += f" class {m.class_names[cid]}"
                out.append(line + "\n")
            elif op == RuleOp.EMIT:
                out.append("\tstep emit\n")
            elif op in _SET_STEP_NAMES:
                out.append(f"\tstep {_SET_STEP_NAMES[op]} {a1}\n")
            elif op in (RuleOp.CHOOSE_FIRSTN, RuleOp.CHOOSE_INDEP):
                mode = "firstn" if op == RuleOp.CHOOSE_FIRSTN else "indep"
                out.append(
                    f"\tstep choose {mode} {a1} type {_type_name(m, a2)}\n"
                )
            elif op in (RuleOp.CHOOSELEAF_FIRSTN, RuleOp.CHOOSELEAF_INDEP):
                mode = (
                    "firstn" if op == RuleOp.CHOOSELEAF_FIRSTN else "indep"
                )
                out.append(
                    f"\tstep chooseleaf {mode} {a1} "
                    f"type {_type_name(m, a2)}\n"
                )
        out.append("}\n")

    if m.choose_args:
        out.append("\n# choose_args\n")
        for ca_id in sorted(m.choose_args, key=str):
            ca = m.choose_args[ca_id]
            out.append(f"choose_args {ca_id} {{\n")
            for bucket_id in sorted(
                set(ca.weight_sets) | set(ca.ids), reverse=True
            ):
                out.append("  {\n")
                out.append(f"    bucket_id {bucket_id}\n")
                if bucket_id in ca.weight_sets:
                    out.append("    weight_set [\n")
                    for row in ca.weight_sets[bucket_id]:
                        out.append(
                            "      [ "
                            + " ".join(_fixedpoint(w) for w in row)
                            + " ]\n"
                        )
                    out.append("    ]\n")
                if bucket_id in ca.ids:
                    out.append(
                        "    ids [ "
                        + " ".join(str(i) for i in ca.ids[bucket_id])
                        + " ]\n"
                    )
                out.append("  }\n")
            out.append("}\n")

    out.append("\n# end crush map\n")
    return "".join(out)
