"""Pure-Python reference CRUSH mapper — the host-side semantic oracle.

Bit-exact re-implementation of the mapping algorithm of the C reference
(reference src/crush/mapper.c): rule interpreter (crush_do_rule), the five
bucket choose functions, firstn/indep replica selection with the full
reject/collision/retry semantics, and all tunables.

This is NOT the fast path (that's ceph_tpu.crush.mapper_jax); it exists to

1. pin the semantics in readable Python, differentially tested against a
   shim-compiled build of the actual reference C (tests/oracle), and
2. serve as the oracle the vmapped TPU kernel is tested against on maps /
   inputs where the C build is unavailable.

All arithmetic uses Python ints with explicit 32/64-bit wrapping to mirror C
integer semantics.
"""

from __future__ import annotations

from ceph_tpu.core.rjenkins import crush_hash32_2, crush_hash32_3, crush_hash32_4
from ceph_tpu.core.lntable import crush_ln_np
from ceph_tpu.core.intmath import div_trunc_int
from ceph_tpu.crush.types import (
    Bucket,
    BucketAlg,
    ChooseArgs,
    CrushMap,
    ITEM_NONE,
    ITEM_UNDEF,
    RuleOp,
)

S64_MIN = -(1 << 63)


def _h2(a, b):
    return int(crush_hash32_2(a & 0xFFFFFFFF, b & 0xFFFFFFFF))


def _h3(a, b, c):
    return int(crush_hash32_3(a & 0xFFFFFFFF, b & 0xFFFFFFFF, c & 0xFFFFFFFF))


def _h4(a, b, c, d):
    return int(
        crush_hash32_4(a & 0xFFFFFFFF, b & 0xFFFFFFFF, c & 0xFFFFFFFF, d & 0xFFFFFFFF)
    )


class _PermState:
    """Per-bucket memoized Fisher-Yates permutation state
    (struct crush_work_bucket, reference src/crush/crush.h:539-547)."""

    __slots__ = ("perm_x", "perm_n", "perm")

    def __init__(self):
        self.perm_x = 0
        self.perm_n = 0
        self.perm: list[int] = []


class WorkSpace:
    """crush_work equivalent: per-bucket perm state, reset per map
    (reference src/crush/mapper.c:858-887)."""

    def __init__(self):
        self.work: dict[int, _PermState] = {}

    def for_bucket(self, bucket_id: int) -> _PermState:
        st = self.work.get(bucket_id)
        if st is None:
            st = self.work[bucket_id] = _PermState()
        return st


def bucket_perm_choose(bucket: Bucket, work: _PermState, x: int, r: int) -> int:
    """reference src/crush/mapper.c:73-131."""
    pr = r % bucket.size
    if work.perm_x != (x & 0xFFFFFFFF) or work.perm_n == 0:
        work.perm_x = x & 0xFFFFFFFF
        if pr == 0:
            s = _h3(x, bucket.id, 0) % bucket.size
            work.perm = [0] * bucket.size
            work.perm[0] = s
            work.perm_n = 0xFFFF  # magic: only slot 0 is valid
            return bucket.items[s]
        work.perm = list(range(bucket.size))
        work.perm_n = 0
    elif work.perm_n == 0xFFFF:
        # clean up after the r=0 fast path
        s = work.perm[0]
        work.perm = list(range(bucket.size))
        work.perm[0] = s
        work.perm[s] = 0
        work.perm_n = 1

    while work.perm_n <= pr:
        p = work.perm_n
        if p < bucket.size - 1:
            i = _h3(x, bucket.id, p) % (bucket.size - p)
            if i:
                work.perm[p + i], work.perm[p] = work.perm[p], work.perm[p + i]
        work.perm_n += 1
    return bucket.items[work.perm[pr]]


def bucket_list_choose(bucket: Bucket, x: int, r: int) -> int:
    """reference src/crush/mapper.c:141-164."""
    assert bucket.sum_weights is not None
    for i in range(bucket.size - 1, -1, -1):
        w = _h4(x, bucket.items[i], r, bucket.id) & 0xFFFF
        w = (w * bucket.sum_weights[i]) >> 16
        if w < bucket.weights[i]:
            return bucket.items[i]
    return bucket.items[0]


def bucket_tree_choose(bucket: Bucket, x: int, r: int) -> int:
    """reference src/crush/mapper.c:195-222."""
    nw = bucket.node_weights
    assert nw is not None
    n = len(nw) >> 1  # root
    while not (n & 1):
        w = nw[n]
        t = (_h4(x, n, r, bucket.id) * w) >> 32
        h = 0
        m = n
        while (m & 1) == 0:
            h += 1
            m >>= 1
        left = n - (1 << (h - 1))
        n = left if t < nw[left] else n + (1 << (h - 1))
    return bucket.items[n >> 1]


def bucket_straw_choose(bucket: Bucket, x: int, r: int) -> int:
    """reference src/crush/mapper.c:227-245."""
    assert bucket.straws is not None
    high = 0
    high_draw = 0
    for i in range(bucket.size):
        draw = (_h3(x, bucket.items[i], r) & 0xFFFF) * bucket.straws[i]
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def _exp_draw(x: int, y: int, z: int, weight: int) -> int:
    """generate_exponential_distribution (reference src/crush/mapper.c:334-359):
    table-driven -ln(U)/w in 64-bit fixed point."""
    u = _h3(x, y, z) & 0xFFFF
    ln = int(crush_ln_np(u)) - 0x1000000000000
    return div_trunc_int(ln, weight)


def bucket_straw2_choose(
    bucket: Bucket,
    x: int,
    r: int,
    arg_weights: list[int] | None,
    arg_ids: list[int] | None,
) -> int:
    """reference src/crush/mapper.c:361-384."""
    weights = arg_weights if arg_weights is not None else bucket.weights
    ids = arg_ids if arg_ids is not None else bucket.items
    high = 0
    high_draw = 0
    for i in range(bucket.size):
        if weights[i]:
            draw = _exp_draw(x, ids[i], r, weights[i])
        else:
            draw = S64_MIN
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def _choose_arg_for(
    choose_args: ChooseArgs | None, bucket: Bucket, position: int
) -> tuple[list[int] | None, list[int] | None]:
    """get_choose_arg_weights/_ids (reference src/crush/mapper.c:309-326)."""
    if choose_args is None:
        return None, None
    ws = choose_args.weight_sets.get(bucket.id)
    ids = choose_args.ids.get(bucket.id)
    w = None
    if ws:
        pos = min(position, len(ws) - 1)
        w = ws[pos]
    return w, ids


def crush_bucket_choose(
    map_: CrushMap,
    work: WorkSpace,
    bucket: Bucket,
    x: int,
    r: int,
    choose_args: ChooseArgs | None,
    position: int,
    recorder=None,
) -> int:
    """reference src/crush/mapper.c:387-418.

    recorder: optional decision recorder (crush.explain.ExplainRecorder
    protocol).  With `recorder.detail`, straw2 draws are re-derived and
    emitted per item — the winner/loser view `crushtool explain` prints.
    Never changes the choice."""
    assert bucket.size > 0
    if bucket.alg == BucketAlg.UNIFORM:
        return bucket_perm_choose(bucket, work.for_bucket(bucket.id), x, r)
    if bucket.alg == BucketAlg.LIST:
        return bucket_list_choose(bucket, x, r)
    if bucket.alg == BucketAlg.TREE:
        return bucket_tree_choose(bucket, x, r)
    if bucket.alg == BucketAlg.STRAW:
        return bucket_straw_choose(bucket, x, r)
    if bucket.alg == BucketAlg.STRAW2:
        aw, ai = _choose_arg_for(choose_args, bucket, position)
        item = bucket_straw2_choose(bucket, x, r, aw, ai)
        if recorder is not None and recorder.detail:
            weights = aw if aw is not None else bucket.weights
            draws = [
                (bucket.items[i],
                 _exp_draw(x, (ai if ai is not None else bucket.items)[i],
                           r, weights[i]) if weights[i] else S64_MIN)
                for i in range(bucket.size)
            ]
            recorder.emit(ev="straw2", bucket=bucket.id, r=r,
                          winner=item, draws=draws)
        return item
    return bucket.items[0]


def is_out(map_: CrushMap, weight: list[int], item: int, x: int) -> bool:
    """reference src/crush/mapper.c:424-438."""
    if item >= len(weight):
        return True
    w = weight[item]
    if w >= 0x10000:
        return False
    if w == 0:
        return True
    return (_h2(x, item) & 0xFFFF) >= w


def crush_choose_firstn(
    map_: CrushMap,
    work: WorkSpace,
    bucket: Bucket,
    weight: list[int],
    x: int,
    numrep: int,
    type_: int,
    out: list[int],
    outpos: int,
    out_size: int,
    tries: int,
    recurse_tries: int,
    local_retries: int,
    local_fallback_retries: int,
    recurse_to_leaf: bool,
    vary_r: int,
    stable: int,
    out2: list[int] | None,
    parent_r: int,
    choose_args: ChooseArgs | None,
    choose_tries_hist: list[int] | None = None,
    recorder=None,
) -> int:
    """reference src/crush/mapper.c:460-648.

    recorder: optional decision recorder; one `draw` event per attempt
    (item, r, final status), `place` on success, `leaf_enter`/`leaf_exit`
    around chooseleaf recursions.  Pure observation — the walk itself is
    untouched."""
    count = out_size
    rep = 0 if stable else outpos
    while rep < numrep and count > 0:
        ftotal = 0
        skip_rep = False
        item = 0
        retry_descent = True
        while retry_descent:
            retry_descent = False
            in_ = bucket
            flocal = 0
            retry_bucket = True
            while retry_bucket:
                retry_bucket = False
                collide = False
                r = rep + parent_r + ftotal

                def _draw(status, it=None, bkt=None):
                    if recorder is not None:
                        recorder.emit(
                            ev="draw", rep=rep, r=r, ftotal=ftotal,
                            bucket=in_.id if bkt is None else bkt,
                            item=it, status=status,
                        )

                if in_.size == 0:
                    reject = True
                    _draw("empty")
                else:
                    if (
                        local_fallback_retries > 0
                        and flocal >= (in_.size >> 1)
                        and flocal > local_fallback_retries
                    ):
                        item = bucket_perm_choose(
                            in_, work.for_bucket(in_.id), x, r
                        )
                    else:
                        item = crush_bucket_choose(
                            map_, work, in_, x, r, choose_args, outpos,
                            recorder=recorder,
                        )
                    if item >= map_.max_devices:
                        skip_rep = True
                        _draw("skip_device_id", item)
                        break

                    child = map_.buckets.get(item) if item < 0 else None
                    if item < 0 and child is None:
                        # dangling bucket id ("bad item type" path; C skips
                        # when -1-item >= max_buckets)
                        skip_rep = True
                        _draw("skip_dangling", item)
                        break
                    itemtype = child.type if item < 0 else 0

                    if itemtype != type_:
                        if item >= 0:
                            skip_rep = True
                            _draw("skip_type", item)
                            break
                        _draw("descend", item)
                        in_ = child
                        retry_bucket = True
                        continue

                    for i in range(outpos):
                        if out[i] == item:
                            collide = True
                            break

                    reject = False
                    reject_why = None
                    if not collide and recurse_to_leaf:
                        if item < 0:
                            sub_r = (r >> (vary_r - 1)) if vary_r else 0
                            if recorder is not None:
                                recorder.emit(ev="leaf_enter", rep=rep,
                                              bucket=item, r=sub_r)
                                recorder.depth += 1
                            got = crush_choose_firstn(
                                map_,
                                work,
                                map_.buckets[item],
                                weight,
                                x,
                                1 if stable else outpos + 1,
                                0,
                                out2,  # type: ignore[arg-type]
                                outpos,
                                count,
                                recurse_tries,
                                0,
                                local_retries,
                                local_fallback_retries,
                                False,
                                vary_r,
                                stable,
                                None,
                                sub_r,
                                choose_args,
                                choose_tries_hist,
                                recorder=recorder,
                            )
                            if recorder is not None:
                                recorder.depth -= 1
                                recorder.emit(ev="leaf_exit", rep=rep,
                                              ok=got > outpos)
                            if got <= outpos:
                                reject = True
                                reject_why = "reject_leaf"
                        else:
                            while len(out2) <= outpos:  # type: ignore[arg-type]
                                out2.append(ITEM_NONE)  # type: ignore[union-attr]
                            out2[outpos] = item  # type: ignore[index]

                    if not reject and not collide:
                        if itemtype == 0:
                            reject = is_out(map_, weight, item, x)
                            if reject:
                                reject_why = "out"
                    _draw("collide" if collide
                          else (reject_why or "ok") if reject else "ok",
                          item)

                if reject or collide:
                    ftotal += 1
                    flocal += 1
                    if collide and flocal <= local_retries:
                        retry_bucket = True
                    elif (
                        local_fallback_retries > 0
                        and flocal <= in_.size + local_fallback_retries
                    ):
                        retry_bucket = True
                    elif ftotal < tries:
                        retry_descent = True
                        break  # leave retry_bucket loop
                    else:
                        skip_rep = True
                        break

        if skip_rep:
            rep += 1
            continue

        # extend out if needed (C writes into caller-sized scratch)
        while len(out) <= outpos:
            out.append(ITEM_NONE)
        out[outpos] = item
        outpos += 1
        count -= 1
        if choose_tries_hist is not None and ftotal <= len(choose_tries_hist) - 1:
            choose_tries_hist[ftotal] += 1
        if recorder is not None:
            recorder.emit(ev="place", rep=rep, item=item, ftotal=ftotal,
                          outpos=outpos - 1)
        rep += 1

    return outpos


def crush_choose_indep(
    map_: CrushMap,
    work: WorkSpace,
    bucket: Bucket,
    weight: list[int],
    x: int,
    left: int,
    numrep: int,
    type_: int,
    out: list[int],
    outpos: int,
    tries: int,
    recurse_tries: int,
    recurse_to_leaf: bool,
    out2: list[int] | None,
    parent_r: int,
    choose_args: ChooseArgs | None,
    choose_tries_hist: list[int] | None = None,
    recorder=None,
) -> None:
    """reference src/crush/mapper.c:655-843."""
    endpos = outpos + left
    while len(out) < endpos:
        out.append(ITEM_NONE)
    if out2 is not None:
        while len(out2) < endpos:
            out2.append(ITEM_NONE)

    for rep in range(outpos, endpos):
        out[rep] = ITEM_UNDEF
        if out2 is not None:
            out2[rep] = ITEM_UNDEF

    ftotal = 0
    while left > 0 and ftotal < tries:
        for rep in range(outpos, endpos):
            if out[rep] != ITEM_UNDEF:
                continue
            in_ = bucket
            while True:
                r = rep + parent_r
                if in_.alg == BucketAlg.UNIFORM and in_.size % numrep == 0:
                    r += (numrep + 1) * ftotal
                else:
                    r += numrep * ftotal

                def _draw(status, it=None):
                    if recorder is not None:
                        recorder.emit(
                            ev="draw", rep=rep, r=r, ftotal=ftotal,
                            bucket=in_.id, item=it, status=status,
                        )

                if in_.size == 0:
                    _draw("empty")
                    break

                item = crush_bucket_choose(
                    map_, work, in_, x, r, choose_args, outpos,
                    recorder=recorder,
                )
                if item >= map_.max_devices:
                    out[rep] = ITEM_NONE
                    if out2 is not None:
                        out2[rep] = ITEM_NONE
                    left -= 1
                    _draw("skip_device_id", item)
                    break

                child = map_.buckets.get(item) if item < 0 else None
                if item < 0 and child is None:
                    out[rep] = ITEM_NONE
                    if out2 is not None:
                        out2[rep] = ITEM_NONE
                    left -= 1
                    _draw("skip_dangling", item)
                    break
                itemtype = child.type if item < 0 else 0

                if itemtype != type_:
                    if item >= 0:
                        out[rep] = ITEM_NONE
                        if out2 is not None:
                            out2[rep] = ITEM_NONE
                        left -= 1
                        _draw("skip_type", item)
                        break
                    _draw("descend", item)
                    in_ = child
                    continue

                collide = False
                for i in range(outpos, endpos):
                    if out[i] == item:
                        collide = True
                        break
                if collide:
                    _draw("collide", item)
                    break

                if recurse_to_leaf:
                    if item < 0:
                        if recorder is not None:
                            recorder.emit(ev="leaf_enter", rep=rep,
                                          bucket=item, r=r)
                            recorder.depth += 1
                        crush_choose_indep(
                            map_,
                            work,
                            map_.buckets[item],
                            weight,
                            x,
                            1,
                            numrep,
                            0,
                            out2,  # type: ignore[arg-type]
                            rep,
                            recurse_tries,
                            0,
                            False,
                            None,
                            r,
                            choose_args,
                            choose_tries_hist,
                            recorder=recorder,
                        )
                        if recorder is not None:
                            recorder.depth -= 1
                            recorder.emit(
                                ev="leaf_exit", rep=rep,
                                ok=not (out2 is not None
                                        and out2[rep] == ITEM_NONE),
                            )
                        if out2 is not None and out2[rep] == ITEM_NONE:
                            _draw("reject_leaf", item)
                            break
                    elif out2 is not None:
                        out2[rep] = item

                if itemtype == 0 and is_out(map_, weight, item, x):
                    _draw("out", item)
                    break

                out[rep] = item
                left -= 1
                _draw("ok", item)
                if recorder is not None:
                    recorder.emit(ev="place", rep=rep, item=item,
                                  ftotal=ftotal, outpos=rep)
                break
        ftotal += 1
        if left <= 0:
            break

    # C increments ftotal in the for(;;ftotal++) header even on the
    # iteration that breaks via left==0; the loop above mirrors that.
    for rep in range(outpos, endpos):
        if out[rep] == ITEM_UNDEF:
            out[rep] = ITEM_NONE
        if out2 is not None and out2[rep] == ITEM_UNDEF:
            out2[rep] = ITEM_NONE
    if choose_tries_hist is not None and ftotal <= len(choose_tries_hist) - 1:
        choose_tries_hist[ftotal] += 1


def find_rule(map_: CrushMap, ruleset: int, type_: int, size: int) -> int:
    """reference src/crush/mapper.c:41-54."""
    for i, r in enumerate(map_.rules):
        if (
            r is not None
            and r.ruleset == ruleset
            and r.type == type_
            and r.min_size <= size <= r.max_size
        ):
            return i
    return -1


def do_rule(
    map_: CrushMap,
    ruleno: int,
    x: int,
    result_max: int,
    weight: list[int],
    choose_args: ChooseArgs | int | str | None = None,
    collect_choose_tries: bool = False,
    recorder=None,
) -> list[int]:
    """crush_do_rule (reference src/crush/mapper.c:900-1105).

    Returns the result vector (length <= result_max).  `weight` is the
    per-device 16.16 in/out weight vector (not the crush tree weights).

    recorder: optional decision recorder (crush.explain.ExplainRecorder)
    — emits take/choose/draw/place/emit events and books the post-step
    work vector after every choose step (`recorder.step_result`), the
    host half of the jax-vs-host first-divergence locator.
    """
    if isinstance(choose_args, (int, str)):
        choose_args = map_.choose_args.get(choose_args)

    if ruleno < 0 or ruleno >= len(map_.rules) or map_.rules[ruleno] is None:
        return []
    rule = map_.rules[ruleno]
    t = map_.tunables

    work = WorkSpace()
    hist = None
    if collect_choose_tries:
        if map_.choose_tries_histogram is None:
            map_.choose_tries_histogram = [0] * (t.choose_total_tries + 1)
        hist = map_.choose_tries_histogram

    choose_tries = t.choose_total_tries + 1  # off-by-one compat
    choose_leaf_tries = 0
    choose_local_retries = t.choose_local_tries
    choose_local_fallback_retries = t.choose_local_fallback_tries
    vary_r = t.chooseleaf_vary_r
    stable = t.chooseleaf_stable

    result: list[int] = []
    w: list[int] = []
    o: list[int] = []
    c: list[int] = []
    wsize = 0

    for op, arg1, arg2 in rule.steps:
        firstn = False
        if op == RuleOp.TAKE:
            if (0 <= arg1 < map_.max_devices) or (arg1 < 0 and arg1 in map_.buckets):
                w = [arg1]
                wsize = 1
            if recorder is not None:
                recorder.emit(ev="take", item=arg1, valid=wsize == 1)
        elif op == RuleOp.SET_CHOOSE_TRIES:
            if arg1 > 0:
                choose_tries = arg1
        elif op == RuleOp.SET_CHOOSELEAF_TRIES:
            if arg1 > 0:
                choose_leaf_tries = arg1
        elif op == RuleOp.SET_CHOOSE_LOCAL_TRIES:
            if arg1 >= 0:
                choose_local_retries = arg1
        elif op == RuleOp.SET_CHOOSE_LOCAL_FALLBACK_TRIES:
            if arg1 >= 0:
                choose_local_fallback_retries = arg1
        elif op == RuleOp.SET_CHOOSELEAF_VARY_R:
            if arg1 >= 0:
                vary_r = arg1
        elif op == RuleOp.SET_CHOOSELEAF_STABLE:
            if arg1 >= 0:
                stable = arg1
        elif op in (
            RuleOp.CHOOSELEAF_FIRSTN,
            RuleOp.CHOOSE_FIRSTN,
            RuleOp.CHOOSELEAF_INDEP,
            RuleOp.CHOOSE_INDEP,
        ):
            if op in (RuleOp.CHOOSELEAF_FIRSTN, RuleOp.CHOOSE_FIRSTN):
                firstn = True
            if wsize == 0:
                continue
            recurse_to_leaf = op in (
                RuleOp.CHOOSELEAF_FIRSTN,
                RuleOp.CHOOSELEAF_INDEP,
            )
            if recorder is not None:
                recorder.emit(ev="choose", op=int(op), firstn=firstn,
                              leafy=recurse_to_leaf, numrep=arg1,
                              type=arg2, sources=list(w[:wsize]))
            osize = 0
            o = []
            c = []
            for i in range(wsize):
                numrep = arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        continue
                if w[i] >= 0 or w[i] not in map_.buckets:
                    continue  # bad take value / ITEM_NONE
                bucket = map_.buckets[w[i]]
                if firstn:
                    if choose_leaf_tries:
                        recurse_tries = choose_leaf_tries
                    elif t.chooseleaf_descend_once:
                        recurse_tries = 1
                    else:
                        recurse_tries = choose_tries
                    while len(o) < osize:
                        o.append(ITEM_NONE)
                    while len(c) < osize:
                        c.append(ITEM_NONE)
                    sub_o = o[osize:]
                    sub_c = c[osize:]
                    n = crush_choose_firstn(
                        map_,
                        work,
                        bucket,
                        weight,
                        x,
                        numrep,
                        arg2,
                        sub_o,
                        0,
                        result_max - osize,
                        choose_tries,
                        recurse_tries,
                        choose_local_retries,
                        choose_local_fallback_retries,
                        recurse_to_leaf,
                        vary_r,
                        stable,
                        sub_c,
                        0,
                        choose_args,
                        hist,
                        recorder=recorder,
                    )
                    o = o[:osize] + sub_o
                    c = c[:osize] + sub_c
                    osize += n
                else:
                    out_size = min(numrep, result_max - osize)
                    sub_o: list[int] = []
                    sub_c: list[int] = []
                    crush_choose_indep(
                        map_,
                        work,
                        bucket,
                        weight,
                        x,
                        out_size,
                        numrep,
                        arg2,
                        sub_o,
                        0,
                        choose_tries,
                        choose_leaf_tries if choose_leaf_tries else 1,
                        recurse_to_leaf,
                        sub_c,
                        0,
                        choose_args,
                        hist,
                        recorder=recorder,
                    )
                    o = o[:osize] + sub_o
                    c = c[:osize] + sub_c
                    osize += out_size
            if recurse_to_leaf:
                c = c + [ITEM_NONE] * (osize - len(c))
                o = list(c[:osize]) + o[osize:]
            w = o
            wsize = osize
            if recorder is not None:
                recorder.step_result(list(w[:wsize]))
        elif op == RuleOp.EMIT:
            for i in range(wsize):
                if len(result) >= result_max:
                    break
                result.append(w[i])
            wsize = 0
            if recorder is not None:
                recorder.emit(ev="emit", result=list(result))

    return result
