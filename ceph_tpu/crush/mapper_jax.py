"""Vmapped CRUSH mapper — the TPU hot path.

Design (TPU-first, not a port):

- The rule's step list is *static* per map, so `compile_rule` unrolls the
  rule interpreter (reference src/crush/mapper.c:900-1105 crush_do_rule) at
  trace time: each TAKE/CHOOSE/EMIT becomes straight-line traced code; the
  SET_* steps fold into static Python ints.  There is no device-side
  interpreter — XLA sees one fused integer program per (map, rule).
- Each bucket draw is a masked lane operation over the padded item axis
  (straw2 = hash + table-log + s64 divide + argmax over [S] lanes,
  reference src/crush/mapper.c:361-384), so a single PG's mapping is a few
  hundred VPU lane-ops and the PG axis vmaps cleanly to millions.
- Data-dependent retry loops (reject/collision, reference
  src/crush/mapper.c:460-648) become `lax.while_loop`s whose trip counts are
  bounded by the map's choose_total_tries tunable; descents through the
  hierarchy are `lax.fori_loop`s bounded by the map's static depth.

Bit-exactness: same rjenkins hash, same fixed-point log tables, same s64
truncating divide, same first-max argmax tie-breaking as the C reference.
Differentially tested against ceph_tpu.crush.mapper_ref (itself tested
against the compiled C) in tests/test_mapper_jax.py.

Restrictions (asserted): the legacy tunables choose_local_tries /
choose_local_fallback_tries must be 0 (their localized-retry semantics —
reference src/crush/mapper.c:610-616 — are pre-2014 compat paths that no
modern map uses; the host mapper_ref still supports them).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ceph_tpu.core.lntable import (
    crush_ln_onehot_jax,
    crush_ln_scan_jax,
    ln64k_table,
)
from ceph_tpu.core.rjenkins import crush_hash32_2, crush_hash32_3, crush_hash32_4
from ceph_tpu.crush.soa import CrushArrays
from ceph_tpu.crush.types import BucketAlg, ITEM_NONE, RuleOp
from ceph_tpu.obs import executables as _executables

S64_MIN = -(2**63)  # plain int: converted at trace time (keeps import
                    # free of device ops so backend fallback can happen)

# descent status codes
_DESCENDING = 0
_FOUND = 1
_SKIP = 2
_EMPTY = 3


def _u32(v):
    return jnp.asarray(v).astype(jnp.uint32)


def _h2(a, b):
    return crush_hash32_2(_u32(a), _u32(b), xp=jnp)


def _h3(a, b, c):
    return crush_hash32_3(_u32(a), _u32(b), _u32(c), xp=jnp)


def _h4(a, b, c, d):
    return crush_hash32_4(_u32(a), _u32(b), _u32(c), _u32(d), xp=jnp)


# --------------------------------------------------------------------------
# Trace-once operand tables.
#
# Everything the kernels read that is per-map DATA — bucket rows, straw2
# weight planes, row-level tables, the ln64k lookup — is carried in a
# `tables` pytree passed as a RUNTIME OPERAND to the compiled function, not
# closed over as a Python constant.  Only genuinely structural facts (rule
# program, table shapes, tunables, bucket topology/alg mix) stay baked into
# the trace; `fn.cache_key` is a hashable signature of exactly those facts,
# so two maps that differ only in weights/choose_args values share one
# compiled executable (the caller keys its jit cache on cache_key and feeds
# each map's own `fn.host_tables` as operands).  This is what turns every
# balancer iteration / upmap round from a recompile into a dispatch, and
# what stops XLA constant-folding multi-second literals out of the trace.
# --------------------------------------------------------------------------

_TABLE_FIELDS = (
    "alg",
    "btype",
    "size",
    "items",
    "weights",
    "sum_weights",
    "straws",
    "node_weights",
    "num_nodes",
    "pos_weights",
    "arg_ids",
)

_LN64K_DEV: dict[str, object] = {}  # per-backend device copy (one upload)


def _ln64k_dev():
    import jax as _jax

    b = _jax.default_backend()
    if b not in _LN64K_DEV:
        _LN64K_DEV[b] = jnp.asarray(ln64k_table())
    return _LN64K_DEV[b]


def host_base_tables(A: CrushArrays) -> dict:
    """The per-map base operand tables (numpy; caller device-puts)."""
    t = {f: getattr(A, f) for f in _TABLE_FIELDS}
    t["ln64k"] = ln64k_table()
    return t


def device_tables(host_tables: dict) -> dict:
    """device_put a host table pytree once; the immutable ln64k table is
    shared from a per-backend cache (it never varies across maps)."""
    out = {}
    for k, v in host_tables.items():
        if k == "rowlvl":
            out[k] = {
                kk: {f: jnp.asarray(a) for f, a in tab.items()}
                for kk, tab in v.items()
            }
        elif k == "ln64k":
            out[k] = _ln64k_dev()
        else:
            out[k] = jnp.asarray(v)
    return out


class _DeviceArrays:
    """Traced view of the kernel tables.

    With `tables` (the operand pytree) the fields bind to traced arrays;
    without it (legacy direct-call paths, e.g. tests vmapping a bare
    compile_rule fn) the numpy tables bind as trace constants exactly as
    before."""

    def __init__(self, A: CrushArrays, tables: dict | None = None,
                 ln_impl: str | None = None):
        self.A = A
        if tables is None:
            tables = host_base_tables(A)
        self.tables = tables
        self.ln_impl = ln_impl or _ln_impl()
        for f in _TABLE_FIELDS:
            setattr(self, f, jnp.asarray(tables[f]))
        self.ln64k = tables.get("ln64k")

    def rowlvl(self, key: str) -> dict | None:
        rl = self.tables.get("rowlvl")
        return None if rl is None else rl.get(key)


def _straw2_choose(d: _DeviceArrays, slot, x, r, position):
    """reference src/crush/mapper.c:361-384 + 334-359."""
    A = d.A
    pos = jnp.clip(position, 0, A.positions - 1)
    w = d.pos_weights[pos, slot].astype(jnp.int64)  # [S]
    ids = d.arg_ids[slot]
    lane = jnp.arange(A.max_size)
    mask = lane < d.size[slot]
    u = (_h3(x, ids, r) & 0xFFFF).astype(jnp.uint32)
    ln = jnp.asarray(d.ln64k)[u] - jnp.int64(0x1000000000000)
    draw = lax.div(ln, jnp.maximum(w, 1))
    draw = jnp.where((w > 0) & mask, draw, S64_MIN)
    return d.items[slot, jnp.argmax(draw)]


def _straw_choose(d: _DeviceArrays, slot, x, r):
    """reference src/crush/mapper.c:227-245."""
    A = d.A
    lane = jnp.arange(A.max_size)
    mask = lane < d.size[slot]
    draw = (_h3(x, d.items[slot], r) & 0xFFFF).astype(jnp.uint64) * d.straws[
        slot
    ].astype(jnp.uint64)
    draw = jnp.where(mask, draw, 0)
    return d.items[slot, jnp.argmax(draw)]


def _list_choose(d: _DeviceArrays, slot, x, r):
    """reference src/crush/mapper.c:141-164 (scan from tail; first hit from
    the high end == max index whose scaled hash falls inside its weight)."""
    A = d.A
    bid = -1 - slot
    lane = jnp.arange(A.max_size)
    w = (_h4(x, d.items[slot], r, bid) & 0xFFFF).astype(jnp.uint64)
    w = (w * d.sum_weights[slot].astype(jnp.uint64)) >> 16
    ok = (w < d.weights[slot].astype(jnp.uint64)) & (lane < d.size[slot])
    best = jnp.max(jnp.where(ok, lane, -1))
    return jnp.where(best >= 0, d.items[slot, jnp.maximum(best, 0)], d.items[slot, 0])


def _ctz(n):
    h = jnp.zeros_like(n)
    m = n
    for s in (16, 8, 4, 2, 1):
        z = (m & ((1 << s) - 1)) == 0
        h = jnp.where(z, h + s, h)
        m = jnp.where(z, m >> s, m)
    return h


def _tree_choose(d: _DeviceArrays, slot, x, r):
    """reference src/crush/mapper.c:195-222."""
    bid = -1 - slot

    def cond(n):
        return (n & 1) == 0

    def body(n):
        w = d.node_weights[slot, n].astype(jnp.uint64)
        t = (_h4(x, n, r, bid).astype(jnp.uint64) * w) >> 32
        h = _ctz(n)
        left = n - (1 << (h - 1))
        return jnp.where(
            t < d.node_weights[slot, left].astype(jnp.uint64),
            left,
            n + (1 << (h - 1)),
        )

    n = lax.while_loop(cond, body, d.num_nodes[slot] >> 1)
    return d.items[slot, n >> 1]


def _perm_choose(d: _DeviceArrays, slot, x, r):
    """Uniform buckets (reference src/crush/mapper.c:73-138).  The C keeps
    memoized Fisher-Yates state per bucket; the permutation is a pure
    function of (x, bucket) — the r=0 fast path + lazy continuation produce
    exactly the full Fisher-Yates shuffle — so we compute it statelessly."""
    A = d.A
    bid = -1 - slot
    n = jnp.maximum(d.size[slot], 1)
    pr = jnp.astype(r, jnp.uint32) % jnp.astype(n, jnp.uint32)

    def body(p, perm):
        i = jnp.astype(_h3(x, bid, p), jnp.uint32) % jnp.astype(
            jnp.maximum(n - p, 1), jnp.uint32
        )
        do = p < n - 1
        pi = jnp.where(do, p + i.astype(jnp.int32), p)
        a = perm[p]
        b = perm[pi]
        perm = perm.at[p].set(jnp.where(do, b, a))
        perm = perm.at[pi].set(jnp.where(do, a, b))
        return perm

    perm = lax.fori_loop(
        0, max(A.max_size - 1, 0), body,
        jnp.arange(A.max_size, dtype=jnp.int32),
    )
    return d.items[slot, perm[pr.astype(jnp.int32)]]


def _bucket_choose(d: _DeviceArrays, slot, x, r, position):
    """Dispatch on bucket alg (reference src/crush/mapper.c:387-418).  Only
    algorithms present in the map are traced."""
    A = d.A
    present = sorted(set(int(a) for a in np.asarray(A.alg)) - {0})
    branches = {
        int(BucketAlg.UNIFORM): lambda: _perm_choose(d, slot, x, r),
        int(BucketAlg.LIST): lambda: _list_choose(d, slot, x, r),
        int(BucketAlg.TREE): lambda: _tree_choose(d, slot, x, r),
        int(BucketAlg.STRAW): lambda: _straw_choose(d, slot, x, r),
        int(BucketAlg.STRAW2): lambda: _straw2_choose(d, slot, x, r, position),
    }
    present = [p for p in present if p in branches]
    if len(present) == 1:
        return branches[present[0]]()
    fns = [branches[p] for p in present]
    idx = jnp.searchsorted(jnp.asarray(present), d.alg[slot])
    return lax.switch(jnp.clip(idx, 0, len(fns) - 1), fns)


def _is_out(x, item, dev_weights, weight_max):
    """reference src/crush/mapper.c:424-438."""
    w = dev_weights[jnp.clip(item, 0, weight_max - 1)].astype(jnp.uint32)
    oor = item >= weight_max
    frac_out = (_h2(x, item) & 0xFFFF) >= w
    return oor | ((w < 0x10000) & ((w == 0) | frac_out))


def _walk_bound(A: CrushArrays, start_slots, target_type: int) -> int:
    """Static upper bound on descent length (bucket choices made) from any
    of start_slots until an item of target_type (or a device) emerges.
    The generic bound is the map depth; rules almost always descend from a
    statically-known level (the TAKE bucket, or buckets of the previous
    CHOOSE's type), so each traced level of the fori_loop we can prove
    unreachable is a full straw2 draw saved per candidate per PG."""
    cap = A.max_depth + 1
    start_slots = list(start_slots)
    if not start_slots:
        return cap
    memo: dict[int, int] = {}

    def L(slot: int, stack: frozenset) -> int:
        if slot in stack:
            return cap  # cyclic map: give up, use the cap
        if slot in memo:
            return memo[slot]
        size = int(A.size[slot])
        best = 1
        for it in A.items[slot][:size]:
            it = int(it)
            if it >= 0:
                continue
            cs = -1 - it
            if cs >= A.n_buckets:
                continue
            if int(A.btype[cs]) == target_type:
                continue  # walk ends with this choice
            best = max(best, 1 + L(cs, stack | {slot}))
            if best >= cap:
                break
        memo[slot] = min(best, cap)
        return memo[slot]

    return min(max(L(s, frozenset()) for s in start_slots), cap)


def _slots_of_type(A: CrushArrays, btype: int):
    return [s for s in range(A.n_buckets) if int(A.btype[s]) == btype]


# --------------------------------------------------------------------------
# Statically-unrolled, gather-free descent ("row path").
#
# XLA lowers data-dependent gathers on TPU to a serial scalar loop (~10
# cycles per index — measured 190ms for one descent level's ln64k gathers
# vs ~15ms for any fused arithmetic op over the same lanes), so the
# generic _descend_impl above — whose fori_loop body gathers bucket rows
# by traced slot and ln values by hash — is gather-bound.  The row path
# removes every hot-loop gather:
#
# - The set of buckets reachable at each descent level is *static* (the
#   rule names the TAKE bucket / the previous step's target type), so the
#   descent unrolls into per-level steps over a precomputed reach set.
#   Level 0 after TAKE is a single bucket: its tables fold to constants.
# - Bucket rows (items / choose_args ids / weights / per-item outcome
#   flags) for |reach| > 1 are fetched by a trace-time-unrolled select
#   scan over the reach set — |reach| vector selects of constant rows,
#   pure VPU lane arithmetic that fuses, instead of a serialized gather.
# - crush_ln uses the 129+256-entry select-scan form
#   (ceph_tpu.core.lntable.crush_ln_scan_jax) on accelerator backends;
#   on CPU the 64k-table gather is faster and compiles quicker.
# - Per-item *outcome* (found / skip / keep-descending vs the step's
#   target type) is precomputed on host into the row tables, replacing
#   the btype gather + comparison chain of the generic path.
#
# Levels whose reach contains bucket algorithms without a row-form
# implementation (tree / uniform-perm) or whose reach exceeds
# _REACH_SCAN_MAX fall back to the generic gather step for that level
# only.  Bit-exactness is untouched: the row path computes the same
# draws, same argmax tie-breaking, same status codes (differential suite
# tests/test_mapper_jax.py covers both paths).
# --------------------------------------------------------------------------

_REACH_SCAN_MAX = 8192  # larger reach sets use the gather fallback level
_REACH_ONEHOT_MIN = 24  # reach sets this big fetch rows by one-hot matmul
                        # (MXU) instead of a trace-unrolled select chain

# ROW field indices ([F, S] i32 per bucket)
_RF_ITEM = 0   # item ids
_RF_ID = 1     # choose_args ids (straw2 hash input)
_RF_W = 2      # straw2 position-0 weights (u32 bit pattern)
_RF_OUT = 3    # per-item descent outcome (_FOUND/_SKIP/_DESCENDING)
_RF_STRAW = 4  # straw scalers (u32 bit pattern; straw buckets only)
_RF_LW = 5     # list weights (u32)
_RF_SW = 6     # list prefix sums (u32)
_RF_M0 = 7     # straw2 divide-free reciprocal: limb 0 (bits 0-23 of m)
_RF_M1 = 8     # limb 1 (bits 24-47)
_RF_M2 = 9     # limb 2 (bits 48+, < 2^2)
_RF_L = 10     # shift l = ceil(log2 w); draw = -(n*m >> (49+l))
_N_RF = 11
# SCA field indices ([G] i32 per bucket)
_SF_SIZE = 0
_SF_ALG = 1
_SF_BID = 2


FORCE_ROW_PATH: bool | None = None  # tests override; None = auto


def _use_row_path() -> bool:
    """Row path on accelerators (where gathers serialize); gather/fori path
    on CPU (gathers are cheap there, giant unrolled selects compile slowly)."""
    import jax as _jax

    if FORCE_ROW_PATH is not None:
        return FORCE_ROW_PATH
    return _jax.default_backend() != "cpu"


def _magic_div_consts(w: int) -> tuple[int, int]:
    """Granlund-Montgomery invariant-divisor constants for the straw2 draw:
    floor(n / w) == (n * m) >> (49 + l) for all 0 <= n <= 2^48, where
    l = ceil(log2 w) and m = ceil(2^(49+l) / w).  Proof obligation
    (m*w - 2^(49+l)) < 2^l holds since the residue is < w <= 2^l; the n
    range covers crush_ln's full output (n = 2^48 at u=0).  Verified
    exhaustively against lax.div in tests/test_mapper_jax.py."""
    assert w >= 1
    l = max(0, (int(w) - 1).bit_length())
    m = -((-(1 << (49 + l))) // int(w))  # ceil division
    return m, l


class _RowLevel:
    """One descent level: reach set + packed row tables.

    `key` names this level's slot in the operand pytree
    (host_tables["rowlvl"][key]); the tables themselves are DATA (weights,
    magic-divide constants, outcome codes) and ride as runtime operands,
    while the reach list / alg mix / field count are structural and go
    into the kernel's cache_key."""

    def __init__(self, A: CrushArrays, reach: list[int], target_type: int,
                 key: str = ""):
        self.key = key
        self.reach = reach
        algs = {int(A.alg[s]) for s in reach}
        self.algs = algs
        self.row_ok = (
            algs <= {int(BucketAlg.STRAW2), int(BucketAlg.STRAW),
                     int(BucketAlg.LIST)}
            and len(reach) <= _REACH_SCAN_MAX
            and A.positions == 1
        )
        self.OH = None
        self.REACH = None
        if not self.row_ok:
            return
        S = A.max_size
        F = 7 if int(BucketAlg.LIST) in algs or int(BucketAlg.STRAW) in algs \
            else 4
        if int(BucketAlg.STRAW2) in algs:
            F = _N_RF
        self.F = F
        row = np.zeros((len(reach), F, S), np.int32)
        sca = np.zeros((len(reach), 3), np.int32)
        magic_memo: dict[int, tuple[int, int]] = {}
        for k, s in enumerate(reach):
            n = int(A.size[s])
            row[k, _RF_ITEM] = A.items[s]
            row[k, _RF_ID] = A.arg_ids[s]
            row[k, _RF_W] = A.pos_weights[0, s].view(np.int32)
            if F >= _N_RF:
                for j in range(n):  # only real slots; pads stay w=0
                    w = int(A.pos_weights[0, s, j])
                    if w > 0:
                        if w not in magic_memo:
                            magic_memo[w] = _magic_div_consts(w)
                        m, l = magic_memo[w]
                        row[k, _RF_M0, j] = m & 0xFFFFFF
                        row[k, _RF_M1, j] = (m >> 24) & 0xFFFFFF
                        row[k, _RF_M2, j] = m >> 48
                        row[k, _RF_L, j] = l
            out = np.full(S, _SKIP, np.int32)
            for j in range(n):
                it = int(A.items[s, j])
                if it < 0:
                    cs = -1 - it
                    if cs >= A.n_buckets:
                        out[j] = _SKIP  # dangling bucket ref
                    elif int(A.btype[cs]) == target_type:
                        out[j] = _FOUND
                    else:
                        out[j] = _DESCENDING
                else:
                    if it >= A.max_devices:
                        out[j] = _SKIP
                    else:
                        out[j] = _FOUND if target_type == 0 else _SKIP
            row[k, _RF_OUT] = out
            if F > 4:
                row[k, _RF_STRAW] = A.straws[s].view(np.int32)
                row[k, _RF_LW] = A.weights[s].view(np.int32)
                row[k, _RF_SW] = A.sum_weights[s].view(np.int32)
            sca[k] = (n, int(A.alg[s]), -1 - s)
        self.ROW = row
        self.SCA = sca
        if len(reach) >= _REACH_ONEHOT_MIN:
            flat = row.reshape(len(reach), F * S)
            lo = (flat & 0xFFFF).astype(np.float32)
            hi = (flat >> 16).astype(np.float32)  # arithmetic: signed hi
            self.OH = np.concatenate(
                [lo, hi, sca.astype(np.float32)], axis=1
            )  # [G, 2*F*S + 3]
            self.REACH = np.asarray(reach, np.int32)
        else:
            self.OH = None
            self.REACH = None

    def host_tab(self) -> dict:
        t = {"ROW": self.ROW, "SCA": self.SCA}
        if self.OH is not None:
            t["OH"] = self.OH
            t["REACH"] = self.REACH
        return t

    def struct_key(self) -> tuple:
        """Structural signature (what the unrolled trace depends on)."""
        return (tuple(self.reach), self.row_ok,
                getattr(self, "F", 0), tuple(sorted(self.algs)))


def _prep_levels(A: CrushArrays, start_slots, target_type: int,
                 key_prefix: str = ""):
    """Static per-level reach analysis from start_slots until items of
    target_type emerge.  Returns a list of _RowLevel (may be empty when
    start_slots is empty — caller falls back to the generic descent)."""
    levels: list[_RowLevel] = []
    cur = sorted(set(start_slots))
    for li in range(A.max_depth + 1):
        if not cur:
            break
        levels.append(_RowLevel(A, cur, target_type, key=f"{key_prefix}{li}"))
        nxt = set()
        for s in cur:
            for it in A.items[s][: int(A.size[s])]:
                it = int(it)
                cs = -1 - it
                if it < 0 and cs < A.n_buckets and (
                    int(A.btype[cs]) != target_type
                ):
                    nxt.add(cs)
        cur = sorted(nxt)
    return levels


def _scan_rows(d: _DeviceArrays, lv: _RowLevel, slot):
    """Fetch the level's packed tables by traced slot scalar, gather-free.

    Small reach: trace-unrolled select chain (|reach| vector selects of
    operand rows).  Large reach: one-hot matmul — f32 can hold any 16-bit
    limb exactly and a one-hot row sum touches exactly one table row, so
    splitting the i32 tables into two 16-bit limb planes and contracting
    [G] x [G, F*S*2+3] on the MXU reconstructs the rows bit-exactly while
    scaling to thousands of buckets (the 10k-OSD map's host level).  The
    tables come from the operand pytree (d.rowlvl) so weight changes are
    new operands, not new traces; bare-fn callers fall back to the level's
    own numpy tables (trace constants, the pre-operand behavior)."""
    tab = d.rowlvl(lv.key) or lv.host_tab()
    G = len(lv.reach)
    if G < _REACH_ONEHOT_MIN:
        ROW = jnp.asarray(tab["ROW"])
        SCA = jnp.asarray(tab["SCA"])
        row = ROW[0]
        sca = SCA[0]
        for k, s in enumerate(lv.reach[1:], start=1):
            m = slot == s
            row = jnp.where(m, ROW[k], row)
            sca = jnp.where(m, SCA[k], sca)
        return row, sca
    F, S = lv.ROW.shape[1], lv.ROW.shape[2]
    oh = (slot == jnp.asarray(tab["REACH"])).astype(jnp.float32)  # [G]
    got = jnp.matmul(
        oh, jnp.asarray(tab["OH"]), precision="highest",
        preferred_element_type=jnp.float32,
    )  # [2*F*S + 3]
    lo = got[: F * S].astype(jnp.int32)
    hi = got[F * S: 2 * F * S].astype(jnp.int32)
    row = ((hi << 16) | lo).reshape(F, S)
    sca = got[2 * F * S:].astype(jnp.int32)
    return row, sca


def _rowpick(row, am):
    """row[am] without a gather (one-hot sum over the S lanes)."""
    lane = jnp.arange(row.shape[-1])
    return jnp.sum(jnp.where(lane == am, row, 0), axis=-1)


def _u32row(row):
    return row.astype(jnp.int64) & 0xFFFFFFFF


LN_IMPL: str | None = None  # None=auto; "gather" | "scan" | "onehot"


def _ln_impl() -> str:
    import jax as _jax

    return LN_IMPL or (
        "gather" if _jax.default_backend() == "cpu" else "onehot"
    )


def _ln_fn(d: _DeviceArrays, u):
    """crush_ln(u) for u = hash & 0xffff: one-hot MXU matmul on
    accelerators, 64k-table gather on CPU (gathers are cheap there, giant
    select chains / useless matmuls are slow).  LN_IMPL overrides (tests
    and the perf probe exercise every form); the chosen impl is captured
    at plan time into the kernel's cache_key (d.ln_impl), and the gather
    form reads the table from the operand pytree — a 64k literal would
    otherwise cost seconds of XLA constant folding per compile."""
    if d.ln_impl == "gather":
        return jnp.asarray(d.ln64k)[u]
    if d.ln_impl == "scan":
        return crush_ln_scan_jax(u)
    return crush_ln_onehot_jax(u)


def _straw2_rows(d: _DeviceArrays, row, size, x, r):
    """Row-table straw2 (same math as _straw2_choose, divide-free).

    The C draw is div64_s64(crush_ln(u) - 2^48, w) (reference
    src/crush/mapper.c:350-358).  With n = 2^48 - crush_ln(u) >= 0 that is
    exactly -floor(n / w); the truncating divide — an emulated multi-
    hundred-cycle op on the 32-bit TPU VPU — becomes a 24-bit-limb
    multiply-high by the per-item constants precomputed in the row tables
    (_magic_div_consts), bit-exact per the Granlund-Montgomery bound."""
    w = _u32row(row[_RF_W])
    u = (_h3(x, row[_RF_ID], r) & 0xFFFF).astype(jnp.uint32)
    n = jnp.int64(1 << 48) - _ln_fn(d, u)  # in [0, 2^48]
    n0 = n & 0xFFFFFF
    n1 = n >> 24
    m0 = row[_RF_M0].astype(jnp.int64)
    m1 = row[_RF_M1].astype(jnp.int64)
    m2 = row[_RF_M2].astype(jnp.int64)
    t0 = n0 * m0
    t1 = n0 * m1 + n1 * m0 + (t0 >> 24)
    t2 = n0 * m2 + n1 * m1 + (t1 >> 24)
    t3 = n1 * m2 + (t2 >> 24)
    high = (t2 & 0xFFFFFF) | (t3 << 24)  # floor(n*m / 2^48)
    q = high >> (row[_RF_L].astype(jnp.int64) + 1)
    mask = jnp.arange(row.shape[-1]) < size
    draw = jnp.where((w > 0) & mask, -q, S64_MIN)
    return jnp.argmax(draw)


def _straw_rows(row, size, x, r):
    """Row-table straw (same math as _straw_choose)."""
    draw = (_h3(x, row[_RF_ITEM], r) & 0xFFFF).astype(jnp.uint64) * _u32row(
        row[_RF_STRAW]
    ).astype(jnp.uint64)
    mask = jnp.arange(row.shape[-1]) < size
    draw = jnp.where(mask, draw, 0)
    return jnp.argmax(draw)


def _list_rows(row, size, bid, x, r):
    """Row-table list choose (same math as _list_choose)."""
    lane = jnp.arange(row.shape[-1])
    w = (_h4(x, row[_RF_ITEM], r, bid) & 0xFFFF).astype(jnp.uint64)
    w = (w * _u32row(row[_RF_SW]).astype(jnp.uint64)) >> 16
    ok = (w < _u32row(row[_RF_LW]).astype(jnp.uint64)) & (lane < size)
    best = jnp.max(jnp.where(ok, lane, -1))
    return jnp.maximum(best, 0)


def _row_level_step(d: _DeviceArrays, lv: _RowLevel, x, item, r_fn):
    """One unrolled descent level on the row path.  Returns
    (nxt, new_status_ignoring_active, r_cur)."""
    A = d.A
    slot = jnp.clip(-1 - item, 0, A.n_buckets - 1)
    row, sca = _scan_rows(d, lv, slot)
    size, alg, bid = sca[_SF_SIZE], sca[_SF_ALG], sca[_SF_BID]
    r_cur = r_fn(alg, size)
    fns = []
    if int(BucketAlg.STRAW2) in lv.algs:
        fns.append((int(BucketAlg.STRAW2),
                    lambda: _straw2_rows(d, row, size, x, r_cur)))
    if int(BucketAlg.STRAW) in lv.algs:
        fns.append((int(BucketAlg.STRAW),
                    lambda: _straw_rows(row, size, x, r_cur)))
    if int(BucketAlg.LIST) in lv.algs:
        fns.append((int(BucketAlg.LIST),
                    lambda: _list_rows(row, size, bid, x, r_cur)))
    am = fns[0][1]()
    for a, f in fns[1:]:
        am = jnp.where(alg == a, f(), am)
    nxt = _rowpick(row[_RF_ITEM], am)
    outcome = _rowpick(row[_RF_OUT], am)
    empty = size == 0
    new_status = jnp.where(empty, jnp.int32(_EMPTY), outcome)
    return jnp.where(empty, item, nxt), new_status, r_cur


def _gather_level_step(d: _DeviceArrays, x, item, r_fn, position,
                       target_type: int):
    """Generic (gather-based) level step — fallback for levels whose reach
    has no row form; same logic as one _descend_impl body iteration."""
    A = d.A
    slot = jnp.clip(-1 - item, 0, A.n_buckets - 1)
    empty = d.size[slot] == 0
    r_cur = r_fn(d.alg[slot], d.size[slot])
    nxt = _bucket_choose(d, slot, x, r_cur, position)
    bad = nxt >= A.max_devices
    is_b = nxt < 0
    dangling = is_b & (-1 - nxt >= A.n_buckets)
    nslot = jnp.clip(-1 - nxt, 0, A.n_buckets - 1)
    ntype = jnp.where(is_b, d.btype[nslot], 0)
    new_status = jnp.where(
        empty,
        jnp.int32(_EMPTY),
        jnp.where(
            bad | dangling,
            jnp.int32(_SKIP),
            jnp.where(
                ntype == target_type,
                jnp.int32(_FOUND),
                jnp.where(~is_b, jnp.int32(_SKIP), jnp.int32(_DESCENDING)),
            ),
        ),
    )
    return jnp.where(empty, item, nxt), new_status, r_cur


def _descend_rows(d: _DeviceArrays, x, start_item, r_fn, position,
                  target_type: int, levels: list[_RowLevel]):
    """Unrolled descent over precomputed levels (row path with per-level
    gather fallback).  r_fn(alg_scalar, size_scalar) -> replica draw for
    the current bucket (constant for firstn; stride-adjusted for indep).
    Returns (item, status, r_last) like _descend_impl."""
    A = d.A
    status = jnp.where(
        (start_item < 0) & (-1 - start_item < A.n_buckets),
        jnp.int32(_DESCENDING),
        jnp.int32(_SKIP),
    )
    item = jnp.asarray(start_item, jnp.int32)
    r_last = jnp.int32(0)
    for lv in levels:
        active = status == _DESCENDING
        if lv.row_ok:
            nxt, new_status, r_cur = _row_level_step(d, lv, x, item, r_fn)
        else:
            nxt, new_status, r_cur = _gather_level_step(
                d, x, item, r_fn, position, target_type
            )
        item = jnp.where(active, nxt, item)
        status = jnp.where(active, new_status, status)
        r_last = jnp.where(active, r_cur, r_last).astype(jnp.int32)
    status = jnp.where(status == _DESCENDING, jnp.int32(_SKIP), status)
    return item, status, r_last


def _descend_impl(
    d: _DeviceArrays, x, start_item, position, target_type: int, r_of_slot,
    bound: int | None = None,
):
    """Walk intervening buckets until an item of target_type emerges
    (the retry_bucket descent of reference src/crush/mapper.c:507-555 /
    710-771).  r_of_slot(slot) yields the replica draw for the current
    bucket — constant for firstn, per-level stride-adjusted for indep
    (reference src/crush/mapper.c:722-728).  Returns (item, status)."""
    A = d.A

    status0 = jnp.where(
        (start_item < 0) & (-1 - start_item < A.n_buckets),
        jnp.int32(_DESCENDING),
        jnp.int32(_SKIP),
    )

    def body(_, st):
        item, status, r_last = st
        slot = jnp.clip(-1 - item, 0, A.n_buckets - 1)
        empty = d.size[slot] == 0
        r_cur = r_of_slot(slot)
        nxt = _bucket_choose(d, slot, x, r_cur, position)
        bad = nxt >= A.max_devices
        is_b = nxt < 0
        dangling = is_b & (-1 - nxt >= A.n_buckets)
        nslot = jnp.clip(-1 - nxt, 0, A.n_buckets - 1)
        ntype = jnp.where(is_b, d.btype[nslot], 0)
        new_status = jnp.where(
            empty,
            jnp.int32(_EMPTY),
            jnp.where(
                bad | dangling,
                jnp.int32(_SKIP),
                jnp.where(
                    ntype == target_type,
                    jnp.int32(_FOUND),
                    jnp.where(~is_b, jnp.int32(_SKIP), jnp.int32(_DESCENDING)),
                ),
            ),
        )
        active = status == _DESCENDING
        return (
            jnp.where(active & ~empty, nxt, item),
            jnp.where(active, new_status, status),
            jnp.where(active, r_cur, r_last).astype(jnp.int32),
        )

    item, status, r_last = lax.fori_loop(
        0, A.max_depth + 1 if bound is None else bound, body,
        (start_item, status0, jnp.int32(0)),
    )
    # still descending after depth bound => treat as skip (cyclic/deep map)
    status = jnp.where(status == _DESCENDING, jnp.int32(_SKIP), status)
    return item, status, r_last


def _descend(d: _DeviceArrays, x, start_item, r, position, target_type: int,
             bound: int | None = None):
    """firstn-style descent: one r for the whole walk."""
    item, status, _ = _descend_impl(
        d, x, start_item, position, target_type, lambda _: r, bound
    )
    return item, status


def _descend_indep(
    d: _DeviceArrays, x, start_item, rep_base, ftotal, numrep: int,
    position, target_type: int, bound: int | None = None,
):
    """indep-style descent: r is re-derived at every level from the current
    bucket — uniform buckets whose size divides numrep use stride numrep+1
    (reference src/crush/mapper.c:719-728)."""

    def r_of_slot(slot):
        uni = (d.alg[slot] == int(BucketAlg.UNIFORM)) & (
            d.size[slot] % numrep == 0
        )
        return (rep_base + jnp.where(uni, numrep + 1, numrep) * ftotal).astype(
            jnp.int32
        )

    return _descend_impl(
        d, x, start_item, position, target_type, r_of_slot, bound
    )


def _collides(out, outpos, item, lo=0):
    lane = jnp.arange(out.shape[0])
    return jnp.any((lane >= lo) & (lane < outpos) & (out == item))


def _leaf_firstn(
    d: _DeviceArrays,
    x,
    item,
    sub_r,
    outpos,
    out2,
    dev_weights,
    weight_max,
    recurse_tries: int,
    stable: int,
):
    """The recursive chooseleaf descent (reference src/crush/mapper.c:573-588):
    pick ONE device under `item`, retrying up to recurse_tries, colliding
    against out2[:outpos].  Returns (leaf, ok)."""
    rep = jnp.where(jnp.bool_(stable), 0, outpos)

    def cond(st):
        ftotal, leaf, ok, dead = st
        return (~ok) & (~dead) & (ftotal < recurse_tries)

    def body(st):
        ftotal, leaf, ok, dead = st
        r = rep + sub_r + ftotal
        cand, status = _descend(d, x, item, r, outpos, 0)
        collide = _collides(out2, outpos, cand)
        reject = _is_out(x, cand, dev_weights, weight_max)
        good = (status == _FOUND) & ~collide & ~reject
        # _SKIP is C's skip_rep inside the recursion: the single rep is
        # abandoned (no further tries) and the call returns <= outpos.
        return (
            ftotal + 1,
            jnp.where(good, cand, leaf),
            ok | good,
            dead | (status == _SKIP),
        )

    _, leaf, ok, _ = lax.while_loop(
        cond, body,
        (jnp.int32(0), jnp.int32(ITEM_NONE), jnp.bool_(False),
         jnp.bool_(False)),
    )
    return leaf, ok


def _choose_firstn_one(
    d: _DeviceArrays,
    x,
    src,
    count,
    dev_weights,
    *,
    numrep: int,
    target_type: int,
    recurse_to_leaf: bool,
    tries: int,
    recurse_tries: int,
    vary_r: int,
    stable: int,
    weight_max: int,
    out_bound: int,
):
    """crush_choose_firstn for one source bucket, outpos starting at 0
    (reference src/crush/mapper.c:460-648; modern tunables: no local
    retries).  The rep loop runs the full numrep (a skipped rep is
    compensated by later rep values, as in C); out_bound just sizes the
    output arrays.  Returns (out[out_bound], out2[out_bound], n_placed)."""
    NR = out_bound
    out = jnp.full(NR, ITEM_NONE, jnp.int32)
    out2 = jnp.full(NR, ITEM_NONE, jnp.int32)

    def rep_body(rep, st):
        outpos, out, out2, cnt = st

        def attempt_cond(ast):
            ftotal, item, leaf, placed, skip = ast
            return (~placed) & (~skip)

        def attempt_body(ast):
            ftotal, item, leaf, placed, skip = ast
            r = rep + ftotal
            cand, status = _descend(d, x, src, r, outpos, target_type)
            collide = _collides(out, outpos, cand)
            if recurse_to_leaf:
                sub_r = (r >> (vary_r - 1)) if vary_r else jnp.int32(0)
                lf, lok = _leaf_firstn(
                    d, x, cand, sub_r, outpos, out2, dev_weights,
                    weight_max, recurse_tries, stable,
                )
                if target_type == 0:
                    # degenerate chooseleaf to device type: item already leaf
                    dev = cand >= 0
                    lf = jnp.where(dev, cand, lf)
                    lok = jnp.where(dev, jnp.bool_(True), lok)
                    rj = jnp.where(
                        dev,
                        _is_out(x, cand, dev_weights, weight_max),
                        ~lok,
                    )
                else:
                    rj = ~lok
                reject = jnp.where(collide, jnp.bool_(False), rj)
            else:
                lf = cand
                if target_type == 0:
                    reject = _is_out(x, cand, dev_weights, weight_max)
                else:
                    reject = jnp.bool_(False)

            found = status == _FOUND
            fail = (~found) | reject | collide
            # status _SKIP => skip_rep immediately; _EMPTY counts as a try
            hard_skip = status == _SKIP
            ftotal2 = ftotal + jnp.where(fail & ~hard_skip, 1, 0)
            exhausted = ftotal2 >= tries
            return (
                ftotal2,
                jnp.where(found & ~fail, cand, item),
                jnp.where(found & ~fail, lf, leaf),
                found & ~fail,
                hard_skip | (fail & ~hard_skip & exhausted),
            )

        ftotal0 = (
            jnp.int32(0),
            jnp.int32(ITEM_NONE),
            jnp.int32(ITEM_NONE),
            jnp.bool_(False),
            jnp.bool_(False),
        )
        active = cnt > 0
        ftotal, item, leaf, placed, skip = lax.while_loop(
            attempt_cond, attempt_body, ftotal0
        )
        ok = active & placed
        safe_pos = jnp.clip(outpos, 0, NR - 1)
        out = out.at[safe_pos].set(jnp.where(ok, item, out[safe_pos]))
        out2 = out2.at[safe_pos].set(jnp.where(ok, leaf, out2[safe_pos]))
        return (
            outpos + jnp.where(ok, 1, 0),
            out,
            out2,
            cnt - jnp.where(ok, 1, 0),
        )

    outpos, out, out2, _ = lax.fori_loop(
        0, numrep, rep_body, (jnp.int32(0), out, out2, jnp.int32(count))
    )
    return out, out2, outpos


def _leaf_indep(
    d: _DeviceArrays,
    x,
    item,
    parent_r,
    rep,
    numrep: int,
    recurse_tries: int,
    dev_weights,
    weight_max: int,
):
    """Recursive indep leaf pick (reference src/crush/mapper.c:784-798):
    left=1, out slot `rep`, parent_r = outer r.  Returns (leaf, ok)."""

    def cond(st):
        ftotal, leaf, ok, dead = st
        return (~ok) & (~dead) & (ftotal < recurse_tries)

    def body(st):
        ftotal, leaf, ok, dead = st
        cand, status, _ = _descend_indep(
            d, x, item, rep + parent_r, ftotal, numrep, rep, 0
        )
        reject = _is_out(x, cand, dev_weights, weight_max)
        good = (status == _FOUND) & ~reject
        # _SKIP writes NONE into the slot in C (left--), ending the attempt
        return (
            ftotal + 1,
            jnp.where(good, cand, leaf),
            ok | good,
            dead | (status == _SKIP),
        )

    _, leaf, ok, _ = lax.while_loop(
        cond, body,
        (jnp.int32(0), jnp.int32(ITEM_NONE), jnp.bool_(False),
         jnp.bool_(False)),
    )
    return leaf, ok


def _choose_indep_one(
    d: _DeviceArrays,
    x,
    src,
    out_size,
    dev_weights,
    *,
    numrep: int,
    target_type: int,
    recurse_to_leaf: bool,
    tries: int,
    recurse_tries: int,
    weight_max: int,
    out_bound: int,
):
    """crush_choose_indep for one source bucket (reference
    src/crush/mapper.c:655-843): breadth-first, positionally stable, NONE
    fills.  out_size (traced) <= out_bound (static array bound); numrep is
    the rule's full choose count, which sets the retry r-stride and the
    uniform-divisibility check per descent level (_descend_indep re-derives
    r at every level exactly as reference src/crush/mapper.c:719-728 does).
    """
    NR = out_bound
    UNDEF = jnp.int32(-0x7FFFFFFE)  # internal marker (distinct from NONE)
    out = jnp.where(jnp.arange(NR) < out_size, UNDEF, jnp.int32(ITEM_NONE))
    out2 = out

    def round_body(st):
        ftotal, left, out, out2 = st

        def rep_body(rep, st2):
            out, out2, left = st2
            todo = (rep < out_size) & (out[rep] == UNDEF)
            # choose_args position is the *call-level* outpos (0 here), not
            # the replica slot (reference src/crush/mapper.c:736-740)
            cand, status, r_last = _descend_indep(
                d, x, src, rep, ftotal, numrep, 0, target_type
            )
            # the leaf recursion's parent_r is the full r of the level where
            # the walk found the item (reference src/crush/mapper.c:794)
            r_leaf = r_last
            found_nc = (status == _FOUND) & ~jnp.any(
                jnp.where(jnp.arange(NR) < out_size, out, ITEM_NONE) == cand
            )
            dev = cand >= 0
            if recurse_to_leaf:
                lf, lok = _leaf_indep(
                    d, x, cand, r_leaf, rep, numrep, recurse_tries,
                    dev_weights, weight_max,
                )
                # a found *device* is written to out2 before the is_out
                # check (reference src/crush/mapper.c:799-801), so a
                # rejected device stays in out2 and is emitted if every
                # try fails; a failed bucket recursion writes NONE
                # (src/crush/mapper.c:794-797 + the recursion's own
                # UNDEF->NONE conversion).
                leaf_val = jnp.where(
                    dev, cand, jnp.where(lok, lf, jnp.int32(ITEM_NONE))
                )
                leaf_ok = lok | dev
                leaf_fail = ~leaf_ok
            else:
                leaf_val = cand
                leaf_ok = jnp.bool_(True)
                leaf_fail = jnp.bool_(False)
            if target_type == 0:
                reject = _is_out(x, cand, dev_weights, weight_max)
            else:
                reject = jnp.bool_(False)
            hard = status == _SKIP  # bad item => NONE + left--
            good = found_nc & ~leaf_fail & ~reject
            newv = jnp.where(
                hard, jnp.int32(ITEM_NONE), jnp.where(good, cand, UNDEF)
            )
            if recurse_to_leaf:
                newl = jnp.where(
                    hard,
                    jnp.int32(ITEM_NONE),
                    jnp.where(found_nc, leaf_val, out2[rep]),
                )
            else:
                newl = newv
            out = out.at[rep].set(jnp.where(todo, newv, out[rep]))
            out2 = out2.at[rep].set(jnp.where(todo, newl, out2[rep]))
            left = left - jnp.where(todo & (hard | good), 1, 0)
            return out, out2, left

        out, out2, left = lax.fori_loop(0, NR, rep_body, (out, out2, left))
        return ftotal + 1, left, out, out2

    def round_cond(st):
        ftotal, left, out, out2 = st
        return (left > 0) & (ftotal < tries)

    _, _, out, out2 = lax.while_loop(
        round_cond, round_body, (jnp.int32(0), jnp.int32(out_size), out, out2)
    )
    out = jnp.where(out == UNDEF, ITEM_NONE, out)
    out2 = jnp.where(out2 == UNDEF, ITEM_NONE, out2)
    return out, out2, out_size


def _choose_firstn_one_fast(
    d: _DeviceArrays,
    x,
    src,
    count,
    dev_weights,
    *,
    numrep: int,
    target_type: int,
    recurse_to_leaf: bool,
    tries: int,
    recurse_tries: int,
    vary_r: int,
    stable: int,
    weight_max: int,
    out_bound: int,
    window: int,
    bound: int | None = None,
    leaf_bound: int | None = None,
    levels: list | None = None,
    leaf_levels: list | None = None,
    with_diag: bool = False,
):
    """Vectorized crush_choose_firstn (same semantics as
    _choose_firstn_one; reference src/crush/mapper.c:460-648).

    Key observation: with modern tunables (no local retries) every retry
    restarts the descent from the TAKE bucket with r = rep + ftotal, so the
    candidate for a given r depends only on (x, src, r) — not on the retry
    history.  rep's retry window is the contiguous r-range
    [rep, rep+tries) and windows of successive reps overlap, so ONE batch
    of T descents (vmapped over the r axis — no while_loop, no serialized
    lanes) covers every draw the C could make.  Selection then walks the
    reps with cheap vectorized mask algebra: first r in the window that
    descended to a valid candidate, with a cumulative-skip mask
    reproducing C's skip_rep abort.

    `window` bounds T below the exact numrep+tries-1 (default tries is 50:
    almost all of those draws are never needed).  A rep whose visible
    window ends truncated with neither a success nor a skip_rep is
    *inconclusive*: the returned `unresolved` flag is set and the caller
    must recompute that x via the loop kernel (PoolMapper/compile_batched
    do this host-side for the rare flagged lanes — exactness is preserved
    while the batch pays only for the short window).

    Requires (asserted by the caller choosing this path): choose_args
    positions == 1 (candidate would otherwise depend on outpos), and
    chooseleaf_stable=1 for chooseleaf steps (leaf rep is the constant 0,
    reference src/crush/mapper.c:573-588; stable=0 makes it outpos-
    dependent — that combination takes the loop path).
    """
    NR = out_bound
    T = min(numrep + tries - 1, window)
    rs = jnp.arange(T, dtype=jnp.int32)
    if levels:
        cand, status, _ = jax.vmap(
            lambda r: _descend_rows(
                d, x, src, lambda alg, size: r, 0, target_type, levels
            )
        )(rs)
    else:
        cand, status = jax.vmap(
            lambda r: _descend(d, x, src, r, 0, target_type, bound)
        )(rs)
    found = status == _FOUND
    skip = status == _SKIP

    leafy = recurse_to_leaf and target_type != 0
    if not leafy:
        out_flag = (
            _is_out(x, cand, dev_weights, weight_max)
            if target_type == 0 else jnp.zeros(T, bool)
        )

    lane_nr = jnp.arange(NR)
    out = jnp.full(NR, ITEM_NONE, jnp.int32)
    outpos = jnp.int32(0)
    cnt = jnp.asarray(count, jnp.int32)
    unresolved = jnp.bool_(False)
    sel_r = []  # per-rep selected r index (traced scalars)
    sel_ok = []
    # diagnostics plane (with_diag): per-placement retry counts plus
    # collision / out-of-weight / skip tallies, derived from the SAME
    # window masks the selection walk already computes — the C attempts
    # exactly the contiguous r-range up to its first success, so the
    # draws "the C would have made" are reconstructible after the fact
    # (mask algebra only; no extra descents)
    tries_sel: list = []
    d_coll = d_rej = d_skip = jnp.int32(0)

    # pass 1 — outer selection.  For chooseleaf the leaf descent is
    # DEFERRED: we optimistically select each rep's first outer-valid
    # candidate and verify leaves in pass 2; any leaf failure (which in C
    # would advance r and re-descend) flags the lane unresolved for the
    # loop-kernel rescue.  Leaf failures are rare (a whole host's devices
    # all out/colliding), so this trades T*recurse_tries leaf descents
    # for numrep + an occasional rescue.
    for rep in range(numrep):
        truncated = rep + tries > T  # static
        in_win = (rs >= rep) & (rs < rep + tries)
        win_skip = in_win & skip
        dead_before = (
            jnp.cumsum(win_skip.astype(jnp.int32))
            - win_skip.astype(jnp.int32)
        ) > 0
        if rep == 0:
            # out/outpos are still trace constants here: emitting the
            # [T, NR] compare would hand XLA a batch-wide all-False
            # broadcast+reduce to constant-fold — seconds per compile at
            # B=65536 (the r05 `pred[65536,11]` folding alarm)
            collide = jnp.zeros(T, bool)
        else:
            collide = jnp.any(
                (cand[:, None] == out[None, :])
                & (lane_nr[None, :] < outpos),
                axis=1,
            )
        reject = jnp.zeros(T, bool) if leafy else out_flag
        valid = in_win & found & ~collide & ~reject & ~dead_before
        ok = jnp.any(valid) & (cnt > 0)
        if truncated:
            unresolved = unresolved | (
                (cnt > 0) & ~ok & ~jnp.any(win_skip)
            )
        rstar = jnp.argmax(valid)
        safe = jnp.clip(outpos, 0, NR - 1)
        out = out.at[safe].set(jnp.where(ok, cand[rstar], out[safe]))
        sel_r.append(rstar)
        sel_ok.append(ok)
        if with_diag:
            # draws the C actually attempts for this rep: the live window
            # up to and including the success (or the first skip_rep);
            # nothing is attempted once the count is exhausted or the
            # source bucket was invalid (cnt starts at 0 then)
            attempted = in_win & ~dead_before & (cnt > 0)
            attempted = attempted & jnp.where(ok, rs <= rstar, True)
            d_coll = d_coll + jnp.sum(
                (attempted & collide).astype(jnp.int32))
            d_rej = d_rej + jnp.sum(
                (attempted & reject & found).astype(jnp.int32))
            d_skip = d_skip + jnp.sum(
                (attempted & win_skip).astype(jnp.int32))
            # retry count at success == host ftotal (r = rep + ftotal)
            tries_sel.append(
                jnp.where(ok, rstar - rep, -1).astype(jnp.int32))
        outpos = outpos + jnp.where(ok, 1, 0)
        cnt = cnt - jnp.where(ok, 1, 0)

    if not leafy:
        # out2 mirrors out (devices/buckets chosen directly)
        if with_diag:
            dstep = {"tries": jnp.stack(tries_sel), "coll": d_coll,
                     "rej": d_rej, "skip": d_skip}
            return out, out, outpos, unresolved, dstep
        return out, out, outpos, unresolved

    # pass 2 — leaf descents for the selected candidates only
    Rt = recurse_tries
    sel_rv = jnp.stack(sel_r)  # [numrep]
    sel_okv = jnp.stack(sel_ok)
    sel_cand = cand[sel_rv]
    if vary_r:
        sub_r = (sel_rv >> (vary_r - 1)).astype(jnp.int32)
    else:
        sub_r = jnp.zeros_like(sel_rv)
    ks = jnp.arange(Rt, dtype=jnp.int32)
    if leaf_levels:
        leaf, lstat, _ = jax.vmap(
            lambda c, sr: jax.vmap(
                lambda k: _descend_rows(
                    d, x, c, lambda alg, size: sr + k, 0, 0, leaf_levels
                )
            )(ks)
        )(sel_cand, sub_r)  # [numrep, Rt]
    else:
        leaf, lstat = jax.vmap(
            lambda c, sr: jax.vmap(
                lambda k: _descend(d, x, c, sr + k, 0, 0, leaf_bound)
            )(ks)
        )(sel_cand, sub_r)  # [numrep, Rt]
    leaf_out = _is_out(x, leaf, dev_weights, weight_max)
    leaf_sel = (lstat == _FOUND) & ~leaf_out
    leaf_skip = lstat == _SKIP
    # a leaf attempt aborts at the first _SKIP (C returns <= outpos)
    leaf_dead = (
        jnp.cumsum(leaf_skip.astype(jnp.int32), axis=1)
        - leaf_skip.astype(jnp.int32)
    ) > 0
    out2 = jnp.full(NR, ITEM_NONE, jnp.int32)
    pos2 = jnp.int32(0)
    leaf_tries: list = []
    for rep in range(numrep):
        ok = sel_okv[rep]
        lgood = leaf_sel[rep] & ~leaf_dead[rep]
        if rep > 0:  # rep 0: out2/pos2 are constants (see pass-1 note)
            lcoll = jnp.any(
                (leaf[rep][:, None] == out2[None, :])
                & (lane_nr[None, :] < pos2),
                axis=1,
            )
            lgood = lgood & ~lcoll
        else:
            lcoll = jnp.zeros(Rt, bool)
        lok = jnp.any(lgood)
        kstar = jnp.argmax(lgood)
        unresolved = unresolved | (ok & ~lok)
        place = ok & lok
        safe = jnp.clip(pos2, 0, NR - 1)
        out2 = out2.at[safe].set(jnp.where(place, leaf[rep][kstar], out2[safe]))
        pos2 = pos2 + jnp.where(place, 1, 0)
        if with_diag:
            # leaf recursion retries: same reconstruction as pass 1 (the
            # host recursion attempts k = 0..ftotal sequentially)
            lattempted = ~leaf_dead[rep] & jnp.where(
                lok, jnp.arange(Rt) <= kstar, True)
            lattempted = lattempted & ok  # no outer pick: no recursion
            d_rej = d_rej + jnp.sum(
                (lattempted & (lstat[rep] == _FOUND)
                 & leaf_out[rep]).astype(jnp.int32))
            d_coll = d_coll + jnp.sum(
                (lattempted & leaf_sel[rep] & lcoll).astype(jnp.int32))
            d_skip = d_skip + jnp.sum(
                (lattempted & leaf_skip[rep]).astype(jnp.int32))
            leaf_tries.append(
                jnp.where(place, kstar, -1).astype(jnp.int32))
    if with_diag:
        dstep = {"tries": jnp.stack(tries_sel + leaf_tries),
                 "coll": d_coll, "rej": d_rej, "skip": d_skip}
        return out, out2, outpos, unresolved, dstep
    return out, out2, outpos, unresolved


def _choose_indep_one_fast(
    d: _DeviceArrays,
    x,
    src,
    out_size,
    dev_weights,
    *,
    numrep: int,
    target_type: int,
    recurse_to_leaf: bool,
    tries: int,
    recurse_tries: int,
    weight_max: int,
    out_bound: int,
    bound: int | None = None,
    leaf_bound: int | None = None,
    levels: list | None = None,
    leaf_levels: list | None = None,
    with_diag: bool = False,
):
    """crush_choose_indep with the per-round rep descents vectorized.

    Same semantics as _choose_indep_one (reference
    src/crush/mapper.c:655-843); the ftotal round loop stays a while_loop
    (its trip count is the max retry depth over the batch, typically 1-2),
    but within a round all NR descents + leaf descents run as one vmapped
    batch instead of a serialized fori_loop, and the only sequential part
    left is the cheap duplicate-check fold over the out slots.
    """
    NR = out_bound
    UNDEF = jnp.int32(-0x7FFFFFFE)
    out = jnp.where(jnp.arange(NR) < out_size, UNDEF, jnp.int32(ITEM_NONE))
    out2 = out
    reps = jnp.arange(NR, dtype=jnp.int32)
    Rt = recurse_tries
    ks = jnp.arange(Rt, dtype=jnp.int32)

    def indep_r_fn(rep_base, ftotal):
        def r_fn(alg, size):
            uni = (alg == int(BucketAlg.UNIFORM)) & (size % numrep == 0)
            return (
                rep_base + jnp.where(uni, numrep + 1, numrep) * ftotal
            ).astype(jnp.int32)
        return r_fn

    def round_body(st):
        ftotal, left, out, out2 = st
        if levels:
            cand, status, r_last = jax.vmap(
                lambda rep: _descend_rows(
                    d, x, src, indep_r_fn(rep, ftotal), 0, target_type,
                    levels,
                )
            )(reps)
        else:
            cand, status, r_last = jax.vmap(
                lambda rep: _descend_indep(
                    d, x, src, rep, ftotal, numrep, 0, target_type, bound
                )
            )(reps)
        cand_out = _is_out(x, cand, dev_weights, weight_max)
        if recurse_to_leaf:
            # leaf retry loop (reference src/crush/mapper.c:784-798)
            # unrolled over the k axis: first good k before the first skip
            if leaf_levels:
                leaf, lstat, _ = jax.vmap(
                    lambda c, pr, rep: jax.vmap(
                        lambda k: _descend_rows(
                            d, x, c, indep_r_fn(rep + pr, k), rep, 0,
                            leaf_levels,
                        )
                    )(ks)
                )(cand, r_last, reps)  # [NR, Rt]
            else:
                leaf, lstat, _ = jax.vmap(
                    lambda c, pr, rep: jax.vmap(
                        lambda k: _descend_indep(
                            d, x, c, rep + pr, k, numrep, rep, 0, leaf_bound
                        )
                    )(ks)
                )(cand, r_last, reps)  # [NR, Rt]
            lgood = (lstat == _FOUND) & ~_is_out(
                x, leaf, dev_weights, weight_max
            )
            ldead = (
                jnp.cumsum((lstat == _SKIP).astype(jnp.int32), axis=1)
                - (lstat == _SKIP).astype(jnp.int32)
            ) > 0
            lsel = lgood & ~ldead
            leaf_ok_v = jnp.any(lsel, axis=1)
            kstar = jnp.argmax(lsel, axis=1)
            leaf_v = jnp.take_along_axis(leaf, kstar[:, None], axis=1)[:, 0]

        def rep_step(rep, st2):
            out, out2, left = st2
            todo = (rep < out_size) & (out[rep] == UNDEF)
            c = cand[rep]
            found_nc = (status[rep] == _FOUND) & ~jnp.any(
                jnp.where(jnp.arange(NR) < out_size, out, ITEM_NONE) == c
            )
            dev = c >= 0
            if recurse_to_leaf:
                lok = leaf_ok_v[rep]
                leaf_val = jnp.where(
                    dev, c, jnp.where(lok, leaf_v[rep], jnp.int32(ITEM_NONE))
                )
                leaf_fail = ~(lok | dev)
            else:
                leaf_fail = jnp.bool_(False)
            if target_type == 0:
                reject = cand_out[rep]
            else:
                reject = jnp.bool_(False)
            hard = status[rep] == _SKIP
            good = found_nc & ~leaf_fail & ~reject
            newv = jnp.where(
                hard, jnp.int32(ITEM_NONE), jnp.where(good, c, UNDEF)
            )
            if recurse_to_leaf:
                newl = jnp.where(
                    hard,
                    jnp.int32(ITEM_NONE),
                    jnp.where(found_nc, leaf_val, out2[rep]),
                )
            else:
                newl = newv
            out = out.at[rep].set(jnp.where(todo, newv, out[rep]))
            out2 = out2.at[rep].set(jnp.where(todo, newl, out2[rep]))
            left = left - jnp.where(todo & (hard | good), 1, 0)
            return out, out2, left

        for rep in range(NR):
            out, out2, left = rep_step(rep, (out, out2, left))
        return ftotal + 1, left, out, out2

    def round_cond(st):
        ftotal, left, out, out2 = st
        return (left > 0) & (ftotal < tries)

    ftot, _, out, out2 = lax.while_loop(
        round_cond, round_body, (jnp.int32(0), jnp.int32(out_size), out, out2)
    )
    out = jnp.where(out == UNDEF, ITEM_NONE, out)
    out2 = jnp.where(out2 == UNDEF, ITEM_NONE, out2)
    if with_diag:
        # the host increments its histogram ONCE per indep call with the
        # final round count (reference src/crush/mapper.c:843 header
        # increment); the lane mirrors that.  Per-draw collision /
        # rejection tallies would need state threaded through the round
        # loop — deliberately left at 0 (diag_exact stays true for the
        # tries lane, which is what the histogram unification consumes).
        dstep = {
            "tries": jnp.where(out_size > 0, ftot, -1)[None].astype(
                jnp.int32),
            "coll": jnp.int32(0), "rej": jnp.int32(0),
            "skip": jnp.int32(0),
        }
        return out, out2, out_size, jnp.bool_(False), dstep
    return out, out2, out_size, jnp.bool_(False)


FAST_WINDOW_EXTRA = 8  # default r-window slack beyond numrep (see above)


def compile_rule(A: CrushArrays, ruleno: int, result_max: int,
                 path: str = "auto", window_extra: int = FAST_WINDOW_EXTRA,
                 with_flag: bool = False, with_diag: bool = False):
    """Build the single-x mapping function for one rule; vmap/jit-ready.

    Returns fn(x: u32 scalar, dev_weights: u32[max_devices]) -> i32[result_max]
    mirroring crush_do_rule's result vector (padded with ITEM_NONE; the C
    returns a length instead — callers mask on ITEM_NONE).

    path: "auto" picks the vectorized candidate-batch kernel where its
    preconditions hold (the common modern-tunables case) and the bounded
    masked-loop kernel otherwise; "fast"/"loop" force one (fast asserts
    its preconditions).

    with_flag: fn additionally returns an `unresolved` bool — True when
    the fast kernel's bounded candidate window (numrep + window_extra
    draws) was exhausted inconclusively and the caller must recompute
    this x via the loop kernel to stay bit-exact (see
    _choose_firstn_one_fast; always False on the loop path).

    Without with_flag there is no way to honor that contract, so
    path="auto" then resolves to the (always-exact) loop kernel;
    requesting the fast kernel flagless is an error.

    with_diag: the fn additionally returns a diagnostics pytree — the
    device-side flight recorder of every decision the C interpreter
    makes invisible once fused: per-placement retry counts (`tries`,
    -1 = unplaced; indexes the same histogram the host reference
    mapper's collect_choose_tries fills), collision / out-of-weight-
    rejection / skip_rep tallies (`coll`/`rej`/`skip`), the bad-mapping
    flag (`bad`: result shorter than result_max), and the per-choose-
    step work vectors (`steps` [n_choose_steps, RMAX]) the first-
    divergence locator compares against the host oracle's step log.
    Requires with_flag (diagnostics of an unresolved lane are garbage;
    the flag tells the caller which lanes to mask / host-rescue).
    Instrumentation is a STATIC plan fact folded into fn.cache_key —
    the default variant's trace is untouched and keeps its own cache
    entry.  `fn.diag_exact` says whether every choose step's tries
    lanes reproduce the host histogram exactly (fast-path firstn and
    non-leafy indep do; loop-path steps emit -1 lanes).
    """
    if path == "auto" and not with_flag:
        path = "loop"
    assert not (path == "fast" and not with_flag), (
        "fast kernel's bounded window is inexact without the unresolved "
        "flag + caller rescue; pass with_flag=True (or use compile_batched)"
    )
    assert not (with_diag and not with_flag), (
        "with_diag needs the unresolved flag: flagged lanes carry "
        "garbage diagnostics and the caller must mask or host-rescue them"
    )
    t = A.tunables
    assert t.choose_local_tries == 0 and t.choose_local_fallback_tries == 0, (
        "legacy local-retry tunables unsupported in the TPU kernel; "
        "use mapper_ref"
    )
    rule = A.rules[ruleno]
    assert rule is not None
    weight_max = A.max_devices
    RMAX = result_max

    # static interpreter state
    choose_tries = t.choose_total_tries + 1
    choose_leaf_tries = 0
    vary_r = t.chooseleaf_vary_r
    stable = t.chooseleaf_stable

    steps = []  # trace plan
    for op, arg1, arg2 in rule.steps:
        if op == RuleOp.SET_CHOOSE_TRIES:
            if arg1 > 0:
                choose_tries = arg1
        elif op == RuleOp.SET_CHOOSELEAF_TRIES:
            if arg1 > 0:
                choose_leaf_tries = arg1
        elif op == RuleOp.SET_CHOOSELEAF_VARY_R:
            if arg1 >= 0:
                vary_r = arg1
        elif op == RuleOp.SET_CHOOSELEAF_STABLE:
            if arg1 >= 0:
                stable = arg1
        elif op in (RuleOp.SET_CHOOSE_LOCAL_TRIES,
                    RuleOp.SET_CHOOSE_LOCAL_FALLBACK_TRIES):
            assert arg1 == 0, "legacy local tries unsupported in TPU kernel"
        else:
            steps.append(
                (op, arg1, arg2, choose_tries, choose_leaf_tries, vary_r,
                 stable)
            )

    # ---- static plan pass -------------------------------------------------
    # Everything trace-structural is resolved here, BEFORE tracing: the
    # evolving (wbound, src_slots) statics, per-step descent bounds, fast
    # eligibility, and row-path level tables.  Level/base DATA lands in
    # host_tables (the operand-pytree template the caller device-puts and
    # feeds back per call); structure lands in key_parts, whose tuple is
    # the kernel's cache_key — equal cache_keys mean identical traces, so
    # callers key their jit caches on it and reuse one executable across
    # maps that differ only in weights/choose_args values.
    ln_impl = _ln_impl()
    row_path = _use_row_path()
    host_tables = host_base_tables(A)
    rowlvl: dict[str, dict] = {}
    key_parts: list = [
        "crush_rule", RMAX, path, window_extra, with_flag, with_diag,
        A.n_buckets, A.max_size, A.max_nodes, A.positions,
        A.max_devices, A.max_depth,
        (t.choose_local_tries, t.choose_local_fallback_tries,
         t.choose_total_tries, t.chooseleaf_descend_once,
         t.chooseleaf_vary_r, t.chooseleaf_stable, t.straw_calc_version),
        tuple(sorted(set(int(a) for a in np.asarray(A.alg)) - {0})),
        row_path, ln_impl,
    ]
    plan: list[dict] = []
    wbound = 0  # static upper bound on wsize
    src_slots: list[int] = []  # statically-known source bucket slots
    diag_exact = True  # every choose step's tries lanes host-exact?
    for si, (op, arg1, arg2, s_tries, s_leaf_tries, s_vary_r,
             s_stable) in enumerate(steps):
        if op == RuleOp.TAKE:
            valid = (0 <= arg1 < A.max_devices) or (
                arg1 < 0 and -1 - arg1 < A.n_buckets
            )
            plan.append({"kind": "take", "arg1": arg1, "valid": valid})
            key_parts.append(("take", arg1, valid))
            if valid:
                wbound = 1
                src_slots = [-1 - arg1] if arg1 < 0 else []
        elif op in (RuleOp.CHOOSE_FIRSTN, RuleOp.CHOOSELEAF_FIRSTN,
                    RuleOp.CHOOSE_INDEP, RuleOp.CHOOSELEAF_INDEP):
            numrep = arg1 if arg1 > 0 else RMAX + arg1
            if numrep <= 0 or wbound == 0:
                key_parts.append(("noop", int(op), arg1, arg2))
                continue
            firstn = op in (RuleOp.CHOOSE_FIRSTN, RuleOp.CHOOSELEAF_FIRSTN)
            leafy = op in (RuleOp.CHOOSELEAF_FIRSTN,
                           RuleOp.CHOOSELEAF_INDEP)
            NR = min(numrep, RMAX)
            if firstn:
                recurse_tries = (
                    s_leaf_tries
                    if s_leaf_tries
                    else (1 if t.chooseleaf_descend_once else s_tries)
                )
            else:
                recurse_tries = s_leaf_tries if s_leaf_tries else 1

            # fast-path eligibility (see _choose_firstn_one_fast)
            fast_ok_firstn = (
                A.positions == 1
                and (not leafy or arg2 == 0 or s_stable)
                and recurse_tries <= 8
            )
            fast_ok_indep = recurse_tries <= 8
            if path == "fast":
                assert fast_ok_firstn if firstn else fast_ok_indep, (
                    "fast mapper path preconditions unmet for this "
                    "rule/map (choose_args positions>1, stable=0 "
                    "chooseleaf, or large chooseleaf tries)"
                )
            use_fast = path != "loop" and (
                fast_ok_firstn if firstn else fast_ok_indep
            )
            # static descent-length bounds for this step
            bound = _walk_bound(A, src_slots, arg2)
            leaf_bound = (
                _walk_bound(A, _slots_of_type(A, arg2), 0)
                if leafy and arg2 != 0 else None
            )
            # row-path level tables (gather-free unrolled descent); only
            # used by the fast kernels, and only on accelerator backends
            # (on CPU the gather fori_loop compiles faster and runs fine)
            levels = leaf_levels = None
            if use_fast and row_path:
                if src_slots:
                    levels = _prep_levels(A, src_slots, arg2,
                                          key_prefix=f"s{si}m")
                if leafy and arg2 != 0:
                    leaf_levels = _prep_levels(
                        A, _slots_of_type(A, arg2), 0, key_prefix=f"s{si}l"
                    )
            for lv in (levels or []) + (leaf_levels or []):
                if lv.row_ok:
                    rowlvl[lv.key] = lv.host_tab()
            # diagnostics lanes this step contributes per source bucket:
            # firstn books one placement per rep (two for chooseleaf —
            # outer + leaf recursion, mirroring the host histogram's
            # double increment); indep books one call-level lane.  Loop-
            # path steps and leafy indep cannot reproduce the host
            # increments and mark the whole plan inexact.
            diag_lanes = (NR * (2 if leafy else 1)) if firstn else 1
            diag_exact = diag_exact and use_fast and (
                firstn or not leafy
            )
            plan.append({
                "kind": "choose", "numrep": numrep, "NR": NR,
                "firstn": firstn, "leafy": leafy, "arg2": arg2,
                "tries": s_tries, "recurse_tries": recurse_tries,
                "vary_r": s_vary_r, "stable": s_stable,
                "use_fast": use_fast, "bound": bound,
                "leaf_bound": leaf_bound, "levels": levels,
                "leaf_levels": leaf_levels, "wbound": min(wbound, RMAX),
                "diag_lanes": diag_lanes,
            })
            key_parts.append((
                "choose", int(op), numrep, arg2, s_tries, recurse_tries,
                s_vary_r, s_stable, use_fast, bound, leaf_bound,
                tuple(lv.struct_key() for lv in (levels or [])),
                tuple(lv.struct_key() for lv in (leaf_levels or [])),
                min(wbound, RMAX),
            ))
            wbound = min(wbound * NR, RMAX)
            # next step's sources: buckets of this step's target type
            # (chooseleaf emits devices: no statically-known slots)
            src_slots = (
                _slots_of_type(A, arg2) if not leafy and arg2 != 0
                else []
            )
        elif op == RuleOp.EMIT:
            plan.append({"kind": "emit"})
            key_parts.append(("emit",))
            wbound = 0
    if rowlvl:
        host_tables["rowlvl"] = rowlvl
    cache_key = tuple(key_parts)

    def fn(x, dev_weights, tables=None):
        d = _DeviceArrays(A, tables, ln_impl)
        x = jnp.asarray(x).astype(jnp.uint32)
        w_items = jnp.full(RMAX, ITEM_NONE, jnp.int32)
        wsize = jnp.int32(0)
        result = jnp.full(RMAX, ITEM_NONE, jnp.int32)
        rlen = jnp.int32(0)
        unresolved = jnp.bool_(False)
        tries_parts: list = []  # with_diag: per-placement retry lanes
        step_items: list = []   # with_diag: post-step work vectors
        d_coll = d_rej = d_skip = jnp.int32(0)

        for st in plan:
            if st["kind"] == "take":
                if st["valid"]:
                    w_items = w_items.at[0].set(st["arg1"])
                    wsize = jnp.int32(1)
            elif st["kind"] == "choose":
                numrep, NR = st["numrep"], st["NR"]
                firstn, leafy = st["firstn"], st["leafy"]
                arg2 = st["arg2"]
                o = jnp.full(RMAX, ITEM_NONE, jnp.int32)
                osize = jnp.int32(0)
                for i in range(st["wbound"]):
                    src = w_items[i]
                    src_ok = (i < wsize) & (src < 0) & (-1 - src < A.n_buckets)
                    if firstn:
                        count = jnp.where(
                            src_ok, RMAX - osize, 0
                        )
                        if st["use_fast"]:
                            got = _choose_firstn_one_fast(
                                d, x, src, count, dev_weights,
                                numrep=numrep, target_type=arg2,
                                recurse_to_leaf=leafy, tries=st["tries"],
                                recurse_tries=st["recurse_tries"],
                                vary_r=st["vary_r"], stable=st["stable"],
                                weight_max=weight_max, out_bound=NR,
                                window=numrep + window_extra,
                                bound=st["bound"],
                                leaf_bound=st["leaf_bound"],
                                levels=st["levels"],
                                leaf_levels=st["leaf_levels"],
                                with_diag=with_diag,
                            )
                            if with_diag:
                                vals, leafs, n, flg, dstep = got
                                tries_parts.append(dstep["tries"])
                                d_coll = d_coll + dstep["coll"]
                                d_rej = d_rej + dstep["rej"]
                                d_skip = d_skip + dstep["skip"]
                            else:
                                vals, leafs, n, flg = got
                            unresolved = unresolved | flg
                        else:
                            vals, leafs, n = _choose_firstn_one(
                                d, x, src, count, dev_weights,
                                numrep=numrep, target_type=arg2,
                                recurse_to_leaf=leafy, tries=st["tries"],
                                recurse_tries=st["recurse_tries"],
                                vary_r=st["vary_r"], stable=st["stable"],
                                weight_max=weight_max, out_bound=NR,
                            )
                            if with_diag:
                                # loop path: no per-draw visibility
                                # (diag_exact is False for this plan)
                                tries_parts.append(jnp.full(
                                    st["diag_lanes"], -1, jnp.int32))
                    else:
                        out_size = jnp.where(
                            src_ok,
                            jnp.minimum(NR, RMAX - osize),
                            0,
                        )
                        if st["use_fast"]:
                            got = _choose_indep_one_fast(
                                d, x, src, out_size, dev_weights,
                                numrep=numrep, target_type=arg2,
                                recurse_to_leaf=leafy, tries=st["tries"],
                                recurse_tries=st["recurse_tries"],
                                weight_max=weight_max, out_bound=NR,
                                bound=st["bound"],
                                leaf_bound=st["leaf_bound"],
                                levels=st["levels"],
                                leaf_levels=st["leaf_levels"],
                                with_diag=with_diag,
                            )
                            if with_diag:
                                vals, leafs, n, _, dstep = got
                                tries_parts.append(dstep["tries"])
                            else:
                                vals, leafs, n, _ = got
                        else:
                            vals, leafs, n = _choose_indep_one(
                                d, x, src, out_size, dev_weights,
                                numrep=numrep, target_type=arg2,
                                recurse_to_leaf=leafy, tries=st["tries"],
                                recurse_tries=st["recurse_tries"],
                                weight_max=weight_max, out_bound=NR,
                            )
                            if with_diag:
                                tries_parts.append(jnp.full(
                                    st["diag_lanes"], -1, jnp.int32))
                    emit_vals = leafs if leafy else vals
                    # scatter emit_vals[:n] into o at osize
                    idx = osize + jnp.arange(NR)
                    keep = (jnp.arange(NR) < n) & (idx < RMAX)
                    o = o.at[jnp.where(keep, idx, RMAX)].set(
                        jnp.where(keep, emit_vals, ITEM_NONE),
                        mode="drop",
                    )
                    osize = osize + n
                w_items = o
                wsize = jnp.minimum(osize, RMAX)
                if with_diag:
                    step_items.append(w_items)
            elif st["kind"] == "emit":
                idx = rlen + jnp.arange(RMAX)
                keep = (jnp.arange(RMAX) < wsize) & (idx < RMAX)
                result = result.at[jnp.where(keep, idx, RMAX)].set(
                    jnp.where(keep, w_items, ITEM_NONE), mode="drop"
                )
                rlen = jnp.minimum(rlen + wsize, RMAX)
                w_items = jnp.full(RMAX, ITEM_NONE, jnp.int32)
                wsize = jnp.int32(0)
        if with_diag:
            valid_n = jnp.sum((result != ITEM_NONE).astype(jnp.int32))
            diag = {
                "tries": (jnp.concatenate(tries_parts) if tries_parts
                          else jnp.zeros(0, jnp.int32)),
                "coll": d_coll, "rej": d_rej, "skip": d_skip,
                "bad": (valid_n < RMAX).astype(jnp.int32),
                "steps": (jnp.stack(step_items) if step_items
                          else jnp.zeros((0, RMAX), jnp.int32)),
            }
            return result, unresolved, diag
        if with_flag:
            return result, unresolved
        return result

    fn.cache_key = cache_key
    fn.host_tables = host_tables
    fn.diag_exact = diag_exact
    fn.diag_tries_bound = t.choose_total_tries
    fn.diag_lanes = sum(
        st["wbound"] * st["diag_lanes"]
        for st in plan if st["kind"] == "choose"
    )
    fn.diag_steps = sum(1 for st in plan if st["kind"] == "choose")
    return fn


RESCUE_PAD = 1024  # largest loop-kernel batch size for flagged lanes

# rescue blocks dispatch in ONE small fixed shape: the exact loop
# kernel's dispatch cost is linear in lanes (a 1024-lane dispatch to
# rescue a handful of flagged PGs dominated small-pool remap time), its
# COMPILE cost is ~seconds per shape (so a ladder of tiers multiplies
# warmup), and chunked 32-lane dispatches price large rescues the same
# as one wide dispatch would.  One compiled shape, warmed alongside the
# kernels (ClusterState / serve staging), never compiled mid-steady.
RESCUE_PADS = (32,)


def rescue_pad_for(k: int) -> int:
    """The rescue block shape (k > the tier chunks over it)."""
    for p in RESCUE_PADS:
        if k <= p:
            return p
    return RESCUE_PADS[-1]

# cache_key -> jitted batched executable.  Keyed on the kernel's structural
# signature, NOT the CrushArrays instance: two maps that differ only in
# weights / choose_args values resolve to the same entry and share one
# compile (their tables ride in as operands).
_KERNEL_CACHE: dict[tuple, object] = {}


def strip_rowlvl(tables: dict) -> dict:
    """Base tables only — the operand pytree shape loop kernels take (the
    loop path reads no row-level tables; a fixed pytree structure keeps
    the shared jit cache signature-stable across callers)."""
    return {k: v for k, v in tables.items() if k != "rowlvl"}


def compile_batched(A: CrushArrays, ruleno: int, result_max: int,
                    path: str = "auto", chunk: int | None = None,
                    window_extra: int = FAST_WINDOW_EXTRA):
    """Batched mapper: fn(xs: u32[N], dev_weights: u32[D]) -> i32[N, RMAX].

    Host-level callable (not itself jittable): runs the jitted fast
    kernel over the batch, then — exactness rescue — recomputes the rare
    lanes whose bounded candidate window was inconclusive through the
    jitted loop kernel in fixed-size RESCUE_PAD blocks (scattered back on
    device, so `device=True` callers never pull O(N) rows to the host).

    The map's tables are device_put once here and passed as operands; the
    jitted executables live in _KERNEL_CACHE keyed by the kernels'
    cache_key, so repeated calls for same-shaped maps (weight changes,
    tester sweeps) dispatch without recompiling.  The whole `run`
    closure — plan pass AND uploaded tables — is additionally memoized
    per CrushArrays instance, so a tester sweeping (rule, num_rep) pairs
    over one map (CrushTester.m_arrays caches the instance) pays the
    O(buckets) host plan/table work once per pair, not once per call.

    chunk: if set, evaluate the batch in fixed-size chunks via lax.map
    (bounds peak memory for the [N, T, S] candidate intermediates of the
    fast path; N must be a multiple of chunk).
    """
    memo = A.__dict__.get("_batched_memo")
    if memo is None:
        memo = {}
        object.__setattr__(A, "_batched_memo", memo)  # frozen dataclass
    mkey = (ruleno, result_max, path, chunk, window_extra)
    if mkey in memo:
        return memo[mkey]
    fast = compile_rule(A, ruleno, result_max, path=path,
                        window_extra=window_extra, with_flag=True)
    tables = device_tables(fast.host_tables)
    base_tables = strip_rowlvl(tables)
    fkey = ("batched", chunk, fast.cache_key)
    jfast = _KERNEL_CACHE.get(fkey)
    if jfast is None:
        vfast = jax.vmap(fast, in_axes=(0, None, None))
        if chunk is None:
            jfast = jax.jit(vfast)
        else:
            @jax.jit
            def jfast(xs, dev_weights, tb):
                n = xs.shape[0]
                assert n % chunk == 0, (n, chunk)
                blocks = xs.reshape(n // chunk, chunk)
                res, flg = lax.map(lambda b: vfast(b, dev_weights, tb),
                                   blocks)
                return res.reshape(n, -1), flg.reshape(n)
        # every _KERNEL_CACHE entry registers in the executable registry
        # (compile cost / dispatch counts / lazy cost analysis)
        jfast = _executables.wrap(jfast, "kernel", "batched_fast", fkey)
        _KERNEL_CACHE[fkey] = jfast

    def run(xs, dev_weights, device: bool = False):
        res, flg = jfast(jnp.asarray(xs), jnp.asarray(dev_weights), tables)
        flg = np.asarray(flg)
        if flg.any():
            loop = compile_rule(A, ruleno, result_max, path="loop")
            lkey = ("batched_loop", loop.cache_key)
            jloop = _KERNEL_CACHE.get(lkey)
            if jloop is None:
                jloop = _executables.wrap(
                    jax.jit(jax.vmap(loop, in_axes=(0, None, None))),
                    "kernel", "batched_loop", lkey,
                )
                _KERNEL_CACHE[lkey] = jloop
            xs = np.asarray(xs)
            idx = np.nonzero(flg)[0]
            for i in range(0, len(idx), RESCUE_PAD):
                blk = idx[i:i + RESCUE_PAD]
                pad = np.resize(blk, RESCUE_PAD)  # cycle-pad to fixed size
                sub = jloop(jnp.asarray(xs[pad]), jnp.asarray(dev_weights),
                            base_tables)
                res = res.at[jnp.asarray(blk)].set(sub[: len(blk)])
        return res if device else np.asarray(res)

    memo[mkey] = run
    return run
