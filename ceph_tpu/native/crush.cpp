// Native batched CRUSH mapper — the C++ host runtime for the placement
// pipeline.
//
// This is a port of the reference semantics, written against this
// framework's Python semantic oracle (ceph_tpu/crush/mapper_ref.py); it is the
// native-code analogue of the reference's ParallelPGMapper (reference
// src/osd/OSDMapMapping.h:18-140): a thread pool shards the x (PG) axis and
// each worker runs the full rule interpreter per input.  Used by the CLIs
// as the fast host backend and by benchmarks as the multicore CPU baseline.
//
// ctypes ABI (flat arrays only): cm_create / cm_add_bucket / cm_add_rule /
// cm_set_choose_args / cm_finalize / cm_map_batch / cm_destroy, plus
// cm_set_ln_tables to inject the fixed-point log tables (built in Python,
// ceph_tpu/core/lntable.py).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <map>
#include <thread>
#include <vector>

namespace {

constexpr int ITEM_NONE = 0x7FFFFFFF;
constexpr int ITEM_UNDEF = 0x7FFFFFFE;
constexpr int64_t S64_MIN_V = INT64_MIN;

// ---- rjenkins 32-bit mix (public-domain Jenkins hash) ---------------------
inline void mix(uint32_t& a, uint32_t& b, uint32_t& c) {
    a -= b; a -= c; a ^= c >> 13;
    b -= c; b -= a; b ^= a << 8;
    c -= a; c -= b; c ^= b >> 13;
    a -= b; a -= c; a ^= c >> 12;
    b -= c; b -= a; b ^= a << 16;
    c -= a; c -= b; c ^= b >> 5;
    a -= b; a -= c; a ^= c >> 3;
    b -= c; b -= a; b ^= a << 10;
    c -= a; c -= b; c ^= b >> 15;
}
constexpr uint32_t SEED = 1315423911u;

inline uint32_t h2(uint32_t a, uint32_t b) {
    uint32_t hash = SEED ^ a ^ b, x = 231232, y = 1232;
    mix(a, b, hash);
    mix(x, a, hash);
    mix(b, y, hash);
    return hash;
}
inline uint32_t h3(uint32_t a, uint32_t b, uint32_t c) {
    uint32_t hash = SEED ^ a ^ b ^ c, x = 231232, y = 1232;
    mix(a, b, hash);
    mix(c, x, hash);
    mix(y, a, hash);
    mix(b, x, hash);
    mix(y, c, hash);
    return hash;
}
inline uint32_t h4(uint32_t a, uint32_t b, uint32_t c, uint32_t d) {
    uint32_t hash = SEED ^ a ^ b ^ c ^ d, x = 231232, y = 1232;
    mix(a, b, hash);
    mix(c, d, hash);
    mix(a, x, hash);
    mix(y, b, hash);
    mix(c, x, hash);
    mix(y, d, hash);
    return hash;
}

// ---- fixed-point log tables (injected from Python) ------------------------
int64_t RH_LH[258];
int64_t LL[256];

inline uint64_t crush_ln(uint32_t xin) {
    uint64_t x = (uint64_t)xin + 1;
    uint64_t iexpon = 15;
    if ((x & 0x18000) == 0) {
        uint32_t masked = (uint32_t)(x & 0x1FFFF);
        int fl = 0;
        uint32_t m = masked;
        for (int s : {16, 8, 4, 2, 1})
            if (m >= (1u << s)) { fl += s; m >>= s; }
        uint64_t bits = 15 - (uint64_t)fl;
        x <<= bits;
        iexpon = 15 - bits;
    }
    int64_t idx1 = (int64_t)((x >> 8) << 1);
    uint64_t RH = (uint64_t)RH_LH[idx1 - 256];
    uint64_t LH = (uint64_t)RH_LH[idx1 + 1 - 256];
    uint64_t xl64 = (x * RH) >> 48;
    uint64_t ll = (uint64_t)LL[xl64 & 0xFF];
    return (iexpon << 44) + ((LH + ll) >> (48 - 12 - 32));
}

// ---- map model ------------------------------------------------------------
struct Bucket {
    int id = 0, alg = 5, type = 0;
    std::vector<int> items, weights;
    std::vector<int> sum_weights;   // LIST
    std::vector<int> node_weights;  // TREE
    std::vector<int> straws;        // STRAW
    int size() const { return (int)items.size(); }
};

struct Rule {
    int ruleset, type, min_size, max_size;
    std::vector<int> ops, a1, a2;
};

struct ChooseArgsEntry {
    std::vector<std::vector<unsigned>> weight_sets;
    std::vector<int> ids;  // empty = use bucket items
};

struct Tunables {
    int choose_local_tries = 0;
    int choose_local_fallback_tries = 0;
    int choose_total_tries = 50;
    int chooseleaf_descend_once = 1;
    int chooseleaf_vary_r = 1;
    int chooseleaf_stable = 1;
};

struct Map {
    Tunables t;
    std::vector<Bucket> buckets;  // index = -1-id; may contain holes
    std::vector<char> present;
    std::vector<Rule> rules;
    std::map<int, ChooseArgsEntry> choose_args;
    int max_devices = 0;

    const Bucket* get(int id) const {
        int idx = -1 - id;
        if (idx < 0 || idx >= (int)buckets.size() || !present[idx])
            return nullptr;
        return &buckets[idx];
    }
};

// per-thread scratch: uniform-bucket permutation memo
struct PermState {
    uint32_t perm_x = 0;
    unsigned perm_n = 0;
    std::vector<int> perm;
};
using Work = std::map<int, PermState>;

// ---- bucket choose functions ---------------------------------------------
int perm_choose(const Bucket& b, PermState& w, uint32_t x, int r) {
    unsigned pr = (unsigned)r % b.size();
    if (w.perm_x != x || w.perm_n == 0) {
        w.perm_x = x;
        if (pr == 0) {
            unsigned s = h3(x, (uint32_t)b.id, 0) % b.size();
            w.perm.assign(b.size(), 0);
            w.perm[0] = (int)s;
            w.perm_n = 0xFFFF;
            return b.items[s];
        }
        w.perm.resize(b.size());
        for (int i = 0; i < b.size(); i++) w.perm[i] = i;
        w.perm_n = 0;
    } else if (w.perm_n == 0xFFFF) {
        int s = w.perm[0];
        for (int i = 0; i < b.size(); i++) w.perm[i] = i;
        w.perm[0] = s;
        w.perm[s] = 0;
        w.perm_n = 1;
    }
    while (w.perm_n <= pr) {
        unsigned p = w.perm_n;
        if ((int)p < b.size() - 1) {
            unsigned i = h3(x, (uint32_t)b.id, p) % (b.size() - p);
            if (i) std::swap(w.perm[p], w.perm[p + i]);
        }
        w.perm_n++;
    }
    return b.items[w.perm[pr]];
}

int list_choose(const Bucket& b, uint32_t x, int r) {
    for (int i = b.size() - 1; i >= 0; i--) {
        uint64_t w = h4(x, (uint32_t)b.items[i], (uint32_t)r,
                        (uint32_t)b.id) & 0xFFFF;
        w = (w * (uint64_t)(uint32_t)b.sum_weights[i]) >> 16;
        if (w < (uint64_t)(uint32_t)b.weights[i]) return b.items[i];
    }
    return b.items[0];
}

int tree_choose(const Bucket& b, uint32_t x, int r) {
    const auto& nw = b.node_weights;
    int n = (int)nw.size() >> 1;
    while (!(n & 1)) {
        uint64_t w = (uint32_t)nw[n];
        uint64_t t =
            ((uint64_t)h4(x, (uint32_t)n, (uint32_t)r, (uint32_t)b.id) * w) >>
            32;
        int h = 0, m = n;
        while ((m & 1) == 0) { h++; m >>= 1; }
        int left = n - (1 << (h - 1));
        n = (t < (uint64_t)(uint32_t)nw[left]) ? left : n + (1 << (h - 1));
    }
    return b.items[n >> 1];
}

int straw_choose(const Bucket& b, uint32_t x, int r) {
    int high = 0;
    uint64_t high_draw = 0;
    for (int i = 0; i < b.size(); i++) {
        uint64_t draw = (uint64_t)(h3(x, (uint32_t)b.items[i], (uint32_t)r) &
                                   0xFFFF) *
                        (uint64_t)(uint32_t)b.straws[i];
        if (i == 0 || draw > high_draw) { high = i; high_draw = draw; }
    }
    return b.items[high];
}

inline int64_t div_trunc(int64_t a, int64_t bdiv) { return a / bdiv; }

int straw2_choose(const Map& m, const Bucket& b, uint32_t x, int r,
                  const std::map<int, ChooseArgsEntry>* camap,
                  int position) {
    const std::vector<unsigned>* aw = nullptr;
    const std::vector<int>* ids = nullptr;
    if (camap) {
        auto it = camap->find(b.id);
        if (it != camap->end()) {
            const ChooseArgsEntry& ca = it->second;
            if (!ca.weight_sets.empty()) {
                size_t pos =
                    std::min((size_t)position, ca.weight_sets.size() - 1);
                aw = &ca.weight_sets[pos];
            }
            if (!ca.ids.empty()) ids = &ca.ids;
        }
    }
    int high = 0;
    int64_t high_draw = 0;
    for (int i = 0; i < b.size(); i++) {
        unsigned wgt = aw ? (*aw)[i] : (unsigned)b.weights[i];
        int64_t draw;
        if (wgt) {
            int id = ids ? (*ids)[i] : b.items[i];
            uint32_t u = h3(x, (uint32_t)id, (uint32_t)r) & 0xFFFF;
            int64_t ln = (int64_t)crush_ln(u) - 0x1000000000000LL;
            draw = div_trunc(ln, (int64_t)wgt);
        } else {
            draw = S64_MIN_V;
        }
        if (i == 0 || draw > high_draw) { high = i; high_draw = draw; }
    }
    return b.items[high];
}

int bucket_choose(const Map& m, Work& work, const Bucket& b, uint32_t x,
                  int r, const std::map<int, ChooseArgsEntry>* ca,
                  int position) {
    switch (b.alg) {
        case 1: return perm_choose(b, work[b.id], x, r);
        case 2: return list_choose(b, x, r);
        case 3: return tree_choose(b, x, r);
        case 4: return straw_choose(b, x, r);
        case 5: return straw2_choose(m, b, x, r, ca, position);
        default: return b.items[0];
    }
}

bool is_out(const Map& m, const unsigned* weight, int wlen, int item,
            uint32_t x) {
    if (item >= wlen) return true;
    unsigned w = weight[item];
    if (w >= 0x10000) return false;
    if (w == 0) return true;
    return (h2(x, (uint32_t)item) & 0xFFFF) >= w;
}

// ---- firstn / indep -------------------------------------------------------
struct Ctx {
    const Map& m;
    Work& work;
    const unsigned* weight;
    int wlen;
    const std::map<int, ChooseArgsEntry>* ca;  // per-bucket lookup
};

int choose_firstn(Ctx& cx, const Bucket& bucket, uint32_t x, int numrep,
                  int type, std::vector<int>& out, int outpos, int out_size,
                  int tries, int recurse_tries, int local_retries,
                  int local_fallback_retries, bool recurse_to_leaf,
                  int vary_r, int stable, std::vector<int>* out2,
                  int parent_r) {
    int count = out_size;
    int rep = stable ? 0 : outpos;
    for (; rep < numrep && count > 0; rep++) {
        int ftotal = 0;
        bool skip_rep = false;
        int item = 0;
        bool retry_descent = true;
        while (retry_descent) {
            retry_descent = false;
            const Bucket* in = &bucket;
            int flocal = 0;
            bool retry_bucket = true;
            while (retry_bucket) {
                retry_bucket = false;
                bool collide = false, reject = false;
                int r = rep + parent_r + ftotal;

                if (in->size() == 0) {
                    reject = true;
                } else {
                    if (local_fallback_retries > 0 &&
                        flocal >= (in->size() >> 1) &&
                        flocal > local_fallback_retries)
                        item = perm_choose(*in, cx.work[in->id], x, r);
                    else
                        item = bucket_choose(cx.m, cx.work, *in, x, r, cx.ca,
                                             outpos);
                    if (item >= cx.m.max_devices) { skip_rep = true; break; }

                    const Bucket* child =
                        item < 0 ? cx.m.get(item) : nullptr;
                    if (item < 0 && !child) { skip_rep = true; break; }
                    int itemtype = item < 0 ? child->type : 0;

                    if (itemtype != type) {
                        if (item >= 0) { skip_rep = true; break; }
                        in = child;
                        retry_bucket = true;
                        continue;
                    }

                    for (int i = 0; i < outpos; i++)
                        if (out[i] == item) { collide = true; break; }

                    if (!collide && recurse_to_leaf) {
                        if (item < 0) {
                            int sub_r = vary_r ? (r >> (vary_r - 1)) : 0;
                            if (choose_firstn(
                                    cx, *cx.m.get(item), x,
                                    stable ? 1 : outpos + 1, 0, *out2,
                                    outpos, count, recurse_tries, 0,
                                    local_retries, local_fallback_retries,
                                    false, vary_r, stable, nullptr,
                                    sub_r) <= outpos)
                                reject = true;
                        } else {
                            if ((int)out2->size() <= outpos)
                                out2->resize(outpos + 1, ITEM_NONE);
                            (*out2)[outpos] = item;
                        }
                    }

                    if (!reject && !collide && itemtype == 0)
                        reject = is_out(cx.m, cx.weight, cx.wlen, item, x);
                }

                if (reject || collide) {
                    ftotal++;
                    flocal++;
                    if (collide && flocal <= local_retries)
                        retry_bucket = true;
                    else if (local_fallback_retries > 0 &&
                             flocal <= in->size() + local_fallback_retries)
                        retry_bucket = true;
                    else if (ftotal < tries)
                        retry_descent = true;
                    else
                        skip_rep = true;
                    if (!retry_bucket) break;
                }
            }
            if (skip_rep) break;
            if (retry_descent) continue;
            break;
        }
        if (skip_rep) continue;
        if ((int)out.size() <= outpos) out.resize(outpos + 1, ITEM_NONE);
        out[outpos] = item;
        outpos++;
        count--;
    }
    return outpos;
}

void choose_indep(Ctx& cx, const Bucket& bucket, uint32_t x, int left,
                  int numrep, int type, std::vector<int>& out, int outpos,
                  int tries, int recurse_tries, bool recurse_to_leaf,
                  std::vector<int>* out2, int parent_r) {
    int endpos = outpos + left;
    if ((int)out.size() < endpos) out.resize(endpos, ITEM_NONE);
    if (out2 && (int)out2->size() < endpos) out2->resize(endpos, ITEM_NONE);
    for (int rep = outpos; rep < endpos; rep++) {
        out[rep] = ITEM_UNDEF;
        if (out2) (*out2)[rep] = ITEM_UNDEF;
    }
    int ftotal = 0;
    while (left > 0 && ftotal < tries) {
        for (int rep = outpos; rep < endpos; rep++) {
            if (out[rep] != ITEM_UNDEF) continue;
            const Bucket* in = &bucket;
            for (;;) {
                int r = rep + parent_r;
                if (in->alg == 1 && in->size() % numrep == 0)
                    r += (numrep + 1) * ftotal;
                else
                    r += numrep * ftotal;

                if (in->size() == 0) break;

                int item =
                    bucket_choose(cx.m, cx.work, *in, x, r, cx.ca, outpos);
                if (item >= cx.m.max_devices) {
                    out[rep] = ITEM_NONE;
                    if (out2) (*out2)[rep] = ITEM_NONE;
                    left--;
                    break;
                }
                const Bucket* child = item < 0 ? cx.m.get(item) : nullptr;
                if (item < 0 && !child) {
                    out[rep] = ITEM_NONE;
                    if (out2) (*out2)[rep] = ITEM_NONE;
                    left--;
                    break;
                }
                int itemtype = item < 0 ? child->type : 0;
                if (itemtype != type) {
                    if (item >= 0) {
                        out[rep] = ITEM_NONE;
                        if (out2) (*out2)[rep] = ITEM_NONE;
                        left--;
                        break;
                    }
                    in = child;
                    continue;
                }
                bool collide = false;
                for (int i = outpos; i < endpos; i++)
                    if (out[i] == item) { collide = true; break; }
                if (collide) break;

                if (recurse_to_leaf) {
                    if (item < 0) {
                        choose_indep(cx, *cx.m.get(item), x, 1, numrep, 0,
                                     *out2, rep, recurse_tries, 0, false,
                                     nullptr, r);
                        if ((*out2)[rep] == ITEM_NONE) break;
                    } else {
                        (*out2)[rep] = item;
                    }
                }

                if (itemtype == 0 &&
                    is_out(cx.m, cx.weight, cx.wlen, item, x))
                    break;

                out[rep] = item;
                left--;
                break;
            }
        }
        ftotal++;
        if (left <= 0) break;
    }
    for (int rep = outpos; rep < endpos; rep++) {
        if (out[rep] == ITEM_UNDEF) out[rep] = ITEM_NONE;
        if (out2 && (*out2)[rep] == ITEM_UNDEF) (*out2)[rep] = ITEM_NONE;
    }
}

// ---- rule interpreter -----------------------------------------------------
enum {
    OP_NOOP = 0, OP_TAKE = 1, OP_CHOOSE_FIRSTN = 2, OP_CHOOSE_INDEP = 3,
    OP_EMIT = 4, OP_CHOOSELEAF_FIRSTN = 6, OP_CHOOSELEAF_INDEP = 7,
    OP_SET_CHOOSE_TRIES = 8, OP_SET_CHOOSELEAF_TRIES = 9,
    OP_SET_CHOOSE_LOCAL_TRIES = 10, OP_SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11,
    OP_SET_CHOOSELEAF_VARY_R = 12, OP_SET_CHOOSELEAF_STABLE = 13,
};

int do_rule(const Map& m, int ruleno, uint32_t x, int result_max,
            const unsigned* weight, int wlen,
            const std::map<int, ChooseArgsEntry>* ca, int* result) {
    if (ruleno < 0 || ruleno >= (int)m.rules.size()) return 0;
    const Rule& rule = m.rules[ruleno];
    Work work;
    Ctx cx{m, work, weight, wlen, ca};
    const Tunables& t = m.t;

    int choose_tries = t.choose_total_tries + 1;
    int choose_leaf_tries = 0;
    int choose_local_retries = t.choose_local_tries;
    int choose_local_fallback_retries = t.choose_local_fallback_tries;
    int vary_r = t.chooseleaf_vary_r;
    int stable = t.chooseleaf_stable;

    std::vector<int> res, w, o, c;
    int wsize = 0;

    for (size_t s = 0; s < rule.ops.size(); s++) {
        int op = rule.ops[s], arg1 = rule.a1[s], arg2 = rule.a2[s];
        bool firstn = false;
        switch (op) {
            case OP_TAKE:
                if ((arg1 >= 0 && arg1 < m.max_devices) ||
                    (arg1 < 0 && m.get(arg1))) {
                    w.assign(1, arg1);
                    wsize = 1;
                }
                break;
            case OP_SET_CHOOSE_TRIES:
                if (arg1 > 0) choose_tries = arg1;
                break;
            case OP_SET_CHOOSELEAF_TRIES:
                if (arg1 > 0) choose_leaf_tries = arg1;
                break;
            case OP_SET_CHOOSE_LOCAL_TRIES:
                if (arg1 >= 0) choose_local_retries = arg1;
                break;
            case OP_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
                if (arg1 >= 0) choose_local_fallback_retries = arg1;
                break;
            case OP_SET_CHOOSELEAF_VARY_R:
                if (arg1 >= 0) vary_r = arg1;
                break;
            case OP_SET_CHOOSELEAF_STABLE:
                if (arg1 >= 0) stable = arg1;
                break;
            case OP_CHOOSELEAF_FIRSTN:
            case OP_CHOOSE_FIRSTN:
            case OP_CHOOSELEAF_INDEP:
            case OP_CHOOSE_INDEP: {
                firstn =
                    (op == OP_CHOOSELEAF_FIRSTN || op == OP_CHOOSE_FIRSTN);
                if (wsize == 0) break;
                bool recurse_to_leaf = (op == OP_CHOOSELEAF_FIRSTN ||
                                        op == OP_CHOOSELEAF_INDEP);
                int osize = 0;
                o.clear();
                c.clear();
                for (int i = 0; i < wsize; i++) {
                    int numrep = arg1;
                    if (numrep <= 0) {
                        numrep += result_max;
                        if (numrep <= 0) continue;
                    }
                    if (w[i] >= 0 || !m.get(w[i])) continue;
                    const Bucket& bucket = *m.get(w[i]);
                    if (firstn) {
                        int recurse_tries =
                            choose_leaf_tries
                                ? choose_leaf_tries
                                : (t.chooseleaf_descend_once ? 1
                                                             : choose_tries);
                        if ((int)o.size() < osize) o.resize(osize, ITEM_NONE);
                        if ((int)c.size() < osize) c.resize(osize, ITEM_NONE);
                        std::vector<int> sub_o(o.begin() + osize, o.end());
                        std::vector<int> sub_c(c.begin() + osize, c.end());
                        int n = choose_firstn(
                            cx, bucket, x, numrep, arg2, sub_o, 0,
                            result_max - osize, choose_tries, recurse_tries,
                            choose_local_retries,
                            choose_local_fallback_retries, recurse_to_leaf,
                            vary_r, stable,
                            &sub_c, 0);
                        o.resize(osize);
                        o.insert(o.end(), sub_o.begin(), sub_o.end());
                        c.resize(osize);
                        c.insert(c.end(), sub_c.begin(), sub_c.end());
                        osize += n;
                    } else {
                        int out_size = std::min(numrep, result_max - osize);
                        std::vector<int> sub_o, sub_c;
                        choose_indep(cx, bucket, x, out_size, numrep, arg2,
                                     sub_o, 0, choose_tries,
                                     choose_leaf_tries ? choose_leaf_tries
                                                       : 1,
                                     recurse_to_leaf, &sub_c, 0);
                        o.resize(osize);
                        o.insert(o.end(), sub_o.begin(), sub_o.end());
                        c.resize(osize);
                        c.insert(c.end(), sub_c.begin(), sub_c.end());
                        osize += out_size;
                    }
                }
                if (recurse_to_leaf) {
                    if ((int)c.size() < osize) c.resize(osize, ITEM_NONE);
                    for (int i = 0; i < osize && i < (int)o.size(); i++)
                        o[i] = c[i];
                    if ((int)o.size() < osize) {
                        size_t old = o.size();
                        o.resize(osize);
                        for (size_t i = old; i < (size_t)osize; i++)
                            o[i] = c[i];
                    }
                }
                w = o;
                wsize = osize;
                break;
            }
            case OP_EMIT:
                for (int i = 0; i < wsize && (int)res.size() < result_max;
                     i++)
                    res.push_back(w[i]);
                wsize = 0;
                break;
            default:
                break;
        }
    }
    int n = (int)res.size();
    for (int i = 0; i < n; i++) result[i] = res[i];
    return n;
}

}  // namespace

extern "C" {

void cm_set_ln_tables(const long long* rh_lh, const long long* ll) {
    std::memcpy(RH_LH, rh_lh, sizeof(RH_LH));
    std::memcpy(LL, ll, sizeof(LL));
}

void* cm_create(int clt, int clft, int ctt, int cdo, int cvr, int cs) {
    Map* m = new Map();
    m->t.choose_local_tries = clt;
    m->t.choose_local_fallback_tries = clft;
    m->t.choose_total_tries = ctt;
    m->t.chooseleaf_descend_once = cdo;
    m->t.chooseleaf_vary_r = cvr;
    m->t.chooseleaf_stable = cs;
    return m;
}

// derived arrays may be NULL when unused by the alg
int cm_add_bucket(void* h, int id, int alg, int type, int size,
                  const int* items, const int* weights,
                  const int* sum_weights, const int* node_weights,
                  int n_nodes, const int* straws) {
    Map* m = (Map*)h;
    int idx = -1 - id;
    if (idx < 0) return -1;
    if ((int)m->buckets.size() <= idx) {
        m->buckets.resize(idx + 1);
        m->present.resize(idx + 1, 0);
    }
    Bucket& b = m->buckets[idx];
    b.id = id;
    b.alg = alg;
    b.type = type;
    b.items.assign(items, items + size);
    b.weights.assign(weights, weights + size);
    if (sum_weights) b.sum_weights.assign(sum_weights, sum_weights + size);
    if (node_weights)
        b.node_weights.assign(node_weights, node_weights + n_nodes);
    if (straws) b.straws.assign(straws, straws + size);
    m->present[idx] = 1;
    for (int i = 0; i < size; i++)
        if (items[i] >= 0 && items[i] + 1 > m->max_devices)
            m->max_devices = items[i] + 1;
    return id;
}

int cm_add_rule(void* h, int ruleno, int ruleset, int type, int min_size,
                int max_size, int nsteps, const int* ops, const int* a1,
                const int* a2) {
    Map* m = (Map*)h;
    if (ruleno < 0) ruleno = (int)m->rules.size();
    if ((int)m->rules.size() <= ruleno) m->rules.resize(ruleno + 1);
    Rule& r = m->rules[ruleno];
    r.ruleset = ruleset;
    r.type = type;
    r.min_size = min_size;
    r.max_size = max_size;
    r.ops.assign(ops, ops + nsteps);
    r.a1.assign(a1, a1 + nsteps);
    r.a2.assign(a2, a2 + nsteps);
    return ruleno;
}

// weight_sets: positions x size flattened; ids NULL = bucket items
int cm_set_choose_args(void* h, int bucket_id, int positions,
                       const unsigned* weight_sets, const int* ids,
                       int size) {
    Map* m = (Map*)h;
    ChooseArgsEntry& e = m->choose_args[bucket_id];
    e.weight_sets.clear();
    for (int p = 0; p < positions; p++)
        e.weight_sets.emplace_back(weight_sets + (size_t)p * size,
                                   weight_sets + (size_t)(p + 1) * size);
    if (ids) e.ids.assign(ids, ids + size);
    return 0;
}

void cm_set_max_devices(void* h, int n) {
    Map* m = (Map*)h;
    if (n > m->max_devices) m->max_devices = n;
}

// out: n * result_max ints, ITEM_NONE-padded; returns mappings done
long long cm_map_batch(void* h, int ruleno, const unsigned* xs, long long n,
                       int result_max, const unsigned* weight, int wlen,
                       int* out, int n_threads, int use_choose_args) {
    Map* m = (Map*)h;
    if (n_threads <= 0)
        n_threads = (int)std::thread::hardware_concurrency();
    if (n_threads < 1) n_threads = 1;
    if (n_threads > 64) n_threads = 64;

    const std::map<int, ChooseArgsEntry>* ca =
        (use_choose_args && !m->choose_args.empty()) ? &m->choose_args
                                                     : nullptr;
    std::atomic<long long> next(0);
    auto worker = [&]() {
        std::vector<int> res(result_max);
        constexpr long long CHUNK = 1024;
        for (;;) {
            long long start = next.fetch_add(CHUNK);
            if (start >= n) break;
            long long end = std::min(n, start + CHUNK);
            for (long long i = start; i < end; i++) {
                int* row = out + (size_t)i * result_max;
                for (int j = 0; j < result_max; j++) row[j] = ITEM_NONE;
                int got = do_rule(*m, ruleno, xs[i], result_max, weight,
                                  wlen, ca, res.data());
                for (int j = 0; j < got && j < result_max; j++)
                    row[j] = res[j];
            }
        }
    };
    std::vector<std::thread> threads;
    for (int i = 1; i < n_threads; i++) threads.emplace_back(worker);
    worker();
    for (auto& th : threads) th.join();
    return n;
}

void cm_destroy(void* h) { delete (Map*)h; }

}  // extern "C"
