// CRC-32C (Castagnoli) region kernel — the native fast path behind
// ceph_tpu.utils.crc32c (role of the reference's src/common/crc32c.cc
// with its SSE4.2 ceph_crc32c_intel_fast backend).
//
// Contract matches ceph_crc32c: caller passes the raw initial value
// (usually 0xffffffff); no pre/post inversion.
//
// Engine selection at runtime: the x86 CRC32 instruction (SSE4.2,
// 8 bytes/op) when the CPU has it, else table slicing-by-8.

#include <cstddef>
#include <cstdint>
#include <cstring>

#if defined(__x86_64__) || defined(_M_X64)
#include <cpuid.h>
#include <nmmintrin.h>
#endif

namespace {

uint32_t table[8][256];

struct TableInit {
  TableInit() {
    const uint32_t poly = 0x82F63B78u;
    for (int i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
      table[0][i] = c;
    }
    for (int t = 1; t < 8; t++)
      for (int i = 0; i < 256; i++)
        table[t][i] = table[0][table[t - 1][i] & 0xff] ^ (table[t - 1][i] >> 8);
  }
} init_;

uint32_t crc_sw(uint32_t crc, const uint8_t* p, size_t n) {
  while (n >= 8) {
    uint64_t q;
    std::memcpy(&q, p, 8);
    q ^= crc;
    crc = table[7][q & 0xff] ^ table[6][(q >> 8) & 0xff] ^
          table[5][(q >> 16) & 0xff] ^ table[4][(q >> 24) & 0xff] ^
          table[3][(q >> 32) & 0xff] ^ table[2][(q >> 40) & 0xff] ^
          table[1][(q >> 48) & 0xff] ^ table[0][(q >> 56) & 0xff];
    p += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ table[0][(crc ^ *p++) & 0xff];
  return crc;
}

#if defined(__x86_64__) || defined(_M_X64)
bool have_sse42() {
  unsigned a, b, c, d;
  if (!__get_cpuid(1, &a, &b, &c, &d)) return false;
  return c & bit_SSE4_2;
}

__attribute__((target("sse4.2")))
uint32_t crc_hw(uint32_t crc, const uint8_t* p, size_t n) {
  uint64_t c = crc;
  while (n >= 8) {
    uint64_t q;
    std::memcpy(&q, p, 8);
    c = _mm_crc32_u64(c, q);
    p += 8;
    n -= 8;
  }
  uint32_t c32 = (uint32_t)c;
  while (n--) c32 = _mm_crc32_u8(c32, *p++);
  return c32;
}

const bool use_hw = have_sse42();
#else
const bool use_hw = false;
uint32_t crc_hw(uint32_t crc, const uint8_t* p, size_t n) {
  return crc_sw(crc, p, n);
}
#endif

}  // namespace

extern "C" {

uint32_t ceph_tpu_crc32c(uint32_t crc, const uint8_t* data, size_t len) {
  return use_hw ? crc_hw(crc, data, len) : crc_sw(crc, data, len);
}

int ceph_tpu_crc32c_hw(void) { return use_hw ? 1 : 0; }
}
