// GF(2^8) region kernels — the native CPU erasure-code engine.
//
// Plays the role of the reference's out-of-tree SIMD GF libraries
// (gf-complete / isa-l, vendored as empty submodules in the reference
// checkout): multiply-accumulate of constant×region over GF(2^8) with the
// 0x11D polynomial, vectorized with AVX2/SSSE3 nibble-table shuffles when
// available and a 64-bit table-pair scalar path otherwise.
//
// Exposed C ABI (ctypes-friendly):
//   gf_native_simd_level()                     -> 0 scalar, 1 ssse3, 2 avx2
//   gf_native_matvec(M, m, k, data, parity, L) -> parity[m][L] = M·data
//   gf_native_mul_region(c, src, dst, L, acc)  -> dst (^)= c*src
//
// Built lazily by ceph_tpu.native (g++ -O3); no external deps.

#include <cstdint>
#include <cstring>

#if defined(__x86_64__) || defined(_M_X64)
#include <immintrin.h>
#endif

namespace {

constexpr unsigned PRIM = 0x11D;

struct Tables {
    // full 256x256 product table
    uint8_t mul[256][256];
    // per-constant nibble tables: lo[c][x&15], hi[c][x>>4]
    uint8_t lo[256][16];
    uint8_t hi[256][16];
    Tables() {
        for (int a = 0; a < 256; a++) {
            for (int b = 0; b < 256; b++) {
                unsigned p = 0, aa = a, bb = b;
                while (bb) {
                    if (bb & 1) p ^= aa;
                    aa <<= 1;
                    if (aa & 0x100) aa ^= PRIM;
                    bb >>= 1;
                }
                mul[a][b] = (uint8_t)p;
            }
        }
        for (int c = 0; c < 256; c++) {
            for (int n = 0; n < 16; n++) {
                lo[c][n] = mul[c][n];
                hi[c][n] = mul[c][n << 4];
            }
        }
    }
};

const Tables T;

#if defined(__AVX2__)
inline void mul_region_avx2(uint8_t c, const uint8_t* src, uint8_t* dst,
                            size_t len, bool accumulate) {
    const __m256i lo =
        _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)T.lo[c]));
    const __m256i hi =
        _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)T.hi[c]));
    const __m256i mask = _mm256_set1_epi8(0x0F);
    size_t i = 0;
    for (; i + 32 <= len; i += 32) {
        __m256i x = _mm256_loadu_si256((const __m256i*)(src + i));
        __m256i l = _mm256_shuffle_epi8(lo, _mm256_and_si256(x, mask));
        __m256i h = _mm256_shuffle_epi8(
            hi, _mm256_and_si256(_mm256_srli_epi64(x, 4), mask));
        __m256i p = _mm256_xor_si256(l, h);
        if (accumulate)
            p = _mm256_xor_si256(
                p, _mm256_loadu_si256((const __m256i*)(dst + i)));
        _mm256_storeu_si256((__m256i*)(dst + i), p);
    }
    for (; i < len; i++) {
        uint8_t p = T.mul[c][src[i]];
        dst[i] = accumulate ? (uint8_t)(dst[i] ^ p) : p;
    }
}
#elif defined(__SSSE3__)
inline void mul_region_ssse3(uint8_t c, const uint8_t* src, uint8_t* dst,
                             size_t len, bool accumulate) {
    const __m128i lo = _mm_loadu_si128((const __m128i*)T.lo[c]);
    const __m128i hi = _mm_loadu_si128((const __m128i*)T.hi[c]);
    const __m128i mask = _mm_set1_epi8(0x0F);
    size_t i = 0;
    for (; i + 16 <= len; i += 16) {
        __m128i x = _mm_loadu_si128((const __m128i*)(src + i));
        __m128i l = _mm_shuffle_epi8(lo, _mm_and_si128(x, mask));
        __m128i h = _mm_shuffle_epi8(
            hi, _mm_and_si128(_mm_srli_epi64(x, 4), mask));
        __m128i p = _mm_xor_si128(l, h);
        if (accumulate)
            p = _mm_xor_si128(p, _mm_loadu_si128((const __m128i*)(dst + i)));
        _mm_storeu_si128((__m128i*)(dst + i), p);
    }
    for (; i < len; i++) {
        uint8_t p = T.mul[c][src[i]];
        dst[i] = accumulate ? (uint8_t)(dst[i] ^ p) : p;
    }
}
#endif

inline void mul_region_scalar(uint8_t c, const uint8_t* src, uint8_t* dst,
                              size_t len, bool accumulate) {
    const uint8_t* row = T.mul[c];
    if (accumulate)
        for (size_t i = 0; i < len; i++) dst[i] ^= row[src[i]];
    else
        for (size_t i = 0; i < len; i++) dst[i] = row[src[i]];
}

inline void mul_region(uint8_t c, const uint8_t* src, uint8_t* dst,
                       size_t len, bool accumulate) {
    if (c == 0) {
        if (!accumulate) std::memset(dst, 0, len);
        return;
    }
    if (c == 1) {
        if (accumulate)
            for (size_t i = 0; i < len; i++) dst[i] ^= src[i];
        else
            std::memcpy(dst, src, len);
        return;
    }
#if defined(__AVX2__)
    mul_region_avx2(c, src, dst, len, accumulate);
#elif defined(__SSSE3__)
    mul_region_ssse3(c, src, dst, len, accumulate);
#else
    mul_region_scalar(c, src, dst, len, accumulate);
#endif
}

}  // namespace

extern "C" {

int gf_native_simd_level() {
#if defined(__AVX2__)
    return 2;
#elif defined(__SSSE3__)
    return 1;
#else
    return 0;
#endif
}

// parity[m][L] = M[m][k] · data[k][L]   (rows contiguous)
void gf_native_matvec(const uint8_t* M, int m, int k, const uint8_t* data,
                      uint8_t* parity, long long L) {
    for (int i = 0; i < m; i++) {
        uint8_t* out = parity + (size_t)i * L;
        bool first = true;
        for (int j = 0; j < k; j++) {
            uint8_t c = M[i * k + j];
            if (c == 0) continue;
            mul_region(c, data + (size_t)j * L, out, (size_t)L, !first);
            first = false;
        }
        if (first) std::memset(out, 0, (size_t)L);
    }
}

void gf_native_mul_region(int c, const uint8_t* src, uint8_t* dst,
                          long long L, int accumulate) {
    mul_region((uint8_t)c, src, dst, (size_t)L, accumulate != 0);
}

}  // extern "C"
